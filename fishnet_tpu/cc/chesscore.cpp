// chesscore: native host-side chess rules library.
//
// Plays the role shakmaty plays in the reference client (validating FEN +
// replaying UCI moves for every acquired batch — reference:
// src/queue.rs:554-581) as compiled code, with the same semantics as the
// perft-validated Python library in fishnet_tpu/chess (X-FEN castling,
// Chess960 king-takes-rook encoding). Exposed via a small C ABI consumed
// with ctypes (fishnet_tpu/chess/native.py).
//
// Standard chess + Chess960. Variant games take the Python path.

#include <cctype>
#include <cstdint>
#include <cstring>
#include <string>
#include <vector>

namespace {

using u64 = uint64_t;

constexpr int WHITE = 0, BLACK = 1;
constexpr int PAWN = 0, KNIGHT = 1, BISHOP = 2, ROOK = 3, QUEEN = 4, KING = 5;

constexpr u64 RANK_1 = 0xFFULL, RANK_2 = 0xFF00ULL, RANK_7 = 0xFF000000000000ULL,
              RANK_8 = 0xFF00000000000000ULL;

inline int lsb(u64 b) { return __builtin_ctzll(b); }
inline int popcount(u64 b) { return __builtin_popcountll(b); }
inline u64 bb(int sq) { return 1ULL << sq; }

// ---- precomputed tables -------------------------------------------------

u64 KNIGHT_ATT[64], KING_ATT[64], PAWN_ATT[2][64];
u64 RAYS[8][64];  // E N NE NW W S SW SE
u64 BETWEEN[64][64];

constexpr int DIRS[8][2] = {{1, 0}, {0, 1}, {1, 1}, {-1, 1},
                            {-1, 0}, {0, -1}, {-1, -1}, {1, -1}};

struct TableInit {
  TableInit() {
    auto steps = [](int sq, const int (*deltas)[2], int n) {
      u64 m = 0;
      int f = sq & 7, r = sq >> 3;
      for (int i = 0; i < n; i++) {
        int nf = f + deltas[i][0], nr = r + deltas[i][1];
        if (0 <= nf && nf < 8 && 0 <= nr && nr < 8) m |= bb(nr * 8 + nf);
      }
      return m;
    };
    constexpr int KN[8][2] = {{1, 2}, {2, 1}, {2, -1}, {1, -2},
                              {-1, -2}, {-2, -1}, {-2, 1}, {-1, 2}};
    constexpr int WP[2][2] = {{-1, 1}, {1, 1}};
    constexpr int BP[2][2] = {{-1, -1}, {1, -1}};
    for (int sq = 0; sq < 64; sq++) {
      KNIGHT_ATT[sq] = steps(sq, KN, 8);
      KING_ATT[sq] = steps(sq, DIRS, 8);
      PAWN_ATT[WHITE][sq] = steps(sq, WP, 2);
      PAWN_ATT[BLACK][sq] = steps(sq, BP, 2);
      for (int d = 0; d < 8; d++) {
        u64 m = 0;
        int nf = (sq & 7) + DIRS[d][0], nr = (sq >> 3) + DIRS[d][1];
        while (0 <= nf && nf < 8 && 0 <= nr && nr < 8) {
          m |= bb(nr * 8 + nf);
          nf += DIRS[d][0];
          nr += DIRS[d][1];
        }
        RAYS[d][sq] = m;
      }
    }
    for (int a = 0; a < 64; a++)
      for (int d = 0; d < 8; d++) {
        u64 ray = RAYS[d][a];
        u64 m = ray;
        while (m) {
          int b_ = lsb(m);
          m &= m - 1;
          BETWEEN[a][b_] = ray & RAYS[(d + 4) % 8][b_];
        }
      }
  }
} table_init;

inline u64 slider_att(int sq, u64 occ, int d0, int d1, int d2, int d3) {
  u64 att = 0;
  const int dirs[4] = {d0, d1, d2, d3};
  for (int i = 0; i < 4; i++) {
    int d = dirs[i];
    u64 ray = RAYS[d][sq];
    u64 blockers = ray & occ;
    if (blockers) {
      int first = d < 4 ? lsb(blockers) : 63 - __builtin_clzll(blockers);
      ray &= ~RAYS[d][first];
    }
    att |= ray;
  }
  return att;
}
inline u64 rook_att(int sq, u64 occ) { return slider_att(sq, occ, 0, 1, 4, 5); }
inline u64 bishop_att(int sq, u64 occ) { return slider_att(sq, occ, 2, 3, 6, 7); }

// ---- position -----------------------------------------------------------

struct Move {
  int from, to, promo;  // promo: -1 none, else piece type; castling = K takes own R
  bool operator==(const Move& o) const {
    return from == o.from && to == o.to && promo == o.promo;
  }
};

struct Pos {
  u64 pieces[2][6] = {};
  u64 occ[2] = {};
  int turn = WHITE;
  u64 castling = 0;  // rook squares retaining rights
  int ep = -1;
  int halfmove = 0, fullmove = 1;

  u64 all() const { return occ[0] | occ[1]; }

  void refresh() {
    occ[0] = occ[1] = 0;
    for (int t = 0; t < 6; t++) {
      occ[0] |= pieces[0][t];
      occ[1] |= pieces[1][t];
    }
  }

  int piece_at(int sq, int color) const {
    for (int t = 0; t < 6; t++)
      if (pieces[color][t] & bb(sq)) return t;
    return -1;
  }

  int king_sq(int color) const {
    return pieces[color][KING] ? lsb(pieces[color][KING]) : -1;
  }

  u64 attackers(int color, int sq, u64 occAll) const {
    u64 a = KNIGHT_ATT[sq] & pieces[color][KNIGHT];
    a |= KING_ATT[sq] & pieces[color][KING];
    a |= PAWN_ATT[color ^ 1][sq] & pieces[color][PAWN];
    u64 rq = pieces[color][ROOK] | pieces[color][QUEEN];
    if (rq) a |= rook_att(sq, occAll) & rq;
    u64 bq = pieces[color][BISHOP] | pieces[color][QUEEN];
    if (bq) a |= bishop_att(sq, occAll) & bq;
    return a;
  }

  bool in_check(int color) const {
    int k = king_sq(color);
    return k >= 0 && attackers(color ^ 1, k, all());
  }

  void remove(int sq) {
    for (int c = 0; c < 2; c++)
      for (int t = 0; t < 6; t++) pieces[c][t] &= ~bb(sq);
  }

  void apply(const Move& m) {
    int us = turn, them = turn ^ 1;
    halfmove++;
    int new_ep = -1;
    int pt = piece_at(m.from, us);
    bool is_castle = pt == KING && (pieces[us][ROOK] & bb(m.to));
    if (is_castle) {
      int rank = us == WHITE ? 0 : 56;
      bool kingside = m.to > m.from;
      remove(m.from);
      remove(m.to);
      pieces[us][KING] |= bb(rank + (kingside ? 6 : 2));
      pieces[us][ROOK] |= bb(rank + (kingside ? 5 : 3));
      castling &= ~(us == WHITE ? RANK_1 : RANK_8);
    } else {
      pieces[us][pt] &= ~bb(m.from);
      int cap_sq = m.to;
      if (pt == PAWN && m.to == ep && !(all() & bb(m.to)))
        cap_sq = m.to + (us == WHITE ? -8 : 8);
      if (occ[them] & bb(cap_sq)) {
        remove(cap_sq);
        halfmove = 0;
        castling &= ~bb(cap_sq);
      }
      if (pt == PAWN) {
        halfmove = 0;
        if ((m.to - m.from) == 16 || (m.from - m.to) == 16)
          new_ep = (m.from + m.to) / 2;
      }
      pieces[us][m.promo >= 0 ? m.promo : pt] |= bb(m.to);
      if (pt == KING) castling &= ~(us == WHITE ? RANK_1 : RANK_8);
      castling &= ~bb(m.from);
    }
    refresh();
    ep = new_ep;
    turn = them;
    if (us == BLACK) fullmove++;
  }

  void pseudo_moves(std::vector<Move>& out) const {
    int us = turn, them = turn ^ 1;
    u64 own = occ[us], enemy = occ[them], occAll = all();
    u64 promo_rank = us == WHITE ? RANK_8 : RANK_1;
    int fwd = us == WHITE ? 8 : -8;
    u64 start = us == WHITE ? RANK_2 : RANK_7;

    auto push = [&](int f, int t) { out.push_back({f, t, -1}); };
    auto push_maybe_promo = [&](int f, int t) {
      if (bb(t) & promo_rank)
        for (int p : {QUEEN, ROOK, BISHOP, KNIGHT}) out.push_back({f, t, p});
      else
        push(f, t);
    };

    u64 pawns = pieces[us][PAWN];
    while (pawns) {
      int f = lsb(pawns);
      pawns &= pawns - 1;
      int t1 = f + fwd;
      if (!(occAll & bb(t1))) {
        push_maybe_promo(f, t1);
        if ((bb(f) & start) && !(occAll & bb(t1 + fwd))) push(f, t1 + fwd);
      }
      u64 caps = PAWN_ATT[us][f] & (enemy | (ep >= 0 ? bb(ep) : 0));
      while (caps) {
        int t = lsb(caps);
        caps &= caps - 1;
        push_maybe_promo(f, t);
      }
    }
    auto gen = [&](int type, auto att_fn) {
      u64 b = pieces[us][type];
      while (b) {
        int f = lsb(b);
        b &= b - 1;
        u64 targets = att_fn(f) & ~own;
        while (targets) {
          int t = lsb(targets);
          targets &= targets - 1;
          push(f, t);
        }
      }
    };
    gen(KNIGHT, [&](int f) { return KNIGHT_ATT[f]; });
    gen(BISHOP, [&](int f) { return bishop_att(f, occAll); });
    gen(ROOK, [&](int f) { return rook_att(f, occAll); });
    gen(QUEEN, [&](int f) { return rook_att(f, occAll) | bishop_att(f, occAll); });
    gen(KING, [&](int f) { return KING_ATT[f]; });

    // castling: king takes own rook encoding; checks done here
    int ksq = king_sq(us);
    u64 back = us == WHITE ? RANK_1 : RANK_8;
    if (ksq >= 0 && (bb(ksq) & back) && !in_check(us)) {
      u64 rights = castling & back & pieces[us][ROOK];
      while (rights) {
        int rsq = lsb(rights);
        rights &= rights - 1;
        bool kingside = rsq > ksq;
        int rank = us == WHITE ? 0 : 56;
        int k_dest = rank + (kingside ? 6 : 2);
        int r_dest = rank + (kingside ? 5 : 3);
        u64 path = (BETWEEN[ksq][k_dest] | BETWEEN[rsq][r_dest] | bb(k_dest) |
                    bb(r_dest)) &
                   ~bb(ksq) & ~bb(rsq);
        if (path & occAll) continue;
        u64 occ2 = occAll & ~bb(ksq) & ~bb(rsq);
        u64 kpath = BETWEEN[ksq][k_dest] | bb(k_dest);
        bool safe = true;
        u64 kp = kpath;
        while (kp) {
          int s = lsb(kp);
          kp &= kp - 1;
          if (attackers(them, s, occ2)) {
            safe = false;
            break;
          }
        }
        if (safe) push(ksq, rsq);
      }
    }
  }

  bool is_castle_move(const Move& m) const {
    return piece_at(m.from, turn) == KING && (pieces[turn][ROOK] & bb(m.to));
  }

  void legal_moves(std::vector<Move>& out) const {
    std::vector<Move> pseudo;
    pseudo_moves(pseudo);
    out.clear();
    for (const Move& m : pseudo) {
      if (is_castle_move(m)) {
        out.push_back(m);  // castling generator already verified safety
        continue;
      }
      Pos child = *this;
      child.apply(m);
      if (!child.in_check(turn)) out.push_back(m);
    }
  }
};

// ---- FEN ----------------------------------------------------------------

int parse_fen(const char* fen, Pos& pos) {
  pos = Pos();
  std::string s(fen);
  std::vector<std::string> parts;
  size_t i = 0;
  while (i < s.size()) {
    while (i < s.size() && s[i] == ' ') i++;
    size_t j = i;
    while (j < s.size() && s[j] != ' ') j++;
    if (j > i) parts.push_back(s.substr(i, j - i));
    i = j;
  }
  if (parts.empty()) return -1;
  int rank = 7, file = 0;
  for (char c : parts[0]) {
    if (c == '/') {
      if (file != 8) return -2;
      rank--;
      file = 0;
    } else if (isdigit((unsigned char)c)) {
      file += c - '0';
    } else if (c == '~') {
      continue;  // promoted marker (crazyhouse FENs); ignore
    } else {
      if (file > 7 || rank < 0) return -2;
      int color = isupper((unsigned char)c) ? WHITE : BLACK;
      int t;
      switch (tolower((unsigned char)c)) {
        case 'p': t = PAWN; break;
        case 'n': t = KNIGHT; break;
        case 'b': t = BISHOP; break;
        case 'r': t = ROOK; break;
        case 'q': t = QUEEN; break;
        case 'k': t = KING; break;
        default: return -3;
      }
      pos.pieces[color][t] |= bb(rank * 8 + file);
      file++;
    }
  }
  if (rank != 0 || file != 8) return -2;
  pos.refresh();
  pos.turn = (parts.size() > 1 && parts[1] == "b") ? BLACK : WHITE;
  if (parts.size() > 2 && parts[2] != "-") {
    for (char c : parts[2]) {
      int color = isupper((unsigned char)c) ? WHITE : BLACK;
      u64 back = color == WHITE ? RANK_1 : RANK_8;
      int ksq = pos.king_sq(color);
      u64 rooks = pos.pieces[color][ROOK] & back;
      char lc = tolower((unsigned char)c);
      if (lc == 'k' || lc == 'q') {
        if (ksq < 0) continue;
        int bestsq = -1;
        u64 r = rooks;
        while (r) {
          int sq = lsb(r);
          r &= r - 1;
          if (lc == 'k' && sq > ksq && sq > bestsq) bestsq = sq;
          if (lc == 'q' && sq < ksq && (bestsq < 0 || sq < bestsq)) bestsq = sq;
        }
        if (bestsq >= 0) pos.castling |= bb(bestsq);
      } else if (lc >= 'a' && lc <= 'h') {
        int sq = (color == WHITE ? 0 : 56) + (lc - 'a');
        pos.castling |= bb(sq);
      } else {
        return -4;
      }
    }
  }
  if (parts.size() > 3 && parts[3] != "-" && parts[3].size() == 2) {
    pos.ep = (parts[3][1] - '1') * 8 + (parts[3][0] - 'a');
  }
  size_t idx = 4;
  if (parts.size() > idx && parts[idx].find('+') != std::string::npos) idx++;
  if (parts.size() > idx) pos.halfmove = atoi(parts[idx].c_str());
  if (parts.size() > idx + 1) pos.fullmove = atoi(parts[idx + 1].c_str());
  if (popcount(pos.pieces[WHITE][KING]) != 1 ||
      popcount(pos.pieces[BLACK][KING]) != 1)
    return -5;
  // side not to move must not be capturable
  if (pos.in_check(pos.turn ^ 1)) return -6;
  return 0;
}

std::string to_fen(const Pos& pos) {
  std::string out;
  for (int rank = 7; rank >= 0; rank--) {
    int empty = 0;
    for (int file = 0; file < 8; file++) {
      int sq = rank * 8 + file;
      char c = 0;
      for (int col = 0; col < 2 && !c; col++) {
        int t = pos.piece_at(sq, col);
        if (t >= 0) {
          c = "pnbrqk"[t];
          if (col == WHITE) c = toupper(c);
        }
      }
      if (!c) {
        empty++;
      } else {
        if (empty) out += std::to_string(empty);
        empty = 0;
        out += c;
      }
    }
    if (empty) out += std::to_string(empty);
    if (rank) out += '/';
  }
  out += pos.turn == WHITE ? " w " : " b ";
  std::string cast;
  for (int color = 0; color < 2; color++) {
    u64 back = color == WHITE ? RANK_1 : RANK_8;
    int ksq = pos.king_sq(color);
    u64 rooks = pos.pieces[color][ROOK] & back;
    // emit in descending square order (kingside first)
    for (int sq = 63; sq >= 0; sq--) {
      if (!(pos.castling & back & bb(sq))) continue;
      char c;
      bool outermost = true;
      u64 r = rooks;
      while (r) {
        int other = lsb(r);
        r &= r - 1;
        if (sq > ksq && other > sq) outermost = false;
        if (sq < ksq && other < sq) outermost = false;
      }
      if (ksq >= 0 && outermost)
        c = sq > ksq ? 'k' : 'q';
      else
        c = 'a' + (sq & 7);
      cast += color == WHITE ? toupper(c) : c;
    }
  }
  out += cast.empty() ? "-" : cast;
  out += ' ';
  if (pos.ep >= 0) {
    out += ('a' + (pos.ep & 7));
    out += ('1' + (pos.ep >> 3));
  } else {
    out += '-';
  }
  out += ' ' + std::to_string(pos.halfmove) + ' ' + std::to_string(pos.fullmove);
  return out;
}

std::string move_uci(const Move& m) {
  std::string s;
  s += 'a' + (m.from & 7);
  s += '1' + (m.from >> 3);
  s += 'a' + (m.to & 7);
  s += '1' + (m.to >> 3);
  if (m.promo >= 0) s += "pnbrqk"[m.promo];
  return s;
}

int parse_uci(const Pos& pos, const std::string& s, Move& out) {
  if (s.size() < 4 || s.size() > 5) return -1;
  auto sq = [](char f, char r) -> int {
    if (f < 'a' || f > 'h' || r < '1' || r > '8') return -1;
    return (r - '1') * 8 + (f - 'a');
  };
  int from = sq(s[0], s[1]), to = sq(s[2], s[3]);
  if (from < 0 || to < 0) return -1;
  int promo = -1;
  if (s.size() == 5) {
    switch (s[4]) {
      case 'n': promo = KNIGHT; break;
      case 'b': promo = BISHOP; break;
      case 'r': promo = ROOK; break;
      case 'q': promo = QUEEN; break;
      default: return -1;
    }
  }
  Move m{from, to, promo};
  // normalize standard castling notation (e1g1) to king-takes-rook
  if (pos.piece_at(from, pos.turn) == KING &&
      !(pos.pieces[pos.turn][ROOK] & bb(to))) {
    int df = (to & 7) - (from & 7);
    if ((df == 2 || df == -2) && (to >> 3) == (from >> 3)) {
      u64 back = pos.turn == WHITE ? RANK_1 : RANK_8;
      u64 rights = pos.castling & back & pos.pieces[pos.turn][ROOK];
      int best = -1;
      u64 r = rights;
      while (r) {
        int rs = lsb(r);
        r &= r - 1;
        if (df > 0 && rs > from && rs > best) best = rs;
        if (df < 0 && rs < from && (best < 0 || rs < best)) best = rs;
      }
      if (best >= 0) m = Move{from, best, -1};
    }
  }
  std::vector<Move> legal;
  pos.legal_moves(legal);
  for (const Move& lm : legal)
    if (lm == m) {
      out = m;
      return 0;
    }
  return -2;
}

long long perft_inner(const Pos& pos, int depth) {
  std::vector<Move> moves;
  pos.legal_moves(moves);
  if (depth <= 1) return (long long)moves.size();
  long long total = 0;
  for (const Move& m : moves) {
    Pos child = pos;
    child.apply(m);
    total += perft_inner(child, depth - 1);
  }
  return total;
}

int put_str(const std::string& s, char* out, int cap) {
  if ((int)s.size() + 1 > cap) return -10;
  memcpy(out, s.c_str(), s.size() + 1);
  return 0;
}

}  // namespace

extern "C" {

// Replay a game: validate `fen` and every space-separated UCI move.
// On success returns 0, writes the final FEN and the Chess960-normalized
// moves. Returns 1+index for the first illegal move, negative for FEN errors.
int cc_replay_game(const char* fen, const char* moves, char* out_fen,
                   int out_fen_cap, char* out_moves, int out_moves_cap) {
  Pos pos;
  int err = parse_fen(fen, pos);
  if (err) return err;
  std::string norm;
  std::string token;
  const char* p = moves;
  int index = 0;
  while (true) {
    if (*p == ' ' || *p == '\0') {
      if (!token.empty()) {
        Move m;
        if (parse_uci(pos, token, m)) return 1 + index;
        if (!norm.empty()) norm += ' ';
        norm += move_uci(m);
        pos.apply(m);
        index++;
        token.clear();
      }
      if (*p == '\0') break;
    } else {
      token += *p;
    }
    p++;
  }
  if (int rc = put_str(to_fen(pos), out_fen, out_fen_cap)) return rc;
  if (int rc = put_str(norm, out_moves, out_moves_cap)) return rc;
  return 0;
}

long long cc_perft(const char* fen, int depth) {
  Pos pos;
  if (parse_fen(fen, pos)) return -1;
  if (depth <= 0) return 1;
  return perft_inner(pos, depth);
}

int cc_legal_moves(const char* fen, char* out, int cap) {
  Pos pos;
  int err = parse_fen(fen, pos);
  if (err) return err;
  std::vector<Move> moves;
  pos.legal_moves(moves);
  std::string s;
  for (const Move& m : moves) {
    if (!s.empty()) s += ' ';
    s += move_uci(m);
  }
  if (int rc = put_str(s, out, cap)) return rc;
  return (int)moves.size();
}

int cc_version() { return 1; }

}  // extern "C"
