"""The TPU batch engine: chunks in, PositionResponses out.

Replaces the reference's engine subprocess + UCI dialogue (reference:
src/stockfish.rs:222-465) with a host→device dispatch: all positions of a
chunk (and all multipv root moves) become lanes of one lockstep
alpha-beta search. Iterative deepening runs host-side, filling the same
multipv×depth score/pv matrices the UCI parser would have accumulated.

Lane counts are padded to fixed buckets so XLA compiles a handful of
program shapes, then caches.
"""
from __future__ import annotations

import asyncio
import sys
import threading
import time
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..chess.position import Position
from ..chess.variants import from_fen
from ..client.ipc import Chunk, Matrix, PositionResponse, WorkPosition
from ..client.wire import AnalysisWork, MoveWork, Score
from ..models import nnue
from ..ops import search as search_ops
from ..ops.board import from_position, stack_boards
from ..obs import inflight as obs_inflight
from ..obs import metrics as obs_metrics
from ..obs import trace as obs_trace
from ..ops.search import INF, MATE, search_batch_resumable
from ..utils import sanitize
from ..utils import settings
from ..utils.syncstats import SegmentController, SyncStats
from .base import EngineError
from .session import ChunkSubmit

# static stack depth; supports search depths up to MAX_PLY-1, with the
# tail past the nominal depth doubling as quiescence headroom (32 leaves
# depth-22 move jobs 10 QS plies — reference skill-8 depth, src/api.rs:275-281).
# Env-tunable because compile cost scales with it: tests and CPU smoke runs
# set a small value (the full program takes minutes to compile on XLA:CPU)
MAX_PLY = settings.get_int("FISHNET_TPU_MAX_PLY")
# 16 covers every single-pv chunk (planner emits ≤10 positions per chunk,
# incl. skip-overlap re-appends — client/planner.py); 64 covers multipv
# root-move lanes. Fewer buckets = fewer cold XLA compiles to warm up.
LANE_BUCKETS = (16, 64, 128, 256)

# aspiration window half-widths tried in order by _search_windowed (the
# final full-width attempt is implicit). Measured on the standard 8-FEN
# set at depth 5 via aspiration_stats (docs/depth.md §"Aspiration
# deltas, measured"): (15, 120) searched the fewest total nodes of the
# six schedules tried — a narrow first rung fails ~2/3 of the time but
# the windowed tree it cuts outweighs the re-searches, and the 120 rung
# catches 90% of the escapees. The old hardcoded (30, 200) measured ~5%
# more nodes; wider schedules up to (60, 250) measured ~9-14% more.
ASPIRATION_DELTAS = settings.get_csv_int("FISHNET_TPU_ASPIRATION") or (15, 120)


def _decode_uci(m: int) -> str:
    frm, to, promo = m & 63, (m >> 6) & 63, (m >> 12) & 7
    if (m >> 15) & 1:  # crazyhouse drop: P@e4 style
        return "PNBRQ"[promo & 7] + "@" + "abcdefgh"[to & 7] + str((to >> 3) + 1)
    s = (
        "abcdefgh"[frm & 7] + str((frm >> 3) + 1)
        + "abcdefgh"[to & 7] + str((to >> 3) + 1)
    )
    if promo:
        s += " nbrqk"[promo]  # 5 = king (antichess promotion)
    return s


# chunk.variant → device search program (ops/search.py static flag);
# variants not listed fall back to host engines via the planner routing.
# All seven lichess variants the reference analyses (src/logger.rs:201-213)
# run on device.
DEVICE_VARIANTS = {
    "standard": "standard",
    "chess960": "standard",
    "fromPosition": "standard",
    "threeCheck": "threeCheck",
    "3check": "threeCheck",
    "crazyhouse": "crazyhouse",
    "antichess": "antichess",
    "atomic": "atomic",
    "horde": "horde",
    "kingOfTheHill": "kingOfTheHill",
    "racingKings": "racingKings",
}


def _score_from_int(v: int, root_ply_to_mate_sign: int = 1) -> Score:
    if v >= MATE - 1000:
        return Score.mate((MATE - v + 1) // 2)
    if v <= -(MATE - 1000):
        return Score.mate(-((MATE + v + 1) // 2))
    return Score.cp(int(v))


def skill_pick(ranked, sf_skill: int, rng):
    """Pick a (score, idx) entry from descending-ranked root moves with
    lichess skill semantics (the TPU-native analog of Stockfish's "Skill
    Level", reference src/api.rs:248-283 maps level 1-8 → Skill Level):
    below full strength the move is drawn from the near-best candidates
    with probability decaying in the cp gap, the acceptance window
    (120 - 2*skill) widening as skill drops. Shared by the engine's move
    jobs and tools/strength_ab.py's skill-vs-skill validation."""
    import math

    top = ranked[0][0]
    if sf_skill >= 20 or len(ranked) == 1:
        return ranked[0]
    weakness = 120 - 2 * sf_skill
    cands = [r for r in ranked if top - r[0] <= 3 * weakness]
    weights = [math.exp(-(top - r[0]) / weakness) for r in cands]
    return rng.choices(cands, weights=weights, k=1)[0]


def _move_job_floor(variant: str) -> int:
    """Minimum move-job lane count per variant — MUST match what
    warmup_variants precompiles, or the first job pays a cold compile
    against its 7 s deadline. Crazyhouse drops push legal counts past
    64, so its bucket is 128."""
    return 128 if variant == "crazyhouse" else 64


def _pad_lanes(n: int) -> int:
    for b in LANE_BUCKETS:
        if n <= b:
            return b
    return ((n + 255) // 256) * 256


class TpuEngine(ChunkSubmit):
    """Batched analysis engine. `variants` lists what it accepts (the
    planner routes only those here — client/planner.py tpu_variants)."""

    def __init__(
        self,
        params: Optional[nnue.NnueParams] = None,
        weights_path: Optional[str] = None,
        max_depth: int = 12,  # production value flows from configure.tpu_depth
        seed: int = 1234,
        tt_size_log2: int = 21,  # 2M slots ≈ 24 MiB HBM; 0 disables
        max_lanes: Optional[int] = None,  # single-dispatch lane ceiling
        helper_lanes: Optional[int] = None,  # Lazy-SMP lanes per position (K)
        refill: Optional[bool] = None,  # continuous lane refill (LaneScheduler)
        mesh_refill: Optional[bool] = None,  # refill on mesh hosts too
        logger=None,  # client Logger for operational warnings; stderr if None
    ) -> None:
        from ..utils import enable_compile_cache

        enable_compile_cache()  # restarts reuse compiled search programs
        # all chips on the host run one sharded program: lanes shard over a
        # 1-D mesh and each device advances its shard independently — the
        # TPU equivalent of the reference's engine-process-per-core
        # (src/main.rs:151-161). Single-device hosts skip the mesh.
        from ..parallel import distributed as dist_mod
        from ..parallel.mesh import make_mesh, make_sharded_table

        # FISHNET_TPU_MESH_HOSTS > 1: join the jax.distributed pod
        # BEFORE the first jax.devices() call, so the mesh below spans
        # the global device set — one logical engine across processes
        dist_mod.ensure_initialized(logger=logger)
        n_dev = len(jax.devices())
        self.mesh = make_mesh() if n_dev > 1 else None
        self.n_dev = n_dev if self.mesh is not None else 1
        # one shared transposition table for every lane and every chunk —
        # the per-process persistent hash (reference: Stockfish's TT,
        # ~64 MiB/core README.md:76). Sharded per device under the mesh.
        # Chunks are dispatched one at a time (self._lock): concurrent
        # executor threads would otherwise interleave whole-table swaps
        # and silently discard each other's stores.
        from ..ops import tt as tt_mod

        self.tt_size_log2 = tt_size_log2
        if not tt_size_log2:
            self.tt = None
        elif self.mesh is not None:
            self.tt = make_sharded_table(self.mesh, tt_size_log2)
        else:
            self.tt = tt_mod.make_table(tt_size_log2)
        self._lock = threading.Lock()
        if params is None:
            if weights_path and str(weights_path).endswith(".nnue"):
                # real Stockfish network file (models/nnue_import.py)
                from ..models import nnue_import

                params = nnue_import.load_nnue(weights_path).as_device()
            elif weights_path:
                params = nnue.load_params(weights_path)
            else:
                # packaged weights (assets.py); board768 = the
                # fully-incremental fast path (see models/nnue.py)
                from ..assets import load_default_params

                params = load_default_params("board768")
            if params is None:
                params = nnue.init_params(
                    jax.random.PRNGKey(seed), l1=64, feature_set="board768"
                )
        self._logger = logger
        # AOT program assets (fishnet_tpu/aot/): when a packed bundle
        # matches this process's fingerprint, the wrapped search jits
        # load serialized executables instead of compiling, and warmup
        # below becomes a no-op. Install is idempotent process-wide.
        from ..aot import registry as aot_registry

        self.aot = aot_registry.install_from_settings(logger=self._warn)
        # FISHNET_TPU_DTYPE quantizes the weights (SURVEY §7.2):
        # bf16 → MXU-native float inputs, f32 accumulators. The int8
        # fixed-point ladder (nnue.quantize_int8) measured a NET LOSS at
        # the production shape (round 5, bench_matrix.json dtype_int8:
        # 37.2 knps vs 58-95 knps f32 — int32 dots keep the MXU idle),
        # so it survives only as an experiment behind an extra flag.
        dtype_env = (settings.get_str("FISHNET_TPU_DTYPE") or "").lower()
        if dtype_env in ("bf16", "bfloat16"):
            params = nnue.cast_params(params, jnp.bfloat16)
        elif dtype_env == "int8":
            if settings.get_bool("FISHNET_TPU_EXPERIMENTAL_INT8"):
                self._warn(
                    "experimental int8 weights enabled: measured SLOWER "
                    "than f32 at production shapes (37.2 vs 58-95 knps, "
                    "round-5 bench)"
                )
                if nnue.is_board768(params):
                    params = nnue.quantize_int8(params)
            else:
                self._warn(
                    "FISHNET_TPU_DTYPE=int8 ignored: measured a net loss "
                    "vs f32 (37.2 vs 58-95 knps); set "
                    "FISHNET_TPU_EXPERIMENTAL_INT8=1 to run it anyway"
                )
        self.params = params
        self.max_depth = max_depth
        # B=2048 falls off the VMEM cliff on v5e (docs/tpu-hang.md round 5:
        # ~1024 lanes is the ceiling) — never let one dispatch exceed it;
        # multipv is the only shape that can (every legal root move of
        # every chunk position becomes a lane)
        self.max_lanes = (
            max_lanes
            if max_lanes is not None
            else settings.get_int("FISHNET_TPU_MAX_LANES")
        )
        # Lazy-SMP helper lanes (docs/profile-r5.md §"Batch completion of
        # deep searches"): an analysed position may occupy up to K lanes —
        # one PRIMARY whose score/PV is the reported result (oracle
        # semantics intact), plus up to K-1 HELPERS searching the same
        # root with jittered move ordering, staggered aspiration windows
        # and +1-ply depth offsets, communicating only through the shared
        # TT. K=1 disables the machinery entirely and is bit-identical to
        # the pre-helper engine; no TT forces K=1 (helpers without the
        # communication channel are pure waste).
        if helper_lanes is None:
            helper_lanes = settings.get_int("FISHNET_TPU_HELPERS")
        self.helper_lanes = max(1, min(int(helper_lanes), 16))
        if self.tt is None:
            self.helper_lanes = 1
        # TT generation counter, bumped per chunk: helper-mode stores
        # carry it so depth-preferred replacement never protects stale
        # entries from earlier chunks (ops/tt.py store)
        self._tt_gen = 0
        # Continuous lane refill (continuous batching from LLM serving,
        # Orca OSDI'22, mapped onto search lanes): single-pv analysis
        # chunks flow through the LaneScheduler, which keeps one
        # full-width compiled step busy by splicing queued positions
        # into DONE lanes at segment boundaries instead of narrowing
        # and draining chunks serially. On mesh hosts the scheduler
        # drives the shard_map'd segment/refill callables
        # (parallel/mesh.py): each device resplices ITS lanes locally
        # and the boundary is one stacked-summary fetch, so the same
        # occupancy win extends across chips. FISHNET_TPU_MESH_REFILL=0
        # pins meshed engines back to strict chunk-serial dispatch
        # (single-device hosts ignore it); everything else — move jobs,
        # multipv, refill off — takes the chunk-serial path, which
        # stays bit-identical to the pre-refill engine.
        if refill is None:
            refill = settings.get_bool("FISHNET_TPU_REFILL")
        self.refill = bool(refill)
        if mesh_refill is None:
            mesh_refill = settings.get_bool("FISHNET_TPU_MESH_REFILL")
        self.mesh_refill = bool(mesh_refill)
        self._scheduler = LaneScheduler(self)
        # per-segment occupancy accounting (live/helper/idle lane
        # counts, refill events), surfaced into bench rows and logs
        self.occupancy_log: List[dict] = []
        self.occupancy_totals = {
            "segments": 0, "steps": 0, "lane_steps": 0,
            "live_lane_steps": 0, "helper_lane_steps": 0,
            "idle_lane_steps": 0, "refills": 0, "positions_done": 0,
            # segment-boundary cost split (utils/syncstats.py): wall-clock
            # the host spent blocked on device results vs doing boundary
            # bookkeeping, plus the host-device transfer count
            "host_ms": 0.0, "device_ms": 0.0, "transfers": 0,
        }
        # per-delta aspiration accounting {delta: [windowed, fail_lo,
        # fail_hi, nodes]} — the measured basis for ASPIRATION_DELTAS
        # (see docs/depth.md §"Aspiration deltas, measured")
        self.aspiration_stats: dict = {}
        # exactly-once delivery hook: called as (wp, response) the moment
        # a position's result is finalized, before the chunk completes.
        # engine/host.py points this at its `partial` frame emitter so
        # the supervisor's session journal sees incremental progress.
        self.on_response = None
        # chunk-aware sibling of on_response, called as (chunk, wp,
        # response) from the same exactly-once delivery point: the
        # analysis cache (fishnet_tpu/cache/) fills from here, so
        # speculative, replayed and re-dispatched results populate it
        # once — the chunk carries the variant/work shape the cache key
        # needs and the bare WorkPosition doesn't.
        self.on_deliver = None
        # TT warm slices (cache/ttwarm.py, FISHNET_TPU_CACHE_TT):
        # when set, _submit splices persisted opening-prefix TT rows
        # into the shared table before the chunk's refill jobs run, and
        # run_chunk exports the rows the search earned back out.
        self.tt_warm = None
        self.tt_warm_prefix = 8
        # FISHNET_TPU_TRACE=1: per-dispatch / per-depth timing lines to
        # stderr (verdict A1: a hang or slow depth must be localizable
        # from logs — compile-vs-run shows up as a slow FIRST dispatch
        # of a shape, steady-state cost as the later ones)
        self.trace = (
            (lambda msg: print(f"T: {msg}", file=sys.stderr, flush=True))
            if settings.get_bool("FISHNET_TPU_TRACE")
            else None
        )

    def _warn(self, msg: str) -> None:
        if self._logger is not None:
            self._logger.warn(msg)
        else:
            print(f"W: {msg}", file=sys.stderr, flush=True)

    def warmup(self, buckets=None, log=None, deep=None) -> List[str]:
        """Pre-compile the hot search program for every production lane
        bucket.

        XLA caches one program per (lane bucket, MAX_PLY) shape; without
        this, the first chunk of a new shape pays 20-40 s of compile
        against its deadline (move jobs have a 7 s deadline — they would
        always fail cold; a first 128/256-lane multipv chunk used to race
        a cold compile too). The reference similarly does its engine prep
        before workers start (Assets::prepare, src/main.rs:94).
        FISHNET_TPU_WARMUP_BUCKETS="16" overrides (e.g. CPU smoke runs
        where each extra compile costs minutes). log: optional callable
        for per-bucket progress lines. deep: compile the distinct
        deep-TT move-job program too; default None = only for the
        untrimmed production bucket set (explicit-bucket callers that
        will serve move jobs must pass deep=True — the program is
        REQUIRED before the first 7 s-deadline move job)."""
        import time as _time

        # an explicitly trimmed set — env var OR caller-supplied buckets
        # (CPU smoke runs/tests) — skips the extra deep_tt program below;
        # only the no-argument production default pays for full prep
        trimmed = buckets is not None
        if buckets is None:
            buckets = (
                settings.get_csv_int("FISHNET_TPU_WARMUP_BUCKETS")
                or LANE_BUCKETS
            )
            trimmed = settings.is_set("FISHNET_TPU_WARMUP_BUCKETS")
        want_deep = deep if deep is not None else not trimmed
        covered = ["buckets"] + (["deep"] if want_deep else [])
        # AOT bundle covering exactly what this warmup would compile:
        # skip it — the wrapped jits load serialized executables at
        # first dispatch in milliseconds instead of compiling here.
        from ..aot import registry as aot_registry

        if aot_registry.warm_covers(*covered):
            rep = aot_registry.boot_report()
            if log is not None:
                log(
                    f"warmup: skipped — AOT bundle {rep.get('fingerprint')} "
                    f"preloads {rep.get('programs')} programs (covers "
                    f"{','.join(rep.get('covers') or [])}); executables "
                    f"load at first dispatch"
                )
            return covered
        for b in buckets:
            b = self._pad(b)
            t0 = _time.monotonic()
            roots = stack_boards([from_position(Position.initial())] * b)
            # with helper lanes enabled, every production analysis
            # dispatch compiles the helper-mode program (prefer_deep
            # stores are a static flag) — warm THAT variant, or the
            # first chunk pays the cold compile anyway
            self._search(
                roots, np.ones(b, np.int32), np.full(b, 64, np.int32),
                helper_store=self.helper_lanes > 1,
            )
            if log is not None:
                log(
                    f"warmup: {b}-lane search program compiled "
                    f"({_time.monotonic() - t0:.1f}s)"
                )
        # move jobs run a DISTINCT program (deep-bounds TT probes are a
        # static compile flag) at the 64-lane root-move bucket — without
        # this the first move job pays a cold compile against its 7 s
        # deadline and always fails. Skipped by default whenever the
        # bucket set was trimmed (env var or explicit caller buckets —
        # usually a CPU smoke run/test that serves no move jobs and
        # where each extra compile costs minutes).
        if not want_deep:
            return covered
        b = self._pad(64)  # root-move lanes pad to 64 for ≤64 legal moves
        t0 = _time.monotonic()
        roots = stack_boards([from_position(Position.initial())] * b)
        self._search(
            roots, np.ones(b, np.int32), np.full(b, 64, np.int32),
            deep_tt=True,
        )
        if log is not None:
            log(
                f"warmup: {b}-lane move-job program compiled "
                f"({_time.monotonic() - t0:.1f}s)"
            )
        return covered

    def warmup_variants(self, log=None) -> List[str]:
        """Compile the per-variant search programs (each variant is a
        distinct statically compiled program — a cold compile at the
        first variant chunk would race its deadline; move jobs' 7 s
        deadline always loses that race). Meant to run in the background
        AFTER the standard warmup. Runs WITHOUT the engine lock against
        a scratch TT of the production shape: holding the serving lock
        across a 20-40 s compile would stall a live move job past its
        7 s deadline before its own clock even started (XLA's compile
        cache is process-wide, so the compiled program still serves the
        live table).

        FISHNET_TPU_WARMUP_VARIANTS: comma list, "all", or "none";
        default warms all device variants on real accelerators and none
        on CPU (where each extra compile costs minutes — tests and smoke
        runs)."""
        import time as _time

        env = settings.get_str("FISHNET_TPU_WARMUP_VARIANTS") or "auto"
        if env.lower() == "auto":
            if jax.default_backend() == "cpu":
                return []
            variants = sorted(set(DEVICE_VARIANTS.values()) - {"standard"})
        elif env.lower() in ("", "none"):
            return []
        elif env.lower() == "all":
            variants = sorted(set(DEVICE_VARIANTS.values()) - {"standard"})
        else:
            variants = [v for v in env.split(",") if v]
        from ..aot import registry as aot_registry

        if aot_registry.warm_covers("variants"):
            if log is not None:
                log(
                    "warmup: variant programs covered by the AOT bundle; "
                    "background compiles skipped"
                )
            return []
        for variant in variants:
            # 16 lanes / exact-depth probes: analysis chunks.
            # _move_job_floor lanes / deep-bounds probes: move-job
            # root-move lanes (the reference routes ALL move jobs to the
            # variant engine, src/queue.rs:562-568, so this is the
            # deadline-critical one)
            for b, deep in ((16, False), (_move_job_floor(variant), True)):
                b = self._pad(b)
                t0 = _time.monotonic()
                start = from_fen(
                    {
                        "crazyhouse": (
                            "rnbqkbnr/pppppppp/8/8/8/8/PPPPPPPP/RNBQKBNR[] "
                            "w KQkq - 0 1"
                        ),
                        "horde": (
                            "rnbqkbnr/pppppppp/8/1PP2PP1/PPPPPPPP/PPPPPPPP/"
                            "PPPPPPPP/PPPPPPPP w kq - 0 1"
                        ),
                        "racingKings": "8/8/8/8/8/8/krbnNBRK/qrbnNBRQ w - - 0 1",
                    }.get(variant, "rnbqkbnr/pppppppp/8/8/8/8/PPPPPPPP/RNBQKBNR w KQkq - 0 1"),
                    variant,
                )
                roots = stack_boards([from_position(start)] * b)
                self._search(
                    roots, np.ones(b, np.int32), np.full(b, 64, np.int32),
                    variant=variant, deep_tt=deep,
                    # a fresh scratch per dispatch: segment dispatches
                    # DONATE the table (ops/search.py), so a shared
                    # scratch would be consumed by the first search
                    tt_override=self._scratch_tt(),
                    # analysis dispatches run the helper-mode program
                    # when helper lanes are on; move jobs stay plain
                    helper_store=(not deep) and self.helper_lanes > 1,
                )
                if log is not None:
                    log(
                        f"warmup: {variant} {b}-lane program compiled "
                        f"({_time.monotonic() - t0:.1f}s)"
                    )
        return variants

    async def go_multiple(self, chunk: Chunk) -> List[PositionResponse]:
        loop = asyncio.get_running_loop()
        try:
            return await loop.run_in_executor(None, self._go_multiple_sync, chunk)
        except EngineError:
            raise
        except Exception as e:  # device/compile errors surface as EngineError
            raise EngineError(f"tpu engine failed: {e}") from e

    async def close(self) -> None:
        pass

    # ----------------------------------------------------------------- sync

    def _pad(self, n: int) -> int:
        b = _pad_lanes(n)
        if b % self.n_dev:
            b = ((b + self.n_dev - 1) // self.n_dev) * self.n_dev
        return b

    def _scratch_tt(self):
        """A throwaway table with the SAME shape as self.tt — warmup
        compiles the production program shapes against it without
        touching (or locking) the live table."""
        if self.tt is None:
            return None
        from ..ops import tt as tt_mod
        from ..parallel.mesh import make_sharded_table

        if self.mesh is not None:
            return make_sharded_table(self.mesh, self.tt_size_log2)
        return tt_mod.make_table(self.tt_size_log2)

    def _search(self, roots, depth_arr, budget_arr, deadline=None,
                variant="standard", hist=None, window=None,
                deep_tt=False, tt_override=None, order_jitter=None,
                group=None, required=None, helper_store=False):
        # the TT is shared across variants: variant state is hashed into
        # the key (ops/tt.py), so entries can't collide across rule sets.
        # tt_override: search against a caller-owned table (warmup
        # scratch) and leave self.tt alone — such calls don't need the
        # engine lock.
        # order_jitter/group/required: Lazy-SMP lane-group layout (see
        # search_batch_resumable); helper_store switches TT stores to the
        # depth-preferred generation-aware policy. helper_store is a
        # STATIC compile flag: it is set for ALL analysis dispatches
        # whenever helper lanes are enabled (multipv groups too, which
        # benefit from the same shallow-write protection) so warmup
        # compiles exactly one program per bucket either way.
        t0 = time.monotonic()
        out = search_batch_resumable(
            self.params, roots, jnp.asarray(depth_arr),
            jnp.asarray(budget_arr), max_ply=MAX_PLY,
            deadline=deadline,
            tt=self.tt if tt_override is None else tt_override,
            mesh=self.mesh,
            variant=variant, hist=hist, window=window, deep_tt=deep_tt,
            order_jitter=order_jitter, group=group, required=required,
            prefer_deep_store=helper_store,
            tt_gen=self._tt_gen if helper_store else 0,
            # deep_tt = move jobs: their narrowed widths would be
            # deep-bounds programs warmup never compiled, and a cold XLA
            # compile inside the 7 s move deadline loses the job. Their
            # lanes are one position's root moves at uniform depth — they
            # finish together, so narrowing has nothing to retire anyway.
            # Analysis narrows through warmed widths only (LANE_BUCKETS
            # halvings land on LANE_BUCKETS members).
            narrow=not deep_tt,
        )
        if tt_override is None:
            self.tt = out.pop("tt")
        else:
            out.pop("tt")
        out = {k: np.asarray(v) for k, v in out.items()}
        if self.trace:
            dt = time.monotonic() - t0
            nodes = int(out["nodes"].sum())
            self.trace(
                f"dispatch variant={variant} B={int(roots.stm.shape[0])} "
                f"maxdepth={int(np.max(depth_arr))} steps={int(out['steps'])} "
                f"nodes={nodes} wall={dt:.3f}s "
                f"nps={nodes / max(dt, 1e-9):,.0f}"
            )
        return out

    def _search_windowed(self, roots, depth_arr, budget_arr, deadline,
                         variant, hist, prev_score, use_win,
                         required=None, win_scale=None, order_jitter=None,
                         group=None, helper_store=False):
        """Aspiration-windowed dispatch (classic iterative-deepening win:
        a narrow window around the previous depth's score cuts most of
        the tree; a fail-low/high re-searches wider, settled lanes ride
        along at depth 0 / budget 1). Returns the merged result dict with
        per-lane nodes summed over attempts.

        Helper-lane extensions: `required` marks the primary lanes —
        only THEIR fail-low/high triggers a re-search (a helper failing
        its window costs nothing; its TT entries already landed), and
        the dispatch stops once all primaries finish. `win_scale` widens
        each lane's delta (staggered helper windows: a helper searching
        a wider window than its primary fails less and seeds EXACT
        entries the primary's re-search can use). Helpers ride along on
        the FIRST attempt only — re-search attempts are primary-only."""
        B = int(depth_arr.shape[0])
        deltas = ASPIRATION_DELTAS + (None,)  # None = full window
        primary = (
            np.ones(B, bool) if required is None
            else np.asarray(required, bool)
        )
        scale = (
            np.ones(B, np.int64) if win_scale is None
            else np.asarray(win_scale, np.int64)
        )
        merged = None
        nodes_acc = np.zeros(B, np.int64)
        live = np.ones(B, bool)
        prev_score = np.asarray(prev_score, np.int64)
        for delta in deltas:
            if delta is None or not use_win.any():
                alpha_w = np.full(B, -INF, np.int32)
                beta_w = np.full(B, INF, np.int32)
            else:
                # clip into [-INF, INF]: a clipped-to-INF bound reads as
                # no-window on that side (the fail checks below exclude it)
                alpha_w = np.where(
                    use_win, np.maximum(prev_score - delta * scale, -INF), -INF
                ).astype(np.int32)
                beta_w = np.where(
                    use_win, np.minimum(prev_score + delta * scale, INF), INF
                ).astype(np.int32)
            out = self._search(
                roots,
                np.where(live, depth_arr, 0).astype(np.int32),
                np.where(live, budget_arr, 1).astype(np.int32),
                deadline, variant=variant, hist=hist,
                window=(alpha_w, beta_w),
                order_jitter=order_jitter, group=group,
                required=required, helper_store=helper_store,
            )
            if merged is None:
                merged = {k: np.array(v) for k, v in out.items()}
            else:
                for k in ("score", "move", "pv", "pv_len", "done"):
                    merged[k][live] = out[k][live]
            nodes_acc[live] += out["nodes"][live]
            score = out["score"]
            fail_lo = (
                live & primary & out["done"]
                & (score <= alpha_w) & (alpha_w > -INF)
            )
            fail_hi = (
                live & primary & out["done"]
                & (score >= beta_w) & (beta_w < INF)
            )
            fail = fail_lo | fail_hi
            if delta is not None and use_win.any():
                st = self.aspiration_stats.setdefault(delta, [0, 0, 0, 0])
                st[0] += int((use_win & live & primary).sum())
                st[1] += int(fail_lo.sum())
                st[2] += int(fail_hi.sum())
                st[3] += int(out["nodes"][live].sum())
            if self.trace and delta is not None and use_win.any():
                # aspiration economics (round-3 verdict: window deltas
                # were guesses with no recorded fail rates or costs)
                self.trace(
                    f"aspiration delta={delta}: windowed="
                    f"{int((use_win & live & primary).sum())} "
                    f"fail_lo={int(fail_lo.sum())} "
                    f"fail_hi={int(fail_hi.sum())} "
                    f"nodes={int(out['nodes'][live].sum())}"
                )
            # lanes that didn't finish (deadline) stay merged as not-done
            live = fail
            if not live.any():
                break
            if deadline is not None and time.monotonic() >= deadline:
                # fail-low/high lanes hold only a BOUND — without the
                # wider re-search it must not be reported as a score
                merged["done"][live] = False
                break
        merged["nodes"] = nodes_acc
        return merged

    @staticmethod
    def _plan_helpers(n_primary: int, B: int, k_max: int, hardness):
        """Allocate the dispatch's spare lanes as helpers, hardest
        positions first: → list of (primary_row, helper_index) with
        helper_index 1..k_max-1, at most k_max-1 helpers per primary,
        at most B - n_primary total. Round-robin in descending-hardness
        order, so every hard position gets its first helper before any
        gets its second. hardness[j] <= 0 excludes primary j (settled,
        terminal, or budget-exhausted lanes get no helpers)."""
        spare = B - n_primary
        out: list = []
        if k_max <= 1 or spare <= 0 or n_primary <= 0:
            return out
        hardness = [int(h) for h in hardness]
        order = sorted(range(n_primary), key=lambda r: (-hardness[r], r))
        grants = [0] * n_primary
        while len(out) < spare:
            progressed = False
            for r in order:
                if len(out) >= spare:
                    break
                if hardness[r] > 0 and grants[r] < k_max - 1:
                    grants[r] += 1
                    out.append((r, grants[r]))
                    progressed = True
            if not progressed:
                break
        return out

    def _helper_width(self, n: int) -> int:
        """Dispatch width for n primaries with helper lanes enabled: grow
        the lane bucket toward n*K so the planner has spare rows to fill
        (a wider lockstep program costs nearly the same per step on TPU —
        docs/depth.md us/step tables — and the narrowing floor is 64
        anyway), but never above the device ceiling. K=1 keeps the
        pre-helper width exactly."""
        B = self._pad(n)
        K = self.helper_lanes
        if K > 1:
            grown = self._pad(min(n * K, self.max_lanes))
            if grown <= max(self.max_lanes, B):
                B = max(B, grown)
        return B

    @staticmethod
    def _history_arrays(hist_lists, B, variant="standard", keep_last=0):
        """Per-lane reversible game tails → device seed arrays.

        hist_lists: list (≤B) of list[Position], oldest first, ending at
        the lane root's parent. The reference hands the engine the whole
        game (`position fen ... moves ...`, src/stockfish.rs:298-306), and
        Stockfish's draw rule (Position::is_draw) scores a repetition as
        a draw when the earlier occurrence is INSIDE the search path, or
        when the position already occurred twice before/at the root. The
        in-search half is the device's path scan; this seeds the other
        half: only game positions occurring >=2x in the reversible tail
        are planted (a single pre-root occurrence is NOT a draw on
        re-visit — distance > ply in Stockfish's check). Chain validity
        (no irreversible move in between, rule50 window) is re-checked on
        device via halfmove distances.

        keep_last: the last keep_last tail entries are planted even when
        they occur only once. Move jobs and multipv decompose the search
        root's legal moves into lanes, so the root itself sits in the
        tail — a return to it inside a lane IS an in-search twofold
        repetition (distance <= ply in Stockfish's check) and must score
        as a draw on first re-visit."""
        from ..ops import tt as tt_mod
        from ..ops.search import HIST_HM_SENTINEL, MAX_HIST

        hh = np.zeros((B, MAX_HIST, 2), np.uint32)
        hm = np.full((B, MAX_HIST), HIST_HM_SENTINEL, np.int32)
        flat, slots = [], []
        for lane, hist in enumerate(hist_lists):
            tail = hist[-MAX_HIST:]
            for j, p in enumerate(tail):
                slots.append((lane, MAX_HIST - len(tail) + j))
                flat.append(from_position(p))
        if flat:
            stacked = stack_boards(flat)
            h1, h2 = tt_mod.hash_boards(stacked, variant)
            h1, h2 = np.asarray(h1), np.asarray(h2)
            hms = np.asarray(stacked.halfmove)
            for n, (lane, k) in enumerate(slots):
                hh[lane, k, 0] = h1[n]
                hh[lane, k, 1] = h2[n]
                hm[lane, k] = hms[n]
            # keep only positions occurring >=2x within their lane's tail
            # (the last keep_last slots are exempt — see docstring)
            for lane in range(B):
                filled = hm[lane] != HIST_HM_SENTINEL
                pairs = [tuple(hh[lane, k]) for k in range(MAX_HIST)]
                for k in range(MAX_HIST - keep_last):
                    if filled[k] and pairs.count(pairs[k]) < 2:
                        hm[lane, k] = HIST_HM_SENTINEL
                        hh[lane, k] = 0
        return hh, hm

    @classmethod
    def _history_arrays_shared(cls, hist, B, variant="standard", keep_last=0):
        """One history list shared by all B lanes (move jobs: every
        root-move lane has the same game prefix). Hashes the tail ONCE
        and broadcasts — the per-lane version costs B×MAX_HIST
        from_position calls on the host, against the 7 s move-job
        deadline."""
        hh1, hm1 = cls._history_arrays([hist], 1, variant, keep_last)
        return (
            np.broadcast_to(hh1, (B,) + hh1.shape[1:]).copy(),
            np.broadcast_to(hm1, (B,) + hm1.shape[1:]).copy(),
        )

    def _go_multiple_sync(self, chunk: Chunk) -> List[PositionResponse]:
        # single-pv analysis chunks flow through the occupancy-driven
        # LaneScheduler when refill is on — on mesh hosts too, via the
        # sharded segment/refill callables (FISHNET_TPU_MESH_REFILL=0
        # opts a meshed engine out); every other shape takes the strict
        # chunk-serial path UNCHANGED — with refill off the engine is
        # bit-identical to the pre-refill code by construction
        # (enforced by tests).
        work = chunk.work
        if (
            self.refill
            and (self.mesh is None or self.mesh_refill)
            and isinstance(work, AnalysisWork)
            and work.effective_multipv() == 1
        ):
            return self._scheduler.run_chunk(chunk)
        with self._lock:
            return self._go_multiple_locked(chunk)

    def _go_multiple_locked(self, chunk: Chunk) -> List[PositionResponse]:
        started = time.monotonic()
        # one TT generation per chunk: helper-mode stores from THIS chunk
        # out-rank each other by depth but always replace earlier chunks'
        # entries (ops/tt.py store; wraps long before int32 overflow)
        self._tt_gen = (self._tt_gen + 1) & 0x3FFFFFFF
        positions = []
        games = []  # per position: the replayed game prefix (oldest first)
        for wp in chunk.positions:
            pos = from_fen(wp.root_fen, chunk.variant)
            prefix = []
            for uci in wp.moves:
                prefix.append(pos)
                pos = pos.push(pos.parse_uci(uci))
            positions.append(pos)
            games.append(prefix)

        work = chunk.work
        if isinstance(work, MoveWork):
            return self._move_job(chunk, positions, games, work, started)
        assert isinstance(work, AnalysisWork)
        multipv = work.effective_multipv()
        target_depth = min(work.depth or self.max_depth, self.max_depth, MAX_PLY - 1)
        budget = work.nodes.get(chunk.flavor.eval_flavor())

        if multipv > 1:
            responses = self._analyse_multipv(
                chunk, positions, games, multipv, target_depth, budget, started
            )
        else:
            responses = self._analyse_single(
                chunk, positions, games, target_depth, budget, started
            )
        return responses

    def _move_job(self, chunk, positions, games, work: MoveWork, started):
        """Play jobs with lichess skill semantics (reference:
        src/api.rs:248-283 maps level 1-8 → movetime/Skill Level/depth;
        src/stockfish.rs:309-333 passes them to the engine).

        Root moves become lanes (one depth-1 search per legal move, deepened
        iteratively); weakening is the TPU-native analog of Stockfish's
        "Skill Level": below full strength, the move is drawn from the
        near-best candidates with probability decaying in the cp gap, with
        the acceptance window widening as the engine skill drops."""
        import random

        level = work.level
        target_depth = min(level.depth, self.max_depth, MAX_PLY - 1)
        hard_deadline = chunk.deadline - 0.25  # 7 s job deadline
        # movetime is a soft budget for DEEPENING; depth 1 always runs to
        # completion under the hard deadline so a move is always produced
        soft_deadline = min(
            hard_deadline, started + level.movetime_ms / 1000.0
        )
        variant = DEVICE_VARIANTS.get(chunk.variant, "standard")

        responses = []
        for wp, pos, game in zip(chunk.positions, positions, games):
            # move jobs dispatch per position (unlike analysis chunks), so
            # each position's reported time is its own measured slice
            p_start = time.monotonic()
            if pos.outcome() is not None:
                responses.append(self._terminal_response(chunk, wp, pos, 0.001))
                continue
            legal = pos.legal_moves()
            # pad to the variant's warmed move-job bucket so every job
            # shares ONE pre-compiled deep-probe program (a <=16-legal
            # endgame would otherwise bucket to a 16-lane program nothing
            # compiles ahead of its 7 s deadline) — lanes are cheap,
            # cold compiles are not
            B = self._pad(max(len(legal), _move_job_floor(variant)))
            boards = [from_position(pos.push(m)) for m in legal]
            roots = stack_boards(boards + [boards[0]] * (B - len(boards)))
            # every root-move lane shares the same history: the game
            # prefix plus the position the move was played from — which
            # is the SEARCH ROOT, seeded unconditionally (keep_last=1):
            # returning to it inside a lane is an in-search repetition
            hist = self._history_arrays_shared(
                game + [pos], B, variant, keep_last=1
            )

            ranked = []
            depth_reached = 0
            nodes_total = 0
            for depth in range(1, target_depth + 1):
                depth_arr = np.zeros(B, np.int32)
                depth_arr[: len(legal)] = depth - 1
                out = self._search(
                    roots, depth_arr, np.full(B, 10_000_000, np.int32),
                    hard_deadline if depth == 1 else soft_deadline,
                    variant=variant, hist=hist,
                    # move jobs report a MOVE, not a score: deeper TT
                    # bounds cut more (reference depth>= rule) and the
                    # score-determinism concern doesn't apply
                    deep_tt=True,
                )
                if not bool(out["done"][: len(legal)].all()):
                    break  # movetime/deadline hit: keep the previous depth
                nodes_total += int(out["nodes"][: len(legal)].sum()) + len(legal)
                ranked = sorted(
                    ((-int(out["score"][j]), j) for j in range(len(legal))),
                    key=lambda t: (-t[0], t[1]),
                )
                depth_reached = depth
                if time.monotonic() >= soft_deadline:
                    break
            if depth_reached == 0:
                raise EngineError("move job deadline expired before depth 1")

            sf_skill = level.engine_skill_level  # -9..20
            # rng seeded per job for reproducibility
            pick = skill_pick(
                ranked, sf_skill, random.Random(f"{work.id}:{wp.position_index}")
            )
            best_move = legal[pick[1]].uci()

            scores, pvs = Matrix(), Matrix()
            scores.set(1, depth_reached, _score_from_int(pick[0]))
            pvs.set(1, depth_reached, [best_move])
            dt = max(time.monotonic() - p_start, 1e-6)
            responses.append(
                PositionResponse(
                    work=chunk.work, position_index=wp.position_index,
                    url=wp.url, scores=scores, pvs=pvs, best_move=best_move,
                    depth=depth_reached, nodes=nodes_total, time_s=dt,
                    nps=int(nodes_total / dt),
                )
            )
        return responses

    def _terminal_response(self, chunk, wp: WorkPosition, pos: Position,
                           elapsed: float) -> PositionResponse:
        winner, _ = pos.outcome()
        scores, pvs = Matrix(), Matrix()
        scores.set(1, 0, Score.mate(0) if winner is not None else Score.cp(0))
        pvs.set(1, 0, [])
        return PositionResponse(
            work=chunk.work, position_index=wp.position_index, url=wp.url,
            scores=scores, pvs=pvs, best_move=None, depth=0, nodes=0,
            time_s=elapsed,
        )

    def _analyse_single(self, chunk, positions, games, target_depth, budget,
                        started):
        terminal = {
            i for i, p in enumerate(positions) if p.outcome() is not None
        }
        lanes = [i for i in range(len(positions)) if i not in terminal]

        scores = [Matrix() for _ in positions]
        pvs = [Matrix() for _ in positions]
        depth_reached = [0] * len(positions)
        best_moves: List[Optional[str]] = [None] * len(positions)
        nodes_total = [0] * len(positions)

        if lanes:
            n = len(lanes)
            K = self.helper_lanes
            B = self._helper_width(n)
            boards = [from_position(positions[i]) for i in lanes]
            pad_board = boards[0]
            variant = DEVICE_VARIANTS.get(chunk.variant, "standard")
            hist_hh, hist_hm = self._history_arrays(
                [games[i] for i in lanes], B, variant
            )
            per_pos_budget = budget if budget is not None else 10_000_000
            # primary-indexed iterative-deepening state (length n)
            remaining = np.full(n, per_pos_budget, dtype=np.int64)
            prev_score = np.zeros(n, np.int64)
            have_prev = np.zeros(n, bool)
            # hardness drives the helper planner: the previous depth's
            # primary node count — the lane that took the most serial
            # work is the one bounding the next depth's lockstep wall
            hardness = np.ones(n, np.int64)

            deadline = chunk.deadline - 0.25  # leave slack to package results
            for depth in range(1, target_depth + 1):
                # ---- lane-group layout for this depth: primaries in
                # rows 0..n-1, helpers next, inert padding after. Helper
                # h of primary j searches j's root with jittered move
                # ordering; odd helpers at the SAME depth (their exact-
                # depth TT entries are consumable THIS iteration — probe
                # requires exact depth, ops/tt.py), even helpers one ply
                # DEEPER (their entries feed ordering now and cutoffs
                # next iteration). All are abandoned mid-flight the
                # moment every primary finishes (required mask).
                helpers = (
                    self._plan_helpers(
                        n, B, K, np.where(remaining > 0, hardness, 0)
                    )
                    if K > 1
                    else []
                )
                roots = stack_boards(
                    boards
                    + [boards[j] for j, _h in helpers]
                    + [pad_board] * (B - n - len(helpers))
                )
                depth_arr = np.zeros(B, np.int32)
                depth_arr[:n] = depth
                budget_arr = np.ones(B, np.int32)
                budget_arr[:n] = np.clip(remaining, 0, 2**31 - 1)
                use_full = np.zeros(B, bool)
                use_full[:n] = (
                    have_prev & (np.abs(prev_score) < MATE - 1000)
                    & (depth >= 2)
                )
                prev_full = np.zeros(B, np.int64)
                prev_full[:n] = prev_score
                if K > 1:
                    hh = hist_hh.copy()
                    hm = hist_hm.copy()
                    jitter = np.zeros(B, np.int32)
                    grp = np.arange(B, dtype=np.int32)
                    scale_arr = np.ones(B, np.int64)
                    req = np.zeros(B, bool)
                    req[:n] = True
                    for idx, (j, h) in enumerate(helpers):
                        r = n + idx
                        hh[r] = hist_hh[j]
                        hm[r] = hist_hm[j]
                        # same depth for odd h, +1 ply for even h
                        depth_arr[r] = min(depth + (1 - (h & 1)), target_depth)
                        budget_arr[r] = budget_arr[j]
                        jitter[r] = j * K + h  # != 0, unique per (j, h)
                        grp[r] = j
                        scale_arr[r] = 1 << min(h, 4)  # staggered windows
                        use_full[r] = use_full[j]
                        prev_full[r] = prev_score[j]
                    hist_args = dict(
                        required=req, win_scale=scale_arr,
                        order_jitter=jitter, group=grp, helper_store=True,
                    )
                    hist_d = (hh, hm)
                else:
                    # K=1: identical arguments (and compiled programs) to
                    # the pre-helper engine — bit-for-bit the same search
                    hist_args = {}
                    hist_d = (hist_hh, hist_hm)
                t_depth = time.monotonic()
                out = self._search_windowed(
                    roots, depth_arr, budget_arr, deadline,
                    variant, hist_d, prev_full, use_full, **hist_args,
                )
                if self.trace:
                    self.trace(
                        f"ID depth={depth} B={B} lanes={n} "
                        f"helpers={len(helpers)} "
                        f"nodes={int(out['nodes'].sum())} "
                        f"wall={time.monotonic() - t_depth:.3f}s"
                    )
                exhausted_all = True
                for j, i in enumerate(lanes):
                    if remaining[j] <= 0 or not bool(out["done"][j]):
                        continue  # lane skipped, or stopped mid-depth on deadline
                    # helper nodes are charged to their primary: the
                    # position consumed that work against its server
                    # budget (same honesty rule as multipv's root-move
                    # lanes; helpers are abandoned at primary completion,
                    # so the charge is the work actually spent)
                    lane_nodes = int(out["nodes"][j])
                    help_nodes = sum(
                        int(out["nodes"][n + idx])
                        for idx, (jj, _h) in enumerate(helpers)
                        if jj == j
                    )
                    hardness[j] = max(lane_nodes, 1)
                    nodes_total[i] += lane_nodes + help_nodes
                    remaining[j] -= lane_nodes + help_nodes
                    sc = int(out["score"][j])
                    prev_score[j] = sc
                    have_prev[j] = True
                    scores[i].set(1, depth, _score_from_int(sc))
                    pv = [
                        _decode_uci(int(m))
                        for m in out["pv"][j][: int(out["pv_len"][j])]
                        if m >= 0
                    ]
                    pvs[i].set(1, depth, pv)
                    depth_reached[i] = depth
                    mv = int(out["move"][j])
                    best_moves[i] = _decode_uci(mv) if mv >= 0 else None
                    if remaining[j] > 0:
                        exhausted_all = False
                if exhausted_all or time.monotonic() >= deadline:
                    break

        # deadline hit before even depth 1 finished: no usable result for
        # some lane — fail the whole chunk so the server reassigns it
        # (reference forgets failed batches, src/queue.rs:226-233)
        if any(depth_reached[i] == 0 for i in lanes):
            raise EngineError("chunk deadline expired before depth 1 completed")

        elapsed = max(time.monotonic() - started, 1e-6)
        times = self._apportion_time(elapsed, nodes_total)
        responses = []
        for i, wp in enumerate(chunk.positions):
            if i in terminal:
                responses.append(
                    self._terminal_response(chunk, wp, positions[i], times[i])
                )
                continue
            nps = int(nodes_total[i] / times[i]) if times[i] > 0 else None
            responses.append(
                PositionResponse(
                    work=chunk.work, position_index=wp.position_index,
                    url=wp.url, scores=scores[i], pvs=pvs[i],
                    best_move=best_moves[i], depth=depth_reached[i],
                    nodes=nodes_total[i], time_s=times[i], nps=nps,
                )
            )
        return responses

    @staticmethod
    def _apportion_time(elapsed: float, nodes: list) -> list:
        """Chunk wall-clock → per-position times, proportional to each
        position's node count.

        All positions of a chunk share one batched dispatch, so there is
        no true per-position wall time; the reference reports what the
        engine measured per `go` (src/stockfish.rs:351-392). The honest
        decomposition of shared lockstep time is by node share — the
        per-position times sum to the chunk's real elapsed, and the
        implied nps is the chunk's uniform lockstep throughput (a
        uniform elapsed/len split instead made light positions look
        slow and heavy ones implausibly fast, round-3 advisor flag)."""
        total = sum(nodes)
        n = max(len(nodes), 1)
        if total <= 0:
            return [elapsed / n] * n
        return [elapsed * nd / total for nd in nodes]

    def _analyse_multipv(self, chunk, positions, games, multipv, target_depth,
                         budget, started):
        """MultiPV via root-move-partitioned lanes: every legal root move
        of EVERY chunk position becomes a lane, all searched together in
        one dispatch per iterative-deepening depth. This is where batching
        beats the reference hardest — Stockfish pays ~multipv× for
        MultiPV (reference: src/stockfish.rs:272 sets MultiPV and the
        engine re-searches), while lanes are just lanes here.

        Node accounting: every legal root move gets a lane, so a position
        spends ~len(legal)× a single-PV search's NODES against the same
        server budget (remaining//len(legal) per lane per round, so a
        round never exceeds the remaining budget). Wall-clock is what
        matters on TPU — the lanes run in the same lockstep dispatch —
        and the budget check stops deepening once the pool is spent.

        Lane ceiling: multipv is the only path whose lane count scales
        with chunk content (positions × legal moves), so it is the only
        one that can blow past `max_lanes` (~1024 on v5e before the VMEM
        cliff, docs/tpu-hang.md round 5). Positions are partitioned
        greedily into dispatch groups of ≤ max_lanes lanes, searched
        sequentially against the shared chunk deadline."""
        live = [i for i, p in enumerate(positions) if p.outcome() is None]
        legal: dict[int, list] = {i: positions[i].legal_moves() for i in live}

        groups: List[List[int]] = []
        cur: List[int] = []
        cur_lanes = 0
        for i in live:
            n = len(legal[i])
            if cur and cur_lanes + n > self.max_lanes:
                groups.append(cur)
                cur, cur_lanes = [], 0
            # a single position over the ceiling still gets its own group:
            # root-move lanes are indivisible (chess tops out ~218 legal,
            # far under the production ceiling — only tiny test ceilings
            # can hit this)
            cur.append(i)
            cur_lanes += n
        if cur:
            groups.append(cur)
        if len(groups) > 1:
            total_lanes = sum(len(legal[i]) for i in live)
            self._warn(
                f"multipv chunk wants {total_lanes} lanes, over the "
                f"{self.max_lanes}-lane device ceiling; splitting into "
                f"{len(groups)} sequential dispatch groups (expect "
                "proportionally longer wall-clock against the same deadline)"
            )

        scores = [Matrix() for _ in positions]
        pvs = [Matrix() for _ in positions]
        depth_reached = [0] * len(positions)
        best_moves: List[Optional[str]] = [None] * len(positions)
        nodes_total = [0] * len(positions)

        for group in groups:
            self._analyse_multipv_group(
                chunk, positions, games, multipv, target_depth, budget,
                group, legal, scores, pvs, depth_reached, best_moves,
                nodes_total,
            )

        if any(depth_reached[i] == 0 for i in live):
            raise EngineError(
                "chunk deadline expired before depth 1 completed (multipv)"
            )

        elapsed = max(time.monotonic() - started, 1e-6)
        times = self._apportion_time(elapsed, nodes_total)
        responses = []
        for i, wp in enumerate(chunk.positions):
            if i not in live:
                responses.append(
                    self._terminal_response(chunk, wp, positions[i], times[i])
                )
                continue
            responses.append(
                PositionResponse(
                    work=chunk.work, position_index=wp.position_index,
                    url=wp.url, scores=scores[i], pvs=pvs[i],
                    best_move=best_moves[i], depth=depth_reached[i],
                    nodes=nodes_total[i], time_s=times[i],
                    nps=int(nodes_total[i] / times[i]) if times[i] > 0 else None,
                )
            )
        return responses

    def _analyse_multipv_group(self, chunk, positions, games, multipv,
                               target_depth, budget, live, legal, scores,
                               pvs, depth_reached, best_moves, nodes_total):
        """One ≤max_lanes dispatch group of `_analyse_multipv`: build the
        lane table for `live`'s root moves and iterate depths, folding
        results into the caller's shared per-position accumulators."""
        # lane table: (position index, move index) per lane
        lane_pos: List[int] = []
        lane_move: List[int] = []
        boards = []
        for i in live:
            for j, m in enumerate(legal[i]):
                lane_pos.append(i)
                lane_move.append(j)
                boards.append(from_position(positions[i].push(m)))

        if boards:
            B = self._pad(max(len(boards), 64))
            roots = stack_boards(boards + [boards[0]] * (B - len(boards)))
            variant = DEVICE_VARIANTS.get(chunk.variant, "standard")
            # lane k's root is positions[lane_pos[k]].push(move): history =
            # that game's prefix plus the position itself (the search
            # root — seeded unconditionally via keep_last, same reasoning
            # as move jobs). Hash each distinct position's tail once and
            # fan out to its lanes.
            from ..ops.search import HIST_HM_SENTINEL

            hh_pos, hm_pos = self._history_arrays(
                [games[i] + [positions[i]] for i in live], len(live),
                variant, keep_last=1,
            )
            pos_row = {i: r for r, i in enumerate(live)}
            hh = np.zeros((B,) + hh_pos.shape[1:], hh_pos.dtype)
            hm = np.full((B,) + hm_pos.shape[1:], HIST_HM_SENTINEL,
                         hm_pos.dtype)
            for k, i in enumerate(lane_pos):
                hh[k] = hh_pos[pos_row[i]]
                hm[k] = hm_pos[pos_row[i]]
            hist = (hh, hm)
            per_pos_budget = budget if budget is not None else 10_000_000
            remaining = {i: per_pos_budget for i in live}

            deadline = chunk.deadline - 0.25
            for depth in range(1, target_depth + 1):
                depth_arr = np.zeros(B, np.int32)
                budget_arr = np.ones(B, np.int32)
                for k, i in enumerate(lane_pos):
                    if remaining[i] > 0:
                        depth_arr[k] = depth - 1
                        budget_arr[k] = min(
                            max(remaining[i] // max(len(legal[i]), 1), 1),
                            2**31 - 1,
                        )
                out = self._search(
                    roots, depth_arr, budget_arr, deadline,
                    variant=variant, hist=hist,
                    # root-move lanes already fill the dispatch, so no
                    # helper replication here — but the depth-preferred
                    # store policy still applies (and keeps the compiled
                    # program identical to the warmed helper-mode one)
                    helper_store=self.helper_lanes > 1,
                )
                done = out["done"]
                # fold lanes back per position
                per_pos_done = {i: True for i in live}
                for k, i in enumerate(lane_pos):
                    if remaining[i] > 0 and not bool(done[k]):
                        per_pos_done[i] = False
                ranked: dict[int, list] = {i: [] for i in live}
                for k, (i, j) in enumerate(zip(lane_pos, lane_move)):
                    if remaining[i] <= 0 or not per_pos_done[i]:
                        continue
                    m = legal[i][j]
                    child_score = -int(out["score"][k])
                    child_pv = [
                        _decode_uci(int(x))
                        for x in out["pv"][k][: int(out["pv_len"][k])]
                        if x >= 0
                    ]
                    ranked[i].append((child_score, j, [m.uci()] + child_pv))
                progressed = False
                for i in live:
                    if remaining[i] <= 0 or not per_pos_done[i] or not ranked[i]:
                        continue
                    step_nodes = sum(
                        int(out["nodes"][k])
                        for k, pi in enumerate(lane_pos)
                        if pi == i
                    ) + len(legal[i])
                    nodes_total[i] += step_nodes
                    remaining[i] -= step_nodes
                    rl = sorted(ranked[i], key=lambda t: (-t[0], t[1]))
                    for rank, (sc, _j, line) in enumerate(rl[:multipv], start=1):
                        scores[i].set(rank, depth, _score_from_int(sc))
                        pvs[i].set(rank, depth, line)
                    depth_reached[i] = depth
                    best_moves[i] = rl[0][2][0]
                    if remaining[i] > 0:
                        progressed = True
                if not progressed or time.monotonic() >= deadline:
                    break

            if self.trace:
                # budget honesty: root-move lanes make a position spend up
                # to ~len(legal)× a single-PV search's nodes against the
                # same server budget — keep the actual consumption visible
                spent = {i: per_pos_budget - remaining[i] for i in live}
                self.trace(
                    "multipv budget: "
                    + " ".join(
                        f"pos{i}={spent[i]}/{per_pos_budget}"
                        f"({len(legal[i])}lanes)"
                        for i in live
                    )
                )


# ---------------------------------------------- continuous lane refill


class _RefillJob:
    """One analysed position flowing through the LaneScheduler.

    Carries its own iterative-deepening and aspiration-window state so
    it progresses independently of every other position sharing the
    batch — the per-lane decomposition of what `_analyse_single` +
    `_search_windowed` track batch-wide. The per-depth policy here must
    stay EXACTLY equivalent per lane (window schedule, fail-low/high
    checks, budget charging), or refill-on scores drift from refill-off
    ones with no TT involved."""

    __slots__ = (
        "entry", "wp", "pos", "board", "variant", "target_depth",
        "remaining", "deadline", "hh", "hm", "depth", "delta_idx",
        "prev_score", "have_prev", "hardness", "scores", "pvs",
        "depth_reached", "best_move", "nodes_total", "nodes_depth",
        "lane", "helpers", "traced", "t_spliced",
    )

    def __init__(self, entry, wp, pos, board, variant, target_depth,
                 budget, deadline, hh, hm):
        self.entry = entry
        self.wp = wp
        self.pos = pos
        self.board = board
        self.variant = variant
        self.target_depth = target_depth
        self.remaining = budget  # node budget left (host int)
        self.deadline = deadline
        self.hh = hh  # (MAX_HIST, 2) repetition-history hashes
        self.hm = hm  # (MAX_HIST,) halfmove distances
        self.depth = 1  # depth currently being searched
        self.delta_idx = 0  # index into ASPIRATION_DELTAS + (None,)
        self.prev_score = 0
        self.have_prev = False
        self.hardness = 1  # previous depth's node count (helper planner)
        self.scores = Matrix()
        self.pvs = Matrix()
        self.depth_reached = 0
        self.best_move: Optional[str] = None
        self.nodes_total = 0
        self.nodes_depth = 0  # nodes across the current depth's attempts
        self.lane = -1  # primary lane index while admitted
        self.helpers: dict = {}  # helper lane index -> helper number h
        # request-scoped tracing (host-side bookkeeping ONLY — nothing
        # here ever reaches a device buffer): traced is the per-request
        # sampling verdict hoisted out of the boundary loop, t_spliced
        # the monotonic time this position won its first lane
        self.traced = False
        self.t_spliced = 0.0


class _ChunkEntry:
    """Per-chunk completion tracking shared between the submitting
    thread and whichever thread is currently driving the device."""

    def __init__(self, chunk: Chunk, started: float):
        self.chunk = chunk
        self.started = started
        self.n_open = 0
        self.responses: dict = {}  # position_index -> PositionResponse
        self.error: Optional[str] = None
        self.event = threading.Event()
        # TT warm-slice plan (cache/ttwarm.py): (prefix key, slots)
        # per position, filled by _submit when the engine has a warm
        # store attached; run_chunk exports these slots on completion
        self.tt_warm: list = []


class LaneScheduler:
    """Occupancy-driven scheduling of the lockstep search (ISSUE 4).

    `_go_multiple_locked` drains chunks strictly serially and a batch
    finishes when its HARDEST position does, so finished lanes idle —
    masked but still stepping — until the power-of-two narrowing halves
    the width. The scheduler applies iteration-level ("continuous")
    batching instead: one pending-position queue fed by every
    concurrently submitted single-pv analysis chunk, one full-width
    compiled step, and at every segment boundary finished lanes are
    refilled (ops/search.py refill_lanes) with queued positions,
    earliest deadline first. Genuinely-spare lanes run Lazy-SMP helpers
    (`_plan_helpers`), and each `PositionResponse` is emitted the moment
    its position finishes rather than when its whole chunk does.

    Concurrency (combining driver): any number of executor threads call
    `run_chunk` concurrently. Each submits its positions to the shared
    queue, then either becomes THE driver — taking the engine lock and
    dispatching segments that serve everyone's jobs — or waits for its
    responses. The engine lock is released between drive sessions so
    move jobs and multipv chunks (which take the serial path) can
    interleave. Per-admission TT generation tags flow into the (B,)
    tt_gen array of `_run_segment_jit`, so depth-preferred replacement
    never protects entries from an earlier occupant of the same lane."""

    def __init__(self, engine: "TpuEngine"):
        self.engine = engine
        self._q_lock = threading.Lock()
        self._pending: List[_RefillJob] = []
        self._driving = False
        self._jitter_seq = 0
        # FISHNET_TPU_SANITIZE, captured once: _deliver pays a single
        # attribute test per position, nothing per boundary
        self._sanitize = sanitize.enabled()

    # ------------------------------------------------------- submission

    def run_chunk(self, chunk: Chunk) -> List[PositionResponse]:
        entry = self._submit(chunk)
        while not entry.event.is_set():
            with self._q_lock:
                drive = not self._driving
                if drive:
                    self._driving = True
            if drive:
                try:
                    self._drive(entry)
                finally:
                    with self._q_lock:
                        self._driving = False
            else:
                entry.event.wait(0.05)
        if entry.error:
            raise EngineError(entry.error)
        if self.engine.tt_warm is not None and entry.tt_warm:
            self._tt_warm_export(entry)
        return [entry.responses[wp.position_index] for wp in chunk.positions]

    def _tt_warm_plan(self, entry: _ChunkEntry, wp, pos, variant) -> None:
        """Opening-prefix TT warm-up (cache/ttwarm.py): compute the TT
        slots of this position and its direct children, remember them on
        the entry for export after the chunk, and splice any persisted
        slice for the same prefix into the shared table. Splicing swaps
        `eng.tt` and so only happens under the queue lock while no drive
        loop is live (the drive loop re-reads `eng.tt` per segment and
        writes it back in its `finally`, which would clobber a
        concurrent swap); a busy engine just skips the warm start."""
        from ..cache import ttwarm as cache_ttwarm
        from ..ops import tt as tt_mod

        eng = self.engine
        store = eng.tt_warm
        if store is None or eng.tt is None:
            return
        try:
            key = cache_ttwarm.prefix_fingerprint(
                wp.root_fen, wp.moves, eng.tt_warm_prefix
            )
            children = [pos.push(m) for m in pos.legal_moves()]
            boards = [pos] + children[: cache_ttwarm.MAX_SLICE_ROWS - 1]
            stacked = stack_boards([from_position(p) for p in boards])
            h1, _h2 = tt_mod.hash_boards(stacked, variant)
            mask = (1 << eng.tt_size_log2) - 1
            slots = [int(h) & mask for h in np.asarray(h1)]
            entry.tt_warm.append((key, slots))
            rows = store.lookup(eng.tt_size_log2, key)
            if not rows:
                return
            with self._q_lock:
                tt = eng.tt
                if (
                    not self._driving
                    and tt is not None
                    and tt.data.ndim == 2
                ):
                    data, n = cache_ttwarm.splice_rows(tt.data, rows)
                    if n:
                        eng.tt = tt._replace(data=data)
                        store.splices += 1
                        store.warm_slots += n
        except Exception as e:
            eng._warn(f"tt warm plan failed: {e}")

    def _tt_warm_export(self, entry: _ChunkEntry) -> None:
        """After a chunk completes, read back the slots planned in
        `_tt_warm_plan` from a table snapshot and persist the non-empty
        rows. Reads a gathered slice from whatever `eng.tt` points at
        now — rows from a later occupant of the same slot still
        self-validate on splice, so staleness is safe."""
        from ..cache import ttwarm as cache_ttwarm

        eng = self.engine
        store = eng.tt_warm
        tt = eng.tt
        if store is None or tt is None or tt.data.ndim != 2:
            return
        try:
            for key, slots in entry.tt_warm:
                idx = np.asarray(slots, dtype=np.int64)
                rows = cache_ttwarm.extract_rows(
                    np.asarray(tt.data[idx]), slots
                )
                if rows:
                    store.record(eng.tt_size_log2, key, rows)
        except Exception as e:
            eng._warn(f"tt warm export failed: {e}")

    def _submit(self, chunk: Chunk) -> _ChunkEntry:
        eng = self.engine
        entry = _ChunkEntry(chunk, time.monotonic())
        work = chunk.work
        assert isinstance(work, AnalysisWork)
        target_depth = min(
            work.depth or eng.max_depth, eng.max_depth, MAX_PLY - 1
        )
        budget = work.nodes.get(chunk.flavor.eval_flavor())
        per_pos_budget = budget if budget is not None else 10_000_000
        variant = DEVICE_VARIANTS.get(chunk.variant, "standard")
        deadline = chunk.deadline - 0.25  # slack to package results
        jobs = []
        for wp in chunk.positions:
            pos = from_fen(wp.root_fen, chunk.variant)
            game = []
            for uci in wp.moves:
                game.append(pos)
                pos = pos.push(pos.parse_uci(uci))
            if pos.outcome() is not None:
                self._deliver(
                    entry, wp, eng._terminal_response(chunk, wp, pos, 0.001)
                )
                continue
            hh, hm = TpuEngine._history_arrays([game], 1, variant)
            if eng.tt_warm is not None:
                self._tt_warm_plan(entry, wp, pos, variant)
            job = _RefillJob(
                entry, wp, pos, from_position(pos), variant, target_depth,
                per_pos_budget, deadline, hh[0], hm[0],
            )
            rec = obs_trace.RECORDER
            ctx = wp.ctx
            if ctx and ctx.get("trace_id"):
                tid = ctx["trace_id"]
                obs_inflight.REGISTRY.position(
                    tid, wp.position_index or 0, "queued"
                )
                if rec is not None and obs_trace.sampled(tid):
                    job.traced = True
                    rec.instant(
                        "position.queued", "request",
                        **obs_trace.ctx_args(
                            ctx, position_index=wp.position_index
                        ),
                    )
                    rec.flow("request", tid, "t")
            jobs.append(job)
        entry.n_open = len(jobs)
        if not jobs:
            entry.event.set()
        with self._q_lock:
            self._pending.extend(jobs)
        return entry

    def _deliver(self, entry: _ChunkEntry, wp, response) -> None:
        """Exactly-once delivery point for one position's result: every
        finalized response — terminal shortcut or searched — lands in
        `entry.responses` through here, and only here, so the
        `on_response` streaming hook fires once per position."""
        if self._sanitize:
            sanitize.check_delivery_once(
                entry.responses, wp.position_index,
                "engine/tpu.py::LaneScheduler._deliver")
        entry.responses[wp.position_index] = response
        ctx = wp.ctx
        if ctx and ctx.get("trace_id"):
            tid = ctx["trace_id"]
            obs_inflight.REGISTRY.position(
                tid, wp.position_index or 0, "delivered"
            )
            rec = obs_trace.RECORDER
            if rec is not None and obs_trace.sampled(tid):
                rec.instant(
                    "position.delivered", "request",
                    **obs_trace.ctx_args(
                        ctx, position_index=wp.position_index,
                        depth=response.depth, nodes=response.nodes,
                    ),
                )
                rec.flow("request", tid, "t")
        hook = self.engine.on_response
        if hook is not None:
            try:
                hook(wp, response)
            except Exception as e:
                self.engine._warn(f"on_response hook failed: {e}")
        deliver = self.engine.on_deliver
        if deliver is not None:
            try:
                deliver(entry.chunk, wp, response)
            except Exception as e:
                self.engine._warn(f"on_deliver hook failed: {e}")

    def _finalize(self, job: _RefillJob, now: float,
                  error: Optional[str] = None) -> None:
        entry = job.entry
        if job.traced and job.t_spliced > 0.0:
            rec = obs_trace.RECORDER
            if rec is not None:
                # retroactive lane-residency span: first splice →
                # finalize, one per position (re-admissions for deeper
                # iterations reuse the lane inside this window)
                rec.complete(
                    "position.lane", job.t_spliced * 1e6,
                    (now - job.t_spliced) * 1e6, cat="request",
                    args=obs_trace.ctx_args(
                        job.wp.ctx, position_index=job.wp.position_index,
                        error=error,
                    ),
                )
        if error is not None:
            entry.error = error
        else:
            dt = max(now - entry.started, 1e-6)
            nps = int(job.nodes_total / dt) if job.nodes_total else None
            self._deliver(entry, job.wp, PositionResponse(
                work=entry.chunk.work, position_index=job.wp.position_index,
                url=job.wp.url, scores=job.scores, pvs=job.pvs,
                best_move=job.best_move, depth=job.depth_reached,
                nodes=job.nodes_total, time_s=dt, nps=nps,
            ))
            self.engine.occupancy_totals["positions_done"] += 1
        entry.n_open -= 1
        if entry.n_open <= 0:
            entry.event.set()

    # ---------------------------------------------------------- driving

    def _drive(self, entry: _ChunkEntry) -> None:
        while not entry.event.is_set():
            with self._q_lock:
                if not self._pending:
                    return
            # lock released between sessions: a blocked move job or
            # multipv chunk gets the device before the next session
            with self.engine._lock:
                self._drive_session(entry)

    def _drive_session(self, entry: _ChunkEntry) -> None:
        """One fixed-width drive session: admit, dispatch segments,
        process boundaries, until no lane is running. Jobs of OTHER
        device variants stay queued (each variant is a distinct static
        program); a later session picks them up."""
        eng = self.engine
        now = time.monotonic()
        with self._q_lock:
            if not self._pending:
                return
            self._pending.sort(key=lambda j: j.deadline)
            variant = self._pending[0].variant
            n_hint = sum(1 for j in self._pending if j.variant == variant)
            filler = next(
                j for j in self._pending if j.variant == variant
            ).board
        K = eng.helper_lanes
        B = eng._helper_width(min(max(n_hint, 1), eng.max_lanes))
        # shard-aware session: under a mesh the SAME loop drives the
        # shard_map'd segment/refill callables (parallel/mesh.py) — B is
        # padded to a multiple of n_dev by _helper_width, each device
        # owns `local` consecutive lanes, and every boundary is one
        # stacked-summary fetch
        mesh = eng.mesh
        n_shard = eng.n_dev if mesh is not None else 1
        local = B // n_shard
        # mesh-topology-aware admission: free lists index GLOBAL shards
        # (lane numbering spans the whole pod) but new work is admitted
        # only into shards whose device this process can address — on a
        # single-host mesh that is every shard, so the historical
        # assignment is unchanged bit-for-bit
        if mesh is not None:
            from ..parallel import distributed as _dist

            fillable_shards = set(_dist.addressable_shards(mesh))
        else:
            fillable_shards = {0}
        seg = settings.get_segment()
        ctrl = None
        if seg is None:  # FISHNET_TPU_SEGMENT=auto
            ctrl = SegmentController(
                settings.get_int("FISHNET_TPU_SEGMENT_MIN"),
                settings.get_int("FISHNET_TPU_SEGMENT_MAX"),
            )
            seg = ctrl.steps
        pipeline = settings.get_bool("FISHNET_TPU_PIPELINE")
        stats = SyncStats()
        prefer_deep = K > 1 and eng.tt is not None
        deltas = ASPIRATION_DELTAS + (None,)  # None = full window

        # host-side lane tables
        lane_job: List[Optional[_RefillJob]] = [None] * B  # primary owner
        lane_owner: List[Optional[_RefillJob]] = [None] * B  # helper owner
        lane_alpha = np.full(B, -INF, np.int64)
        lane_beta = np.full(B, INF, np.int64)
        gen = np.zeros(B, np.int32)
        active: List[_RefillJob] = []

        # idle base state: budget-0 lanes park in DONE within two steps;
        # passing every optional init arg as a full array shares ONE
        # _init_state_jit trace with refill_lanes' fresh states
        from ..ops.search import HIST_HM_SENTINEL, MAX_HIST

        state = search_ops._init_state_jit(
            eng.params, stack_boards([filler] * B),
            jnp.zeros(B, jnp.int32), jnp.zeros(B, jnp.int32),
            MAX_PLY, variant,
            hist_hash=jnp.zeros((B, MAX_HIST, 2), jnp.uint32),
            hist_halfmove=jnp.full(
                (B, MAX_HIST), HIST_HM_SENTINEL, jnp.int32
            ),
            root_alpha=jnp.full((B,), -INF, jnp.int32),
            root_beta=jnp.full((B,), INF, jnp.int32),
            order_jitter=jnp.zeros((B,), jnp.int32),
            group=jnp.zeros((B,), jnp.int32),
        )
        if mesh is not None:
            from ..parallel.mesh import (
                refill_lanes_sharded,
                run_segment_sharded,
                shard_batch,
            )

            # place the base state sharded before the first dispatch:
            # the sharded segment donates its operands, and donation
            # only takes when the input already carries the sharding
            state = shard_batch(mesh, state)
        tt = eng.tt

        # admissions accumulated between boundaries, flushed as ONE
        # refill_lanes call before each dispatch
        adm: dict = {k: [] for k in (
            "lane", "board", "depth", "budget", "alpha", "beta",
            "jitter", "group", "hh", "hm",
        )}

        def window_for(job: _RefillJob, scale: int):
            """Per-lane mirror of _search_windowed's window: narrow
            around the previous depth's score, widening per failed
            attempt, full-width first at depth 1 / after a mate score."""
            use_win = (
                job.have_prev
                and abs(job.prev_score) < MATE - 1000
                and job.depth >= 2
            )
            delta = deltas[min(job.delta_idx, len(deltas) - 1)]
            if not use_win or delta is None:
                return -INF, INF, None
            return (
                max(job.prev_score - delta * scale, -INF),
                min(job.prev_score + delta * scale, INF),
                delta,
            )

        def admit(lane, board, depth, budget, alpha, beta, jit, grp,
                  hh, hm):
            adm["lane"].append(lane)
            adm["board"].append(board)
            adm["depth"].append(depth)
            adm["budget"].append(int(np.clip(budget, 1, 2**31 - 1)))
            adm["alpha"].append(alpha)
            adm["beta"].append(beta)
            adm["jitter"].append(jit)
            adm["group"].append(grp)
            adm["hh"].append(hh)
            adm["hm"].append(hm)
            lane_alpha[lane] = alpha
            lane_beta[lane] = beta
            # fresh TT generation per admission: depth-preferred
            # replacement must never protect the lane's previous
            # occupant's entries (ops/tt.py store)
            eng._tt_gen = (eng._tt_gen + 1) & 0x3FFFFFFF
            gen[lane] = eng._tt_gen

        def admit_primary(job: _RefillJob, lane: int):
            job.lane = lane
            lane_job[lane] = job
            wp = job.wp
            if wp.ctx:
                obs_inflight.REGISTRY.position(
                    wp.ctx.get("trace_id"), wp.position_index or 0,
                    "lane", lane=lane,
                )
            if job.traced:
                job.t_spliced = time.monotonic()
                rec = obs_trace.RECORDER
                if rec is not None:
                    rec.instant(
                        "position.spliced", "request",
                        **obs_trace.ctx_args(
                            wp.ctx, position_index=wp.position_index,
                            lane=lane,
                        ),
                    )
                    rec.flow("request", wp.ctx["trace_id"], "t")
            a, b, _delta = window_for(job, 1)
            admit(lane, job.board, job.depth, job.remaining, a, b,
                  0, lane, job.hh, job.hm)

        def admit_helper(job: _RefillJob, lane: int, h: int):
            # same layout as _analyse_single: odd h at the primary's
            # depth (exact-depth TT entries consumable THIS iteration),
            # even h one ply deeper; staggered window scale; nonzero
            # unique jitter; group = primary lane
            job.helpers[lane] = h
            lane_owner[lane] = job
            self._jitter_seq = (self._jitter_seq & 0xFFFF) + 1
            a, b, _delta = window_for(job, 1 << min(h, 4))
            d = min(job.depth + (1 - (h & 1)), job.target_depth)
            admit(lane, job.board, d, job.remaining, a, b,
                  self._jitter_seq, job.lane, job.hh, job.hm)

        def release(job: _RefillJob, nodes_row):
            """Free the job's primary + helper lanes; mid-flight helper
            work is charged at its last-boundary node count (nodes_row:
            the latest boundary's (B,) per-lane node counts — the work
            actually spent against the position's budget, same honesty
            rule as _analyse_single's helper charging)."""
            if job.lane >= 0:
                lane_job[job.lane] = None
                job.lane = -1
            for hl in list(job.helpers):
                if nodes_row is not None:
                    hn = int(nodes_row[hl])
                    job.nodes_total += hn
                    job.remaining -= hn
                lane_owner[hl] = None
            job.helpers.clear()

        def on_primary_done(job: _RefillJob, lane: int, res: dict,
                            now: float):
            """One primary lane parked in DONE: fail-low/high re-search,
            next depth, or finalize — the per-lane equivalent of one
            `_search_windowed` attempt boundary. The fail checks and the
            widening schedule mirror that method exactly, so with no TT
            a refilled lane's score chain is bit-identical to the
            serial path's."""
            score = int(res["score"][lane])
            nodes = int(res["nodes"][lane])
            job.nodes_depth += nodes
            a_w = int(lane_alpha[lane])
            b_w = int(lane_beta[lane])
            fail_lo = score <= a_w and a_w > -INF
            fail_hi = score >= b_w and b_w < INF
            delta = deltas[min(job.delta_idx, len(deltas) - 1)]
            if a_w > -INF or b_w < INF:
                # same per-delta accounting as _search_windowed
                st = eng.aspiration_stats.setdefault(delta, [0, 0, 0, 0])
                st[0] += 1
                st[1] += int(fail_lo)
                st[2] += int(fail_hi)
                st[3] += nodes
            if (fail_lo or fail_hi) and delta is not None:
                # re-search the same depth with the next wider window;
                # the lane stays this job's — only its window changes
                job.delta_idx += 1
                a, b, _d = window_for(job, 1)
                admit(lane, job.board, job.depth, job.remaining, a, b,
                      0, lane, job.hh, job.hm)
                return
            # depth complete: record, charge the depth's nodes, advance
            job.prev_score = score
            job.have_prev = True
            job.hardness = max(nodes, 1)
            job.nodes_total += job.nodes_depth
            job.remaining -= job.nodes_depth
            job.nodes_depth = 0
            job.delta_idx = 0
            job.scores.set(1, job.depth, _score_from_int(score))
            pv = [
                _decode_uci(int(m))
                for m in res["pv"][lane][: int(res["pv_len"][lane])]
                if m >= 0
            ]
            job.pvs.set(1, job.depth, pv)
            job.depth_reached = job.depth
            mv = int(res["move"][lane])
            job.best_move = _decode_uci(mv) if mv >= 0 else None
            if (
                job.depth >= job.target_depth
                or job.remaining <= 0
                or now >= job.deadline
            ):
                release(job, res["nodes"])
                active.remove(job)
                self._finalize(job, now)
                return
            job.depth += 1
            a, b, _d = window_for(job, 1)
            admit(lane, job.board, job.depth, job.remaining, a, b,
                  0, lane, job.hh, job.hm)

        # pipelined boundary state: PV pulls deferred past speculative
        # boundaries as (job, lane, depth, final) — the PV row is the
        # one per-lane result NOT in the packed summary
        pv_pending: List[tuple] = []
        last_device_s = 0.0

        def q_len_locked() -> int:
            with self._q_lock:
                return len(self._pending)

        def traced_snapshot():
            """(ctx, lane, position_index) for every sampled job resident
            in this segment — captured at dispatch, because by the time
            the boundary is processed jobs may have parked/finalized."""
            if obs_trace.RECORDER is None:
                return ()
            return [
                (j.wp.ctx, j.lane, j.wp.position_index)
                for j in active if j.traced
            ]

        def traced_residency(snapshot, t0_s: float, t1_s: float):
            """Retroactive per-position residency spans for one segment:
            which lanes a request's positions occupied while the device
            ran — the finest grain of the request waterfall."""
            rec = obs_trace.RECORDER
            if rec is None:
                return
            for ctx, lane, idx in snapshot:
                rec.complete(
                    "segment.residency", t0_s * 1e6,
                    (t1_s - t0_s) * 1e6, cat="request",
                    args=obs_trace.ctx_args(
                        ctx, lane=lane, position_index=idx
                    ),
                )

        if mesh is not None:
            def dispatch(st, table, n_steps):
                # donates st and table (parallel/mesh.py): both handles
                # are dead after this call — always rebind to the
                # outputs. Each device advances its shard locally; the
                # summary arrives stacked (n_shard, local+1, 4).
                return run_segment_sharded(
                    mesh, eng.params, st, table, n_steps,
                    variant=variant, prefer_deep=prefer_deep,
                    tt_gen=jnp.asarray(gen),
                )
        else:
            def dispatch(st, table, n_steps):
                # donates st and table (ops/search.py): both handles are
                # dead after this call — always rebind to the outputs
                return search_ops._run_segment_jit(
                    eng.params, st, table, n_steps, variant, False,
                    prefer_deep, jnp.asarray(gen),
                )

        def canon_summ(raw):
            """Boundary summary → ((B, 4) lane rows, step count,
            per-shard step list). Single-device summaries are (B+1, 4);
            sharded ones come back stacked (n_shard, local+1, 4) and
            the step count is the max over shards (devices park
            independently)."""
            if mesh is None:
                return raw[:B], int(raw[B, search_ops.SUM_DONE]), None
            lanes = raw[:, :local, :].reshape(B, search_ops.SUM_W)
            shard_steps = [
                int(x) for x in raw[:, local, search_ops.SUM_DONE]
            ]
            return lanes, max(shard_steps), shard_steps

        def shard_occup():
            """Busy (primary or helper) lane count per shard, or None
            off-mesh — the per-shard occupancy column of the log."""
            if mesh is None:
                return None
            return [
                sum(
                    1 for i in range(s * local, (s + 1) * local)
                    if lane_job[i] is not None or lane_owner[i] is not None
                )
                for s in range(n_shard)
            ]

        def on_primary_parked(job: _RefillJob, lane: int, score: int,
                              move: int, nodes: int, nodes_row,
                              now: float):
            """Summary-only twin of on_primary_done for the pipelined
            loop: the aspiration verdict and all bookkeeping come from
            the packed boundary summary; the PV row is deferred to
            flush_pv, which reads it from the next RESOLVED state —
            legal because a DONE lane is frozen until the refill splice
            that flush_pv always precedes."""
            job.nodes_depth += nodes
            a_w = int(lane_alpha[lane])
            b_w = int(lane_beta[lane])
            fail_lo = score <= a_w and a_w > -INF
            fail_hi = score >= b_w and b_w < INF
            delta = deltas[min(job.delta_idx, len(deltas) - 1)]
            if a_w > -INF or b_w < INF:
                st = eng.aspiration_stats.setdefault(delta, [0, 0, 0, 0])
                st[0] += 1
                st[1] += int(fail_lo)
                st[2] += int(fail_hi)
                st[3] += nodes
            if (fail_lo or fail_hi) and delta is not None:
                job.delta_idx += 1
                a, b, _d = window_for(job, 1)
                admit(lane, job.board, job.depth, job.remaining, a, b,
                      0, lane, job.hh, job.hm)
                return
            job.prev_score = score
            job.have_prev = True
            job.hardness = max(nodes, 1)
            job.nodes_total += job.nodes_depth
            job.remaining -= job.nodes_depth
            job.nodes_depth = 0
            job.delta_idx = 0
            job.scores.set(1, job.depth, _score_from_int(score))
            job.depth_reached = job.depth
            job.best_move = _decode_uci(move) if move >= 0 else None
            final = (
                job.depth >= job.target_depth
                or job.remaining <= 0
                or now >= job.deadline
            )
            pv_pending.append((job, lane, job.depth, final))
            if final:
                release(job, nodes_row)
                active.remove(job)
                return  # _finalize waits in flush_pv for the PV row
            job.depth += 1
            a, b, _d = window_for(job, 1)
            admit(lane, job.board, job.depth, job.remaining, a, b,
                  0, lane, job.hh, job.hm)

        def flush_pv(st, now: float):
            """Materialize deferred PV rows with two small device-side
            gathers from a resolved state, then finalize the jobs whose
            response waited only on the PV. Must run BEFORE flush_adm:
            a refill splice resets the spliced lanes' PV tables."""
            if not pv_pending:
                return
            rows = jnp.asarray(
                np.asarray([e[1] for e in pv_pending], np.int64)
            )
            pv_rows = stats.fetch(
                jnp.take(st.pv[:, 0], rows, axis=0), "pv"
            )
            pv_lens = stats.fetch(
                jnp.take(st.nt[:, 0, search_ops.NT_PVLEN], rows, axis=0),
                "pv_len",
            )
            for i, (job, _lane, depth, final) in enumerate(pv_pending):
                pv = [
                    _decode_uci(int(m))
                    for m in pv_rows[i][: int(pv_lens[i])]
                    if m >= 0
                ]
                job.pvs.set(1, depth, pv)
                if final:
                    self._finalize(job, now)
            pv_pending.clear()

        def reap_jobs(now: float, nodes_row):
            # ---- reap jobs past their chunk deadline
            for job in list(active):
                if now >= job.deadline:
                    release(job, nodes_row)
                    active.remove(job)
                    if pv_pending:
                        # the response built below holds job.pvs BY
                        # REFERENCE: a deferred pull landing after it
                        # would mutate an already-sent response
                        pv_pending[:] = [
                            e for e in pv_pending if e[0] is not job
                        ]
                    if job.depth_reached == 0:
                        # no usable result: fail the chunk so the
                        # server reassigns it (same contract as the
                        # serial path)
                        self._finalize(
                            job, now,
                            error="chunk deadline expired before "
                                  "depth 1 completed",
                        )
                    else:
                        self._finalize(job, now)

        def admit_new(now: float):
            # ---- admit pending positions, earliest deadline first.
            # Free lanes are tracked per shard and every admission lands
            # on the shard with the most free lanes (ties → lowest
            # shard), hardest-deadline-first within the boundary, so
            # queued positions spread across devices instead of piling
            # onto shard 0's early lanes. With one shard this is exactly
            # the historical ascending-lane assignment (one list, front
            # pops) — the single-device bit-identity contract holds.
            free_by_shard: List[List[int]] = [[] for _ in range(n_shard)]
            for i in range(B):
                if lane_job[i] is None and lane_owner[i] is None:
                    if (i // local) in fillable_shards:
                        free_by_shard[i // local].append(i)
            n_free = sum(len(f) for f in free_by_shard)

            def take_lane() -> int:
                s = max(
                    range(n_shard), key=lambda i: len(free_by_shard[i])
                )
                return free_by_shard[s].pop(0)

            if not entry.event.is_set():
                with self._q_lock:
                    self._pending.sort(key=lambda j: j.deadline)
                    take: List[_RefillJob] = []
                    for j in list(self._pending):
                        if len(take) >= n_free:
                            break
                        if j.variant != variant:
                            continue
                        self._pending.remove(j)
                        take.append(j)
                for job in take:
                    if now >= job.deadline:
                        self._finalize(
                            job, now,
                            error="chunk deadline expired before "
                                  "depth 1 completed",
                        )
                        continue
                    admit_primary(job, take_lane())
                    n_free -= 1
                    active.append(job)
            # ---- spend leftover free lanes on Lazy-SMP helpers
            if K > 1 and tt is not None and n_free and active:
                n_act = len(active)
                cur = sum(len(j.helpers) for j in active)
                hardness = [
                    j.hardness if j.remaining > 0 else 0
                    for j in active
                ]
                plan = TpuEngine._plan_helpers(
                    n_act, n_act + cur + n_free, K, hardness
                )
                want: dict = {}
                for r, _h in plan:
                    want[r] = want.get(r, 0) + 1
                for r, job in enumerate(active):
                    while n_free and len(job.helpers) < want.get(r, 0):
                        admit_helper(
                            job, take_lane(), len(job.helpers) + 1
                        )
                        n_free -= 1

        def flush_adm(st):
            # ---- flush staged admissions in ONE refill splice (donates
            # st — rebind to the return value); under a mesh the splice
            # runs through the shard_map'd masked merge, each device
            # rewriting only its own lanes. Returns (state, count,
            # per-shard admission counts or None).
            n_adm = len(adm["lane"])
            if not n_adm:
                return st, 0, None
            adm_shard = (
                None if mesh is None else np.bincount(
                    np.asarray(adm["lane"], np.int64) // local,
                    minlength=n_shard,
                ).astype(int).tolist()
            )
            splice_args = (
                eng.params, st, stack_boards(adm["board"]),
                adm["lane"],
                np.asarray(adm["depth"], np.int32),
                np.asarray(adm["budget"], np.int32),
            )
            splice_kw = dict(
                variant=variant,
                hist_hash=np.stack(adm["hh"]),
                hist_halfmove=np.stack(adm["hm"]),
                root_alpha=np.asarray(adm["alpha"], np.int32),
                root_beta=np.asarray(adm["beta"], np.int32),
                order_jitter=np.asarray(adm["jitter"], np.int32),
                group=np.asarray(adm["group"], np.int32),
            )
            if mesh is not None:
                st = refill_lanes_sharded(mesh, *splice_args, **splice_kw)
            else:
                st = search_ops.refill_lanes(*splice_args, **splice_kw)
            for k in adm:
                adm[k].clear()
            return st, n_adm, adm_shard

        res: Optional[dict] = None
        try:
            if not pipeline:
                # round-7 synchronous loop (FISHNET_TPU_PIPELINE=0):
                # block on the segment, materialize the full result
                # set, refill, repeat — kept bit-for-bit as the A/B
                # baseline, instrumented through SyncStats
                while True:
                    now = time.monotonic()
                    reap_jobs(
                        now, res["nodes"] if res is not None else None
                    )
                    admit_new(now)
                    state, n_adm, adm_shard = flush_adm(state)
                    if not active:
                        break  # nothing running; next session continues
                    # ---- dispatch one segment and block on it
                    live_n = len(active)
                    helper_n = sum(len(j.helpers) for j in active)
                    shard_live = shard_occup()
                    disp_steps = seg
                    seg_res = traced_snapshot()
                    t0 = time.monotonic()
                    with obs_trace.span("segment.dispatch", "engine",
                                        steps=seg, live=live_n):
                        state, tt, n, _summ = dispatch(state, tt, seg)
                    n_arr = np.asarray(
                        stats.fetch(n, "steps")
                    ).reshape(-1)
                    n = int(n_arr.max())
                    wall = time.monotonic() - t0
                    traced_residency(seg_res, t0, t0 + wall)
                    q_len = q_len_locked()
                    # ---- process finished lanes at the boundary
                    lane_done = stats.fetch(
                        state.lane[:, search_ops.LN_MODE]
                        == search_ops.MODE_DONE,
                        "done",
                    )
                    res = {
                        k: stats.fetch(v, k)
                        for k, v in search_ops.extract_results(
                            state, 0
                        ).items()
                        if k != "steps"
                    }
                    now = time.monotonic()
                    # helper lanes that parked on their own: charge+free
                    for lane in range(B):
                        job = lane_owner[lane]
                        if job is not None and lane_done[lane]:
                            hn = int(res["nodes"][lane])
                            job.nodes_total += hn
                            job.remaining -= hn
                            del job.helpers[lane]
                            lane_owner[lane] = None
                    # primary lanes that parked: aspiration verdict
                    for lane in range(B):
                        job = lane_job[lane]
                        if job is None or not lane_done[lane]:
                            continue
                        on_primary_done(job, lane, res, now)
                    snap = stats.boundary()
                    self._record_occupancy(
                        B, n, live_n, helper_n, n_adm, q_len, wall,
                        snap["host_ms"], snap["device_ms"],
                        snap["transfers"],
                        shard=None if mesh is None else {
                            "shard_live": shard_live,
                            "shard_refilled":
                                adm_shard or [0] * n_shard,
                            "shard_steps": [int(x) for x in n_arr],
                        },
                    )
                    if ctrl is not None:
                        seg = ctrl.update(
                            n >= disp_steps, snap["host_ms"],
                            snap["device_ms"],
                        )
            else:
                # pipelined double-buffered loop: one segment always in
                # flight; the boundary is processed from its packed
                # summary (one small transfer), and when every boundary
                # decision is already settled the NEXT segment is
                # dispatched speculatively before blocking, so all the
                # host bookkeeping below overlaps device compute
                now = time.monotonic()
                reap_jobs(now, None)
                admit_new(now)
                state, n_adm, adm_shard = flush_adm(state)
                pend = None
                if active:
                    pend_meta = (
                        len(active),
                        sum(len(j.helpers) for j in active),
                        n_adm, q_len_locked(),
                        shard_occup(), adm_shard,
                    )
                    pend_steps = seg
                    pend_res = traced_snapshot()
                    pend_t0 = time.monotonic()
                    with obs_trace.span("segment.dispatch", "engine",
                                        steps=seg):
                        pend = dispatch(state, tt, seg)
                    tt = pend[1]
                while pend is not None:
                    p_state, p_tt, _pn, p_summ = pend
                    nxt = None
                    now = time.monotonic()
                    margin = now + 2.0 * last_device_s
                    if (not adm["lane"] and not pv_pending
                            and q_len_locked() == 0
                            and all(margin < j.deadline for j in active)):
                        # no admissions staged, no PV owed, nothing
                        # queued, no deadline within ~2 segments: the
                        # synchronous loop would redispatch unchanged
                        # after this boundary, so issue segment k+1 now
                        # (donating the in-flight outputs in place)
                        nxt_meta = (
                            len(active),
                            sum(len(j.helpers) for j in active), 0, 0,
                            shard_occup(), None,
                        )
                        nxt_steps = seg
                        nxt_res = traced_snapshot()
                        nxt_t0 = time.monotonic()
                        with obs_trace.span("segment.dispatch", "engine",
                                            steps=seg, speculative=True):
                            nxt = dispatch(p_state, p_tt, seg)
                        tt = nxt[1]
                    summ, n, shard_steps = canon_summ(
                        stats.fetch(p_summ, "summary")
                    )
                    traced_residency(pend_res, pend_t0, time.monotonic())
                    lane_done = summ[:, search_ops.SUM_DONE].astype(bool)
                    nodes_row = summ[:, search_ops.SUM_NODES]
                    # lanes whose park was already handled at an earlier
                    # speculative boundary (admission staged, splice
                    # still pending) report DONE again — skip them
                    staged = set(adm["lane"])
                    now = time.monotonic()
                    # helper lanes that parked on their own: charge+free
                    for lane in range(B):
                        job = lane_owner[lane]
                        if (job is not None and lane_done[lane]
                                and lane not in staged):
                            hn = int(nodes_row[lane])
                            job.nodes_total += hn
                            job.remaining -= hn
                            del job.helpers[lane]
                            lane_owner[lane] = None
                    # primary lanes that parked: aspiration verdict
                    for lane in range(B):
                        job = lane_job[lane]
                        if (job is None or not lane_done[lane]
                                or lane in staged):
                            continue
                        on_primary_parked(
                            job, lane,
                            int(summ[lane, search_ops.SUM_SCORE]),
                            int(summ[lane, search_ops.SUM_MOVE]),
                            int(nodes_row[lane]), nodes_row, now,
                        )
                    reap_jobs(now, nodes_row)
                    admit_new(now)
                    if nxt is None:
                        # PV pulls read the resolved p_state BEFORE the
                        # refill splice below resets those lanes
                        flush_pv(p_state, now)
                    snap = stats.boundary()
                    last_device_s = snap["device_ms"] / 1000.0
                    self._record_occupancy(
                        B, n, pend_meta[0], pend_meta[1], pend_meta[2],
                        pend_meta[3],
                        (snap["host_ms"] + snap["device_ms"]) / 1000.0,
                        snap["host_ms"], snap["device_ms"],
                        snap["transfers"],
                        shard=None if mesh is None else {
                            "shard_live": pend_meta[4],
                            "shard_refilled":
                                pend_meta[5] or [0] * n_shard,
                            "shard_steps": shard_steps,
                        },
                    )
                    if ctrl is not None:
                        seg = ctrl.update(
                            n >= pend_steps, snap["host_ms"],
                            snap["device_ms"],
                        )
                    if nxt is not None:
                        pend = nxt
                        pend_meta = nxt_meta
                        pend_steps = nxt_steps
                        pend_res = nxt_res
                        pend_t0 = nxt_t0
                        continue
                    state, n_adm, adm_shard = flush_adm(p_state)
                    if not active:
                        break  # next session handles the rest
                    pend_meta = (
                        len(active),
                        sum(len(j.helpers) for j in active),
                        n_adm, q_len_locked(),
                        shard_occup(), adm_shard,
                    )
                    pend_steps = seg
                    pend_res = traced_snapshot()
                    pend_t0 = time.monotonic()
                    with obs_trace.span("segment.dispatch", "engine",
                                        steps=seg):
                        pend = dispatch(state, tt, seg)
                    tt = pend[1]
        except BaseException as e:
            # the driver died mid-session (device fault, OOM...): fail
            # every admitted job so no submitting thread waits forever
            now = time.monotonic()
            for job in active:
                release(job, None)
                self._finalize(job, now, error=f"tpu engine failed: {e}")
            # jobs released at a park boundary whose _finalize was still
            # deferred behind a PV pull: complete them with what the
            # summary recorded, or their submitters wait forever
            for job, _lane, _depth, final in pv_pending:
                if final:
                    self._finalize(job, now)
            pv_pending.clear()
            raise
        finally:
            eng.tt = tt

    def _record_occupancy(self, width, steps, live, helpers, refilled,
                          queue, wall, host_ms=0.0, device_ms=0.0,
                          transfers=0, shard=None):
        eng = self.engine
        tot = eng.occupancy_totals
        idle = width - live - helpers
        if steps == 0 and refilled == 0:
            # Pipelined overrun dispatch: the prefetched segment ran zero
            # steps because every lane finished during the previous one.
            # Its sync costs are real, but a no-op segment must not become
            # an occupancy row — consumers weight columns by `steps`, and
            # a refilled lane always steps at least once, so nothing else
            # is lost by dropping it.
            tot["host_ms"] += host_ms
            tot["device_ms"] += device_ms
            tot["transfers"] += transfers
            return
        tot["segments"] += 1
        tot["steps"] += steps
        tot["lane_steps"] += steps * width
        tot["live_lane_steps"] += steps * live
        tot["helper_lane_steps"] += steps * helpers
        tot["idle_lane_steps"] += steps * idle
        tot["refills"] += refilled
        tot["host_ms"] += host_ms
        tot["device_ms"] += device_ms
        tot["transfers"] += transfers
        row = {
            "segment": tot["segments"], "width": width, "steps": steps,
            "live": live, "helpers": helpers, "idle": idle,
            "refilled": refilled, "queue": queue,
            "transfers": transfers, "host_ms": host_ms,
            "device_ms": device_ms,
        }
        if shard is not None:
            # mesh sessions: per-shard busy-lane counts, admissions and
            # device step counts (shard_live counts LANES — primaries
            # plus helpers — where the scalar `live` counts positions)
            row.update(shard)
        eng.occupancy_log.append(row)
        if len(eng.occupancy_log) > 4096:
            del eng.occupancy_log[:-4096]
        rec = obs_trace.RECORDER
        if rec is not None:
            # lane-occupancy counter tracks render under the segment
            # spans SyncStats.boundary() emitted for this interval
            rec.counter("lanes.live", live, "engine")
            rec.counter("lanes.helpers", helpers, "engine")
            rec.counter("lanes.idle", idle, "engine")
            rec.counter("queue.depth", queue, "engine")
        # mirror the scheduler's ad-hoc totals into the metrics registry
        # (boundary-rate, not step-rate: a handful of locked updates per
        # segment, invisible next to a single device fetch)
        reg = obs_metrics.REGISTRY
        reg.absorb_totals("fishnet_occupancy", tot)
        reg.gauge("fishnet_lanes_live").set(live)
        reg.gauge("fishnet_queue_depth").set(queue)
        reg.histogram("fishnet_boundary_host_ms").observe(host_ms)
        if eng.trace:
            eng.trace(
                f"refill seg={tot['segments']} steps={steps} "
                f"live={live}/{width} helpers={helpers} idle={idle} "
                f"refilled={refilled} queue={queue} wall={wall:.3f}s "
                f"host={host_ms:.1f}ms dev={device_ms:.1f}ms "
                f"xfers={transfers}"
            )
