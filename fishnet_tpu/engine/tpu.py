"""The TPU batch engine: chunks in, PositionResponses out.

Replaces the reference's engine subprocess + UCI dialogue (reference:
src/stockfish.rs:222-465) with a host→device dispatch: all positions of a
chunk (and all multipv root moves) become lanes of one lockstep
alpha-beta search. Iterative deepening runs host-side, filling the same
multipv×depth score/pv matrices the UCI parser would have accumulated.

Lane counts are padded to fixed buckets so XLA compiles a handful of
program shapes, then caches.
"""
from __future__ import annotations

import asyncio
import os
import time
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..chess.position import Position
from ..chess.variants import from_fen
from ..client.ipc import Chunk, Matrix, PositionResponse, WorkPosition
from ..client.wire import AnalysisWork, MoveWork, Score
from ..models import nnue
from ..ops.board import from_position, stack_boards
from ..ops.search import MATE, search_batch_resumable
from .base import EngineError

MAX_PLY = 24  # static stack depth; supports search depths up to 23
# 16 covers every single-pv chunk (planner emits ≤10 positions per chunk,
# incl. skip-overlap re-appends — client/planner.py); 64 covers multipv
# root-move lanes. Fewer buckets = fewer cold XLA compiles to warm up.
LANE_BUCKETS = (16, 64, 128, 256)


def _decode_uci(m: int) -> str:
    frm, to, promo = m & 63, (m >> 6) & 63, (m >> 12) & 7
    s = (
        "abcdefgh"[frm & 7] + str((frm >> 3) + 1)
        + "abcdefgh"[to & 7] + str((to >> 3) + 1)
    )
    if promo:
        s += " nbrq"[promo]
    return s


def _score_from_int(v: int, root_ply_to_mate_sign: int = 1) -> Score:
    if v >= MATE - 1000:
        return Score.mate((MATE - v + 1) // 2)
    if v <= -(MATE - 1000):
        return Score.mate(-((MATE + v + 1) // 2))
    return Score.cp(int(v))


def _pad_lanes(n: int) -> int:
    for b in LANE_BUCKETS:
        if n <= b:
            return b
    return ((n + 255) // 256) * 256


class TpuEngine:
    """Batched analysis engine. `variants` lists what it accepts (the
    planner routes only those here — client/planner.py tpu_variants)."""

    def __init__(
        self,
        params: Optional[nnue.NnueParams] = None,
        weights_path: Optional[str] = None,
        max_depth: int = 6,
        seed: int = 1234,
        tt_size_log2: int = 21,  # 2M slots ≈ 24 MiB HBM; 0 disables
    ) -> None:
        from ..utils import enable_compile_cache

        enable_compile_cache()  # restarts reuse compiled search programs
        # one shared transposition table for every lane and every chunk —
        # the per-process persistent hash (reference: Stockfish's TT,
        # ~64 MiB/core README.md:76). Concurrent workers may interleave
        # updates; tables are immutable arrays so interleaving only loses
        # entries, never corrupts (plus tt.py's XOR validation).
        from ..ops import tt as tt_mod

        self.tt = tt_mod.make_table(tt_size_log2) if tt_size_log2 else None
        if params is None:
            if weights_path and str(weights_path).endswith(".nnue"):
                # real Stockfish network file (models/nnue_import.py)
                from ..models import nnue_import

                params = nnue_import.load_nnue(weights_path).as_device()
            elif weights_path:
                params = nnue.load_params(weights_path)
            else:
                # packaged weights (assets.py); board768 = the
                # fully-incremental fast path (see models/nnue.py)
                from ..assets import load_default_params

                params = load_default_params("board768")
            if params is None:
                params = nnue.init_params(
                    jax.random.PRNGKey(seed), l1=64, feature_set="board768"
                )
        self.params = params
        self.max_depth = max_depth

    def warmup(self, buckets=None) -> None:
        """Pre-compile the hot search program for the given lane buckets.

        XLA caches one program per (lane bucket, MAX_PLY) shape; without
        this, the first chunk pays 20-40 s of compile against its deadline
        (move jobs have a 7 s deadline — they would always fail cold).
        16 covers single-pv chunks; 64 covers multipv root-move lanes
        (which pad to ≥64). The reference similarly does its engine prep
        before workers start (Assets::prepare, src/main.rs:94).
        FISHNET_TPU_WARMUP_BUCKETS="16" overrides (e.g. CPU smoke runs
        where each extra compile costs minutes)."""
        if buckets is None:
            env = os.environ.get("FISHNET_TPU_WARMUP_BUCKETS")
            buckets = (
                tuple(int(x) for x in env.split(",") if x)
                if env
                else LANE_BUCKETS[:2]
            )
        for b in buckets:
            roots = stack_boards([from_position(Position.initial())] * b)
            out = search_batch_resumable(
                self.params, roots, jnp.ones((b,), jnp.int32),
                jnp.full((b,), 64, jnp.int32), max_ply=MAX_PLY, tt=self.tt,
            )
            self.tt = out.pop("tt")
            jax.block_until_ready(out["nodes"])

    async def go_multiple(self, chunk: Chunk) -> List[PositionResponse]:
        loop = asyncio.get_running_loop()
        try:
            return await loop.run_in_executor(None, self._go_multiple_sync, chunk)
        except EngineError:
            raise
        except Exception as e:  # device/compile errors surface as EngineError
            raise EngineError(f"tpu engine failed: {e}") from e

    async def close(self) -> None:
        pass

    # ----------------------------------------------------------------- sync

    def _go_multiple_sync(self, chunk: Chunk) -> List[PositionResponse]:
        started = time.monotonic()
        positions = []
        for wp in chunk.positions:
            pos = from_fen(wp.root_fen, chunk.variant)
            for uci in wp.moves:
                pos = pos.push(pos.parse_uci(uci))
            positions.append(pos)

        work = chunk.work
        if isinstance(work, AnalysisWork):
            multipv = work.effective_multipv()
            target_depth = min(work.depth or self.max_depth, self.max_depth, MAX_PLY - 1)
            budget = work.nodes.get(chunk.flavor.eval_flavor())
        else:
            assert isinstance(work, MoveWork)
            multipv = 1
            target_depth = min(work.level.depth, self.max_depth, MAX_PLY - 1)
            budget = None

        if multipv > 1:
            responses = self._analyse_multipv(
                chunk, positions, multipv, target_depth, budget, started
            )
        else:
            responses = self._analyse_single(
                chunk, positions, target_depth, budget, started
            )
        return responses

    def _terminal_response(self, chunk, wp: WorkPosition, pos: Position,
                           elapsed: float) -> PositionResponse:
        winner, _ = pos.outcome()
        scores, pvs = Matrix(), Matrix()
        scores.set(1, 0, Score.mate(0) if winner is not None else Score.cp(0))
        pvs.set(1, 0, [])
        return PositionResponse(
            work=chunk.work, position_index=wp.position_index, url=wp.url,
            scores=scores, pvs=pvs, best_move=None, depth=0, nodes=0,
            time_s=elapsed,
        )

    def _analyse_single(self, chunk, positions, target_depth, budget, started):
        terminal = {
            i for i, p in enumerate(positions) if p.outcome() is not None
        }
        lanes = [i for i in range(len(positions)) if i not in terminal]

        scores = [Matrix() for _ in positions]
        pvs = [Matrix() for _ in positions]
        depth_reached = [0] * len(positions)
        best_moves: List[Optional[str]] = [None] * len(positions)
        nodes_total = [0] * len(positions)

        if lanes:
            B = _pad_lanes(len(lanes))
            boards = [from_position(positions[i]) for i in lanes]
            pad = from_position(positions[lanes[0]])
            roots = stack_boards(boards + [pad] * (B - len(boards)))
            per_pos_budget = budget if budget is not None else 10_000_000
            remaining = np.full(B, per_pos_budget, dtype=np.int64)

            deadline = chunk.deadline - 0.25  # leave slack to package results
            for depth in range(1, target_depth + 1):
                depth_arr = np.zeros(B, np.int32)
                depth_arr[: len(lanes)] = depth
                budget_arr = np.clip(remaining, 0, 2**31 - 1).astype(np.int32)
                out = search_batch_resumable(
                    self.params, roots, jnp.asarray(depth_arr),
                    jnp.asarray(budget_arr), max_ply=MAX_PLY,
                    deadline=deadline, tt=self.tt,
                )
                self.tt = out.pop("tt")
                out = {k: np.asarray(v) for k, v in out.items()}
                exhausted_all = True
                for j, i in enumerate(lanes):
                    if remaining[j] <= 0 or not bool(out["done"][j]):
                        continue  # lane skipped, or stopped mid-depth on deadline
                    nodes_total[i] += int(out["nodes"][j])
                    remaining[j] -= int(out["nodes"][j])
                    sc = int(out["score"][j])
                    scores[i].set(1, depth, _score_from_int(sc))
                    pv = [
                        _decode_uci(int(m))
                        for m in out["pv"][j][: int(out["pv_len"][j])]
                        if m >= 0
                    ]
                    pvs[i].set(1, depth, pv)
                    depth_reached[i] = depth
                    mv = int(out["move"][j])
                    best_moves[i] = _decode_uci(mv) if mv >= 0 else None
                    if remaining[j] > 0:
                        exhausted_all = False
                if exhausted_all or time.monotonic() >= deadline:
                    break

        # deadline hit before even depth 1 finished: no usable result for
        # some lane — fail the whole chunk so the server reassigns it
        # (reference forgets failed batches, src/queue.rs:226-233)
        if any(depth_reached[i] == 0 for i in lanes):
            raise EngineError("chunk deadline expired before depth 1 completed")

        elapsed = max(time.monotonic() - started, 1e-6)
        per_pos_time = elapsed / max(len(positions), 1)
        responses = []
        for i, wp in enumerate(chunk.positions):
            if i in terminal:
                responses.append(
                    self._terminal_response(chunk, wp, positions[i], per_pos_time)
                )
                continue
            nps = int(nodes_total[i] / per_pos_time) if per_pos_time > 0 else None
            responses.append(
                PositionResponse(
                    work=chunk.work, position_index=wp.position_index,
                    url=wp.url, scores=scores[i], pvs=pvs[i],
                    best_move=best_moves[i], depth=depth_reached[i],
                    nodes=nodes_total[i], time_s=per_pos_time, nps=nps,
                )
            )
        return responses

    def _analyse_multipv(self, chunk, positions, multipv, target_depth,
                         budget, started):
        """MultiPV via root-move lanes: every legal root move of every
        position becomes a lane searched at depth-1."""
        responses = []
        elapsed_base = time.monotonic()
        for wp, pos in zip(chunk.positions, positions):
            t0 = time.monotonic()
            if pos.outcome() is not None:
                responses.append(
                    self._terminal_response(chunk, wp, pos, 0.001)
                )
                continue
            legal = pos.legal_moves()
            children = [pos.push(m) for m in legal]
            # pad to ≥64 so warmup's precompiled bucket covers the common
            # 20-40 legal-move case (>64 legal moves is rare; pays compile)
            B = _pad_lanes(max(len(children), 64))
            boards = [from_position(c) for c in children]
            roots = stack_boards(boards + [boards[0]] * (B - len(boards)))

            scores, pvs = Matrix(), Matrix()
            nodes_total = 0
            depth_reached = 0
            best_move = None
            per_pos_budget = budget if budget is not None else 10_000_000
            remaining = per_pos_budget

            deadline = chunk.deadline - 0.25
            for depth in range(1, target_depth + 1):
                depth_arr = np.zeros(B, np.int32)
                depth_arr[: len(children)] = depth - 1
                share = max(remaining // max(len(children), 1), 1)
                out = search_batch_resumable(
                    self.params, roots,
                    jnp.asarray(depth_arr),
                    jnp.asarray(np.full(B, min(share, 2**31 - 1), np.int32)),
                    max_ply=MAX_PLY,
                    deadline=deadline, tt=self.tt,
                )
                self.tt = out.pop("tt")
                out = {k: np.asarray(v) for k, v in out.items()}
                if not bool(out["done"][: len(children)].all()):
                    break  # deadline hit mid-depth: keep previous depth's lines
                step_nodes = int(out["nodes"][: len(children)].sum()) + len(children)
                nodes_total += step_nodes
                remaining -= step_nodes
                ranked = []
                for j, m in enumerate(legal):
                    child_score = -int(out["score"][j])
                    child_pv = [
                        _decode_uci(int(x))
                        for x in out["pv"][j][: int(out["pv_len"][j])]
                        if x >= 0
                    ]
                    ranked.append((child_score, m.uci(), [m.uci()] + child_pv))
                ranked.sort(key=lambda t: -t[0])
                for rank, (sc, _mv, line) in enumerate(ranked[:multipv], start=1):
                    scores.set(rank, depth, _score_from_int(sc))
                    pvs.set(rank, depth, line)
                depth_reached = depth
                best_move = ranked[0][1]
                if remaining <= 0 or time.monotonic() >= deadline:
                    break

            if depth_reached == 0:
                raise EngineError(
                    "chunk deadline expired before depth 1 completed (multipv)"
                )
            dt = max(time.monotonic() - t0, 1e-6)
            responses.append(
                PositionResponse(
                    work=chunk.work, position_index=wp.position_index,
                    url=wp.url, scores=scores, pvs=pvs, best_move=best_move,
                    depth=depth_reached, nodes=nodes_total, time_s=dt,
                    nps=int(nodes_total / dt),
                )
            )
        return responses
