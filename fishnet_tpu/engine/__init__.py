"""Engine backends: TPU (JAX/XLA), UCI subprocess, and pure-Python CPU,
plus the process-isolation supervisor that hosts any of them in a
killable child process."""
from .base import Engine, EngineError, EngineFactory
from .supervisor import SupervisedEngine, SupervisorStats, default_host_cmd

__all__ = [
    "Engine",
    "EngineError",
    "EngineFactory",
    "SupervisedEngine",
    "SupervisorStats",
    "default_host_cmd",
]
