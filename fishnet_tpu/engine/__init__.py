"""Engine backends: TPU (JAX/XLA), UCI subprocess, and pure-Python CPU."""
from .base import Engine, EngineError, EngineFactory

__all__ = ["Engine", "EngineError", "EngineFactory"]
