"""Pure-Python fallback engine.

Plays the role of the bundled engines for environments with neither TPU nor
an external UCI binary: a small iterative-deepening negamax with material +
mobility evaluation over the host rules library. It exists for functional
completeness and as a pipeline oracle — the TPU engine is the performance
path. Engine surface mirrors the reference's per-chunk dialogue
(reference: src/stockfish.rs:222-288): one response per position, scores
and PVs accumulated per depth into multipv×depth matrices.
"""
from __future__ import annotations

import asyncio
import random
import time
from typing import List, Optional, Tuple

from ..chess.position import Position
from ..chess.types import BISHOP, KNIGHT, PAWN, QUEEN, ROOK
from ..chess.variants import from_fen
from ..client.ipc import Chunk, Matrix, PositionResponse, WorkPosition
from ..client.wire import AnalysisWork, MoveWork, Score
from .session import ChunkSubmit

MATE_VALUE = 32000
PIECE_VALUES = {PAWN: 100, KNIGHT: 300, BISHOP: 315, ROOK: 500, QUEEN: 900, 5: 0}


class SearchBudgetExceeded(Exception):
    pass


class PySearch:
    def __init__(self, node_budget: Optional[int] = None):
        self.nodes = 0
        self.node_budget = node_budget

    def evaluate(self, pos: Position) -> int:
        """Material + mobility, from the side to move's perspective."""
        us = pos.turn
        score = 0
        for ptype, val in PIECE_VALUES.items():
            score += val * (
                bin(pos.bbs[us][ptype]).count("1")
                - bin(pos.bbs[us ^ 1][ptype]).count("1")
            )
        score += 2 * len(pos.legal_moves())
        return score

    def _ordered_moves(self, pos: Position):
        moves = pos.legal_moves()
        them_occ = pos.occ[pos.turn ^ 1]
        moves.sort(key=lambda m: 0 if (1 << m.to_sq) & them_occ else 1)
        return moves

    def negamax(
        self, pos: Position, depth: int, alpha: int, beta: int, ply: int
    ) -> Tuple[int, List[str]]:
        self.nodes += 1
        if self.node_budget is not None and self.nodes > self.node_budget:
            raise SearchBudgetExceeded()
        moves = self._ordered_moves(pos)
        outcome = pos.outcome(moves)
        if outcome is not None:
            winner, _reason = outcome
            if winner is None:
                return 0, []
            # a decided game means the side to move lost (checkmate/variant
            # loss) unless the variant outcome says the mover won
            return (
                (MATE_VALUE - ply) if winner == pos.turn else -(MATE_VALUE - ply)
            ), []
        if depth <= 0:
            return self.evaluate(pos), []
        best_line: List[str] = []
        best = -MATE_VALUE * 2
        for move in moves:
            child = pos.push(move)
            score, line = self.negamax(child, depth - 1, -beta, -alpha, ply + 1)
            score = -score
            if score > best:
                best = score
                best_line = [move.uci()] + line
            alpha = max(alpha, score)
            if alpha >= beta:
                break
        return best, best_line


def _score_of(value: int, ply_base: int = 0) -> Score:
    if value >= MATE_VALUE - 1000:
        return Score.mate((MATE_VALUE - value + 1) // 2)
    if value <= -(MATE_VALUE - 1000):
        return Score.mate(-((MATE_VALUE + value + 1) // 2))
    return Score.cp(value)


class PyEngine(ChunkSubmit):
    """Analyses chunks synchronously on the executor."""

    def __init__(self, max_depth: int = 3, multipv_max: int = 5):
        self.max_depth = max_depth
        self.multipv_max = multipv_max

    async def go_multiple(self, chunk: Chunk) -> List[PositionResponse]:
        loop = asyncio.get_running_loop()
        return await loop.run_in_executor(None, self._go_multiple_sync, chunk)

    async def close(self) -> None:
        pass

    def _go_multiple_sync(self, chunk: Chunk) -> List[PositionResponse]:
        return [self._analyse(chunk, pos) for pos in chunk.positions]

    def _analyse(self, chunk: Chunk, wp: WorkPosition) -> PositionResponse:
        started = time.monotonic()
        pos = from_fen(wp.root_fen, chunk.variant)
        for uci in wp.moves:
            pos = pos.push(pos.parse_uci(uci))

        work = chunk.work
        move_deadline: Optional[float] = None
        if isinstance(work, AnalysisWork):
            target_depth = min(work.depth or self.max_depth, self.max_depth)
            multipv = min(work.effective_multipv(), self.multipv_max)
            node_budget = work.nodes.get(chunk.flavor.eval_flavor())
        else:
            assert isinstance(work, MoveWork)
            target_depth = min(work.level.depth, self.max_depth)
            multipv = 1
            node_budget = None
            # play jobs are time-budgeted, not node-budgeted: the skill
            # table's movetime is the whole point of "play-speed" moves
            # (the reference passes it to Stockfish as `go movetime`)
            move_deadline = started + work.level.movetime_ms / 1000.0

        scores = Matrix()
        pvs = Matrix()
        search = PySearch(node_budget)
        best_move: Optional[str] = None

        outcome = pos.outcome()
        if outcome is not None:
            winner, _ = outcome
            if winner is None:
                score = Score.cp(0)
            else:
                score = Score.mate(0)
            scores.set(1, 0, score)
            pvs.set(1, 0, [])
            return PositionResponse(
                work=work,
                position_index=wp.position_index,
                url=wp.url,
                scores=scores,
                pvs=pvs,
                best_move=None,
                depth=0,
                nodes=search.nodes,
                time_s=time.monotonic() - started,
            )

        reached_depth = 0
        root_scored: List[Tuple[int, str, List[str]]] = []
        try:
            for depth in range(1, target_depth + 1):
                # depth 1 always completes so a move exists even on a
                # 50 ms level-1 budget; deeper iterations only start or
                # continue while the movetime budget allows
                if move_deadline is not None and depth > 1 and \
                        time.monotonic() >= move_deadline:
                    break
                moves = search._ordered_moves(pos)
                depth_scored = []
                aborted = False
                for move in moves:
                    if move_deadline is not None and depth > 1 and \
                            time.monotonic() >= move_deadline:
                        aborted = True  # discard the partial depth
                        break
                    child = pos.push(move)
                    value, line = search.negamax(
                        child, depth - 1, -MATE_VALUE * 2, MATE_VALUE * 2, 1
                    )
                    depth_scored.append((-value, move.uci(), [move.uci()] + line))
                if aborted:
                    break
                depth_scored.sort(key=lambda t: -t[0])
                root_scored = depth_scored
                reached_depth = depth
                for rank, (value, _uci, line) in enumerate(
                    depth_scored[:multipv], start=1
                ):
                    scores.set(rank, depth, _score_of(value))
                    pvs.set(rank, depth, line)
        except SearchBudgetExceeded:
            pass

        if root_scored:
            best_move = self._pick_move(work, root_scored)
        elapsed = max(time.monotonic() - started, 1e-6)
        return PositionResponse(
            work=work,
            position_index=wp.position_index,
            url=wp.url,
            scores=scores,
            pvs=pvs,
            best_move=best_move,
            depth=reached_depth,
            nodes=search.nodes,
            time_s=elapsed,
            nps=int(search.nodes / elapsed),
        )

    def _pick_move(self, work, root_scored) -> str:
        """Move jobs weaken play below max level by sampling near-best moves
        (the reference delegates this to Stockfish's Skill Level option —
        src/stockfish.rs:261-277; here it is approximated directly)."""
        if isinstance(work, MoveWork) and work.level.level < 8:
            margin = (9 - work.level.level) * 30
            best_value = root_scored[0][0]
            candidates = [t for t in root_scored if t[0] >= best_value - margin]
            return random.choice(candidates)[1]
        return root_scored[0][1]
