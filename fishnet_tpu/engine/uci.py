"""UCI subprocess engine adapter.

Behavioral parity with the reference's Stockfish actor (reference:
src/stockfish.rs:18-465): spawn in own process group (^C must not reach the
engine), init with UCI_Chess960=true + isready, per-chunk option setup
(MultiVariant: Use NNUE / UCI_AnalyseMode / UCI_Variant; always MultiPV and
Skill Level), per-position `position fen … moves …` + `go …`, and parse
`info`/`bestmove` into the multipv×depth score/pv matrices.

This framework bundles no engine binaries (weights are the asset, not
executables — see assets.py); this adapter exists for capability parity
when the operator points it at an external Stockfish/Fairy-Stockfish build,
and doubles as the reference-oracle hook for cross-checking the TPU engine.
"""
from __future__ import annotations

import asyncio
import os
import time
from typing import List, Optional

from ..client.ipc import Chunk, Matrix, PositionResponse, WorkPosition
from ..client.wire import AnalysisWork, EngineFlavor, MoveWork
from ..client.wire import Score
from .base import EngineError
from .session import ChunkSubmit

# lichess variant key → UCI_Variant value (reference: shakmaty Variant::uci)
UCI_VARIANT_NAMES = {
    "standard": "chess",
    "chess960": "chess",
    "fromPosition": "chess",
    "crazyhouse": "crazyhouse",
    "antichess": "antichess",
    "atomic": "atomic",
    "horde": "horde",
    "kingOfTheHill": "kingofthehill",
    "racingKings": "racingkings",
    "threeCheck": "3check",
}


class UciEngine(ChunkSubmit):
    def __init__(self, exe_path: str, logger=None, flavor: EngineFlavor = EngineFlavor.OFFICIAL):
        self.exe_path = exe_path
        self.logger = logger
        self.flavor = flavor
        self.proc: Optional[asyncio.subprocess.Process] = None
        self._initialized = False

    async def _ensure_started(self) -> None:
        if self.proc is not None and self.proc.returncode is None:
            return
        try:
            self.proc = await asyncio.create_subprocess_exec(
                self.exe_path,
                stdin=asyncio.subprocess.PIPE,
                stdout=asyncio.subprocess.PIPE,
                stderr=asyncio.subprocess.DEVNULL,
                # own process group so ^C at the client doesn't kill the
                # engine mid-chunk (reference: src/stockfish.rs:97-113)
                start_new_session=True,
            )
        except OSError as e:
            raise EngineError(f"failed to spawn {self.exe_path}: {e}") from e
        self._initialized = False

    async def _send(self, line: str) -> None:
        assert self.proc is not None and self.proc.stdin is not None
        self.proc.stdin.write(line.encode() + b"\n")
        await self.proc.stdin.drain()

    async def _read_line(self) -> str:
        assert self.proc is not None and self.proc.stdout is not None
        raw = await self.proc.stdout.readline()
        if not raw:
            raise EngineError("engine closed stdout")
        return raw.decode(errors="replace").rstrip("\r\n")

    async def _init_dialogue(self) -> None:
        if self._initialized:
            return
        await self._send("setoption name UCI_Chess960 value true")
        await self._send("isready")
        while True:
            line = await self._read_line()
            if line.strip() == "readyok":
                break
        self._initialized = True

    async def go_multiple(self, chunk: Chunk) -> List[PositionResponse]:
        try:
            await self._ensure_started()
            await self._init_dialogue()
            await self._send("ucinewgame")
            work = chunk.work
            if chunk.flavor is EngineFlavor.MULTI_VARIANT:
                nnue = chunk.flavor.eval_flavor().value == "nnue"
                await self._send(f"setoption name Use NNUE value {str(nnue).lower()}")
                analyse = isinstance(work, AnalysisWork)
                await self._send(
                    f"setoption name UCI_AnalyseMode value {str(analyse).lower()}"
                )
                variant = UCI_VARIANT_NAMES.get(chunk.variant, chunk.variant)
                await self._send(f"setoption name UCI_Variant value {variant}")
            await self._send(
                f"setoption name MultiPV value {work.effective_multipv()}"
            )
            skill = 20 if isinstance(work, AnalysisWork) else work.level.engine_skill_level
            await self._send(f"setoption name Skill Level value {skill}")

            responses = []
            for wp in chunk.positions:
                responses.append(await self._go(chunk, wp))
            return responses
        except (OSError, asyncio.IncompleteReadError) as e:
            raise EngineError(str(e)) from e

    async def _go(self, chunk: Chunk, wp: WorkPosition) -> PositionResponse:
        work = chunk.work
        moves = " ".join(wp.moves)
        await self._send(f"position fen {wp.root_fen} moves {moves}")
        if isinstance(work, MoveWork):
            go = (
                f"go movetime {work.level.movetime_ms} depth {work.level.depth}"
            )
            if work.clock is not None:
                wtime = work.clock.wtime_centis * 10
                btime = work.clock.btime_centis * 10
                inc = work.clock.inc_seconds * 1000
                go += f" wtime {wtime} btime {btime} winc {inc} binc {inc}"
        else:
            assert isinstance(work, AnalysisWork)
            go = f"go nodes {work.nodes.get(chunk.flavor.eval_flavor())}"
            if work.depth is not None:
                go += f" depth {work.depth}"
        await self._send(go)

        scores = Matrix()
        pvs = Matrix()
        depth = 0
        multipv = 1
        time_s = 0.0
        nodes = 0
        nps = None
        while True:
            line = await self._read_line()
            parts = line.split(" ")
            if parts[0] == "bestmove":
                if scores.best() is None:
                    raise EngineError("missing score in engine output")
                best_move = parts[1] if len(parts) > 1 and parts[1] != "(none)" else None
                return PositionResponse(
                    work=work, position_index=wp.position_index, url=wp.url,
                    scores=scores, pvs=pvs, best_move=best_move, depth=depth,
                    nodes=nodes, time_s=time_s, nps=nps,
                )
            if parts[0] != "info":
                continue
            it = iter(parts[1:])
            for tok in it:
                if tok == "multipv":
                    multipv = int(next(it))
                elif tok == "depth":
                    depth = int(next(it))
                elif tok == "nodes":
                    nodes = int(next(it))
                elif tok == "time":
                    time_s = int(next(it)) / 1000.0
                elif tok == "nps":
                    nps = int(next(it))
                elif tok == "score":
                    kind = next(it)
                    value = int(next(it))
                    if kind == "cp":
                        scores.set(multipv, depth, Score.cp(value))
                    elif kind == "mate":
                        scores.set(multipv, depth, Score.mate(value))
                    else:
                        raise EngineError(f"expected cp or mate, got {kind!r}")
                elif tok == "pv":
                    pvs.set(multipv, depth, list(it))

    async def close(self) -> None:
        if self.proc is None:
            return
        proc, self.proc = self.proc, None
        try:
            if proc.returncode is None:
                proc.kill()
            # bounded: a kill that doesn't stick (stuck in uninterruptible
            # IO) must not wedge close() forever
            await asyncio.wait_for(proc.wait(), timeout=10.0)
        except ProcessLookupError:
            pass
        except asyncio.TimeoutError:
            pass  # killed but unreaped; abandon rather than block
