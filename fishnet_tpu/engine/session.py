"""Position-level session interface over the chunk engines.

The roadmap's refactor unlock (ROADMAP.md "New directions" #1): everything
above the `LaneScheduler` used to assume exactly one upstream speaking the
fishnet chunk protocol — `engine/tpu.py` routes all work through
`go_multiple(Chunk)`. This module splits the session-driving core out from
behind that protocol: a frontend holds `PositionRequest`s (one position, its
own deadline and priority) and an `EngineSession` converts them into chunks
and feeds whatever engine it wraps. Concurrent `submit()` calls against the
TPU engine land in the `LaneScheduler`'s shared pending queue (any executor
thread submitting a chunk joins the combining driver), so the lichess client
(`client/workers.py`), the HTTP server (`fishnet_tpu/serve/`) and `bench.py`
all feed the same lane pool — the scheduler's hardest-deadline-first
admission orders their positions against each other by the per-request
deadlines carried through here.

The `submit()` surface is part of the `Engine` protocol (engine/base.py);
`ChunkSubmit` below is the shared conformance mixin for chunk-native
backends (PyEngine, UciEngine, TpuEngine, SupervisedEngine — the last
covers the scripted fakehost child too, since it proxies chunks over the
supervisor pipe protocol).
"""
from __future__ import annotations

import asyncio
import itertools
import time
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

from ..client.ipc import Chunk, PositionResponse, WorkPosition
from ..obs import trace as obs_trace
from ..client.wire import (
    MAX_CHUNK_POSITIONS,
    AnalysisWork,
    EngineFlavor,
    MoveWork,
    NodeLimit,
    SkillLevel,
    Work,
)

# Priority tiers: interactive bestmove traffic outranks batch analysis at
# the admission controller; within a tier, deadlines order the work
# (hardest first — both in the serve waiting room and in the
# LaneScheduler's pending queue).
PRIORITY_INTERACTIVE = 0
PRIORITY_BATCH = 1

# Default per-position node budget for served requests with no explicit
# budget: the reference's production sf16/classical budgets
# (src/api.rs:214-233 order of magnitude), pre-scaled up by 7/6 so
# NodeLimit.get()'s chunk-overlap compensation lands back on round numbers.
DEFAULT_NODES = NodeLimit(sf16=2_800_000, classical=5_040_000)

DEFAULT_TIMEOUT_S = 8.0

_batch_seq = itertools.count()


@dataclass(frozen=True)
class PositionRequest:
    """One position submitted by any frontend.

    deadline is a time.monotonic() timestamp (None: now + DEFAULT_TIMEOUT_S
    at submission). priority is one of the PRIORITY_* tiers. kind is
    "analysis" (scores/pvs matrices) or "bestmove" (play a move at a
    lichess skill level).
    """

    fen: str
    moves: Tuple[str, ...] = ()
    variant: str = "standard"
    kind: str = "analysis"
    depth: Optional[int] = None
    multipv: Optional[int] = None
    nodes: Optional[int] = None
    level: int = 8
    deadline: Optional[float] = None
    priority: int = PRIORITY_BATCH
    # Request context (obs/trace.py make_ctx) stamped by the frontend
    # that accepted this request, or None when untraced. Observability
    # metadata only — deliberately NOT part of _GroupKey, so tracing a
    # request can never change how it chunks or what the engine sees.
    # Stored as a hashable key/value tuple because the dataclass is
    # frozen+hashable; ctx() rebuilds the dict.
    trace_ctx: Optional[Tuple[Tuple[str, object], ...]] = None

    def ctx(self) -> Optional[dict]:
        return dict(self.trace_ctx) if self.trace_ctx else None

    @staticmethod
    def freeze_ctx(ctx: Optional[dict]):
        return tuple(sorted(ctx.items())) if ctx else None


@dataclass(frozen=True)
class _GroupKey:
    """Requests sharing a group key are compatible with one Chunk: a chunk
    carries exactly one Work and one deadline, and both shape the search."""

    kind: str
    variant: str
    depth: Optional[int]
    multipv: Optional[int]
    nodes: Optional[int]
    level: int
    deadline: float


def _work_for(key: _GroupKey, batch_id: str) -> Work:
    if key.kind == "bestmove":
        return MoveWork(id=batch_id, level=SkillLevel(key.level))
    nodes = key.nodes
    if nodes is None:
        limit = DEFAULT_NODES
    else:
        # an explicit per-request budget applies as-is to either eval
        # flavor; pre-scale so NodeLimit.get()'s overlap compensation
        # cancels out and the engine sees exactly `nodes`
        scaled = nodes * (MAX_CHUNK_POSITIONS + 1) // MAX_CHUNK_POSITIONS
        limit = NodeLimit(sf16=scaled, classical=scaled)
    return AnalysisWork(
        id=batch_id,
        nodes=limit,
        timeout_s=7.0,
        depth=key.depth,
        multipv=key.multipv,
    )


def next_batch_id(prefix: str = "serve") -> str:
    """Work ids are capped at 24 chars by the wire layer; a process-local
    counter keeps them short and unique."""
    return f"{prefix}{next(_batch_seq) % 10**8:08d}"


def requests_to_chunks(
    requests: Sequence[PositionRequest],
    flavor: EngineFlavor = EngineFlavor.TPU,
    id_prefix: str = "serve",
    now: Optional[float] = None,
) -> List[Tuple[Chunk, List[int]]]:
    """Group compatible requests into chunks of <= MAX_CHUNK_POSITIONS.

    Returns (chunk, request_indices) pairs; index i of the chunk's
    positions (== position_index) answers requests[request_indices[i]].
    Only requests with identical work shape AND deadline share a chunk —
    the deadline cuts off the search, so merging deadlines would change
    results vs. submitting each request alone.
    """
    if now is None:
        now = time.monotonic()
    groups: Dict[_GroupKey, List[int]] = {}
    for i, req in enumerate(requests):
        deadline = req.deadline
        if deadline is None:
            deadline = now + DEFAULT_TIMEOUT_S
        key = _GroupKey(
            kind=req.kind, variant=req.variant, depth=req.depth,
            multipv=req.multipv, nodes=req.nodes, level=req.level,
            deadline=deadline,
        )
        groups.setdefault(key, []).append(i)
    out: List[Tuple[Chunk, List[int]]] = []
    for key, indices in groups.items():
        for lo in range(0, len(indices), MAX_CHUNK_POSITIONS):
            part = indices[lo:lo + MAX_CHUNK_POSITIONS]
            work = _work_for(key, next_batch_id(id_prefix))
            positions = [
                WorkPosition(
                    work=work,
                    position_index=slot,
                    url=None,
                    skip=False,
                    root_fen=requests[i].fen,
                    moves=list(requests[i].moves),
                    ctx=requests[i].ctx(),
                )
                for slot, i in enumerate(part)
            ]
            chunk = Chunk(
                work=work,
                deadline=key.deadline,
                variant=key.variant,
                flavor=flavor,
                positions=positions,
            )
            out.append((chunk, part))
    return out


class ChunkSubmit:
    """Conformance mixin: `submit()` for any engine exposing
    `go_multiple(Chunk)`. One request becomes a one-position chunk; the
    TpuEngine's scheduler merges concurrent one-position chunks into the
    shared lane pool, so per-request submission costs no batching there."""

    _submit_flavor = EngineFlavor.TPU

    async def submit(self, request: PositionRequest) -> PositionResponse:
        (chunk, _indices), = requests_to_chunks(
            [request], flavor=self._submit_flavor
        )
        responses = await self.go_multiple(chunk)
        return responses[0]


@dataclass
class _SessionStats:
    submitted: int = 0
    completed: int = 0
    failed: int = 0


class EngineSession:
    """Shared front door for position-level callers.

    Owns nothing but the conversion: deadlines/priorities ride the
    requests, chunks are built per compatible group, and the wrapped
    engine's own concurrency model does the multiplexing (the TPU
    engine's LaneScheduler pools every concurrent chunk's positions;
    chunk-serial backends simply serialize). close() leaves the engine
    alive — the session is one of possibly many tenants of it.
    """

    def __init__(self, engine, flavor: EngineFlavor = EngineFlavor.TPU,
                 id_prefix: str = "serve"):
        self.engine = engine
        self.flavor = flavor
        self.id_prefix = id_prefix
        self.stats = _SessionStats()

    async def submit(self, request: PositionRequest) -> PositionResponse:
        results = await self.submit_many([request])
        return results[0]

    async def submit_many(
        self, requests: Sequence[PositionRequest]
    ) -> List[PositionResponse]:
        """Submit a batch of requests; responses come back in request
        order. Chunks run concurrently — against the TPU engine they
        share one lane pool and finish as their positions finish."""
        self.stats.submitted += len(requests)
        plan = requests_to_chunks(
            requests, flavor=self.flavor, id_prefix=self.id_prefix
        )
        out: List[Optional[PositionResponse]] = [None] * len(requests)

        async def run(chunk: Chunk, indices: List[int]) -> None:
            rec = obs_trace.RECORDER
            # one chunk can merge positions from several traced requests
            # (grouping is by work shape, not by caller) — the chunk
            # span lists every trace_id and carries each sampled flow
            tids = sorted({
                wp.ctx["trace_id"] for wp in chunk.positions
                if wp.ctx and wp.ctx.get("trace_id")
            })
            if rec is not None and tids:
                sampled = [t for t in tids if obs_trace.sampled(t)]
                with rec.span("serve.chunk", "serve",
                              batch=chunk.work.id, trace_ids=sampled):
                    for t in sampled:
                        rec.flow("request", t, "t")
                    responses = await self.engine.go_multiple(chunk)
            else:
                responses = await self.engine.go_multiple(chunk)
            for slot, i in enumerate(indices):
                out[i] = responses[slot]

        try:
            await asyncio.gather(*(run(c, idx) for c, idx in plan))
        except Exception:
            self.stats.failed += len(requests)
            raise
        assert all(r is not None for r in out)
        self.stats.completed += len(requests)
        return out  # type: ignore[return-value]

    async def close(self) -> None:
        pass
