"""Process-isolated engine supervisor: proxy + watchdog + circuit breaker.

The reference client's core robustness invariant is that an engine is
always killable: the per-core worker races each chunk against its
deadline and kills/respawns the Stockfish *subprocess* on overrun
(reference src/main.rs:263-390). The in-process TPU engine broke that
invariant — a wedged device leaves a zombie executor thread holding the
engine lock forever (docs/tpu-hang.md). `SupervisedEngine` restores it
by hosting the engine in a child process (engine/host.py) behind the
`Engine` protocol:

- **Phase heartbeats** (engine/frames.py protocol) prove the child is
  alive; the watchdog hard-kills it when the stream stalls for
  `hb_timeout`, or when an in-flight chunk overruns its deadline (the
  device-hang signature: heartbeats flow, the search phase never ends).
- **Respawn** is gated by `RandomizedBackoff` (reset on the first
  successful chunk) and re-runs the child's warmup, whose long XLA
  compiles are covered by warmup-phase heartbeats rather than a fixed
  timeout.
- **Session recovery** (round 9): the child streams each finished
  position as a `partial` frame (engine/host.py, fed by the
  LaneScheduler's exactly-once delivery hook) into an in-memory session
  journal keyed by position fingerprint (client/ipc.py). After a kill,
  the recovery ladder re-dispatches only the unfinished suffix
  (*replay*); a residual set that fails twice without progress is split
  in half (*bisection*) until the faulting position is isolated; an
  isolated poison position is *quarantined* — routed to the CPU
  fallback individually, this chunk and every later chunk, while the
  rest of the work stays on the TPU path. Failure becomes a
  per-position event instead of a per-engine event.
- **Circuit breaker**: after `breaker_threshold` child deaths within
  `breaker_window` seconds, the flavor degrades to the pure-Python CPU
  engine (engine/pyengine.py) so the client keeps acquiring and
  submitting work while the device is wedged. Every `probe_interval`
  seconds one chunk probes the child path; a successful probe restores
  it. Deaths the recovery ladder absorbs (it will replay/bisect/
  quarantine within the chunk) do NOT feed the breaker window — only
  one breaker-visible death is recorded when the ladder gives up, so a
  single poison position can no longer trip the whole-engine breaker.

Fault paths are exercised deterministically by pointing `host_cmd` at
the scriptable fake host (engine/fakehost.py); tests/test_supervisor.py
and tests/test_recovery.py cover every branch on CPU, and
tools/chaos.py replays the same scripts interactively (`--scenario`
runs the CI acceptance ladder end-to-end).
"""
from __future__ import annotations

import asyncio
import os
import sys
import time
from collections import deque
from dataclasses import dataclass, replace
from pathlib import Path
from typing import Deque, Dict, List, Optional, Set, Tuple

from ..client.backoff import RandomizedBackoff
from ..client.ipc import (
    Chunk,
    PositionResponse,
    WorkPosition,
    chunk_to_wire,
    position_fingerprint,
    responses_from_wire,
)
from ..client.logger import Logger
from ..obs import trace as obs_trace
from ..utils import sanitize
from ..utils import settings
from .base import EngineError
from .frames import FrameError, PipeClosed, encode, read_frame_async
from .session import ChunkSubmit

# the child must be able to `import fishnet_tpu` no matter where the
# parent was launched from
_PKG_PARENT = str(Path(__file__).resolve().parents[2])


def default_host_cmd(
    backend: str = "tpu",
    weights: Optional[str] = None,
    depth: Optional[int] = None,
    hb_interval: float = 1.0,
    helpers: Optional[int] = None,
    refill: Optional[bool] = None,
    mesh_refill: Optional[bool] = None,
    partials: Optional[bool] = None,
) -> List[str]:
    cmd = [
        sys.executable, "-m", "fishnet_tpu.engine.host",
        "--backend", backend, "--hb-interval", str(hb_interval),
    ]
    if weights:
        cmd += ["--weights", str(weights)]
    if depth is not None:
        cmd += ["--depth", str(depth)]
    if helpers is not None:
        # Lazy-SMP lane groups (engine/tpu.py helper_lanes); 1 disables
        cmd += ["--helpers", str(helpers)]
    if refill is not None:
        # continuous lane refill (engine/tpu.py LaneScheduler); 0 disables
        cmd += ["--refill", "1" if refill else "0"]
    if mesh_refill is not None:
        # shard-aware refill on multi-chip hosts; 0 pins meshed engines
        # back to chunk-serial dispatch (FISHNET_TPU_MESH_REFILL)
        cmd += ["--mesh-refill", "1" if mesh_refill else "0"]
    if partials is not None:
        # incremental per-position result streaming for the supervisor's
        # session journal (engine/host.py partial frames); 0 disables
        cmd += ["--partials", "1" if partials else "0"]
    return cmd


@dataclass
class SupervisorStats:
    """Plain counters; introspected by tests and tools/chaos.py."""

    spawns: int = 0
    deaths: int = 0  # involuntary child exits + supervisor kills
    kills: int = 0
    hb_stalls: int = 0
    deadline_kills: int = 0
    protocol_errors: int = 0
    breaker_trips: int = 0
    breaker_resets: int = 0
    probes: int = 0
    fallback_chunks: int = 0
    chunks_ok: int = 0
    # session recovery (round 9)
    partials: int = 0            # partial frames journaled
    duplicate_partials: int = 0  # exactly-once: re-sent partials ignored
    replays: int = 0             # re-dispatches resumed with a journal-shrunk suffix
    replayed_positions: int = 0  # positions recovered from the journal, not re-searched
    bisections: int = 0          # residual splits isolating a faulting position
    quarantined: int = 0         # poison positions routed individually to CPU
    quarantine_routed: int = 0   # positions pre-routed via the quarantine list
    progress_stalls: int = 0     # kills for a stalled partial stream


class _ChildErrReply(EngineError):
    """`err` reply frame: the child handled the failure itself and is
    still sane — not a death, never retried by the recovery ladder."""


def _consume_exc(fut: asyncio.Future) -> None:
    # futures may be resolved with an exception after their awaiter gave
    # up (kill races); retrieve it so asyncio doesn't log "never retrieved"
    if not fut.cancelled():
        fut.exception()


class SupervisedEngine(ChunkSubmit):
    """`Engine`-protocol proxy to a child engine host.

    Reusable after `close()` (the worker's drop-and-respawn pattern
    closes the engine on any error and asks the factory again — the
    factory caches this object, so breaker state survives the drop)."""

    def __init__(
        self,
        host_cmd: Optional[List[str]] = None,
        *,
        backend: str = "tpu",
        weights_path: Optional[str] = None,
        max_depth: Optional[int] = None,
        helper_lanes: Optional[int] = None,
        refill: Optional[bool] = None,
        mesh_refill: Optional[bool] = None,
        logger: Optional[Logger] = None,
        hb_interval: float = 1.0,
        hb_timeout: Optional[float] = None,
        deadline_margin: float = 0.15,
        breaker_threshold: int = 3,
        breaker_window: float = 600.0,
        probe_interval: float = 60.0,
        fallback_factory=None,
        backoff: Optional[RandomizedBackoff] = None,
        env: Optional[dict] = None,
        replay: Optional[bool] = None,
        bisect_max: Optional[int] = None,
        quarantine: Optional[bool] = None,
        progress_timeout: Optional[float] = None,
        stats_recorder=None,
    ) -> None:
        # session-recovery policy (None defers to the settings registry)
        self.replay = (
            settings.get_bool("FISHNET_TPU_REPLAY")
            if replay is None else bool(replay)
        )
        self.bisect_max = (
            settings.get_int("FISHNET_TPU_BISECT_MAX")
            if bisect_max is None else int(bisect_max)
        )
        self.quarantine_on = (
            settings.get_bool("FISHNET_TPU_QUARANTINE")
            if quarantine is None else bool(quarantine)
        )
        # optional hang bisection: with >=1 partial delivered this
        # dispatch, a partial stream silent for this long is killed even
        # though heartbeats flow — the device-hang signature caught
        # before the deadline, leaving the ladder time to bisect
        self.progress_timeout = progress_timeout
        self.host_cmd = host_cmd or default_host_cmd(
            backend=backend, weights=weights_path, depth=max_depth,
            hb_interval=hb_interval, helpers=helper_lanes, refill=refill,
            mesh_refill=mesh_refill, partials=self.replay,
        )
        self.logger = logger or Logger()
        self.hb_interval = hb_interval
        # N missed beats = dead, not slow: generous enough for scheduler
        # jitter, far under any chunk deadline
        self.hb_timeout = hb_timeout if hb_timeout is not None else 8 * hb_interval
        self.deadline_margin = deadline_margin
        self.breaker_threshold = breaker_threshold
        self.breaker_window = breaker_window
        self.probe_interval = probe_interval
        self.fallback_factory = fallback_factory
        self.env = env
        self.stats = SupervisorStats()

        self._lock = asyncio.Lock()  # one in-flight chunk, like TpuEngine
        self._backoff = backoff or RandomizedBackoff()
        self.proc: Optional[asyncio.subprocess.Process] = None
        self._reader: Optional[asyncio.Task] = None
        self._ready: Optional[asyncio.Future] = None
        self._pending = None  # (go id, future) for the in-flight chunk
        self._last_frame = 0.0
        self._phase: dict = {}
        # last ready frame's AOT boot report (engine/host.py): did this
        # child boot warm from a program bundle, and what does it cover
        self.aot_report: Optional[dict] = None
        self.mesh_report: Optional[dict] = None  # host mesh topology
        self._down_noted = True  # no live child yet
        self._closing = False
        self._go_id = 0
        self._deaths: Deque[float] = deque()
        self._breaker_open = False
        self._next_probe = 0.0
        self._fallback = None
        # session journal: fp -> wire response, filled by partial frames
        # from the CURRENT dispatch. Single-writer invariant (lint rule
        # conc-journal-writer): mutated only via _journal_record /
        # _journal_reset, so the recovery ladder can trust its contents.
        self._journal: Dict[str, dict] = {}
        self._journal_expect: Set[str] = set()
        self._last_partial: Optional[float] = None
        # FISHNET_TPU_SANITIZE, captured once: duplicate partials then
        # verify payload consistency (identical replay is designed;
        # a DIFFERENT answer for a journaled fingerprint is a bug)
        self._sanitize = sanitize.enabled()
        # poison positions (by content fingerprint), routed individually
        # to the CPU fallback for the rest of this process's life
        self._quarantine: Set[str] = set()
        # position-ack observer (fleet/coordinator.py): called with
        # (fp, wire_response) for every partial accepted into the
        # journal, so an upstream dispatcher can keep its own
        # exactly-once ledger even when this engine's ladder gives up
        # and the journaled results above never leave go_multiple
        self.on_partial = None
        self._ladder_active = False
        self._stats_recorder = stats_recorder
        # trace timeline (obs/trace.py): when FISHNET_TPU_TRACE_DIR is
        # set, the parent ring holds the merged supervisor+host timeline
        # (the child streams increments over trace frames) and the
        # recovery ladder dumps it as the flight recorder. Install the
        # module-global recorder only if the app hasn't already.
        self._trace_dir = settings.get_str("FISHNET_TPU_TRACE_DIR")
        if self._trace_dir and obs_trace.RECORDER is None:
            obs_trace.install_from_settings("supervisor")
        # child-monotonic → parent-monotonic mapping; rebuilt per child
        # incarnation in _spawn (each process has its own epoch)
        self._clock = obs_trace.ClockSync()

    # --------------------------------------------------------------- health

    @property
    def breaker_open(self) -> bool:
        """Public breaker state for upstream health checks (the fleet
        coordinator drains members whose engines degraded to fallback)."""
        return self._breaker_open

    @property
    def heartbeat_age(self) -> Optional[float]:
        """Seconds since the child's last frame, or None with no live
        child — the fleet's per-member liveness signal."""
        if self.proc is None or self._down_noted:
            return None
        return max(time.monotonic() - self._last_frame, 0.0)

    # ------------------------------------------------------------ lifecycle

    async def start(self) -> None:
        """Spawn the child and wait for warmup (heartbeat-governed, no
        fixed timeout — XLA compiles run minutes with phase=warmup beats).
        Called by app startup; `go_multiple` also self-heals lazily."""
        async with self._lock:
            await self._ensure_ready(None)

    async def close(self) -> None:
        self._closing = True
        try:
            proc = self.proc
            if proc is not None and proc.returncode is None:
                try:
                    await self._send({"t": "quit"})
                    await asyncio.wait_for(proc.wait(), timeout=2.0)
                except (EngineError, asyncio.TimeoutError):
                    await self._kill("shutdown", count=False)
            if self._reader is not None:
                self._reader.cancel()
                await asyncio.gather(self._reader, return_exceptions=True)
            if self._fallback is not None:
                fallback, self._fallback = self._fallback, None
                await fallback.close()
        finally:
            self.proc = None
            self._reader = None
            self._ready = None
            self._pending = None
            self._down_noted = True
            self._closing = False

    # ------------------------------------------------------------- dispatch

    async def go_multiple(self, chunk: Chunk) -> List[PositionResponse]:
        async with self._lock:
            if self._breaker_open:
                if time.monotonic() >= self._next_probe:
                    self.stats.probes += 1
                    self.logger.info(
                        "Circuit breaker: probing the supervised engine path"
                    )
                    try:
                        # probes bypass the recovery ladder: one cheap
                        # dispatch decides whether the child path is back
                        responses = await self._go_child(chunk, probe=True)
                    except EngineError as e:
                        self._next_probe = time.monotonic() + self.probe_interval
                        self.logger.warn(
                            f"Probe failed ({e}); staying on CPU fallback"
                        )
                        return await self._go_fallback(chunk)
                    self._breaker_open = False
                    self.stats.breaker_resets += 1
                    self.logger.headline(
                        "Circuit breaker CLOSED: supervised engine recovered"
                    )
                    return responses
                return await self._go_fallback(chunk)
            try:
                return await self._go_child(chunk)
            except EngineError:
                if self._breaker_open and time.monotonic() < chunk.deadline:
                    # this very death tripped the breaker: salvage the
                    # chunk on the fallback instead of failing it
                    return await self._go_fallback(chunk)
                raise

    async def _go_fallback(self, chunk: Chunk) -> List[PositionResponse]:
        if self._fallback is None:
            if self.fallback_factory is not None:
                self._fallback = self.fallback_factory()
            else:
                from .pyengine import PyEngine

                self._fallback = PyEngine()
        self.stats.fallback_chunks += 1
        try:
            return await self._fallback.go_multiple(chunk)
        except EngineError:
            raise
        except Exception as e:
            raise EngineError(f"fallback engine failed: {e}") from e

    async def _go_child(
        self, chunk: Chunk, probe: bool = False
    ) -> List[PositionResponse]:
        deadline = chunk.deadline - self.deadline_margin
        pairs = [(wp, position_fingerprint(wp)) for wp in chunk.positions]
        if probe or not self.replay:
            # legacy whole-chunk semantics: one dispatch, all-or-nothing
            responses = await self._dispatch_once(
                chunk, [wp for wp, _ in pairs], deadline
            )
            self.stats.chunks_ok += 1
            return responses

        results: Dict[str, PositionResponse] = {}
        healthy: List[Tuple[WorkPosition, str]] = []
        routed: List[Tuple[WorkPosition, str]] = []
        for wp, fp in pairs:
            if self.quarantine_on and fp in self._quarantine:
                routed.append((wp, fp))
            else:
                healthy.append((wp, fp))
        if healthy:
            await self._run_ladder(chunk, healthy, results, deadline)
        for wp, fp in routed:
            # known-poison positions go straight to the CPU fallback,
            # one at a time, without risking the child
            self.stats.quarantine_routed += 1
            results[fp] = await self._go_quarantined(chunk, wp)
        self.stats.chunks_ok += 1
        return [results[fp] for _, fp in pairs]

    async def _dispatch_once(
        self, chunk: Chunk, wps: List[WorkPosition], deadline: Optional[float]
    ) -> List[PositionResponse]:
        """One go/ok round-trip for a (sub-)chunk. Success clears the
        breaker window and resets the respawn backoff; an `err` reply
        raises `_ChildErrReply`; any death/kill raises plain EngineError
        (the recovery ladder's cue to harvest the journal and retry)."""
        # clear stale journal state BEFORE _ensure_ready: a leftover
        # _last_partial from a killed dispatch must not trigger a
        # progress-stall kill during the respawned child's warmup
        self._journal_reset()
        self._last_partial = None
        await self._ensure_ready(deadline)
        sub = (
            chunk if len(wps) == len(chunk.positions)
            else replace(chunk, positions=list(wps))
        )
        self._go_id += 1
        gid = self._go_id
        fut = asyncio.get_running_loop().create_future()
        fut.add_done_callback(_consume_exc)
        self._journal_reset(expect=[position_fingerprint(wp) for wp in wps])
        self._pending = (gid, fut)
        # sampled request contexts riding the sub-chunk: the dispatch
        # span lists them and carries each flow, so a replayed suffix
        # after a kill shows up as another linked dispatch on the same
        # trace_id (the ladder reuses the same WorkPositions, ctx intact)
        tids = sorted({
            wp.ctx["trace_id"] for wp in wps
            if wp.ctx and wp.ctx.get("trace_id")
        })
        tids = [t for t in tids if obs_trace.sampled(t)]
        try:
            with obs_trace.span(
                "supervisor.dispatch", "supervisor",
                id=gid, batch=str(chunk.work.id), positions=len(wps),
                trace_ids=tids,
            ):
                rec = obs_trace.RECORDER
                if rec is not None:
                    for t_id in tids:
                        rec.flow("request", t_id, "t")
                await self._send(
                    {"t": "go", "id": gid, "chunk": chunk_to_wire(sub)}
                )
                reply = await self._watch(
                    fut, deadline, kill_on_deadline=True,
                    label=f"chunk of batch {chunk.work.id}",
                )
        finally:
            self._pending = None
        if reply.get("t") == "err":
            # the child handled the failure itself and is still sane
            raise _ChildErrReply(f"engine host: {reply.get('error')}")
        try:
            responses = responses_from_wire(chunk.work, reply["responses"])
        except (KeyError, TypeError, ValueError) as e:
            self.stats.protocol_errors += 1
            await self._kill(f"malformed ok frame: {e}")
            raise EngineError(f"engine host sent a malformed result: {e}") from e
        if len(responses) != len(wps):
            self.stats.protocol_errors += 1
            await self._kill(
                f"ok frame carries {len(responses)} responses "
                f"for {len(wps)} positions"
            )
            raise EngineError("engine host returned a mismatched result count")
        self._deaths.clear()
        self._backoff.reset()
        return responses

    # ------------------------------------------------------ recovery ladder

    async def _run_ladder(
        self,
        chunk: Chunk,
        pairs: List[Tuple[WorkPosition, str]],
        results: Dict[str, PositionResponse],
        deadline: float,
    ) -> None:
        """Replay → bisect → quarantine. Work is a queue of position
        groups (initially one group: the whole chunk). A failed dispatch
        first harvests finished positions from the session journal; a
        shrunken residual is simply retried (*replay*). A residual that
        fails twice with no progress is split in half (*bisection*,
        consistent with docs/tpu-hang.md: B=8 is clean at shapes where
        B>=16 faults) until the faulting position is isolated; an
        isolated repeat offender is *quarantined* to the CPU fallback.
        The death budget (`bisect_max`), the chunk deadline, and the
        backoff-vs-deadline check in `_ensure_ready` bound the ladder."""
        queue: Deque[List[Tuple[WorkPosition, str]]] = deque([list(pairs)])
        fail_counts: Dict[Tuple[str, ...], int] = {}
        attempts = 0
        self._ladder_active = True
        try:
            while queue:
                group = queue.popleft()
                try:
                    responses = await self._dispatch_once(
                        chunk, [wp for wp, _ in group], deadline
                    )
                except _ChildErrReply:
                    raise
                except EngineError as e:
                    attempts += 1
                    harvested = self._harvest(chunk, group, results)
                    residual = [
                        (wp, fp) for wp, fp in group if fp not in results
                    ]
                    if not residual:
                        # every position of the group was already streamed
                        self.stats.replays += 1
                        self.stats.replayed_positions += harvested
                        continue
                    now = time.monotonic()
                    if now >= deadline:
                        self._breaker_count(f"{e}")
                        raise
                    if attempts > self.bisect_max:
                        self._breaker_count(f"{e}")
                        raise EngineError(
                            f"recovery ladder exhausted after {attempts} "
                            f"child deaths for batch {chunk.work.id}: {e}"
                        ) from e
                    if harvested:
                        # progress: hand the respawned child the suffix
                        self.stats.replays += 1
                        self.stats.replayed_positions += harvested
                        self.logger.warn(
                            f"Replaying {len(residual)} unfinished of "
                            f"{len(group)} positions after: {e}"
                        )
                        queue.appendleft(residual)
                        continue
                    gkey = tuple(fp for _, fp in residual)
                    fails = fail_counts.get(gkey, 0) + 1
                    fail_counts[gkey] = fails
                    if fails < 2:
                        queue.appendleft(residual)  # plain retry
                    elif len(residual) == 1:
                        wp, fp = residual[0]
                        if not self.quarantine_on:
                            self._breaker_count(f"{e}")
                            raise
                        self._quarantine_add(fp, wp, chunk)
                        results[fp] = await self._go_quarantined(chunk, wp)
                    else:
                        mid = len(residual) // 2
                        self.stats.bisections += 1
                        self.logger.warn(
                            f"Bisecting a {len(residual)}-position "
                            f"residual that failed twice ({e})"
                        )
                        queue.appendleft(residual[mid:])
                        queue.appendleft(residual[:mid])
                else:
                    for (wp, fp), res in zip(group, responses):
                        results[fp] = res  # ok reply wins over any partial
        finally:
            self._ladder_active = False

    def _harvest(
        self,
        chunk: Chunk,
        group: List[Tuple[WorkPosition, str]],
        results: Dict[str, PositionResponse],
    ) -> int:
        """Recover journaled partials of a failed dispatch into results.
        Returns how many positions were saved from re-search."""
        harvested = 0
        for wp, fp in group:
            wire = self._journal.get(fp)
            if wire is None or fp in results:
                continue
            try:
                results[fp] = responses_from_wire(chunk.work, [wire])[0]
            except (KeyError, TypeError, ValueError):
                self.stats.protocol_errors += 1
                continue  # malformed journal entry: just re-search it
            harvested += 1
        return harvested

    async def _go_quarantined(
        self, chunk: Chunk, wp: WorkPosition
    ) -> PositionResponse:
        responses = await self._go_fallback(replace(chunk, positions=[wp]))
        if len(responses) != 1:
            raise EngineError(
                "fallback engine returned a mismatched result count"
            )
        return responses[0]

    def _quarantine_add(self, fp: str, wp: WorkPosition, chunk: Chunk) -> None:
        self._quarantine.add(fp)
        self.stats.quarantined += 1
        self.logger.error(
            f"Quarantined poison position {fp} (batch {chunk.work.id}, "
            f"index {wp.position_index}): it alone goes to the CPU "
            "fallback; the rest of the chunk stays on the engine path"
        )
        if self._stats_recorder is not None:
            try:
                self._stats_recorder.record_quarantine(
                    fp, str(chunk.work.id), wp.position_index
                )
            except Exception as e:
                self.logger.warn(f"quarantine sink write failed: {e}")

    # ------------------------------------------------------ session journal

    def _journal_reset(self, expect=()) -> None:
        """Start a fresh journal for one dispatch (with _journal_record,
        the ONLY write path — lint rule conc-journal-writer)."""
        self._journal = {}
        self._journal_expect = set(expect)

    def _journal_record(self, fp: str, wire: dict,
                        ctx: Optional[dict] = None) -> None:
        """Deliver one partial frame into the journal: the single write
        path (lint rule conc-journal-writer), called only from the
        reader task so the ladder can trust exactly-once contents."""
        if fp not in self._journal_expect:
            return  # stale or alien fingerprint
        if fp in self._journal:
            if self._sanitize:
                sanitize.check_replay_consistent(
                    self._journal, fp, wire,
                    "engine/supervisor.py::_journal_record")
            self.stats.duplicate_partials += 1
            return  # exactly-once: re-sent partials are ignored
        self._journal[fp] = wire
        self.stats.partials += 1
        self._last_partial = time.monotonic()
        # ctx rode the partial frame (engine/host.py): pin the journal
        # event to its request so a post-kill harvest/replay stays on
        # the same causal chain in the merged timeline
        rec = obs_trace.RECORDER
        if (rec is not None and ctx and ctx.get("trace_id")
                and obs_trace.sampled(ctx["trace_id"])):
            rec.instant("position.journaled", "request",
                        **obs_trace.ctx_args(ctx, fp=fp))
            rec.flow("request", ctx["trace_id"], "t")
        if self.on_partial is not None:
            try:
                self.on_partial(fp, wire)
            except Exception as e:  # observer bugs must not kill delivery
                self.logger.warn(f"on_partial observer failed: {e}")

    # ------------------------------------------------------------- watchdog

    async def _watch(self, fut, deadline, kill_on_deadline: bool, label: str):
        """Await `fut` under watchdog policy: kill on heartbeat stall
        (always) or deadline overrun (chunks: yes; warmup: give up but
        let the child keep compiling for the next chunk)."""
        while True:
            if fut.done():
                return fut.result()  # raises EngineError if the child died
            now = time.monotonic()
            hb_age = now - self._last_frame
            if hb_age > self.hb_timeout:
                self.stats.hb_stalls += 1
                await self._kill(
                    f"missed heartbeats for {hb_age:.1f}s during {label}"
                )
                raise EngineError(
                    f"engine host missed heartbeats during {label}"
                )
            if deadline is not None and now >= deadline:
                if kill_on_deadline:
                    self.stats.deadline_kills += 1
                    phase = self._phase.get("phase", "?")
                    await self._kill(
                        f"{label} overran its deadline (phase={phase})"
                    )
                    raise EngineError(f"{label} overran its deadline")
                raise EngineError(f"engine host not ready in time for {label}")
            if (
                self.progress_timeout is not None
                and self._last_partial is not None
                and now - self._last_partial > self.progress_timeout
            ):
                # heartbeats flow but the partial stream went silent: the
                # device-hang signature, caught while deadline budget
                # remains for the recovery ladder to replay/bisect
                self.stats.progress_stalls += 1
                await self._kill(
                    f"partial stream stalled for "
                    f"{now - self._last_partial:.1f}s during {label}"
                )
                raise EngineError(
                    f"engine host stopped streaming results during {label}"
                )
            timeout = max(self.hb_timeout - hb_age, self.hb_interval / 4)
            if deadline is not None:
                timeout = min(timeout, deadline - now)
            if self.progress_timeout is not None and self._last_partial is not None:
                timeout = min(
                    timeout,
                    self._last_partial + self.progress_timeout - now,
                )
            # the min() clamps above can go non-positive when a deadline
            # passes between checks; floor it so wait() never gets <=0
            # and the loop re-checks the policy branches promptly
            timeout = max(timeout, 0.01)
            await asyncio.wait([fut], timeout=timeout)

    async def _ensure_ready(self, deadline: Optional[float]) -> None:
        # _down_noted, not returncode: a crashed child's returncode stays
        # None until the event loop reaps it, but the reader task notes
        # the death the moment the pipe closes
        if self.proc is None or self._down_noted or self.proc.returncode is not None:
            if self._backoff.pending():
                delay = self._backoff.next()
                if deadline is not None and time.monotonic() + delay >= deadline:
                    raise EngineError(
                        "respawn backoff would outlast the chunk deadline"
                    )
                self.logger.warn(
                    f"Waiting {delay:.1f}s before respawning the engine host"
                )
                await asyncio.sleep(delay)
            await self._spawn()
        assert self._ready is not None
        if not self._ready.done():
            await self._watch(
                self._ready, deadline, kill_on_deadline=False, label="warmup"
            )
        else:
            self._ready.result()  # re-raise a recorded startup failure

    async def _spawn(self) -> None:
        env = dict(os.environ)
        env["PYTHONPATH"] = _PKG_PARENT + (
            os.pathsep + env["PYTHONPATH"] if env.get("PYTHONPATH") else ""
        )
        # engine-affecting FISHNET_TPU_* vars explicitly, so a future
        # sanitized-env spawn can't strand engine config on the parent
        # side (lint rule config-engine-wire keeps this line honest)
        env.update(settings.engine_env())
        if self.env:
            env.update({k: str(v) for k, v in self.env.items()})
        try:
            self.proc = await asyncio.create_subprocess_exec(
                *self.host_cmd,
                stdin=asyncio.subprocess.PIPE,
                stdout=asyncio.subprocess.PIPE,
                stderr=None,  # engine logs/tracebacks pass through
                # own process group: ^C at the client must not reach the
                # engine mid-chunk (same as engine/uci.py)
                start_new_session=True,
                env=env,
            )
        except OSError as e:
            self._down_noted = False
            self._note_down(f"spawn failed: {e}")
            raise EngineError(f"failed to spawn engine host: {e}") from e
        self.stats.spawns += 1
        self._down_noted = False
        self._last_frame = time.monotonic()
        self._phase = {}
        # fresh child, fresh monotonic epoch: the old offset is garbage
        self._clock = obs_trace.ClockSync()
        rec = obs_trace.RECORDER
        if rec is not None:
            rec.set_process_name("engine-host", pid=self.proc.pid)
            rec.instant("spawn", "supervisor", pid=self.proc.pid)
        ready = asyncio.get_running_loop().create_future()
        ready.add_done_callback(_consume_exc)
        self._ready = ready
        self._reader = asyncio.ensure_future(self._read_loop(self.proc, ready))

    async def _read_loop(self, proc, ready_fut) -> None:
        reason = "engine host exited"
        try:
            while True:
                try:
                    msg = await read_frame_async(proc.stdout)
                except PipeClosed:
                    rc = proc.returncode
                    if rc is not None and rc != 0:
                        reason = f"engine host exited with status {rc}"
                    break
                except FrameError as e:
                    self.stats.protocol_errors += 1
                    reason = f"corrupt frame: {e}"
                    await self._kill(reason)
                    break
                self._last_frame = time.monotonic()
                t = msg.get("t")
                if t == "hb":
                    self._phase = msg
                    mono = msg.get("mono")
                    if isinstance(mono, (int, float)):
                        # re-check the clock offset on every heartbeat;
                        # ClockSync keeps the min (= least pipe latency)
                        self._clock.sample(float(mono), self._last_frame)
                elif t == "ready":
                    mono = msg.get("mono")
                    if isinstance(mono, (int, float)):
                        # config-time estimate: first usable offset
                        self._clock.sample(float(mono), self._last_frame)
                    mesh_rep = msg.get("mesh")
                    if isinstance(mesh_rep, dict):
                        # pod members span devices on several processes;
                        # surface the topology next to the AOT report
                        self.mesh_report = mesh_rep
                    rep = msg.get("aot")
                    if isinstance(rep, dict):
                        # surfaced into fleet member health and logs: a
                        # replica that booted warm (AOT bundle) vs cold
                        self.aot_report = rep
                        if rep.get("enabled"):
                            self.logger.info(
                                f"engine host: AOT assets active — "
                                f"{rep.get('programs', 0)} programs "
                                f"(bundle {rep.get('fingerprint', '?')}, "
                                f"covers "
                                f"{','.join(rep.get('covers') or []) or 'none'})"
                            )
                    if not ready_fut.done():
                        ready_fut.set_result(True)
                elif t == "trace":
                    # merge the child's drained ring increment onto the
                    # parent timeline (host.py ships a hb frame carrying
                    # "mono" before any trace frame, so an offset exists
                    # by the time events arrive; 0.0 is a safe fallback
                    # for hosts that never sent one)
                    rec = obs_trace.RECORDER
                    if rec is not None:
                        off = self._clock.offset_us
                        rec.absorb(
                            msg.get("events") or (),
                            off if off is not None else 0.0,
                        )
                elif t in ("ok", "err"):
                    if self._pending is not None and self._pending[0] == msg.get("id"):
                        fut = self._pending[1]
                        if not fut.done():
                            fut.set_result(msg)
                elif t == "partial":
                    # journal one streamed position for the in-flight
                    # dispatch. Buffered partials are always drained
                    # before this coroutine's finally fails the pending
                    # future, so a post-crash harvest sees all of them.
                    fp = msg.get("fp")
                    wire = msg.get("response")
                    if (
                        self._pending is not None
                        and self._pending[0] == msg.get("id")
                        and isinstance(fp, str)
                        and isinstance(wire, dict)
                    ):
                        self._journal_record(
                            fp, wire,
                            ctx=obs_trace.ctx_from_wire(msg.get("ctx")),
                        )
                elif t == "log":
                    self.logger.info(f"engine host: {msg.get('msg', '')}")
        except asyncio.CancelledError:
            raise
        finally:
            err = EngineError(reason)
            if not ready_fut.done():
                ready_fut.set_exception(err)
            if self._pending is not None and not self._pending[1].done():
                self._pending[1].set_exception(err)
            self._note_down(reason)

    # ------------------------------------------------------- death handling

    async def _kill(self, reason: str, count: bool = True) -> None:
        proc = self.proc
        if proc is None or proc.returncode is not None:
            return
        if count:
            self.stats.kills += 1
            self.logger.warn(f"Killing engine host: {reason}")
            self._note_down(reason)
        try:
            proc.kill()
        except ProcessLookupError:
            pass
        try:
            await asyncio.wait_for(proc.wait(), timeout=10.0)
        except asyncio.TimeoutError:
            self.logger.error("Engine host ignored SIGKILL (unreapable?)")

    def _note_down(self, reason: str) -> None:
        """Record one involuntary child death (idempotent per incarnation).
        Deaths the recovery ladder will absorb stay invisible to the
        circuit breaker — the ladder records exactly one breaker-visible
        death via `_breaker_count` if it gives up."""
        if self._down_noted:
            return
        self._down_noted = True
        if self._closing:
            return  # voluntary shutdown, not a fault
        # flight recorder: every involuntary death — crash, hb stall,
        # deadline kill, progress stall — lands here exactly once per
        # incarnation, with the child's streamed spans already merged
        self._flight_dump("child-death", reason)
        self.stats.deaths += 1
        self._backoff.next()  # arm the respawn delay
        if self._ladder_active:
            self.logger.warn(f"Engine host down: {reason} (recovery ladder active)")
            return
        self._breaker_count(reason)

    def _flight_dump(self, slug: str, reason: str) -> None:
        """Dump the merged trace ring next to the journal
        (FISHNET_TPU_TRACE_DIR). Best-effort: forensics must never turn
        a recoverable death into an unrecoverable one."""
        rec = obs_trace.RECORDER
        if rec is None or not self._trace_dir:
            return
        rec.instant("flight-dump", "supervisor", reason=reason)
        try:
            path = rec.flight_dump(self._trace_dir, slug)
        except OSError as e:
            self.logger.warn(f"Flight-recorder dump failed: {e}")
        else:
            self.logger.warn(f"Flight recorder: trace dumped to {path}")

    def _breaker_count(self, reason: str) -> None:
        """One breaker-window death; trips the breaker on the Nth within
        the window."""
        now = time.monotonic()
        self._deaths.append(now)
        while self._deaths and now - self._deaths[0] > self.breaker_window:
            self._deaths.popleft()
        if not self._breaker_open and len(self._deaths) >= self.breaker_threshold:
            self._breaker_open = True
            self._flight_dump("breaker-trip", reason)
            self.stats.breaker_trips += 1
            self._next_probe = now + self.probe_interval
            self._deaths.clear()
            self.logger.error(
                f"Engine host died {self.breaker_threshold} times within "
                f"{self.breaker_window:.0f}s ({reason}); circuit breaker OPEN "
                "— degrading to the CPU fallback engine"
            )
        else:
            self.logger.warn(f"Engine host down: {reason}")

    # ------------------------------------------------------------- plumbing

    async def _send(self, obj: dict) -> None:
        proc = self.proc
        if proc is None or proc.stdin is None:
            raise EngineError("engine host is not running")
        try:
            proc.stdin.write(encode(obj))
            await proc.stdin.drain()
        except (BrokenPipeError, ConnectionResetError, OSError) as e:
            raise EngineError(f"engine host pipe write failed: {e}") from e
