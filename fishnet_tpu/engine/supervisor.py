"""Process-isolated engine supervisor: proxy + watchdog + circuit breaker.

The reference client's core robustness invariant is that an engine is
always killable: the per-core worker races each chunk against its
deadline and kills/respawns the Stockfish *subprocess* on overrun
(reference src/main.rs:263-390). The in-process TPU engine broke that
invariant — a wedged device leaves a zombie executor thread holding the
engine lock forever (docs/tpu-hang.md). `SupervisedEngine` restores it
by hosting the engine in a child process (engine/host.py) behind the
`Engine` protocol:

- **Phase heartbeats** (engine/frames.py protocol) prove the child is
  alive; the watchdog hard-kills it when the stream stalls for
  `hb_timeout`, or when an in-flight chunk overruns its deadline (the
  device-hang signature: heartbeats flow, the search phase never ends).
- **Respawn** is gated by `RandomizedBackoff` (reset on the first
  successful chunk) and re-runs the child's warmup, whose long XLA
  compiles are covered by warmup-phase heartbeats rather than a fixed
  timeout.
- **Circuit breaker**: after `breaker_threshold` child deaths within
  `breaker_window` seconds, the flavor degrades to the pure-Python CPU
  engine (engine/pyengine.py) so the client keeps acquiring and
  submitting work while the device is wedged. Every `probe_interval`
  seconds one chunk probes the child path; a successful probe restores
  it.

Fault paths are exercised deterministically by pointing `host_cmd` at
the scriptable fake host (engine/fakehost.py); tests/test_supervisor.py
covers every branch on CPU, and tools/chaos.py replays the same scripts
interactively.
"""
from __future__ import annotations

import asyncio
import os
import sys
import time
from collections import deque
from dataclasses import dataclass
from pathlib import Path
from typing import Deque, List, Optional

from ..client.backoff import RandomizedBackoff
from ..client.ipc import Chunk, PositionResponse, chunk_to_wire, responses_from_wire
from ..client.logger import Logger
from ..utils import settings
from .base import EngineError
from .frames import FrameError, PipeClosed, encode, read_frame_async

# the child must be able to `import fishnet_tpu` no matter where the
# parent was launched from
_PKG_PARENT = str(Path(__file__).resolve().parents[2])


def default_host_cmd(
    backend: str = "tpu",
    weights: Optional[str] = None,
    depth: Optional[int] = None,
    hb_interval: float = 1.0,
    helpers: Optional[int] = None,
    refill: Optional[bool] = None,
) -> List[str]:
    cmd = [
        sys.executable, "-m", "fishnet_tpu.engine.host",
        "--backend", backend, "--hb-interval", str(hb_interval),
    ]
    if weights:
        cmd += ["--weights", str(weights)]
    if depth is not None:
        cmd += ["--depth", str(depth)]
    if helpers is not None:
        # Lazy-SMP lane groups (engine/tpu.py helper_lanes); 1 disables
        cmd += ["--helpers", str(helpers)]
    if refill is not None:
        # continuous lane refill (engine/tpu.py LaneScheduler); 0 disables
        cmd += ["--refill", "1" if refill else "0"]
    return cmd


@dataclass
class SupervisorStats:
    """Plain counters; introspected by tests and tools/chaos.py."""

    spawns: int = 0
    deaths: int = 0  # involuntary child exits + supervisor kills
    kills: int = 0
    hb_stalls: int = 0
    deadline_kills: int = 0
    protocol_errors: int = 0
    breaker_trips: int = 0
    breaker_resets: int = 0
    probes: int = 0
    fallback_chunks: int = 0
    chunks_ok: int = 0


def _consume_exc(fut: asyncio.Future) -> None:
    # futures may be resolved with an exception after their awaiter gave
    # up (kill races); retrieve it so asyncio doesn't log "never retrieved"
    if not fut.cancelled():
        fut.exception()


class SupervisedEngine:
    """`Engine`-protocol proxy to a child engine host.

    Reusable after `close()` (the worker's drop-and-respawn pattern
    closes the engine on any error and asks the factory again — the
    factory caches this object, so breaker state survives the drop)."""

    def __init__(
        self,
        host_cmd: Optional[List[str]] = None,
        *,
        backend: str = "tpu",
        weights_path: Optional[str] = None,
        max_depth: Optional[int] = None,
        helper_lanes: Optional[int] = None,
        refill: Optional[bool] = None,
        logger: Optional[Logger] = None,
        hb_interval: float = 1.0,
        hb_timeout: Optional[float] = None,
        deadline_margin: float = 0.15,
        breaker_threshold: int = 3,
        breaker_window: float = 600.0,
        probe_interval: float = 60.0,
        fallback_factory=None,
        backoff: Optional[RandomizedBackoff] = None,
        env: Optional[dict] = None,
    ) -> None:
        self.host_cmd = host_cmd or default_host_cmd(
            backend=backend, weights=weights_path, depth=max_depth,
            hb_interval=hb_interval, helpers=helper_lanes, refill=refill,
        )
        self.logger = logger or Logger()
        self.hb_interval = hb_interval
        # N missed beats = dead, not slow: generous enough for scheduler
        # jitter, far under any chunk deadline
        self.hb_timeout = hb_timeout if hb_timeout is not None else 8 * hb_interval
        self.deadline_margin = deadline_margin
        self.breaker_threshold = breaker_threshold
        self.breaker_window = breaker_window
        self.probe_interval = probe_interval
        self.fallback_factory = fallback_factory
        self.env = env
        self.stats = SupervisorStats()

        self._lock = asyncio.Lock()  # one in-flight chunk, like TpuEngine
        self._backoff = backoff or RandomizedBackoff()
        self.proc: Optional[asyncio.subprocess.Process] = None
        self._reader: Optional[asyncio.Task] = None
        self._ready: Optional[asyncio.Future] = None
        self._pending = None  # (go id, future) for the in-flight chunk
        self._last_frame = 0.0
        self._phase: dict = {}
        self._down_noted = True  # no live child yet
        self._closing = False
        self._go_id = 0
        self._deaths: Deque[float] = deque()
        self._breaker_open = False
        self._next_probe = 0.0
        self._fallback = None

    # ------------------------------------------------------------ lifecycle

    async def start(self) -> None:
        """Spawn the child and wait for warmup (heartbeat-governed, no
        fixed timeout — XLA compiles run minutes with phase=warmup beats).
        Called by app startup; `go_multiple` also self-heals lazily."""
        async with self._lock:
            await self._ensure_ready(None)

    async def close(self) -> None:
        self._closing = True
        try:
            proc = self.proc
            if proc is not None and proc.returncode is None:
                try:
                    await self._send({"t": "quit"})
                    await asyncio.wait_for(proc.wait(), timeout=2.0)
                except (EngineError, asyncio.TimeoutError):
                    await self._kill("shutdown", count=False)
            if self._reader is not None:
                self._reader.cancel()
                await asyncio.gather(self._reader, return_exceptions=True)
            if self._fallback is not None:
                fallback, self._fallback = self._fallback, None
                await fallback.close()
        finally:
            self.proc = None
            self._reader = None
            self._ready = None
            self._pending = None
            self._down_noted = True
            self._closing = False

    # ------------------------------------------------------------- dispatch

    async def go_multiple(self, chunk: Chunk) -> List[PositionResponse]:
        async with self._lock:
            if self._breaker_open:
                if time.monotonic() >= self._next_probe:
                    self.stats.probes += 1
                    self.logger.info(
                        "Circuit breaker: probing the supervised engine path"
                    )
                    try:
                        responses = await self._go_child(chunk)
                    except EngineError as e:
                        self._next_probe = time.monotonic() + self.probe_interval
                        self.logger.warn(
                            f"Probe failed ({e}); staying on CPU fallback"
                        )
                        return await self._go_fallback(chunk)
                    self._breaker_open = False
                    self.stats.breaker_resets += 1
                    self.logger.headline(
                        "Circuit breaker CLOSED: supervised engine recovered"
                    )
                    return responses
                return await self._go_fallback(chunk)
            try:
                return await self._go_child(chunk)
            except EngineError:
                if self._breaker_open and time.monotonic() < chunk.deadline:
                    # this very death tripped the breaker: salvage the
                    # chunk on the fallback instead of failing it
                    return await self._go_fallback(chunk)
                raise

    async def _go_fallback(self, chunk: Chunk) -> List[PositionResponse]:
        if self._fallback is None:
            if self.fallback_factory is not None:
                self._fallback = self.fallback_factory()
            else:
                from .pyengine import PyEngine

                self._fallback = PyEngine()
        self.stats.fallback_chunks += 1
        try:
            return await self._fallback.go_multiple(chunk)
        except EngineError:
            raise
        except Exception as e:
            raise EngineError(f"fallback engine failed: {e}") from e

    async def _go_child(self, chunk: Chunk) -> List[PositionResponse]:
        deadline = chunk.deadline - self.deadline_margin
        await self._ensure_ready(deadline)
        self._go_id += 1
        gid = self._go_id
        fut = asyncio.get_running_loop().create_future()
        fut.add_done_callback(_consume_exc)
        self._pending = (gid, fut)
        try:
            await self._send({"t": "go", "id": gid, "chunk": chunk_to_wire(chunk)})
            reply = await self._watch(
                fut, deadline, kill_on_deadline=True,
                label=f"chunk of batch {chunk.work.id}",
            )
        finally:
            self._pending = None
        if reply.get("t") == "err":
            # the child handled the failure itself and is still sane
            raise EngineError(f"engine host: {reply.get('error')}")
        try:
            responses = responses_from_wire(chunk.work, reply["responses"])
        except (KeyError, TypeError, ValueError) as e:
            self.stats.protocol_errors += 1
            await self._kill(f"malformed ok frame: {e}")
            raise EngineError(f"engine host sent a malformed result: {e}") from e
        self._deaths.clear()
        self._backoff.reset()
        self.stats.chunks_ok += 1
        return responses

    # ------------------------------------------------------------- watchdog

    async def _watch(self, fut, deadline, kill_on_deadline: bool, label: str):
        """Await `fut` under watchdog policy: kill on heartbeat stall
        (always) or deadline overrun (chunks: yes; warmup: give up but
        let the child keep compiling for the next chunk)."""
        while True:
            if fut.done():
                return fut.result()  # raises EngineError if the child died
            now = time.monotonic()
            hb_age = now - self._last_frame
            if hb_age > self.hb_timeout:
                self.stats.hb_stalls += 1
                await self._kill(
                    f"missed heartbeats for {hb_age:.1f}s during {label}"
                )
                raise EngineError(
                    f"engine host missed heartbeats during {label}"
                )
            if deadline is not None and now >= deadline:
                if kill_on_deadline:
                    self.stats.deadline_kills += 1
                    phase = self._phase.get("phase", "?")
                    await self._kill(
                        f"{label} overran its deadline (phase={phase})"
                    )
                    raise EngineError(f"{label} overran its deadline")
                raise EngineError(f"engine host not ready in time for {label}")
            timeout = max(self.hb_timeout - hb_age, self.hb_interval / 4)
            if deadline is not None:
                timeout = min(timeout, deadline - now)
            await asyncio.wait([fut], timeout=max(timeout, 0.01))

    async def _ensure_ready(self, deadline: Optional[float]) -> None:
        # _down_noted, not returncode: a crashed child's returncode stays
        # None until the event loop reaps it, but the reader task notes
        # the death the moment the pipe closes
        if self.proc is None or self._down_noted or self.proc.returncode is not None:
            if self._backoff.pending():
                delay = self._backoff.next()
                if deadline is not None and time.monotonic() + delay >= deadline:
                    raise EngineError(
                        "respawn backoff would outlast the chunk deadline"
                    )
                self.logger.warn(
                    f"Waiting {delay:.1f}s before respawning the engine host"
                )
                await asyncio.sleep(delay)
            await self._spawn()
        assert self._ready is not None
        if not self._ready.done():
            await self._watch(
                self._ready, deadline, kill_on_deadline=False, label="warmup"
            )
        else:
            self._ready.result()  # re-raise a recorded startup failure

    async def _spawn(self) -> None:
        env = dict(os.environ)
        env["PYTHONPATH"] = _PKG_PARENT + (
            os.pathsep + env["PYTHONPATH"] if env.get("PYTHONPATH") else ""
        )
        # engine-affecting FISHNET_TPU_* vars explicitly, so a future
        # sanitized-env spawn can't strand engine config on the parent
        # side (lint rule config-engine-wire keeps this line honest)
        env.update(settings.engine_env())
        if self.env:
            env.update({k: str(v) for k, v in self.env.items()})
        try:
            self.proc = await asyncio.create_subprocess_exec(
                *self.host_cmd,
                stdin=asyncio.subprocess.PIPE,
                stdout=asyncio.subprocess.PIPE,
                stderr=None,  # engine logs/tracebacks pass through
                # own process group: ^C at the client must not reach the
                # engine mid-chunk (same as engine/uci.py)
                start_new_session=True,
                env=env,
            )
        except OSError as e:
            self._down_noted = False
            self._note_down(f"spawn failed: {e}")
            raise EngineError(f"failed to spawn engine host: {e}") from e
        self.stats.spawns += 1
        self._down_noted = False
        self._last_frame = time.monotonic()
        self._phase = {}
        ready = asyncio.get_running_loop().create_future()
        ready.add_done_callback(_consume_exc)
        self._ready = ready
        self._reader = asyncio.ensure_future(self._read_loop(self.proc, ready))

    async def _read_loop(self, proc, ready_fut) -> None:
        reason = "engine host exited"
        try:
            while True:
                try:
                    msg = await read_frame_async(proc.stdout)
                except PipeClosed:
                    rc = proc.returncode
                    if rc is not None and rc != 0:
                        reason = f"engine host exited with status {rc}"
                    break
                except FrameError as e:
                    self.stats.protocol_errors += 1
                    reason = f"corrupt frame: {e}"
                    await self._kill(reason)
                    break
                self._last_frame = time.monotonic()
                t = msg.get("t")
                if t == "hb":
                    self._phase = msg
                elif t == "ready":
                    if not ready_fut.done():
                        ready_fut.set_result(True)
                elif t in ("ok", "err"):
                    if self._pending is not None and self._pending[0] == msg.get("id"):
                        fut = self._pending[1]
                        if not fut.done():
                            fut.set_result(msg)
                elif t == "log":
                    self.logger.info(f"engine host: {msg.get('msg', '')}")
        except asyncio.CancelledError:
            raise
        finally:
            err = EngineError(reason)
            if not ready_fut.done():
                ready_fut.set_exception(err)
            if self._pending is not None and not self._pending[1].done():
                self._pending[1].set_exception(err)
            self._note_down(reason)

    # ------------------------------------------------------- death handling

    async def _kill(self, reason: str, count: bool = True) -> None:
        proc = self.proc
        if proc is None or proc.returncode is not None:
            return
        if count:
            self.stats.kills += 1
            self.logger.warn(f"Killing engine host: {reason}")
            self._note_down(reason)
        try:
            proc.kill()
        except ProcessLookupError:
            pass
        try:
            await asyncio.wait_for(proc.wait(), timeout=10.0)
        except asyncio.TimeoutError:
            self.logger.error("Engine host ignored SIGKILL (unreapable?)")

    def _note_down(self, reason: str) -> None:
        """Record one involuntary child death (idempotent per incarnation)
        and trip the circuit breaker on the Nth within the window."""
        if self._down_noted:
            return
        self._down_noted = True
        if self._closing:
            return  # voluntary shutdown, not a fault
        self.stats.deaths += 1
        self._backoff.next()  # arm the respawn delay
        now = time.monotonic()
        self._deaths.append(now)
        while self._deaths and now - self._deaths[0] > self.breaker_window:
            self._deaths.popleft()
        if not self._breaker_open and len(self._deaths) >= self.breaker_threshold:
            self._breaker_open = True
            self.stats.breaker_trips += 1
            self._next_probe = now + self.probe_interval
            self._deaths.clear()
            self.logger.error(
                f"Engine host died {self.breaker_threshold} times within "
                f"{self.breaker_window:.0f}s ({reason}); circuit breaker OPEN "
                "— degrading to the CPU fallback engine"
            )
        else:
            self.logger.warn(f"Engine host down: {reason}")

    # ------------------------------------------------------------- plumbing

    async def _send(self, obj: dict) -> None:
        proc = self.proc
        if proc is None or proc.stdin is None:
            raise EngineError("engine host is not running")
        try:
            proc.stdin.write(encode(obj))
            await proc.stdin.drain()
        except (BrokenPipeError, ConnectionResetError, OSError) as e:
            raise EngineError(f"engine host pipe write failed: {e}") from e
