"""Engine interface: chunk batches and position-level sessions.

The reference keeps Stockfish subprocesses behind exactly the
`go_multiple` shape (reference: src/stockfish.rs:36-48
`StockfishStub::go_multiple`); here it is the seam between the client
framework and the backends (TPU batch engine, UCI subprocess,
pure-Python fallback, supervised child host).

Since the serving round the protocol also carries `submit()`: one
position with its own deadline and priority, answered by one
PositionResponse (engine/session.py `PositionRequest`). Frontends that
hold positions rather than fishnet chunks — the HTTP server
(fishnet_tpu/serve/), bench closed-loop clients — speak this surface;
backends conform via the `ChunkSubmit` mixin (engine/session.py), which
wraps a request as a one-position chunk, so every backend that can run
a chunk can serve position traffic too.
"""
from __future__ import annotations

from typing import TYPE_CHECKING, List, Protocol

from ..client.ipc import Chunk, PositionResponse

if TYPE_CHECKING:  # circular at runtime: session.py builds Chunks
    from .session import PositionRequest


class EngineError(Exception):
    """Engine died or misbehaved; the worker drops and respawns it with
    backoff (reference: src/main.rs:330-336)."""


class Engine(Protocol):
    async def go_multiple(self, chunk: Chunk) -> List[PositionResponse]:
        """Analyse every position of the chunk, in order."""
        ...

    async def submit(self, request: "PositionRequest") -> PositionResponse:
        """Analyse one position-level request (engine/session.py); the
        deadline/priority ride the request instead of a chunk."""
        ...

    async def close(self) -> None:
        ...


class EngineFactory(Protocol):
    def __call__(self, flavor) -> Engine:
        ...
