"""Engine interface: everything behind `go_multiple(Chunk)`.

The reference keeps Stockfish subprocesses behind exactly this shape
(reference: src/stockfish.rs:36-48 `StockfishStub::go_multiple`); here it is
the seam between the client framework and the three backends (TPU batch
engine, UCI subprocess, pure-Python fallback).
"""
from __future__ import annotations

from typing import List, Protocol

from ..client.ipc import Chunk, PositionResponse


class EngineError(Exception):
    """Engine died or misbehaved; the worker drops and respawns it with
    backoff (reference: src/main.rs:330-336)."""


class Engine(Protocol):
    async def go_multiple(self, chunk: Chunk) -> List[PositionResponse]:
        """Analyse every position of the chunk, in order."""
        ...

    async def close(self) -> None:
        ...


class EngineFactory(Protocol):
    def __call__(self, flavor) -> Engine:
        ...
