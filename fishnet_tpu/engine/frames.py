"""Length-framed JSON messages for the supervisor↔host pipe protocol.

One frame = 4-byte big-endian payload length + UTF-8 JSON object. JSON
(not pickle) so a corrupt or adversarial child can at worst produce a
`FrameError`, never code execution in the parent; the length prefix is
bounded so a garbage header can't trigger an unbounded read.

Used on both sides of the pipe: synchronous helpers for the child host
(blocking stdio) and an asyncio helper for the parent supervisor.
Incremental `partial` frames (one position's response each, for the
supervisor's session journal) are single-position and sit far under
MAX_FRAME_BYTES by construction; they optionally carry the position's
request context (`ctx`, obs/trace.py CTX_KEYS) so a trace survives a
mid-chunk kill through the journal.
"""
from __future__ import annotations

import json
import struct
from typing import BinaryIO

HEADER = struct.Struct(">I")
# analysis replies carry ≤6 positions of multipv×depth matrices — even a
# pathological frame is far under this; anything bigger is corruption
MAX_FRAME_BYTES = 8 * 1024 * 1024


class FrameError(Exception):
    """Framing-level corruption: bad length, truncated payload, or
    undecodable JSON. The peer process can no longer be trusted and must
    be killed (supervisor) or exit (host)."""


class PipeClosed(Exception):
    """Clean EOF between frames: the peer went away."""


def encode(obj: dict) -> bytes:
    payload = json.dumps(obj, separators=(",", ":")).encode("utf-8")
    if len(payload) > MAX_FRAME_BYTES:
        raise FrameError(f"frame too large: {len(payload)} bytes")
    return HEADER.pack(len(payload)) + payload


def _decode_payload(payload: bytes) -> dict:
    try:
        obj = json.loads(payload.decode("utf-8"))
    except (UnicodeDecodeError, json.JSONDecodeError) as e:
        raise FrameError(f"undecodable frame: {e}") from e
    if not isinstance(obj, dict):
        raise FrameError(f"frame is not an object: {type(obj).__name__}")
    return obj


def write_frame(fp: BinaryIO, obj: dict) -> None:
    """Child-side blocking write (caller holds any needed lock)."""
    fp.write(encode(obj))
    fp.flush()


def _read_exact(fp: BinaryIO, n: int, *, at_boundary: bool) -> bytes:
    buf = b""
    while len(buf) < n:
        part = fp.read(n - len(buf))
        if not part:
            if at_boundary and not buf:
                raise PipeClosed()
            raise FrameError("truncated frame")
        buf += part
    return buf


def read_frame(fp: BinaryIO) -> dict:
    """Child-side blocking read. Raises PipeClosed on clean EOF."""
    header = _read_exact(fp, HEADER.size, at_boundary=True)
    (length,) = HEADER.unpack(header)
    if length > MAX_FRAME_BYTES:
        raise FrameError(f"frame length {length} exceeds cap")
    return _decode_payload(_read_exact(fp, length, at_boundary=False))


async def read_frame_async(reader) -> dict:
    """Parent-side read from an asyncio StreamReader. Raises PipeClosed
    on clean EOF at a frame boundary, FrameError on corruption."""
    import asyncio

    try:
        header = await reader.readexactly(HEADER.size)
    except asyncio.IncompleteReadError as e:
        if not e.partial:
            raise PipeClosed() from e
        raise FrameError("truncated frame header") from e
    (length,) = HEADER.unpack(header)
    if length > MAX_FRAME_BYTES:
        raise FrameError(f"frame length {length} exceeds cap")
    try:
        payload = await reader.readexactly(length)
    except asyncio.IncompleteReadError as e:
        raise FrameError("truncated frame payload") from e
    return _decode_payload(payload)
