"""Engine host: the child-process side of the supervised engine.

Runs ONE engine (TPU by default) behind the length-framed pipe protocol
(engine/frames.py) so the parent supervisor can hard-kill it when the
device wedges — restoring the reference's "an engine is always killable"
invariant (reference src/main.rs:263-390) that an in-process JAX dispatch
breaks (a blocked device call keeps its executor thread, the engine lock,
and the device forever; docs/tpu-hang.md).

Protocol (all frames are JSON objects with a "t" tag):

  child → parent
    hb     {phase, busy_s, seq}   ticker thread, every --hb-interval
    ready  {}                     warmup finished; chunks may be sent
    log    {msg}                  relayed to the parent's logger
    partial {id, fp, response,    one finished position, streamed as the
             ctx?}                engine's exactly-once delivery hook
                                  fires (feeds the supervisor's session
                                  journal; fp = client/ipc.py fingerprint;
                                  ctx = the position's request context
                                  when it rode the chunk wire)
    ok     {id, responses}        chunk result (client/ipc.py wire form)
    err    {id, error}            chunk failed but the host is still sane
  parent → child
    go     {id, chunk}            analyse one chunk
    quit   {}                     clean shutdown

Liveness contract: the ticker thread keeps beating through a blocked
device dispatch (JAX releases the GIL), so a silent heartbeat stream
means the process is frozen or dead — the supervisor kills on that. A
flowing stream with phase=search busy past the chunk deadline is the
device-hang signature — the supervisor kills on that too. Warmup is
allowed to run long (minutes of XLA compiles) exactly because its
heartbeats keep flowing with phase=warmup.

Run as:  python -m fishnet_tpu.engine.host --backend tpu|py [...]
"""
from __future__ import annotations

import argparse
import asyncio
import os
import sys
import threading
import time

from ..client.ipc import chunk_from_wire, position_fingerprint, response_to_wire
from ..obs import trace
from ..utils.heartbeat import PhaseTracker
from .frames import FrameError, PipeClosed, read_frame, write_frame


def _build_engine(args, log):
    if args.backend == "py":
        from .pyengine import PyEngine

        return PyEngine(max_depth=args.depth or 3)
    from .tpu import TpuEngine

    engine = TpuEngine(
        weights_path=args.weights or None,
        max_depth=args.depth or 12,
        helper_lanes=args.helpers,
        refill=None if args.refill is None else bool(args.refill),
        mesh_refill=(None if args.mesh_refill is None
                     else bool(args.mesh_refill)),
    )
    if not args.skip_warmup:
        from ..aot import registry as aot_registry

        engine.warmup(None, log)
        if aot_registry.warm_covers("variants"):
            # every variant program is preloaded from the AOT bundle —
            # spinning the compile thread anyway would silently paper
            # over bundle misses (the aot smoke asserts it stays quiet)
            log("warmup: variant programs preloaded from AOT bundle; "
                "background compile thread skipped")
        else:
            # variant programs compile in the background, same as the old
            # in-process wiring (client/app.py round 5) — chunks interleave
            # behind the engine lock while the remaining shapes warm
            threading.Thread(
                target=lambda: engine.warmup_variants(log), daemon=True
            ).start()
    return engine


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="fishnet-tpu-engine-host")
    p.add_argument("--backend", choices=["tpu", "py"], default="tpu")
    p.add_argument("--weights", default=None)
    p.add_argument("--depth", type=int, default=None)
    # Lazy-SMP lanes per analysed position (engine/tpu.py helper_lanes);
    # None defers to FISHNET_TPU_HELPERS / the engine default, 1 disables
    p.add_argument("--helpers", type=int, default=None)
    # continuous lane refill (engine/tpu.py LaneScheduler); None defers
    # to FISHNET_TPU_REFILL / the engine default, 0 disables
    p.add_argument("--refill", type=int, default=None)
    # shard-aware refill on multi-chip hosts (parallel/mesh.py sharded
    # callables); None defers to FISHNET_TPU_MESH_REFILL, 0 pins meshed
    # engines back to chunk-serial dispatch
    p.add_argument("--mesh-refill", type=int, default=None)
    # stream per-position `partial` frames for the supervisor's session
    # journal (engine/supervisor.py recovery ladder); 0 disables
    p.add_argument("--partials", type=int, default=1)
    p.add_argument("--hb-interval", type=float, default=1.0)
    p.add_argument("--skip-warmup", action="store_true")
    args = p.parse_args(argv)

    stdin = sys.stdin.buffer
    stdout = sys.stdout.buffer
    # anything the engine prints must not corrupt the frame stream
    sys.stdout = sys.stderr

    wlock = threading.Lock()
    phases = PhaseTracker("boot")
    # the host records its own ring (FISHNET_TPU_TRACE_DIR is forwarded
    # by the supervisor's engine_env overlay); the ticker streams
    # increments to the parent, which owns the merged timeline — a
    # SIGKILL'd child loses nothing that already crossed the pipe
    recorder = trace.install_from_settings("engine-host")
    if recorder is not None:
        recorder.set_thread_name("host-main")

    def send(obj: dict) -> None:
        with wlock:
            write_frame(stdout, obj)

    def log(msg) -> None:
        try:
            send({"t": "log", "msg": str(msg)})
        except OSError:
            pass

    stop = threading.Event()

    def send_trace() -> None:
        """Drain the ring into trace frames (batched well under the
        8 MiB frame cap)."""
        if recorder is None:
            return
        events = recorder.drain()
        while events:
            batch, events = events[:2000], events[2000:]
            send({"t": "trace", "events": batch})

    def ticker() -> None:
        while not stop.wait(args.hb_interval):
            snap = phases.snapshot()
            snap["t"] = "hb"
            # child monotonic reading: the supervisor's ClockSync pairs
            # it with its own receive time to map our timestamps onto
            # the parent timeline (re-checked every heartbeat)
            snap["mono"] = time.monotonic()
            try:
                send(snap)
                send_trace()
            except OSError:
                os._exit(1)  # parent gone; nothing left to serve

    threading.Thread(target=ticker, daemon=True).start()

    phases.enter("warmup")
    try:
        with trace.span("warmup", "host"):
            engine = _build_engine(args, log)
    except Exception as e:
        log(f"engine construction/warmup failed: {type(e).__name__}: {e}")
        return 1
    # the ready frame carries the AOT boot report so the supervisor can
    # log (and the fleet surface) whether this replica booted warm, plus
    # the mesh topology (parallel/partition.py) so a pod: fleet member's
    # health surfaces how many devices/processes its one logical engine
    # actually spans
    from ..aot import registry as aot_registry
    from ..parallel.partition import default_topology

    send({
        "t": "ready", "mono": time.monotonic(),
        "aot": aot_registry.boot_report(),
        "mesh": default_topology(),
    })
    phases.enter("idle")

    # stream each finished position the moment the engine's exactly-once
    # delivery hook fires (engine/tpu.py LaneScheduler._deliver), tagged
    # with the in-flight go id so the supervisor can journal it
    cur = {"id": None}

    def emit_partial(wp, res) -> None:
        try:
            frame = {
                "t": "partial",
                "id": cur["id"],
                "fp": position_fingerprint(wp),
                "response": response_to_wire(res),
            }
            # request context rides the partial so the supervisor's
            # journal (and a replay after a mid-chunk kill) can keep
            # the position pinned to its originating trace
            if wp.ctx:
                frame["ctx"] = wp.ctx
            send(frame)
        except OSError:
            pass  # parent gone mid-stream; the ticker exits for us

    if args.partials and hasattr(engine, "on_response"):
        engine.on_response = emit_partial

    while True:
        try:
            msg = read_frame(stdin)
        except PipeClosed:
            break
        except FrameError as e:
            log(f"protocol error from supervisor: {e}")
            return 2
        t = msg.get("t")
        if t == "quit":
            break
        if t != "go":
            log(f"ignoring unknown frame type {t!r}")
            continue
        chunk = chunk_from_wire(msg["chunk"])
        cur["id"] = msg.get("id")
        phases.enter("search")
        # sampled request contexts riding the chunk link this child's
        # search span into each request's causal chain (flow id =
        # trace_id, same as every other hop)
        tids = sorted({
            wp.ctx["trace_id"] for wp in chunk.positions
            if wp.ctx and wp.ctx.get("trace_id")
        })
        tids = [t for t in tids if trace.sampled(t)]
        try:
            with trace.span("search", "host", id=msg.get("id"),
                            positions=len(chunk.positions),
                            trace_ids=tids):
                if recorder is not None:
                    for t_id in tids:
                        recorder.flow("request", t_id, "t")
                responses = asyncio.run(engine.go_multiple(chunk))
        except Exception as e:
            send({
                "t": "err",
                "id": msg.get("id"),
                "error": f"{type(e).__name__}: {e}",
            })
        else:
            send({
                "t": "ok",
                "id": msg.get("id"),
                "responses": [response_to_wire(r) for r in responses],
            })
        phases.enter("idle")

    try:
        asyncio.run(engine.close())
    except Exception as e:
        log(f"engine close failed: {type(e).__name__}: {e}")
    try:
        send_trace()  # final flush: a clean quit ships the tail too
    except OSError:
        pass
    return 0


if __name__ == "__main__":
    sys.exit(main())
