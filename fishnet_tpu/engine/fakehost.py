"""Scriptable fake engine host: deterministic fault injection.

Speaks the exact supervisor↔host protocol of engine/host.py but executes
a *fault script* instead of a real engine, so every supervisor path —
heartbeat-stall kill, deadline kill, crash respawn, corrupt-frame kill,
circuit-breaker trip and probe recovery — is exercisable in tier-1 on
CPU with no JAX import at all. tools/chaos.py replays the same scripts
against a live supervisor for manual soak testing.

A script is a JSON object:

    {"boot":   ["ready", "crash:3", "stall", "slow:2.0", ...],
     "chunks": ["ok", "hang", "stall", "crash:9", "corrupt",
                "slow:1.5", "err", "ok:333", ...]}

`boot[i]` is the startup behavior of the i-th host incarnation;
`chunks[j]` the behavior for the j-th chunk EVER dispatched (counted
across respawns). Position-level `submit()` traffic (engine/session.py)
reaches a fakehost child the same way chunks do: SupervisedEngine's
ChunkSubmit conformance wraps the request as a one-position chunk and
ships it over this pipe protocol, so serve-layer tests can script the
fake host behind the HTTP front-end too. Lists are extended by repeating their last entry. The
cross-incarnation counters persist in --state (a JSON file) — without
it, every respawn would replay the script from the top and a
crash-then-recover sequence could never be expressed.

Actions:
    ready       boot only: warm up instantly and send ready
    ok[:CP]     reply with a depth-1 response per position, score cp CP
                (default 777 — a signature tests use to tell the fake
                host's responses from the CPU fallback engine's)
    slow:S      sleep S seconds (heartbeats continue), then ok
    slow-after:K[:S]  chunks 0..K-1 answer instantly, every later chunk
                sleeps S seconds (default 1.0) first — a member that
                *becomes* a straggler, for load-balancing tests (the
                chunk counter persists in --state, so the K-th chunk is
                counted across respawns like everything else)
    hang        keep heartbeating, never reply — killed at the deadline
    stall       stop ALL output and sleep forever — killed by the
                heartbeat watchdog
    crash:N     exit immediately with status N
    corrupt     write garbage bytes into the frame stream
    err         reply with an err frame (host stays alive)

Session-recovery actions (round 9) — these stream `partial` frames so
the supervisor's journal/replay/bisect/quarantine ladder is exercisable
deterministically (tests/test_recovery.py, tools/chaos.py --scenario):

    partial-ok[:CP]  a partial frame per position, then ok
    dup-partial      every partial sent twice (exactly-once check), then ok
    die-after:N      N partials, then exit 9 (kill-after-k-partials)
    stall-at:N       N partials, then stop ALL output (watchdog kill)
    hang-at:N        N partials, then heartbeat-only silence — killed at
                     the deadline, or earlier by progress_timeout
    crash-on-fp:P    stream partials per position in order, but exit 9 on
                     the position whose fingerprint starts with P — the
                     deterministic poison position the ladder must isolate

The `--echo PATH` flag appends one JSON line per boot ({"t":"boot",
argv, FISHNET_TPU_* env}) and per chunk ({"t":"go", positions, fps}) so
tests can assert the respawned child re-received the full engine config
and exactly which positions each incarnation was asked to search.
Engine-config flags of the real host (--backend/--weights/--depth/
--helpers/--refill/--partials/--hb-interval) are accepted and echoed,
never interpreted.

`--latency-ms N` adds a fixed N-millisecond service delay to EVERY
chunk before its scripted action runs (heartbeats continue). Unlike the
one-shot `slow:S` action this models a member's steady-state speed, so
fleet load-balancing and scaling tests (tests/test_fleet.py, bench.py
fleet_scaling) can build deterministically asymmetric members.
`--jitter-ms N` layers uniform [0, N] ms of per-chunk jitter on top —
service-time VARIANCE rather than speed — drawn from a RNG seeded by
(--jitter-seed, chunk index), so the delay sequence is reproducible
across runs and across respawns of the same member (an incarnation
resuming at chunk k sleeps exactly what the dead one would have).

`FlakyProxy` (in-process, asyncio) is the NETWORK counterpart of the
fault scripts: a TCP shim between a remote fleet member (HttpEngine)
and its serve endpoint that injects connection-level faults —
`refuse-for:S` (listener closed for S seconds: real ECONNREFUSED, the
transient fault fleet/faults.py retries in-dispatch), `reset-after-
headers` (RST after the request head: a mid-stream loss), and
`delay:MS` (added connect latency). tools/chaos.py --scenario
fleet-flap drives it.
"""
from __future__ import annotations

import argparse
import asyncio
import json
import os
import random
import socket as _socket
import struct
import sys
import threading
import time
from typing import Optional, Tuple

from ..client.ipc import wire_position_fingerprint
from .frames import FrameError, PipeClosed, read_frame, write_frame

FAKE_CP = 777  # default signature score for "ok" responses

NAMED_SCRIPTS = {
    # one-fault scripts, then recovered: the canonical chaos menu
    "ok": {"chunks": ["ok"]},
    "hang": {"chunks": ["hang", "ok"]},
    "stall": {"chunks": ["stall", "ok"]},
    "crash": {"chunks": ["crash:9", "ok"]},
    "corrupt": {"chunks": ["corrupt", "ok"]},
    "slow": {"chunks": ["slow:2.0", "ok"]},
    "err": {"chunks": ["err", "ok"]},
    # dies repeatedly, then recovers — trips a small-threshold breaker
    # and lets a later probe restore the primary path
    "flap": {"chunks": ["crash:9", "crash:9", "crash:9", "ok"]},
    # boot-time faults: warmup that never heartbeats / dies / crawls
    "boot-stall": {"boot": ["stall", "ready"]},
    "boot-crash": {"boot": ["crash:7", "ready"]},
    "boot-slow": {"boot": ["slow:3.0"]},
    # session-recovery ladder rungs (round 9)
    "partials": {"chunks": ["partial-ok"]},
    "die-mid-chunk": {"chunks": ["die-after:2", "partial-ok"]},
    "hang-mid-chunk": {"chunks": ["hang-at:1", "partial-ok"]},
    "dup-partial": {"chunks": ["dup-partial"]},
    # fast for one chunk, then a 1s straggler — the fleet planner must
    # shift load off it (tests/test_fleet.py least-backlog spread)
    "straggler": {"chunks": ["slow-after:1:1.0"]},
}


def _load_script(spec: str) -> dict:
    if spec in NAMED_SCRIPTS:
        return NAMED_SCRIPTS[spec]
    if spec.startswith("@"):
        with open(spec[1:]) as f:
            return json.load(f)
    return json.loads(spec)


def _action(seq, index, default):
    if not seq:
        return default
    return seq[min(index, len(seq) - 1)]


class _State:
    """Cross-incarnation counters, persisted so respawns advance the
    script instead of replaying it."""

    def __init__(self, path):
        self.path = path
        self.data = {"boot": 0, "chunks": 0}
        if path and os.path.exists(path):
            try:
                with open(path) as f:
                    self.data.update(json.load(f))
            except (OSError, ValueError):
                pass

    def bump(self, key: str) -> int:
        n = self.data[key]
        self.data[key] = n + 1
        if self.path:
            tmp = self.path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(self.data, f)
            os.replace(tmp, self.path)
        return n


def _fake_response(wp: dict, cp: int) -> dict:
    return {
        "position_index": wp.get("position_index"),
        "url": wp.get("url"),
        "scores": [[None, {"cp": cp}]],
        "pvs": [[None, ["e2e4"]]],
        "best_move": "e2e4",
        "depth": 1,
        "nodes": 1,
        "time_s": 0.001,
        "nps": 1000,
    }


class FlakyProxy:
    """Scriptable TCP shim: client ↔ proxy ↔ target, with injectable
    connection-level faults. Runs inside the caller's event loop (tests
    and tools/chaos.py build it next to the coordinator).

    Actions (`await set_fault(...)`):

        none                 transparent pipe (the default)
        refuse-for:S         close the listening socket for S seconds —
                             connecting clients get a genuine
                             ECONNREFUSED (kernel RSTs the SYN), the
                             transient connect-phase fault the fleet
                             retries in-dispatch; the listener re-opens
                             on the SAME port when the window ends
        reset-after-headers  accept, swallow the request head, then RST
                             (SO_LINGER 0) — the request hit the wire
                             and died mid-response: a loss, never
                             retried blindly
        delay:MS             hold each new connection MS milliseconds
                             before piping — a slow network path
    """

    def __init__(self, target_host: str, target_port: int):
        self.target_host = target_host
        self.target_port = target_port
        self.host = "127.0.0.1"
        self.port = 0
        self.conns = 0  # connections actually accepted
        self._mode = "none"
        self._server: Optional[asyncio.AbstractServer] = None
        self._resume_task: Optional[asyncio.Task] = None

    async def start(self) -> Tuple[str, int]:
        self._server = await asyncio.start_server(
            self._handle, self.host, self.port or 0
        )
        self.port = self._server.sockets[0].getsockname()[1]
        return self.host, self.port

    async def close(self) -> None:
        if self._resume_task is not None:
            self._resume_task.cancel()
            self._resume_task = None
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
            self._server = None

    async def set_fault(self, action: str) -> None:
        if action in ("", "none"):
            self._mode = "none"
            return
        if action.startswith("refuse-for:"):
            secs = float(action.split(":", 1)[1])
            await self._pause_listener(secs)
            return
        if action == "reset-after-headers" or action.startswith("delay:"):
            self._mode = action
            return
        raise ValueError(f"flaky_proxy: unknown action {action!r}")

    async def wait_recovered(self) -> None:
        """Block until a pending refuse-for window has re-opened the
        listener (chaos scenarios sequence their phases on this)."""
        if self._resume_task is not None:
            await self._resume_task
            self._resume_task = None

    async def _pause_listener(self, secs: float) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
            self._server = None

        async def _resume() -> None:
            await asyncio.sleep(secs)
            # same port: members keep their configured address across
            # the outage, exactly like a real host rebooting
            self._server = await asyncio.start_server(
                self._handle, self.host, self.port
            )

        self._resume_task = asyncio.ensure_future(_resume())

    async def _handle(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        self.conns += 1
        mode = self._mode
        upstream_w: Optional[asyncio.StreamWriter] = None
        try:
            if mode == "reset-after-headers":
                buf = b""
                while b"\r\n\r\n" not in buf:
                    data = await reader.read(1024)
                    if not data:
                        break
                    buf += data
                sock = writer.get_extra_info("socket")
                if sock is not None:
                    # linger(on, 0): close() sends RST, not FIN — the
                    # client sees a reset mid-response, not a clean EOF
                    sock.setsockopt(
                        _socket.SOL_SOCKET, _socket.SO_LINGER,
                        struct.pack("ii", 1, 0),
                    )
                return
            if mode.startswith("delay:"):
                await asyncio.sleep(float(mode.split(":", 1)[1]) / 1000.0)
            upstream_r, upstream_w = await asyncio.open_connection(
                self.target_host, self.target_port
            )
            await asyncio.gather(
                self._pipe(reader, upstream_w),
                self._pipe(upstream_r, writer),
            )
        except (ConnectionError, OSError, asyncio.IncompleteReadError):
            pass  # either side dropped; the other gets torn down below
        finally:
            for w in (writer, upstream_w):
                if w is None:
                    continue
                w.close()
                try:
                    await w.wait_closed()
                except (ConnectionError, OSError):
                    pass

    @staticmethod
    async def _pipe(
        reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        try:
            while True:
                data = await reader.read(65536)
                if not data:
                    break
                writer.write(data)
                await writer.drain()
        finally:
            try:
                writer.write_eof()
            except (OSError, RuntimeError):
                pass  # transport already closed


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="fishnet-tpu-fake-host")
    p.add_argument("--script", required=True,
                   help="named script, inline JSON, or @path")
    p.add_argument("--state", default=None,
                   help="JSON file persisting script position across respawns")
    p.add_argument("--hb-interval", type=float, default=0.05)
    p.add_argument("--echo", default=None,
                   help="append one JSON line per boot/chunk for config-"
                        "fidelity and replay-suffix assertions")
    # engine-config flags of the real host (engine/host.py): accepted so
    # a supervisor-built host_cmd works verbatim; echoed, not interpreted
    p.add_argument("--backend", default=None)
    p.add_argument("--weights", default=None)
    p.add_argument("--depth", type=int, default=None)
    p.add_argument("--helpers", type=int, default=None)
    p.add_argument("--refill", type=int, default=None)
    p.add_argument("--partials", type=int, default=1)
    # fixed per-chunk service delay (fleet asymmetric-member tests);
    # applied before every chunk's scripted action, heartbeats continue
    p.add_argument("--latency-ms", type=float, default=0.0)
    # uniform per-chunk latency jitter in [0, N] ms on top of
    # --latency-ms, drawn from a --jitter-seed'd RNG so a given member
    # incarnation replays the identical delay sequence
    p.add_argument("--jitter-ms", type=float, default=0.0)
    p.add_argument("--jitter-seed", type=int, default=0)
    # clock-sync fault injection (obs/trace.py ClockSync): report a
    # monotonic clock running S seconds BEHIND the real one in hb/ready
    # `mono` fields, and stream a synthetic child trace ring stamped on
    # that same skewed clock — the supervisor's offset estimate must
    # land the merged events back on the parent timeline regardless
    p.add_argument("--trace-skew", type=float, default=None)
    args = p.parse_args(argv)

    script = _load_script(args.script)
    state = _State(args.state)
    stdin = sys.stdin.buffer
    stdout = sys.stdout.buffer

    def echo(record: dict) -> None:
        if args.echo:
            with open(args.echo, "a") as f:
                f.write(json.dumps(record) + "\n")

    echo({
        "t": "boot",
        "argv": list(argv) if argv is not None else sys.argv[1:],
        "env": {
            k: v for k, v in os.environ.items()
            if k.startswith("FISHNET_TPU_")
        },
    })

    wlock = threading.Lock()
    stalled = threading.Event()

    def send(obj: dict) -> None:
        with wlock:
            write_frame(stdout, obj)

    def fake_mono() -> float:
        # the child's (possibly skewed) view of its monotonic clock
        return time.monotonic() - (args.trace_skew or 0.0)

    def ticker() -> None:
        seq = 0
        while not stalled.wait(args.hb_interval):
            seq += 1
            try:
                send({"t": "hb", "phase": "fake", "busy_s": 0.0,
                      "seq": seq, "mono": fake_mono()})
            except OSError:
                os._exit(1)

    threading.Thread(target=ticker, daemon=True).start()

    def freeze() -> None:
        stalled.set()  # heartbeats cease; process lingers until killed
        while True:
            time.sleep(3600)

    boot = _action(script.get("boot"), state.bump("boot"), "ready")
    if boot.startswith("crash:"):
        os._exit(int(boot.split(":", 1)[1]))
    elif boot == "stall":
        freeze()
    elif boot.startswith("slow:"):
        time.sleep(float(boot.split(":", 1)[1]))
    send({"t": "ready", "mono": fake_mono()})

    while True:
        try:
            msg = read_frame(stdin)
        except (PipeClosed, FrameError):
            return 0
        t = msg.get("t")
        if t == "quit":
            return 0
        if t != "go":
            continue
        gid = msg.get("id")
        positions = msg.get("chunk", {}).get("positions", [])
        fps = [wire_position_fingerprint(wp) for wp in positions]
        echo({"t": "go", "positions": len(positions), "fps": fps})
        chunk_idx = state.bump("chunks")
        action = _action(script.get("chunks"), chunk_idx, "ok")
        if args.latency_ms > 0:
            time.sleep(args.latency_ms / 1000.0)
        if args.jitter_ms > 0:
            # seeded per chunk INDEX (not per boot) so a respawned
            # incarnation resuming at chunk k sleeps the same jitter
            # the dead one would have
            jrng = random.Random(f"{args.jitter_seed}:{chunk_idx}")
            time.sleep(jrng.uniform(0.0, args.jitter_ms) / 1000.0)

        if args.trace_skew is not None:
            # one synthetic span per chunk, stamped on the SKEWED clock
            # (same epoch the mono fields report) — the supervisor must
            # shift it back onto the parent timeline when absorbing
            span = {
                "name": "fake.search", "cat": "host", "ph": "X",
                "ts": fake_mono() * 1e6,
                "dur": args.hb_interval * 1e6,
                "pid": os.getpid(), "tid": 1,
            }
            tids = sorted({
                wp["ctx"]["trace_id"] for wp in positions
                if isinstance(wp.get("ctx"), dict)
                and wp["ctx"].get("trace_id")
            })
            if tids:
                span["args"] = {"trace_ids": tids}
            # request flow hops on this child's track, same skewed clock
            # (like the real host's search span): the merged dump must
            # show each request's causal chain crossing into this
            # process — and into the survivor after a re-dispatch
            send({"t": "trace", "events": [span] + [{
                "name": "request", "cat": "request", "ph": "t",
                "id": t_id, "ts": span["ts"],
                "pid": os.getpid(), "tid": 1,
            } for t_id in tids]})

        def send_partial(wp: dict, times: int = 1, cp: int = FAKE_CP) -> None:
            frame = {
                "t": "partial",
                "id": gid,
                "fp": wire_position_fingerprint(wp),
                "response": _fake_response(wp, cp),
            }
            # echo request ctx like the real host (engine/host.py): the
            # chaos continuity scenarios assert trace_ids survive a
            # kill-mid-chunk through the journaled partials
            if isinstance(wp.get("ctx"), dict):
                frame["ctx"] = wp["ctx"]
            for _ in range(times):
                send(frame)

        if action.startswith("crash:"):
            os._exit(int(action.split(":", 1)[1]))
        elif action == "stall":
            freeze()
        elif action == "hang":
            while True:  # heartbeats keep flowing; never answer
                time.sleep(3600)
        elif action == "corrupt":
            with wlock:
                stdout.write(b"\xde\xad\xbe\xef" * 8)
                stdout.flush()
            freeze()
        elif action == "err":
            send({"t": "err", "id": gid, "error": "scripted engine error"})
            continue
        elif action.startswith("die-after:"):
            # k positions finish and stream out, then the child dies —
            # the supervisor must replay only the unfinished suffix
            k = int(action.split(":", 1)[1])
            for wp in positions[:k]:
                send_partial(wp)
            time.sleep(2 * args.hb_interval)  # let the frames flush
            os._exit(9)
        elif action.startswith("stall-at:"):
            k = int(action.split(":", 1)[1])
            for wp in positions[:k]:
                send_partial(wp)
            freeze()
        elif action.startswith("hang-at:"):
            # the device-hang signature mid-chunk: partial stream stops,
            # heartbeats keep flowing
            k = int(action.split(":", 1)[1])
            for wp in positions[:k]:
                send_partial(wp)
            while True:
                time.sleep(3600)
        elif action.startswith("crash-on-fp:"):
            # deterministic poison position, addressed by fingerprint so
            # it stays poison across replays/bisections/batches
            prefix = action.split(":", 1)[1]
            for wp in positions:
                if wire_position_fingerprint(wp).startswith(prefix):
                    time.sleep(2 * args.hb_interval)
                    os._exit(9)
                send_partial(wp)
            send({"t": "ok", "id": gid,
                  "responses": [_fake_response(wp, FAKE_CP)
                                for wp in positions]})
        elif action == "dup-partial":
            for wp in positions:
                send_partial(wp, times=2)
            send({"t": "ok", "id": gid,
                  "responses": [_fake_response(wp, FAKE_CP)
                                for wp in positions]})
        else:
            cp = FAKE_CP
            if action.startswith("slow:"):
                time.sleep(float(action.split(":", 1)[1]))
            elif action.startswith("slow-after:"):
                parts = action.split(":")
                after = int(parts[1])
                delay = float(parts[2]) if len(parts) > 2 else 1.0
                if chunk_idx >= after:
                    time.sleep(delay)
            elif action.startswith("ok:"):
                cp = int(action.split(":", 1)[1])
            elif action.startswith("partial-ok"):
                part = action.split(":", 1)
                if len(part) == 2:
                    cp = int(part[1])
                for wp in positions:
                    send_partial(wp, cp=cp)
            send({"t": "ok", "id": gid,
                  "responses": [_fake_response(wp, cp) for wp in positions]})


if __name__ == "__main__":
    sys.exit(main())
