"""Scriptable fake engine host: deterministic fault injection.

Speaks the exact supervisor↔host protocol of engine/host.py but executes
a *fault script* instead of a real engine, so every supervisor path —
heartbeat-stall kill, deadline kill, crash respawn, corrupt-frame kill,
circuit-breaker trip and probe recovery — is exercisable in tier-1 on
CPU with no JAX import at all. tools/chaos.py replays the same scripts
against a live supervisor for manual soak testing.

A script is a JSON object:

    {"boot":   ["ready", "crash:3", "stall", "slow:2.0", ...],
     "chunks": ["ok", "hang", "stall", "crash:9", "corrupt",
                "slow:1.5", "err", "ok:333", ...]}

`boot[i]` is the startup behavior of the i-th host incarnation;
`chunks[j]` the behavior for the j-th chunk EVER dispatched (counted
across respawns). Lists are extended by repeating their last entry. The
cross-incarnation counters persist in --state (a JSON file) — without
it, every respawn would replay the script from the top and a
crash-then-recover sequence could never be expressed.

Actions:
    ready       boot only: warm up instantly and send ready
    ok[:CP]     reply with a depth-1 response per position, score cp CP
                (default 777 — a signature tests use to tell the fake
                host's responses from the CPU fallback engine's)
    slow:S      sleep S seconds (heartbeats continue), then ok
    hang        keep heartbeating, never reply — killed at the deadline
    stall       stop ALL output and sleep forever — killed by the
                heartbeat watchdog
    crash:N     exit immediately with status N
    corrupt     write garbage bytes into the frame stream
    err         reply with an err frame (host stays alive)
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

from .frames import FrameError, PipeClosed, read_frame, write_frame

FAKE_CP = 777  # default signature score for "ok" responses

NAMED_SCRIPTS = {
    # one-fault scripts, then recovered: the canonical chaos menu
    "ok": {"chunks": ["ok"]},
    "hang": {"chunks": ["hang", "ok"]},
    "stall": {"chunks": ["stall", "ok"]},
    "crash": {"chunks": ["crash:9", "ok"]},
    "corrupt": {"chunks": ["corrupt", "ok"]},
    "slow": {"chunks": ["slow:2.0", "ok"]},
    "err": {"chunks": ["err", "ok"]},
    # dies repeatedly, then recovers — trips a small-threshold breaker
    # and lets a later probe restore the primary path
    "flap": {"chunks": ["crash:9", "crash:9", "crash:9", "ok"]},
    # boot-time faults: warmup that never heartbeats / dies / crawls
    "boot-stall": {"boot": ["stall", "ready"]},
    "boot-crash": {"boot": ["crash:7", "ready"]},
    "boot-slow": {"boot": ["slow:3.0"]},
}


def _load_script(spec: str) -> dict:
    if spec in NAMED_SCRIPTS:
        return NAMED_SCRIPTS[spec]
    if spec.startswith("@"):
        with open(spec[1:]) as f:
            return json.load(f)
    return json.loads(spec)


def _action(seq, index, default):
    if not seq:
        return default
    return seq[min(index, len(seq) - 1)]


class _State:
    """Cross-incarnation counters, persisted so respawns advance the
    script instead of replaying it."""

    def __init__(self, path):
        self.path = path
        self.data = {"boot": 0, "chunks": 0}
        if path and os.path.exists(path):
            try:
                with open(path) as f:
                    self.data.update(json.load(f))
            except (OSError, ValueError):
                pass

    def bump(self, key: str) -> int:
        n = self.data[key]
        self.data[key] = n + 1
        if self.path:
            tmp = self.path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(self.data, f)
            os.replace(tmp, self.path)
        return n


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="fishnet-tpu-fake-host")
    p.add_argument("--script", required=True,
                   help="named script, inline JSON, or @path")
    p.add_argument("--state", default=None,
                   help="JSON file persisting script position across respawns")
    p.add_argument("--hb-interval", type=float, default=0.05)
    args = p.parse_args(argv)

    script = _load_script(args.script)
    state = _State(args.state)
    stdin = sys.stdin.buffer
    stdout = sys.stdout.buffer

    wlock = threading.Lock()
    stalled = threading.Event()

    def send(obj: dict) -> None:
        with wlock:
            write_frame(stdout, obj)

    def ticker() -> None:
        seq = 0
        while not stalled.wait(args.hb_interval):
            seq += 1
            try:
                send({"t": "hb", "phase": "fake", "busy_s": 0.0, "seq": seq})
            except OSError:
                os._exit(1)

    threading.Thread(target=ticker, daemon=True).start()

    def freeze() -> None:
        stalled.set()  # heartbeats cease; process lingers until killed
        while True:
            time.sleep(3600)

    boot = _action(script.get("boot"), state.bump("boot"), "ready")
    if boot.startswith("crash:"):
        os._exit(int(boot.split(":", 1)[1]))
    elif boot == "stall":
        freeze()
    elif boot.startswith("slow:"):
        time.sleep(float(boot.split(":", 1)[1]))
    send({"t": "ready"})

    while True:
        try:
            msg = read_frame(stdin)
        except (PipeClosed, FrameError):
            return 0
        t = msg.get("t")
        if t == "quit":
            return 0
        if t != "go":
            continue
        action = _action(script.get("chunks"), state.bump("chunks"), "ok")
        if action.startswith("crash:"):
            os._exit(int(action.split(":", 1)[1]))
        elif action == "stall":
            freeze()
        elif action == "hang":
            while True:  # heartbeats keep flowing; never answer
                time.sleep(3600)
        elif action == "corrupt":
            with wlock:
                stdout.write(b"\xde\xad\xbe\xef" * 8)
                stdout.flush()
            freeze()
        elif action == "err":
            send({"t": "err", "id": msg.get("id"),
                  "error": "scripted engine error"})
            continue
        else:
            cp = FAKE_CP
            if action.startswith("slow:"):
                time.sleep(float(action.split(":", 1)[1]))
            elif action.startswith("ok:"):
                cp = int(action.split(":", 1)[1])
            positions = msg.get("chunk", {}).get("positions", [])
            send({
                "t": "ok",
                "id": msg.get("id"),
                "responses": [
                    {
                        "position_index": wp.get("position_index"),
                        "url": wp.get("url"),
                        "scores": [[None, {"cp": cp}]],
                        "pvs": [[None, ["e2e4"]]],
                        "best_move": "e2e4",
                        "depth": 1,
                        "nodes": 1,
                        "time_s": 0.001,
                        "nps": 1000,
                    }
                    for wp in positions
                ],
            })


if __name__ == "__main__":
    sys.exit(main())
