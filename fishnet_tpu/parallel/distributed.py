"""Multi-host mesh lowering: jax.distributed boot, global-mesh helpers,
and addressable-shard-aware host I/O.

One logical engine spanning a pod slice means the Mesh covers devices on
SEVERAL processes. Device-side nothing changes — the segment/merge
callables (parallel/mesh.py) are collective-free by design, so each
process advances its addressable shards locally under the same compiled
program. What DOES change is every host touch point:

  * placement — `jax.device_put` cannot build a non-addressable global
    array from host data; `put_global` switches to
    `jax.make_array_from_callback`, where each process supplies only the
    shards it can see (every process holds the same host-side values, so
    the global array is consistent by construction).
  * fetches — `np.asarray` on a non-fully-addressable array raises. The
    streaming loops' per-boundary reads route through `fetch_summary` /
    `gather_rows`: ONE SyncStats-accounted fetch of the process's local
    shards (the one-fetch-per-boundary property from the pipelined
    scheduler holds per host), then a host-level allgather of the tiny
    payload over `HostExchange` — a plain TCP star on
    coordinator-port+1, no device collectives, so CPU meshes need no
    gloo/MPI build.

Every process must drive the SAME dispatch sequence (SPMD discipline);
the exchange gives every host an identical global boundary picture, so
identical code makes identical decisions. tools/mesh_smoke.py is the
2-process CI proof; docs/mesh.md has the topology matrix and runbook.
"""
from __future__ import annotations

import functools
import pickle
import socket
import struct
import threading
import time
from typing import Any, Callable, List, Optional, Sequence, Tuple

import numpy as np

from ..utils import settings
from . import partition as _partition

_EXCHANGE: Optional["HostExchange"] = None
_INITIALIZED = False


def ensure_initialized(logger=None) -> bool:
    """Boot jax.distributed from the FISHNET_TPU_MESH_* settings.

    No-op (returns False) unless FISHNET_TPU_MESH_HOSTS > 1. Otherwise
    connects this process to the coordinator
    (FISHNET_TPU_MESH_COORDINATOR host:port, process id
    FISHNET_TPU_MESH_PROCESS_ID), starts the host-level boundary
    exchange one port above the coordinator, and returns True.
    Idempotent — callers sprinkle it before first device use."""
    global _INITIALIZED
    n = settings.get_int("FISHNET_TPU_MESH_HOSTS")
    if n <= 1:
        return False
    if _INITIALIZED:
        return True
    coord = settings.get_str("FISHNET_TPU_MESH_COORDINATOR")
    pid = settings.get_int("FISHNET_TPU_MESH_PROCESS_ID")
    if not coord or ":" not in coord:
        raise ValueError(
            "FISHNET_TPU_MESH_HOSTS > 1 requires "
            "FISHNET_TPU_MESH_COORDINATOR as host:port"
        )
    import jax

    # the XLA:CPU client refuses ANY computation spanning processes
    # unless a CPU collectives backend is configured — even though the
    # segment/merge callables are collective-free; gloo ships in jaxlib
    # and only coordinates the runtime here (TPU meshes ignore this)
    try:
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except Exception:
        pass  # older jaxlib without the knob; TPU pods don't need it
    jax.distributed.initialize(
        coordinator_address=coord, num_processes=n, process_id=pid,
    )
    _INITIALIZED = True
    host, port = coord.rsplit(":", 1)
    _start_exchange(host, int(port) + 1, n, pid)
    if logger is not None:
        logger.info(
            "mesh: jax.distributed up — process %d/%d, coordinator %s"
            % (pid, n, coord)
        )
    return True


def host_exchange() -> "HostExchange":
    """The process-global boundary exchange; raises if the process is not
    a distributed-mesh participant."""
    if _EXCHANGE is None:
        raise RuntimeError(
            "no host exchange — multi-host paths require "
            "distributed.ensure_initialized() (FISHNET_TPU_MESH_HOSTS)"
        )
    return _EXCHANGE


def _start_exchange(host: str, port: int, num: int, pid: int) -> None:
    global _EXCHANGE
    _EXCHANGE = HostExchange(host, port, num, pid)


@functools.lru_cache(maxsize=None)
def spans_processes(mesh) -> bool:
    """True when the mesh's devices live on more than one process."""
    return len({d.process_index for d in mesh.devices.flat}) > 1


@functools.lru_cache(maxsize=None)
def addressable_shards(mesh) -> Tuple[int, ...]:
    """Global shard indices (mesh device order) this process can see.

    Single-process meshes address everything; under jax.distributed the
    LaneScheduler admits new work only into these shards while its free
    lists keep GLOBAL shard indexing (engine/tpu.py)."""
    import jax

    me = jax.process_index()
    return tuple(
        i for i, d in enumerate(mesh.devices.flat) if d.process_index == me
    )


def global_mesh(axis: str = "dp"):
    """The Mesh over every device of every participating process —
    make_mesh already enumerates jax.devices(), which is global once
    jax.distributed is up."""
    from .mesh import make_mesh

    return make_mesh(axis=axis)


# -------------------------------------------------------------- placement


def put_global(mesh, x, spec):
    """Place host/local data as a (possibly multi-host) global array.

    Single-process: a plain device_put. Multi-process: every process
    holds the same full-size host value and contributes its addressable
    shards via jax.make_array_from_callback — no cross-host transfer."""
    sharding = _partition.named_sharding(mesh, spec)
    if not spans_processes(mesh):
        import jax

        return jax.device_put(x, sharding)
    import jax

    arr = np.asarray(x)
    return jax.make_array_from_callback(
        arr.shape, sharding, lambda idx: arr[idx]
    )


def replicate_tree(mesh, tree):
    """Every leaf placed fully replicated on the global mesh (NNUE
    params before the first sharded dispatch)."""
    import jax

    return jax.tree_util.tree_map(
        lambda x: put_global(mesh, x, _partition.replicated_spec()), tree
    )


# ---------------------------------------------------------------- fetches


def fetch_summary(mesh, p_summ, stats, label: str = "summary"):
    """The stacked (ndev, local+1, 4) boundary summary, on every host.

    Single-process: the usual one SyncStats fetch. Multi-process: ONE
    fetch of this process's addressable summary rows (keeping the
    one-fetch-per-boundary invariant per host), then a host-level
    allgather reassembles the global block identically everywhere."""
    if not spans_processes(mesh):
        return stats.fetch(p_summ, label)
    import jax.numpy as jnp

    shards = sorted(
        p_summ.addressable_shards, key=lambda s: s.index[0].start or 0
    )
    rows = [s.index[0].start or 0 for s in shards]
    local = stats.fetch(
        jnp.concatenate([s.data for s in shards], axis=0), label
    )
    out = np.zeros(p_summ.shape, p_summ.dtype)
    seen = np.zeros(p_summ.shape[0], bool)
    for blob in host_exchange().allgather(pickle.dumps((rows, local))):
        peer_rows, peer_local = pickle.loads(blob)
        for j, r in enumerate(peer_rows):
            n = peer_local.shape[0] // len(peer_rows)
            out[r:r + n] = peer_local[j * n:(j + 1) * n]
            seen[r:r + n] = True
    if not seen.all():
        raise RuntimeError(
            "boundary exchange left summary shards unfilled: "
            f"{np.nonzero(~seen)[0].tolist()}"
        )
    return out


def gather_rows(mesh, x, rows, stats, label: str = "",
                pick: Optional[Callable[[Any], Any]] = None,
                tail: Tuple[int, ...] = (), dtype=np.int32) -> np.ndarray:
    """Global rows of a lane-sharded array, assembled on every host.

    `pick` maps a (local, ...) shard block to the slice actually wanted
    (e.g. lambda a: a[:, 0] for PV rows) BEFORE the device→host copy, so
    the fetch stays as small as the single-process jnp.take path. Each
    process fetches only rows its addressable shards own (one
    SyncStats-accounted fetch), then the host exchange fills in the
    rest. Returns (len(rows),) + tail, identical on every process."""
    import jax.numpy as jnp

    rows = np.asarray(rows, np.int64).reshape(-1)
    pick = pick if pick is not None else (lambda a: a)
    if not spans_processes(mesh):
        taken = jnp.take(pick(x), jnp.asarray(rows), axis=0)
        return np.asarray(stats.fetch(taken, label), dtype)
    owned_pos: List[np.ndarray] = []
    owned_vals = []
    for s in x.addressable_shards:
        start = s.index[0].start or 0
        stop = s.index[0].stop
        stop = start + s.data.shape[0] if stop is None else stop
        sel = np.nonzero((rows >= start) & (rows < stop))[0]
        if sel.size:
            owned_pos.append(sel)
            owned_vals.append(
                jnp.take(pick(s.data), jnp.asarray(rows[sel] - start),
                         axis=0)
            )
    if owned_vals:
        local = np.asarray(
            stats.fetch(jnp.concatenate(owned_vals, axis=0), label), dtype
        )
        pos = np.concatenate(owned_pos)
    else:
        local = np.zeros((0,) + tail, dtype)
        pos = np.zeros(0, np.int64)
    out = np.zeros((len(rows),) + tail, dtype)
    filled = np.zeros(len(rows), bool)
    for blob in host_exchange().allgather(pickle.dumps((pos, local))):
        peer_pos, peer_vals = pickle.loads(blob)
        out[peer_pos] = peer_vals
        filled[peer_pos] = True
    if not filled.all():
        raise RuntimeError(
            f"boundary exchange left rows unfilled: "
            f"{rows[~filled].tolist()}"
        )
    return out


# --------------------------------------------------------- host exchange


class HostExchange:
    """Tiny TCP-star allgather for per-boundary host payloads.

    Process 0 binds `port`; every worker keeps one persistent connection.
    `allgather(payload)` is a collective: every process contributes its
    bytes and receives the full pid-ordered list. Payloads are boundary
    summaries and finished-lane rows — hundreds of bytes — so a
    sequential star is plenty, and staying off the device interconnect
    means CPU test meshes need no collectives backend at all."""

    def __init__(self, host: str, port: int, num: int, pid: int,
                 timeout: float = 60.0) -> None:
        self.num = num
        self.pid = pid
        self._lock = threading.Lock()
        if pid == 0:
            srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            # workers may sit on other machines: bind all interfaces
            srv.bind(("", port))
            srv.listen(num)
            srv.settimeout(timeout)
            self._peers: dict[int, socket.socket] = {}
            deadline = time.monotonic() + timeout
            while len(self._peers) < num - 1:
                if time.monotonic() > deadline:
                    raise TimeoutError(
                        f"host exchange: {len(self._peers)}/{num - 1} "
                        "workers connected before timeout"
                    )
                conn, _ = srv.accept()
                conn.settimeout(timeout)
                (peer,) = struct.unpack("<I", _read_exact(conn, 4))
                self._peers[peer] = conn
            srv.close()
        else:
            deadline = time.monotonic() + timeout
            last_err: Optional[Exception] = None
            while True:
                try:
                    conn = socket.create_connection((host, port), timeout=5)
                    break
                except OSError as e:
                    last_err = e
                    if time.monotonic() > deadline:
                        raise TimeoutError(
                            f"host exchange: cannot reach coordinator "
                            f"{host}:{port}: {last_err}"
                        ) from e
                    time.sleep(0.05)
            conn.settimeout(timeout)
            conn.sendall(struct.pack("<I", pid))
            self._conn = conn

    def allgather(self, payload: bytes) -> List[bytes]:
        """All processes' payloads, ordered by process id. Collective:
        every participant must call once per boundary, in lockstep."""
        with self._lock:
            if self.pid == 0:
                parts: List[bytes] = [b""] * self.num
                parts[0] = payload
                for peer, conn in self._peers.items():
                    (n,) = struct.unpack("<I", _read_exact(conn, 4))
                    parts[peer] = _read_exact(conn, n)
                blob = struct.pack("<I", self.num) + b"".join(
                    struct.pack("<I", len(p)) + p for p in parts
                )
                for conn in self._peers.values():
                    conn.sendall(blob)
                return parts
            self._conn.sendall(
                struct.pack("<I", len(payload)) + payload
            )
            (num,) = struct.unpack("<I", _read_exact(self._conn, 4))
            parts = []
            for _ in range(num):
                (n,) = struct.unpack("<I", _read_exact(self._conn, 4))
                parts.append(_read_exact(self._conn, n))
            return parts

    def close(self) -> None:
        if self.pid == 0:
            for conn in self._peers.values():
                conn.close()
        else:
            self._conn.close()


def _read_exact(conn: socket.socket, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = conn.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("host exchange peer closed mid-frame")
        buf += chunk
    return buf
