"""Mesh construction and sharded dispatch.

The reference scales within a host by one engine process per core
(reference: src/main.rs:151-161) and across hosts by server-mediated work
stealing. Here the within-host axis is a `jax.sharding.Mesh`: search lanes
are embarrassingly parallel, so the batch dimension shards over all chips
("dp"), with NNUE weights replicated in every chip's HBM — collectives only
appear in training (psum of grads over dp, all_gather over tp).
"""
from __future__ import annotations

from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def make_mesh(n_devices: Optional[int] = None, axis: str = "dp") -> Mesh:
    devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    return Mesh(np.array(devices), (axis,))


def make_2d_mesh(dp: int, tp: int) -> Mesh:
    devices = np.array(jax.devices()[: dp * tp]).reshape(dp, tp)
    return Mesh(devices, ("dp", "tp"))


def shard_batch(mesh: Mesh, tree, axis: str = "dp"):
    """Place a pytree of batched arrays with the leading dim sharded."""

    def put(x):
        spec = P(axis, *([None] * (x.ndim - 1)))
        return jax.device_put(x, NamedSharding(mesh, spec))

    return jax.tree_util.tree_map(put, tree)


def replicate(mesh: Mesh, tree):
    def put(x):
        return jax.device_put(x, NamedSharding(mesh, P()))

    return jax.tree_util.tree_map(put, tree)


def sharded_search(params, roots, depth, node_budget, max_ply: int,
                   mesh: Optional[Mesh] = None):
    """Run the batched search with lanes sharded across the mesh.

    The search program is identical to the single-chip one; XLA partitions
    the lane dimension and runs each shard locally — no collectives are
    needed until results are gathered back to host.
    """
    from ..ops.search import search_batch_jit

    mesh = mesh or make_mesh()
    import jax.numpy as jnp

    B = int(roots.stm.shape[0])
    n = mesh.devices.size
    if B % n != 0:
        raise ValueError(f"lane count {B} must divide over {n} devices")
    depth = jnp.broadcast_to(jnp.asarray(depth, jnp.int32), (B,))
    node_budget = jnp.broadcast_to(jnp.asarray(node_budget, jnp.int32), (B,))
    roots = shard_batch(mesh, roots)
    depth = shard_batch(mesh, depth)
    node_budget = shard_batch(mesh, node_budget)
    params = replicate(mesh, params)
    return search_batch_jit(params, roots, depth, node_budget, max_ply=max_ply)
