"""Mesh construction and sharded dispatch.

The reference scales within a host by one engine process per core
(reference: src/main.rs:151-161) and across hosts by server-mediated work
stealing. Here the within-host axis is a `jax.sharding.Mesh`: search lanes
are embarrassingly parallel, so the batch dimension shards over all chips
("dp"), with NNUE weights replicated in every chip's HBM — collectives only
appear in training (psum of grads over dp, all_gather over tp).

Every in/out spec below derives from the partition-rule registry
(parallel/partition.py) rather than hand-built literals, so a single-host
shard_map, a forced-multi-device CPU mesh and a multi-host
jax.distributed mesh (parallel/distributed.py builds that one) are ONE
data-driven code path; fishnet-lint's mesh-unregistered-spec rule keeps
it that way.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh

from ..aot import registry as _aot_registry
from ..utils import sanitize as _sanitize
from . import partition as _partition

try:
    _shard_map = jax.shard_map
    _SHARD_MAP_KW = {"check_vma": False}
except AttributeError:  # older jax ships it under experimental, as check_rep
    from jax.experimental.shard_map import shard_map as _shard_map

    _SHARD_MAP_KW = {"check_rep": False}


def make_mesh(n_devices: Optional[int] = None, axis: str = "dp") -> Mesh:
    devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    return Mesh(np.array(devices), (axis,))


def make_2d_mesh(dp: int, tp: int) -> Mesh:
    devices = np.array(jax.devices()[: dp * tp]).reshape(dp, tp)
    return Mesh(devices, ("dp", "tp"))


def shard_batch(mesh: Mesh, tree, axis: str = "dp"):
    """Place a pytree of batched arrays with the leading dim sharded.

    Routed through distributed.put_global so the same call works when
    the mesh spans jax.distributed processes (each host contributes its
    addressable shards from identical host-side values)."""
    from . import distributed as _distributed

    def put(x):
        return _distributed.put_global(
            mesh, x, _partition.batch_spec(getattr(x, "ndim", 1), axis)
        )

    return jax.tree_util.tree_map(put, tree)


def replicate(mesh: Mesh, tree):
    from . import distributed as _distributed

    def put(x):
        return _distributed.put_global(
            mesh, x, _partition.replicated_spec()
        )

    return jax.tree_util.tree_map(put, tree)


@functools.lru_cache(maxsize=None)
def _segment_callable(mesh: Mesh, axis: str, has_tt: bool,
                      variant: str = "standard", deep_tt: bool = False,
                      prefer_deep: bool = False):
    """shard_map'd search segment: each device advances ITS lanes with ITS
    transposition-table shard, fully locally — no collectives, and a device
    whose lanes all park in DONE exits its while_loop early instead of
    spinning in lockstep with slower devices. This is the TPU-native
    equivalent of the reference's independent engine processes per core
    (reference: src/main.rs:151-161).

    segment_steps is a TRACED replicated scalar (retuning never recompiles)
    and tt_gen a per-lane (B,) sharded array. The per-shard packed boundary
    summary comes back stacked as (ndev, local_B+1, 4) so a no-finish
    boundary is one small host fetch, and state+TT are donated — a
    boundary rebinds shard handles instead of copying them."""
    from ..ops.search import _run_segment

    def seg(params, state, ttab, segment_steps, tt_gen):
        if ttab is not None:
            ttab = jax.tree.map(lambda a: a[0], ttab)  # (1, N) block → (N,)
        state, ttab, n, summ = _run_segment(
            params, state, ttab, segment_steps, variant, deep_tt,
            prefer_deep, tt_gen,
        )
        if ttab is not None:
            ttab = jax.tree.map(lambda a: a[None], ttab)
        return state, ttab, n.reshape(1), summ[None]

    in_specs, out_specs = _partition.segment_specs(has_tt, axis)
    fn = _shard_map(
        seg,
        mesh=mesh,
        in_specs=in_specs,
        out_specs=out_specs,
        **_SHARD_MAP_KW,
    )
    # AOT-wrapped (fishnet_tpu/aot/): the shard_map closure's compile
    # flags become extra key material — all call arguments are dynamic.
    # The donation guard is a no-op unless FISHNET_TPU_SANITIZE is set,
    # and lru_cache means it wraps once per mesh config, not per call.
    return _sanitize.guard_donation(
        "parallel/mesh.py::mesh_segment",
        _aot_registry.wrap(
            "mesh_segment", jax.jit(fn, donate_argnums=(1, 2)), seg,
            extra_static={
                "mesh": "x".join(str(d) for d in mesh.devices.shape),
                "axis": axis, "has_tt": has_tt, "variant": variant,
                "deep_tt": deep_tt, "prefer_deep": prefer_deep,
            },
        ),
        argnums=(1, 2),
    )


def run_segment_sharded(mesh: Mesh, params, state, ttab, segment_steps: int,
                        axis: str = "dp", variant: str = "standard",
                        deep_tt: bool = False, prefer_deep: bool = False,
                        tt_gen=0):
    """Advance a sharded search ≤ segment_steps on every device.

    state: SearchState with lane dim divisible by mesh size. ttab: TTable
    whose arrays carry a leading (n_devices,) shard dim (see
    make_sharded_table), or None. Returns (state, ttab, steps (ndev,),
    summary (ndev, B/ndev + 1, 4)) — the packed per-shard boundary
    summary of ops/search._run_segment, stacked over shards.

    state and ttab are DONATED: the handles passed in are dead after the
    call and the caller must rebind to the outputs. segment_steps is
    traced, so retuning the segment length reuses the compiled program.
    prefer_deep/tt_gen: helper-lane TT store policy (ops/tt.py store);
    tt_gen may be a scalar or a per-lane (B,) array."""
    import jax.numpy as jnp

    from . import distributed as _distributed

    fn = _segment_callable(
        mesh, axis, ttab is not None, variant, deep_tt, prefer_deep,
    )
    B = int(state.lane.shape[0])
    gen = jnp.asarray(tt_gen, jnp.int32)
    if gen.ndim == 0:
        gen = jnp.full((B,), gen, jnp.int32)
    steps = jnp.int32(segment_steps)
    if _distributed.spans_processes(mesh):
        # host-local scalars/arrays must be promoted to global arrays
        # before a multi-host dispatch (every process holds identical
        # values, so this is pure placement, no communication)
        gen = _distributed.put_global(
            mesh, gen, _partition.spec_for("tt_gen", axis))
        steps = _distributed.put_global(
            mesh, steps, _partition.spec_for("segment_steps", axis))
    return fn(params, state, ttab, steps, gen)


@functools.lru_cache(maxsize=None)
def _merge_callable(mesh: Mesh, axis: str):
    """shard_map'd masked lane merge (ops/search._merge_lanes): the splice
    is elementwise along the lane dim, so each shard merges its own slice
    of the fresh state — values change, shapes and shardings never, and
    the segment program keeps running with zero recompiles. Both inputs
    are donated (the merge rebinds, never copies)."""
    from ..ops.search import _merge_lanes

    in_specs, out_specs = _partition.merge_specs(axis)
    fn = _shard_map(
        _merge_lanes,
        mesh=mesh,
        in_specs=in_specs,
        out_specs=out_specs,
        **_SHARD_MAP_KW,
    )
    return _sanitize.guard_donation(
        "parallel/mesh.py::mesh_merge",
        _aot_registry.wrap(
            "mesh_merge", jax.jit(fn, donate_argnums=(0, 1)), _merge_lanes,
            extra_static={
                "mesh": "x".join(str(d) for d in mesh.devices.shape),
                "axis": axis,
            },
        ),
        argnums=(0, 1),
    )


def refill_lanes_sharded(mesh: Mesh, params, state, new_roots, lane_idx,
                         depth, node_budget, *, axis: str = "dp",
                         variant: str = "standard", hist_hash=None,
                         hist_halfmove=None, root_alpha=None, root_beta=None,
                         order_jitter=None, group=None):
    """Splice replacement positions into DONE lanes of a SHARDED state.

    Same contract as ops/search.refill_lanes, with the merge routed
    through the shard_map'd masked splice: each device rewrites only its
    own lanes, locally. `state` is donated (rebind to the return value).
    lane_idx is global lane numbering — the host assigns lanes, the
    shard split falls out of the sharding."""
    from ..ops.search import _refill_fresh

    fresh, mask = _refill_fresh(
        params, state, new_roots, lane_idx, depth, node_budget,
        variant=variant, hist_hash=hist_hash, hist_halfmove=hist_halfmove,
        root_alpha=root_alpha, root_beta=root_beta,
        order_jitter=order_jitter, group=group,
    )
    if fresh is None:
        return state
    import jax.numpy as jnp

    fresh = shard_batch(mesh, fresh, axis)
    mask_dev = shard_batch(mesh, jnp.asarray(mask), axis)
    return _merge_callable(mesh, axis)(state, fresh, mask_dev)


def make_sharded_table(mesh: Mesh, size_log2: int):
    """Per-device TT shards as one (ndev, N) array pair, placed sharded.

    Each device hashes into its private shard (ops/tt.py masks by the
    LOCAL size under shard_map) — cross-lane sharing happens within a
    device's lanes, which is where the lockstep phase offsets are anyway."""
    from ..ops import tt as tt_mod

    n = mesh.devices.size
    base = tt_mod.make_table(size_log2)
    import jax.numpy as jnp

    t = tt_mod.TTable(
        data=jnp.zeros((n, base.size, 4), jnp.int32),
    )
    return shard_batch(mesh, t)


def sharded_search(params, roots, depth, node_budget, max_ply: int,
                   mesh: Optional[Mesh] = None, tt=None, **kw):
    """Run the batched search with lanes sharded across the mesh.

    Thin wrapper over ops.search.search_batch_resumable(mesh=...) — the
    same code path the production TpuEngine uses (segments, deadline and
    the shared table all work sharded)."""
    from ..ops.search import search_batch_resumable

    mesh = mesh or make_mesh()
    B = int(roots.stm.shape[0])
    n = mesh.devices.size
    if B % n != 0:
        raise ValueError(f"lane count {B} must divide over {n} devices")
    return search_batch_resumable(
        params, roots, depth, node_budget, max_ply=max_ply, mesh=mesh,
        tt=tt, **kw
    )
