"""Partition-rule registry: regex-keyed sharding rules for every pytree
the sharded engine moves across a mesh.

Before this module, every shard_map'd callable in parallel/mesh.py carried
its own hand-built ``P("dp")`` literals — three copies of the same layout
decision, none of them checkable against the real search-state pytree, and
all of them single-host by construction. The registry inverts that: ONE
table of ``(path-regex, PartitionSpec)`` rules describes how the engine's
pytrees shard, `match_partition_rules` turns any pytree into a sharding
tree (loudly failing on unmatched leaves), and mesh.py derives every
in/out spec from it — so a single-host shard_map, a forced-multi-device
CPU mesh and a multi-host `jax.distributed` mesh are one data-driven code
path that differs only in the Mesh object (parallel/distributed.py builds
the multi-host one).

Layout, in one screen:

  * per-lane search state (SearchState: bt/nt/lane/hist_hash/
    hist_halfmove/moves/hist/pv/acc) — leading dim is the lane axis,
    sharded over ``dp``; trailing dims replicated.
  * NNUE weights (NnueParams) — replicated on every chip (`PARAM_RULES`),
    or tensor-sharded over an optional ``tp`` axis for the
    feature-transform width (`PARAM_RULES_TP`, the training layout).
  * transposition table (TTable.data, (ndev, N, 4)) — leading shard dim
    over ``dp``: each device hashes into its private shard.
  * boundary plumbing — per-lane ``tt_gen`` and splice ``mask`` shard
    with the lanes; the traced ``segment_steps`` scalar is replicated;
    per-shard ``steps`` and the packed boundary ``summary`` come back
    sharded over ``dp``.

fishnet-lint's `mesh-unregistered-spec` rule (lint/mesh_rules.py) pins
spec construction to this module + mesh.py, so a new sharded callable
cannot quietly fork the layout.
"""
from __future__ import annotations

import re
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# One rule: ('/'-joined pytree-path regex matched with re.search,
# PartitionSpec). First matching rule wins; order is specific → generic.
Rule = Tuple[str, P]


class UnmatchedLeafError(ValueError):
    """A pytree leaf reached the mesh boundary with no partition rule.

    Raised by match_partition_rules so an unregistered field fails at
    spec-derivation time with the offending paths named, instead of
    sailing through under some default layout and corrupting results
    (or deadlocking a multi-host mesh) at dispatch time."""


# --------------------------------------------------------------- registry

# per-lane search state: every SearchState field carries the lane batch
# as its leading dim, so all of them shard over dp and nothing else
STATE_RULES: Tuple[Rule, ...] = (
    (r"(^|/)(bt|nt|lane|hist_hash|hist_halfmove|moves|hist|pv|acc)$",
     P("dp")),
)

# transposition table: (ndev, N, 4) with the leading shard dim over dp —
# each device owns one private shard (parallel/mesh.make_sharded_table)
TT_RULES: Tuple[Rule, ...] = (
    (r"(^|/)data$", P("dp")),
)

# NNUE weights, search layout: replicated into every chip's HBM — the
# eval stack is tiny and the lanes are embarrassingly parallel
PARAM_RULES: Tuple[Rule, ...] = (
    (r"(^|/)(ft_w|ft_b|l1_w|l1_b|l2_w|l2_b|out_w|out_b)$", P()),
)

# NNUE weights, training layout: the gather-heavy feature transform
# splits its output width over tp; the small layer stack is replicated
# (models/train.py derives its param shardings from these)
PARAM_RULES_TP: Tuple[Rule, ...] = (
    (r"(^|/)ft_w$", P(None, "tp")),
    (r"(^|/)ft_b$", P("tp")),
    (r"(^|/)(l1_w|l1_b|l2_w|l2_b|out_w|out_b)$", P()),
)

# boundary plumbing of the segment/merge callables
AUX_RULES: Tuple[Rule, ...] = (
    (r"(^|/)tt_gen$", P("dp")),        # per-lane TT generation tags
    (r"(^|/)segment_steps$", P()),     # traced replicated scalar
    (r"(^|/)mask$", P("dp")),          # (B,) refill splice mask
    (r"(^|/)steps$", P("dp")),         # (ndev,) per-shard step counts
    (r"(^|/)summary$", P("dp", None, None)),  # stacked boundary summary
)

# the full search-side registry, in match order
SEARCH_RULES: Tuple[Rule, ...] = (
    STATE_RULES + TT_RULES + PARAM_RULES + AUX_RULES
)


# ------------------------------------------------------------ pytree paths


def iter_paths(tree: Any, prefix: str = "") -> List[Tuple[str, Any]]:
    """('/'-joined path, leaf) pairs in jax flatten order.

    NamedTuples contribute field names, dicts their (sorted) keys,
    sequences their indices; None subtrees are empty, matching the jax
    pytree convention — so the path list zips exactly against
    jax.tree_util.tree_flatten's leaves for the trees this engine moves
    (all NamedTuples/dicts/tuples of arrays)."""
    out: List[Tuple[str, Any]] = []

    def walk(node: Any, path: str) -> None:
        if node is None:
            return
        if hasattr(node, "_fields"):  # NamedTuple: field names
            for name, child in zip(node._fields, node):
                walk(child, f"{path}/{name}" if path else name)
        elif isinstance(node, dict):
            for name in sorted(node):
                walk(node[name], f"{path}/{name}" if path else str(name))
        elif isinstance(node, (list, tuple)):
            for i, child in enumerate(node):
                walk(child, f"{path}/{i}" if path else str(i))
        else:
            out.append((path, node))

    walk(tree, prefix)
    return out


def matching_rules(path: str,
                   rules: Sequence[Rule] = SEARCH_RULES) -> List[int]:
    """Indices of every rule whose regex matches this path (re.search)."""
    return [i for i, (pat, _) in enumerate(rules) if re.search(pat, path)]


def rename_axes(spec: P, axis_map: Dict[str, str]) -> P:
    """A PartitionSpec with mesh-axis names substituted — the registry
    speaks canonical 'dp'/'tp'; callables built over a differently-named
    axis rename at derivation time."""

    def sub(part):
        if part is None:
            return None
        if isinstance(part, (tuple, list)):
            return tuple(sub(p) for p in part)
        return axis_map.get(part, part)

    return P(*(sub(part) for part in spec))


# --------------------------------------------------------------- matching


def match_partition_rules(tree: Any, rules: Optional[Sequence[Rule]] = None,
                          *, prefix: str = "",
                          axis_map: Optional[Dict[str, str]] = None) -> Any:
    """A pytree of PartitionSpecs, same structure as `tree`.

    Each leaf takes the FIRST rule whose regex matches its '/'-joined
    path (0-d array leaves short-circuit to replicated `P()` — a scalar
    has no axis to shard). Leaves no rule matches raise
    UnmatchedLeafError naming every offender at once: an unregistered
    field is a layout decision nobody made, and the mesh boundary is
    where it must fail."""
    rules = SEARCH_RULES if rules is None else tuple(rules)
    paths = iter_paths(tree, prefix)
    specs: List[P] = []
    unmatched: List[str] = []
    for path, leaf in paths:
        if getattr(leaf, "ndim", None) == 0:
            specs.append(P())
            continue
        hit = matching_rules(path, rules)
        if hit:
            specs.append(rules[hit[0]][1])
        else:
            unmatched.append(path)
    if unmatched:
        raise UnmatchedLeafError(
            "no partition rule matches pytree leaf(s): "
            + ", ".join(repr(p) for p in unmatched)
            + " — register a (regex, PartitionSpec) rule in "
            "parallel/partition.py before moving this tree across a mesh"
        )
    treedef = jax.tree_util.tree_structure(tree)
    if treedef.num_leaves != len(specs):
        raise ValueError(
            f"path walk found {len(specs)} leaves but jax flattens "
            f"{treedef.num_leaves} — tree contains a custom pytree node "
            "iter_paths does not understand"
        )
    if axis_map:
        specs = [rename_axes(s, axis_map) for s in specs]
    return jax.tree_util.tree_unflatten(treedef, specs)


def validate_rules(tree: Any = None,
                   rules: Optional[Sequence[Rule]] = None,
                   *, prefix: str = "") -> Dict[str, int]:
    """Check every rule fires at least once on the real pytree.

    Returns {rule regex: first-match count}. A rule that never wins a
    leaf is dead weight — usually a renamed field or a shadowing earlier
    rule — and raises ValueError naming it. Unmatched leaves raise
    UnmatchedLeafError exactly as match_partition_rules would."""
    rules = SEARCH_RULES if rules is None else tuple(rules)
    if tree is None:
        tree = search_proto()
    counts = {pat: 0 for pat, _ in rules}
    unmatched: List[str] = []
    for path, leaf in iter_paths(tree, prefix):
        if getattr(leaf, "ndim", None) == 0:
            continue
        hit = matching_rules(path, rules)
        if hit:
            counts[rules[hit[0]][0]] += 1
        else:
            unmatched.append(path)
    if unmatched:
        raise UnmatchedLeafError(
            "no partition rule matches pytree leaf(s): "
            + ", ".join(repr(p) for p in unmatched)
        )
    dead = [pat for pat, n in counts.items() if n == 0]
    if dead:
        raise ValueError(
            "partition rule(s) never fire on the real pytree: "
            + ", ".join(repr(p) for p in dead)
            + " — stale regex or shadowed by an earlier rule"
        )
    return counts


# -------------------------------------------------------------- prototypes
#
# Spec derivation happens when a callable is BUILT (lru-cached per mesh
# config), before any real array exists — so the registry matches against
# prototype trees whose leaves are their own path strings. Field renames
# in the real NamedTuples flow into the prototypes automatically.


def state_proto():
    """A SearchState whose leaves are field-name strings."""
    from ..ops.search import SearchState

    return SearchState(*SearchState._fields)


def tt_proto():
    """A TTable whose leaves are field-name strings."""
    from ..ops.tt import TTable

    return TTable(*TTable._fields)


def param_proto():
    """An NnueParams whose leaves are field-name strings."""
    from ..models.nnue import NnueParams

    return NnueParams(*NnueParams._fields)


def search_proto() -> Dict[str, Any]:
    """Everything that crosses the mesh boundary, as one prototype tree —
    the default subject of validate_rules()."""
    return {
        "params": param_proto(),
        "state": state_proto(),
        "tt": tt_proto(),
        "tt_gen": "tt_gen",
        "segment_steps": "segment_steps",
        "mask": "mask",
        "steps": "steps",
        "summary": "summary",
    }


# ---------------------------------------------------------- derived specs


def _axis_map(axis: str) -> Optional[Dict[str, str]]:
    return None if axis == "dp" else {"dp": axis}


def state_specs(axis: str = "dp"):
    """SearchState-shaped tree of PartitionSpecs (lanes over `axis`)."""
    return match_partition_rules(state_proto(), axis_map=_axis_map(axis))


def tt_specs(axis: str = "dp"):
    """TTable-shaped tree of PartitionSpecs (shard dim over `axis`)."""
    return match_partition_rules(tt_proto(), axis_map=_axis_map(axis))


def param_specs(tp: bool = False):
    """NnueParams-shaped spec tree: replicated (search) or ft-width
    tensor-sharded over tp (training)."""
    rules = PARAM_RULES_TP if tp else PARAM_RULES
    return match_partition_rules(param_proto(), rules)


def spec_for(name: str, axis: str = "dp") -> P:
    """The registry's spec for one named boundary value (tt_gen, mask,
    segment_steps, steps, summary)."""
    tree = match_partition_rules({name: name}, axis_map=_axis_map(axis))
    return tree[name]


def segment_specs(has_tt: bool, axis: str = "dp"):
    """(in_specs, out_specs) of the shard_map'd search segment — the
    registry-derived replacement for mesh.py's old hand-built literals.

    Argument order mirrors parallel.mesh._segment_callable's seg():
    (params, state, ttab, segment_steps, tt_gen) →
    (state, ttab, steps, summary). A ttab-less build replicates the None
    placeholder."""
    tt = tt_specs(axis) if has_tt else P()
    in_specs = (
        param_specs(),
        state_specs(axis),
        tt,
        spec_for("segment_steps", axis),
        spec_for("tt_gen", axis),
    )
    out_specs = (
        state_specs(axis),
        tt,
        spec_for("steps", axis),
        spec_for("summary", axis),
    )
    return in_specs, out_specs


def merge_specs(axis: str = "dp"):
    """(in_specs, out_specs) of the shard_map'd masked lane merge:
    (state, fresh, mask) → state, everything lane-sharded."""
    st = state_specs(axis)
    return (st, st, spec_for("mask", axis)), st


def batch_spec(ndim: int, axis: str = "dp") -> P:
    """Leading-dim-sharded spec for a rank-`ndim` batched array — the
    placement rule behind mesh.shard_batch."""
    return P(axis, *([None] * (max(ndim, 1) - 1)))


def replicated_spec() -> P:
    return P()


def named_sharding(mesh: Mesh, spec: P) -> NamedSharding:
    """The one NamedSharding constructor the rest of the tree uses —
    keeps sharding objects flowing out of the registry (and keeps
    lint/mesh_rules.py's allow-list to this module + mesh.py)."""
    return NamedSharding(mesh, spec)


def default_topology() -> Dict[str, Any]:
    """The mesh topology this process would build: shape, axis names,
    process count — folded into the AOT store fingerprint (aot/keys.py)
    so a bundle packed on one topology is rejected-with-named-diff on
    another instead of deserializing garbage."""
    try:
        n_dev = len(jax.devices())
    except Exception:
        n_dev = 0
    try:
        n_proc = jax.process_count()
    except Exception:
        n_proc = 1
    return {
        "mesh_shape": str(n_dev),
        "mesh_axes": "dp",
        "process_count": n_proc,
    }
