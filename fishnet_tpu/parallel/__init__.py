"""Device-mesh parallelism: sharded search dispatch and training."""
