"""Counter/gauge/histogram registry with a Prometheus text endpoint.

One interface absorbing the ad-hoc counter piles that grew per
subsystem — SupervisorStats (engine/supervisor.py), SyncStats totals
(utils/syncstats.py), LaneScheduler occupancy totals (engine/tpu.py) —
so a single scrape (or one sqlite row via client/stats.py) sees the
whole stack. Two consumers:

- an opt-in stdlib-http endpoint serving Prometheus text exposition
  format 0.0.4 (FISHNET_TPU_METRICS_PORT; off by default, binds
  loopback only);
- `snapshot()`, a flat name→value dict the client folds into the
  existing sqlite StatsRecorder time series.

Pure stdlib, no JAX/numpy at module scope (same constraint as
obs/trace.py). All mutators take the registry lock — metrics are
updated at segment boundaries and summary ticks, never inside the
device hot loop, so a plain Lock is cheap enough.
"""
from __future__ import annotations

import re
import threading
from typing import Dict, List, Optional, Tuple

__all__ = [
    "REGISTRY",
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "SloRecorder",
    "serve",
    "serve_from_settings",
    "set_build_info",
]

_NAME_OK = re.compile(r"[^a-zA-Z0-9_:]")

# Milliseconds-oriented default buckets: segment boundaries run ~0.1 ms
# (CPU smoke) to seconds (cold compile); powers of ~2.5 cover the range
# in few buckets.
DEFAULT_BUCKETS = (
    1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0, 500.0,
    1000.0, 2500.0, 5000.0, 10000.0,
)


def _sanitize(name: str) -> str:
    out = _NAME_OK.sub("_", name)
    if not out or out[0].isdigit():
        out = "_" + out
    return out


class Counter:
    """Monotonically non-decreasing count."""

    kind = "counter"
    __slots__ = ("name", "help", "_lock", "_value")

    def __init__(self, name: str, help: str = "") -> None:
        self.name = name
        self.help = help
        self._lock = threading.Lock()
        self._value = 0.0

    def inc(self, n: float = 1.0) -> None:
        if n < 0:
            raise ValueError(f"counter {self.name} cannot decrease")
        with self._lock:
            self._value += n

    def set_total(self, total: float) -> None:
        """Absorb an externally-kept running total (SupervisorStats and
        occupancy totals keep their own counters; the registry mirrors
        them). Never moves backwards."""
        with self._lock:
            if total > self._value:
                self._value = total

    @property
    def value(self) -> float:
        with self._lock:
            return self._value

    def render(self) -> List[str]:
        return [f"{self.name} {_fmt(self.value)}"]

    def flatten(self) -> Dict[str, float]:
        return {self.name: self.value}


class Gauge:
    """Point-in-time value (occupancy share, queue depth, offsets)."""

    kind = "gauge"
    __slots__ = ("name", "help", "_lock", "_value")

    def __init__(self, name: str, help: str = "") -> None:
        self.name = name
        self.help = help
        self._lock = threading.Lock()
        self._value = 0.0

    def set(self, value: float) -> None:
        with self._lock:
            self._value = float(value)

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self._value += n

    @property
    def value(self) -> float:
        with self._lock:
            return self._value

    def render(self) -> List[str]:
        return [f"{self.name} {_fmt(self.value)}"]

    def flatten(self) -> Dict[str, float]:
        return {self.name: self.value}


class Histogram:
    """Cumulative-bucket histogram (Prometheus semantics: each bucket
    counts observations <= its upper bound, +Inf catches all)."""

    kind = "histogram"
    __slots__ = ("name", "help", "buckets", "_lock", "_counts",
                 "_sum", "_count")

    def __init__(self, name: str, help: str = "",
                 buckets: Optional[Tuple[float, ...]] = None) -> None:
        self.name = name
        self.help = help
        self.buckets = tuple(sorted(buckets or DEFAULT_BUCKETS))
        self._lock = threading.Lock()
        self._counts = [0] * len(self.buckets)
        self._sum = 0.0
        self._count = 0

    def observe(self, value: float) -> None:
        with self._lock:
            self._sum += value
            self._count += 1
            for i, ub in enumerate(self.buckets):
                if value <= ub:
                    self._counts[i] += 1
                    break

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    @property
    def sum(self) -> float:
        with self._lock:
            return self._sum

    def render(self) -> List[str]:
        with self._lock:
            counts = list(self._counts)
            total, sum_ = self._count, self._sum
        out: List[str] = []
        cum = 0
        for ub, c in zip(self.buckets, counts):
            cum += c
            out.append(f'{self.name}_bucket{{le="{_fmt(ub)}"}} {cum}')
        out.append(f'{self.name}_bucket{{le="+Inf"}} {total}')
        out.append(f"{self.name}_sum {_fmt(sum_)}")
        out.append(f"{self.name}_count {total}")
        return out

    def flatten(self) -> Dict[str, float]:
        return {
            f"{self.name}_sum": self.sum,
            f"{self.name}_count": float(self.count),
        }


def _fmt(v: float) -> str:
    # Integral values render without the trailing ".0" Prometheus text
    # tooling chokes on in le= labels.
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


class MetricsRegistry:
    """Get-or-create registry; creation is idempotent per (name, kind)
    and a kind clash raises instead of silently shadowing."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._metrics: Dict[str, object] = {}

    def _get(self, cls, name: str, help: str, **kwargs):
        name = _sanitize(name)
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = cls(name, help, **kwargs)
                self._metrics[name] = m
            elif not isinstance(m, cls):
                raise TypeError(
                    f"metric {name} already registered as {m.kind}, "
                    f"wanted {cls.kind}"
                )
            return m

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get(Counter, name, help)

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._get(Gauge, name, help)

    def histogram(self, name: str, help: str = "",
                  buckets: Optional[Tuple[float, ...]] = None) -> Histogram:
        return self._get(Histogram, name, help, buckets=buckets)

    def absorb_totals(self, prefix: str, totals: Dict[str, float],
                      kind: str = "counter") -> None:
        """Mirror an externally-kept dict of running totals (e.g.
        dataclasses.asdict(SupervisorStats), occupancy_totals) as
        prefixed counters/gauges. Non-numeric values are skipped."""
        for key, value in totals.items():
            if isinstance(value, bool) or not isinstance(value, (int, float)):
                continue
            name = f"{prefix}_{key}"
            if kind == "counter":
                self.counter(name).set_total(float(value))
            else:
                self.gauge(name).set(float(value))

    def snapshot(self) -> Dict[str, float]:
        """Flat name→value view for the sqlite fold-in (histograms
        flatten to _sum/_count)."""
        with self._lock:
            metrics = list(self._metrics.values())
        out: Dict[str, float] = {}
        for m in metrics:
            out.update(m.flatten())
        return out

    def render_prometheus(self) -> str:
        with self._lock:
            metrics = sorted(self._metrics.items())
        lines: List[str] = []
        for name, m in metrics:
            if m.help:
                lines.append(f"# HELP {name} {m.help}")
            lines.append(f"# TYPE {name} {m.kind}")
            lines.extend(m.render())
        return "\n".join(lines) + "\n"


class SloRecorder:
    """Per-tenant/per-kind SLO accounting for the serving stack.

    One observe() per finished request records its end-to-end latency
    and the same latency split into where the time went:

    - queue-wait: admission waiting room (deadline-ordered heap);
    - device: the engine's own search wall clock (the max across the
      request's positions — they run concurrently in the lane pool);
    - host: everything else — chunking, pipe hops, serde, the serve
      loop itself (total − queue − device, floored at zero).

    Metric names follow the serve stack's name-embedded label scheme
    (`fishnet_serve_latency_ms_<tenant>`): per (kind, tenant) —
    `fishnet_slo_latency_ms_<kind>_<tenant>` plus _queue_ms/_device_ms/
    _host_ms histograms and deadline_miss/shed/requests counters. The
    p50/p99 SLO tier (ROADMAP item 5) and bench.py's serve_slo row read
    these straight out of render_prometheus()/snapshot()."""

    __slots__ = ("registry", "prefix")

    def __init__(self, registry: Optional["MetricsRegistry"] = None,
                 prefix: str = "fishnet_slo") -> None:
        self.registry = registry if registry is not None else REGISTRY
        self.prefix = prefix

    def _hist(self, what: str, kind: str, tenant: str) -> Histogram:
        return self.registry.histogram(
            f"{self.prefix}_{what}_ms_{kind}_{tenant}",
            f"request {what} (ms) for kind {kind}, tenant {tenant}",
        )

    def _ctr(self, what: str, kind: str, tenant: str) -> Counter:
        return self.registry.counter(
            f"{self.prefix}_{what}_total_{kind}_{tenant}",
            f"{what} for kind {kind}, tenant {tenant}",
        )

    def observe(self, tenant: str, kind: str, total_ms: float,
                queue_ms: float = 0.0, device_ms: float = 0.0,
                deadline_missed: bool = False) -> None:
        queue_ms = max(0.0, min(queue_ms, total_ms))
        device_ms = max(0.0, min(device_ms, total_ms - queue_ms))
        host_ms = max(0.0, total_ms - queue_ms - device_ms)
        self._ctr("requests", kind, tenant).inc()
        self._hist("latency", kind, tenant).observe(total_ms)
        self._hist("queue", kind, tenant).observe(queue_ms)
        self._hist("device", kind, tenant).observe(device_ms)
        self._hist("host", kind, tenant).observe(host_ms)
        if deadline_missed:
            self._ctr("deadline_miss", kind, tenant).inc()

    def shed(self, tenant: str, kind: str) -> None:
        self._ctr("shed", kind, tenant).inc()


# The process-wide default registry every subsystem feeds.
REGISTRY = MetricsRegistry()


def set_build_info(info: Dict[str, object],
                   registry: Optional[MetricsRegistry] = None) -> Gauge:
    """The standard Prometheus build-info idiom, adapted to this
    registry's label-less model: a `fishnet_build_info` gauge pinned at
    1 whose identifying fields (git sha, jax/jaxlib versions, backend,
    device kind/count — collected by obs/perf.py build_info()) render
    in the HELP line of every /metrics scrape. The same dict is stamped
    into perf-ledger rows and trace dump metadata, so one scrape
    suffices to join a host's series across those surfaces."""
    reg = registry if registry is not None else REGISTRY
    help_text = " ".join(f"{k}={info[k]}" for k in sorted(info))
    g = reg.gauge("fishnet_build_info", help_text)
    g.help = help_text  # refresh if registered earlier with stale info
    g.set(1.0)
    return g


def serve(port: int, registry: Optional[MetricsRegistry] = None):
    """Start the /metrics endpoint on loopback in a daemon thread.

    port > 0 binds that port; port == 0 binds an OS-assigned ephemeral
    port (tests — read server.server_address[1]); port < 0 is off.
    Returns the ThreadingHTTPServer, or None when off.
    """
    if port < 0:
        return None
    reg = registry if registry is not None else REGISTRY

    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    class Handler(BaseHTTPRequestHandler):
        def do_GET(self) -> None:  # noqa: N802 (stdlib handler API)
            if self.path.split("?", 1)[0] not in ("/", "/metrics"):
                self.send_error(404)
                return
            body = reg.render_prometheus().encode("utf-8")
            self.send_response(200)
            self.send_header(
                "Content-Type", "text/plain; version=0.0.4; charset=utf-8"
            )
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, fmt: str, *args) -> None:
            pass  # scrapes must not spam the engine's stderr heartbeat

    server = ThreadingHTTPServer(("127.0.0.1", port), Handler)
    thread = threading.Thread(
        target=server.serve_forever, daemon=True, name="metrics-http"
    )
    thread.start()
    return server


def serve_from_settings(registry: Optional[MetricsRegistry] = None):
    """Start the endpoint iff FISHNET_TPU_METRICS_PORT is a positive
    port; the registry default 0 keeps it off."""
    from ..utils import settings

    port = settings.get_int("FISHNET_TPU_METRICS_PORT")
    if port <= 0:
        return None
    return serve(port, registry)
