"""Live in-flight request registry: what is the serving stack doing
RIGHT NOW, per request.

The trace ring answers "where did request X spend its 900 ms" after the
fact; this registry answers "where is request X right now" while it is
still in flight — the stage it has reached (received → admitted →
dispatched → lane → done), the lanes its positions occupy, its age and
its remaining deadline slack. `GET /debug/requests` on the serve server
and the `fishnet-tpu inflight` CLI both render snapshot().

Keyed by trace_id: the serve edge begin()s an entry when it stamps the
request context, every later hop that still runs in the same process
(admission, chunk dispatch, the LaneScheduler's splice/boundary path)
updates it by the trace_id riding the context, and the edge end()s it
when the response leaves. Hops in OTHER processes (a supervised engine
host child) update their own process-local registry — which nobody
serves — so their writes are harmless no-ops from the operator's point
of view; stage granularity at the serve surface is whatever ran
in-process, which for the python/in-process backends includes lanes.

Always on: entries are a few dict writes per request, so there is no
enable switch to forget. Unknown trace_ids are ignored (the lichess
client path stamps contexts nobody begin()s). Pure stdlib.
"""
from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional

from ..utils import sanitize

__all__ = ["InflightRegistry", "REGISTRY"]

# Stage ordering for the coarse request-level stage: position updates
# never move a request backwards (a replayed position re-entering
# "queued" must not hide that the request had reached the lanes).
_STAGE_ORDER = (
    "received", "admitted", "dispatched", "queued", "lane", "delivered",
    "done",
)
_STAGE_RANK = {s: i for i, s in enumerate(_STAGE_ORDER)}


class InflightRegistry:
    """Thread-safe map of trace_id → live request state."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._entries: Dict[str, dict] = {}
        # FISHNET_TPU_SANITIZE, captured once: an unknown stage label is
        # a typo'd call site that would silently rank 0 and vanish from
        # the ordering — strict mode rejects it. Backward stage moves
        # stay CLAMPED, never raised: re-dispatch after member loss
        # legitimately replays positions through earlier stages.
        self._strict = sanitize.enabled()

    def begin(self, trace_id: str, req_id: str, tenant: str, kind: str,
              deadline_mono_s: Optional[float] = None,
              n_positions: int = 0) -> None:
        if not trace_id:
            return
        with self._lock:
            self._entries[trace_id] = {
                "trace_id": trace_id,
                "id": req_id,
                "tenant": tenant,
                "kind": kind,
                "stage": "received",
                "t0_mono_s": time.monotonic(),
                "deadline_mono_s": deadline_mono_s,
                "n_positions": int(n_positions),
                "positions": {},
            }

    def stage(self, trace_id: Optional[str], stage: str) -> None:
        if not trace_id:
            return
        if self._strict and stage not in _STAGE_RANK:
            raise sanitize.SanitizeError(
                f"sanitize[obs/inflight.py::stage]: unknown stage label "
                f"{stage!r} (known: {', '.join(_STAGE_ORDER)})"
            )
        with self._lock:
            entry = self._entries.get(trace_id)
            if entry is None:
                return
            if _STAGE_RANK.get(stage, 0) >= _STAGE_RANK.get(
                    entry["stage"], 0):
                entry["stage"] = stage

    def position(self, trace_id: Optional[str], pos_index: int,
                 stage: str, lane: Optional[int] = None) -> None:
        """Per-position progress from the LaneScheduler: the position's
        own stage plus the lane it occupies once spliced."""
        if not trace_id:
            return
        if self._strict and stage not in _STAGE_RANK:
            raise sanitize.SanitizeError(
                f"sanitize[obs/inflight.py::position]: unknown stage label "
                f"{stage!r} (known: {', '.join(_STAGE_ORDER)})"
            )
        with self._lock:
            entry = self._entries.get(trace_id)
            if entry is None:
                return
            entry["positions"][int(pos_index)] = {
                "stage": stage,
                "lane": lane,
            }
            if _STAGE_RANK.get(stage, 0) > _STAGE_RANK.get(
                    entry["stage"], 0):
                entry["stage"] = stage

    def end(self, trace_id: Optional[str]) -> None:
        if not trace_id:
            return
        with self._lock:
            self._entries.pop(trace_id, None)

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def snapshot(self) -> List[dict]:
        """JSON-safe copies with derived age/slack, oldest first."""
        now = time.monotonic()
        with self._lock:
            entries = [
                (e, dict(e, positions=dict(e["positions"])))
                for e in self._entries.values()
            ]
        out: List[dict] = []
        for src, e in sorted(entries, key=lambda p: p[0]["t0_mono_s"]):
            deadline = e.pop("deadline_mono_s")
            t0 = e.pop("t0_mono_s")
            e["age_ms"] = round((now - t0) * 1e3, 1)
            e["slack_ms"] = (
                round((deadline - now) * 1e3, 1)
                if deadline is not None else None
            )
            e["lanes"] = sorted({
                p["lane"] for p in e["positions"].values()
                if p.get("lane") is not None
            })
            e["positions"] = {
                str(k): v for k, v in sorted(e["positions"].items())
            }
            out.append(e)
        return out


# Process-local singleton; the serve server and the in-process scheduler
# share it, child processes each get their own inert copy.
REGISTRY = InflightRegistry()
