"""fishnet-tpu observability: one timeline, one metrics surface.

Two modules, both zero-dependency (pure stdlib — no JAX, no numpy at
module scope, same import constraint as utils/settings.py): they are
imported by conftest, the linter, and the engine host child before JAX
initializes, and must never drag device runtime into a process that only
wants to read a trace dump.

- `obs.trace`: thread-safe bounded ring-buffer recorder (span context
  managers, instant events, counter samples on `time.monotonic`),
  exported as Chrome trace-event JSON that loads in Perfetto or
  `chrome://tracing`. The engine host records its own ring and streams
  it to the supervisor over the frames protocol; ClockSync maps the
  child's monotonic clock onto the parent's so the merged file shows
  `queue.acquire` → `supervisor.dispatch` → host `search` spans with the
  SyncStats device/host split as children of each segment.
- `obs.metrics`: counter/gauge/histogram registry absorbing the ad-hoc
  counters (SupervisorStats, SyncStats totals, LaneScheduler occupancy
  totals), rendered as Prometheus text over an opt-in stdlib-http
  endpoint (FISHNET_TPU_METRICS_PORT) and folded into the sqlite
  StatsRecorder time series.

Tracing is OFF by default: `trace.RECORDER` is None and every
instrumentation site costs one attribute load + one `is None` check —
no events, no allocations, no context managers. See docs/observability.md.
"""
from . import metrics, trace  # noqa: F401
