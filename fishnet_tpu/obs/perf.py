"""fishnet-perf: the persistent performance ledger + program cost accounting.

Point-in-time observability (the trace timeline, the SLO histograms)
answers "where did this run spend its time"; this module answers the
longitudinal question — "is this build faster or slower than the last
twenty" — which nothing in the repo could answer before: BENCH_rNN.json
artifacts were written by the bench driver and never compared.

Three pieces:

- **PerfLedger** — a sqlite ``perf_ledger`` table (one row per
  (run, bench row, metric)) keyed on git sha + the AOT store
  fingerprint digest (aot/keys.py), so values measured under different
  jax/backend/topology/settings envelopes are never gated against each
  other. The schema/insert helpers are shared with the client's
  stats.db sink (client/stats.py ensure_perf_table/record_perf) so one
  sqlite file can carry both time series. ``backfill()`` ingests the
  checked-in ``BENCH_r01–r05.json`` + ``MULTICHIP_r*.json`` artifacts
  (idempotently — stable run ids + INSERT OR REPLACE), so trend history
  starts populated; ``emit_bench_round()`` writes the next
  ``BENCH_rNN.json`` from the ledger instead of by hand.

- **Program cost accounting** — ``program_cost(compiled)`` reads
  ``cost_analysis()`` FLOPs/bytes-accessed and ``memory_analysis()``
  sizes off an AOT-compiled executable; ``record_program_cost`` exports
  them as ``fishnet_program_*`` gauges. Capture sites are the places a
  Compiled object already exists (bench.py's precompile, the AOT
  registry's export path) — never an extra compile.

- **build_info()** — git sha + jax/jaxlib versions + backend + device
  kind/count, registered as the ``fishnet_build_info`` gauge (value 1,
  fields in the HELP line — the registry has no label system), stamped
  into every ledger row and into trace dump metadata: the join key for
  cross-host comparison.

Pure stdlib at module scope (same constraint as obs/metrics.py and
obs/trace.py): jax and the settings registry are imported lazily inside
functions and every capture degrades to a no-op when they are absent.
tools/perf_report.py holds the direction table and the regression
detector that reads this ledger; docs/perf.md is the contract.
"""
from __future__ import annotations

import json
import os
import re
import sqlite3
import subprocess
import time
from typing import Any, Dict, List, Optional, Tuple

__all__ = [
    "PERF_TABLE_SQL",
    "PerfLedger",
    "backfill_rows_from_artifacts",
    "build_info",
    "default_ledger_path",
    "ensure_perf_table",
    "env_fingerprint",
    "flatten_result",
    "insert_perf_rows",
    "program_cost",
    "record_program_cost",
    "register_build_info",
    "split_mesh_rows",
    "live_snapshot",
]

# One row per (run, bench row, metric). `seq` orders runs within one
# ledger (assigned at insert: max+1); the UNIQUE key + INSERT OR
# REPLACE make re-ingesting the same run id (backfill re-runs) a no-op
# rather than a duplicate series.
PERF_TABLE_SQL = (
    "CREATE TABLE IF NOT EXISTS perf_ledger ("
    " id INTEGER PRIMARY KEY AUTOINCREMENT,"
    " run_id TEXT NOT NULL,"
    " seq INTEGER NOT NULL,"
    " timestamp INTEGER NOT NULL,"
    " git_sha TEXT NOT NULL DEFAULT '',"
    " fingerprint TEXT NOT NULL DEFAULT '',"
    " build_info TEXT NOT NULL DEFAULT '{}',"
    " source TEXT NOT NULL DEFAULT 'bench',"
    " bench_row TEXT NOT NULL,"
    " metric TEXT NOT NULL,"
    " value REAL NOT NULL,"
    " UNIQUE (run_id, bench_row, metric))"
)

_BENCH_ARTIFACT_RE = re.compile(r"^BENCH_r(\d+)\.json$")
_MULTICHIP_ARTIFACT_RE = re.compile(r"^MULTICHIP_r(\d+)\.json$")
_CONFIG_LINE_RE = re.compile(r"^bench config ([A-Za-z0-9_.\-]+): (\{.*)$")
_SEARCH_NODES_RE = re.compile(r"search nodes (\d+)")

_build_info_cache: Optional[Dict[str, Any]] = None


# --------------------------------------------------------------- build info


def repo_root() -> Optional[str]:
    """The checkout root (the directory holding bench.py), or None when
    running from an installed/zipped package."""
    root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    if os.path.isfile(os.path.join(root, "bench.py")):
        return root
    return None


def git_sha(short: int = 12) -> str:
    root = repo_root()
    if root is None:
        return ""
    try:
        out = subprocess.run(
            ["git", "-C", root, "rev-parse", f"--short={short}", "HEAD"],
            stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
            text=True, timeout=10.0,
        )
    except (OSError, subprocess.SubprocessError):
        return ""
    return out.stdout.strip() if out.returncode == 0 else ""


def build_info(refresh: bool = False) -> Dict[str, Any]:
    """git sha + jax/jaxlib versions + backend + device kind/count.
    Degrades field-by-field (empty strings / zero) with no JAX or no
    git — callable from pure-stdlib contexts."""
    global _build_info_cache
    if _build_info_cache is not None and not refresh:
        return dict(_build_info_cache)
    info: Dict[str, Any] = {
        "git_sha": git_sha(),
        "jax": "",
        "jaxlib": "",
        "backend": "",
        "device_kind": "",
        "device_count": 0,
    }
    try:
        import jax

        info["jax"] = str(jax.__version__)
        try:
            import jaxlib

            info["jaxlib"] = str(getattr(jaxlib, "__version__", ""))
        except Exception:
            pass
        info["backend"] = str(jax.default_backend())
        devs = jax.devices()
        info["device_kind"] = devs[0].device_kind if devs else ""
        info["device_count"] = len(devs)
    except Exception:
        pass
    _build_info_cache = dict(info)
    return info


def register_build_info(registry=None) -> Dict[str, Any]:
    """Register the ``fishnet_build_info`` gauge (value 1; the
    identifying fields ride in the HELP line — standard Prometheus
    build-info practice, minus the label system this registry doesn't
    have). Returns the info dict."""
    info = build_info()
    from . import metrics as obs_metrics  # lazy: avoid cycles
    obs_metrics.set_build_info(info, registry=registry)
    return info


def env_fingerprint() -> str:
    """The AOT store fingerprint digest (aot/keys.py) truncated to 12
    hex chars — the env compatibility envelope a ledger row was
    measured under. Empty string when JAX is unavailable (rows without
    a fingerprint are compared report-only, never gated)."""
    try:
        from ..aot import keys

        return keys.fingerprint_digest(keys.store_fingerprint())[:12]
    except Exception:
        return ""


# ----------------------------------------------------------------- flatten


def flatten_result(result: Dict[str, Any],
                   prefix: str = "") -> Dict[str, float]:
    """One bench RESULT dict → flat metric→value rows. Nested dicts
    (occupancy summaries, per-ndev tables) flatten to dotted names;
    strings and lists are skipped (a list's aggregate belongs in the
    RESULT row itself, e.g. mean_live_occupancy next to
    shard_live_occupancy)."""
    out: Dict[str, float] = {}
    for k, v in result.items():
        key = f"{prefix}{k}"
        if isinstance(v, bool):
            out[key] = 1.0 if v else 0.0
        elif isinstance(v, (int, float)):
            out[key] = float(v)
        elif isinstance(v, dict):
            out.update(flatten_result(v, prefix=key + "."))
    return out


# ------------------------------------------------------------------ ledger


def default_ledger_path() -> str:
    """FISHNET_TPU_PERF_LEDGER if set; else perf_ledger.db at the
    checkout root; else under ~/.cache/fishnet-tpu."""
    try:
        from ..utils import settings

        configured = settings.get_str("FISHNET_TPU_PERF_LEDGER")
    except Exception:
        configured = ""
    if configured:
        return configured
    root = repo_root()
    if root is not None:
        return os.path.join(root, "perf_ledger.db")
    return os.path.join(
        os.path.expanduser("~"), ".cache", "fishnet-tpu", "perf_ledger.db"
    )


def ensure_perf_table(db: sqlite3.Connection) -> None:
    db.execute(PERF_TABLE_SQL)


def insert_perf_rows(
    db: sqlite3.Connection,
    run_id: str,
    rows: Dict[str, Dict[str, float]],
    *,
    source: str = "bench",
    sha: Optional[str] = None,
    fingerprint: Optional[str] = None,
    info: Optional[Dict[str, Any]] = None,
    timestamp: Optional[int] = None,
) -> int:
    """Shared insert used by PerfLedger and the client's StatsRecorder
    sink. `rows` maps bench_row → {metric: value}. Returns rows
    written. Re-inserting an existing run_id replaces its values and
    keeps its seq (idempotent backfill)."""
    ensure_perf_table(db)
    cur = db.execute(
        "SELECT seq FROM perf_ledger WHERE run_id = ? LIMIT 1", (run_id,)
    ).fetchone()
    if cur is not None:
        seq = int(cur[0])
    else:
        top = db.execute("SELECT MAX(seq) FROM perf_ledger").fetchone()
        seq = (int(top[0]) + 1) if top and top[0] is not None else 1
    if sha is None:
        sha = git_sha()
    if fingerprint is None:
        fingerprint = env_fingerprint()
    info_json = json.dumps(info or {}, sort_keys=True)
    if timestamp is None:
        # report timestamp correlated with external logs — wall clock
        # is the sanctioned form here (same idiom as client/stats.py)
        timestamp = int(time.time())  # fishnet-lint: disable=obs-wall-clock
    n = 0
    for bench_row, metrics in rows.items():
        for metric, value in sorted(metrics.items()):
            db.execute(
                "INSERT OR REPLACE INTO perf_ledger"
                " (run_id, seq, timestamp, git_sha, fingerprint,"
                "  build_info, source, bench_row, metric, value)"
                " VALUES (?, ?, ?, ?, ?, ?, ?, ?, ?, ?)",
                (run_id, seq, timestamp, sha, fingerprint, info_json,
                 source, bench_row, metric, float(value)),
            )
            n += 1
    db.commit()
    return n


class PerfLedger:
    """One sqlite perf ledger. All readers/writers go through here (or
    through the same helpers on the client's stats.db connection)."""

    def __init__(self, db: sqlite3.Connection, path: str = "") -> None:
        self.db = db
        self.path = path
        ensure_perf_table(db)
        db.commit()

    @classmethod
    def open(cls, path: Optional[str] = None) -> "PerfLedger":
        """Open (creating if needed) the ledger at `path` / the default
        path; falls back to an in-memory ledger when the path is
        unwritable (a read-only checkout must never crash bench)."""
        p = path or default_ledger_path()
        try:
            os.makedirs(os.path.dirname(p) or ".", exist_ok=True)
            db = sqlite3.connect(p)
            return cls(db, p)
        except (OSError, sqlite3.Error):
            return cls(sqlite3.connect(":memory:"), ":memory:")

    def close(self) -> None:
        try:
            self.db.close()
        except sqlite3.Error:
            pass

    # ------------------------------------------------------------ write

    def ingest_run(self, run_id: str, rows: Dict[str, Dict[str, float]],
                   **kw: Any) -> int:
        return insert_perf_rows(self.db, run_id, rows, **kw)

    def ingest_results(self, run_id: str, results: Dict[str, Any],
                       **kw: Any) -> int:
        """Raw bench RESULT dicts (bench_row → RESULT json) → one
        ledger run: per-ndev tables split into their own rows, nested
        summaries flattened to dotted metric names."""
        rows: Dict[str, Dict[str, float]] = {}
        for name, res in results.items():
            if not isinstance(res, dict):
                continue
            rest = split_mesh_rows(rows, name, res)
            flat = flatten_result(rest)
            if flat:
                rows[name] = flat
        if not rows:
            return 0
        return self.ingest_run(run_id, rows, **kw)

    def backfill(self, root: Optional[str] = None) -> int:
        """Ingest the checked-in BENCH_r*.json + MULTICHIP_r*.json
        artifacts. Stable run ids (`backfill:BENCH_r03`) + REPLACE
        semantics make this idempotent. Backfilled rows carry no env
        fingerprint — the detector compares them report-only."""
        root = root or repo_root()
        if root is None:
            return 0
        n = 0
        for name, rows in backfill_rows_from_artifacts(root):
            n += self.ingest_run(
                f"backfill:{name}", rows, source="backfill",
                sha="", fingerprint="", info={"artifact": name},
            )
        return n

    # ------------------------------------------------------------- read

    def runs(self) -> List[Dict[str, Any]]:
        """Every run, ordered by seq: run_id/seq/timestamp/git_sha/
        fingerprint/source plus its row count."""
        try:
            cur = self.db.execute(
                "SELECT run_id, seq, MIN(timestamp), MIN(git_sha),"
                " MIN(fingerprint), MIN(source), COUNT(*)"
                " FROM perf_ledger GROUP BY run_id, seq ORDER BY seq"
            )
        except sqlite3.Error:
            return []
        return [
            {"run_id": r[0], "seq": int(r[1]), "timestamp": int(r[2]),
             "git_sha": r[3], "fingerprint": r[4], "source": r[5],
             "metrics": int(r[6])}
            for r in cur.fetchall()
        ]

    def latest_run(self) -> Optional[Dict[str, Any]]:
        runs = self.runs()
        return runs[-1] if runs else None

    def run_metrics(self, run_id: str) -> Dict[str, Dict[str, float]]:
        out: Dict[str, Dict[str, float]] = {}
        try:
            cur = self.db.execute(
                "SELECT bench_row, metric, value FROM perf_ledger"
                " WHERE run_id = ? ORDER BY bench_row, metric", (run_id,)
            )
        except sqlite3.Error:
            return out
        for bench_row, metric, value in cur.fetchall():
            out.setdefault(bench_row, {})[metric] = float(value)
        return out

    def history(self, bench_row: str, metric: str, *,
                fingerprint: Optional[str] = None,
                before_seq: Optional[int] = None,
                limit: int = 20) -> List[Tuple[int, float]]:
        """(seq, value) series for one metric, oldest first — the
        rolling-baseline input. With `fingerprint`, only runs measured
        under that exact env envelope count."""
        q = ("SELECT seq, value FROM perf_ledger"
             " WHERE bench_row = ? AND metric = ?")
        args: List[Any] = [bench_row, metric]
        if fingerprint is not None:
            q += " AND fingerprint = ?"
            args.append(fingerprint)
        if before_seq is not None:
            q += " AND seq < ?"
            args.append(before_seq)
        q += " ORDER BY seq DESC LIMIT ?"
        args.append(limit)
        try:
            rows = self.db.execute(q, args).fetchall()
        except sqlite3.Error:
            return []
        return [(int(s), float(v)) for s, v in reversed(rows)]

    # ----------------------------------------------------- BENCH emission

    def next_round(self, root: Optional[str] = None) -> int:
        root = root or repo_root() or "."
        top = 0
        try:
            names = os.listdir(root)
        except OSError:
            names = []
        for name in names:
            m = _BENCH_ARTIFACT_RE.match(name)
            if m:
                top = max(top, int(m.group(1)))
        return top + 1

    def emit_bench_round(self, run_id: str,
                         root: Optional[str] = None) -> Optional[str]:
        """Write the next BENCH_rNN.json from this ledger run: the same
        artifact shape the bench driver recorded by hand for r01–r05
        (n/rc/tail/parsed), plus build-info + env fingerprint and the
        full per-row metric table."""
        root = root or repo_root()
        if root is None:
            return None
        rows = self.run_metrics(run_id)
        if not rows:
            return None
        meta = next(
            (r for r in self.runs() if r["run_id"] == run_id), None)
        headline = rows.get("headline", {})
        tail_lines = [
            f"bench config {name}: {json.dumps(metrics, sort_keys=True)}"
            for name, metrics in sorted(rows.items()) if name != "headline"
        ]
        parsed = {
            "metric": "batched alpha-beta+NNUE nodes/sec/chip",
            "value": headline.get("value", 0.0),
            "unit": "nodes/sec",
            "vs_baseline": headline.get("vs_baseline", 0.0),
        } if headline else None
        if parsed is not None:
            tail_lines.append(json.dumps(parsed))
        n = self.next_round(root)
        artifact = {
            "n": n,
            "cmd": "perf-ledger",
            "rc": 0,
            "run_id": run_id,
            "git_sha": (meta or {}).get("git_sha", ""),
            "fingerprint": (meta or {}).get("fingerprint", ""),
            "build_info": build_info(),
            "rows": rows,
            "tail": "\n".join(tail_lines) + "\n",
            "parsed": parsed,
        }
        path = os.path.join(root, f"BENCH_r{n:02d}.json")
        try:
            tmp = path + ".tmp"
            with open(tmp, "w", encoding="utf-8") as fh:
                json.dump(artifact, fh, indent=1)
            os.replace(tmp, path)
        except OSError:
            return None
        return path


# ---------------------------------------------------------------- backfill


def backfill_rows_from_artifacts(
        root: str) -> List[Tuple[str, Dict[str, Dict[str, float]]]]:
    """(artifact name, bench_row → metrics) per checked-in artifact,
    in round order — BENCH_r*.json first, then MULTICHIP_r*.json."""
    out: List[Tuple[str, Dict[str, Dict[str, float]]]] = []
    try:
        names = os.listdir(root)
    except OSError:
        return out
    bench = sorted(
        (int(m.group(1)), n) for n in names
        if (m := _BENCH_ARTIFACT_RE.match(n))
    )
    multi = sorted(
        (int(m.group(1)), n) for n in names
        if (m := _MULTICHIP_ARTIFACT_RE.match(n))
    )
    for _, name in bench:
        rows = _parse_bench_artifact(os.path.join(root, name))
        if rows:
            out.append((os.path.splitext(name)[0], rows))
    for _, name in multi:
        rows = _parse_multichip_artifact(os.path.join(root, name))
        if rows:
            out.append((os.path.splitext(name)[0], rows))
    return out


def _load_json(path: str) -> Optional[dict]:
    try:
        with open(path, "r", encoding="utf-8") as fh:
            obj = json.load(fh)
    except (OSError, ValueError):
        return None
    return obj if isinstance(obj, dict) else None


def _parse_bench_artifact(path: str) -> Dict[str, Dict[str, float]]:
    """One driver BENCH_rNN.json → bench rows. The tail text holds
    `bench config NAME: {json}` lines (one per matrix row) and the
    final stdout headline JSON; `parsed` (when the driver captured it)
    holds the same headline. Ledger-emitted artifacts (this module's
    own emission) carry an explicit `rows` table and are read directly."""
    obj = _load_json(path)
    if obj is None:
        return {}
    rows: Dict[str, Dict[str, float]] = {}
    if isinstance(obj.get("rows"), dict):
        for name, metrics in obj["rows"].items():
            if isinstance(metrics, dict):
                flat = flatten_result(metrics)
                if flat:
                    rows[str(name)] = flat
        return rows
    tail = obj.get("tail") or ""
    for line in str(tail).splitlines():
        m = _CONFIG_LINE_RE.match(line.strip())
        if m:
            try:
                res = json.loads(m.group(2))
            except ValueError:
                continue
            if isinstance(res, dict):
                flat = flatten_result(split_mesh_rows(rows, m.group(1),
                                                      res))
                if flat:
                    rows[m.group(1)] = flat
            continue
        stripped = line.strip()
        if stripped.startswith("{") and '"metric"' in stripped:
            try:
                head = json.loads(stripped)
            except ValueError:
                continue
            if isinstance(head, dict) and "value" in head:
                rows["headline"] = flatten_result(
                    {k: head[k] for k in ("value", "vs_baseline")
                     if k in head})
    parsed = obj.get("parsed")
    if "headline" not in rows and isinstance(parsed, dict) \
            and "value" in parsed:
        rows["headline"] = flatten_result(
            {k: parsed[k] for k in ("value", "vs_baseline") if k in parsed})
    if not rows and "rc" in obj:
        # a failed/timed-out round (BENCH_r01/r02 in the checked-in
        # history) still ingests: its exit code is the whole story
        rows["artifact"] = {"rc": float(obj.get("rc") or 0)}
    return rows


def split_mesh_rows(rows: Dict[str, Dict[str, float]], name: str,
                    res: dict) -> dict:
    """A mesh-scaling-shaped result (its "ndev" key maps device count →
    per-count RESULT row) becomes one bench row per device count — the
    deterministic scaling gate wants per-ndev series, not dotted names.
    Everything else passes through untouched. (A stage's own RESULT
    carries "ndev" as an int, which this deliberately ignores.)"""
    ndev = res.get("ndev")
    if isinstance(ndev, dict):
        for count, row in ndev.items():
            if isinstance(row, dict):
                flat = flatten_result(row)
                if flat:
                    rows[f"{name}_ndev{count}"] = flat
        return {k: v for k, v in res.items() if k != "ndev"}
    return res


def _parse_multichip_artifact(path: str) -> Dict[str, Dict[str, float]]:
    """One MULTICHIP_rNN.json ({n_devices, rc, ok, skipped, tail}) →
    a single row: ok flag + dry-run search nodes when present."""
    obj = _load_json(path)
    if obj is None or obj.get("skipped"):
        return {}
    metrics: Dict[str, float] = {
        "ok": 1.0 if obj.get("ok") else 0.0,
        "rc": float(obj.get("rc") or 0),
    }
    m = _SEARCH_NODES_RE.search(str(obj.get("tail") or ""))
    if m:
        metrics["nodes"] = float(m.group(1))
    ndev = obj.get("n_devices") or 0
    return {f"multichip_ndev{ndev}": metrics}


# -------------------------------------------------------- program costs


def program_cost(compiled: Any) -> Dict[str, float]:
    """FLOPs / bytes-accessed / memory sizes off one jax Compiled
    object. Tolerates every historical cost_analysis() return shape
    (dict, or a one-element list of dicts) and missing analyses
    (backends without implementations return {} fields)."""
    out: Dict[str, float] = {}
    try:
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        if isinstance(ca, dict):
            flops = ca.get("flops")
            if isinstance(flops, (int, float)):
                out["flops"] = float(flops)
            nbytes = ca.get("bytes accessed")
            if isinstance(nbytes, (int, float)):
                out["bytes_accessed"] = float(nbytes)
    except Exception:
        pass
    try:
        ma = compiled.memory_analysis()
        for metric, attr in (
            ("peak_bytes", "temp_size_in_bytes"),
            ("argument_bytes", "argument_size_in_bytes"),
            ("output_bytes", "output_size_in_bytes"),
            ("code_bytes", "generated_code_size_in_bytes"),
        ):
            v = getattr(ma, attr, None)
            if isinstance(v, (int, float)):
                out[metric] = float(v)
    except Exception:
        pass
    return out


def _program_slug(name: str) -> str:
    slug = re.sub(r"[^a-z0-9_]", "_", name.lower()).strip("_")
    return slug or "unnamed"


def record_program_cost(name: str, compiled: Any,
                        registry=None) -> Dict[str, float]:
    """Export one program's cost/memory analysis as fishnet_program_*
    gauges (name-embedded program label, the registry's idiom) and
    return the cost dict for ledger ingestion. Never raises."""
    cost = program_cost(compiled)
    if not cost:
        return cost
    try:
        if registry is None:
            from .metrics import REGISTRY as registry
        slug = _program_slug(name)
        for metric, value in cost.items():
            registry.gauge(
                f"fishnet_program_{metric}_{slug}",
                f"cost_analysis/memory_analysis {metric} for "
                f"program {name}",
            ).set(value)
    except Exception:
        pass
    return cost


# ------------------------------------------------------------ live surface


_SNAPSHOT_PREFIXES = (
    "fishnet_occupancy", "fishnet_lanes", "fishnet_queue",
    "fishnet_boundary", "fishnet_cache", "fishnet_serve_inflight",
    "fishnet_serve_queued", "fishnet_fleet_members", "fishnet_compile",
    "fishnet_autoscale_members",
)


def live_snapshot(registry=None,
                  ledger_path: Optional[str] = None) -> Dict[str, Any]:
    """The /debug/perf payload: build info, the per-program cost table,
    the perf-relevant slice of the metrics registry, and the last
    ledger run as the baseline column."""
    if registry is None:
        from .metrics import REGISTRY as registry
    snap = registry.snapshot()
    programs: Dict[str, Dict[str, float]] = {}
    metrics: Dict[str, float] = {}
    for name, value in sorted(snap.items()):
        if name.startswith("fishnet_program_"):
            rest = name[len("fishnet_program_"):]
            for metric in ("flops", "bytes_accessed", "peak_bytes",
                           "argument_bytes", "output_bytes", "code_bytes"):
                if rest.startswith(metric + "_"):
                    prog = rest[len(metric) + 1:]
                    programs.setdefault(prog, {})[metric] = value
                    break
        elif name.startswith(_SNAPSHOT_PREFIXES):
            metrics[name] = value
    cache_hits = snap.get("fishnet_cache_hits", 0.0)
    cache_misses = snap.get("fishnet_cache_misses", 0.0)
    looked = cache_hits + cache_misses
    baseline: Optional[Dict[str, Any]] = None
    path: Optional[str] = ledger_path or default_ledger_path()
    if path != ":memory:" and not os.path.exists(path):
        path = None  # a debug read must not create the ledger
    try:
        ledger = PerfLedger.open(path) if path is not None else None
        if ledger is None:
            raise OSError("no ledger")
        try:
            last = ledger.latest_run()
            if last is not None:
                baseline = dict(last)
                baseline["rows"] = ledger.run_metrics(last["run_id"])
        finally:
            ledger.close()
    except Exception:
        baseline = None
    return {
        "build": build_info(),
        "fingerprint": env_fingerprint(),
        "programs": programs,
        "metrics": metrics,
        "cache_hit_ratio": round(cache_hits / looked, 4) if looked else None,
        "baseline": baseline,
    }
