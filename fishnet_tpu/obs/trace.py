"""Thread-safe ring-buffer tracing on the monotonic clock.

The recorder is a bounded deque of Chrome trace-event dicts (the JSON
format Perfetto and chrome://tracing load natively): complete events
("ph": "X") for spans, instants ("i") for point markers, counter samples
("C") for time series. Timestamps are `time.monotonic()` in microseconds
— never wall clock (lint rule obs-wall-clock): an NTP step must not be
able to fold a hang timeline over itself.

Cost model, in order of importance:

1. Tracing OFF (default): `RECORDER` is None. Instrumentation sites do
   `rec = trace.RECORDER` / `if rec is not None` — one attribute load
   and one identity check, zero allocation. The module-level `span()`
   helper returns a shared no-op context manager for the same price.
2. Tracing ON: one small dict append per event into a
   `collections.deque(maxlen=N)` — append and the implied eviction are
   atomic under the GIL, so the hot path takes no lock. Only drain /
   snapshot / export touch the lock-free deque in bulk.

Cross-process story: the engine host child owns its own recorder and its
ticker thread drains new events into `{"t": "trace", "events": [...]}`
frames; the supervisor `absorb()`s them into the parent ring after
shifting timestamps by the ClockSync offset. Because the parent holds
the merged ring at all times, a SIGKILL'd child still leaves its spans
in the flight-recorder dump — there is no end-of-life flush to lose.

Keep this module pure stdlib (no JAX, no numpy): it is imported by
conftest, fishnet-lint, and the engine host before JAX initializes.
"""
from __future__ import annotations

import collections
import json
import os
import threading
import time
import zlib
from typing import Dict, Iterable, List, Optional, Tuple

__all__ = [
    "RECORDER",
    "ClockSync",
    "TraceRecorder",
    "counter",
    "ctx_args",
    "flow",
    "install",
    "install_from_settings",
    "instant",
    "make_ctx",
    "new_id",
    "now_us",
    "sampled",
    "span",
    "uninstall",
]

# Module-global recorder. None means tracing is off; every
# instrumentation site guards on exactly this:
#     rec = trace.RECORDER
#     if rec is not None: rec.instant(...)
RECORDER: Optional["TraceRecorder"] = None


def now_us() -> float:
    """The trace clock: monotonic microseconds."""
    return time.monotonic() * 1e6


class _NullSpan:
    """Shared no-op context manager returned by span() when tracing is
    off — no allocation on the hot path."""

    __slots__ = ()

    def __enter__(self) -> "_NullSpan":
        return self

    def __exit__(self, *exc) -> bool:
        return False


NULL_SPAN = _NullSpan()


class _Span:
    """Context manager that emits one complete event on exit.

    Exception-safe: the event is emitted whether or not the body raised,
    and a raise annotates the event with the exception type (the span
    still closes, so the timeline never shows a hole where an error
    happened). The exception itself propagates unchanged.
    """

    __slots__ = ("_rec", "_name", "_cat", "_args", "_t0")

    def __init__(self, rec: "TraceRecorder", name: str, cat: str,
                 args: Optional[dict]) -> None:
        self._rec = rec
        self._name = name
        self._cat = cat
        self._args = args

    def __enter__(self) -> "_Span":
        self._t0 = time.monotonic()
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        t1 = time.monotonic()
        args = self._args
        if exc_type is not None:
            args = dict(args) if args else {}
            args["error"] = exc_type.__name__
        self._rec.complete(
            self._name,
            self._t0 * 1e6,
            (t1 - self._t0) * 1e6,
            cat=self._cat,
            args=args,
        )
        return False


class TraceRecorder:
    """Bounded ring of Chrome trace events, safe to append from any
    thread. Oldest events fall off the back (deque maxlen), so the ring
    always holds the *last* window of activity — exactly what a flight
    recorder wants."""

    def __init__(self, capacity: int = 65536,
                 process_name: Optional[str] = None,
                 pid: Optional[int] = None) -> None:
        self.capacity = max(16, int(capacity))
        self.pid = os.getpid() if pid is None else int(pid)
        self._events: collections.deque = collections.deque(
            maxlen=self.capacity
        )
        self._meta_lock = threading.Lock()
        self._process_names: Dict[int, str] = {}
        self._thread_names: Dict[Tuple[int, int], str] = {}
        self._dump_lock = threading.Lock()
        # Approximate (unlocked) count of everything ever emitted;
        # emitted - len(ring) estimates eviction for trace_report.
        self.emitted = 0
        if process_name:
            self.set_process_name(process_name)

    # -------------------------------------------------------- identity

    def set_process_name(self, name: str, pid: Optional[int] = None) -> None:
        with self._meta_lock:
            self._process_names[self.pid if pid is None else pid] = name

    def set_thread_name(self, name: str, tid: Optional[int] = None) -> None:
        with self._meta_lock:
            key = (self.pid, self._tid() if tid is None else tid)
            self._thread_names[key] = name

    @staticmethod
    def _tid() -> int:
        # Mask to 32 bits: CPython thread idents are pointer-sized and
        # make Perfetto's track labels unreadable at full width.
        return threading.get_ident() & 0xFFFFFFFF

    # ------------------------------------------------------------ emit

    def complete(self, name: str, ts_us: float, dur_us: float,
                 cat: str = "app", args: Optional[dict] = None,
                 tid: Optional[int] = None) -> None:
        """One complete event ("X") with explicit start/duration — used
        both by _Span on exit and by retroactive emitters (SyncStats
        boundary accounting describes an interval that already ended)."""
        ev = {
            "name": name,
            "cat": cat,
            "ph": "X",
            "ts": ts_us,
            "dur": max(dur_us, 0.0),
            "pid": self.pid,
            "tid": self._tid() if tid is None else tid,
        }
        if args:
            ev["args"] = args
        self._events.append(ev)
        self.emitted += 1

    def span(self, name: str, cat: str = "app", **args) -> _Span:
        return _Span(self, name, cat, args or None)

    def instant(self, name: str, cat: str = "app", **args) -> None:
        ev = {
            "name": name,
            "cat": cat,
            "ph": "i",
            "s": "t",
            "ts": now_us(),
            "pid": self.pid,
            "tid": self._tid(),
        }
        if args:
            ev["args"] = args
        self._events.append(ev)
        self.emitted += 1

    def counter(self, name: str, value: float, cat: str = "app") -> None:
        self._events.append({
            "name": name,
            "cat": cat,
            "ph": "C",
            "ts": now_us(),
            "pid": self.pid,
            "tid": 0,
            "args": {"value": value},
        })
        self.emitted += 1

    def flow(self, name: str, flow_id: str, phase: str = "t",
             cat: str = "app", ts_us: Optional[float] = None,
             tid: Optional[int] = None,
             args: Optional[dict] = None) -> None:
        """The span-link primitive: a Chrome flow event tying slices on
        different tracks (threads, processes) into one causal chain.

        phase "s" starts a flow, "t" carries it through an intermediate
        slice, "f" terminates it. Events sharing the same `flow_id`
        render as arrows in Perfetto; a request's trace_id is its flow
        id, so every hop a request takes — HTTP edge, admission, chunk
        dispatch, lane splice, delivery — hangs off one arrow chain even
        after absorb() merges the rings of four processes (flow ids are
        strings, immune to the timestamp shift)."""
        if phase not in ("s", "t", "f"):
            raise ValueError(f"flow phase must be s/t/f, got {phase!r}")
        ev = {
            "name": name,
            "cat": cat,
            "ph": phase,
            "id": str(flow_id),
            "ts": now_us() if ts_us is None else ts_us,
            "pid": self.pid,
            "tid": self._tid() if tid is None else tid,
        }
        if phase == "f":
            # bind to the enclosing slice's end, not the next slice's
            # start — the chain must not imply causality that isn't there
            ev["bp"] = "e"
        if args:
            ev["args"] = args
        self._events.append(ev)
        self.emitted += 1

    # ------------------------------------------------- cross-process IO

    def drain(self) -> List[dict]:
        """Pop every currently-buffered event (oldest first). The child
        ticker calls this to stream increments to the supervisor; each
        event leaves the ring exactly once."""
        out: List[dict] = []
        pop = self._events.popleft
        try:
            while True:
                out.append(pop())
        except IndexError:
            pass
        return out

    def absorb(self, events: Iterable[dict],
               offset_us: float = 0.0) -> int:
        """Merge foreign events (a child's drained increment) into this
        ring, shifting their timestamps by offset_us — the ClockSync
        estimate mapping the child's monotonic clock onto ours."""
        n = 0
        for ev in events:
            if not isinstance(ev, dict) or "ph" not in ev:
                continue
            ev = dict(ev)
            try:
                ev["ts"] = float(ev.get("ts", 0.0)) + offset_us
            except (TypeError, ValueError):
                continue
            self._events.append(ev)
            self.emitted += 1
            n += 1
        return n

    # ---------------------------------------------------------- export

    def snapshot(self, window_s: Optional[float] = None) -> List[dict]:
        """Copy of the ring (non-destructive), optionally clipped to the
        trailing window_s seconds of trace time."""
        evs = list(self._events)
        if window_s is not None:
            cutoff = now_us() - window_s * 1e6
            evs = [
                e for e in evs
                if float(e.get("ts", 0.0)) + float(e.get("dur", 0.0))
                >= cutoff
            ]
        return evs

    def _metadata_events(self) -> List[dict]:
        with self._meta_lock:
            procs = dict(self._process_names)
            threads = dict(self._thread_names)
        out: List[dict] = []
        for pid, name in sorted(procs.items()):
            out.append({
                "name": "process_name", "ph": "M", "pid": pid, "tid": 0,
                "args": {"name": name},
            })
        for (pid, tid), name in sorted(threads.items()):
            out.append({
                "name": "thread_name", "ph": "M", "pid": pid, "tid": tid,
                "args": {"name": name},
            })
        return out

    def export(self, window_s: Optional[float] = None) -> dict:
        """The Chrome trace-event JSON object — load the dumped file
        straight into Perfetto / chrome://tracing. Top-level
        `buildInfo` (git sha, jax versions, backend, devices — the
        perf layer's cross-host join key) is an extra key the trace
        viewers ignore and tools/trace_report.py --compare reports."""
        evs = self.snapshot(window_s)
        evs.sort(key=lambda e: float(e.get("ts", 0.0)))
        out = {
            "traceEvents": self._metadata_events() + evs,
            "displayTimeUnit": "ms",
        }
        try:
            from . import perf

            out["buildInfo"] = perf.build_info()
        except Exception:
            pass  # a dump without build info is still a valid trace
        return out

    def dump(self, path: str, window_s: Optional[float] = None) -> str:
        """Write the export atomically (tmp + rename): a watcher tailing
        the trace dir never reads a half-written JSON."""
        with self._dump_lock:
            tmp = f"{path}.tmp.{self.pid}"
            with open(tmp, "w", encoding="utf-8") as fh:
                json.dump(self.export(window_s), fh)
            os.replace(tmp, path)
        return path

    def flight_dump(self, dir_path: str, reason: str,
                    window_s: Optional[float] = None) -> str:
        """The flight-recorder write: dump the trailing window into
        dir_path with a self-describing, collision-free name. Called by
        the supervisor's recovery ladder next to its journal."""
        os.makedirs(dir_path, exist_ok=True)
        safe = "".join(
            c if (c.isalnum() or c in "-_") else "-" for c in reason
        )
        stamp = time.strftime("%Y%m%dT%H%M%S")
        base = f"trace-{safe}-{stamp}-pid{self.pid}"
        path = os.path.join(dir_path, base + ".json")
        n = 1
        while os.path.exists(path):
            path = os.path.join(dir_path, f"{base}-{n}.json")
            n += 1
        return self.dump(path, window_s)


class ClockSync:
    """Child-monotonic → parent-monotonic offset estimator.

    time.monotonic() has an arbitrary per-process epoch, so child event
    timestamps mean nothing on the parent timeline until shifted. Each
    sample pairs a child reading (the "mono" field the host puts in its
    ready and hb frames) with the parent's receive time:

        offset = parent_recv_mono - child_mono

    overestimates the true epoch difference by exactly the one-way
    pipe+scheduling latency, which is strictly positive — so the MINIMUM
    over samples is the best available estimate, it can only improve as
    heartbeats keep arriving, and one quiet-moment frame pins it tight.
    Estimated from the ready frame at config time, re-checked on every
    heartbeat (supervisor._read_loop).
    """

    def __init__(self) -> None:
        self.offset_us: Optional[float] = None
        self.samples = 0

    def sample(self, child_mono_s: float,
               parent_recv_mono_s: float) -> float:
        off = (parent_recv_mono_s - child_mono_s) * 1e6
        if self.offset_us is None or off < self.offset_us:
            self.offset_us = off
        self.samples += 1
        return self.offset_us


# ----------------------------------------------------- request context
#
# A request context is the 5-tuple the tentacles of a single user
# request carry across every process boundary:
#
#     {"trace_id", "span_id", "tenant", "kind", "deadline_ms"}
#
# represented as a plain JSON-safe dict so it rides the existing wire
# dicts and pipe frames untouched (client/ipc.py chunk wire field
# "ctx", engine/frames.py partial frames, serve protocol JSON).
# trace_id names the whole request and doubles as its flow id; span_id
# names the hop that stamped the context (the parent span of everything
# downstream). The context is pure metadata: it must never reach an
# engine input or a _GroupKey — search results are bit-identical with
# tracing on or off.

CTX_KEYS = ("trace_id", "span_id", "tenant", "kind", "deadline_ms")


def new_id() -> str:
    """A fresh 16-hex-char trace/span id (64 random bits)."""
    return os.urandom(8).hex()


def make_ctx(tenant: str, kind: str, deadline_ms: Optional[int] = None,
             trace_id: Optional[str] = None,
             span_id: Optional[str] = None) -> dict:
    """Stamp a request context at an edge (serve front-end, lichess
    client). Reuses a caller-supplied trace_id (an upstream header)
    or mints one."""
    return {
        "trace_id": trace_id or new_id(),
        "span_id": span_id or new_id(),
        "tenant": str(tenant or "")[:32],
        "kind": str(kind or "")[:16],
        "deadline_ms": int(deadline_ms) if deadline_ms else None,
    }


def ctx_from_wire(obj) -> Optional[dict]:
    """Validate a context read off a wire dict / pipe frame. Foreign
    junk degrades to None (no context) rather than crashing a frame
    reader mid-chunk."""
    if not isinstance(obj, dict) or not obj.get("trace_id"):
        return None
    ctx = {k: obj.get(k) for k in CTX_KEYS}
    ctx["trace_id"] = str(ctx["trace_id"])[:32]
    ctx["span_id"] = str(ctx.get("span_id") or "")[:32]
    return ctx


def ctx_args(ctx: Optional[dict], **extra) -> dict:
    """Span-args annotation for a context: every per-request span gets
    args.trace_id so trace_report can reassemble the waterfall even
    where flow arrows were evicted from a ring."""
    if not ctx:
        return extra
    out = {"trace_id": ctx.get("trace_id"),
           "tenant": ctx.get("tenant"),
           "kind": ctx.get("kind")}
    out.update(extra)
    return out


def sampled(trace_id: str) -> bool:
    """Deterministic per-request sampling decision, shared by every
    process that sees the id: the same trace_id hashes to the same
    verdict on the serve edge, the supervisor, and the engine host, so
    a sampled request is traced at EVERY hop or none (no half
    waterfalls). Rate from FISHNET_TPU_TRACE_SAMPLE in [0, 1]."""
    rate = _sample_rate()
    if rate >= 1.0:
        return True
    if rate <= 0.0:
        return False
    return (zlib.crc32(trace_id.encode("utf-8", "replace")) & 0xFFFFFFFF) \
        < rate * 4294967296.0


def _sample_rate() -> float:
    from ..utils import settings

    raw = settings.get_str("FISHNET_TPU_TRACE_SAMPLE")
    try:
        return min(1.0, max(0.0, float(raw)))
    except (TypeError, ValueError):
        return 1.0


# ------------------------------------------------- module-level helpers
#
# Convenience wrappers for non-hot-path call sites; all are free when
# tracing is off. Hot loops should hoist `rec = trace.RECORDER` instead.


def flow(name: str, flow_id: str, phase: str = "t", cat: str = "app",
         ts_us: Optional[float] = None, args: Optional[dict] = None) -> None:
    rec = RECORDER
    if rec is not None:
        rec.flow(name, flow_id, phase, cat, ts_us=ts_us, args=args)


def span(name: str, cat: str = "app", **args):
    rec = RECORDER
    if rec is None:
        return NULL_SPAN
    return rec.span(name, cat, **args)


def instant(name: str, cat: str = "app", **args) -> None:
    rec = RECORDER
    if rec is not None:
        rec.instant(name, cat, **args)


def counter(name: str, value: float, cat: str = "app") -> None:
    rec = RECORDER
    if rec is not None:
        rec.counter(name, value, cat)


def install(recorder: TraceRecorder) -> TraceRecorder:
    global RECORDER
    RECORDER = recorder
    return recorder


def uninstall() -> None:
    global RECORDER
    RECORDER = None


def install_from_settings(process_name: str) -> Optional[TraceRecorder]:
    """Install the module-global recorder iff FISHNET_TPU_TRACE_DIR is
    set (tracing's single opt-in switch); ring size from
    FISHNET_TPU_TRACE_BUF. Returns the recorder, or None when tracing
    stays off."""
    from ..utils import settings

    trace_dir = settings.get_str("FISHNET_TPU_TRACE_DIR")
    if not trace_dir:
        return None
    capacity = settings.get_int("FISHNET_TPU_TRACE_BUF")
    return install(TraceRecorder(capacity=capacity,
                                 process_name=process_name))
