"""Thread-safe ring-buffer tracing on the monotonic clock.

The recorder is a bounded deque of Chrome trace-event dicts (the JSON
format Perfetto and chrome://tracing load natively): complete events
("ph": "X") for spans, instants ("i") for point markers, counter samples
("C") for time series. Timestamps are `time.monotonic()` in microseconds
— never wall clock (lint rule obs-wall-clock): an NTP step must not be
able to fold a hang timeline over itself.

Cost model, in order of importance:

1. Tracing OFF (default): `RECORDER` is None. Instrumentation sites do
   `rec = trace.RECORDER` / `if rec is not None` — one attribute load
   and one identity check, zero allocation. The module-level `span()`
   helper returns a shared no-op context manager for the same price.
2. Tracing ON: one small dict append per event into a
   `collections.deque(maxlen=N)` — append and the implied eviction are
   atomic under the GIL, so the hot path takes no lock. Only drain /
   snapshot / export touch the lock-free deque in bulk.

Cross-process story: the engine host child owns its own recorder and its
ticker thread drains new events into `{"t": "trace", "events": [...]}`
frames; the supervisor `absorb()`s them into the parent ring after
shifting timestamps by the ClockSync offset. Because the parent holds
the merged ring at all times, a SIGKILL'd child still leaves its spans
in the flight-recorder dump — there is no end-of-life flush to lose.

Keep this module pure stdlib (no JAX, no numpy): it is imported by
conftest, fishnet-lint, and the engine host before JAX initializes.
"""
from __future__ import annotations

import collections
import json
import os
import threading
import time
from typing import Dict, Iterable, List, Optional, Tuple

__all__ = [
    "RECORDER",
    "ClockSync",
    "TraceRecorder",
    "counter",
    "install",
    "install_from_settings",
    "instant",
    "now_us",
    "span",
    "uninstall",
]

# Module-global recorder. None means tracing is off; every
# instrumentation site guards on exactly this:
#     rec = trace.RECORDER
#     if rec is not None: rec.instant(...)
RECORDER: Optional["TraceRecorder"] = None


def now_us() -> float:
    """The trace clock: monotonic microseconds."""
    return time.monotonic() * 1e6


class _NullSpan:
    """Shared no-op context manager returned by span() when tracing is
    off — no allocation on the hot path."""

    __slots__ = ()

    def __enter__(self) -> "_NullSpan":
        return self

    def __exit__(self, *exc) -> bool:
        return False


NULL_SPAN = _NullSpan()


class _Span:
    """Context manager that emits one complete event on exit.

    Exception-safe: the event is emitted whether or not the body raised,
    and a raise annotates the event with the exception type (the span
    still closes, so the timeline never shows a hole where an error
    happened). The exception itself propagates unchanged.
    """

    __slots__ = ("_rec", "_name", "_cat", "_args", "_t0")

    def __init__(self, rec: "TraceRecorder", name: str, cat: str,
                 args: Optional[dict]) -> None:
        self._rec = rec
        self._name = name
        self._cat = cat
        self._args = args

    def __enter__(self) -> "_Span":
        self._t0 = time.monotonic()
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        t1 = time.monotonic()
        args = self._args
        if exc_type is not None:
            args = dict(args) if args else {}
            args["error"] = exc_type.__name__
        self._rec.complete(
            self._name,
            self._t0 * 1e6,
            (t1 - self._t0) * 1e6,
            cat=self._cat,
            args=args,
        )
        return False


class TraceRecorder:
    """Bounded ring of Chrome trace events, safe to append from any
    thread. Oldest events fall off the back (deque maxlen), so the ring
    always holds the *last* window of activity — exactly what a flight
    recorder wants."""

    def __init__(self, capacity: int = 65536,
                 process_name: Optional[str] = None,
                 pid: Optional[int] = None) -> None:
        self.capacity = max(16, int(capacity))
        self.pid = os.getpid() if pid is None else int(pid)
        self._events: collections.deque = collections.deque(
            maxlen=self.capacity
        )
        self._meta_lock = threading.Lock()
        self._process_names: Dict[int, str] = {}
        self._thread_names: Dict[Tuple[int, int], str] = {}
        self._dump_lock = threading.Lock()
        # Approximate (unlocked) count of everything ever emitted;
        # emitted - len(ring) estimates eviction for trace_report.
        self.emitted = 0
        if process_name:
            self.set_process_name(process_name)

    # -------------------------------------------------------- identity

    def set_process_name(self, name: str, pid: Optional[int] = None) -> None:
        with self._meta_lock:
            self._process_names[self.pid if pid is None else pid] = name

    def set_thread_name(self, name: str, tid: Optional[int] = None) -> None:
        with self._meta_lock:
            key = (self.pid, self._tid() if tid is None else tid)
            self._thread_names[key] = name

    @staticmethod
    def _tid() -> int:
        # Mask to 32 bits: CPython thread idents are pointer-sized and
        # make Perfetto's track labels unreadable at full width.
        return threading.get_ident() & 0xFFFFFFFF

    # ------------------------------------------------------------ emit

    def complete(self, name: str, ts_us: float, dur_us: float,
                 cat: str = "app", args: Optional[dict] = None,
                 tid: Optional[int] = None) -> None:
        """One complete event ("X") with explicit start/duration — used
        both by _Span on exit and by retroactive emitters (SyncStats
        boundary accounting describes an interval that already ended)."""
        ev = {
            "name": name,
            "cat": cat,
            "ph": "X",
            "ts": ts_us,
            "dur": max(dur_us, 0.0),
            "pid": self.pid,
            "tid": self._tid() if tid is None else tid,
        }
        if args:
            ev["args"] = args
        self._events.append(ev)
        self.emitted += 1

    def span(self, name: str, cat: str = "app", **args) -> _Span:
        return _Span(self, name, cat, args or None)

    def instant(self, name: str, cat: str = "app", **args) -> None:
        ev = {
            "name": name,
            "cat": cat,
            "ph": "i",
            "s": "t",
            "ts": now_us(),
            "pid": self.pid,
            "tid": self._tid(),
        }
        if args:
            ev["args"] = args
        self._events.append(ev)
        self.emitted += 1

    def counter(self, name: str, value: float, cat: str = "app") -> None:
        self._events.append({
            "name": name,
            "cat": cat,
            "ph": "C",
            "ts": now_us(),
            "pid": self.pid,
            "tid": 0,
            "args": {"value": value},
        })
        self.emitted += 1

    # ------------------------------------------------- cross-process IO

    def drain(self) -> List[dict]:
        """Pop every currently-buffered event (oldest first). The child
        ticker calls this to stream increments to the supervisor; each
        event leaves the ring exactly once."""
        out: List[dict] = []
        pop = self._events.popleft
        try:
            while True:
                out.append(pop())
        except IndexError:
            pass
        return out

    def absorb(self, events: Iterable[dict],
               offset_us: float = 0.0) -> int:
        """Merge foreign events (a child's drained increment) into this
        ring, shifting their timestamps by offset_us — the ClockSync
        estimate mapping the child's monotonic clock onto ours."""
        n = 0
        for ev in events:
            if not isinstance(ev, dict) or "ph" not in ev:
                continue
            ev = dict(ev)
            try:
                ev["ts"] = float(ev.get("ts", 0.0)) + offset_us
            except (TypeError, ValueError):
                continue
            self._events.append(ev)
            self.emitted += 1
            n += 1
        return n

    # ---------------------------------------------------------- export

    def snapshot(self, window_s: Optional[float] = None) -> List[dict]:
        """Copy of the ring (non-destructive), optionally clipped to the
        trailing window_s seconds of trace time."""
        evs = list(self._events)
        if window_s is not None:
            cutoff = now_us() - window_s * 1e6
            evs = [
                e for e in evs
                if float(e.get("ts", 0.0)) + float(e.get("dur", 0.0))
                >= cutoff
            ]
        return evs

    def _metadata_events(self) -> List[dict]:
        with self._meta_lock:
            procs = dict(self._process_names)
            threads = dict(self._thread_names)
        out: List[dict] = []
        for pid, name in sorted(procs.items()):
            out.append({
                "name": "process_name", "ph": "M", "pid": pid, "tid": 0,
                "args": {"name": name},
            })
        for (pid, tid), name in sorted(threads.items()):
            out.append({
                "name": "thread_name", "ph": "M", "pid": pid, "tid": tid,
                "args": {"name": name},
            })
        return out

    def export(self, window_s: Optional[float] = None) -> dict:
        """The Chrome trace-event JSON object — load the dumped file
        straight into Perfetto / chrome://tracing."""
        evs = self.snapshot(window_s)
        evs.sort(key=lambda e: float(e.get("ts", 0.0)))
        return {
            "traceEvents": self._metadata_events() + evs,
            "displayTimeUnit": "ms",
        }

    def dump(self, path: str, window_s: Optional[float] = None) -> str:
        """Write the export atomically (tmp + rename): a watcher tailing
        the trace dir never reads a half-written JSON."""
        with self._dump_lock:
            tmp = f"{path}.tmp.{self.pid}"
            with open(tmp, "w", encoding="utf-8") as fh:
                json.dump(self.export(window_s), fh)
            os.replace(tmp, path)
        return path

    def flight_dump(self, dir_path: str, reason: str,
                    window_s: Optional[float] = None) -> str:
        """The flight-recorder write: dump the trailing window into
        dir_path with a self-describing, collision-free name. Called by
        the supervisor's recovery ladder next to its journal."""
        os.makedirs(dir_path, exist_ok=True)
        safe = "".join(
            c if (c.isalnum() or c in "-_") else "-" for c in reason
        )
        stamp = time.strftime("%Y%m%dT%H%M%S")
        base = f"trace-{safe}-{stamp}-pid{self.pid}"
        path = os.path.join(dir_path, base + ".json")
        n = 1
        while os.path.exists(path):
            path = os.path.join(dir_path, f"{base}-{n}.json")
            n += 1
        return self.dump(path, window_s)


class ClockSync:
    """Child-monotonic → parent-monotonic offset estimator.

    time.monotonic() has an arbitrary per-process epoch, so child event
    timestamps mean nothing on the parent timeline until shifted. Each
    sample pairs a child reading (the "mono" field the host puts in its
    ready and hb frames) with the parent's receive time:

        offset = parent_recv_mono - child_mono

    overestimates the true epoch difference by exactly the one-way
    pipe+scheduling latency, which is strictly positive — so the MINIMUM
    over samples is the best available estimate, it can only improve as
    heartbeats keep arriving, and one quiet-moment frame pins it tight.
    Estimated from the ready frame at config time, re-checked on every
    heartbeat (supervisor._read_loop).
    """

    def __init__(self) -> None:
        self.offset_us: Optional[float] = None
        self.samples = 0

    def sample(self, child_mono_s: float,
               parent_recv_mono_s: float) -> float:
        off = (parent_recv_mono_s - child_mono_s) * 1e6
        if self.offset_us is None or off < self.offset_us:
            self.offset_us = off
        self.samples += 1
        return self.offset_us


# ------------------------------------------------- module-level helpers
#
# Convenience wrappers for non-hot-path call sites; all are free when
# tracing is off. Hot loops should hoist `rec = trace.RECORDER` instead.


def span(name: str, cat: str = "app", **args):
    rec = RECORDER
    if rec is None:
        return NULL_SPAN
    return rec.span(name, cat, **args)


def instant(name: str, cat: str = "app", **args) -> None:
    rec = RECORDER
    if rec is not None:
        rec.instant(name, cat, **args)


def counter(name: str, value: float, cat: str = "app") -> None:
    rec = RECORDER
    if rec is not None:
        rec.counter(name, value, cat)


def install(recorder: TraceRecorder) -> TraceRecorder:
    global RECORDER
    RECORDER = recorder
    return recorder


def uninstall() -> None:
    global RECORDER
    RECORDER = None


def install_from_settings(process_name: str) -> Optional[TraceRecorder]:
    """Install the module-global recorder iff FISHNET_TPU_TRACE_DIR is
    set (tracing's single opt-in switch); ring size from
    FISHNET_TPU_TRACE_BUF. Returns the recorder, or None when tracing
    stays off."""
    from ..utils import settings

    trace_dir = settings.get_str("FISHNET_TPU_TRACE_DIR")
    if not trace_dir:
        return None
    capacity = settings.get_int("FISHNET_TPU_TRACE_BUF")
    return install(TraceRecorder(capacity=capacity,
                                 process_name=process_name))
