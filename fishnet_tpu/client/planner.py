"""Batch planner: validates acquired work and plans it into chunks.

Behavioral parity with the reference's IncomingBatch::from_acquired
(reference: src/queue.rs:546-700): FEN + every UCI move re-validated by
replay, engine flavor chosen, per-ply positions built *in reverse* (backwards
analysis so mate scores propagate naturally), tiled into chunks of ≤6 with a
one-position overlap that warms engine state and is discarded
(position_index None). The TPU backend doesn't need warm-up overlap — it
analyses whole batches at once — but the chunk plan is kept identical so the
subprocess path and accounting stay compatible.
"""
from __future__ import annotations

import dataclasses
import time
from dataclasses import dataclass
from typing import List, Optional, Set

from ..chess.position import IllegalMoveError, InvalidFenError
from ..chess.variants import from_fen
from .ipc import Chunk, WorkPosition
from .wire import (
    AcquireResponseBody,
    AnalysisPartSkipped,
    AnalysisWork,
    EngineFlavor,
    MoveWork,
)

SKIP = "skip"  # sentinel marking a skipped position slot


class IncomingError(Exception):
    pass


class AllSkipped(IncomingError):
    """Batch completes immediately: every position was skipped
    (reference: src/queue.rs:684-694)."""

    def __init__(self, completed: "CompletedBatch"):
        super().__init__("all positions skipped")
        self.completed = completed


@dataclass
class IncomingBatch:
    work: object
    url: Optional[str]
    flavor: EngineFlavor
    variant: str
    chunks: List[Chunk]

    @staticmethod
    def from_acquired(
        endpoint_url: str,
        body: AcquireResponseBody,
        tpu_variants: Optional[Set[str]] = None,
        tpu_moves: bool = False,
        now: Optional[float] = None,
    ) -> "IncomingBatch":
        """Validate and plan an acquired batch.

        tpu_variants: variants the TPU engine handles for analysis jobs;
        tpu_moves: whether move jobs also route to the TPU engine. With both
        unset the flavor choice matches the reference exactly
        (reference: src/queue.rs:562-568).
        """
        url = body.batch_url(endpoint_url)
        now = time.monotonic() if now is None else now

        is_standard_chess = body.variant in ("standard", "chess960", "fromPosition")

        try:
            root_pos = from_fen(body.position, body.variant)
        except (InvalidFenError, ValueError) as e:
            raise IncomingError(f"invalid position: {e}") from e

        # hot replay path: the native C++ core validates and re-encodes the
        # move list for standard chess; variants and environments without a
        # toolchain fall through to the pure-Python replay below
        body_moves: Optional[List[str]] = None
        if is_standard_chess:
            from ..chess import native

            try:
                replayed = native.replay_game(body.position, body.moves)
            except native.NativeError as e:
                raise IncomingError(str(e)) from None
            if replayed is not None:
                _final_fen, body_moves = replayed
        if body.work.is_analysis and is_standard_chess:
            flavor = (
                EngineFlavor.TPU
                if tpu_variants and body.variant in tpu_variants
                else EngineFlavor.OFFICIAL
            )
        else:
            # variants and *all* move jobs go to the multi-variant engine
            flavor = (
                EngineFlavor.TPU
                if tpu_variants
                and body.variant in tpu_variants
                and (body.work.is_analysis or tpu_moves)
                else EngineFlavor.MULTI_VARIANT
            )

        root_fen = root_pos.to_fen()

        if body_moves is None:
            # replay every move, re-encoding into Chess960-style UCI
            body_moves = []
            pos = root_pos
            for uci in body.moves:
                try:
                    move = pos.parse_uci(uci)
                except (IllegalMoveError, ValueError) as e:
                    raise IncomingError(f"illegal uci move: {e}") from e
                body_moves.append(move.uci())
                pos = pos.push(move)

        if isinstance(body.work, MoveWork):
            chunk = Chunk(
                work=body.work,
                deadline=now + body.work.timeout_per_ply(),
                flavor=flavor,
                variant=body.variant,
                positions=[
                    WorkPosition(
                        work=body.work,
                        url=url,
                        skip=False,
                        position_index=0,
                        root_fen=root_fen,
                        moves=body_moves,
                    )
                ],
            )
            return IncomingBatch(body.work, url, flavor, body.variant, [chunk])

        assert isinstance(body.work, AnalysisWork)
        num_positions = len(body_moves) + 1
        deadline = now + body.work.timeout_per_ply() * num_positions
        skip_set = set(body.skip_positions)

        positions: List[WorkPosition] = []
        for index in range(num_positions):
            positions.append(
                WorkPosition(
                    work=body.work,
                    url=f"{url}#{index}" if url else None,
                    skip=index in skip_set,
                    position_index=index,
                    root_fen=root_fen,
                    moves=body_moves[:index],
                )
            )

        # analyse backwards (reference: src/queue.rs:639-640)
        positions.reverse()

        # pair every position with its predecessor-in-analysis-order, which
        # becomes a discarded warm-up overlap at chunk boundaries
        prevs: List[Optional[WorkPosition]] = [None]
        for p in positions[:-1]:
            prevs.append(dataclasses.replace(p, position_index=None))

        chunks: List[Chunk] = []
        group_size = Chunk.MAX_POSITIONS - 1
        pairs = list(zip(prevs, positions))
        for start in range(0, len(pairs), group_size):
            chunk_positions: List[WorkPosition] = []
            for prev, current in pairs[start : start + group_size]:
                if current.skip:
                    continue
                if prev is not None and (prev.skip or not chunk_positions):
                    chunk_positions.append(prev)
                chunk_positions.append(current)
            if chunk_positions:
                chunks.append(
                    Chunk(
                        work=body.work,
                        deadline=deadline,
                        flavor=flavor,
                        variant=body.variant,
                        positions=chunk_positions,
                    )
                )

        if not chunks:
            raise AllSkipped(
                CompletedBatch(
                    work=body.work,
                    url=url,
                    flavor=flavor,
                    variant=body.variant,
                    positions=[SKIP] * num_positions,
                    total_nodes=0,
                    total_cpu_time=0.0,
                )
            )

        return IncomingBatch(body.work, url, flavor, body.variant, chunks)


@dataclass
class PendingBatch:
    """Sparse reassembly buffer (reference: src/queue.rs:745-789)."""

    work: object
    url: Optional[str]
    flavor: EngineFlavor
    variant: str
    positions: List[object]  # None (outstanding) | SKIP | PositionResponse
    total_nodes: int = 0
    total_cpu_time: float = 0.0

    def pending(self) -> int:
        return sum(1 for p in self.positions if p is None)

    def try_into_completed(self) -> Optional["CompletedBatch"]:
        if any(p is None for p in self.positions):
            return None
        return CompletedBatch(
            work=self.work,
            url=self.url,
            flavor=self.flavor,
            variant=self.variant,
            positions=list(self.positions),
            total_nodes=self.total_nodes,
            total_cpu_time=self.total_cpu_time,
        )

    def progress_report(self) -> List[Optional[dict]]:
        """Quirk: lila distinguishes progress reports from complete analysis
        by the first part being null (reference: src/queue.rs:773-784)."""
        out: List[Optional[dict]] = []
        for i, p in enumerate(self.positions):
            if i > 0 and p is not None and p is not SKIP:
                out.append(p.to_best().to_json())
            else:
                out.append(None)
        return out


@dataclass
class CompletedBatch:
    """Fully analysed batch (reference: src/queue.rs:791-838)."""

    work: object
    url: Optional[str]
    flavor: EngineFlavor
    variant: str
    positions: List[object]  # SKIP | PositionResponse
    total_nodes: int
    total_cpu_time: float

    def into_analysis(self) -> List[Optional[dict]]:
        out = []
        for p in self.positions:
            if p is SKIP:
                out.append(AnalysisPartSkipped().to_json())
            elif p.work.matrix_wanted():
                out.append(p.into_matrix().to_json())
            else:
                out.append(p.to_best().to_json())
        return out

    def into_best_move(self) -> Optional[str]:
        if not self.positions or self.positions[0] is SKIP:
            return None
        return self.positions[0].best_move

    def total_positions(self) -> int:
        return sum(1 for p in self.positions if p is not SKIP)

    def nps(self) -> Optional[int]:
        if self.total_cpu_time <= 0:
            return None
        return int(self.total_nodes / self.total_cpu_time)
