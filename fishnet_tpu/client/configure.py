"""Config: CLI flags, fishnet.ini, interactive dialog; precedence CLI > ini.

Parity with the reference's three-layer config system (reference:
src/configure.rs:20-643): same flags, same ini file format (default section
"Fishnet"), same subcommands (run | configure | systemd | systemd-user |
license), same human duration parsing (d/h/m/s/ms), same Cores/Backlog
semantics — plus the TPU backend's own knobs (backend selection, weight
file, engine paths for the subprocess fallback).
"""
from __future__ import annotations

import argparse
import configparser
import os
import re
import sys
from dataclasses import dataclass, field
from pathlib import Path
from typing import List, Optional

_DURATION_RE = re.compile(r"^\s*(\d+)\s*(d|h|m|s|ms)?\s*$")


def parse_duration(text: str) -> float:
    """'90s', '2m', '1h', '1d', '500ms', bare seconds → seconds
    (reference: src/configure.rs:340-355)."""
    m = _DURATION_RE.match(text)
    if not m:
        raise ValueError(f"invalid duration: {text!r}")
    value = int(m.group(1))
    unit = m.group(2) or "s"
    scale = {"d": 86400, "h": 3600, "m": 60, "s": 1, "ms": 0.001}[unit]
    return value * scale


def parse_cores(text: Optional[str]) -> int:
    """'auto' = n-1, 'all'/'max' = n, or an explicit number
    (reference: src/configure.rs:177-219)."""
    n = os.cpu_count() or 1
    if text is None or text == "auto":
        return max(n - 1, 1)
    if text in ("all", "max"):
        return n
    value = int(text)
    if value < 1:
        raise ValueError("cores must be >= 1")
    return min(value, n)


def parse_backlog(text: Optional[str]) -> Optional[float]:
    """'short' = 30s, 'long' = 1h, duration, or None
    (reference: src/configure.rs:244-289)."""
    if text is None or text == "":
        return None
    if text == "short":
        return 30.0
    if text == "long":
        return 3600.0
    return parse_duration(text)


def validate_key(key: str) -> str:
    key = key.strip()
    if not key:
        return key
    if not key.isalnum():
        raise ValueError("fishnet key must be alphanumeric")
    return key


@dataclass
class Config:
    command: str = "run"
    endpoint: str = "https://lichess.org/fishnet"
    key: Optional[str] = None
    key_file: Optional[str] = None
    cores: int = 1
    backend: str = "tpu"  # tpu | subprocess | python
    engine_path: Optional[str] = None  # external Stockfish (Official flavor)
    variant_engine_path: Optional[str] = None  # external Fairy-Stockfish
    tpu_weights: Optional[str] = None
    # analysis depth cap. Deepening is ALSO governed per position by the
    # server node budget and the chunk deadline (engine/tpu.py stops
    # iterating when either runs out), so this cap only binds when budget
    # remains — raised 6 → 8 → 12 in round 4 as the pruning stack grew
    # (NMP + LMR, then frontier futility). The measured node table
    # (docs/depth.md, tools/depth_table.py: EBF ≈ 2.8 with the full
    # stack + TT) puts the reference's own per-position budgets
    # (api.rs:214-233, ×6/7 overlap scaling) at budget-emergent depth
    # ~9-10 (sf16) / ~10-11 (classical), so 12 lets the BUDGET bind —
    # matching the reference, whose depth is likewise budget-emergent —
    # while the deadline race still cuts off any iteration a slow
    # backend can't afford.
    tpu_depth: int = 12
    # Lazy-SMP helper lanes per analysed position (engine/tpu.py): spare
    # batch lanes re-search the hardest roots with perturbed ordering and
    # share results through the TT. 1 disables helpers entirely.
    tpu_helpers: int = 4
    # continuous lane refill (engine/tpu.py LaneScheduler): finished
    # lanes are respliced with queued positions at segment boundaries
    # instead of narrowing and draining chunks serially; --no-tpu-refill
    # restores strict chunk-serial dispatch
    tpu_refill: bool = True
    # shard-aware refill on multi-chip hosts (parallel/mesh.py sharded
    # segment/refill callables driven by the same LaneScheduler);
    # --no-tpu-mesh-refill pins meshed engines back to chunk-serial
    # dispatch without touching single-device refill
    tpu_mesh_refill: bool = True
    # host the TPU engine in a supervised child process (engine/supervisor.py)
    # so a wedged device can be hard-killed and respawned; --no-supervisor
    # reverts to the in-process engine (debugging, single-process profiling)
    supervisor: bool = True
    # session recovery (engine/supervisor.py recovery ladder). None defers
    # to the FISHNET_TPU_REPLAY / _BISECT_MAX / _QUARANTINE registry
    # settings so env-var config keeps working without CLI/ini mirrors.
    tpu_replay: Optional[bool] = None
    tpu_bisect_max: Optional[int] = None
    tpu_quarantine: Optional[bool] = None
    user_backlog: Optional[float] = None
    system_backlog: Optional[float] = None
    max_backoff: float = 30.0
    cpu_priority: Optional[str] = None
    stats_file: Optional[str] = None
    no_stats_file: bool = False
    auto_update: bool = False
    # `serve` subcommand (fishnet_tpu/serve/): bind overrides; None
    # defers to the FISHNET_TPU_SERVE_HOST/_PORT registry settings
    serve_host: Optional[str] = None
    serve_port: Optional[int] = None
    # fleet coordinator (fishnet_tpu/fleet/): --fleet swaps the engine
    # factory's TPU path for a FleetCoordinator over --fleet-members
    # (None defers to FISHNET_TPU_FLEET_MEMBERS); the `fleet` command
    # is `serve` with the coordinator forced on
    fleet: bool = False
    fleet_members: Optional[str] = None
    # elastic capacity (fishnet_tpu/fleet/autoscaler.py): tri-state —
    # unset (None) defers to the FISHNET_TPU_AUTOSCALE registry setting;
    # min/max override the FISHNET_TPU_AUTOSCALE_MIN/_MAX clamp
    autoscale: Optional[bool] = None
    autoscale_min: Optional[int] = None
    autoscale_max: Optional[int] = None
    # analysis-result cache (fishnet_tpu/cache/): --no-cache forces it
    # off regardless of FISHNET_TPU_CACHE; cache_dir overrides
    # FISHNET_TPU_CACHE_DIR for the persisted tier
    cache: bool = True
    cache_dir: Optional[str] = None
    # fleet-ctl: machine-readable output (`fleet-ctl --json list`)
    json_output: bool = False
    # AOT program assets (fishnet_tpu/aot/): `pack` builds a bundle,
    # `warm` installs one. aot_bundle = pack output / warm source;
    # aot_dir = warm's install target. Engines read the store root from
    # FISHNET_TPU_AOT_DIR only — these flags never touch the environment
    aot_bundle: Optional[str] = None
    aot_dir: Optional[str] = None
    conf: Optional[str] = None
    no_conf: bool = False
    verbose: int = 0
    extra_args: List[str] = field(default_factory=list)

    def resolved_key(self) -> Optional[str]:
        if self.key:
            return self.key
        if self.key_file:
            return Path(self.key_file).read_text().strip()
        return None


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="fishnet-tpu",
        description="Distributed analysis client for lichess.org with a TPU engine",
    )
    p.add_argument("command", nargs="?", default="run",
                   choices=["run", "configure", "systemd", "systemd-user",
                            "license", "bench", "serve", "fleet",
                            "pack", "warm", "inflight", "fleet-ctl",
                            "perf"])
    p.add_argument("subargs", nargs="*", default=[],
                   help="subcommand arguments (fleet-ctl: list | "
                        "add SPEC | drain NAME | remove NAME)")
    p.add_argument("--verbose", "-v", action="count", default=0)
    p.add_argument("--auto-update", action="store_true")
    p.add_argument("--conf", help="path to fishnet.ini")
    p.add_argument("--no-conf", action="store_true")
    p.add_argument("--key", help="fishnet key")
    p.add_argument("--key-file", help="file containing the fishnet key")
    p.add_argument("--endpoint", help="API endpoint")
    p.add_argument("--cores", help="number of workers: auto, all, or a number")
    p.add_argument("--backend", choices=["tpu", "subprocess", "python"],
                   help="analysis backend (default tpu)")
    p.add_argument("--engine-path", help="external Stockfish binary (subprocess backend)")
    p.add_argument("--variant-engine-path", help="external Fairy-Stockfish binary")
    p.add_argument("--tpu-weights",
                   help="NNUE weights: our .npz or a Stockfish .nnue file")
    p.add_argument("--tpu-depth", type=int, help="max search depth for the TPU engine")
    p.add_argument("--tpu-helpers", type=int,
                   help="Lazy-SMP helper lanes per position (1 disables)")
    p.add_argument("--no-tpu-refill", action="store_true",
                   help="disable continuous lane refill (strict "
                        "chunk-serial engine dispatch)")
    p.add_argument("--no-tpu-mesh-refill", action="store_true",
                   help="disable shard-aware lane refill on multi-chip "
                        "hosts (meshed engines fall back to chunk-serial "
                        "dispatch; single-device refill is unaffected)")
    p.add_argument("--no-supervisor", action="store_true",
                   help="run the TPU engine in-process instead of in a "
                        "supervised child process")
    p.add_argument("--no-tpu-replay", action="store_true",
                   help="disable partial-progress replay after an engine "
                        "host death (whole-chunk retry semantics)")
    p.add_argument("--tpu-bisect-max", type=int,
                   help="child-death budget for the per-chunk recovery "
                        "ladder (replay/bisect/quarantine)")
    p.add_argument("--no-tpu-quarantine", action="store_true",
                   help="never quarantine isolated poison positions to "
                        "the CPU fallback")
    p.add_argument("--serve-host",
                   help="serve subcommand: bind address (default "
                        "FISHNET_TPU_SERVE_HOST, loopback)")
    p.add_argument("--serve-port", type=int,
                   help="serve subcommand: TCP port; 0 binds an ephemeral "
                        "port (default FISHNET_TPU_SERVE_PORT)")
    p.add_argument("--fleet", action="store_true",
                   help="dispatch work through the fleet coordinator "
                        "(fishnet_tpu/fleet/) instead of one engine; "
                        "implied by the `fleet` command")
    p.add_argument("--fleet-members",
                   help="comma-separated member specs: 'local', 'local*N' "
                        "(supervised host children here) or "
                        "'http://HOST:PORT' (remote serve endpoints); "
                        "default FISHNET_TPU_FLEET_MEMBERS")
    p.add_argument("--autoscale", action="store_true",
                   help="run the elastic-capacity control loop on the "
                        "fleet coordinator (fishnet_tpu/fleet/"
                        "autoscaler.py); requires --fleet")
    p.add_argument("--no-autoscale", action="store_true",
                   help="force the autoscaler off even when "
                        "FISHNET_TPU_AUTOSCALE is set")
    p.add_argument("--autoscale-min", type=int,
                   help="autoscaler member floor (default "
                        "FISHNET_TPU_AUTOSCALE_MIN)")
    p.add_argument("--autoscale-max", type=int,
                   help="autoscaler member ceiling (default "
                        "FISHNET_TPU_AUTOSCALE_MAX)")
    p.add_argument("--no-cache", action="store_true",
                   help="serve subcommand: disable the analysis-result "
                        "cache (fishnet_tpu/cache/) even when "
                        "FISHNET_TPU_CACHE is set")
    p.add_argument("--cache-dir",
                   help="serve subcommand: persisted-cache directory "
                        "(default FISHNET_TPU_CACHE_DIR, "
                        "~/.cache/fishnet-tpu/cache)")
    p.add_argument("--json", action="store_true", dest="json_output",
                   help="fleet-ctl list: print the raw health payload as "
                        "JSON instead of the human table")
    p.add_argument("--aot-bundle",
                   help="pack subcommand: output directory for the AOT "
                        "program bundle (default: the live store); warm "
                        "subcommand: bundle directory to install")
    p.add_argument("--aot-dir",
                   help="warm subcommand: store root to install the bundle "
                        "into (default FISHNET_TPU_AOT_DIR, "
                        "~/.cache/fishnet-tpu/aot); engines read the store "
                        "root from FISHNET_TPU_AOT_DIR at boot")
    p.add_argument("--user-backlog", help="short, long, or duration")
    p.add_argument("--system-backlog", help="short, long, or duration")
    p.add_argument("--max-backoff", help="maximum backoff duration")
    p.add_argument("--cpu-priority", choices=["min", "normal"])
    p.add_argument("--stats-file")
    p.add_argument("--no-stats-file", action="store_true")
    return p


INI_SECTION = "Fishnet"  # reference: src/configure.rs:421


def read_ini(path: Path) -> dict:
    parser = configparser.ConfigParser()
    try:
        parser.read(path)
        if parser.has_section(INI_SECTION):
            return dict(parser.items(INI_SECTION))
    except configparser.Error:
        pass
    # tolerate files without a section header
    try:
        with open(path) as f:
            content = f"[{INI_SECTION}]\n" + f.read()
        parser = configparser.ConfigParser()
        parser.read_string(content)
        return dict(parser.items(INI_SECTION))
    except (OSError, configparser.Error):
        return {}


def write_ini(path: Path, values: dict) -> None:
    parser = configparser.ConfigParser()
    parser[INI_SECTION] = {k: str(v) for k, v in values.items() if v is not None}
    with open(path, "w") as f:
        parser.write(f)


def merge(args: argparse.Namespace, ini: dict) -> Config:
    """CLI wins over ini (reference: src/configure.rs:602-627)."""

    def pick(cli_value, ini_key, default=None):
        if cli_value is not None and cli_value is not False:
            return cli_value
        if ini_key in ini and ini[ini_key] != "":
            return ini[ini_key]
        return default

    cfg = Config()
    cfg.command = args.command
    cfg.verbose = args.verbose
    cfg.auto_update = bool(pick(args.auto_update or None, "auto_update", False))
    cfg.endpoint = str(pick(args.endpoint, "endpoint", cfg.endpoint)).rstrip("/")
    key = pick(args.key, "key")
    cfg.key = validate_key(str(key)) if key else None
    cfg.key_file = pick(args.key_file, "key_file")
    cfg.cores = parse_cores(pick(args.cores, "cores"))
    cfg.backend = str(pick(args.backend, "backend", "tpu"))
    cfg.engine_path = pick(args.engine_path, "engine_path")
    cfg.variant_engine_path = pick(args.variant_engine_path, "variant_engine_path")
    cfg.tpu_weights = pick(args.tpu_weights, "tpu_weights")
    cfg.tpu_depth = int(pick(args.tpu_depth, "tpu_depth", Config.tpu_depth))
    cfg.tpu_helpers = int(pick(args.tpu_helpers, "tpu_helpers", Config.tpu_helpers))
    refill_ini = str(ini.get("tpu_refill", "")).strip().lower()
    cfg.tpu_refill = not (
        args.no_tpu_refill or refill_ini in ("0", "false", "no", "off")
    )
    mesh_refill_ini = str(ini.get("tpu_mesh_refill", "")).strip().lower()
    cfg.tpu_mesh_refill = not (
        args.no_tpu_mesh_refill
        or mesh_refill_ini in ("0", "false", "no", "off")
    )
    supervisor_ini = str(ini.get("supervisor", "")).strip().lower()
    cfg.supervisor = not (
        args.no_supervisor or supervisor_ini in ("0", "false", "no", "off")
    )
    # tri-state recovery knobs: unset (None) defers to the settings
    # registry, so FISHNET_TPU_REPLAY=0 et al. keep working
    replay_ini = str(ini.get("tpu_replay", "")).strip().lower()
    if args.no_tpu_replay or replay_ini in ("0", "false", "no", "off"):
        cfg.tpu_replay = False
    elif replay_ini:
        cfg.tpu_replay = True
    quarantine_ini = str(ini.get("tpu_quarantine", "")).strip().lower()
    if args.no_tpu_quarantine or quarantine_ini in ("0", "false", "no", "off"):
        cfg.tpu_quarantine = False
    elif quarantine_ini:
        cfg.tpu_quarantine = True
    bisect_max = pick(args.tpu_bisect_max, "tpu_bisect_max")
    cfg.tpu_bisect_max = int(bisect_max) if bisect_max is not None else None
    cfg.serve_host = pick(args.serve_host, "serve_host")
    serve_port = pick(args.serve_port, "serve_port")
    cfg.serve_port = int(serve_port) if serve_port is not None else None
    cfg.fleet = bool(args.fleet) or args.command == "fleet" or \
        str(ini.get("fleet", "")).strip().lower() in ("1", "true", "yes", "on")
    cfg.fleet_members = pick(args.fleet_members, "fleet_members")
    # tri-state autoscale: unset (None) defers to FISHNET_TPU_AUTOSCALE
    autoscale_ini = str(ini.get("autoscale", "")).strip().lower()
    if args.no_autoscale or autoscale_ini in ("0", "false", "no", "off"):
        cfg.autoscale = False
    elif args.autoscale or autoscale_ini:
        cfg.autoscale = True
    autoscale_min = pick(args.autoscale_min, "autoscale_min")
    cfg.autoscale_min = int(autoscale_min) if autoscale_min is not None else None
    autoscale_max = pick(args.autoscale_max, "autoscale_max")
    cfg.autoscale_max = int(autoscale_max) if autoscale_max is not None else None
    cache_ini = str(ini.get("cache", "")).strip().lower()
    cfg.cache = not (
        args.no_cache or cache_ini in ("0", "false", "no", "off")
    )
    cfg.cache_dir = pick(args.cache_dir, "cache_dir")
    cfg.json_output = bool(args.json_output)
    cfg.aot_bundle = pick(args.aot_bundle, "aot_bundle")
    cfg.aot_dir = pick(args.aot_dir, "aot_dir")
    cfg.user_backlog = parse_backlog(pick(args.user_backlog, "user_backlog"))
    cfg.system_backlog = parse_backlog(pick(args.system_backlog, "system_backlog"))
    cfg.max_backoff = parse_duration(str(pick(args.max_backoff, "max_backoff", "30s")))
    cfg.cpu_priority = pick(args.cpu_priority, "cpu_priority")
    cfg.stats_file = pick(args.stats_file, "stats_file")
    cfg.no_stats_file = bool(args.no_stats_file)
    cfg.conf = args.conf
    cfg.no_conf = args.no_conf
    cfg.extra_args = list(args.subargs)
    return cfg


def interactive_dialog(cfg: Config, check_key=None, stream=sys.stdout) -> Config:
    """The reference's 5-step first-run dialog (reference:
    src/configure.rs:433-600): endpoint, key (with optional online
    validation), cores, backlog, write fishnet.ini."""

    def ask(prompt: str, default: str = "") -> str:
        suffix = f" ({default})" if default else ""
        stream.write(f"{prompt}{suffix}: ")
        stream.flush()
        line = input().strip()
        return line or default

    endpoint = ask("Endpoint", cfg.endpoint).rstrip("/")
    key = ask("Personal fishnet key (https://lichess.org/get-fishnet)", cfg.key or "")
    key = validate_key(key)
    if key and check_key is not None and not check_key(endpoint, key):
        raise ValueError("key rejected by server")
    cores = ask("Number of logical cores to use", "auto")
    backlog = ask(
        "Analysis backlog: short (user games), long (system), or duration", ""
    )
    cfg.endpoint = endpoint
    cfg.key = key or None
    cfg.cores = parse_cores(cores if cores != "auto" else None)
    cfg.user_backlog = parse_backlog(backlog or None)
    target = ask("Write configuration to", str(Path("fishnet.ini").absolute()))
    write_ini(
        Path(target),
        {
            "endpoint": cfg.endpoint,
            "key": cfg.key,
            "cores": cfg.cores,
            "user_backlog": backlog or None,
        },
    )
    return cfg


def parse_and_configure(argv: Optional[List[str]] = None, interactive: bool = True,
                        check_key=None) -> Config:
    args = build_parser().parse_args(argv)
    ini: dict = {}
    conf_path = Path(args.conf) if args.conf else Path("fishnet.ini")
    if not args.no_conf and conf_path.exists():
        ini = read_ini(conf_path)
    cfg = merge(args, ini)
    needs_dialog = args.command == "configure" or (
        interactive
        and not args.no_conf
        and not conf_path.exists()
        and args.command == "run"
        and sys.stdin.isatty()
    )
    if needs_dialog:
        cfg = interactive_dialog(cfg, check_key=check_key)
    return cfg
