"""Logger and progress UI (reference: src/logger.rs:19-213).

Level prefixes (`D:`, `W:`, `E:`, and the `><> ` fishnet headline), an
in-place `\\r` progress line on TTYs, the ASCII queue gauge
`[===  |=  ]` of pending-positions-vs-cores, and deep links into games
(`https://lichess.org/{game}#{ply}`).
"""
from __future__ import annotations

import sys
import threading
from dataclasses import dataclass
from typing import Optional

# short variant names for the progress line (reference: src/logger.rs:201-213)
SHORT_VARIANT_NAMES = {
    "standard": None,
    "fromPosition": None,
    "chess960": "960",
    "antichess": "anti",
    "atomic": "atomic",
    "crazyhouse": "zh",
    "horde": "horde",
    "kingOfTheHill": "koth",
    "racingKings": "race",
    "threeCheck": "3check",
}


def short_variant_name(variant: str) -> Optional[str]:
    return SHORT_VARIANT_NAMES.get(variant, variant)


@dataclass
class ProgressAt:
    batch_id: str
    batch_url: Optional[str]
    position_index: Optional[int]

    def __str__(self) -> str:
        if self.batch_url:
            frag = f"#{self.position_index}" if self.position_index is not None else ""
            return f"{self.batch_url}{frag}"
        return f"batch {self.batch_id}"


@dataclass
class QueueStatusBar:
    """`[===  |=  ]`: filled to pending positions, bar at cores."""

    pending: int
    cores: int

    def __str__(self) -> str:
        width = max(self.cores, 1)
        total = max(self.pending, 0)
        inside = min(total, width)
        overflow = total - inside
        bar = "=" * inside + " " * (width - inside)
        s = f"[{bar}|{'=' * min(overflow, width)}{' ' * max(0, width - overflow)}]"
        return s


class Logger:
    """Verbosity-gated logger; progress lines rewrite in place on a TTY."""

    def __init__(self, verbose: int = 0, stream=None) -> None:
        self.verbose = verbose
        self.stream = stream or sys.stdout
        self._lock = threading.Lock()
        self._progress_line_len = 0

    def _clear_progress(self) -> None:
        if self._progress_line_len:
            self.stream.write("\r" + " " * self._progress_line_len + "\r")
            self._progress_line_len = 0

    def _emit(self, line: str) -> None:
        with self._lock:
            self._clear_progress()
            self.stream.write(line + "\n")
            self.stream.flush()

    def headline(self, text: str) -> None:
        self._emit(f"><> {text}")

    def info(self, text: str) -> None:
        self._emit(text)

    def debug(self, text: str) -> None:
        if self.verbose > 0:
            self._emit(f"D: {text}")

    def warn(self, text: str) -> None:
        self._emit(f"W: {text}")

    def error(self, text: str) -> None:
        self._emit(f"E: {text}")

    def progress(self, status_bar, progress_at) -> None:
        line = f"{status_bar} {progress_at}"
        with self._lock:
            if self.stream.isatty():
                pad = max(0, self._progress_line_len - len(line))
                self.stream.write("\r" + line + " " * pad)
                self.stream.flush()
                self._progress_line_len = len(line)
            elif self.verbose > 0:
                self.stream.write(line + "\n")
                self.stream.flush()
