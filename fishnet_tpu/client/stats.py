"""Lifetime stats, NPS estimation, and the SQLite time-series sink.

Parity with the reference's StatsRecorder (reference: src/stats.rs:21-231):
JSON counters persisted to ~/.fishnet-stats, NNUE NPS EWMA (α=0.9, seeded
400 knps, uncertainty decay), plus the fork-added SQLite sink (stats.db;
reference: src/stats.rs:158-194 — implemented there against a missing
rusqlite dependency, done here with the stdlib sqlite3 module). Also restores
`min_user_backlog`, which the fork deleted but the queue's backlog logic
requires (call site in reference: src/queue.rs:350-361; intent documented in
reference README.md:83-87 — clients slower than the admission target
self-select out of user-facing work).
"""
from __future__ import annotations

import json
import sqlite3
import time
from dataclasses import asdict, dataclass, field
from pathlib import Path
from typing import List, Optional, Tuple


@dataclass
class Stats:
    total_batches: int = 0
    total_positions: int = 0
    total_nodes: int = 0


class NpsRecorder:
    """EWMA of observed NNUE nodes/sec with decaying uncertainty."""

    def __init__(self, seed_nps: int = 400_000) -> None:
        self.nps = seed_nps  # optimistic prior (reference: src/stats.rs:206)
        self.uncertainty = 1.0

    def record(self, nps: int) -> None:
        alpha = 0.9
        self.uncertainty *= alpha
        self.nps = int(self.nps * alpha + nps * (1.0 - alpha))

    def __str__(self) -> str:
        s = f"{self.nps // 1000} knps/core"
        for threshold in (0.1, 0.4, 0.7):
            if self.uncertainty > threshold:
                s += "?" if s.endswith("?") else " ?"
        return s


class StatsRecorder:
    def __init__(
        self,
        stats_file: Optional[Path] = None,
        no_stats_file: bool = False,
        db_file: Optional[Path] = None,
        cores: int = 1,
    ) -> None:
        self.cores = cores
        self.nnue_nps = NpsRecorder()
        self.stats = Stats()
        self._path: Optional[Path] = None
        self._db: Optional[sqlite3.Connection] = None
        # latest SupervisorStats snapshot (engine/supervisor.py), if any
        self.last_supervisor: Optional[dict] = None

        if not no_stats_file:
            self._path = stats_file or (Path.home() / ".fishnet-stats")
            try:
                if self._path.exists() and self._path.stat().st_size > 0:
                    self.stats = Stats(**json.loads(self._path.read_text()))
            except (OSError, ValueError, TypeError):
                self.stats = Stats()
            if db_file is not None:
                try:
                    self._db = sqlite3.connect(str(db_file))
                    self._db.execute(
                        "CREATE TABLE IF NOT EXISTS stats ("
                        " id INTEGER PRIMARY KEY AUTOINCREMENT,"
                        " timestamp INTEGER NOT NULL,"
                        " total_batches INTEGER NOT NULL,"
                        " total_positions INTEGER NOT NULL,"
                        " total_nodes INTEGER NOT NULL,"
                        " nnue_nps INTEGER NOT NULL)"
                    )
                    # supervisor recovery time series (engine/supervisor.py
                    # SupervisorStats snapshots + the quarantine event log);
                    # read back by tools/occupancy_report.py --stats-db
                    self._db.execute(
                        "CREATE TABLE IF NOT EXISTS supervisor_stats ("
                        " id INTEGER PRIMARY KEY AUTOINCREMENT,"
                        " timestamp INTEGER NOT NULL,"
                        " counters TEXT NOT NULL)"
                    )
                    self._db.execute(
                        "CREATE TABLE IF NOT EXISTS supervisor_quarantine ("
                        " id INTEGER PRIMARY KEY AUTOINCREMENT,"
                        " timestamp INTEGER NOT NULL,"
                        " fingerprint TEXT NOT NULL,"
                        " batch_id TEXT,"
                        " position_index INTEGER)"
                    )
                    # metrics registry fold-in (obs/metrics.py snapshot):
                    # one row per (summary tick, metric), so the sqlite
                    # sink carries the same series the Prometheus
                    # endpoint exposes
                    self._db.execute(
                        "CREATE TABLE IF NOT EXISTS metrics ("
                        " id INTEGER PRIMARY KEY AUTOINCREMENT,"
                        " timestamp INTEGER NOT NULL,"
                        " name TEXT NOT NULL,"
                        " value REAL NOT NULL)"
                    )
                    self._db.commit()
                except sqlite3.Error:
                    self._db = None

    def record_batch(self, positions: int, nodes: int, nnue_nps: Optional[int]) -> None:
        self.stats.total_batches += 1
        self.stats.total_positions += positions
        self.stats.total_nodes += nodes
        if nnue_nps is not None:
            self.nnue_nps.record(nnue_nps)
        if self._path is not None:
            try:
                self._path.write_text(json.dumps(asdict(self.stats), indent=2))
            except OSError:
                pass
        if self._db is not None:
            try:
                self._db.execute(
                    "INSERT INTO stats (timestamp, total_batches, total_positions,"
                    " total_nodes, nnue_nps) VALUES (?, ?, ?, ?, ?)",
                    (
                        # report timestamp, not a duration — wall clock
                        # is the sanctioned form here
                        int(time.time()),  # fishnet-lint: disable=obs-wall-clock
                        self.stats.total_batches,
                        self.stats.total_positions,
                        self.stats.total_nodes,
                        nnue_nps or 0,
                    ),
                )
                self._db.commit()
            except sqlite3.Error:
                pass

    def record_supervisor(self, counters: dict) -> None:
        """Persist one SupervisorStats snapshot (dict of plain counters)
        into the time-series sink; latest kept in memory regardless."""
        self.last_supervisor = dict(counters)
        if self._db is not None:
            try:
                self._db.execute(
                    "INSERT INTO supervisor_stats (timestamp, counters)"
                    " VALUES (?, ?)",
                    # report timestamp (see record_metrics)
                    # fishnet-lint: disable=obs-wall-clock
                    (int(time.time()), json.dumps(self.last_supervisor)),
                )
                self._db.commit()
            except sqlite3.Error:
                pass

    def record_quarantine(
        self,
        fingerprint: str,
        batch_id: Optional[str] = None,
        position_index: Optional[int] = None,
    ) -> None:
        """Persist one poison-position quarantine event (called from the
        supervisor's recovery ladder)."""
        if self._db is not None:
            try:
                self._db.execute(
                    "INSERT INTO supervisor_quarantine"
                    " (timestamp, fingerprint, batch_id, position_index)"
                    " VALUES (?, ?, ?, ?)",
                    # report timestamp (see record_metrics)
                    # fishnet-lint: disable=obs-wall-clock
                    (int(time.time()), fingerprint, batch_id, position_index),
                )
                self._db.commit()
            except sqlite3.Error:
                pass

    def record_metrics(self, snapshot: dict) -> None:
        """Fold one metrics-registry snapshot (obs/metrics.py: flat
        name → value) into the time-series sink on the summary cadence."""
        if self._db is None or not snapshot:
            return
        # wall clock is the sanctioned form for REPORT timestamps (rows
        # correlated with external logs), not durations
        ts = int(time.time())  # fishnet-lint: disable=obs-wall-clock
        try:
            self._db.executemany(
                "INSERT INTO metrics (timestamp, name, value)"
                " VALUES (?, ?, ?)",
                [(ts, name, float(value))
                 for name, value in sorted(snapshot.items())],
            )
            self._db.commit()
        except sqlite3.Error:
            pass

    # ------------------------------------------------- analysis cache index
    #
    # The fleet-wide analysis cache (fishnet_tpu/cache/store.py) keeps
    # its restart-surviving index here: one row per cached shape key
    # pointing at a payload file whose sha256 the loader verifies
    # (corruption quarantines the file with a `.bad` rename, mirroring
    # aot/registry.py). cache_meta pins the engine identity fingerprint
    # the entries were searched under — a mismatch at open invalidates
    # the whole store (docs/caching.md).

    def ensure_cache_tables(self) -> bool:
        """Create the analysis-cache tables; False if no db sink."""
        if self._db is None:
            return False
        try:
            self._db.execute(
                "CREATE TABLE IF NOT EXISTS analysis_cache ("
                " row_id TEXT PRIMARY KEY,"
                " timestamp INTEGER NOT NULL,"
                " key TEXT NOT NULL,"
                " depth INTEGER NOT NULL,"
                " sha256 TEXT NOT NULL,"
                " nbytes INTEGER NOT NULL,"
                " filename TEXT NOT NULL)"
            )
            self._db.execute(
                "CREATE TABLE IF NOT EXISTS cache_meta ("
                " key TEXT PRIMARY KEY,"
                " value TEXT NOT NULL)"
            )
            self._db.commit()
            return True
        except sqlite3.Error:
            return False

    def cache_identity(self) -> Optional[str]:
        if self._db is None:
            return None
        try:
            row = self._db.execute(
                "SELECT value FROM cache_meta WHERE key = 'identity'"
            ).fetchone()
            return row[0] if row else None
        except sqlite3.Error:
            return None

    def set_cache_identity(self, identity: str) -> None:
        if self._db is None:
            return
        try:
            self._db.execute(
                "INSERT OR REPLACE INTO cache_meta (key, value)"
                " VALUES ('identity', ?)",
                (identity,),
            )
            self._db.commit()
        except sqlite3.Error:
            pass

    def cache_put(
        self,
        row_id: str,
        key_json: str,
        depth: int,
        sha256: str,
        nbytes: int,
        filename: str,
    ) -> None:
        if self._db is None:
            return
        try:
            self._db.execute(
                "INSERT OR REPLACE INTO analysis_cache"
                " (row_id, timestamp, key, depth, sha256, nbytes, filename)"
                " VALUES (?, ?, ?, ?, ?, ?, ?)",
                # report timestamp (see record_metrics)
                # fishnet-lint: disable=obs-wall-clock
                (row_id, int(time.time()), key_json, depth, sha256,
                 nbytes, filename),
            )
            self._db.commit()
        except sqlite3.Error:
            pass

    def cache_rows(self) -> List[Tuple[str, str, int, str, int, str]]:
        """The whole persisted index, oldest first:
        (row_id, key_json, depth, sha256, nbytes, filename)."""
        if self._db is None:
            return []
        try:
            return list(self._db.execute(
                "SELECT row_id, key, depth, sha256, nbytes, filename"
                " FROM analysis_cache ORDER BY timestamp, row_id"
            ))
        except sqlite3.Error:
            return []

    def cache_delete(self, row_id: str) -> None:
        if self._db is None:
            return
        try:
            self._db.execute(
                "DELETE FROM analysis_cache WHERE row_id = ?", (row_id,)
            )
            self._db.commit()
        except sqlite3.Error:
            pass

    def cache_clear(self) -> int:
        """Drop every persisted entry (identity invalidation); returns
        how many rows were dropped."""
        if self._db is None:
            return 0
        try:
            n = self._db.execute(
                "SELECT COUNT(*) FROM analysis_cache"
            ).fetchone()[0]
            self._db.execute("DELETE FROM analysis_cache")
            self._db.commit()
            return int(n)
        except sqlite3.Error:
            return 0

    def cache_trim(self, max_entries: int) -> List[str]:
        """Enforce the on-disk entry cap, oldest rows first; returns
        the payload filenames of the dropped rows so the caller can
        unlink them."""
        if self._db is None or max_entries < 0:
            return []
        try:
            rows = list(self._db.execute(
                "SELECT row_id, filename FROM analysis_cache"
                " ORDER BY timestamp DESC, row_id DESC"
                " LIMIT -1 OFFSET ?", (max_entries,)
            ))
            if rows:
                self._db.executemany(
                    "DELETE FROM analysis_cache WHERE row_id = ?",
                    [(r[0],) for r in rows],
                )
                self._db.commit()
            return [r[1] for r in rows]
        except sqlite3.Error:
            return []

    # --------------------------------------------------------- perf ledger
    #
    # The longitudinal perf ledger (fishnet_tpu/obs/perf.py, docs/perf.md)
    # shares this sink's plumbing: same schema helpers, so the client's
    # stats.db can carry the perf_ledger table next to the stats/metrics
    # time series, while bench.py and tools/perf_report.py use their own
    # standalone ledger file at the checkout root.

    def ensure_perf_table(self) -> bool:
        """Create the perf_ledger table; False if no db sink."""
        if self._db is None:
            return False
        try:
            from ..obs.perf import ensure_perf_table

            ensure_perf_table(self._db)
            self._db.commit()
            return True
        except sqlite3.Error:
            return False

    def record_perf(self, run_id: str, rows: dict, **kw) -> int:
        """Ingest one run's bench_row → {metric: value} table into the
        perf ledger (obs/perf.py insert_perf_rows); returns rows
        written, 0 when there is no db sink."""
        if self._db is None:
            return 0
        try:
            from ..obs.perf import insert_perf_rows

            return insert_perf_rows(self._db, run_id, rows, **kw)
        except sqlite3.Error:
            return 0

    def min_user_backlog(self) -> float:
        """Seconds of user-queue backlog below which this client should not
        take user-facing jobs: clients slower than the ~2 Mnodes / 6 s
        admission target (reference README.md:83-87) wait until the user
        queue has aged. A typical batch is ~60 positions × ~2.25 Mnodes;
        top-end clients clear it in ~35 s.
        """
        best_batch_seconds = 35.0
        typical_batch_nodes = 60 * 2_250_000
        batch_seconds = typical_batch_nodes / max(self.nnue_nps.nps, 1)
        return max(0.0, batch_seconds - best_batch_seconds)

    def close(self) -> None:
        if self._db is not None:
            self._db.close()
            self._db = None
