"""Wire model for the fishnet HTTP protocol.

Mirrors the serde types of the reference client (reference: src/api.rs:120-403
and doc/protocol.md) as plain dataclasses with explicit to/from JSON-dict
conversion. The protocol is the compatibility contract: a lichess server (or
lila-fishnet) must not be able to tell this client from the reference.
"""
from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import List, Optional, Union

MAX_CHUNK_POSITIONS = 6  # reference: src/ipc.rs:23


class EngineFlavor(enum.Enum):
    """Which engine backend a chunk is routed to.

    The reference has Official (Stockfish) and MultiVariant (Fairy-Stockfish)
    (reference: src/assets.rs:124-137); this framework adds TPU, the batched
    JAX/XLA engine.
    """

    OFFICIAL = "official"
    MULTI_VARIANT = "multivariant"
    TPU = "tpu"

    def eval_flavor(self) -> "EvalFlavor":
        # Official runs NNUE, MultiVariant runs HCE (reference:
        # src/assets.rs:130-137); the TPU engine evaluates with NNUE weights.
        if self is EngineFlavor.MULTI_VARIANT:
            return EvalFlavor.HCE
        return EvalFlavor.NNUE


class EvalFlavor(enum.Enum):
    NNUE = "nnue"
    HCE = "classical"

    def to_json(self) -> str:
        return self.value


@dataclass(frozen=True)
class NodeLimit:
    """Per-position node budget keyed by engine generation.

    `get` pre-scales by MAX/(MAX+1) to pay for the chunk-overlap position
    (reference: src/api.rs:220-233).
    """

    sf16: int
    classical: int

    def get(self, flavor: EvalFlavor) -> int:
        base = self.classical if flavor is EvalFlavor.HCE else self.sf16
        return base * MAX_CHUNK_POSITIONS // (MAX_CHUNK_POSITIONS + 1)

    @staticmethod
    def from_json(obj: dict) -> "NodeLimit":
        return NodeLimit(sf16=int(obj["sf16"]), classical=int(obj["classical"]))


# Skill level 1-8 → (movetime ms, engine Skill Level, depth)
# (reference: src/api.rs:248-283)
_SKILL_TABLE = {
    1: (50, -9, 5),
    2: (100, -5, 5),
    3: (150, -1, 5),
    4: (200, 3, 5),
    5: (300, 7, 5),
    6: (400, 11, 8),
    7: (500, 16, 13),
    8: (1000, 20, 22),
}


@dataclass(frozen=True)
class SkillLevel:
    level: int  # 1..8

    def __post_init__(self):
        if not 1 <= self.level <= 8:
            raise ValueError(f"skill level out of range: {self.level}")

    @property
    def movetime_ms(self) -> int:
        return _SKILL_TABLE[self.level][0]

    @property
    def engine_skill_level(self) -> int:
        return _SKILL_TABLE[self.level][1]

    @property
    def depth(self) -> int:
        return _SKILL_TABLE[self.level][2]


@dataclass(frozen=True)
class Clock:
    wtime_centis: int
    btime_centis: int
    inc_seconds: int

    @staticmethod
    def from_json(obj: dict) -> "Clock":
        return Clock(
            wtime_centis=int(obj["wtime"]),
            btime_centis=int(obj["btime"]),
            inc_seconds=int(obj["inc"]),
        )


@dataclass(frozen=True)
class AnalysisWork:
    id: str
    nodes: NodeLimit
    timeout_s: float  # per ply
    depth: Optional[int] = None
    multipv: Optional[int] = None

    def timeout_per_ply(self) -> float:
        return self.timeout_s

    @property
    def is_analysis(self) -> bool:
        return True

    @property
    def is_move(self) -> bool:
        return False

    def effective_multipv(self) -> int:
        return self.multipv or 1

    def matrix_wanted(self) -> bool:
        return self.multipv is not None


@dataclass(frozen=True)
class MoveWork:
    id: str
    level: SkillLevel
    clock: Optional[Clock] = None

    def timeout_per_ply(self) -> float:
        return 7.0  # reference: src/api.rs:163-168

    @property
    def is_analysis(self) -> bool:
        return False

    @property
    def is_move(self) -> bool:
        return True

    def effective_multipv(self) -> int:
        return 1

    def matrix_wanted(self) -> bool:
        return False


Work = Union[AnalysisWork, MoveWork]


def work_to_json(work: "Work") -> dict:
    """Inverse of work_from_json (same shapes the server sends) — used by
    the supervisor↔host pipe protocol to ship chunks across the process
    boundary (engine/supervisor.py)."""
    if isinstance(work, AnalysisWork):
        out: dict = {
            "type": "analysis",
            "id": work.id,
            "nodes": {"sf16": work.nodes.sf16, "classical": work.nodes.classical},
            "timeout": int(work.timeout_s * 1000),
        }
        if work.depth is not None:
            out["depth"] = work.depth
        if work.multipv is not None:
            out["multipv"] = work.multipv
        return out
    assert isinstance(work, MoveWork)
    out = {"type": "move", "id": work.id, "level": work.level.level}
    if work.clock is not None:
        out["clock"] = {
            "wtime": work.clock.wtime_centis,
            "btime": work.clock.btime_centis,
            "inc": work.clock.inc_seconds,
        }
    return out


def work_from_json(obj: dict) -> Work:
    batch_id = str(obj["id"])
    if len(batch_id) > 24:
        raise ValueError(f"batch id too long: {batch_id!r}")
    if obj.get("type") == "analysis":
        return AnalysisWork(
            id=batch_id,
            nodes=NodeLimit.from_json(obj["nodes"]),
            timeout_s=int(obj["timeout"]) / 1000.0,
            depth=int(obj["depth"]) if obj.get("depth") is not None else None,
            multipv=int(obj["multipv"]) if obj.get("multipv") is not None else None,
        )
    if obj.get("type") == "move":
        clock = obj.get("clock")
        return MoveWork(
            id=batch_id,
            level=SkillLevel(int(obj["level"])),
            clock=Clock.from_json(clock) if clock else None,
        )
    raise ValueError(f"unknown work type: {obj.get('type')!r}")


@dataclass
class AcquireResponseBody:
    work: Work
    position: str  # X-FEN
    variant: str = "standard"
    moves: List[str] = field(default_factory=list)
    skip_positions: List[int] = field(default_factory=list)
    game_id: Optional[str] = None

    @staticmethod
    def from_json(obj: dict) -> "AcquireResponseBody":
        game_id = obj.get("game_id") or None  # empty string → None
        moves_field = obj.get("moves", "")
        moves = moves_field.split() if isinstance(moves_field, str) else list(moves_field)
        return AcquireResponseBody(
            work=work_from_json(obj["work"]),
            game_id=game_id,
            position=obj.get("position", STARTING_FEN_DEFAULT),
            variant=obj.get("variant") or "standard",
            moves=moves,
            skip_positions=[int(i) for i in obj.get("skipPositions", [])],
        )

    def batch_url(self, endpoint_url: str) -> Optional[str]:
        if not self.game_id:
            return None
        from urllib.parse import urlsplit, urlunsplit

        parts = urlsplit(endpoint_url)
        return urlunsplit((parts.scheme, parts.netloc, f"/{self.game_id}", "", ""))


STARTING_FEN_DEFAULT = "rnbqkbnr/pppppppp/8/8/8/8/PPPPPPPP/RNBQKBNR w KQkq - 0 1"


@dataclass(frozen=True)
class Score:
    """Either a centipawn or a mate score (reference: src/api.rs:391-397)."""

    kind: str  # "cp" | "mate"
    value: int

    def to_json(self) -> dict:
        return {self.kind: self.value}

    @staticmethod
    def cp(value: int) -> "Score":
        return Score("cp", value)

    @staticmethod
    def mate(value: int) -> "Score":
        return Score("mate", value)

    @staticmethod
    def from_json(obj: dict) -> "Score":
        if "cp" in obj:
            return Score.cp(int(obj["cp"]))
        if "mate" in obj:
            return Score.mate(int(obj["mate"]))
        raise ValueError(f"score is neither cp nor mate: {obj!r}")


@dataclass
class AnalysisPartSkipped:
    def to_json(self) -> dict:
        return {"skipped": True}


@dataclass
class AnalysisPartBest:
    pv: List[str]
    score: Score
    depth: int
    nodes: int
    time_ms: int
    nps: Optional[int] = None

    def to_json(self) -> dict:
        out = {
            "score": self.score.to_json(),
            "depth": self.depth,
            "nodes": self.nodes,
            "time": self.time_ms,
        }
        if self.pv:
            out["pv"] = " ".join(self.pv)
        if self.nps is not None:
            out["nps"] = self.nps
        return out


@dataclass
class AnalysisPartMatrix:
    """Full multipv×depth matrices (reference: src/api.rs:380-389)."""

    pv: List[List[Optional[List[str]]]]
    score: List[List[Optional[Score]]]
    depth: int
    nodes: int
    time_ms: int
    nps: Optional[int] = None

    def to_json(self) -> dict:
        # matrix pv stays a nested array of UCI-move lists (reference:
        # src/api.rs:381 — no string-join on the Matrix variant)
        out = {
            "pv": [
                [list(pv) if pv is not None else None for pv in row]
                for row in self.pv
            ],
            "score": [
                [s.to_json() if s is not None else None for s in row]
                for row in self.score
            ],
            "depth": self.depth,
            "nodes": self.nodes,
            "time": self.time_ms,
        }
        if self.nps is not None:
            out["nps"] = self.nps
        return out


AnalysisPart = Union[AnalysisPartSkipped, AnalysisPartBest, AnalysisPartMatrix]
