"""Work types crossing the queue↔worker↔engine boundary.

Python analogue of the reference's IPC layer (reference: src/ipc.rs:13-118).
In this framework a "chunk" is also the unit handed to the TPU engine, which
may batch many chunks into one device dispatch.
"""
from __future__ import annotations

import hashlib
from dataclasses import dataclass, field
from typing import List, Optional

from ..obs.trace import ctx_from_wire as _ctx_from_wire
from .wire import (
    AnalysisPartBest,
    AnalysisPartMatrix,
    EngineFlavor,
    MAX_CHUNK_POSITIONS,
    Score,
    Work,
)


@dataclass
class WorkPosition:
    """One position to analyse (reference: src/ipc.rs:26-35).

    position_index None marks a chunk-overlap warm-up position whose result
    is discarded (reference: src/queue.rs:642-681).

    ctx is the request context stamped at the edge that created this
    position (obs/trace.py make_ctx: trace_id/span_id/tenant/kind/
    deadline_ms) or None when tracing is off / the request unsampled.
    Pure observability metadata: it rides the wire next to the position
    so supervisor replay and fleet re-dispatch — which reuse the same
    WorkPosition objects — keep the causal chain, but it never reaches
    an engine input and is excluded from position fingerprints.
    """

    work: Work
    position_index: Optional[int]
    url: Optional[str]
    skip: bool
    root_fen: str
    moves: List[str]
    ctx: Optional[dict] = None


@dataclass
class Chunk:
    """≤6 positions dispatched to one engine as a unit (src/ipc.rs:13-24)."""

    work: Work
    deadline: float  # time.monotonic() timestamp
    variant: str
    flavor: EngineFlavor
    positions: List[WorkPosition]

    MAX_POSITIONS = MAX_CHUNK_POSITIONS


class Matrix:
    """Sparse [multipv-1][depth] matrix; best() = first row, last entry
    (reference: src/ipc.rs:76-96)."""

    def __init__(self) -> None:
        self.matrix: List[List[Optional[object]]] = []

    def set(self, multipv: int, depth: int, value) -> None:
        row_idx = multipv - 1
        while len(self.matrix) <= row_idx:
            self.matrix.append([])
        row = self.matrix[row_idx]
        while len(row) <= depth:
            row.append(None)
        row[depth] = value

    def best(self):
        if not self.matrix or not self.matrix[0]:
            return None
        return self.matrix[0][-1]


@dataclass
class PositionResponse:
    """Result for one position (reference: src/ipc.rs:37-74)."""

    work: Work
    position_index: Optional[int]
    url: Optional[str]
    scores: Matrix
    pvs: Matrix
    best_move: Optional[str]
    depth: int
    nodes: int
    time_s: float
    nps: Optional[int] = None

    def to_best(self) -> AnalysisPartBest:
        best_score = self.scores.best()
        assert best_score is not None, "position response without score"
        pv = self.pvs.best()
        return AnalysisPartBest(
            pv=list(pv) if pv else [],
            score=best_score,
            depth=self.depth,
            nodes=self.nodes,
            time_ms=int(self.time_s * 1000),
            nps=self.nps,
        )

    def into_matrix(self) -> AnalysisPartMatrix:
        return AnalysisPartMatrix(
            pv=[list(row) for row in self.pvs.matrix],
            score=[list(row) for row in self.scores.matrix],
            depth=self.depth,
            nodes=self.nodes,
            time_ms=int(self.time_s * 1000),
            nps=self.nps,
        )


# ------------------------------------------------------------ fingerprints
#
# Stable identity of one position across child respawns and sub-chunk
# re-dispatches: the supervisor's session journal, quarantine list, and
# the host's `partial` frames all key on this (engine/supervisor.py).
# Content-addressed (root_fen + moves + position_index), NOT keyed on
# chunk/batch ids — the same poison position re-acquired in a later
# batch must hit the quarantine list again.


def _fingerprint(root_fen: str, moves: List[str], position_index) -> str:
    key = "\x00".join([root_fen, " ".join(moves), str(position_index)])
    return hashlib.sha256(key.encode("utf-8")).hexdigest()[:16]


def position_fingerprint(wp: WorkPosition) -> str:
    return _fingerprint(wp.root_fen, wp.moves, wp.position_index)


def wire_position_fingerprint(p: dict) -> str:
    """Same hash over the chunk wire-dict form (engine/fakehost.py
    computes fingerprints without constructing WorkPosition objects)."""
    return _fingerprint(p["root_fen"], list(p["moves"]), p["position_index"])


# -------------------------------------------------------- pipe-wire serde
#
# JSON-dict conversion for Chunk and PositionResponse, used by the
# supervisor↔host pipe protocol (engine/supervisor.py, engine/host.py).
# Deadlines are time.monotonic() timestamps, which do NOT transfer across
# processes — the wire form carries remaining seconds ("ttl") and each side
# re-anchors against its own clock.


def _matrix_to_wire(matrix: Matrix, cell) -> list:
    return [[None if v is None else cell(v) for v in row] for row in matrix.matrix]


def _matrix_from_wire(rows: list, cell) -> Matrix:
    m = Matrix()
    m.matrix = [[None if v is None else cell(v) for v in row] for row in rows]
    return m


def chunk_to_wire(chunk: Chunk) -> dict:
    import time

    from .wire import work_to_json

    return {
        "work": work_to_json(chunk.work),
        "ttl": chunk.deadline - time.monotonic(),
        "variant": chunk.variant,
        "flavor": chunk.flavor.value,
        "positions": [
            {
                "position_index": wp.position_index,
                "url": wp.url,
                "skip": wp.skip,
                "root_fen": wp.root_fen,
                "moves": wp.moves,
                "ctx": wp.ctx,
            }
            for wp in chunk.positions
        ],
    }


def chunk_from_wire(obj: dict) -> Chunk:
    import time

    from .wire import work_from_json

    work = work_from_json(obj["work"])
    return Chunk(
        work=work,
        deadline=time.monotonic() + float(obj["ttl"]),
        variant=obj["variant"],
        flavor=EngineFlavor(obj["flavor"]),
        positions=[
            WorkPosition(
                work=work,
                position_index=p["position_index"],
                url=p["url"],
                skip=p["skip"],
                root_fen=p["root_fen"],
                moves=list(p["moves"]),
                ctx=_ctx_from_wire(p.get("ctx")),
            )
            for p in obj["positions"]
        ],
    )


def response_to_wire(res: PositionResponse) -> dict:
    return {
        "position_index": res.position_index,
        "url": res.url,
        "scores": _matrix_to_wire(res.scores, lambda s: s.to_json()),
        "pvs": _matrix_to_wire(res.pvs, list),
        "best_move": res.best_move,
        "depth": res.depth,
        "nodes": res.nodes,
        "time_s": res.time_s,
        "nps": res.nps,
    }


def responses_from_wire(work: Work, objs: List[dict]) -> List[PositionResponse]:
    return [
        PositionResponse(
            work=work,
            position_index=o["position_index"],
            url=o["url"],
            scores=_matrix_from_wire(o["scores"], Score.from_json),
            pvs=_matrix_from_wire(o["pvs"], list),
            best_move=o["best_move"],
            depth=int(o["depth"]),
            nodes=int(o["nodes"]),
            time_s=float(o["time_s"]),
            nps=int(o["nps"]) if o.get("nps") is not None else None,
        )
        for o in objs
    ]


class ChunkFailed(Exception):
    """Engine-side failure; the batch is forgotten so the server re-queues it
    by timeout (reference: src/queue.rs:226-233)."""

    def __init__(self, batch_id: str):
        super().__init__(f"chunk failed for batch {batch_id}")
        self.batch_id = batch_id
