"""Work types crossing the queue↔worker↔engine boundary.

Python analogue of the reference's IPC layer (reference: src/ipc.rs:13-118).
In this framework a "chunk" is also the unit handed to the TPU engine, which
may batch many chunks into one device dispatch.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional

from .wire import (
    AnalysisPartBest,
    AnalysisPartMatrix,
    EngineFlavor,
    MAX_CHUNK_POSITIONS,
    Score,
    Work,
)


@dataclass
class WorkPosition:
    """One position to analyse (reference: src/ipc.rs:26-35).

    position_index None marks a chunk-overlap warm-up position whose result
    is discarded (reference: src/queue.rs:642-681).
    """

    work: Work
    position_index: Optional[int]
    url: Optional[str]
    skip: bool
    root_fen: str
    moves: List[str]


@dataclass
class Chunk:
    """≤6 positions dispatched to one engine as a unit (src/ipc.rs:13-24)."""

    work: Work
    deadline: float  # time.monotonic() timestamp
    variant: str
    flavor: EngineFlavor
    positions: List[WorkPosition]

    MAX_POSITIONS = MAX_CHUNK_POSITIONS


class Matrix:
    """Sparse [multipv-1][depth] matrix; best() = first row, last entry
    (reference: src/ipc.rs:76-96)."""

    def __init__(self) -> None:
        self.matrix: List[List[Optional[object]]] = []

    def set(self, multipv: int, depth: int, value) -> None:
        row_idx = multipv - 1
        while len(self.matrix) <= row_idx:
            self.matrix.append([])
        row = self.matrix[row_idx]
        while len(row) <= depth:
            row.append(None)
        row[depth] = value

    def best(self):
        if not self.matrix or not self.matrix[0]:
            return None
        return self.matrix[0][-1]


@dataclass
class PositionResponse:
    """Result for one position (reference: src/ipc.rs:37-74)."""

    work: Work
    position_index: Optional[int]
    url: Optional[str]
    scores: Matrix
    pvs: Matrix
    best_move: Optional[str]
    depth: int
    nodes: int
    time_s: float
    nps: Optional[int] = None

    def to_best(self) -> AnalysisPartBest:
        best_score = self.scores.best()
        assert best_score is not None, "position response without score"
        pv = self.pvs.best()
        return AnalysisPartBest(
            pv=list(pv) if pv else [],
            score=best_score,
            depth=self.depth,
            nodes=self.nodes,
            time_ms=int(self.time_s * 1000),
            nps=self.nps,
        )

    def into_matrix(self) -> AnalysisPartMatrix:
        return AnalysisPartMatrix(
            pv=[list(row) for row in self.pvs.matrix],
            score=[list(row) for row in self.scores.matrix],
            depth=self.depth,
            nodes=self.nodes,
            time_ms=int(self.time_s * 1000),
            nps=self.nps,
        )


class ChunkFailed(Exception):
    """Engine-side failure; the batch is forgotten so the server re-queues it
    by timeout (reference: src/queue.rs:226-233)."""

    def __init__(self, batch_id: str):
        super().__init__(f"chunk failed for batch {batch_id}")
        self.batch_id = batch_id
