"""Application core: wires config, queue, workers, engines, and signals.

Parity with the reference's orchestrator (reference: src/main.rs:44-261):
N workers, graceful SIGINT (second SIGINT aborts), SIGTERM immediate, the
120 s summary line, background auto-update every 5 h, CPU priority, and
abort-on-shutdown of pending batches.
"""
from __future__ import annotations

import asyncio
import os
import signal
from dataclasses import asdict
from pathlib import Path
from typing import Optional, Set

from ..engine.pyengine import PyEngine
from ..obs import metrics as obs_metrics
from ..obs import perf as obs_perf
from ..obs import trace as obs_trace
from ..utils import settings
from .api import ApiClient, ApiError, Endpoint
from .configure import Config
from .logger import Logger
from .queue import BacklogOpt, Queue
from .stats import StatsRecorder
from .update import auto_update, restart_process
from .wire import EngineFlavor
from .workers import worker

SUMMARY_INTERVAL_S = 120.0  # reference: src/main.rs:202-214
UPDATE_INTERVAL_S = 5 * 3600.0  # reference: src/main.rs:180-200


async def _http_get(url: str) -> bytes:
    import urllib.request

    def fetch() -> bytes:
        with urllib.request.urlopen(url, timeout=30.0) as r:
            return r.read()

    return await asyncio.to_thread(fetch)


def tpu_variants_for(cfg: Config) -> Optional[Set[str]]:
    if cfg.backend != "tpu":
        return None
    # all seven lichess variants run on device
    # (engine/tpu.py DEVICE_VARIANTS; ops/ variant static flags)
    return {
        "standard", "chess960", "fromPosition", "threeCheck", "crazyhouse",
        "antichess", "atomic", "horde", "kingOfTheHill", "racingKings",
    }


def make_engine_factory(cfg: Config, logger: Logger, stats=None):
    tpu_engine = None

    def factory(flavor: EngineFlavor):
        nonlocal tpu_engine
        if cfg.fleet:
            # fleet mode: every flavor feeds the one coordinator
            # (fishnet_tpu/fleet/) — it spreads position work over N
            # members (supervised host children here, or remote serve
            # endpoints) behind the same Engine protocol, so workers,
            # serve and bench need no other change
            if tpu_engine is None:
                from ..fleet import FleetCoordinator
                from ..fleet.member import (
                    make_local_member,
                    members_from_specs,
                )

                # the engine host child speaks --backend tpu|py; the
                # CLI's "python" backend maps to its "py"
                backend = (
                    "py" if cfg.backend == "python"
                    else "tpu"
                )

                def local_factory(name: str):
                    return make_local_member(
                        name,
                        backend=backend,
                        weights_path=cfg.tpu_weights,
                        max_depth=cfg.tpu_depth,
                        helper_lanes=cfg.tpu_helpers,
                        refill=cfg.tpu_refill,
                        mesh_refill=cfg.tpu_mesh_refill,
                        logger=logger,
                        stats_recorder=stats,
                    )

                tpu_engine = FleetCoordinator(
                    members_from_specs(
                        cfg.fleet_members,
                        local_factory=local_factory,
                        logger=logger,
                    ),
                    logger=logger,
                    # runtime membership (POST /fleet/members, fleet-ctl):
                    # an added 'local' member builds through the same
                    # Config-closed factory as the boot-time ones
                    local_factory=local_factory,
                )
            return tpu_engine
        if flavor is EngineFlavor.TPU:
            if tpu_engine is None:
                if cfg.supervisor:
                    # device work runs in a killable child process behind
                    # the supervisor proxy (engine/supervisor.py): a wedged
                    # device gets SIGKILLed and respawned instead of
                    # wedging this process's executor threads forever
                    from ..engine.supervisor import SupervisedEngine

                    tpu_engine = SupervisedEngine(
                        backend="tpu",
                        weights_path=cfg.tpu_weights,
                        max_depth=cfg.tpu_depth,
                        helper_lanes=cfg.tpu_helpers,
                        refill=cfg.tpu_refill,
                        mesh_refill=cfg.tpu_mesh_refill,
                        logger=logger,
                        replay=cfg.tpu_replay,
                        bisect_max=cfg.tpu_bisect_max,
                        quarantine=cfg.tpu_quarantine,
                        stats_recorder=stats,
                    )
                else:
                    from ..engine.tpu import TpuEngine

                    tpu_engine = TpuEngine(
                        weights_path=cfg.tpu_weights,
                        max_depth=cfg.tpu_depth,
                        helper_lanes=cfg.tpu_helpers,
                        refill=cfg.tpu_refill,
                        mesh_refill=cfg.tpu_mesh_refill,
                        logger=logger,
                    )
            # one device program (or supervised child) shared by all
            # workers; workers close() it on drop — SupervisedEngine
            # stays reusable across close(), preserving breaker state
            return tpu_engine
        if cfg.backend == "subprocess" or cfg.engine_path or cfg.variant_engine_path:
            from ..engine.uci import UciEngine

            path = (
                cfg.engine_path
                if flavor is EngineFlavor.OFFICIAL
                else (cfg.variant_engine_path or cfg.engine_path)
            )
            if path:
                return UciEngine(path, logger=logger, flavor=flavor)
        return PyEngine()

    # non-creating accessor: the summary loop exports SupervisorStats
    # without forcing an engine (and its warmup) into existence
    factory.peek_tpu = lambda: tpu_engine
    return factory


async def run(cfg: Config) -> int:
    logger = Logger(verbose=cfg.verbose)
    logger.headline(f"fishnet-tpu starting ({cfg.cores} cores, backend={cfg.backend})")

    bucket_url = settings.get_str("FISHNET_TPU_UPDATE_URL")
    if cfg.auto_update:
        # startup check (reference: src/main.rs:50-68): update THEN exec a
        # fresh process so work starts on the new version
        try:
            new_version = await auto_update(_http_get, bucket_url, logger)
        except Exception as e:
            logger.warn(f"Auto-update check failed: {e}")
            new_version = None
        if new_version:
            logger.headline(f"Updated to {new_version}; restarting ...")
            restart_process()

    if cfg.cpu_priority == "min":
        try:
            os.nice(19)  # reference: src/main.rs:163-171
        except OSError:
            pass

    api = ApiClient(
        Endpoint(cfg.endpoint),
        cfg.resolved_key(),
        logger=logger,
        max_backoff_s=cfg.max_backoff,
    )
    stats = StatsRecorder(
        stats_file=Path(cfg.stats_file) if cfg.stats_file else None,
        no_stats_file=cfg.no_stats_file,
        db_file=Path("stats.db") if not cfg.no_stats_file else None,
        cores=cfg.cores,
    )
    # observability opt-ins: the client-side trace ring (the supervisor
    # merges the engine host's spans into it and dumps it as the flight
    # recorder) and the Prometheus text endpoint on loopback
    if obs_trace.RECORDER is None:
        obs_trace.install_from_settings("client")
    try:
        obs_perf.register_build_info()
    except (ImportError, TypeError, ValueError):
        pass  # build-info gauge is best-effort decoration
    metrics_server = obs_metrics.serve_from_settings()
    if metrics_server is not None:
        logger.info(
            "Serving metrics at "
            f"http://127.0.0.1:{metrics_server.server_address[1]}/metrics"
        )
    queue = Queue(
        api,
        cores=cfg.cores,
        backlog=BacklogOpt(user=cfg.user_backlog, system=cfg.system_backlog),
        stats=stats,
        logger=logger,
        tpu_variants=tpu_variants_for(cfg),
        # play jobs ride the TPU engine too (skill semantics in
        # engine/tpu.py _move_job; reference runs them on the bundled
        # MultiVariant engine, src/queue.rs:562-568)
        tpu_moves=cfg.backend == "tpu",
        max_backoff_s=cfg.max_backoff,
    )

    loop = asyncio.get_running_loop()
    sigint_count = 0
    hard_stop = asyncio.Event()

    def on_sigint():
        nonlocal sigint_count
        sigint_count += 1
        if sigint_count == 1:
            logger.headline("Stopping after pending batches (press ^C again to abort)")
            queue.stop_acquiring()
        else:
            logger.headline("Aborting pending batches ...")
            hard_stop.set()

    def on_sigterm():
        hard_stop.set()

    # install handlers BEFORE the (slow) warmup: ^C during the first XLA
    # compile must not dump a KeyboardInterrupt traceback
    try:
        loop.add_signal_handler(signal.SIGINT, on_sigint)
        loop.add_signal_handler(signal.SIGTERM, on_sigterm)
    except NotImplementedError:
        pass  # non-unix

    factory = make_engine_factory(cfg, logger, stats=stats)
    if cfg.backend == "tpu":
        # pay the XLA compile cost now, before any chunk deadline ticks;
        # a flaky device at startup is non-fatal (workers retry per chunk)
        logger.info("Warming up TPU engine (compiling search program) ...")
        for attempt in range(3):
            try:
                engine = factory(EngineFlavor.TPU)
                if cfg.fleet:
                    # members spawn concurrently; one that fails to come
                    # up cools down instead of failing the fleet
                    await engine.start()
                    logger.info("Fleet coordinator ready.")
                elif cfg.supervisor:
                    # the child owns the device: its warmup (and the
                    # background variant compiles, engine/host.py) runs
                    # under heartbeat watch rather than a fixed timeout
                    await engine.start()
                    logger.info("Supervised TPU engine host ready.")
                else:
                    await asyncio.to_thread(engine.warmup, None, logger.info)
                    logger.info("TPU engine ready (all lane buckets compiled).")
                    from ..aot import registry as aot_registry

                    if aot_registry.warm_covers("variants"):
                        # same skip as engine/host.py: compiling would
                        # silently mask AOT bundle misses
                        logger.info(
                            "Variant programs preloaded from AOT bundle."
                        )
                    else:
                        # variant programs compile in the background;
                        # dispatches interleave behind the engine lock, so
                        # standard chunks flow immediately while variant
                        # chunks stop racing their deadlines within the
                        # first few minutes
                        asyncio.ensure_future(
                            asyncio.to_thread(
                                engine.warmup_variants, logger.info
                            )
                        )
                break
            except Exception as e:
                logger.warn(f"TPU warmup attempt {attempt + 1} failed: {e}")
                if attempt < 2:
                    await asyncio.sleep(5.0)
        else:
            logger.warn(
                "Proceeding with a cold TPU engine; first chunks may miss "
                "their deadlines while XLA compiles."
            )
    tasks = [
        asyncio.ensure_future(worker(i, queue, factory, logger))
        for i in range(cfg.cores)
    ]

    async def summary_loop():
        while True:
            await asyncio.sleep(SUMMARY_INTERVAL_S)
            logger.info(queue.stats_summary())
            # recovery counters ride the same cadence into the SQLite
            # sink, so quarantines/replays are visible next to occupancy
            # (tools/occupancy_report.py --stats-db)
            eng = factory.peek_tpu()
            if eng is not None and hasattr(eng, "stats"):
                sup = asdict(eng.stats)
                stats.record_supervisor(sup)
                # mirror the supervisor's ad-hoc counters into the
                # metrics registry (tentpole: one interface over the
                # scattered counter piles)
                obs_metrics.REGISTRY.absorb_totals("fishnet_supervisor", sup)
            # fold the registry into the sqlite time series on the same
            # cadence as the summary line
            stats.record_metrics(obs_metrics.REGISTRY.snapshot())

    summary = asyncio.ensure_future(summary_loop())

    restart_after_drain = False

    async def update_loop():
        # 5-hourly background check (reference: src/main.rs:180-200): on a
        # new release, stop acquiring, let pending batches drain, restart
        nonlocal restart_after_drain
        while True:
            await asyncio.sleep(UPDATE_INTERVAL_S)
            if not cfg.auto_update:
                continue
            try:
                new_version = await auto_update(_http_get, bucket_url, logger)
            except Exception as e:
                logger.warn(f"Auto-update check failed: {e}")
                continue
            if new_version:
                logger.headline(
                    f"Updated to {new_version}; finishing pending batches "
                    "before restart ..."
                )
                restart_after_drain = True
                queue.stop_acquiring()
                return

    updater = asyncio.ensure_future(update_loop())

    stopper = asyncio.ensure_future(hard_stop.wait())
    done, _ = await asyncio.wait(
        tasks + [stopper], return_when=asyncio.FIRST_COMPLETED
    )
    if stopper in done:
        await queue.shutdown()
    await asyncio.gather(*tasks, return_exceptions=True)
    stopper.cancel()
    summary.cancel()
    updater.cancel()
    await queue.shutdown()
    await queue.drain_submissions()
    stats.close()
    if restart_after_drain:
        logger.headline("Restarting into the updated version ...")
        restart_process()  # exec: replaces this process (src/main.rs:399-425)
    logger.headline("Bye.")
    return 0


def _sync_check_key(endpoint: str, key: str) -> bool:
    """Online key validation for the first-run dialog (reference:
    src/configure.rs:487-498 spawns an ApiActor just for check_key)."""
    try:
        api = ApiClient(Endpoint(endpoint), key, logger=Logger(verbose=0))
        return asyncio.run(api.check_key())
    except (ApiError, OSError):
        return True  # network trouble: accept and let `run` find out


def run_inflight(cfg: Config) -> int:
    """`fishnet-tpu inflight`: one-shot view of what a running serve
    process is doing RIGHT NOW — GET /debug/requests rendered as a
    table (stage, lanes, age, deadline slack per in-flight request)."""
    import json
    import urllib.error
    import urllib.request

    host = cfg.serve_host or settings.get_str("FISHNET_TPU_SERVE_HOST")
    port = (
        cfg.serve_port if cfg.serve_port is not None
        else settings.get_int("FISHNET_TPU_SERVE_PORT")
    )
    url = f"http://{host}:{port}/debug/requests"
    try:
        with urllib.request.urlopen(url, timeout=5.0) as r:
            payload = json.loads(r.read().decode("utf-8"))
    except (urllib.error.URLError, OSError, ValueError) as e:
        print(f"inflight: cannot reach {url}: {e}")
        return 1
    reqs = payload.get("requests") or []
    print(f"{len(reqs)} request(s) in flight at {host}:{port}")
    if not reqs:
        return 0
    cols = ("trace_id", "id", "tenant", "kind", "stage", "pos", "lanes",
            "age_ms", "slack_ms")
    rows = []
    for e in reqs:
        done = sum(
            1 for p in (e.get("positions") or {}).values()
            if p.get("stage") in ("delivered", "done")
        )
        slack = e.get("slack_ms")
        rows.append((
            str(e.get("trace_id", "")), str(e.get("id", "")),
            str(e.get("tenant", "")), str(e.get("kind", "")),
            str(e.get("stage", "")),
            f"{done}/{e.get('n_positions', 0)}",
            ",".join(str(x) for x in e.get("lanes") or []) or "-",
            str(e.get("age_ms", "")),
            str(slack) if slack is not None else "-",
        ))
    widths = [
        max(len(c), *(len(r[i]) for r in rows))
        for i, c in enumerate(cols)
    ]
    print("  ".join(c.ljust(w) for c, w in zip(cols, widths)))
    for r in rows:
        print("  ".join(v.ljust(w) for v, w in zip(r, widths)))
    return 0


def run_perf(cfg: Config) -> int:
    """`fishnet-tpu perf`: the performance surface in one screen — GET
    /debug/perf from a running serve process (build info, program cost
    table, perf counters, last ledger baseline), falling back to this
    process's own view when no server is up (build info + the local
    ledger; program costs need a live process that compiled
    something)."""
    import json
    import urllib.error
    import urllib.request

    host = cfg.serve_host or settings.get_str("FISHNET_TPU_SERVE_HOST")
    port = (
        cfg.serve_port if cfg.serve_port is not None
        else settings.get_int("FISHNET_TPU_SERVE_PORT")
    )
    url = f"http://{host}:{port}/debug/perf"
    source = url
    try:
        with urllib.request.urlopen(url, timeout=5.0) as r:
            snap = json.loads(r.read().decode("utf-8"))
    except (urllib.error.URLError, OSError, ValueError):
        source = "local (no serve process reachable)"
        snap = obs_perf.live_snapshot()

    print(f"perf: {source}")
    build = snap.get("build") or {}
    if build:
        print("build: " + " ".join(
            f"{k}={build[k]}" for k in sorted(build)))
    fp = snap.get("fingerprint")
    print(f"env fingerprint: {fp or '(no AOT store fingerprint)'}")

    programs = snap.get("programs") or {}
    if programs:
        print("\nprogram cost (cost_analysis/memory_analysis at compile):")
        cols = ("program", "flops", "bytes_accessed", "peak_bytes")
        rows = [
            (name,
             *(f"{costs[c]:.3e}" if c in costs else "-"
               for c in cols[1:]))
            for name, costs in sorted(programs.items())
        ]
        widths = [
            max(len(c), *(len(r[i]) for r in rows))
            for i, c in enumerate(cols)
        ]
        print("  ".join(c.ljust(w) for c, w in zip(cols, widths)))
        for r in rows:
            print("  ".join(v.ljust(w) for v, w in zip(r, widths)))

    metrics = snap.get("metrics") or {}
    if metrics:
        print("\ncounters:")
        for name in sorted(metrics):
            print(f"  {name} = {metrics[name]:g}")
    ratio = snap.get("cache_hit_ratio")
    if ratio is not None:
        print(f"  cache hit ratio = {ratio:.2%}")

    baseline = snap.get("baseline")
    if baseline:
        print(
            f"\nledger baseline: run {baseline.get('run_id')} "
            f"(seq {baseline.get('seq')}, source "
            f"{baseline.get('source')}, sha {baseline.get('git_sha')}, "
            f"fingerprint {baseline.get('fingerprint') or '-'})"
        )
        for bench_row, metrics_row in sorted(
                (baseline.get("rows") or {}).items()):
            for metric, value in sorted(metrics_row.items()):
                print(f"  {bench_row}.{metric} = {value:g}")
    else:
        print("\nledger baseline: (empty — run bench.py to seed it)")
    return 0


def run_fleet_ctl(cfg: Config) -> int:
    """`fishnet-tpu fleet-ctl [list | add SPEC | drain NAME | remove
    NAME]`: runtime membership against a running fleet front-end's
    /fleet/members admin surface (--serve-host/--serve-port pick the
    target). `drain` + `remove` + `add` is a zero-loss rolling restart
    (docs/fleet.md). `--json` makes `list` print the raw health payload
    (machine-readable; scripts and the autoscaling runbook use it)."""
    import json
    import urllib.error
    import urllib.request

    host = cfg.serve_host or settings.get_str("FISHNET_TPU_SERVE_HOST")
    port = (
        cfg.serve_port if cfg.serve_port is not None
        else settings.get_int("FISHNET_TPU_SERVE_PORT")
    )
    url = f"http://{host}:{port}/fleet/members"
    sub = list(cfg.extra_args) or ["list"]
    action, operand = sub[0], (sub[1] if len(sub) > 1 else None)
    if action in ("add", "drain", "remove") and operand is None:
        print(f"fleet-ctl: {action} needs an argument "
              "(add SPEC / drain NAME / remove NAME)")
        return 2
    try:
        if action == "list":
            req = urllib.request.Request(url, method="GET")
        elif action in ("add", "drain", "remove"):
            body = {"action": action}
            body["spec" if action == "add" else "member"] = operand
            req = urllib.request.Request(
                url, method="POST", data=json.dumps(body).encode("utf-8"),
                headers={"Content-Type": "application/json"},
            )
        else:
            print(f"fleet-ctl: unknown action {action!r} "
                  "(use list / add / drain / remove)")
            return 2
        with urllib.request.urlopen(req, timeout=30.0) as r:
            payload = json.loads(r.read().decode("utf-8"))
    except urllib.error.HTTPError as e:
        try:
            detail = json.loads(e.read().decode("utf-8")).get("error", "")
        except (ValueError, OSError):
            detail = ""
        print(f"fleet-ctl: {url} answered HTTP {e.code}: {detail}")
        return 1
    except (urllib.error.URLError, OSError, ValueError) as e:
        print(f"fleet-ctl: cannot reach {url}: {e}")
        return 1
    if action != "list":
        print(json.dumps(payload, indent=2))
        return 0
    if cfg.json_output:
        print(json.dumps(payload, indent=2, sort_keys=True))
        return 0
    members = payload.get("members") or []
    print(
        f"{len(members)} member(s), {payload.get('members_live', 0)} "
        f"live; losses={payload.get('losses', 0)} "
        f"readmissions={payload.get('readmissions', 0)} "
        f"hedges={payload.get('hedges', 0)}"
    )
    cols = ("name", "kind", "state", "backlog", "inflight", "losses",
            "cooldown_s")
    rows = [
        tuple(str(m.get(c, "")) for c in cols) for m in members
    ]
    widths = [
        max(len(c), *(len(r[i]) for r in rows)) if rows else len(c)
        for i, c in enumerate(cols)
    ]
    print("  ".join(c.ljust(w) for c, w in zip(cols, widths)))
    for r in rows:
        print("  ".join(v.ljust(w) for v, w in zip(r, widths)))
    return 0


def main(argv=None) -> int:
    from .configure import parse_and_configure
    from .systemd import system_unit, user_unit

    cfg = parse_and_configure(argv, check_key=_sync_check_key)
    if cfg.command == "license":
        print("fishnet-tpu is free software distributed under GPLv3+ terms,")
        print("matching the licensing of the fishnet protocol ecosystem.")
        return 0
    if cfg.command == "systemd":
        print(system_unit(cfg))
        return 0
    if cfg.command == "systemd-user":
        print(user_unit(cfg))
        return 0
    if cfg.command == "bench":
        import runpy
        import sys as _sys

        runpy.run_path(
            str(Path(__file__).resolve().parents[2] / "bench.py"),
            run_name="__main__",
        )
        return 0
    if cfg.command in ("pack", "warm"):
        # AOT program assets (fishnet_tpu/aot/): `pack` compiles and
        # serializes every hot search program into a bundle; `warm`
        # installs a bundle so the next boot loads instead of compiling
        from ..aot.pack import main_pack, main_warm

        return main_pack(cfg) if cfg.command == "pack" else main_warm(cfg)
    if cfg.command == "fleet-ctl":
        # runtime fleet membership against a running front-end
        # (fleet/coordinator.py + serve /fleet/members admin surface)
        return run_fleet_ctl(cfg)
    if cfg.command == "inflight":
        # live in-flight introspection against a running serve process
        # (obs/inflight.py; --serve-host/--serve-port pick the target)
        return run_inflight(cfg)
    if cfg.command == "perf":
        # build info, program cost table, and the perf-ledger baseline
        # (obs/perf.py; reaches a serve process's /debug/perf if up)
        return run_perf(cfg)
    if cfg.command in ("serve", "fleet"):
        # the analysis-serving front-end (fishnet_tpu/serve/): many
        # concurrent HTTP tenants multiplex into the same lane pool the
        # lichess client feeds. `fleet` is serve with the coordinator
        # forced on (cfg.fleet, set by parse): one HTTP front door over
        # N engine hosts
        from ..serve.server import run_serve

        return asyncio.run(run_serve(cfg))
    if cfg.command == "configure":
        return 0  # parse_and_configure already ran the dialog
    return asyncio.run(run(cfg))
