"""Randomized exponential backoff (behavioral parity with the reference's
RandomizedBackoff — reference: src/util.rs:11-40)."""
from __future__ import annotations

import random


class RandomizedBackoff:
    """Each call draws uniform(100ms, 4×max(100ms, last)) capped at max_s."""

    def __init__(self, max_s: float = 30.0) -> None:
        self.max_s = max_s
        self._last_ms = 0

    def next(self) -> float:
        low = 100
        cap = max(low, int(self.max_s * 1000))
        high = 4 * max(low, self._last_ms)
        t = min(cap, random.randint(low, max(low, high - 1)))
        self._last_ms = t
        return t / 1000.0

    def reset(self) -> None:
        self._last_ms = 0

    def pending(self) -> bool:
        """True when the previous cycle failed, i.e. the next (re)start
        should be delayed by `next()` rather than immediate."""
        return self._last_ms > 0
