"""Worker loop: races engine execution against chunk deadlines.

Parity with the reference's per-core worker (reference: src/main.rs:263-390):
one engine instance per flavor kept warm, deadline race with engine kill on
overrun, drop-and-respawn with randomized backoff on engine errors, and
ChunkFailed reporting so the queue forgets the batch.
"""
from __future__ import annotations

import asyncio
import time
from typing import Dict, List, Optional, Union

from ..engine.base import Engine, EngineError
from ..obs import trace as obs_trace
from .backoff import RandomizedBackoff
from .ipc import Chunk, ChunkFailed, PositionResponse
from .logger import Logger
from .queue import Queue, ShuttingDown


async def worker(
    index: int,
    queue: Queue,
    engine_factory,
    logger: Optional[Logger] = None,
) -> None:
    logger = logger or Logger()
    engines: Dict[object, Engine] = {}
    backoffs: Dict[object, RandomizedBackoff] = {}
    responses: Union[List[PositionResponse], ChunkFailed, None] = None

    try:
        while True:
            try:
                chunk = await queue.pull(responses)
            except ShuttingDown:
                break
            responses = None
            flavor = chunk.flavor

            engine = engines.get(flavor)
            if engine is None:
                backoff = backoffs.setdefault(flavor, RandomizedBackoff())
                if backoff.pending():
                    delay = backoff.next()
                    logger.warn(
                        f"Worker {index} waiting {delay:.1f}s before restarting"
                        f" {flavor.value} engine"
                    )
                    await asyncio.sleep(delay)
                try:
                    engine = engine_factory(flavor)
                except Exception as e:
                    logger.error(f"Worker {index} failed to start engine: {e}")
                    backoffs[flavor].next()
                    responses = ChunkFailed(chunk.work.id)
                    continue
                engines[flavor] = engine

            timeout = chunk.deadline - time.monotonic()
            if timeout <= 0:
                logger.warn(f"Worker {index} got chunk past its deadline")
                responses = ChunkFailed(chunk.work.id)
                continue
            try:
                with obs_trace.span(
                    "worker.chunk", "client", worker=index,
                    batch=str(chunk.work.id), positions=len(chunk.positions),
                ):
                    responses = await asyncio.wait_for(
                        engine.go_multiple(chunk), timeout=timeout
                    )
                backoffs.setdefault(flavor, RandomizedBackoff()).reset()
            except asyncio.TimeoutError:
                logger.warn(
                    f"Worker {index} chunk of batch {chunk.work.id} timed out;"
                    " dropping engine"
                )
                await _drop_engine(engines, flavor, logger)
                responses = ChunkFailed(chunk.work.id)
            except EngineError as e:
                logger.error(f"Worker {index} engine error: {e}; dropping engine")
                await _drop_engine(engines, flavor, logger)
                backoffs.setdefault(flavor, RandomizedBackoff()).next()
                responses = ChunkFailed(chunk.work.id)
    finally:
        for engine in engines.values():
            try:
                await engine.close()
            except Exception as e:
                logger.debug(f"Worker {index} engine close failed: {e}")


async def _drop_engine(engines: Dict, flavor, logger: Logger) -> None:
    engine = engines.pop(flavor, None)
    if engine is not None:
        try:
            await engine.close()
        except Exception as e:
            logger.debug(f"Dropped {flavor.value} engine close failed: {e}")
