"""systemd unit generators (reference: src/systemd.rs:11-189).

Prints hardened service units reproducing the current invocation's flags,
for `fishnet-tpu systemd` (system unit) and `systemd-user`.
"""
from __future__ import annotations

import shlex
import sys
from pathlib import Path

from .configure import Config


def exec_start(cfg: Config) -> str:
    """Rebuild the command line from the effective config (reference:
    src/systemd.rs:117-189)."""
    parts = [sys.executable, "-m", "fishnet_tpu", "run", "--no-conf"]
    if cfg.endpoint != "https://lichess.org/fishnet":
        parts += ["--endpoint", cfg.endpoint]
    if cfg.key_file:
        parts += ["--key-file", cfg.key_file]
    elif cfg.key:
        parts += ["--key", cfg.key]
    parts += ["--cores", str(cfg.cores)]
    if cfg.backend != "tpu":
        parts += ["--backend", cfg.backend]
    if cfg.engine_path:
        parts += ["--engine-path", cfg.engine_path]
    if cfg.variant_engine_path:
        parts += ["--variant-engine-path", cfg.variant_engine_path]
    if cfg.tpu_weights:
        parts += ["--tpu-weights", cfg.tpu_weights]
    if cfg.user_backlog is not None:
        parts += ["--user-backlog", f"{int(cfg.user_backlog)}s"]
    if cfg.system_backlog is not None:
        parts += ["--system-backlog", f"{int(cfg.system_backlog)}s"]
    if cfg.max_backoff != 30.0:
        parts += ["--max-backoff", f"{int(cfg.max_backoff)}s"]
    if cfg.cpu_priority:
        parts += ["--cpu-priority", cfg.cpu_priority]
    if cfg.stats_file:
        parts += ["--stats-file", cfg.stats_file]
    if cfg.no_stats_file:
        parts += ["--no-stats-file"]
    if cfg.auto_update:
        parts += ["--auto-update"]
    return " ".join(shlex.quote(p) for p in parts)


def system_unit(cfg: Config, user: str = "fishnet") -> str:
    """Hardened system service (reference: src/systemd.rs:11-54)."""
    return f"""[Unit]
Description=Fishnet TPU client
After=network-online.target
Wants=network-online.target

[Service]
ExecStart={exec_start(cfg)}
WorkingDirectory={Path.cwd()}
User={user}
Nice=5
CapabilityBoundingSet=
PrivateTmp=true
PrivateDevices=false
DevicePolicy=closed
DeviceAllow=char-accel rw
ProtectSystem=strict
NoNewPrivileges=true
Restart=on-failure

[Install]
WantedBy=multi-user.target
"""


def user_unit(cfg: Config) -> str:
    """User-level service (reference: src/systemd.rs:56-93)."""
    return f"""[Unit]
Description=Fishnet TPU client
After=network-online.target
Wants=network-online.target

[Service]
ExecStart={exec_start(cfg)}
WorkingDirectory={Path.cwd()}
Nice=5
PrivateTmp=true
DevicePolicy=closed
DeviceAllow=char-accel rw
NoNewPrivileges=true
Restart=on-failure

[Install]
WantedBy=default.target
"""
