"""HTTP client for the fishnet work-stealing protocol.

Owns all northbound traffic like the reference's ApiActor (reference:
src/api.rs:481-756): acquire, submit analysis, submit move (with job
chaining), abort, status, key check. Error handling parity: per-request
randomized backoff, HTTP 429 → ≥60 s suspension (reference:
src/api.rs:516-535), acquire rejections (400/401/403/406) signal the client
to stop (reference: src/api.rs:649-678, doc/protocol.md:240-244).
"""
from __future__ import annotations

import asyncio
import http.client
import json
import threading
import urllib.error
import urllib.request
from dataclasses import dataclass
from typing import List, Optional

from .. import __version__
from .backoff import RandomizedBackoff
from .wire import AcquireResponseBody, EvalFlavor


@dataclass
class Endpoint:
    """Server endpoint; any non-lichess.org host counts as a development
    server that may run keyless (reference: src/configure.rs:90-125)."""

    url: str = "https://lichess.org/fishnet"

    def __post_init__(self):
        self.url = self.url.rstrip("/")

    @property
    def is_development(self) -> bool:
        from urllib.parse import urlsplit

        host = urlsplit(self.url).hostname or ""
        return host != "lichess.org"

    def join(self, path: str) -> str:
        return f"{self.url}/{path.lstrip('/')}"

    def __str__(self) -> str:
        return self.url


class AcquiredKind:
    ACCEPTED = "accepted"
    NO_CONTENT = "no_content"
    REJECTED = "rejected"


@dataclass
class Acquired:
    kind: str
    body: Optional[AcquireResponseBody] = None


@dataclass
class QueueStatus:
    user_oldest: float
    system_oldest: float


@dataclass
class HttpResponse:
    status: int
    body: bytes

    def json(self):
        return json.loads(self.body.decode("utf-8"))


class UrllibTransport:
    """Blocking stdlib transport, run on the event loop's executor.

    Connections are kept alive and reused per host (reference uses a
    pooled reqwest client with 25 s idle, src/main.rs:427-456 — a fresh
    TLS handshake per acquire/submit would dominate small-request
    latency). A connection that died while idle is retried once on a
    fresh one."""

    IDLE_TIMEOUT_S = 25.0  # reference: src/main.rs:452 pool_idle_timeout

    def __init__(self, timeout: float = 30.0):
        self.timeout = timeout  # reference: src/main.rs:451 (30 s)
        self._lock = threading.Lock()
        self._pool: dict = {}  # (scheme, netloc) -> [(conn, last_used)]

    def _get_conn(self, scheme: str, netloc: str):
        """→ (conn, reused): reused=True for a kept-alive pooled socket."""
        import time as _time

        with self._lock:
            entries = self._pool.get((scheme, netloc), [])
            while entries:
                conn, last = entries.pop()
                if _time.monotonic() - last < self.IDLE_TIMEOUT_S:
                    return conn, True
                conn.close()
        if scheme == "https":
            return http.client.HTTPSConnection(netloc, timeout=self.timeout), False
        return http.client.HTTPConnection(netloc, timeout=self.timeout), False

    def _put_conn(self, scheme: str, netloc: str, conn) -> None:
        import time as _time

        with self._lock:
            self._pool.setdefault((scheme, netloc), []).append(
                (conn, _time.monotonic())
            )

    def request(
        self, method: str, url: str, headers: dict, body: Optional[bytes]
    ) -> HttpResponse:
        from urllib.parse import urlsplit

        parts = urlsplit(url)
        path = parts.path + (f"?{parts.query}" if parts.query else "")
        while True:  # drain stale kept-alive sockets, then one fresh try
            conn, reused = self._get_conn(parts.scheme, parts.netloc)
            try:
                conn.request(method, path, body=body, headers=headers)
                resp = conn.getresponse()
                data = resp.read()
                if resp.will_close:
                    conn.close()
                else:
                    self._put_conn(parts.scheme, parts.netloc, conn)
                return HttpResponse(resp.status, data)
            except TimeoutError:
                # a slow server may still be processing the delivered
                # body; retrying would duplicate a non-idempotent POST
                # (acquire/submit/move) — surface it instead
                conn.close()
                raise
            except (ConnectionError, OSError, http.client.HTTPException):
                conn.close()
                if not reused:
                    # fresh-socket failure is a real error, not the
                    # stale-keep-alive case the retry exists for — and
                    # the request may already have reached the server
                    raise
                # reused sockets are finite (each failure pops one), so
                # this terminates at a fresh connection at the latest


class ApiError(Exception):
    def __init__(self, status: int, msg: str = ""):
        super().__init__(f"HTTP {status} {msg}")
        self.status = status


class ApiClient:
    def __init__(
        self,
        endpoint: Endpoint,
        key: Optional[str],
        transport=None,
        logger=None,
        max_backoff_s: float = 30.0,
    ) -> None:
        self.endpoint = endpoint
        self.key = key
        self.transport = transport or UrllibTransport()
        self.logger = logger
        self.backoff = RandomizedBackoff(max_backoff_s)
        self._suspended_until = 0.0

    # ------------------------------------------------------------- low level

    def _headers(self, with_body: bool) -> dict:
        headers = {
            # reference sends fishnet-<os>-<arch>/<version> (src/main.rs:444-449)
            "User-Agent": f"fishnet-tpu/{__version__}",
        }
        if with_body:
            headers["Content-Type"] = "application/json"
        if self.key:
            headers["Authorization"] = f"Bearer {self.key}"
        return headers

    def _fishnet_body(self) -> dict:
        return {"fishnet": {"version": __version__, "apikey": self.key or ""}}

    async def _request(
        self, method: str, url: str, body: Optional[dict] = None
    ) -> HttpResponse:
        loop = asyncio.get_running_loop()
        now = loop.time()
        if now < self._suspended_until:
            await asyncio.sleep(self._suspended_until - now)
        payload = json.dumps(body).encode() if body is not None else None
        try:
            resp = await loop.run_in_executor(
                None,
                self.transport.request,
                method,
                url,
                self._headers(payload is not None),
                payload,
            )
        except Exception as e:  # network failure → backoff and propagate
            delay = self.backoff.next()
            if self.logger:
                self.logger.warn(f"{method} {url} failed: {e}; backing off {delay:.1f}s")
            await asyncio.sleep(delay)
            raise ApiError(0, str(e)) from e
        if resp.status == 429:
            # rate limited: suspend all requests for at least 60 s
            self._suspended_until = loop.time() + 60.0 + self.backoff.next()
            if self.logger:
                self.logger.warn("Rate limited (429); suspending requests for 60s+")
        return resp

    # ------------------------------------------------------------ high level

    async def check_key(self) -> bool:
        """GET /key (bearer no-op) with legacy GET /key/{key} fallback
        (reference: src/api.rs:560-612)."""
        resp = await self._request("GET", self.endpoint.join("key"))
        if resp.status == 200:
            return True
        if resp.status == 404 and self.key:
            legacy = await self._request("GET", self.endpoint.join(f"key/{self.key}"))
            return legacy.status == 200
        return False

    async def status(self) -> Optional[QueueStatus]:
        try:
            resp = await self._request("GET", self.endpoint.join("status"))
        except ApiError:
            return None  # reference: api.rs status errors resolve to None
        if resp.status != 200:
            return None
        try:
            obj = resp.json()
            return QueueStatus(
                user_oldest=float(obj["analysis"]["user"].get("oldest", 0)),
                system_oldest=float(obj["analysis"]["system"].get("oldest", 0)),
            )
        except (ValueError, KeyError):
            return None

    async def acquire(self, slow: bool) -> Acquired:
        url = self.endpoint.join("acquire") + ("?slow=true" if slow else "")
        resp = await self._request("POST", url, self._fishnet_body())
        if resp.status in (200, 202):
            self.backoff.reset()
            return Acquired(AcquiredKind.ACCEPTED, AcquireResponseBody.from_json(resp.json()))
        if resp.status == 204:
            return Acquired(AcquiredKind.NO_CONTENT)
        if resp.status in (400, 401, 403, 406):
            # server-driven kill switch (reference: src/api.rs:653-663)
            return Acquired(AcquiredKind.REJECTED)
        raise ApiError(resp.status, "acquire")

    async def submit_analysis(
        self, batch_id: str, flavor: EvalFlavor, analysis: List[Optional[dict]]
    ) -> None:
        url = self.endpoint.join(f"analysis/{batch_id}") + "?stop=true"
        body = dict(self._fishnet_body())
        body["stockfish"] = {"flavor": flavor.to_json()}
        body["analysis"] = analysis
        resp = await self._request("POST", url, body)
        if resp.status >= 300:
            raise ApiError(resp.status, "submit analysis")

    async def submit_move_and_acquire(
        self, batch_id: str, best_move: Optional[str]
    ) -> Optional[Acquired]:
        """POST /move/{id}; a 202 response chains the next job directly
        without an /acquire round trip (reference: src/api.rs:710-751)."""
        url = self.endpoint.join(f"move/{batch_id}")
        body = dict(self._fishnet_body())
        body["move"] = {"bestmove": best_move}
        resp = await self._request("POST", url, body)
        if resp.status == 202:
            return Acquired(AcquiredKind.ACCEPTED, AcquireResponseBody.from_json(resp.json()))
        if resp.status < 300:
            return Acquired(AcquiredKind.NO_CONTENT)
        raise ApiError(resp.status, "submit move")

    async def abort(self, batch_id: str) -> None:
        """Hand a job back on shutdown (reference: src/api.rs:537-558)."""
        url = self.endpoint.join(f"abort/{batch_id}")
        resp = await self._request("POST", url, self._fishnet_body())
        if resp.status == 404:
            return  # abort not supported by this server
        if resp.status >= 300:
            raise ApiError(resp.status, "abort")
