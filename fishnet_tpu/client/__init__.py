"""Client framework: protocol, planner, queue, workers, config, stats."""
