"""Auto-updater (reference: src/update.rs:13-200).

Lists an S3-style release bucket (ListBucketResult XML), picks the highest
semver for this target, streams the download, and atomically replaces the
running entry point; the caller re-execs (reference: src/main.rs:399-425).
For a Python deployment the replaceable artifact is a zipapp/pex-style
single file; updates are skipped when running from a plain source tree.
"""
from __future__ import annotations

import os
import platform
import re
import sys
import tempfile
import xml.etree.ElementTree as ET
from dataclasses import dataclass
from pathlib import Path
from typing import List, Optional

from ..utils import settings

_S3_NS = "{http://s3.amazonaws.com/doc/2006-03-01/}"
_VERSION_RE = re.compile(r"v?(\d+)\.(\d+)\.(\d+)")

# release channel (reference: src/update.rs:24 fishnet-releases bucket);
# FISHNET_TPU_UPDATE_URL overrides (e.g. a local fixture in tests). The
# canonical default lives in the settings registry — single source of
# truth for every env-var default (utils/settings.py).
DEFAULT_BUCKET_URL = settings.lookup("FISHNET_TPU_UPDATE_URL").default


def current_target() -> str:
    """Target triple analogue, e.g. linux-x86_64 (gnu→musl mapping of the
    reference collapses here: a zipapp is platform-portable per-arch)."""
    return f"{sys.platform}-{platform.machine()}"


@dataclass(frozen=True)
class Release:
    version: tuple
    key: str

    @property
    def version_str(self) -> str:
        return ".".join(str(v) for v in self.version)


def parse_bucket_listing(xml_text: str, target: str) -> List[Release]:
    """Parse ListBucketResult XML → releases for this target
    (reference: src/update.rs:63-89)."""
    root = ET.fromstring(xml_text)
    releases = []
    for contents in root.iter(f"{_S3_NS}Contents"):
        key_el = contents.find(f"{_S3_NS}Key")
        if key_el is None or not key_el.text:
            continue
        key = key_el.text
        if target not in key:
            continue
        m = _VERSION_RE.search(key)
        if not m:
            continue
        releases.append(Release(tuple(int(g) for g in m.groups()), key))
    return releases


def latest_release(xml_text: str, target: Optional[str] = None) -> Optional[Release]:
    releases = parse_bucket_listing(xml_text, target or current_target())
    return max(releases, key=lambda r: r.version, default=None)


def replaceable_artifact() -> Optional[Path]:
    """The running single-file artifact, or None when running from a source
    tree (in which case auto-update is a no-op, like the reference running
    from cargo)."""
    main = Path(sys.argv[0]).resolve()
    if main.suffix in (".pyz", ".pex") and os.access(main, os.W_OK):
        return main
    return None


def self_replace(artifact: Path, new_bytes: bytes) -> None:
    """Atomic replacement of the running artifact
    (reference: src/update.rs:59 via the self-replace crate)."""
    fd, tmp = tempfile.mkstemp(dir=str(artifact.parent), prefix=".update-")
    try:
        with os.fdopen(fd, "wb") as f:
            f.write(new_bytes)
        os.chmod(tmp, 0o755)
        os.replace(tmp, artifact)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def restart_process() -> None:
    """Replace the process image with a fresh invocation
    (reference: src/main.rs:399-425)."""
    os.execv(sys.executable, [sys.executable] + sys.argv)


async def auto_update(http_get, bucket_url: str, logger) -> Optional[str]:
    """Check the bucket and self-replace if a newer version exists.

    http_get: async (url) -> bytes. Returns the new version string when an
    update was applied (caller should restart_process after graceful drain).
    """
    from .. import __version__

    artifact = replaceable_artifact()
    if artifact is None:
        logger.debug("Not running from a replaceable artifact; skipping update")
        return None
    xml_text = (await http_get(bucket_url)).decode("utf-8", "replace")
    release = latest_release(xml_text)
    if release is None:
        logger.debug("No releases found for this target")
        return None
    current = tuple(int(x) for x in __version__.split(".")[:3])
    if release.version <= current:
        logger.debug(f"Up to date (latest {release.version_str})")
        return None
    logger.info(f"Updating to {release.version_str} ...")
    blob = await http_get(bucket_url.rstrip("/") + "/" + release.key)
    self_replace(artifact, blob)
    return release.version_str
