"""Central scheduler: acquire → plan → dispatch → reassemble → submit.

Asyncio re-design of the reference's queue actor (reference:
src/queue.rs:37-522). Workers call `pull(responses)`: completed chunk
results are folded into pending batches, then the next chunk is handed out;
if none is queued, the puller drives the acquire loop (backlog-aware idling,
randomized backoff on empty polls, move-job chaining, kill-switch on
rejection). Single event loop replaces the actor mailboxes; state needs no
lock beyond the acquire critical section.
"""
from __future__ import annotations

import asyncio
import time
from collections import deque
from dataclasses import dataclass
from typing import Deque, Dict, List, Optional, Set, Union

from ..obs import trace as obs_trace
from .api import Acquired, AcquiredKind, ApiClient, ApiError
from .backoff import RandomizedBackoff
from .ipc import Chunk, ChunkFailed, PositionResponse
from .logger import Logger, ProgressAt, QueueStatusBar, short_variant_name
from .planner import (
    SKIP,
    AllSkipped,
    IncomingBatch,
    IncomingError,
    PendingBatch,
)
from .stats import StatsRecorder
from .wire import AnalysisWork, EvalFlavor, MoveWork


class ShuttingDown(Exception):
    """Raised from pull() when the queue drains for shutdown."""


@dataclass
class BacklogOpt:
    """Minimum queue ages before acquiring (reference: src/configure.rs:244-289:
    Short=30s, Long=1h, or an explicit duration)."""

    user: Optional[float] = None
    system: Optional[float] = None

    SHORT = 30.0
    LONG = 3600.0


@dataclass
class MoveSubmission:
    batch_id: str
    best_move: Optional[str]


class Queue:
    def __init__(
        self,
        api: ApiClient,
        cores: int,
        backlog: Optional[BacklogOpt] = None,
        stats: Optional[StatsRecorder] = None,
        logger: Optional[Logger] = None,
        tpu_variants: Optional[Set[str]] = None,
        tpu_moves: bool = False,
        max_backoff_s: float = 30.0,
    ) -> None:
        self.api = api
        self.cores = cores
        self.backlog = backlog or BacklogOpt()
        self.stats = stats or StatsRecorder(no_stats_file=True, cores=cores)
        self.logger = logger or Logger()
        self.tpu_variants = tpu_variants
        self.tpu_moves = tpu_moves

        self.incoming: Deque[Chunk] = deque()
        self.pending: Dict[str, PendingBatch] = {}
        self.move_submissions: Deque[MoveSubmission] = deque()
        self.shutdown_soon = False
        self.backoff = RandomizedBackoff(max_backoff_s)
        self._acquire_lock = asyncio.Lock()
        self._interrupt = asyncio.Event()
        self._submit_tasks: Set[asyncio.Task] = set()

    # -------------------------------------------------------------- plumbing

    def status_bar(self) -> QueueStatusBar:
        return QueueStatusBar(
            pending=sum(p.pending() for p in self.pending.values()),
            cores=self.cores,
        )

    def _spawn_submit(self, coro) -> None:
        task = asyncio.ensure_future(coro)
        self._submit_tasks.add(task)
        task.add_done_callback(self._submit_tasks.discard)

    async def _safe_submit_analysis(self, batch_id, flavor, analysis) -> None:
        try:
            await self.api.submit_analysis(batch_id, flavor, analysis)
        except ApiError as e:
            self.logger.error(f"Failed to submit analysis for {batch_id}: {e}")

    # -------------------------------------------------------- state handling

    def add_incoming_batch(self, batch: IncomingBatch) -> None:
        """(reference: src/queue.rs:155-189)"""
        batch_id = batch.work.id
        if batch_id in self.pending:
            self.logger.error(f"Dropping duplicate incoming batch {batch_id}")
            return
        positions: List[object] = []
        for chunk in batch.chunks:
            for pos in chunk.positions:
                if pos.position_index is None:
                    continue
                while len(positions) <= pos.position_index:
                    positions.append(SKIP)  # gaps = skipped plies
                positions[pos.position_index] = SKIP if pos.skip else None
            self.incoming.append(chunk)
        self.pending[batch_id] = PendingBatch(
            work=batch.work,
            url=batch.url,
            flavor=batch.flavor,
            variant=batch.variant,
            positions=positions,
        )
        self.logger.progress(
            self.status_bar(), ProgressAt(batch_id, batch.url, None)
        )

    def handle_position_responses(
        self, responses: Union[List[PositionResponse], ChunkFailed, None]
    ) -> None:
        """(reference: src/queue.rs:191-234)"""
        if responses is None:
            return
        if isinstance(responses, ChunkFailed):
            # forget the batch; the server will re-queue it by timeout
            self.pending.pop(responses.batch_id, None)
            self.incoming = deque(
                c for c in self.incoming if c.work.id != responses.batch_id
            )
            return
        progress_at = None
        batch_ids: List[str] = []
        for res in responses:
            pending = self.pending.get(res.work.id)
            if pending is None:
                continue
            pending.total_nodes += res.nodes
            pending.total_cpu_time += res.time_s
            if res.position_index is None:
                continue  # discarded overlap position
            if res.position_index >= len(pending.positions):
                continue
            # res.url already carries its #ply fragment (set by the planner)
            progress_at = ProgressAt(res.work.id, res.url, None)
            pending.positions[res.position_index] = res
            if res.work.id not in batch_ids:
                batch_ids.append(res.work.id)
        if progress_at is not None:
            self.logger.progress(self.status_bar(), progress_at)
        for batch_id in batch_ids:
            self.maybe_finished(batch_id)

    def maybe_finished(self, batch_id: str) -> None:
        """(reference: src/queue.rs:247-319)"""
        pending = self.pending.pop(batch_id, None)
        if pending is None:
            return
        completed = pending.try_into_completed()
        if completed is None:
            if not pending.work.matrix_wanted():
                # stream partial analysis as a progress report
                self._spawn_submit(
                    self._safe_submit_analysis(
                        pending.work.id,
                        pending.flavor.eval_flavor(),
                        pending.progress_report(),
                    )
                )
            self.pending[batch_id] = pending
            return

        extra = []
        sv = short_variant_name(completed.variant)
        if sv:
            extra.append(sv)
        if completed.flavor.eval_flavor() is EvalFlavor.HCE:
            extra.append("hce")
        nps = completed.nps()
        if nps is not None:
            nnue_nps = nps if completed.flavor.eval_flavor() is EvalFlavor.NNUE else None
            self.stats.record_batch(
                completed.total_positions(), completed.total_nodes, nnue_nps
            )
            extra.append(f"{nps // 1000} knps/core")
        else:
            extra.append("? nps")
        where = completed.url or f"batch {batch_id}"
        log_line = f"{self.status_bar()} {where} finished ({', '.join(extra)})"

        if isinstance(completed.work, AnalysisWork):
            self.logger.info(log_line)
            self._spawn_submit(
                self._safe_submit_analysis(
                    completed.work.id,
                    completed.flavor.eval_flavor(),
                    completed.into_analysis(),
                )
            )
        else:
            self.logger.debug(log_line)
            self.move_submissions.append(
                MoveSubmission(completed.work.id, completed.into_best_move())
            )
            self._interrupt.set()

    # --------------------------------------------------------- acquire logic

    async def _backlog_wait_time(self) -> tuple:
        """(reference: src/queue.rs:350-390)"""
        user_backlog = max(
            self.stats.min_user_backlog(), self.backlog.user or 0.0
        )
        system_backlog = self.backlog.system or 0.0
        if user_backlog >= 1.0 or system_backlog >= 1.0:
            status = await self.api.status()
            if status is not None:
                user_wait = max(0.0, user_backlog - status.user_oldest)
                system_wait = max(0.0, system_backlog - status.system_oldest)
                slow = user_wait >= system_wait + 1.0
                return (min(user_wait, system_wait), slow)
            slow = user_backlog >= system_backlog + 1.0
            return (0.0, slow)
        return (0.0, False)

    async def handle_acquired_response_body(self, body) -> None:
        """(reference: src/queue.rs:392-429)"""
        batch_id = body.work.id
        try:
            incoming = IncomingBatch.from_acquired(
                str(self.api.endpoint),
                body,
                tpu_variants=self.tpu_variants,
                tpu_moves=self.tpu_moves,
            )
        except AllSkipped as all_skipped:
            completed = all_skipped.completed
            self.logger.warn(f"Completed empty batch {batch_id}.")
            self._spawn_submit(
                self._safe_submit_analysis(
                    completed.work.id,
                    completed.flavor.eval_flavor(),
                    completed.into_analysis(),
                )
            )
            return
        except IncomingError as err:
            if body.work.is_move:
                self.logger.warn(f"Invalid move request {batch_id}: {err}")
                self.move_submissions.append(MoveSubmission(batch_id, None))
                self._interrupt.set()
            else:
                self.logger.warn(f"Ignoring invalid batch {batch_id}: {err}")
            return
        self.add_incoming_batch(incoming)

    async def _handle_move_submissions(self) -> None:
        """(reference: src/queue.rs:431-457)"""
        while not self.shutdown_soon and self.move_submissions:
            sub = self.move_submissions.popleft()
            try:
                acquired = await self.api.submit_move_and_acquire(
                    sub.batch_id, sub.best_move
                )
            except ApiError as e:
                self.logger.error(f"Failed to submit move for {sub.batch_id}: {e}")
                continue
            if acquired and acquired.kind == AcquiredKind.ACCEPTED and acquired.body:
                await self.handle_acquired_response_body(acquired.body)

    async def _interruptible_sleep(self, delay: float) -> None:
        try:
            await asyncio.wait_for(self._interrupt.wait(), timeout=delay)
            self._interrupt.clear()
        except asyncio.TimeoutError:
            pass

    async def pull(
        self, responses: Union[List[PositionResponse], ChunkFailed, None]
    ) -> Chunk:
        """Fold in finished work, then obtain the next chunk; the calling
        worker drives acquisition when the queue is empty
        (reference: src/queue.rs:459-522 + main.rs:237-243)."""
        self.handle_position_responses(responses)
        while True:
            await self._handle_move_submissions()
            if self.incoming:
                return self.incoming.popleft()
            if self.shutdown_soon:
                raise ShuttingDown()

            async with self._acquire_lock:
                if self.incoming or self.shutdown_soon:
                    continue  # another worker already acquired

                wait, slow = await self._backlog_wait_time()
                if wait >= 1.0:
                    level = self.logger.info if wait >= 40.0 else self.logger.debug
                    level(f"Going idle for {wait:.0f}s.")
                    await self._interruptible_sleep(wait)
                    continue

                try:
                    with obs_trace.span("queue.acquire", "client", slow=slow):
                        acquired = await self.api.acquire(slow)
                except ApiError:
                    continue  # backoff already applied inside the client
                if acquired.kind == AcquiredKind.ACCEPTED and acquired.body:
                    self.backoff.reset()
                    await self.handle_acquired_response_body(acquired.body)
                elif acquired.kind == AcquiredKind.NO_CONTENT:
                    delay = self.backoff.next()
                    self.logger.debug(f"No job received. Backing off {delay:.1f}s.")
                    await self._interruptible_sleep(delay)
                elif acquired.kind == AcquiredKind.REJECTED:
                    self.logger.error(
                        "Client update or reconfiguration might be required."
                        " Stopping queue."
                    )
                    self.shutdown_soon = True

    # -------------------------------------------------------------- shutdown

    def stop_acquiring(self) -> None:
        self.shutdown_soon = True
        self._interrupt.set()

    async def shutdown(self) -> None:
        """Abort all pending batches so the server reassigns them immediately
        (reference: src/queue.rs:107-114, src/api.rs:537-558)."""
        self.shutdown_soon = True
        self._interrupt.set()
        for batch_id in list(self.pending):
            self.pending.pop(batch_id, None)
            try:
                await self.api.abort(batch_id)
            except ApiError as e:
                self.logger.warn(f"Failed to abort {batch_id}: {e}")
        self.incoming.clear()
        if self._submit_tasks:
            await asyncio.gather(*list(self._submit_tasks), return_exceptions=True)

    async def drain_submissions(self) -> None:
        if self._submit_tasks:
            await asyncio.gather(*list(self._submit_tasks), return_exceptions=True)

    def stats_summary(self) -> str:
        """The 120 s summary line (reference: src/main.rs:202-214)."""
        s = self.stats.stats
        return (
            f"{self.stats.nnue_nps} (nnue), {s.total_batches} batches, "
            f"{s.total_positions} positions, {s.total_nodes} nodes"
        )
