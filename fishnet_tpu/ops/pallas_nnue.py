"""Fused batched NNUE evaluation as a Pallas TPU kernel.

The XLA path (models/nnue.py) evaluates a board batch as separate ops:
feature-index gather, feature-transform row sums, clipped ReLU, three
bucketed matmuls. This kernel fuses the whole pipeline per batch tile in
VMEM (SURVEY.md §7.2's "fused int8 matmul→clipped-ReLU stack", float
variant):

  boards (T, 64) ──► one-hot features (T, 768) built in-register via an
  iota compare (no scatter) ──► (T, 768) @ ft_w (768, L1) on the MXU ──►
  perspective select + clipped ReLU ──► dense head over ALL 8 output
  buckets at once — (8,) small matmuls are cheaper than per-lane weight
  gathers on TPU — ──► per-lane bucket select ──► (T,) centipawn scores.

Dense-over-buckets is the TPU-first trade: 8× the head FLOPs (trivial —
the head is tiny) for zero gather/scatter in the hot path.

Used by models/train.py's batched_forward when FISHNET_TPU_PALLAS=1 and
on CPU test runs via interpret mode; the XLA path stays the default
until the kernel is profiled on real hardware. board768 feature set
only (the search's incremental path has its own accumulators).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ..models import nnue

TILE = 8  # lanes per grid step; f32 min sublane tile


def _kernel(boards_ref, stm_ref, ft_w_ref, ft_b_ref, l1_w_ref, l1_b_ref,
            l2_w_ref, l2_b_ref, out_w_ref, out_b_ref, out_ref):
    boards = boards_ref[:]  # (T, 64) int32 piece codes
    stm = stm_ref[:]  # (T,) int32

    nf = ft_w_ref.shape[0]  # 768
    l1 = ft_w_ref.shape[1]

    def onehot_features(perspective):
        # board768 feature index per square, -1 when empty (mirrors
        # nnue.feature_indices_768, kept in-kernel so everything fuses)
        sq = jax.lax.broadcasted_iota(jnp.int32, (TILE, 64), 1)
        code = boards
        pt = (code - 1) % 6
        col = jnp.where(code > 6, 1, 0)
        persp = perspective[:, None]
        kind = jnp.where(col == persp, pt, 6 + pt)
        o_sq = sq ^ jnp.where(persp == 1, 56, 0)
        idx = jnp.where(code > 0, kind * 64 + o_sq, -1)  # (T, 64)
        # one-hot via compare against a feature iota: (T, 64, NF) reduce
        # over squares → (T, NF). No scatter; lowers to VPU compares.
        feat = jax.lax.broadcasted_iota(jnp.int32, (TILE, 64, nf), 2)
        onehot = (feat == idx[:, :, None]).astype(jnp.float32)
        return onehot.sum(axis=1)  # (T, NF)

    own = onehot_features(stm)
    opp = onehot_features(1 - stm)
    ft_w = ft_w_ref[:]
    ft_b = ft_b_ref[:]
    acc_own = own @ ft_w + ft_b  # (T, L1) — MXU
    acc_opp = opp @ ft_w + ft_b

    x = jnp.clip(jnp.concatenate([acc_own, acc_opp], axis=1), 0.0, 1.0)

    # dense over all 8 output buckets, select per lane at the end
    piece_count = (boards > 0).sum(axis=1)  # (T,)
    bucket = jnp.clip((piece_count - 1) // 4, 0, nnue.NUM_OUTPUT_BUCKETS - 1)

    l1_w = l1_w_ref[:]  # (8, 2*L1, H1)
    l2_w = l2_w_ref[:]  # (8, H1, H2)
    out_w = out_w_ref[:]  # (8, H2)
    h = jnp.clip(
        jnp.einsum("tc,bch->bth", x, l1_w) + l1_b_ref[:][:, None, :], 0.0, 1.0
    )  # (8, T, H1)
    h = jnp.clip(
        jnp.einsum("bth,bhk->btk", h, l2_w) + l2_b_ref[:][:, None, :], 0.0, 1.0
    )  # (8, T, H2)
    o = jnp.einsum("btk,bk->bt", h, out_w) + out_b_ref[:][:, None]  # (8, T)

    lane_bucket_onehot = (
        jax.lax.broadcasted_iota(jnp.int32, (nnue.NUM_OUTPUT_BUCKETS, TILE), 0)
        == bucket[None, :]
    ).astype(jnp.float32)
    out_ref[:] = (o * lane_bucket_onehot).sum(axis=0) * nnue.OUTPUT_SCALE


@functools.partial(jax.jit, static_argnames=("interpret",))
def evaluate_batch(params: nnue.NnueParams, boards: jnp.ndarray,
                   stms: jnp.ndarray, interpret: bool = False) -> jnp.ndarray:
    """(B, 64) boards, (B,) stms → (B,) centipawn scores (board768 nets).

    interpret=True runs the kernel in the Pallas interpreter (CPU tests).
    """
    from jax.experimental import pallas as pl

    if params.ft_w.shape[0] != nnue.NUM_FEATURES_768:
        raise ValueError("pallas kernel supports the board768 feature set only")
    if not interpret and jax.default_backend() == "cpu":
        interpret = True  # Mosaic doesn't lower to host CPU; emulate
    B = boards.shape[0]
    pad = (-B) % TILE
    if pad:
        boards = jnp.concatenate(
            [boards, jnp.zeros((pad, 64), boards.dtype)], axis=0
        )
        stms = jnp.concatenate([stms, jnp.zeros((pad,), stms.dtype)], axis=0)
    n = boards.shape[0]

    f32 = lambda a: jnp.asarray(a, jnp.float32)  # noqa: E731
    grid = (n // TILE,)
    lane_spec = pl.BlockSpec((TILE, 64), lambda i: (i, 0))
    stm_spec = pl.BlockSpec((TILE,), lambda i: (i,))
    full = lambda a: pl.BlockSpec(a.shape, lambda i: (0,) * a.ndim)  # noqa: E731

    args = (
        boards.astype(jnp.int32), stms.astype(jnp.int32),
        f32(params.ft_w), f32(params.ft_b),
        f32(params.l1_w), f32(params.l1_b),
        f32(params.l2_w), f32(params.l2_b),
        f32(params.out_w), f32(params.out_b),
    )
    out = pl.pallas_call(
        _kernel,
        grid=grid,
        in_specs=[lane_spec, stm_spec] + [full(a) for a in args[2:]],
        out_specs=pl.BlockSpec((TILE,), lambda i: (i,)),
        out_shape=jax.ShapeDtypeStruct((n,), jnp.float32),
        interpret=interpret,
    )(*args)
    return out[:B]


def is_enabled() -> bool:
    import os

    return bool(os.environ.get("FISHNET_TPU_PALLAS"))


# ------------------------------------------------------- differentiable wrap
#
# pallas_call has no built-in autodiff; training needs d(score)/d(params).
# Standard pattern (pallas guide §custom-vjp): run the fused kernel
# forward, compute the backward with the XLA reference path — backward
# cost dominates training anyway, and the two forwards agree to f32
# tolerance (tests/test_pallas_nnue.py).


def _xla_forward(params, boards, stms):
    return jax.vmap(nnue.evaluate, in_axes=(None, 0, 0))(params, boards, stms)


@jax.custom_vjp
def evaluate_batch_trainable(params, boards, stms):
    return evaluate_batch(params, boards, stms)


def _fwd(params, boards, stms):
    return evaluate_batch(params, boards, stms), (params, boards, stms)


def _bwd(res, g):
    params, boards, stms = res
    _, vjp = jax.vjp(lambda p: _xla_forward(p, boards, stms), params)
    (gp,) = vjp(g)
    zero_i = lambda a: np.zeros(a.shape, dtype=jax.dtypes.float0)  # noqa: E731
    return gp, zero_i(boards), zero_i(stms)


evaluate_batch_trainable.defvjp(_fwd, _bwd)
