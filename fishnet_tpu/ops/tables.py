"""Precomputed geometry tables shared by the device movegen and attack query.

Generated with numpy from the same geometry as the host library
(fishnet_tpu.chess.attacks), so the two can be property-tested against each
other. All tables use -1 padding for "no square" and are baked into the jit
program as constants (they live in HBM/VMEM as XLA prefers).
"""
from __future__ import annotations

import numpy as np

# squares are a1=0 .. h8=63, file = sq & 7, rank = sq >> 3

_KNIGHT_D = [(1, 2), (2, 1), (2, -1), (1, -2), (-1, -2), (-2, -1), (-2, 1), (-1, 2)]
_KING_D = [(1, 0), (1, 1), (0, 1), (-1, 1), (-1, 0), (-1, -1), (0, -1), (1, -1)]
# ray directions: E, N, NE, NW, W, S, SW, SE (0-3 "positive", 4-7 mirror)
RAY_DIRS = [(1, 0), (0, 1), (1, 1), (-1, 1), (-1, 0), (0, -1), (-1, -1), (1, -1)]
BISHOP_DIR_IDS = (2, 3, 6, 7)
ROOK_DIR_IDS = (0, 1, 4, 5)


def _steps(deltas) -> np.ndarray:
    out = np.full((64, len(deltas)), -1, dtype=np.int32)
    for sq in range(64):
        f, r = sq & 7, sq >> 3
        for i, (df, dr) in enumerate(deltas):
            nf, nr = f + df, r + dr
            if 0 <= nf < 8 and 0 <= nr < 8:
                out[sq, i] = nr * 8 + nf
    return out


KNIGHT_TARGETS = _steps(_KNIGHT_D)  # (64, 8)
KING_TARGETS = _steps(_KING_D)  # (64, 8)

# PAWN_CAPTURES[color, sq, i]: squares a pawn of `color` on sq attacks
PAWN_CAPTURES = np.stack(
    [_steps([(-1, 1), (1, 1)]), _steps([(-1, -1), (1, -1)])]
)  # (2, 64, 2)


def _rays() -> np.ndarray:
    out = np.full((64, 8, 7), -1, dtype=np.int32)
    for sq in range(64):
        f, r = sq & 7, sq >> 3
        for d, (df, dr) in enumerate(RAY_DIRS):
            nf, nr = f + df, r + dr
            i = 0
            while 0 <= nf < 8 and 0 <= nr < 8:
                out[sq, d, i] = nr * 8 + nf
                nf += df
                nr += dr
                i += 1
    return out


RAYS = _rays()  # (64, 8, 7): ray squares from sq (exclusive), -1 padded

# piece codes on the device board: 0 empty, 1-6 white PNBRQK, 7-12 black
EMPTY = 0
W_PAWN, W_KNIGHT, W_BISHOP, W_ROOK, W_QUEEN, W_KING = 1, 2, 3, 4, 5, 6
B_PAWN, B_KNIGHT, B_BISHOP, B_ROOK, B_QUEEN, B_KING = 7, 8, 9, 10, 11, 12

# SLIDER_MASK[dir, piece_code]: does piece_code slide along dir?
SLIDER_MASK = np.zeros((8, 13), dtype=bool)
for d in range(8):
    for code, is_rook_like, is_bishop_like in (
        (W_ROOK, True, False), (B_ROOK, True, False),
        (W_BISHOP, False, True), (B_BISHOP, False, True),
        (W_QUEEN, True, True), (B_QUEEN, True, True),
    ):
        if (d in ROOK_DIR_IDS and is_rook_like) or (d in BISHOP_DIR_IDS and is_bishop_like):
            SLIDER_MASK[d, code] = True

# move encoding: from | to<<6 | promo<<12 (promo 0 none, 1-4 = N B R Q,
# 5 = K — antichess promotes to king)
PROMO_NONE, PROMO_N, PROMO_B, PROMO_R, PROMO_Q, PROMO_K = 0, 1, 2, 3, 4, 5
PROMO_TO_PIECE = np.array([0, 2, 3, 4, 5, 6], dtype=np.int32)  # white codes; +6 black

MAX_MOVES = 224  # fixed per-ply move-list capacity (max legal known is 218)


def encode_move(from_sq: int, to_sq: int, promo: int = 0) -> int:
    return from_sq | (to_sq << 6) | (promo << 12)


def decode_move(m: int):
    return m & 63, (m >> 6) & 63, (m >> 12) & 7
