"""Lockstep batched alpha-beta search.

The reference's "search layer" is Stockfish's recursive C++ alpha-beta run
in one process per core (reference: §2 of SURVEY.md; fishnet drives it via
`go nodes N` per position, src/stockfish.rs:290-350). On TPU the recursion
becomes an explicit per-lane DFS stack advanced in lockstep by a single
jitted `lax.while_loop` step over B independent lanes:

- copy-make: child boards are written to a (B, MAX_PLY, ...) stack, so
  there is no unmake logic on device;
- pseudo-legal movegen + king-capture refutation: a mover that leaves the
  king en prise is refuted at the child (ILLEGAL sentinel), which keeps
  pin/evasion logic out of the kernel;
- one state machine step = phase ENTER (classify node: illegal/leaf/expand
  with movegen) → phase RETURN (fold a finished child into its parent) →
  phase TRYMOVE (pick next move or finish the node). Phase order is chosen
  so a leaf child costs a single step;
- per-lane node budgets and depth limits; lanes park in DONE and are
  masked out (divergence tax: a step costs the same while any lane runs).

MultiPV and iterative deepening are driven from the host (engine/tpu.py):
lanes are cheap, so multipv lanes are just more lanes.
"""
from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from ..models import nnue
from .board import (
    Board,
    is_attacked,
    king_square,
    make_move,
    move_piece_changes,
)
from .movegen import MAX_MOVES, generate_moves

INF = 32500
MATE = 32000
ILLEGAL = 99999  # sentinel: the move leading to this node was illegal
DRAW = 0

MODE_ENTER = 0
MODE_RETURN = 1
MODE_TRYMOVE = 2
MODE_DONE = 3


class SearchState(NamedTuple):
    # stacks, leading dims (B, MAX_PLY[+1])
    board: jnp.ndarray  # (B, P+1, 64) int32
    stm: jnp.ndarray  # (B, P+1)
    ep: jnp.ndarray  # (B, P+1)
    castling: jnp.ndarray  # (B, P+1, 4)
    halfmove: jnp.ndarray  # (B, P+1)
    moves: jnp.ndarray  # (B, P, MAX_MOVES) int32
    count: jnp.ndarray  # (B, P)
    midx: jnp.ndarray  # (B, P)
    searched: jnp.ndarray  # (B, P) legal children folded so far
    alpha: jnp.ndarray  # (B, P) int32
    beta: jnp.ndarray  # (B, P)
    best: jnp.ndarray  # (B, P)
    best_move: jnp.ndarray  # (B, P)
    incheck: jnp.ndarray  # (B, P) bool
    pv: jnp.ndarray  # (B, P, P) int32
    pv_len: jnp.ndarray  # (B, P)
    acc: jnp.ndarray  # (B, P+1, 2, L1) f32 incremental NNUE accumulators
    ply: jnp.ndarray  # (B,)
    mode: jnp.ndarray  # (B,)
    ret: jnp.ndarray  # (B,) value returned by just-finished node
    nodes: jnp.ndarray  # (B,) int32 visited nodes
    depth_limit: jnp.ndarray  # (B,)
    node_budget: jnp.ndarray  # (B,)
    root_score: jnp.ndarray  # (B,)
    root_move: jnp.ndarray  # (B,)


def _board_at(s: SearchState, ply: jnp.ndarray) -> Board:
    return Board(
        board=s.board[ply],
        stm=s.stm[ply],
        ep=s.ep[ply],
        castling=s.castling[ply],
        halfmove=s.halfmove[ply],
    )


def init_state(params: nnue.NnueParams, roots: Board, depth: jnp.ndarray,
               node_budget: jnp.ndarray, max_ply: int) -> SearchState:
    """roots: batched Board (B leading dim); depth/node_budget: (B,)."""
    B = roots.stm.shape[0]
    P = max_ply
    l1 = params.ft_w.shape[1]
    if nnue.is_board768(params):
        root_acc = jax.vmap(nnue.accumulators_768, in_axes=(None, 0))(
            params, roots.board
        )
    else:
        root_acc = jnp.zeros((B, 2, l1), params.ft_w.dtype)
    acc = jnp.zeros((B, P + 1, 2, l1), params.ft_w.dtype)
    acc = acc.at[:, 0].set(root_acc)

    def z(*shape, dtype=jnp.int32, fill=0):
        return jnp.full((B, *shape), fill, dtype=dtype)

    board = z(P + 1, 64)
    board = board.at[:, 0].set(roots.board)
    stm = z(P + 1)
    stm = stm.at[:, 0].set(roots.stm)
    ep = z(P + 1, fill=-1)
    ep = ep.at[:, 0].set(roots.ep)
    castling = z(P + 1, 4, fill=-1)
    castling = castling.at[:, 0].set(roots.castling)
    halfmove = z(P + 1)
    halfmove = halfmove.at[:, 0].set(roots.halfmove)
    return SearchState(
        board=board, stm=stm, ep=ep, castling=castling, halfmove=halfmove,
        moves=z(P, MAX_MOVES, fill=-1),
        count=z(P), midx=z(P), searched=z(P),
        alpha=z(P, fill=-INF), beta=z(P, fill=INF),
        best=z(P, fill=-INF), best_move=z(P, fill=-1),
        incheck=z(P, dtype=jnp.bool_),
        pv=z(P, P, fill=-1), pv_len=z(P),
        acc=acc,
        ply=z(), mode=z(), ret=z(),
        nodes=z(),
        depth_limit=depth.astype(jnp.int32),
        node_budget=node_budget.astype(jnp.int32),
        root_score=z(fill=-INF), root_move=z(fill=-1),
    )


def _step_lane(params: nnue.NnueParams, s: SearchState) -> SearchState:
    """One state-machine step for a single lane (vmapped over B).

    Every stack mutation is a masked *row-level* update (`at[ply].set` with
    a where-selected row): tree-level conds/selects would force XLA to copy
    whole (MAX_PLY, …) stacks per step, which dominates per-step cost.
    """
    # ---------------------------------------------------------- phase ENTER
    ply = s.ply
    enter = s.mode == MODE_ENTER

    b = _board_at(s, ply)
    us = b.stm
    them = 1 - us
    our_k = king_square(b.board, us)
    their_k = king_square(b.board, them)
    # parent's move was illegal iff the side that just moved (them)
    # left its king attacked (or captured outright)
    parent_illegal = (ply > 0) & (
        (their_k < 0) | is_attacked(b.board, jnp.maximum(their_k, 0), us)
    )
    we_are_checked = is_attacked(b.board, jnp.maximum(our_k, 0), them)
    depth_left = s.depth_limit - ply
    over_budget = s.nodes >= s.node_budget
    fifty = b.halfmove >= 100
    is_leaf = (depth_left <= 0) | fifty | over_budget

    # leaf value: NNUE eval (or draw for 50-move). On the board768 fast
    # path the accumulator came down the stack incrementally and only the
    # small layer stack runs here; the halfkav2_hm compat path pays a full
    # refresh per step.
    if nnue.is_board768(params):
        leaf_val = jnp.int32(
            nnue.forward_from_acc(params, s.acc[ply], us, nnue.output_bucket(b.board))
        )
    else:
        leaf_val = jnp.int32(nnue.evaluate(params, b.board, us))
    leaf_val = jnp.clip(leaf_val, -MATE + 1000, MATE - 1000)
    leaf_val = jnp.where(fifty, DRAW, leaf_val)

    gen_moves, gen_count = generate_moves(b)

    to_return = parent_illegal | is_leaf
    expand = enter & ~to_return

    def row_upd(arr, val, mask):
        return arr.at[ply].set(jnp.where(mask, val, arr[ply]))

    moves = s.moves.at[ply].set(jnp.where(expand, gen_moves, s.moves[ply]))
    count = row_upd(s.count, gen_count, expand)
    midx = row_upd(s.midx, 0, expand)
    searched = row_upd(s.searched, 0, expand)
    alpha = row_upd(
        s.alpha, jnp.where(ply == 0, -INF, -s.beta[jnp.maximum(ply - 1, 0)]), expand
    )
    beta = row_upd(
        s.beta, jnp.where(ply == 0, INF, -s.alpha[jnp.maximum(ply - 1, 0)]), expand
    )
    best = row_upd(s.best, -INF, expand)
    best_move = row_upd(s.best_move, -1, expand)
    incheck = row_upd(s.incheck, we_are_checked, enter)
    # leaf nodes must also zero pv_len: the fold at the parent reads
    # pv_len[child_ply], which would otherwise be a stale slot
    pv_len = row_upd(s.pv_len, 0, enter)
    ret = jnp.where(
        enter & to_return, jnp.where(parent_illegal, ILLEGAL, leaf_val), s.ret
    )
    nodes = s.nodes + jnp.where(enter & ~parent_illegal, 1, 0)
    mode = jnp.where(
        enter, jnp.where(to_return, MODE_RETURN, MODE_TRYMOVE), s.mode
    )

    # --------------------------------------------------------- phase RETURN
    # the node at `ply` finished with value `ret` (from its stm's view)
    ret_m = mode == MODE_RETURN
    at_root = ply == 0
    parent = jnp.maximum(ply - 1, 0)
    was_illegal = ret == ILLEGAL
    v = -ret
    tried = moves[parent, jnp.maximum(midx[parent] - 1, 0)]
    better = ret_m & (~at_root) & (~was_illegal) & (v > best[parent])
    fold = ret_m & ~at_root

    best = best.at[parent].set(jnp.where(better, v, best[parent]))
    best_move = best_move.at[parent].set(jnp.where(better, tried, best_move[parent]))
    alpha = alpha.at[parent].set(
        jnp.where(fold, jnp.maximum(alpha[parent], best[parent]), alpha[parent])
    )
    searched = searched.at[parent].set(
        searched[parent] + jnp.where(fold & ~was_illegal, 1, 0)
    )
    # pv[parent] = tried + pv[ply]
    new_pv_row = jnp.concatenate([tried[None], s.pv[ply][:-1]])
    pv = s.pv.at[parent].set(jnp.where(better, new_pv_row, s.pv[parent]))
    pv_len = pv_len.at[parent].set(
        jnp.where(
            better,
            jnp.minimum(pv_len[ply] + 1, s.pv.shape[-1]),
            pv_len[parent],
        )
    )
    # root: record and park (ret, not best[0] — ret carries the
    # mate/stalemate value when the root had no legal moves)
    root_score = jnp.where(ret_m & at_root, ret, s.root_score)
    root_move = jnp.where(ret_m & at_root, best_move[0], s.root_move)
    ply = jnp.where(fold, parent, ply)
    mode = jnp.where(
        ret_m, jnp.where(at_root, MODE_DONE, MODE_TRYMOVE), mode
    )

    # -------------------------------------------------------- phase TRYMOVE
    # note: the node budget is enforced in ENTER (children degrade to leaf
    # evals), not here — finishing a node early with searched==0 would
    # return -INF garbage to the parent
    try_m = mode == MODE_TRYMOVE
    exhausted = midx[ply] >= count[ply]
    cutoff = alpha[ply] >= beta[ply]
    finish = exhausted | cutoff
    advance = try_m & ~finish

    # finished node value: best, or mate/stalemate when no legal child
    no_legal = searched[ply] == 0
    mate_val = jnp.where(incheck[ply], -(MATE - ply), DRAW)
    fin_val = jnp.where(no_legal & exhausted, mate_val, best[ply])

    move = moves[ply, jnp.minimum(midx[ply], MAX_MOVES - 1)]
    parent_b = Board(
        board=s.board[ply], stm=s.stm[ply], ep=s.ep[ply],
        castling=s.castling[ply], halfmove=s.halfmove[ply],
    )
    child = make_move(parent_b, jnp.maximum(move, 0))
    nply = jnp.minimum(ply + 1, s.board.shape[0] - 1)

    midx = midx.at[ply].add(jnp.where(advance, 1, 0))
    board = s.board.at[nply].set(jnp.where(advance, child.board, s.board[nply]))
    stm = s.stm.at[nply].set(jnp.where(advance, child.stm, s.stm[nply]))
    ep = s.ep.at[nply].set(jnp.where(advance, child.ep, s.ep[nply]))
    castling = s.castling.at[nply].set(
        jnp.where(advance, child.castling, s.castling[nply])
    )
    halfmove = s.halfmove.at[nply].set(
        jnp.where(advance, child.halfmove, s.halfmove[nply])
    )
    if nnue.is_board768(params):
        codes, sqs, signs = move_piece_changes(parent_b, jnp.maximum(move, 0))
        child_acc = nnue.apply_acc_updates_768(params, s.acc[ply], codes, sqs, signs)
        acc = s.acc.at[nply].set(jnp.where(advance, child_acc, s.acc[nply]))
    else:
        acc = s.acc

    ret = jnp.where(try_m & finish, fin_val, ret)
    mode = jnp.where(
        try_m, jnp.where(finish, MODE_RETURN, MODE_ENTER), mode
    )
    ply = jnp.where(advance, nply, ply)

    return SearchState(
        board=board, stm=stm, ep=ep, castling=castling, halfmove=halfmove,
        moves=moves, count=count, midx=midx, searched=searched,
        alpha=alpha, beta=beta, best=best, best_move=best_move,
        incheck=incheck, pv=pv, pv_len=pv_len, acc=acc,
        ply=ply, mode=mode, ret=ret, nodes=nodes,
        depth_limit=s.depth_limit, node_budget=s.node_budget,
        root_score=root_score, root_move=root_move,
    )


def make_search_step(params: nnue.NnueParams):
    lane_axes = SearchState(
        *[0 for _ in SearchState._fields]
    )
    return jax.vmap(lambda s: _step_lane(params, s), in_axes=(lane_axes,))


# ------------------------------------------------- segmented (resumable) run
#
# A deep search can take hundreds of thousands of lockstep steps. Running
# them as ONE device program is fragile (a multi-minute XLA program can
# trip device/runtime watchdogs, and cannot be interrupted when the chunk
# deadline passes — reference fishnet races `go_multiple` against the
# deadline and kills the engine process, src/main.rs:307-338). The
# TPU-native equivalent of that kill switch: run the while_loop in bounded
# segments and let the HOST decide between segments whether to continue,
# stop on deadline, or abandon. State lives on device throughout; the only
# per-segment host traffic is one scalar (steps executed).


def _run_segment(params: nnue.NnueParams, state: SearchState,
                 segment_steps: int):
    step = make_search_step(params)

    def cond(carry):
        s, i = carry
        return (i < segment_steps) & jnp.any(s.mode != MODE_DONE)

    def body(carry):
        s, i = carry
        return step(s), i + 1

    state, n = jax.lax.while_loop(cond, body, (state, jnp.int32(0)))
    return state, n


_run_segment_jit = jax.jit(_run_segment, static_argnames=("segment_steps",))
_init_state_jit = jax.jit(init_state, static_argnames=("max_ply",))


def extract_results(state: SearchState, steps) -> dict:
    return {
        "score": state.root_score,
        "move": state.root_move,
        "pv": state.pv[:, 0],
        "pv_len": state.pv_len[:, 0],
        "nodes": state.nodes,
        "done": state.mode == MODE_DONE,
        "steps": steps,
    }


def search_batch_resumable(
    params: nnue.NnueParams,
    roots: Board,
    depth,
    node_budget,
    max_ply: int,
    segment_steps: int = 20_000,
    max_steps: int = 4_000_000,
    deadline: float | None = None,
):
    """Like `search_batch`, but dispatched in bounded segments.

    deadline: absolute time.monotonic() stamp; between segments the host
    stops early when passed. Lanes not DONE at stop report done=False and
    their root_score/move must be ignored by the caller.
    """
    import time as _time

    B = roots.stm.shape[0]
    depth = jnp.broadcast_to(jnp.asarray(depth, jnp.int32), (B,))
    node_budget = jnp.broadcast_to(jnp.asarray(node_budget, jnp.int32), (B,))
    state = _init_state_jit(params, roots, depth, node_budget, max_ply)
    total = 0
    while total < max_steps:
        if deadline is not None and _time.monotonic() >= deadline:
            break  # don't dispatch (or cold-compile) a segment we'd discard
        state, n = _run_segment_jit(params, state, segment_steps)
        total += int(n)  # sync point: segment finished on device
        if int(n) < segment_steps:
            break  # every lane parked in DONE
        if deadline is not None and _time.monotonic() >= deadline:
            break
    return extract_results(state, jnp.int32(total))


def search_batch(params: nnue.NnueParams, roots: Board, depth, node_budget,
                 max_ply: int, max_steps: int = 2_000_000):
    """Run fixed-depth alpha-beta on B root positions in lockstep.

    Requires max_ply > max(depth): leaves live at ply == depth and need
    stack slots. Returns a dict of (B,)-shaped results; scores are
    centipawn ints from the root side to move's perspective; ±(MATE-n)
    encodes mate in n plies.
    """
    B = roots.stm.shape[0]
    depth = jnp.broadcast_to(jnp.asarray(depth, jnp.int32), (B,))
    node_budget = jnp.broadcast_to(jnp.asarray(node_budget, jnp.int32), (B,))
    state = init_state(params, roots, depth, node_budget, max_ply)
    state, steps = _run_segment(params, state, max_steps)
    return extract_results(state, steps)


search_batch_jit = jax.jit(search_batch, static_argnames=("max_ply", "max_steps"))
