"""Lockstep batched alpha-beta search.

The reference's "search layer" is Stockfish's recursive C++ alpha-beta run
in one process per core (reference: §2 of SURVEY.md; fishnet drives it via
`go nodes N` per position, src/stockfish.rs:290-350). On TPU the recursion
becomes an explicit per-lane DFS stack advanced in lockstep by a single
jitted `lax.while_loop` step over B independent lanes:

- copy-make: child boards are written to a (B, MAX_PLY, ...) stack, so
  there is no unmake logic on device;
- pseudo-legal movegen + king-capture refutation: a mover that leaves the
  king en prise is refuted at the child (ILLEGAL sentinel), which keeps
  pin/evasion logic out of the kernel;
- one state machine step = phase ENTER (classify node: illegal/leaf/expand
  with movegen) → phase RETURN (fold a finished child into its parent) →
  phase TRYMOVE (pick next move or finish the node). Phase order is chosen
  so a leaf child costs a single step;
- per-lane node budgets and depth limits; lanes park in DONE and are
  masked out (divergence tax: a step costs the same while any lane runs).

MultiPV and iterative deepening are driven from the host (engine/tpu.py):
lanes are cheap, so multipv lanes are just more lanes.
"""
from __future__ import annotations

import os
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from ..models import nnue
from .board import (
    TERM_LOSS,
    TERM_NONE,
    TERM_WIN,
    Board,
    make_move,
    move_piece_changes,
    node_rules,
)
from .movegen import MAX_MOVES, generate_moves, max_moves_for
from . import tt as _tt_mod

INF = 32500
MATE = 32000
ILLEGAL = 99999  # sentinel: the move leading to this node was illegal
DRAW = 0

MODE_ENTER = 0
MODE_RETURN = 1
MODE_TRYMOVE = 2
MODE_DONE = 3

# game-history repetition seeding: hashes of up to MAX_HIST reversible
# game positions before each lane's root (the reference feeds Stockfish
# the full `position fen ... moves ...` history, so repetitions against
# already-played positions score as draws — src/stockfish.rs:298-306).
# Slot MAX_HIST-1 is the root's parent; unused slots carry the sentinel
# halfmove, which can never satisfy the reversible-chain condition.
MAX_HIST = 16
HIST_HM_SENTINEL = -32000

# FISHNET_TPU_SELECT_UPDATES=1: implement every per-lane dynamic row
# write as a one-hot masked select instead of a dynamic-update-slice
# scatter. This is the candidate workaround for the device fault
# bisected in docs/tpu-hang.md (B>=16 lanes with max_ply>=4 hangs or
# kills the TPU worker — suspected miscompiled scatter at multi-sublane
# lane counts), and masked selects are often faster on TPU anyway. The
# two modes are bit-identical (tests/test_search.py proves it on CPU).
_SELECT_UPDATES = bool(os.environ.get("FISHNET_TPU_SELECT_UPDATES"))

# FISHNET_TPU_NO_PRUNING=1: disable null-move pruning, late-move
# reductions AND futility pruning (debug/A-B lever; the oracle mirrors
# whatever mode is active). All three cut the tree the reference's
# engine cuts it with (Stockfish's search.cpp nullMove/LMR/futility are
# the biggest reducers behind its depth-22 budgets — reference
# src/api.rs:275-281 sends depth 22 move jobs unreachable by plain
# alpha-beta; futility itself lives at the ENTER phase below):
# - null move: at a non-PV-critical node whose static eval already
#   beats beta, give the opponent a free move at reduced depth; if the
#   score STILL comes back >= beta, the node fails high without
#   expanding a single real child.
# - LMR: late, quiet, unchecked moves search at reduced depth first and
#   only re-search at full depth when the reduced result beats alpha.
_PRUNING = not os.environ.get("FISHNET_TPU_NO_PRUNING")
NULL_R = 2  # base null-move depth reduction (+1 at depth_left >= 7)


def _is_quiet(move: jnp.ndarray, board_row: jnp.ndarray) -> jnp.ndarray:
    """Non-capture, non-promotion move (drops count as quiet; en passant
    reads as quiet, which only costs ordering). Shared by the killer/
    history credit and the LMR reduction test so the two paths can never
    disagree on what 'quiet' means; move must be >= 0 (masked upstream)."""
    to = jnp.clip((move >> 6) & 63, 0, 63)
    return (((move >> 15) & 1) == 1) | (
        (board_row[to] == 0) & (((move >> 12) & 7) == 0)
    )


def _row_set(arr: jnp.ndarray, idx, row, mask) -> jnp.ndarray:
    """arr (P, ...) ← row at position idx where mask (all unbatched;
    vmapped over lanes). Scatter or one-hot select per _SELECT_UPDATES."""
    if not _SELECT_UPDATES:
        return arr.at[idx].set(jnp.where(mask, row, arr[idx]))
    sel = (jnp.arange(arr.shape[0], dtype=jnp.int32) == idx) & mask
    sel = sel.reshape((arr.shape[0],) + (1,) * (arr.ndim - 1))
    return jnp.where(sel, row, arr)


class SearchState(NamedTuple):
    # stacks, leading dims (B, MAX_PLY[+1])
    board: jnp.ndarray  # (B, P+1, 64) int32
    stm: jnp.ndarray  # (B, P+1)
    ep: jnp.ndarray  # (B, P+1)
    castling: jnp.ndarray  # (B, P+1, 4)
    halfmove: jnp.ndarray  # (B, P+1)
    extra: jnp.ndarray  # (B, P+1, 12) variant side-state (board.EXTRA_*)
    phash: jnp.ndarray  # (B, P+1, 2) uint32 path hashes (repetition scan)
    hist_hash: jnp.ndarray  # (B, MAX_HIST, 2) uint32 pre-root game hashes
    hist_halfmove: jnp.ndarray  # (B, MAX_HIST) their halfmove counters
    moves: jnp.ndarray  # (B, P, MAX_MOVES) int32
    count: jnp.ndarray  # (B, P)
    midx: jnp.ndarray  # (B, P)
    # per-node remaining depth (root row = lane depth limit; children get
    # parent-1 minus any null-move/LMR reduction on push). Replaces the
    # lane-global depth_limit - ply derivation so reductions can differ
    # per node — the enabler for null-move pruning and LMR.
    depth_left: jnp.ndarray  # (B, P+1)
    null_st: jnp.ndarray  # (B, P) 0 none/spent, 1 pending, 2 in flight
    last_red: jnp.ndarray  # (B, P) reduction applied to last pushed child
    research: jnp.ndarray  # (B,) bool: re-push last child at full depth
    killers: jnp.ndarray  # (B, P, 2) killer-move slots per ply (-1 empty)
    hist: jnp.ndarray  # (B, 4096) from|to-indexed history counters
    searched: jnp.ndarray  # (B, P) legal children folded so far
    alpha: jnp.ndarray  # (B, P) int32
    alpha0: jnp.ndarray  # (B, P) window lower bound at entry (for TT flags)
    beta: jnp.ndarray  # (B, P)
    best: jnp.ndarray  # (B, P)
    best_move: jnp.ndarray  # (B, P)
    incheck: jnp.ndarray  # (B, P) bool
    pv: jnp.ndarray  # (B, P, P) int32
    pv_len: jnp.ndarray  # (B, P)
    acc: jnp.ndarray  # (B, P+1, 2, L1) f32 incremental NNUE accumulators
    ply: jnp.ndarray  # (B,)
    mode: jnp.ndarray  # (B,)
    ret: jnp.ndarray  # (B,) value returned by just-finished node
    ret_depth: jnp.ndarray  # (B,) searched depth of that value (-1: from TT)
    # leaf evals fold into their parent within ONE step (ENTER→RETURN
    # cascade), so they are never visible at a step boundary; the step
    # marks them here and the TT runner stores them with the pre-step hash
    store_mark: jnp.ndarray  # (B,) bool: this step produced a leaf eval
    store_val: jnp.ndarray  # (B,) its static eval
    nodes: jnp.ndarray  # (B,) int32 visited nodes
    depth_limit: jnp.ndarray  # (B,)
    node_budget: jnp.ndarray  # (B,)
    root_score: jnp.ndarray  # (B,)
    root_move: jnp.ndarray  # (B,)
    root_alpha: jnp.ndarray  # (B,) aspiration window at the root
    root_beta: jnp.ndarray  # (B,)


def _board_at(s: SearchState, ply: jnp.ndarray) -> Board:
    return Board(
        board=s.board[ply],
        stm=s.stm[ply],
        ep=s.ep[ply],
        castling=s.castling[ply],
        halfmove=s.halfmove[ply],
        extra=s.extra[ply],
    )


def init_state(params: nnue.NnueParams, roots: Board, depth: jnp.ndarray,
               node_budget: jnp.ndarray, max_ply: int,
               variant: str = "standard",
               hist_hash=None, hist_halfmove=None,
               root_alpha=None, root_beta=None) -> SearchState:
    """roots: batched Board (B leading dim); depth/node_budget: (B,).

    hist_hash (B, MAX_HIST, 2) / hist_halfmove (B, MAX_HIST): optional
    reversible game-history tail per lane (see MAX_HIST above); None
    seeds the sentinel (no pre-root repetitions possible).
    root_alpha/root_beta (B,): optional aspiration window at the root
    (host-side iterative deepening re-searches on fail-low/high)."""
    B = roots.stm.shape[0]
    P = max_ply
    l1 = params.ft_w.shape[1]
    if nnue.is_board768(params):
        root_acc = jax.vmap(nnue.accumulators_768, in_axes=(None, 0))(
            params, roots.board
        )
    else:
        root_acc = jnp.zeros((B, 2, l1), params.ft_w.dtype)
    # acc stays f32 even under bf16-quantized weights (nnue.cast_params):
    # incremental adds accumulate rounding error down the stack otherwise.
    # int8-quantized nets use int32 accumulators — integer adds are exact.
    adt = nnue.acc_dtype(params)
    acc = jnp.zeros((B, P + 1, 2, l1), adt)
    acc = acc.at[:, 0].set(root_acc.astype(adt))

    def z(*shape, dtype=jnp.int32, fill=0):
        return jnp.full((B, *shape), fill, dtype=dtype)

    board = z(P + 1, 64)
    board = board.at[:, 0].set(roots.board)
    stm = z(P + 1)
    stm = stm.at[:, 0].set(roots.stm)
    ep = z(P + 1, fill=-1)
    ep = ep.at[:, 0].set(roots.ep)
    castling = z(P + 1, 4, fill=-1)
    castling = castling.at[:, 0].set(roots.castling)
    halfmove = z(P + 1)
    halfmove = halfmove.at[:, 0].set(roots.halfmove)
    extra = z(P + 1, 12)
    extra = extra.at[:, 0].set(roots.extra)
    phash = jnp.zeros((B, P + 1, 2), jnp.uint32)
    if hist_hash is None:
        hist_hash = jnp.zeros((B, MAX_HIST, 2), jnp.uint32)
    if hist_halfmove is None:
        hist_halfmove = jnp.full((B, MAX_HIST), HIST_HM_SENTINEL, jnp.int32)
    return SearchState(
        board=board, stm=stm, ep=ep, castling=castling, halfmove=halfmove,
        extra=extra, phash=phash,
        hist_hash=jnp.asarray(hist_hash, jnp.uint32),
        hist_halfmove=jnp.asarray(hist_halfmove, jnp.int32),
        moves=z(P, max_moves_for(variant), fill=-1),
        count=z(P), midx=z(P),
        depth_left=jnp.concatenate(
            [depth.astype(jnp.int32)[:, None], jnp.zeros((B, P), jnp.int32)],
            axis=1,
        ),
        null_st=z(P), last_red=z(P),
        research=z(dtype=jnp.bool_),
        killers=z(P, 2, fill=-1), hist=z(4096),
        searched=z(P),
        alpha=z(P, fill=-INF), alpha0=z(P, fill=-INF), beta=z(P, fill=INF),
        best=z(P, fill=-INF), best_move=z(P, fill=-1),
        incheck=z(P, dtype=jnp.bool_),
        pv=z(P, P, fill=-1), pv_len=z(P),
        acc=acc,
        ply=z(), mode=z(), ret=z(), ret_depth=z(),
        store_mark=z(dtype=jnp.bool_), store_val=z(),
        nodes=z(),
        depth_limit=depth.astype(jnp.int32),
        node_budget=node_budget.astype(jnp.int32),
        root_score=z(fill=-INF), root_move=z(fill=-1),
        root_alpha=(
            jnp.full((B,), -INF, jnp.int32) if root_alpha is None
            else jnp.asarray(root_alpha, jnp.int32)
        ),
        root_beta=(
            jnp.full((B,), INF, jnp.int32) if root_beta is None
            else jnp.asarray(root_beta, jnp.int32)
        ),
    )


def _step_lane(params: nnue.NnueParams, s: SearchState,
               tt_hit=None, tt_score=None, tt_move=None,
               variant: str = "standard") -> SearchState:
    """One state-machine step for a single lane (vmapped over B).

    Every stack mutation is a masked *row-level* update (`at[ply].set` with
    a where-selected row): tree-level conds/selects would force XLA to copy
    whole (MAX_PLY, …) stacks per step, which dominates per-step cost.

    tt_hit/tt_score: a usable transposition-table cutoff for this lane's
    current ENTER node (probed outside the vmap against the shared table);
    tt_move: stored best move for ordering (-1 when none). None → no TT.
    """
    # ---------------------------------------------------------- phase ENTER
    ply = s.ply
    enter = s.mode == MODE_ENTER

    b = _board_at(s, ply)
    us = b.stm
    # legality of the move that led here + check state + variant-rule
    # game end, all per the statically compiled variant (board.node_rules)
    illegal_raw, we_are_checked, term_kind = node_rules(b, variant)
    parent_illegal = (ply > 0) & illegal_raw
    depth_left = s.depth_left[ply]
    parent_ix = jnp.maximum(ply - 1, 0)
    # this node was reached by a null move: its window is the parent's
    # null-window (beta-1, beta) seen from this side — and it must not
    # null-move again (two passes in a row search the parent's position)
    parent_null = (ply > 0) & (s.null_st[jnp.minimum(parent_ix, s.null_st.shape[0] - 1)] == 2)
    over_budget = s.nodes >= s.node_budget
    fifty = b.halfmove >= 100

    # twofold repetition along the search path (reference behavior is
    # Stockfish's draw scoring, observable through src/stockfish.rs score
    # output): hash the position on entry, scan ancestors for an equal
    # hash reachable through an unbroken reversible-move chain
    # (halfmove[ply]-halfmove[k] == ply-k). Path-dependent by nature, so
    # repetition draws are never TT-stored and never TT-overridden; the
    # residual graph-history interaction is the same approximation every
    # real engine ships. (_tt_mod is imported at module top: importing it
    # lazily inside this jit-traced function once leaked its module-level
    # Zobrist tables as tracers — see round-2 verdict.)
    h1, h2 = _tt_mod.hash_board(
        b.board, us, b.ep, b.castling, b.extra, variant
    )
    phash = _row_set(s.phash, ply, jnp.stack([h1, h2]), enter)
    ks = jnp.arange(s.phash.shape[0], dtype=jnp.int32)
    chain_ok = (b.halfmove - s.halfmove[ks]) == (ply - ks)
    repet_path = jnp.any(
        (ks < ply)
        & chain_ok
        & (s.phash[:, 0] == h1)
        & (s.phash[:, 1] == h2)
    )
    # ... and against the pre-root game history: slot k sits at virtual
    # ply k - MAX_HIST, so the unbroken-reversible-chain condition is
    # halfmove distance == ply distance with that offset
    hk = jnp.arange(s.hist_halfmove.shape[0], dtype=jnp.int32)
    hist_chain = (b.halfmove - s.hist_halfmove) == (
        ply + (s.hist_halfmove.shape[0] - hk)
    )
    repet_hist = jnp.any(
        hist_chain & (s.hist_hash[:, 0] == h1) & (s.hist_hash[:, 1] == h2)
    )
    repet = enter & (repet_path | repet_hist)
    # window inherited from the parent (negamax flip); a null child runs
    # the parent's zero-width null-window (beta-1, beta) instead
    entry_alpha = jnp.where(ply == 0, s.root_alpha, -s.beta[parent_ix])
    entry_beta = jnp.where(
        ply == 0, s.root_beta,
        jnp.where(parent_null, 1 - s.beta[parent_ix], -s.alpha[parent_ix]),
    )
    # quiescence: past the nominal depth, keep expanding CAPTURES until
    # the position is quiet (gen_noisy == 0), the stack is full, or the
    # budget runs out — the standard horizon-effect fix, with stand-pat
    # as the floor (see the expand section below)
    in_qs = depth_left <= 0
    stack_full = ply >= s.moves.shape[0]  # no moves row / child slot left

    # leaf value: NNUE eval (or draw for 50-move). On the board768 fast
    # path the accumulator came down the stack incrementally and only the
    # small layer stack runs here; the halfkav2_hm compat path pays a full
    # refresh per step — as does atomic, whose explosions exceed the
    # 4-slot incremental update scheme (move_piece_changes).
    if nnue.is_board768(params) and variant != "atomic":
        leaf_val = jnp.int32(
            nnue.forward_from_acc(params, s.acc[ply], us, nnue.output_bucket(b.board))
        )
    else:
        leaf_val = jnp.int32(nnue.evaluate(params, b.board, us))
    leaf_val = jnp.clip(leaf_val, -MATE + 1000, MATE - 1000)
    static_val = leaf_val  # pre-draw-override eval (null-move eligibility)
    leaf_val = jnp.where(fifty | repet, DRAW, leaf_val)

    # variant-rule game end (3 checks, exploded king, hill, goal rank,
    # horde destroyed) ends the node at once — takes precedence over
    # draws; mate-range (or rule-draw) values are never TT-stored
    vterm = term_kind != TERM_NONE
    leaf_val = jnp.where(
        vterm,
        jnp.where(
            term_kind == TERM_LOSS, -(MATE - ply),
            jnp.where(term_kind == TERM_WIN, MATE - ply, DRAW),
        ),
        leaf_val,
    )

    gen_moves, gen_count, gen_noisy = generate_moves(
        b, variant,
        killers=s.killers[jnp.minimum(ply, s.killers.shape[0] - 1)],
        hist=s.hist,
    )
    # futility pruning: at a frontier node (depth_left 1-2, not in check,
    # non-mate window) whose static eval sits a margin below alpha, quiet
    # moves cannot realistically raise alpha — expand only the noisy
    # prefix, exactly the QS mechanics with the static eval as the
    # fail-soft floor (static < alpha, so the floor never raises alpha).
    # The same speculative unsoundness every real engine ships: skipped
    # quiets are treated as searched-and-failed-low.
    if _PRUNING:
        f_margin = jnp.where(depth_left == 1, 150, 300)
        futile = (
            ~in_qs
            & (depth_left <= 2)
            & ~we_are_checked
            & (ply > 0)
            & (static_val + f_margin <= entry_alpha)
            & (entry_alpha > -(MATE - 1000))
            & (entry_alpha < MATE - 1000)
        )
    else:
        futile = jnp.bool_(False)
    qs_like = in_qs | futile  # expands noisy prefix only, static floor
    is_leaf = (
        fifty | repet | vterm | over_budget | stack_full
        | (qs_like & (gen_noisy == 0))
    )
    # stand-pat beta cutoff: in QS the static eval is already >= beta —
    # the opponent wouldn't enter this line; fail high immediately
    stand_pat_cut = in_qs & (leaf_val >= entry_beta)
    is_leaf |= stand_pat_cut

    # TT cutoff: treat as a leaf return with the stored score (never at
    # the root — the root must produce a move; never on fifty-move or
    # repetition draws — the hash excludes the halfmove counter and the
    # path, so a stored score must not override a forced draw)
    use_tt = (
        (tt_hit & (ply > 0) & ~fifty & ~repet & ~vterm)
        if tt_hit is not None
        else jnp.bool_(False)
    )
    to_return = parent_illegal | is_leaf | use_tt
    expand = enter & ~to_return
    # mark fresh static-eval leaves for the runner's depth-0 TT store.
    # Quiet positions only: a quiet static eval IS the node's QS value,
    # while a noisy leaf (budget/stack cutoff) stored as depth-0 EXACT
    # would later short-circuit a real QS expansion of the same position.
    # (fifty/repetition draws excluded: they don't transpose; variant
    # terminals excluded: their ply-relative mate-range values must
    # never be TT-stored)
    leaf_store = (
        enter & is_leaf & ~parent_illegal & ~use_tt & ~fifty & ~repet
        & ~vterm & (gen_noisy == 0)
    )
    store_mark = leaf_store
    store_val = jnp.where(leaf_store, leaf_val, 0)

    # order the stored TT move first (classic biggest ordering win); not
    # in QS, where the swap could pull a quiet move into the noisy prefix
    if tt_move is not None:
        tm_at = jnp.argmax(gen_moves == tt_move)
        # ~qs_like: the swap could pull a quiet move into the noisy prefix
        tm_present = (tt_move >= 0) & (gen_moves[tm_at] == tt_move) & ~qs_like
        m0 = gen_moves[0]
        # dynamic-index swap routed through _row_set so the
        # SELECT_UPDATES experiment covers this scatter too (the index-0
        # write below is static — not a dynamic-update-slice)
        gen_moves = _row_set(gen_moves, tm_at, m0, tm_present)
        gen_moves = gen_moves.at[0].set(
            jnp.where(tm_present, tt_move, gen_moves[0])
        )

    def row_upd(arr, val, mask):
        return _row_set(arr, ply, val, mask)

    moves = _row_set(
        s.moves, jnp.minimum(ply, s.moves.shape[0] - 1), gen_moves, expand
    )
    # QS (and futile) nodes expand only the noisy prefix of the move list
    count = row_upd(s.count, jnp.where(qs_like, gen_noisy, gen_count), expand)
    midx = row_upd(s.midx, 0, expand)
    searched = row_upd(s.searched, 0, expand)
    # stand-pat: in QS the node may decline every capture and keep the
    # static eval, so it floors both best and alpha (futile nodes reuse
    # the same floor; their static sits below alpha by construction, so
    # only `best` actually moves — the fail-soft return value)
    qs_floor = qs_like & expand
    alpha = row_upd(
        s.alpha,
        jnp.where(qs_floor, jnp.maximum(entry_alpha, leaf_val), entry_alpha),
        expand,
    )
    alpha0 = row_upd(s.alpha0, entry_alpha, expand)
    beta = row_upd(s.beta, entry_beta, expand)
    best = row_upd(s.best, jnp.where(qs_floor, leaf_val, -INF), expand)
    best_move = row_upd(s.best_move, -1, expand)
    # null-move eligibility (Stockfish search.cpp nullMove conditions,
    # minus the zugzwang verification search): interior node, depth to
    # spare, not in check, not already inside a null subtree, static
    # eval >= beta, non-mate window, and side to move still has a piece
    # (pawn/king-only positions are where the null observation fails)
    if _PRUNING and variant != "antichess":
        # antichess excluded: captures are FORCED there, so passing is
        # not "at least as bad as the best move" — the null observation
        # that justifies the cutoff simply doesn't hold
        us_base = us * 6
        nonpawn = jnp.any(
            (b.board >= us_base + 2) & (b.board <= us_base + 5)
        )
        nmp_ok = (
            ~in_qs
            & (depth_left >= 3)
            & ~we_are_checked
            & ~parent_null
            & (ply > 0)
            & (static_val >= entry_beta)
            & (entry_beta < MATE - 1000)
            & (entry_beta > -(MATE - 1000))
            & nonpawn
        )
        null_st = row_upd(s.null_st, jnp.where(nmp_ok, 1, 0), expand)
    else:
        null_st = row_upd(s.null_st, 0, expand)
    last_red = row_upd(s.last_red, 0, expand)
    incheck = row_upd(s.incheck, we_are_checked, enter)
    # leaf nodes must also zero pv_len: the fold at the parent reads
    # pv_len[child_ply], which would otherwise be a stale slot
    pv_len = row_upd(s.pv_len, 0, enter)
    ret = jnp.where(
        enter & to_return,
        jnp.where(
            parent_illegal,
            ILLEGAL,
            jnp.where(use_tt, tt_score, leaf_val) if tt_score is not None
            else leaf_val,
        ),
        s.ret,
    )
    # ret_depth: 0 for static leaves, -1 for TT-sourced values (already in
    # the table — don't re-store them)
    ret_depth = jnp.where(
        enter & to_return, jnp.where(use_tt, -1, 0), s.ret_depth
    )
    nodes = s.nodes + jnp.where(enter & ~parent_illegal, 1, 0)
    mode = jnp.where(
        enter, jnp.where(to_return, MODE_RETURN, MODE_TRYMOVE), s.mode
    )

    # --------------------------------------------------------- phase RETURN
    # the node at `ply` finished with value `ret` (from its stm's view)
    ret_m = mode == MODE_RETURN
    at_root = ply == 0
    parent = jnp.maximum(ply - 1, 0)
    was_illegal = ret == ILLEGAL
    v = -ret
    tried = moves[parent, jnp.maximum(midx[parent] - 1, 0)]
    # the child that just returned was the parent's null move: score it
    # against beta only — a fail-high ends the parent (unproven-mate
    # guard: never cut on a mate-range null score), a fail-low is simply
    # discarded. Either way it folds into nothing: no best_move, no pv,
    # no searched credit.
    is_null_ret = ret_m & ~at_root & (null_st[parent] == 2)
    null_cut = (
        is_null_ret & ~was_illegal & (v >= beta[parent]) & (v < MATE - 1000)
    )
    # LMR re-search: the last child was depth-reduced and its reduced
    # score beat alpha — discard the fold and re-push it at full depth
    need_rs = (
        ret_m & ~at_root & ~was_illegal & ~is_null_ret
        & (last_red[parent] > 0) & (v > alpha[parent])
    )
    better = (
        ret_m & (~at_root) & (~was_illegal) & (v > best[parent])
        & ~is_null_ret & ~need_rs
    )
    fold = ret_m & ~at_root

    best = _row_set(best, parent, v, better | null_cut)
    best_move = _row_set(best_move, parent, tried, better)
    alpha = _row_set(
        alpha, parent, jnp.maximum(alpha[parent], best[parent]), fold
    )
    searched = _row_set(
        searched, parent, searched[parent] + 1,
        fold & ~was_illegal & ~is_null_ret & ~need_rs,
    )
    null_st = _row_set(null_st, parent, 0, is_null_ret)
    research = jnp.where(ret_m, need_rs, s.research)
    # pv[parent] = tried + pv[ply]
    new_pv_row = jnp.concatenate([tried[None], s.pv[ply][:-1]])
    pv = _row_set(s.pv, parent, new_pv_row, better)
    pv_len = _row_set(
        pv_len, parent, jnp.minimum(pv_len[ply] + 1, s.pv.shape[-1]), better
    )
    # root: record and park (ret, not best[0] — ret carries the
    # mate/stalemate value when the root had no legal moves)
    root_score = jnp.where(ret_m & at_root, ret, s.root_score)
    root_move = jnp.where(ret_m & at_root, best_move[0], s.root_move)
    ply = jnp.where(fold, parent, ply)
    mode = jnp.where(
        ret_m, jnp.where(at_root, MODE_DONE, MODE_TRYMOVE), mode
    )

    # -------------------------------------------------------- phase TRYMOVE
    # note: the node budget is enforced in ENTER (children degrade to leaf
    # evals), not here — finishing a node early with searched==0 would
    # return -INF garbage to the parent
    try_m = mode == MODE_TRYMOVE
    exhausted = midx[ply] >= count[ply]
    cutoff = alpha[ply] >= beta[ply]
    # a pending null move is tried BEFORE the first real move; an LMR
    # re-push (research, set by RETURN this same step) re-enters the
    # previous move at full depth and overrides finish — exhausted may
    # already be true when the reduced move was the last one
    re_push = try_m & research
    do_null = try_m & ~re_push & (null_st[ply] == 1) & ~cutoff
    finish = (exhausted | cutoff) & ~do_null & ~re_push
    advance = try_m & ~finish
    normal_adv = advance & ~re_push & ~do_null
    dl_node = s.depth_left[ply]

    # killer/history credit on fail-high: the quiet move that raised
    # alpha >= beta becomes killer slot 0 for this ply and earns a
    # depth²-weighted history bump (captures already order by MVV-LVA;
    # en-passant reads as quiet here, which only costs ordering)
    cause = best_move[ply]
    c_quiet = (cause >= 0) & _is_quiet(cause, s.board[ply])
    k_upd = try_m & cutoff & c_quiet
    k0 = s.killers[ply, 0]
    new_row = jnp.stack([cause, jnp.where(cause == k0, s.killers[ply, 1], k0)])
    killers = _row_set(s.killers, ply, new_row, k_upd & (cause != k0))
    h_idx = jnp.clip(cause, 0) & 4095
    dl = jnp.maximum(dl_node, 0)
    h_w = jnp.minimum(dl * dl + 1, 1024)
    hist = _row_set(
        s.hist, h_idx, jnp.minimum(s.hist[h_idx] + h_w, 1 << 20), k_upd
    )

    # finished node value: best, or mate/stalemate when no legal child.
    # QS nodes only tried captures — no legal capture is NOT mate; their
    # stand-pat floor in `best` already covers the quiet alternatives.
    node_in_qs = dl_node <= 0
    # best == -INF guards the count==0 + null-cutoff corner: a null-move
    # fail-high set best without any legal child being searched, and the
    # node must return that score, not a phantom mate/stalemate
    no_legal = (searched[ply] == 0) & ~node_in_qs & (best[ply] == -INF)
    if variant == "antichess":
        # losing chess: the side with no moves left (stalemated or out of
        # pieces) WINS (host: AntichessPosition._variant_outcome)
        mate_val = MATE - ply
    else:
        mate_val = jnp.where(incheck[ply], -(MATE - ply), DRAW)
    fin_val = jnp.where(no_legal & exhausted, mate_val, best[ply])

    m_ix = jnp.where(
        re_push,
        jnp.maximum(midx[ply] - 1, 0),
        jnp.minimum(midx[ply], moves.shape[-1] - 1),
    )
    move = moves[ply, m_ix]
    parent_b = Board(
        board=s.board[ply], stm=s.stm[ply], ep=s.ep[ply],
        castling=s.castling[ply], halfmove=s.halfmove[ply],
        extra=s.extra[ply],
    )
    child = make_move(parent_b, jnp.maximum(move, 0), variant)
    # late-move reduction: late, quiet, unchecked moves of a deep-enough
    # node search 1 ply shallower (2 from move 8); RETURN re-pushes at
    # full depth when the reduced score beats alpha
    if _PRUNING:
        m_quiet = _is_quiet(jnp.maximum(move, 0), s.board[ply])
        lmr_ok = (
            (dl_node >= 3) & (midx[ply] >= 3) & m_quiet
            & ~incheck[ply] & ~node_in_qs
        )
        red = jnp.where(
            lmr_ok, jnp.where(midx[ply] >= 8, 2, 1), 0
        )
        red = jnp.where(re_push | do_null, 0, red)
        # the null child: same position, opponent to move, no ep, and a
        # reset halfmove clock — which deliberately breaks the reversible
        # repetition chain across the null (Stockfish's pliesFromNull)
        child = Board(
            board=jnp.where(do_null, parent_b.board, child.board),
            stm=jnp.where(do_null, 1 - parent_b.stm, child.stm),
            ep=jnp.where(do_null, -1, child.ep),
            castling=jnp.where(do_null, parent_b.castling, child.castling),
            halfmove=jnp.where(do_null, 0, child.halfmove),
            extra=jnp.where(do_null, parent_b.extra, child.extra),
        )
        null_r = NULL_R + jnp.where(dl_node >= 7, 1, 0)
        child_dl = jnp.maximum(
            jnp.where(do_null, dl_node - 1 - null_r, dl_node - 1 - red), 0
        )
    else:
        red = jnp.int32(0)
        child_dl = jnp.maximum(dl_node - 1, 0)
    nply = jnp.minimum(ply + 1, s.board.shape[0] - 1)

    midx = _row_set(midx, ply, midx[ply] + 1, normal_adv)
    null_st = _row_set(null_st, ply, 2, do_null)
    last_red = _row_set(last_red, ply, red, advance)
    research = jnp.where(try_m, jnp.bool_(False), research)
    depth_left = _row_set(s.depth_left, nply, child_dl, advance)
    board = _row_set(s.board, nply, child.board, advance)
    stm = _row_set(s.stm, nply, child.stm, advance)
    ep = _row_set(s.ep, nply, child.ep, advance)
    castling = _row_set(s.castling, nply, child.castling, advance)
    halfmove = _row_set(s.halfmove, nply, child.halfmove, advance)
    extra_st = _row_set(s.extra, nply, child.extra, advance)
    if nnue.is_board768(params) and variant != "atomic":
        codes, sqs, signs = move_piece_changes(
            parent_b, jnp.maximum(move, 0), variant
        )
        if _PRUNING:
            # a null move changes no pieces: zeroed slots make the
            # incremental update an exact no-op (code 0 → no-op)
            codes = jnp.where(do_null, 0, codes)
            signs = jnp.where(do_null, 0, signs)
        child_acc = nnue.apply_acc_updates_768(params, s.acc[ply], codes, sqs, signs)
        acc = _row_set(s.acc, nply, child_acc, advance)
    else:
        acc = s.acc

    ret = jnp.where(try_m & finish, fin_val, ret)
    ret_depth = jnp.where(try_m & finish, dl_node, ret_depth)
    mode = jnp.where(
        try_m, jnp.where(finish, MODE_RETURN, MODE_ENTER), mode
    )
    ply = jnp.where(advance, nply, ply)

    return SearchState(
        board=board, stm=stm, ep=ep, castling=castling, halfmove=halfmove,
        extra=extra_st, phash=phash,
        hist_hash=s.hist_hash, hist_halfmove=s.hist_halfmove,
        moves=moves, count=count, midx=midx,
        depth_left=depth_left, null_st=null_st, last_red=last_red,
        research=research,
        killers=killers, hist=hist,
        searched=searched,
        alpha=alpha, alpha0=alpha0, beta=beta, best=best, best_move=best_move,
        incheck=incheck, pv=pv, pv_len=pv_len, acc=acc,
        ply=ply, mode=mode, ret=ret, ret_depth=ret_depth,
        store_mark=store_mark, store_val=store_val, nodes=nodes,
        depth_limit=s.depth_limit, node_budget=s.node_budget,
        root_score=root_score, root_move=root_move,
        root_alpha=s.root_alpha, root_beta=s.root_beta,
    )


def make_search_step(params: nnue.NnueParams, variant: str = "standard"):
    lane_axes = SearchState(
        *[0 for _ in SearchState._fields]
    )
    return jax.vmap(
        lambda s: _step_lane(params, s, variant=variant), in_axes=(lane_axes,)
    )


def make_search_step_tt(params: nnue.NnueParams, variant: str = "standard"):
    lane_axes = SearchState(
        *[0 for _ in SearchState._fields]
    )
    return jax.vmap(
        lambda s, h, sc, m: _step_lane(params, s, h, sc, m, variant=variant),
        in_axes=(lane_axes, 0, 0, 0),
    )


def _gather_ply(arr: jnp.ndarray, ply: jnp.ndarray) -> jnp.ndarray:
    """arr (B, P, ...) → per-lane row at each lane's ply, shape (B, ...)."""
    return jax.vmap(lambda a, p: a[p])(arr, ply)


# ------------------------------------------------- segmented (resumable) run
#
# A deep search can take hundreds of thousands of lockstep steps. Running
# them as ONE device program is fragile (a multi-minute XLA program can
# trip device/runtime watchdogs, and cannot be interrupted when the chunk
# deadline passes — reference fishnet races `go_multiple` against the
# deadline and kills the engine process, src/main.rs:307-338). The
# TPU-native equivalent of that kill switch: run the while_loop in bounded
# segments and let the HOST decide between segments whether to continue,
# stop on deadline, or abandon. State lives on device throughout; the only
# per-segment host traffic is one scalar (steps executed).


def _run_segment(params: nnue.NnueParams, state: SearchState,
                 ttab, segment_steps: int, variant: str = "standard",
                 deep_tt: bool = False):
    """Advance all lanes ≤ segment_steps. ttab: shared tt.TTable or None.
    deep_tt (STATIC): accept deeper LOWER/UPPER TT entries as cutoffs
    (move-job strength mode — see ops/tt.py probe).

    The TT lives OUTSIDE the vmap: each iteration first stores every lane
    parked in RETURN (its finished node's value), then probes every lane
    in ENTER against the just-updated table, and feeds the probe results
    into the vmapped step. Stores from one lane are visible to every
    other lane in the same iteration — the cross-lane sharing that makes
    one HBM table worth more than B private ones."""

    if ttab is None:
        step = make_search_step(params, variant)

        def body(carry):
            s, t, i = carry
            return step(s), t, i + 1
    else:
        step = make_search_step_tt(params, variant)

        def body(carry):
            s, t, i = carry
            bb = _gather_ply(s.board, s.ply)
            st = _gather_ply(s.stm, s.ply)
            epv = _gather_ply(s.ep, s.ply)
            ca = _gather_ply(s.castling, s.ply)
            ex = _gather_ply(s.extra, s.ply)
            h1, h2 = jax.vmap(
                lambda b_, s_, e_, c_, x_: _tt_mod.hash_board(
                    b_, s_, e_, c_, x_, variant
                )
            )(bb, st, epv, ca, ex)

            # ---- store lanes whose INTERIOR node just finished. (Leaf
            # returns fold into the parent within one step — the ENTER→
            # RETURN cascade — so a lane parked in RETURN here always
            # carries ret_depth >= 1, except TT-sourced values at -1.)
            ret_m = s.mode == MODE_RETURN
            store_mask = (
                ret_m
                & (s.ret != ILLEGAL)
                & (s.ret_depth >= 1)  # -1: value came from the TT itself
                # after budget exhaustion subtrees are degraded — their
                # values are shallow despite the nominal depth label
                & (s.nodes < s.node_budget)
            )
            beta_at = _gather_ply(s.beta, s.ply)
            alpha0_at = _gather_ply(s.alpha0, s.ply)
            flag = jnp.where(
                s.ret >= beta_at,
                _tt_mod.FLAG_LOWER,
                jnp.where(
                    s.ret <= alpha0_at, _tt_mod.FLAG_UPPER, _tt_mod.FLAG_EXACT
                ),
            )
            bm = _gather_ply(s.best_move, s.ply)
            t = _tt_mod.store(
                t, h1, h2, s.ret, jnp.maximum(s.ret_depth, 0), flag, bm,
                store_mask,
            )

            # ---- probe lanes about to enter a node (mode == ENTER);
            # the probe window must match the window ENTER will give the
            # node — incl. the zero-width null window for null children,
            # or stored LOWER bounds inside [1-beta_p, -alpha_p) would
            # miss valid null-search fail-high cutoffs
            enter = s.mode == MODE_ENTER
            parent = jnp.maximum(s.ply - 1, 0)
            pnull = (s.ply > 0) & (_gather_ply(s.null_st, parent) == 2)
            a_w = jnp.where(
                s.ply == 0, s.root_alpha, -_gather_ply(s.beta, parent)
            )
            b_w = jnp.where(
                s.ply == 0, s.root_beta,
                jnp.where(
                    pnull,
                    1 - _gather_ply(s.beta, parent),
                    -_gather_ply(s.alpha, parent),
                ),
            )
            usable, score, _mv, order_mv = _tt_mod.probe(
                t, h1, h2, _gather_ply(s.depth_left, s.ply), a_w, b_w,
                deep_bounds=deep_tt,
            )
            usable &= enter
            order_mv = jnp.where(enter, order_mv, -1)
            s = step(s, usable, score, order_mv)

            # ---- store leaves the step just evaluated (depth-0 EXACT).
            # Their hash is the PRE-step hash: a marking lane was in ENTER
            # at this ply, exactly the position h1/h2 were computed for.
            t = _tt_mod.store(
                t, h1, h2, s.store_val, jnp.zeros_like(s.store_val),
                jnp.full_like(s.store_val, _tt_mod.FLAG_EXACT),
                jnp.full_like(s.store_val, -1), s.store_mark,
            )
            return s, t, i + 1

    def cond(carry):
        s, t, i = carry
        return (i < segment_steps) & jnp.any(s.mode != MODE_DONE)

    state, ttab, n = jax.lax.while_loop(
        cond, body, (state, ttab, jnp.int32(0))
    )
    return state, ttab, n


_run_segment_jit = jax.jit(
    _run_segment, static_argnames=("segment_steps", "variant", "deep_tt")
)
_init_state_jit = jax.jit(init_state, static_argnames=("max_ply", "variant"))


def extract_results(state: SearchState, steps) -> dict:
    return {
        "score": state.root_score,
        "move": state.root_move,
        "pv": state.pv[:, 0],
        "pv_len": state.pv_len[:, 0],
        "nodes": state.nodes,
        "done": state.mode == MODE_DONE,
        "steps": steps,
    }


def search_batch_resumable(
    params: nnue.NnueParams,
    roots: Board,
    depth,
    node_budget,
    max_ply: int,
    segment_steps: int = 20_000,
    max_steps: int = 4_000_000,
    deadline: float | None = None,
    tt=None,
    mesh=None,
    variant: str = "standard",
    hist=None,
    window=None,
    deep_tt: bool = False,
):
    """Like `search_batch`, but dispatched in bounded segments.

    window: optional (root_alpha (B,), root_beta (B,)) aspiration window;
    a root whose true value falls outside reports a bound (fail-low /
    fail-high) — the caller re-searches with a wider window.

    deep_tt: accept deeper LOWER/UPPER TT entries as cutoffs (move-job
    strength mode; analysis keeps deterministic exact-depth probes).

    deadline: absolute time.monotonic() stamp; between segments the host
    stops early when passed. Lanes not DONE at stop report done=False and
    their root_score/move must be ignored by the caller.

    tt: optional shared ops.tt.TTable; the updated table is returned as
    results["tt"] so callers can carry it across searches (the engine
    keeps one per process, like Stockfish's persistent hash).

    mesh: optional jax.sharding.Mesh — lanes shard over its devices and
    each device advances its shard independently (parallel.mesh). With a
    mesh, tt must carry a leading (ndev,) shard dim
    (parallel.mesh.make_sharded_table) or be None.
    """
    import time as _time

    B = roots.stm.shape[0]
    depth = jnp.broadcast_to(jnp.asarray(depth, jnp.int32), (B,))
    node_budget = jnp.broadcast_to(jnp.asarray(node_budget, jnp.int32), (B,))
    hist_hash, hist_halfmove = hist if hist is not None else (None, None)
    root_alpha, root_beta = window if window is not None else (None, None)
    state = _init_state_jit(
        params, roots, depth, node_budget, max_ply, variant,
        hist_hash=hist_hash, hist_halfmove=hist_halfmove,
        root_alpha=root_alpha, root_beta=root_beta,
    )
    if mesh is not None:
        from ..parallel.mesh import run_segment_sharded

        def dispatch(state, tt):
            state, tt, n = run_segment_sharded(
                mesh, params, state, tt, segment_steps, variant=variant,
                deep_tt=deep_tt,
            )
            # devices stop independently; continue while ANY used the
            # full segment (i.e. may still have live lanes)
            return state, tt, int(np.max(np.asarray(n)))
    else:
        def dispatch(state, tt):
            state, tt, n = _run_segment_jit(
                params, state, tt, segment_steps, variant, deep_tt
            )
            return state, tt, int(n)

    total = 0
    while total < max_steps:
        if deadline is not None and _time.monotonic() >= deadline:
            break  # don't dispatch (or cold-compile) a segment we'd discard
        state, tt, n = dispatch(state, tt)
        total += n  # sync point: segment finished on device
        if n < segment_steps:
            break  # every lane parked in DONE
        if deadline is not None and _time.monotonic() >= deadline:
            break
    out = extract_results(state, jnp.int32(total))
    out["tt"] = tt
    return out


def search_batch(params: nnue.NnueParams, roots: Board, depth, node_budget,
                 max_ply: int, max_steps: int = 2_000_000, tt=None,
                 variant: str = "standard", hist=None):
    """Run fixed-depth alpha-beta + capture quiescence on B roots in
    lockstep.

    Requires max_ply > max(depth): past the nominal depth the search
    keeps expanding captures (quiescence with stand-pat) until quiet or
    until the max_ply stack runs out, so max_ply - depth is the QS
    headroom. Returns a dict of (B,)-shaped results; scores are
    centipawn ints from the root side to move's perspective; ±(MATE-n)
    encodes mate in n plies. tt: optional shared ops.tt.TTable.

    Thin wrapper over `search_batch_resumable` (one compile surface —
    tests and production share the same `_run_segment_jit` programs; a
    second whole-search jit used to double every suite's compile cost).
    """
    return search_batch_resumable(
        params, roots, depth, node_budget, max_ply=max_ply,
        segment_steps=min(max_steps, 20_000), max_steps=max_steps,
        tt=tt, variant=variant, hist=hist,
    )


# alias kept for callers that used the jitted entry point; the segment
# dispatch inside is jitted, so a separate outer jit adds nothing
search_batch_jit = search_batch
