"""Lockstep batched alpha-beta search.

The reference's "search layer" is Stockfish's recursive C++ alpha-beta run
in one process per core (reference: §2 of SURVEY.md; fishnet drives it via
`go nodes N` per position, src/stockfish.rs:290-350). On TPU the recursion
becomes an explicit per-lane DFS stack advanced in lockstep by a single
jitted `lax.while_loop` step over B independent lanes:

- copy-make: child boards are written to a (B, MAX_PLY, ...) stack, so
  there is no unmake logic on device;
- pseudo-legal movegen + king-capture refutation: a mover that leaves the
  king en prise is refuted at the child (ILLEGAL sentinel), which keeps
  pin/evasion logic out of the kernel;
- one state machine step = phase ENTER (classify node: illegal/leaf/expand
  with movegen) → phase RETURN (fold a finished child into its parent) →
  phase TRYMOVE (pick next move or finish the node). Phase order is chosen
  so a leaf child costs a single step;
- per-lane node budgets and depth limits; lanes park in DONE and are
  masked out (divergence tax: a step costs the same while any lane runs).

State layout (round-5 redesign): the round-5 device profile
(docs/profile-r5.md) showed the step's cost dominated by per-op overhead —
~380 compiled ops and a ~330 us/step fixed scheduling gap — rather than
compute. The ~30 small per-node arrays are therefore PACKED into three
tables so each phase issues ONE fused row write instead of ~a dozen:

  bt   (B, P+1, BT_W)  board rows: board(64), stm, ep, castling(4),
                       halfmove, extra(12), path-hash words (int32 bits)
  nt   (B, P+1, NT_W)  per-node search scalars: move cursor, window,
                       null/LMR state, pv length, remaining depth,
                       in-check flag, killer slots
  lane (B, LN_W)       per-lane scalars: ply, mode, return value/depth,
                       leaf-store mark, node counter, budget, root
                       window/result, LMR re-search flag

MultiPV and iterative deepening are driven from the host (engine/tpu.py):
lanes are cheap, so multipv lanes are just more lanes.
"""
from __future__ import annotations

import warnings
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

# Buffer donation below is best-effort by design: XLA:CPU declines to
# alias through the select ops _merge_lanes lowers to, and jax then
# warns once per compile. The donation still holds wherever the backend
# CAN alias (the big _run_segment tables, TPU merges), so the warning is
# pure noise here — silence exactly it, nothing broader.
warnings.filterwarnings(
    "ignore", message="Some donated buffers were not usable"
)

from ..aot import registry as _aot_registry
from ..models import nnue
from ..utils import sanitize as _sanitize
from ..utils import settings
from .board import (
    TERM_LOSS,
    TERM_NONE,
    TERM_WIN,
    Board,
    make_move,
    move_piece_changes,
    node_rules,
)
from .movegen import MAX_MOVES, generate_moves, max_moves_for
from . import tt as _tt_mod

INF = 32500
MATE = 32000
ILLEGAL = 99999  # sentinel: the move leading to this node was illegal
DRAW = 0

MODE_ENTER = 0
MODE_RETURN = 1
MODE_TRYMOVE = 2
MODE_DONE = 3

# packed boundary summary (int32, shape (B+1, 4)): everything the host
# needs to decide a segment boundary — done bitmap plus per-lane
# nodes/score/best-move — in ONE small transfer instead of the full
# extract_results set; row B broadcasts the segment's step count. PV
# rows are pulled separately, and only for lanes that actually finished.
SUM_DONE, SUM_NODES, SUM_SCORE, SUM_MOVE = range(4)
SUM_W = 4

# game-history repetition seeding: hashes of up to MAX_HIST reversible
# game positions before each lane's root (the reference feeds Stockfish
# the full `position fen ... moves ...` history, so repetitions against
# already-played positions score as draws — src/stockfish.rs:298-306).
# Slot MAX_HIST-1 is the root's parent; unused slots carry the sentinel
# halfmove, which can never satisfy the reversible-chain condition.
MAX_HIST = 16
HIST_HM_SENTINEL = -32000

# ---------------------------------------------------------- packed layouts
# nt fields (one int32 row per node)
(NT_COUNT, NT_MIDX, NT_SEARCHED, NT_ALPHA, NT_ALPHA0, NT_BETA, NT_BEST,
 NT_BMOVE, NT_NULL, NT_LASTRED, NT_PVLEN, NT_DL, NT_INCHECK, NT_K0,
 NT_K1) = range(15)
NT_W = 16
# bt fields (one int32 row per node's board)
BT_BOARD = 0
BT_STM = 64
BT_EP = 65
BT_CAST = 66
BT_HM = 70
BT_EXTRA = 71
BT_PH1 = 83  # path-hash words, uint32 stored as int32 bits
BT_PH2 = 84
BT_W = 96
# lane fields
(LN_PLY, LN_MODE, LN_RET, LN_RETD, LN_SMARK, LN_SVAL, LN_NODES, LN_DLIM,
 LN_BUDGET, LN_RSCORE, LN_RMOVE, LN_RALPHA, LN_RBETA, LN_RESEARCH) = range(14)
# lane-group metadata (Lazy-SMP helper lanes, engine/tpu.py): the lane's
# ordering-jitter seed (0 = primary / unperturbed) and its group id (the
# original lane index of the primary whose root it replicates). Carried
# for debugging/extraction; the jitter's effect is baked into the
# initial history table by init_state.
LN_JITTER = 14
LN_GROUP = 15
LN_W = 16

# nt fields ENTER initializes on node expansion vs on every entry: a
# single full-row write reproduces the per-field masks because the row
# vector keeps the old value wherever the mask is off (see _step_lane)
_FM_EXPAND = np.zeros(NT_W, bool)
_FM_EXPAND[[NT_COUNT, NT_MIDX, NT_SEARCHED, NT_ALPHA, NT_ALPHA0, NT_BETA,
            NT_BEST, NT_BMOVE, NT_NULL, NT_LASTRED]] = True
_FM_ENTER = np.zeros(NT_W, bool)
_FM_ENTER[[NT_PVLEN, NT_INCHECK]] = True

# FISHNET_TPU_SELECT_UPDATES: implement every per-lane dynamic row write
# as a one-hot masked select (=1, the DEFAULT since round 5) instead of a
# dynamic-update-slice scatter (=0). Select is the workaround for the
# device fault bisected in docs/tpu-hang.md (B>=16 lanes with max_ply>=4
# hung or killed the TPU worker — suspected miscompiled scatter at
# multi-sublane lane counts) AND, since the round-5 packed-table layout,
# dramatically faster: scatter lowers the packed row writes to a
# serialized form costing 25 ms/step at B=256 vs select's 1.15 ms
# (docs/profile-r5.md). The two modes are bit-identical
# (tests/test_search.py proves it on CPU).
_SELECT_UPDATES = settings.get_bool("FISHNET_TPU_SELECT_UPDATES")

# FISHNET_TPU_NO_PRUNING=1: disable null-move pruning, late-move
# reductions AND futility pruning (debug/A-B lever; the oracle mirrors
# whatever mode is active). All three cut the tree the reference's
# engine cuts it with (Stockfish's search.cpp nullMove/LMR/futility are
# the biggest reducers behind its depth-22 budgets — reference
# src/api.rs:275-281 sends depth 22 move jobs unreachable by plain
# alpha-beta; futility itself lives at the ENTER phase below):
# - null move: at a non-PV-critical node whose static eval already
#   beats beta, give the opponent a free move at reduced depth; if the
#   score STILL comes back >= beta, the node fails high without
#   expanding a single real child.
# - LMR: late, quiet, unchecked moves search at reduced depth first and
#   only re-search at full depth when the reduced result beats alpha.
# ("" and "0" both leave pruning ON — same parse as SELECT_UPDATES, so
# exporting the var as 0 never silently flips the search mode)
_PRUNING = not settings.get_bool("FISHNET_TPU_NO_PRUNING")
NULL_R = 2  # base null-move depth reduction (+1 at depth_left >= 7)


def _is_quiet(move: jnp.ndarray, board_row: jnp.ndarray) -> jnp.ndarray:
    """Non-capture, non-promotion move (drops count as quiet; en passant
    reads as quiet, which only costs ordering). Shared by the killer/
    history credit and the LMR reduction test so the two paths can never
    disagree on what 'quiet' means; move must be >= 0 (masked upstream)."""
    to = jnp.clip((move >> 6) & 63, 0, 63)
    return (((move >> 15) & 1) == 1) | (
        (board_row[to] == 0) & (((move >> 12) & 7) == 0)
    )


def _row_set(arr: jnp.ndarray, idx, row, mask) -> jnp.ndarray:
    """arr (R, ...) ← row at position idx where mask (all unbatched;
    vmapped over lanes). Scatter or one-hot select per _SELECT_UPDATES."""
    if not _SELECT_UPDATES:
        return arr.at[idx].set(jnp.where(mask, row, arr[idx]))
    sel = (jnp.arange(arr.shape[0], dtype=jnp.int32) == idx) & mask
    sel = sel.reshape((arr.shape[0],) + (1,) * (arr.ndim - 1))
    return jnp.where(sel, row, arr)


def _field_set(tab: jnp.ndarray, row_idx, field: int, val, mask) -> jnp.ndarray:
    """tab (R, W): tab[row_idx, field] ← val where mask, as one fused
    2-D one-hot select (no row read needed)."""
    oh_r = (jnp.arange(tab.shape[0], dtype=jnp.int32) == row_idx) & mask
    oh_f = jnp.arange(tab.shape[1], dtype=jnp.int32) == field
    return jnp.where(oh_r[:, None] & oh_f[None, :], val, tab)


def _board_from_row(row: jnp.ndarray) -> Board:
    return Board(
        board=row[BT_BOARD:BT_BOARD + 64],
        stm=row[BT_STM],
        ep=row[BT_EP],
        castling=row[BT_CAST:BT_CAST + 4],
        halfmove=row[BT_HM],
        extra=row[BT_EXTRA:BT_EXTRA + 12],
    )


def _row_from_board(b: Board, ph1=None, ph2=None) -> jnp.ndarray:
    z = jnp.zeros((1,), jnp.int32)
    ph1 = z if ph1 is None else jnp.asarray(ph1, jnp.int32)[None]
    ph2 = z if ph2 is None else jnp.asarray(ph2, jnp.int32)[None]
    return jnp.concatenate([
        b.board.astype(jnp.int32),
        jnp.asarray(b.stm, jnp.int32)[None],
        jnp.asarray(b.ep, jnp.int32)[None],
        b.castling.astype(jnp.int32),
        jnp.asarray(b.halfmove, jnp.int32)[None],
        b.extra.astype(jnp.int32),
        ph1, ph2,
        jnp.zeros((BT_W - BT_PH2 - 1,), jnp.int32),
    ])


class SearchState(NamedTuple):
    bt: jnp.ndarray  # (B, P+1, BT_W) int32 board rows
    nt: jnp.ndarray  # (B, P+1, NT_W) int32 per-node scalars
    lane: jnp.ndarray  # (B, LN_W) int32 per-lane scalars
    hist_hash: jnp.ndarray  # (B, MAX_HIST, 2) uint32 pre-root game hashes
    hist_halfmove: jnp.ndarray  # (B, MAX_HIST) their halfmove counters
    moves: jnp.ndarray  # (B, P, MAX_MOVES) int32
    hist: jnp.ndarray  # (B, 4096) from|to-indexed history counters
    pv: jnp.ndarray  # (B, P, P) int32
    acc: jnp.ndarray  # (B, P+1, 2, L1) incremental NNUE accumulators


def init_state(params: nnue.NnueParams, roots: Board, depth: jnp.ndarray,
               node_budget: jnp.ndarray, max_ply: int,
               variant: str = "standard",
               hist_hash=None, hist_halfmove=None,
               root_alpha=None, root_beta=None,
               order_jitter=None, group=None) -> SearchState:
    """roots: batched Board (B leading dim); depth/node_budget: (B,).

    hist_hash (B, MAX_HIST, 2) / hist_halfmove (B, MAX_HIST): optional
    reversible game-history tail per lane (see MAX_HIST above); None
    seeds the sentinel (no pre-root repetitions possible).
    root_alpha/root_beta (B,): optional aspiration window at the root
    (host-side iterative deepening re-searches on fail-low/high).
    order_jitter (B,): optional per-lane move-ordering perturbation seed
    for Lazy-SMP helper lanes. A lane with jitter j > 0 starts with
    small pseudo-random history counters (hash-mixed from j), so its
    quiet-move ordering breaks ties differently from every other lane of
    its group — the lanes then explore the shared tree in different
    orders and feed each other TT entries. Jitter 0 seeds exact zeros:
    a jitter-0 lane is bit-identical to one searched without the
    argument. group (B,): opaque per-lane group tag (stored, unused by
    the search itself)."""
    B = roots.stm.shape[0]
    P = max_ply
    l1 = params.ft_w.shape[1]
    if nnue.is_board768(params):
        root_acc = jax.vmap(nnue.accumulators_768, in_axes=(None, 0))(
            params, roots.board
        )
    else:
        root_acc = jnp.zeros((B, 2, l1), params.ft_w.dtype)
    # acc stays f32 even under bf16-quantized weights (nnue.cast_params):
    # incremental adds accumulate rounding error down the stack otherwise.
    # int8-quantized nets use int32 accumulators — integer adds are exact.
    adt = nnue.acc_dtype(params)
    acc = jnp.zeros((B, P + 1, 2, l1), adt)
    acc = acc.at[:, 0].set(root_acc.astype(adt))

    bt = jnp.zeros((B, P + 1, BT_W), jnp.int32)
    bt = bt.at[:, :, BT_EP].set(-1)
    bt = bt.at[:, :, BT_CAST:BT_CAST + 4].set(-1)
    root_rows = jax.vmap(_row_from_board)(roots)
    bt = bt.at[:, 0].set(root_rows)

    nt = jnp.zeros((B, P + 1, NT_W), jnp.int32)
    nt = nt.at[:, :, NT_ALPHA].set(-INF)
    nt = nt.at[:, :, NT_ALPHA0].set(-INF)
    nt = nt.at[:, :, NT_BETA].set(INF)
    nt = nt.at[:, :, NT_BEST].set(-INF)
    nt = nt.at[:, :, NT_BMOVE].set(-1)
    nt = nt.at[:, :, NT_K0].set(-1)
    nt = nt.at[:, :, NT_K1].set(-1)
    nt = nt.at[:, 0, NT_DL].set(depth.astype(jnp.int32))

    lane = jnp.zeros((B, LN_W), jnp.int32)
    lane = lane.at[:, LN_DLIM].set(depth.astype(jnp.int32))
    lane = lane.at[:, LN_BUDGET].set(node_budget.astype(jnp.int32))
    lane = lane.at[:, LN_RSCORE].set(-INF)
    lane = lane.at[:, LN_RMOVE].set(-1)
    lane = lane.at[:, LN_RALPHA].set(
        jnp.full((B,), -INF, jnp.int32) if root_alpha is None
        else jnp.asarray(root_alpha, jnp.int32)
    )
    lane = lane.at[:, LN_RBETA].set(
        jnp.full((B,), INF, jnp.int32) if root_beta is None
        else jnp.asarray(root_beta, jnp.int32)
    )
    if order_jitter is not None:
        lane = lane.at[:, LN_JITTER].set(jnp.asarray(order_jitter, jnp.int32))
    if group is not None:
        lane = lane.at[:, LN_GROUP].set(jnp.asarray(group, jnp.int32))

    hist0 = jnp.zeros((B, 4096), jnp.int32)
    if order_jitter is not None:
        # jittered lanes start from small (0..255) pseudo-random history
        # counters instead of zeros, and exactly zero where jitter == 0.
        # The range matters: move ordering reads hist >> 5 (movegen.py
        # hbonus), so seeds below 32 would be invisible — 0..255 yields
        # ordering bonuses of 0..7 key units, enough to reorder the
        # equal-history quiet tail, while sustained real cutoffs (dl²+1
        # credit each, growing to 2^20) still dominate within a few
        # fail-highs
        j = jnp.asarray(order_jitter, jnp.int32).astype(jnp.uint32)
        idx = jnp.arange(4096, dtype=jnp.uint32)
        mix = (j[:, None] * jnp.uint32(2654435761)) ^ (
            idx[None, :] * jnp.uint32(2246822519)
        )
        mix = mix ^ (mix >> 15)
        hist0 = jnp.where(
            (j > 0)[:, None], (mix & jnp.uint32(255)).astype(jnp.int32), hist0
        )

    if hist_hash is None:
        hist_hash = jnp.zeros((B, MAX_HIST, 2), jnp.uint32)
    if hist_halfmove is None:
        hist_halfmove = jnp.full((B, MAX_HIST), HIST_HM_SENTINEL, jnp.int32)
    return SearchState(
        bt=bt, nt=nt, lane=lane,
        hist_hash=jnp.asarray(hist_hash, jnp.uint32),
        hist_halfmove=jnp.asarray(hist_halfmove, jnp.int32),
        moves=jnp.full((B, P, max_moves_for(variant)), -1, jnp.int32),
        hist=hist0,
        pv=jnp.full((B, P, P), -1, jnp.int32),
        acc=acc,
    )


def _step_lane(params: nnue.NnueParams, s: SearchState,
               tt_hit=None, tt_score=None, tt_move=None,
               variant: str = "standard") -> SearchState:
    """One state-machine step for a single lane (vmapped over B).

    The three phases keep their row state in registers: ENTER composes
    the entered node's nt/bt rows, RETURN composes the parent's, and
    TRYMOVE selects whichever row it acts on from those — so the whole
    step issues four nt row writes, two bt row writes and one write each
    to moves/pv/acc/hist, instead of ~30 per-array scatters (round-5
    profile: per-op overhead dominated the step).

    tt_hit/tt_score: a usable transposition-table cutoff for this lane's
    current ENTER node (probed outside the vmap against the shared table);
    tt_move: stored best move for ordering (-1 when none). None → no TT.
    """
    lane = s.lane
    ply0 = lane[LN_PLY]
    mode0 = lane[LN_MODE]
    nodes = lane[LN_NODES]
    parent0 = jnp.maximum(ply0 - 1, 0)
    P1 = s.bt.shape[0]  # P+1 rows
    ntr0 = s.nt[ply0]
    ntp0 = s.nt[parent0]
    btr0 = s.bt[ply0]
    btp0 = s.bt[parent0]
    moves_p_row = s.moves[jnp.minimum(parent0, s.moves.shape[0] - 1)]

    # ---------------------------------------------------------- phase ENTER
    enter = mode0 == MODE_ENTER
    b = _board_from_row(btr0)
    us = b.stm
    # legality of the move that led here + check state + variant-rule
    # game end, all per the statically compiled variant (board.node_rules)
    illegal_raw, we_are_checked, term_kind = node_rules(b, variant)
    parent_illegal = (ply0 > 0) & illegal_raw
    depth_left = ntr0[NT_DL]
    # this node was reached by a null move: its window is the parent's
    # null-window (beta-1, beta) seen from this side — and it must not
    # null-move again (two passes in a row search the parent's position)
    parent_null = (ply0 > 0) & (ntp0[NT_NULL] == 2)
    over_budget = nodes >= lane[LN_BUDGET]
    fifty = b.halfmove >= 100

    # twofold repetition along the search path (reference behavior is
    # Stockfish's draw scoring, observable through src/stockfish.rs score
    # output): hash the position on entry, scan ancestors for an equal
    # hash reachable through an unbroken reversible-move chain
    # (halfmove[ply]-halfmove[k] == ply-k). Path-dependent by nature, so
    # repetition draws are never TT-stored and never TT-overridden; the
    # residual graph-history interaction is the same approximation every
    # real engine ships. (_tt_mod is imported at module top: importing it
    # lazily inside this jit-traced function once leaked its module-level
    # Zobrist tables as tracers — see round-2 verdict.)
    h1, h2 = _tt_mod.hash_board(
        b.board, us, b.ep, b.castling, b.extra, variant
    )
    h1i = jax.lax.bitcast_convert_type(h1, jnp.int32)
    h2i = jax.lax.bitcast_convert_type(h2, jnp.int32)
    ks = jnp.arange(P1, dtype=jnp.int32)
    chain_ok = (b.halfmove - s.bt[:, BT_HM]) == (ply0 - ks)
    repet_path = jnp.any(
        (ks < ply0)
        & chain_ok
        & (s.bt[:, BT_PH1] == h1i)
        & (s.bt[:, BT_PH2] == h2i)
    )
    # ... and against the pre-root game history: slot k sits at virtual
    # ply k - MAX_HIST, so the unbroken-reversible-chain condition is
    # halfmove distance == ply distance with that offset
    hk = jnp.arange(s.hist_halfmove.shape[0], dtype=jnp.int32)
    hist_chain = (b.halfmove - s.hist_halfmove) == (
        ply0 + (s.hist_halfmove.shape[0] - hk)
    )
    repet_hist = jnp.any(
        hist_chain & (s.hist_hash[:, 0] == h1) & (s.hist_hash[:, 1] == h2)
    )
    repet = enter & (repet_path | repet_hist)
    # window inherited from the parent (negamax flip); a null child runs
    # the parent's zero-width null-window (beta-1, beta) instead
    entry_alpha = jnp.where(ply0 == 0, lane[LN_RALPHA], -ntp0[NT_BETA])
    entry_beta = jnp.where(
        ply0 == 0, lane[LN_RBETA],
        jnp.where(parent_null, 1 - ntp0[NT_BETA], -ntp0[NT_ALPHA]),
    )
    # quiescence: past the nominal depth, keep expanding CAPTURES until
    # the position is quiet (gen_noisy == 0), the stack is full, or the
    # budget runs out — the standard horizon-effect fix, with stand-pat
    # as the floor (see the expand section below)
    in_qs = depth_left <= 0
    stack_full = ply0 >= s.moves.shape[0]  # no moves row / child slot left

    # leaf value: NNUE eval (or draw for 50-move). On the board768 fast
    # path the accumulator came down the stack incrementally and only the
    # small layer stack runs here; the halfkav2_hm compat path pays a full
    # refresh per step — as does atomic, whose explosions exceed the
    # 4-slot incremental update scheme (move_piece_changes).
    if nnue.is_board768(params) and variant != "atomic":
        leaf_val = jnp.int32(
            nnue.forward_from_acc(params, s.acc[ply0], us, nnue.output_bucket(b.board))
        )
    else:
        leaf_val = jnp.int32(nnue.evaluate(params, b.board, us))
    leaf_val = jnp.clip(leaf_val, -MATE + 1000, MATE - 1000)
    static_val = leaf_val  # pre-draw-override eval (null-move eligibility)
    leaf_val = jnp.where(fifty | repet, DRAW, leaf_val)

    # variant-rule game end (3 checks, exploded king, hill, goal rank,
    # horde destroyed) ends the node at once — takes precedence over
    # draws; mate-range (or rule-draw) values are never TT-stored
    vterm = term_kind != TERM_NONE
    leaf_val = jnp.where(
        vterm,
        jnp.where(
            term_kind == TERM_LOSS, -(MATE - ply0),
            jnp.where(term_kind == TERM_WIN, MATE - ply0, DRAW),
        ),
        leaf_val,
    )

    gen_moves, gen_count, gen_noisy = generate_moves(
        b, variant,
        killers=jnp.stack([ntr0[NT_K0], ntr0[NT_K1]]),
        hist=s.hist,
    )
    # futility pruning: at a frontier node (depth_left 1-2, not in check,
    # non-mate window) whose static eval sits a margin below alpha, quiet
    # moves cannot realistically raise alpha — expand only the noisy
    # prefix, exactly the QS mechanics with the static eval as the
    # fail-soft floor (static < alpha, so the floor never raises alpha).
    # The same speculative unsoundness every real engine ships: skipped
    # quiets are treated as searched-and-failed-low.
    if _PRUNING:
        f_margin = jnp.where(depth_left == 1, 150, 300)
        futile = (
            ~in_qs
            & (depth_left <= 2)
            & ~we_are_checked
            & (ply0 > 0)
            & (static_val + f_margin <= entry_alpha)
            & (entry_alpha > -(MATE - 1000))
            & (entry_alpha < MATE - 1000)
        )
    else:
        futile = jnp.bool_(False)
    qs_like = in_qs | futile  # expands noisy prefix only, static floor
    is_leaf = (
        fifty | repet | vterm | over_budget | stack_full
        | (qs_like & (gen_noisy == 0))
    )
    # stand-pat beta cutoff: in QS the static eval is already >= beta —
    # the opponent wouldn't enter this line; fail high immediately
    stand_pat_cut = in_qs & (leaf_val >= entry_beta)
    is_leaf |= stand_pat_cut

    # TT cutoff: treat as a leaf return with the stored score (never at
    # the root — the root must produce a move; never on fifty-move or
    # repetition draws — the hash excludes the halfmove counter and the
    # path, so a stored score must not override a forced draw)
    use_tt = (
        (tt_hit & (ply0 > 0) & ~fifty & ~repet & ~vterm)
        if tt_hit is not None
        else jnp.bool_(False)
    )
    to_return = parent_illegal | is_leaf | use_tt
    expand = enter & ~to_return
    # mark fresh static-eval leaves for the runner's depth-0 TT store.
    # Quiet positions only: a quiet static eval IS the node's QS value,
    # while a noisy leaf (budget/stack cutoff) stored as depth-0 EXACT
    # would later short-circuit a real QS expansion of the same position.
    # (fifty/repetition draws excluded: they don't transpose; variant
    # terminals excluded: their ply-relative mate-range values must
    # never be TT-stored)
    leaf_store = (
        enter & is_leaf & ~parent_illegal & ~use_tt & ~fifty & ~repet
        & ~vterm & (gen_noisy == 0)
    )
    store_mark = leaf_store
    store_val = jnp.where(leaf_store, leaf_val, 0)

    # order the stored TT move first (classic biggest ordering win); not
    # in QS, where the swap could pull a quiet move into the noisy prefix
    if tt_move is not None:
        tm_at = jnp.argmax(gen_moves == tt_move)
        tm_present = (tt_move >= 0) & (gen_moves[tm_at] == tt_move) & ~qs_like
        m0 = gen_moves[0]
        # dynamic-index swap routed through _row_set so the
        # SELECT_UPDATES experiment covers this scatter too (the index-0
        # write below is static — not a dynamic-update-slice)
        gen_moves = _row_set(gen_moves, tm_at, m0, tm_present)
        gen_moves = gen_moves.at[0].set(
            jnp.where(tm_present, tt_move, gen_moves[0])
        )

    # stand-pat: in QS the node may decline every capture and keep the
    # static eval, so it floors both best and alpha (futile nodes reuse
    # the same floor; their static sits below alpha by construction, so
    # only `best` actually moves — the fail-soft return value)
    # null-move eligibility (Stockfish search.cpp nullMove conditions,
    # minus the zugzwang verification search): interior node, depth to
    # spare, not in check, not already inside a null subtree, static
    # eval >= beta, non-mate window, and side to move still has a piece
    # (pawn/king-only positions are where the null observation fails)
    if _PRUNING and variant != "antichess":
        # antichess excluded: captures are FORCED there, so passing is
        # not "at least as bad as the best move" — the null observation
        # that justifies the cutoff simply doesn't hold
        us_base = us * 6
        nonpawn = jnp.any(
            (b.board >= us_base + 2) & (b.board <= us_base + 5)
        )
        nmp_ok = (
            ~in_qs
            & (depth_left >= 3)
            & ~we_are_checked
            & ~parent_null
            & (ply0 > 0)
            & (static_val >= entry_beta)
            & (entry_beta < MATE - 1000)
            & (entry_beta > -(MATE - 1000))
            & nonpawn
        )
        null_v = jnp.where(nmp_ok, 1, 0)
    else:
        null_v = jnp.int32(0)

    # the entered node's nt row, composed once: fields in _FM_EXPAND take
    # their expansion value under `expand`, _FM_ENTER fields under
    # `enter`, everything else keeps its old value — so one full-row
    # write under `enter` reproduces the per-field write masks exactly
    nv = jnp.stack([
        jnp.where(qs_like, gen_noisy, gen_count),            # NT_COUNT
        jnp.int32(0),                                        # NT_MIDX
        jnp.int32(0),                                        # NT_SEARCHED
        jnp.where(qs_like, jnp.maximum(entry_alpha, leaf_val),
                  entry_alpha),                              # NT_ALPHA
        entry_alpha,                                         # NT_ALPHA0
        entry_beta,                                          # NT_BETA
        jnp.where(qs_like, leaf_val, -INF),                  # NT_BEST
        jnp.int32(-1),                                       # NT_BMOVE
        null_v,                                              # NT_NULL
        jnp.int32(0),                                        # NT_LASTRED
        jnp.int32(0),                                        # NT_PVLEN
        ntr0[NT_DL],                                         # NT_DL
        we_are_checked.astype(jnp.int32),                    # NT_INCHECK
        ntr0[NT_K0],                                         # NT_K0
        ntr0[NT_K1],                                         # NT_K1
        jnp.int32(0),
    ])
    sel = (jnp.asarray(_FM_EXPAND) & expand) | (jnp.asarray(_FM_ENTER) & enter)
    ntE = jnp.where(sel, nv, ntr0)
    nt_new = _row_set(s.nt, ply0, ntE, enter)

    btE = btr0.at[BT_PH1].set(h1i).at[BT_PH2].set(h2i)
    bt_new = _row_set(s.bt, ply0, btE, enter)
    moves_new = _row_set(
        s.moves, jnp.minimum(ply0, s.moves.shape[0] - 1), gen_moves, expand
    )

    ret = jnp.where(
        enter & to_return,
        jnp.where(
            parent_illegal,
            ILLEGAL,
            jnp.where(use_tt, tt_score, leaf_val) if tt_score is not None
            else leaf_val,
        ),
        lane[LN_RET],
    )
    # ret_depth: 0 for static leaves, -1 for TT-sourced values (already in
    # the table — don't re-store them)
    ret_depth = jnp.where(
        enter & to_return, jnp.where(use_tt, -1, 0), lane[LN_RETD]
    )
    nodes = nodes + jnp.where(enter & ~parent_illegal, 1, 0)
    mode = jnp.where(
        enter, jnp.where(to_return, MODE_RETURN, MODE_TRYMOVE), mode0
    )

    # --------------------------------------------------------- phase RETURN
    # the node at ply0 finished with value `ret` (from its stm's view);
    # it folds into parent0
    ret_m = mode == MODE_RETURN
    at_root = ply0 == 0
    was_illegal = ret == ILLEGAL
    v = -ret
    tried = moves_p_row[jnp.maximum(ntp0[NT_MIDX] - 1, 0)]
    # the child that just returned was the parent's null move: score it
    # against beta only — a fail-high ends the parent (unproven-mate
    # guard: never cut on a mate-range null score), a fail-low is simply
    # discarded. Either way it folds into nothing: no best_move, no pv,
    # no searched credit.
    is_null_ret = ret_m & ~at_root & (ntp0[NT_NULL] == 2)
    null_cut = (
        is_null_ret & ~was_illegal & (v >= ntp0[NT_BETA]) & (v < MATE - 1000)
    )
    # LMR re-search: the last child was depth-reduced and its reduced
    # score beat alpha — discard the fold and re-push it at full depth
    need_rs = (
        ret_m & ~at_root & ~was_illegal & ~is_null_ret
        & (ntp0[NT_LASTRED] > 0) & (v > ntp0[NT_ALPHA])
    )
    better = (
        ret_m & (~at_root) & (~was_illegal) & (v > ntp0[NT_BEST])
        & ~is_null_ret & ~need_rs
    )
    fold = ret_m & ~at_root

    best_p = jnp.where(better | null_cut, v, ntp0[NT_BEST])
    bmove_p = jnp.where(better, tried, ntp0[NT_BMOVE])
    alpha_p = jnp.where(
        fold, jnp.maximum(ntp0[NT_ALPHA], best_p), ntp0[NT_ALPHA]
    )
    searched_p = ntp0[NT_SEARCHED] + jnp.where(
        fold & ~was_illegal & ~is_null_ret & ~need_rs, 1, 0
    )
    null_p = jnp.where(is_null_ret, 0, ntp0[NT_NULL])
    # pv[parent] = tried + pv[ply]; pv_len[ply] is the post-ENTER value
    # (a leaf that entered this same step zeroed it)
    pvlen_child = ntE[NT_PVLEN]
    pvlen_p = jnp.where(
        better, jnp.minimum(pvlen_child + 1, s.pv.shape[-1]), ntp0[NT_PVLEN]
    )
    ntP = ntp0
    for f_ix, f_val in ((NT_BEST, best_p), (NT_BMOVE, bmove_p),
                        (NT_ALPHA, alpha_p), (NT_SEARCHED, searched_p),
                        (NT_NULL, null_p), (NT_PVLEN, pvlen_p)):
        ntP = ntP.at[f_ix].set(f_val)
    nt_new = _row_set(nt_new, parent0, ntP, fold)

    new_pv_row = jnp.concatenate([tried[None], s.pv[ply0][:-1]])
    pv_new = _row_set(s.pv, parent0, new_pv_row, better)
    research = jnp.where(ret_m, need_rs, lane[LN_RESEARCH] != 0)
    # root: record and park (ret, not best[0] — ret carries the
    # mate/stalemate value when the root had no legal moves)
    root_score = jnp.where(ret_m & at_root, ret, lane[LN_RSCORE])
    root_move = jnp.where(ret_m & at_root, ntp0[NT_BMOVE], lane[LN_RMOVE])
    ply1 = jnp.where(fold, parent0, ply0)
    mode = jnp.where(
        ret_m, jnp.where(at_root, MODE_DONE, MODE_TRYMOVE), mode
    )

    # -------------------------------------------------------- phase TRYMOVE
    # note: the node budget is enforced in ENTER (children degrade to leaf
    # evals), not here — finishing a node early with searched==0 would
    # return -INF garbage to the parent
    try_m = mode == MODE_TRYMOVE
    # the row TRYMOVE acts on: the freshly-expanded node (ENTER cascade)
    # or the freshly-folded parent (RETURN cascade) — both in registers
    came_from_enter = enter & expand
    nt1 = jnp.where(came_from_enter, ntE, ntP)
    moves_row1 = jnp.where(came_from_enter, gen_moves, moves_p_row)
    bt1 = jnp.where(came_from_enter, btE, btp0)
    parent_b = _board_from_row(bt1)
    exhausted = nt1[NT_MIDX] >= nt1[NT_COUNT]
    cutoff = nt1[NT_ALPHA] >= nt1[NT_BETA]
    # a pending null move is tried BEFORE the first real move; an LMR
    # re-push (research, set by RETURN this same step) re-enters the
    # previous move at full depth and overrides finish — exhausted may
    # already be true when the reduced move was the last one
    re_push = try_m & research
    do_null = try_m & ~re_push & (nt1[NT_NULL] == 1) & ~cutoff
    finish = (exhausted | cutoff) & ~do_null & ~re_push
    advance = try_m & ~finish
    normal_adv = advance & ~re_push & ~do_null
    dl_node = nt1[NT_DL]

    # killer/history credit on fail-high: the quiet move that raised
    # alpha >= beta becomes killer slot 0 for this ply and earns a
    # depth²-weighted history bump (captures already order by MVV-LVA;
    # en-passant reads as quiet here, which only costs ordering)
    cause = nt1[NT_BMOVE]
    c_quiet = (cause >= 0) & _is_quiet(cause, bt1[BT_BOARD:BT_BOARD + 64])
    k_upd = try_m & cutoff & c_quiet
    k_new = k_upd & (cause != nt1[NT_K0])
    k0_v = jnp.where(k_new, cause, nt1[NT_K0])
    k1_v = jnp.where(k_new, nt1[NT_K0], nt1[NT_K1])
    h_idx = jnp.clip(cause, 0) & 4095
    dl = jnp.maximum(dl_node, 0)
    h_w = jnp.minimum(dl * dl + 1, 1024)
    hist_new = _row_set(
        s.hist, h_idx, jnp.minimum(s.hist[h_idx] + h_w, 1 << 20), k_upd
    )

    # finished node value: best, or mate/stalemate when no legal child.
    # QS nodes only tried captures — no legal capture is NOT mate; their
    # stand-pat floor in `best` already covers the quiet alternatives.
    node_in_qs = dl_node <= 0
    # best == -INF guards the count==0 + null-cutoff corner: a null-move
    # fail-high set best without any legal child being searched, and the
    # node must return that score, not a phantom mate/stalemate
    no_legal = (nt1[NT_SEARCHED] == 0) & ~node_in_qs & (nt1[NT_BEST] == -INF)
    if variant == "antichess":
        # losing chess: the side with no moves left (stalemated or out of
        # pieces) WINS (host: AntichessPosition._variant_outcome)
        mate_val = MATE - ply1
    else:
        mate_val = jnp.where(nt1[NT_INCHECK] != 0, -(MATE - ply1), DRAW)
    fin_val = jnp.where(no_legal & exhausted, mate_val, nt1[NT_BEST])

    m_ix = jnp.where(
        re_push,
        jnp.maximum(nt1[NT_MIDX] - 1, 0),
        jnp.minimum(nt1[NT_MIDX], moves_row1.shape[0] - 1),
    )
    move = moves_row1[m_ix]
    child = make_move(parent_b, jnp.maximum(move, 0), variant)
    # late-move reduction: late, quiet, unchecked moves of a deep-enough
    # node search 1 ply shallower (2 from move 8); RETURN re-pushes at
    # full depth when the reduced score beats alpha
    if _PRUNING:
        m_quiet = _is_quiet(jnp.maximum(move, 0), bt1[BT_BOARD:BT_BOARD + 64])
        lmr_ok = (
            (dl_node >= 3) & (nt1[NT_MIDX] >= 3) & m_quiet
            & (nt1[NT_INCHECK] == 0) & ~node_in_qs
        )
        red = jnp.where(
            lmr_ok, jnp.where(nt1[NT_MIDX] >= 8, 2, 1), 0
        )
        red = jnp.where(re_push | do_null, 0, red)
        # the null child: same position, opponent to move, no ep, and a
        # reset halfmove clock — which deliberately breaks the reversible
        # repetition chain across the null (Stockfish's pliesFromNull)
        child = Board(
            board=jnp.where(do_null, parent_b.board, child.board),
            stm=jnp.where(do_null, 1 - parent_b.stm, child.stm),
            ep=jnp.where(do_null, -1, child.ep),
            castling=jnp.where(do_null, parent_b.castling, child.castling),
            halfmove=jnp.where(do_null, 0, child.halfmove),
            extra=jnp.where(do_null, parent_b.extra, child.extra),
        )
        null_r = NULL_R + jnp.where(dl_node >= 7, 1, 0)
        child_dl = jnp.maximum(
            jnp.where(do_null, dl_node - 1 - null_r, dl_node - 1 - red), 0
        )
    else:
        red = jnp.int32(0)
        child_dl = jnp.maximum(dl_node - 1, 0)
    nply = jnp.minimum(ply1 + 1, P1 - 1)

    # TRYMOVE's own-row write (midx/null/lastred/killers), then the
    # child-push writes: depth_left of the pushed row (a single-field
    # 2-D one-hot — the row's other fields belong to the OLD node there
    # and are rewritten when the child expands), its board row, and its
    # incremental accumulator
    nt1w = nt1
    for f_ix, f_val in (
        (NT_MIDX, jnp.where(normal_adv, nt1[NT_MIDX] + 1, nt1[NT_MIDX])),
        (NT_NULL, jnp.where(do_null, 2, nt1[NT_NULL])),
        (NT_LASTRED, jnp.where(advance, red, nt1[NT_LASTRED])),
        (NT_K0, k0_v), (NT_K1, k1_v),
    ):
        nt1w = nt1w.at[f_ix].set(f_val)
    nt_new = _row_set(nt_new, ply1, nt1w, try_m)
    nt_new = _field_set(nt_new, nply, NT_DL, child_dl, advance)
    research = jnp.where(try_m, jnp.bool_(False), research)

    bt_new = _row_set(bt_new, nply, _row_from_board(child), advance)
    if nnue.is_board768(params) and variant != "atomic":
        codes, sqs, signs = move_piece_changes(
            parent_b, jnp.maximum(move, 0), variant
        )
        if _PRUNING:
            # a null move changes no pieces: zeroed slots make the
            # incremental update an exact no-op (code 0 → no-op)
            codes = jnp.where(do_null, 0, codes)
            signs = jnp.where(do_null, 0, signs)
        child_acc = nnue.apply_acc_updates_768(params, s.acc[ply1], codes, sqs, signs)
        acc_new = _row_set(s.acc, nply, child_acc, advance)
    else:
        acc_new = s.acc

    ret = jnp.where(try_m & finish, fin_val, ret)
    ret_depth = jnp.where(try_m & finish, dl_node, ret_depth)
    mode = jnp.where(
        try_m, jnp.where(finish, MODE_RETURN, MODE_ENTER), mode
    )
    ply_f = jnp.where(advance, nply, ply1)

    lane_new = jnp.stack([
        ply_f, mode, ret, ret_depth,
        store_mark.astype(jnp.int32), store_val,
        nodes, lane[LN_DLIM], lane[LN_BUDGET],
        root_score, root_move, lane[LN_RALPHA], lane[LN_RBETA],
        research.astype(jnp.int32),
        lane[LN_JITTER], lane[LN_GROUP],
    ])

    return SearchState(
        bt=bt_new, nt=nt_new, lane=lane_new,
        hist_hash=s.hist_hash, hist_halfmove=s.hist_halfmove,
        moves=moves_new, hist=hist_new, pv=pv_new, acc=acc_new,
    )


def make_search_step(params: nnue.NnueParams, variant: str = "standard"):
    lane_axes = SearchState(
        *[0 for _ in SearchState._fields]
    )
    return jax.vmap(
        lambda s: _step_lane(params, s, variant=variant), in_axes=(lane_axes,)
    )


def make_search_step_tt(params: nnue.NnueParams, variant: str = "standard"):
    lane_axes = SearchState(
        *[0 for _ in SearchState._fields]
    )
    return jax.vmap(
        lambda s, h, sc, m: _step_lane(params, s, h, sc, m, variant=variant),
        in_axes=(lane_axes, 0, 0, 0),
    )


def _gather_ply(arr: jnp.ndarray, ply: jnp.ndarray) -> jnp.ndarray:
    """arr (B, P, ...) → per-lane row at each lane's ply, shape (B, ...)."""
    return jax.vmap(lambda a, p: a[p])(arr, ply)


# ------------------------------------------------- segmented (resumable) run
#
# A deep search can take hundreds of thousands of lockstep steps. Running
# them as ONE device program is fragile (a multi-minute XLA program can
# trip device/runtime watchdogs, and cannot be interrupted when the chunk
# deadline passes — reference fishnet races `go_multiple` against the
# deadline and kills the engine process, src/main.rs:307-338). The
# TPU-native equivalent of that kill switch: run the while_loop in bounded
# segments and let the HOST decide between segments whether to continue,
# stop on deadline, or abandon. State lives on device throughout; the only
# per-segment host traffic is one scalar (steps executed).


def _run_segment(params: nnue.NnueParams, state: SearchState,
                 ttab, segment_steps: int, variant: str = "standard",
                 deep_tt: bool = False, prefer_deep: bool = False,
                 tt_gen=0):
    """Advance all lanes ≤ segment_steps. ttab: shared tt.TTable or None.
    deep_tt (STATIC): accept deeper LOWER/UPPER TT entries as cutoffs
    (move-job strength mode — see ops/tt.py probe).
    prefer_deep (STATIC) + tt_gen (traced): helper-lane dispatches store
    under the depth-preferred generation-aware replacement policy
    (ops/tt.py store) so helper writes don't evict primary-path entries.

    The TT lives OUTSIDE the vmap: each iteration first stores every lane
    parked in RETURN (its finished node's value), then probes every lane
    in ENTER against the just-updated table, and feeds the probe results
    into the vmapped step. Stores from one lane are visible to every
    other lane in the same iteration — the cross-lane sharing that makes
    one HBM table worth more than B private ones."""
    gen_i = jnp.asarray(tt_gen, jnp.int32)

    if ttab is None:
        step = make_search_step(params, variant)

        def body(carry):
            s, t, i = carry
            return step(s), t, i + 1
    else:
        step = make_search_step_tt(params, variant)

        def body(carry):
            s, t, i = carry
            lane = s.lane
            ply = lane[:, LN_PLY]
            btrow = _gather_ply(s.bt, ply)  # one row gather serves all
            h1, h2 = jax.vmap(
                lambda r: _tt_mod.hash_board(
                    r[BT_BOARD:BT_BOARD + 64], r[BT_STM], r[BT_EP],
                    r[BT_CAST:BT_CAST + 4], r[BT_EXTRA:BT_EXTRA + 12],
                    variant,
                )
            )(btrow)

            # ---- store lanes whose INTERIOR node just finished. (Leaf
            # returns fold into the parent within one step — the ENTER→
            # RETURN cascade — so a lane parked in RETURN here always
            # carries ret_depth >= 1, except TT-sourced values at -1.)
            ret_m = lane[:, LN_MODE] == MODE_RETURN
            store_mask = (
                ret_m
                & (lane[:, LN_RET] != ILLEGAL)
                & (lane[:, LN_RETD] >= 1)  # -1: value came from the TT
                # after budget exhaustion subtrees are degraded — their
                # values are shallow despite the nominal depth label
                & (lane[:, LN_NODES] < lane[:, LN_BUDGET])
            )
            ntrow = _gather_ply(s.nt, ply)
            flag = jnp.where(
                lane[:, LN_RET] >= ntrow[:, NT_BETA],
                _tt_mod.FLAG_LOWER,
                jnp.where(
                    lane[:, LN_RET] <= ntrow[:, NT_ALPHA0],
                    _tt_mod.FLAG_UPPER, _tt_mod.FLAG_EXACT,
                ),
            )
            t = _tt_mod.store(
                t, h1, h2, lane[:, LN_RET],
                jnp.maximum(lane[:, LN_RETD], 0), flag, ntrow[:, NT_BMOVE],
                store_mask, prefer_deep=prefer_deep, gen=gen_i,
            )

            # ---- probe lanes about to enter a node (mode == ENTER);
            # the probe window must match the window ENTER will give the
            # node — incl. the zero-width null window for null children,
            # or stored LOWER bounds inside [1-beta_p, -alpha_p) would
            # miss valid null-search fail-high cutoffs
            enter = lane[:, LN_MODE] == MODE_ENTER
            parent = jnp.maximum(ply - 1, 0)
            ntprow = _gather_ply(s.nt, parent)
            pnull = (ply > 0) & (ntprow[:, NT_NULL] == 2)
            a_w = jnp.where(
                ply == 0, lane[:, LN_RALPHA], -ntprow[:, NT_BETA]
            )
            b_w = jnp.where(
                ply == 0, lane[:, LN_RBETA],
                jnp.where(
                    pnull, 1 - ntprow[:, NT_BETA], -ntprow[:, NT_ALPHA]
                ),
            )
            usable, score, _mv, order_mv = _tt_mod.probe(
                t, h1, h2, ntrow[:, NT_DL], a_w, b_w,
                deep_bounds=deep_tt,
            )
            usable &= enter
            order_mv = jnp.where(enter, order_mv, -1)
            s = step(s, usable, score, order_mv)

            # ---- store leaves the step just evaluated (depth-0 EXACT).
            # Their hash is the PRE-step hash: a marking lane was in ENTER
            # at this ply, exactly the position h1/h2 were computed for.
            sval = s.lane[:, LN_SVAL]
            t = _tt_mod.store(
                t, h1, h2, sval, jnp.zeros_like(sval),
                jnp.full_like(sval, _tt_mod.FLAG_EXACT),
                jnp.full_like(sval, -1), s.lane[:, LN_SMARK] != 0,
                prefer_deep=prefer_deep, gen=gen_i,
            )
            return s, t, i + 1

    def cond(carry):
        s, t, i = carry
        return (i < segment_steps) & jnp.any(s.lane[:, LN_MODE] != MODE_DONE)

    state, ttab, n = jax.lax.while_loop(
        cond, body, (state, ttab, jnp.int32(0))
    )
    lane = state.lane
    summary = jnp.concatenate([
        jnp.stack([
            (lane[:, LN_MODE] == MODE_DONE).astype(jnp.int32),
            lane[:, LN_NODES],
            lane[:, LN_RSCORE],
            lane[:, LN_RMOVE],
        ], axis=1),
        jnp.full((1, SUM_W), n, jnp.int32),
    ], axis=0)
    return state, ttab, n, summary


# segment_steps is TRACED (an int32 operand of the while cond), not
# static: the FISHNET_TPU_SEGMENT=auto controller retunes the length
# between segments with zero recompiles. state and ttab are DONATED —
# chained segments alias the multi-MB tables in place instead of
# copying them, so a caller must treat the arguments it passed as
# consumed and continue from the returned state/ttab only.
_run_segment_jit = _aot_registry.wrap(
    "run_segment",
    jax.jit(
        _run_segment,
        static_argnames=("variant", "deep_tt", "prefer_deep"),
        donate_argnums=(1, 2),
    ),
    _run_segment,
    static_names=("variant", "deep_tt", "prefer_deep"),
)
# the big tables are OUTPUTS of init_state; its only device-state-shaped
# inputs are the history rows, donated so refill splices don't copy them
_init_state_jit = _aot_registry.wrap(
    "init_state",
    jax.jit(
        init_state, static_argnames=("max_ply", "variant"),
        donate_argnames=("hist_hash", "hist_halfmove"),
    ),
    init_state,
    static_names=("max_ply", "variant"),
)


def extract_results(state: SearchState, steps) -> dict:
    return {
        "score": state.lane[:, LN_RSCORE],
        "move": state.lane[:, LN_RMOVE],
        "pv": state.pv[:, 0],
        "pv_len": state.nt[:, 0, NT_PVLEN],
        "nodes": state.lane[:, LN_NODES],
        "done": state.lane[:, LN_MODE] == MODE_DONE,
        "steps": steps,
    }


# ------------------------------------------------- continuous lane refill
#
# A lockstep step costs the same however many lanes are live, so a DONE
# lane is pure waste until the batch narrows or the chunk drains — the
# static-batching tax. The iteration-level scheduling fix from LLM
# serving (continuous batching: when one sequence finishes, splice the
# next request into its slot without relaunching the batch) maps
# one-to-one onto lanes: at a segment boundary the host reinitializes
# exactly the DONE lanes it wants to reuse — board rows, NNUE
# accumulators, lane scalars, move/pv/history tables — while live
# lanes' state is untouched bit-for-bit, and the SAME _run_segment_jit
# program keeps running (refill changes array values, never shapes, so
# there is no recompile). Per-lane TT generation tags stay host-side:
# the caller passes a (B,) tt_gen array into _run_segment_jit, which
# ops/tt.py broadcasts elementwise, so a refilled lane's stores carry
# its own fresh generation without any tt.py change.


def _merge_lanes(state: SearchState, fresh: SearchState,
                 mask: jnp.ndarray) -> SearchState:
    """Per-lane select between two same-shape states: lanes where mask
    (B,) is True take `fresh`, the rest keep `state` — one fused masked
    select per state field, no scatter."""
    def pick(old, new):
        m = mask.reshape((old.shape[0],) + (1,) * (old.ndim - 1))
        return jnp.where(m, new, old)

    return jax.tree.map(pick, state, fresh)


# both inputs are donated: the running state's tables are overwritten in
# place where the mask selects, and the fresh (refill-sized) state is
# consumed by the splice — a refill boundary allocates nothing big
_merge_lanes_jit = _aot_registry.wrap(
    "merge_lanes",
    jax.jit(_merge_lanes, donate_argnums=(0, 1)),
    _merge_lanes,
)

# FISHNET_TPU_SANITIZE: poison donated inputs after dispatch so a
# use-after-donate raises on CPU too (XLA:CPU only warns and leaves the
# handles readable). guard_donation returns each jit UNCHANGED when the
# flag is off — the default path pays nothing. docs/sanitizer.md.
_run_segment_jit = _sanitize.guard_donation(
    "ops/search.py::_run_segment_jit", _run_segment_jit, argnums=(1, 2))
_init_state_jit = _sanitize.guard_donation(
    "ops/search.py::_init_state_jit", _init_state_jit,
    argnames=("hist_hash", "hist_halfmove"))
_merge_lanes_jit = _sanitize.guard_donation(
    "ops/search.py::_merge_lanes_jit", _merge_lanes_jit, argnums=(0, 1))


def _refill_fresh(params: nnue.NnueParams, state: SearchState,
                  new_roots: Board, lane_idx, depth, node_budget, *,
                  variant: str = "standard", hist_hash=None,
                  hist_halfmove=None, root_alpha=None, root_beta=None,
                  order_jitter=None, group=None):
    """Build the full-width fresh state and (B,) splice mask for a refill.

    Shared by the single-device `refill_lanes` and the sharded
    parallel.mesh.refill_lanes_sharded — the fresh state and mask are
    mesh-agnostic (the merge is what differs: plain jit vs shard_map).
    Returns (fresh, mask), or (None, None) when lane_idx is empty."""
    B = state.lane.shape[0]
    max_ply = state.bt.shape[1] - 1
    lane_idx = np.asarray(lane_idx, np.int64).reshape(-1)
    n = int(lane_idx.shape[0])
    if n == 0:
        return None, None
    take = np.zeros(B, np.int64)
    take[lane_idx] = np.arange(n)
    mask = np.zeros(B, bool)
    mask[lane_idx] = True
    tk = jnp.asarray(take)

    def expand(x, fill, dtype, tail=()):
        if x is None:
            x = np.full((n,) + tail, fill, dtype)
        elif isinstance(x, jax.Array):
            # already device-resident (e.g. carried from a previous
            # segment's outputs): gather on device — np.asarray here
            # would block the host and round-trip the rows through it
            return jnp.take(x, tk, axis=0)
        return jnp.asarray(np.asarray(x))[tk]

    roots_full = jax.tree.map(lambda a: jnp.asarray(a)[tk], new_roots)
    fresh = _init_state_jit(
        params, roots_full,
        expand(depth, 0, np.int32), expand(node_budget, 0, np.int32),
        max_ply, variant,
        hist_hash=expand(hist_hash, 0, np.uint32, (MAX_HIST, 2)),
        hist_halfmove=expand(
            hist_halfmove, HIST_HM_SENTINEL, np.int32, (MAX_HIST,)
        ),
        root_alpha=expand(root_alpha, -INF, np.int32),
        root_beta=expand(root_beta, INF, np.int32),
        order_jitter=expand(order_jitter, 0, np.int32),
        group=expand(group, 0, np.int32),
    )
    return fresh, mask


def refill_lanes(params: nnue.NnueParams, state: SearchState, new_roots: Board,
                 lane_idx, depth, node_budget, *, variant: str = "standard",
                 hist_hash=None, hist_halfmove=None,
                 root_alpha=None, root_beta=None,
                 order_jitter=None, group=None) -> SearchState:
    """Splice fresh root positions into selected lanes of a running state.

    new_roots: batched Board with n rows; lane_idx: host sequence of n
    distinct lane indices to reinitialize; depth/node_budget (n,) and the
    optional per-lane arrays follow init_state semantics (None defaults
    are expanded to the init_state defaults so every call shares ONE
    _init_state_jit trace with the initial fill).

    Lanes not in lane_idx keep their exact pre-call state — including
    mid-segment stack contents, accumulators and history — so live
    searches are unaffected. The caller is responsible for only
    refilling DONE lanes and for bumping those lanes' TT generation
    tags before the next _run_segment_jit dispatch. For a mesh-sharded
    state use parallel.mesh.refill_lanes_sharded (same contract, merge
    routed through the shard_map'd splice)."""
    fresh, mask = _refill_fresh(
        params, state, new_roots, lane_idx, depth, node_budget,
        variant=variant, hist_hash=hist_hash, hist_halfmove=hist_halfmove,
        root_alpha=root_alpha, root_beta=root_beta,
        order_jitter=order_jitter, group=group,
    )
    if fresh is None:
        return state
    return _merge_lanes_jit(state, fresh, jnp.asarray(mask))


def search_stream(
    params: nnue.NnueParams,
    roots: Board,
    depth,
    node_budget,
    max_ply: int,
    width: int,
    segment_steps: int | None = None,
    max_steps: int = 50_000_000,
    deadline: float | None = None,
    tt=None,
    mesh=None,
    variant: str = "standard",
    hist=None,
    prefer_deep_store: bool = False,
    tt_gen_start: int = 1,
    pipeline: bool | None = None,
    sync_stats=None,
):
    """Stream N root positions through a fixed `width`-lane program.

    The occupancy-driven counterpart of `search_batch_resumable`: instead
    of narrowing as lanes finish, the host refills DONE lanes with queued
    positions at every segment boundary, keeping the compiled step at
    full width until the queue drains. The engine-level LaneScheduler
    adds helper lanes, aspiration windows and per-position deadlines on
    top of the same primitives.

    mesh: optional jax.sharding.Mesh — lanes shard over its devices
    (width must divide evenly) and segment/refill/merge route through
    the shard_map'd callables in parallel.mesh: each device advances and
    resplices ITS lanes locally, the host sees one stacked
    (ndev, width/ndev + 1, 4) boundary summary per dispatch, and the
    sharded jits donate state+TT exactly like the single-device path.
    With a mesh, tt must carry a leading (ndev,) shard dim
    (parallel.mesh.make_sharded_table) or be None, and each occupancy
    row gains shard_live / shard_refilled / shard_steps lists (one entry
    per shard).

    pipeline (default FISHNET_TPU_PIPELINE): asynchronous segment
    boundaries — the host fetches ONE packed summary per boundary
    instead of the full result set, pulls PV rows only for lanes that
    actually finished, and, when the refill queue is empty (no boundary
    decision pending), dispatches the next segment speculatively before
    blocking on the current one, so host bookkeeping overlaps device
    compute. False restores the round-7 synchronous loop; results are
    bit-identical in both modes. sync_stats: optional
    utils.syncstats.SyncStats to account transfers into.

    segment_steps None reads FISHNET_TPU_SEGMENT; "auto" runs the
    measured-feedback SegmentController within the registry bounds.

    Returns per-position (N,) results keyed as extract_results, plus:
      occupancy: list of per-segment dicts {segment, steps, live, idle,
                 refilled, queue, transfers, elements, host_ms,
                 device_ms} — live counts lanes still searching at the
                 boundary, refilled the lanes spliced this boundary,
                 idle = width - live - refilled; the last four come from
                 utils.syncstats (transfer count and the host/device
                 wall-clock split of the boundary interval).
      refills:   total refill events (lanes spliced) across the run.
    Positions not finished by deadline/max_steps report done=False.
    """
    import time as _time

    from ..utils.syncstats import SegmentController, SyncStats

    if pipeline is None:
        pipeline = settings.get_bool("FISHNET_TPU_PIPELINE")
    stats = sync_stats if sync_stats is not None else SyncStats()
    ctrl = None
    if segment_steps is None:
        segment_steps = settings.get_segment()
        if segment_steps is None:  # FISHNET_TPU_SEGMENT=auto
            ctrl = SegmentController(
                settings.get_int("FISHNET_TPU_SEGMENT_MIN"),
                settings.get_int("FISHNET_TPU_SEGMENT_MAX"),
            )
            segment_steps = ctrl.steps
    N = int(roots.stm.shape[0])
    P = max_ply
    depth = np.broadcast_to(np.asarray(depth, np.int32), (N,)).copy()
    node_budget = np.broadcast_to(
        np.asarray(node_budget, np.int32), (N,)
    ).copy()
    hist_hash, hist_halfmove = hist if hist is not None else (None, None)
    if hist_hash is not None:
        hist_hash = np.asarray(hist_hash)
        hist_halfmove = np.asarray(hist_halfmove)

    def gather_roots(pos_idx):
        ix = jnp.asarray(np.asarray(pos_idx, np.int64))
        return jax.tree.map(lambda a: jnp.asarray(a)[ix], roots)

    def hist_rows(pos_idx):
        if hist_hash is None:
            return None, None
        return hist_hash[pos_idx], hist_halfmove[pos_idx]

    # initial admission: positions 0..k-1 into lanes 0..k-1; surplus
    # lanes start with budget 0 so they park in DONE within two steps
    lane_pos = np.full(width, -1, np.int64)
    k = min(width, N)
    lane_pos[:k] = np.arange(k)
    queue = list(range(k, N))
    take0 = np.where(lane_pos >= 0, lane_pos, 0)
    assigned0 = lane_pos >= 0
    hh0, hm0 = hist_rows(take0)
    state = _init_state_jit(
        params, gather_roots(take0),
        jnp.asarray(np.where(assigned0, depth[take0], 0).astype(np.int32)),
        jnp.asarray(
            np.where(assigned0, node_budget[take0], 0).astype(np.int32)
        ),
        max_ply, variant,
        hist_hash=jnp.asarray(
            hh0 if hh0 is not None
            else np.zeros((width, MAX_HIST, 2), np.uint32)
        ),
        hist_halfmove=jnp.asarray(
            hm0 if hm0 is not None
            else np.full((width, MAX_HIST), HIST_HM_SENTINEL, np.int32)
        ),
        root_alpha=jnp.full((width,), -INF, jnp.int32),
        root_beta=jnp.full((width,), INF, jnp.int32),
        order_jitter=jnp.zeros((width,), jnp.int32),
        group=jnp.zeros((width,), jnp.int32),
    )
    ndev = local = 1
    multiproc = False
    if mesh is not None:
        from ..parallel import distributed as _dist
        from ..parallel.mesh import (
            refill_lanes_sharded,
            run_segment_sharded,
            shard_batch,
        )

        ndev = mesh.devices.size
        if width % ndev != 0:
            raise ValueError(
                f"stream width {width} must divide over {ndev} devices")
        local = width // ndev
        multiproc = _dist.spans_processes(mesh)
        if multiproc:
            # multi-host stream: every participating process drives this
            # same loop with identical inputs (SPMD discipline); only
            # the pipelined loop's host fetches are addressable-shard
            # aware (parallel/distributed.py), the synchronous loop
            # materializes full sharded arrays and cannot be
            if not pipeline:
                raise ValueError(
                    "a multi-host mesh requires the pipelined stream "
                    "loop (FISHNET_TPU_PIPELINE=1)")
            params = _dist.replicate_tree(mesh, params)
        # place the fresh state sharded BEFORE the first dispatch: the
        # sharded segment donates its operands, and donation only takes
        # when the input already carries the program's sharding
        state = shard_batch(mesh, state)
    gen = np.zeros(width, np.int32)
    next_gen = int(tt_gen_start)
    gen[assigned0] = np.arange(next_gen, next_gen + k, dtype=np.int32)
    next_gen += k

    out = {
        "score": np.zeros(N, np.int32),
        "move": np.full(N, -1, np.int32),
        "pv": np.full((N, P), -1, np.int32),
        "pv_len": np.zeros(N, np.int32),
        "nodes": np.zeros(N, np.int32),
    }
    done_out = np.zeros(N, bool)
    occupancy: list[dict] = []
    refills_total = 0
    total = 0
    seg_i = 0

    if mesh is not None:
        def dispatch(st, table, seg_n):
            return run_segment_sharded(
                mesh, params, st, table, seg_n, variant=variant,
                prefer_deep=prefer_deep_store, tt_gen=jnp.asarray(gen),
            )
    else:
        def dispatch(st, table, seg_n):
            return _run_segment_jit(
                params, st, table, seg_n, variant, False,
                prefer_deep_store, jnp.asarray(gen),
            )

    def canon_summ(raw):
        """Boundary summary → ((width, 4) lane rows, step count,
        per-shard step list). Single-device summaries are (width+1, 4);
        sharded ones come back stacked (ndev, local+1, 4) and the step
        count is the max over shards (devices park independently)."""
        if mesh is None:
            return raw[:width], int(raw[width, SUM_DONE]), None
        lanes = raw[:, :local, :].reshape(width, SUM_W)
        shard_steps = [int(x) for x in raw[:, local, SUM_DONE]]
        return lanes, max(shard_steps), shard_steps

    def do_refill(st, free, n_ref):
        nonlocal next_gen, refills_total
        take_pos = np.asarray(queue[:n_ref], np.int64)
        del queue[:n_ref]
        sel = free[:n_ref]
        lane_pos[sel] = take_pos
        gen[sel] = (
            np.arange(next_gen, next_gen + n_ref) & 0x3FFFFFFF
        ).astype(np.int32)
        next_gen += n_ref
        hh, hm = hist_rows(take_pos)
        refills_total += n_ref
        if mesh is not None:
            return refill_lanes_sharded(
                mesh, params, st, gather_roots(take_pos), sel,
                depth[take_pos], node_budget[take_pos], variant=variant,
                hist_hash=hh, hist_halfmove=hm,
            )
        return refill_lanes(
            params, st, gather_roots(take_pos), sel,
            depth[take_pos], node_budget[take_pos], variant=variant,
            hist_hash=hh, hist_halfmove=hm,
        )

    def shard_row(free, n_ref, shard_steps):
        """Per-shard occupancy columns (mesh runs only): live lanes,
        lanes respliced this boundary, device step counts. lane_pos is
        sampled pre-refill (do_refill mutates it), so `free` carries the
        boundary's free-lane snapshot."""
        if mesh is None:
            return None
        busy = lane_pos >= 0
        busy[free] = False
        sel = np.asarray(free[:n_ref], np.int64)
        return {
            "shard_live": [
                int(busy[s * local:(s + 1) * local].sum())
                for s in range(ndev)
            ],
            "shard_refilled": np.bincount(
                sel // local, minlength=ndev).astype(int).tolist(),
            "shard_steps": shard_steps,
        }

    def pull_pv(st, lanes, pos):
        """Materialize PV rows for finished lanes only: two small
        device-side gathers instead of the full (B, P) table. On a
        multi-host mesh each process gathers the rows its addressable
        shards own and the host exchange fills in the rest, so every
        process assembles identical results."""
        if multiproc:
            from ..parallel import distributed as _dist

            out["pv"][pos] = _dist.gather_rows(
                mesh, st.pv, lanes, stats, "pv",
                pick=lambda a: a[:, 0], tail=(P,), dtype=np.int32)
            out["pv_len"][pos] = _dist.gather_rows(
                mesh, st.nt, lanes, stats, "pv_len",
                pick=lambda a: a[:, 0, NT_PVLEN], tail=(),
                dtype=np.int32)
            return
        rows = jnp.asarray(np.asarray(lanes, np.int64))
        out["pv"][pos] = stats.fetch(
            jnp.take(st.pv[:, 0], rows, axis=0), "pv")
        out["pv_len"][pos] = stats.fetch(
            jnp.take(st.nt[:, 0, NT_PVLEN], rows, axis=0), "pv_len")

    def pull_summ(p_summ):
        """One boundary summary fetch; addressable-shard aware when the
        mesh spans processes (ONE local fetch + host exchange)."""
        if multiproc:
            from ..parallel import distributed as _dist

            return _dist.fetch_summary(mesh, p_summ, stats, "summary")
        return stats.fetch(p_summ, "summary")

    def record(n, live, n_ref, pend_steps, shard=None):
        nonlocal seg_i, segment_steps
        seg_i += 1
        snap = stats.boundary()
        row = {
            "segment": seg_i, "steps": int(n), "live": live,
            "refilled": int(n_ref),
            "idle": width - live - int(n_ref), "queue": len(queue),
            **snap,
        }
        if shard is not None:
            row.update(shard)
        occupancy.append(row)
        if ctrl is not None:
            segment_steps = ctrl.update(
                int(n) >= pend_steps, snap["host_ms"], snap["device_ms"])

    final_state, final_tt = state, tt
    if not pipeline:
        # round-7 synchronous loop: block on the segment, materialize
        # the full result set, refill, repeat (kept bit-for-bit for
        # FISHNET_TPU_PIPELINE=0 and as the A/B baseline)
        while total < max_steps:
            if deadline is not None and _time.monotonic() >= deadline:
                break
            state, tt, n, _summ = dispatch(state, tt, segment_steps)
            pend_steps = segment_steps
            n_arr = np.asarray(stats.fetch(n, "steps")).reshape(-1)
            shard_steps = (
                [int(x) for x in n_arr] if mesh is not None else None
            )
            n = int(n_arr.max())
            total += n
            lane_done = stats.fetch(
                state.lane[:, LN_MODE] == MODE_DONE, "done")
            res = extract_results(state, jnp.int32(total))
            fin = np.nonzero(lane_done & (lane_pos >= 0))[0]
            if fin.size:
                for key in out:
                    out[key][lane_pos[fin]] = stats.fetch(res[key], key)[fin]
                done_out[lane_pos[fin]] = True
                lane_pos[fin] = -1
            live = int((lane_pos >= 0).sum())
            free = np.nonzero(lane_pos < 0)[0]
            n_ref = min(len(free), len(queue))
            if n_ref and (deadline is None or _time.monotonic() < deadline):
                state = do_refill(state, free, n_ref)
            else:
                n_ref = 0
            record(n, live, n_ref, pend_steps,
                   shard_row(free, n_ref, shard_steps))
            if live == 0 and n_ref == 0 and not queue:
                break
        final_state, final_tt = state, tt
    else:
        # pipelined loop: one in-flight segment at all times; while it
        # runs, the host processes the PREVIOUS boundary from its packed
        # summary, and when no refill decision is pending the NEXT
        # segment is dispatched speculatively (chained on the in-flight
        # segment's output futures) before blocking on the summary
        pend = None
        pend_steps = segment_steps
        prev_live = k > 0
        pv_pending: list[tuple[int, int]] = []  # deferred (lane, pos)
        if total < max_steps and (
                deadline is None or _time.monotonic() < deadline):
            pend = dispatch(state, tt, segment_steps)
        while pend is not None:
            p_state, p_tt, _p_n, p_summ = pend
            nxt = None
            nxt_steps = segment_steps
            if (prev_live and not queue
                    and total + pend_steps < max_steps
                    and (deadline is None or _time.monotonic() < deadline)):
                # the queue is empty, so the synchronous loop would
                # dispatch this exact segment after the boundary anyway;
                # issuing it now donates p_state/p_tt in place and keeps
                # the device busy across the host's boundary work
                nxt = dispatch(p_state, p_tt, nxt_steps)
            summ, n, shard_steps = canon_summ(pull_summ(p_summ))
            total += n
            lane_done = summ[:, SUM_DONE].astype(bool)
            fin = np.nonzero(lane_done & (lane_pos >= 0))[0]
            if fin.size:
                pos = lane_pos[fin]
                out["score"][pos] = summ[fin, SUM_SCORE]
                out["move"][pos] = summ[fin, SUM_MOVE]
                out["nodes"][pos] = summ[fin, SUM_NODES]
                done_out[pos] = True
                if nxt is None:
                    pull_pv(p_state, fin, pos)
                else:
                    # p_state was donated into the speculative dispatch;
                    # DONE lanes stay frozen (and the empty queue means
                    # they are never respliced), so their PV rows are
                    # pulled from a later resolved state
                    pv_pending.extend(zip(fin.tolist(), pos.tolist()))
                lane_pos[fin] = -1
            if pv_pending and nxt is None:
                lanes = np.asarray([ln for ln, _ in pv_pending], np.int64)
                pos = np.asarray([p for _, p in pv_pending], np.int64)
                pull_pv(p_state, lanes, pos)
                pv_pending.clear()
            live = int((lane_pos >= 0).sum())
            free = np.nonzero(lane_pos < 0)[0]
            n_ref = min(len(free), len(queue))
            cur_state = p_state
            if (n_ref and nxt is None
                    and (deadline is None or _time.monotonic() < deadline)):
                cur_state = do_refill(cur_state, free, n_ref)
            else:
                n_ref = 0
            record(n, live, n_ref, pend_steps,
                   shard_row(free, n_ref, shard_steps))
            if nxt is not None:
                pend = nxt
                pend_steps = nxt_steps
                prev_live = live > 0
                continue
            stop = (
                (live == 0 and n_ref == 0 and not queue)
                or total >= max_steps
                or (deadline is not None
                    and _time.monotonic() >= deadline)
            )
            if stop:
                final_state, final_tt = cur_state, p_tt
                pend = None
            else:
                pend = dispatch(cur_state, p_tt, segment_steps)
                pend_steps = segment_steps
                prev_live = live > 0 or n_ref > 0

    return {
        "score": jnp.asarray(out["score"]),
        "move": jnp.asarray(out["move"]),
        "pv": jnp.asarray(out["pv"]),
        "pv_len": jnp.asarray(out["pv_len"]),
        "nodes": jnp.asarray(out["nodes"]),
        "done": jnp.asarray(done_out),
        "steps": jnp.int32(total),
        "occupancy": occupancy,
        "refills": refills_total,
        "tt": final_tt,
    }


def search_batch_resumable(
    params: nnue.NnueParams,
    roots: Board,
    depth,
    node_budget,
    max_ply: int,
    segment_steps: int | None = None,
    max_steps: int = 4_000_000,
    deadline: float | None = None,
    tt=None,
    mesh=None,
    variant: str = "standard",
    hist=None,
    window=None,
    deep_tt: bool = False,
    narrow: bool = True,
    order_jitter=None,
    group=None,
    required=None,
    prefer_deep_store: bool = False,
    tt_gen: int = 0,
):
    """Like `search_batch`, but dispatched in bounded segments.

    order_jitter/group (B,): Lazy-SMP lane-group metadata — see
    init_state. required (B,) bool: the lanes whose completion the
    caller actually needs (the PRIMARY lanes of helper groups). Once
    every required lane is DONE the host stops dispatching segments and
    abandons the rest mid-flight — helper lanes exist only to feed the
    shared TT, and a lockstep step costs the same however few lanes run,
    so finishing them would pay pure wall-clock for entries nobody will
    read. None means every lane is required (the pre-helper behavior).
    prefer_deep_store + tt_gen: store policy for helper dispatches
    (ops/tt.py store).

    window: optional (root_alpha (B,), root_beta (B,)) aspiration window;
    a root whose true value falls outside reports a bound (fail-low /
    fail-high) — the caller re-searches with a wider window.

    deep_tt: accept deeper LOWER/UPPER TT entries as cutoffs (move-job
    strength mode; analysis keeps deterministic exact-depth probes).

    deadline: absolute time.monotonic() stamp; between segments the host
    stops early when passed. Lanes not DONE at stop report done=False and
    their root_score/move must be ignored by the caller.

    tt: optional shared ops.tt.TTable; the updated table is returned as
    results["tt"] so callers can carry it across searches (the engine
    keeps one per process, like Stockfish's persistent hash).

    mesh: optional jax.sharding.Mesh — lanes shard over its devices and
    each device advances its shard independently (parallel.mesh). With a
    mesh, tt must carry a leading (ndev,) shard dim
    (parallel.mesh.make_sharded_table) or be None.

    narrow: at segment boundaries, retire DONE lanes and continue the
    live ones in a half-width program (repeatedly, power-of-two buckets,
    floor FISHNET_TPU_NARROW_FLOOR, default 64). A lockstep step costs the same whether 1 or B lanes are
    live, so the finish-tail otherwise dominates batch wall-clock (the
    round-5 bench measured 105 knps batch-completion vs 258 knps
    steady-state at B=1024 from exactly this). Off under a mesh (shards
    must keep their static width). With tt=None results are identical —
    narrowing relocates lanes, it never changes any lane's search. With a
    shared TT they are identical up to scatter write order: narrowing
    permutes lane order, and simultaneous stores to one TT slot keep an
    order-dependent winner — the same already-documented tolerance every
    TT-on search has (ops/tt.py: a lost/torn entry only costs a
    re-search, never a wrong score).
    """
    import time as _time

    # segment length and narrowing floor are registry-backed so deployments
    # can trade host-check latency against dispatch overhead without code
    # edits; the defaults reproduce the historical hardcoded values exactly.
    # FISHNET_TPU_SEGMENT=auto has no feedback loop on this path (the
    # controller lives in the streaming loops) — it falls back to the
    # registry's upper bound
    if segment_steps is None:
        segment_steps = settings.get_segment()
        if segment_steps is None:
            segment_steps = settings.get_int("FISHNET_TPU_SEGMENT_MAX")
    narrow_floor = settings.get_int("FISHNET_TPU_NARROW_FLOOR")

    B = roots.stm.shape[0]
    depth = jnp.broadcast_to(jnp.asarray(depth, jnp.int32), (B,))
    node_budget = jnp.broadcast_to(jnp.asarray(node_budget, jnp.int32), (B,))
    hist_hash, hist_halfmove = hist if hist is not None else (None, None)
    root_alpha, root_beta = window if window is not None else (None, None)
    state = _init_state_jit(
        params, roots, depth, node_budget, max_ply, variant,
        hist_hash=hist_hash, hist_halfmove=hist_halfmove,
        root_alpha=root_alpha, root_beta=root_beta,
        order_jitter=order_jitter, group=group,
    )
    if mesh is not None:
        from ..parallel.mesh import run_segment_sharded, shard_batch

        # place the fresh state sharded BEFORE the first dispatch: the
        # sharded segment donates its operands, and donation only takes
        # when the input already carries the program's sharding
        state = shard_batch(mesh, state)

        def dispatch(state, tt):
            state, tt, n, _summ = run_segment_sharded(
                mesh, params, state, tt, segment_steps, variant=variant,
                deep_tt=deep_tt, prefer_deep=prefer_deep_store,
                tt_gen=tt_gen,
            )
            # devices stop independently; continue while ANY used the
            # full segment (i.e. may still have live lanes)
            return state, tt, int(np.max(np.asarray(n)))
    else:
        def dispatch(state, tt):
            state, tt, n, _summ = _run_segment_jit(
                params, state, tt, segment_steps, variant, deep_tt,
                prefer_deep_store, jnp.int32(tt_gen),
            )
            return state, tt, int(n)

    # retired-lane result buffers (original lane indexing); `orig` maps
    # current state rows → original lanes, `valid` marks rows that still
    # OWN their original lane (padding rows after a narrow do not)
    flushed: dict[str, np.ndarray] | None = None
    orig = np.arange(B)
    valid = np.ones(B, bool)
    req = None if required is None else np.asarray(required, bool).copy()

    def _flush(res: dict, mask: np.ndarray) -> None:
        nonlocal flushed
        if flushed is None:
            flushed = {
                k: np.zeros((B,) + np.asarray(v).shape[1:],
                            np.asarray(v).dtype)
                for k, v in res.items() if k != "steps"
            }
        for k, buf in flushed.items():
            buf[orig[mask]] = np.asarray(res[k])[mask]

    total = 0
    while total < max_steps:
        if deadline is not None and _time.monotonic() >= deadline:
            break  # don't dispatch (or cold-compile) a segment we'd discard
        state, tt, n = dispatch(state, tt)
        total += n  # sync point: segment finished on device
        if n < segment_steps:
            break  # every lane parked in DONE
        if req is not None:
            done_now = np.asarray(state.lane[:, LN_MODE] == MODE_DONE)
            if not np.any(req & valid & ~done_now):
                break  # all required lanes finished; abandon the helpers
        if deadline is not None and _time.monotonic() >= deadline:
            break
        cur = state.lane.shape[0]
        if narrow and mesh is None and cur > narrow_floor:
            done = np.asarray(state.lane[:, LN_MODE] == MODE_DONE)
            live = int((~done & valid).sum())
            # target width: smallest power of two >= live, floor
            # FISHNET_TPU_NARROW_FLOOR (default 64) — always a power of
            # two even when the caller's width is not (the engine pads
            # >256-lane batches to multiples of 256), so narrowed
            # programs land on the handful of pow2 shapes the compile
            # cache / engine warmup already know
            new_b = narrow_floor
            while new_b < live:
                new_b *= 2
            if new_b < cur:
                _flush(extract_results(state, jnp.int32(total)),
                       done & valid)
                keep = np.nonzero(~done & valid)[0]
                # pad with retired rows: they are DONE, so they park
                # inertly; their `valid` goes False so the final merge
                # never double-reports their original lane
                pad = np.nonzero(done)[0][: new_b - len(keep)]
                order = np.concatenate([keep, pad])
                state = jax.tree.map(lambda a: a[jnp.asarray(order)], state)
                orig = orig[order]
                if req is not None:
                    req = req[order]
                valid = np.concatenate(
                    [np.ones(len(keep), bool), np.zeros(len(pad), bool)]
                )

    out = extract_results(state, jnp.int32(total))
    if flushed is not None:
        final = {k: np.asarray(v) for k, v in out.items() if k != "steps"}
        for k, buf in flushed.items():
            buf[orig[valid]] = final[k][valid]
        out = {k: jnp.asarray(v) for k, v in flushed.items()}
        out["steps"] = jnp.int32(total)
    out["tt"] = tt
    return out


def search_batch(params: nnue.NnueParams, roots: Board, depth, node_budget,
                 max_ply: int, max_steps: int = 2_000_000, tt=None,
                 variant: str = "standard", hist=None):
    """Run fixed-depth alpha-beta + capture quiescence on B roots in
    lockstep.

    Requires max_ply > max(depth): past the nominal depth the search
    keeps expanding captures (quiescence with stand-pat) until quiet or
    until the max_ply stack runs out, so max_ply - depth is the QS
    headroom. Returns a dict of (B,)-shaped results; scores are
    centipawn ints from the root side to move's perspective; ±(MATE-n)
    encodes mate in n plies. tt: optional shared ops.tt.TTable.

    Thin wrapper over `search_batch_resumable` (one compile surface —
    tests and production share the same `_run_segment_jit` programs; a
    second whole-search jit used to double every suite's compile cost).
    """
    seg = settings.get_segment()
    if seg is None:
        seg = settings.get_int("FISHNET_TPU_SEGMENT_MAX")
    return search_batch_resumable(
        params, roots, depth, node_budget, max_ply=max_ply,
        segment_steps=min(max_steps, seg),
        max_steps=max_steps, tt=tt, variant=variant, hist=hist,
    )


# alias kept for callers that used the jitted entry point; the segment
# dispatch inside is jitted, so a separate outer jit adds nothing
search_batch_jit = search_batch
