"""Lockstep batched alpha-beta search.

The reference's "search layer" is Stockfish's recursive C++ alpha-beta run
in one process per core (reference: §2 of SURVEY.md; fishnet drives it via
`go nodes N` per position, src/stockfish.rs:290-350). On TPU the recursion
becomes an explicit per-lane DFS stack advanced in lockstep by a single
jitted `lax.while_loop` step over B independent lanes:

- copy-make: child boards are written to a (B, MAX_PLY, ...) stack, so
  there is no unmake logic on device;
- pseudo-legal movegen + king-capture refutation: a mover that leaves the
  king en prise is refuted at the child (ILLEGAL sentinel), which keeps
  pin/evasion logic out of the kernel;
- one state machine step = phase ENTER (classify node: illegal/leaf/expand
  with movegen) → phase RETURN (fold a finished child into its parent) →
  phase TRYMOVE (pick next move or finish the node). Phase order is chosen
  so a leaf child costs a single step;
- per-lane node budgets and depth limits; lanes park in DONE and are
  masked out (divergence tax: a step costs the same while any lane runs).

MultiPV and iterative deepening are driven from the host (engine/tpu.py):
lanes are cheap, so multipv lanes are just more lanes.
"""
from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from ..models import nnue
from .board import (
    Board,
    is_attacked,
    king_square,
    make_move,
    move_piece_changes,
)
from .movegen import MAX_MOVES, generate_moves

INF = 32500
MATE = 32000
ILLEGAL = 99999  # sentinel: the move leading to this node was illegal
DRAW = 0

MODE_ENTER = 0
MODE_RETURN = 1
MODE_TRYMOVE = 2
MODE_DONE = 3


class SearchState(NamedTuple):
    # stacks, leading dims (B, MAX_PLY[+1])
    board: jnp.ndarray  # (B, P+1, 64) int32
    stm: jnp.ndarray  # (B, P+1)
    ep: jnp.ndarray  # (B, P+1)
    castling: jnp.ndarray  # (B, P+1, 4)
    halfmove: jnp.ndarray  # (B, P+1)
    moves: jnp.ndarray  # (B, P, MAX_MOVES) int32
    count: jnp.ndarray  # (B, P)
    midx: jnp.ndarray  # (B, P)
    searched: jnp.ndarray  # (B, P) legal children folded so far
    alpha: jnp.ndarray  # (B, P) int32
    beta: jnp.ndarray  # (B, P)
    best: jnp.ndarray  # (B, P)
    best_move: jnp.ndarray  # (B, P)
    incheck: jnp.ndarray  # (B, P) bool
    pv: jnp.ndarray  # (B, P, P) int32
    pv_len: jnp.ndarray  # (B, P)
    acc: jnp.ndarray  # (B, P+1, 2, L1) f32 incremental NNUE accumulators
    ply: jnp.ndarray  # (B,)
    mode: jnp.ndarray  # (B,)
    ret: jnp.ndarray  # (B,) value returned by just-finished node
    nodes: jnp.ndarray  # (B,) int32 visited nodes
    depth_limit: jnp.ndarray  # (B,)
    node_budget: jnp.ndarray  # (B,)
    root_score: jnp.ndarray  # (B,)
    root_move: jnp.ndarray  # (B,)


def _board_at(s: SearchState, ply: jnp.ndarray) -> Board:
    return Board(
        board=s.board[ply],
        stm=s.stm[ply],
        ep=s.ep[ply],
        castling=s.castling[ply],
        halfmove=s.halfmove[ply],
    )


def init_state(params: nnue.NnueParams, roots: Board, depth: jnp.ndarray,
               node_budget: jnp.ndarray, max_ply: int) -> SearchState:
    """roots: batched Board (B leading dim); depth/node_budget: (B,)."""
    B = roots.stm.shape[0]
    P = max_ply
    l1 = params.ft_w.shape[1]
    if nnue.is_board768(params):
        root_acc = jax.vmap(nnue.accumulators_768, in_axes=(None, 0))(
            params, roots.board
        )
    else:
        root_acc = jnp.zeros((B, 2, l1), params.ft_w.dtype)
    acc = jnp.zeros((B, P + 1, 2, l1), params.ft_w.dtype)
    acc = acc.at[:, 0].set(root_acc)

    def z(*shape, dtype=jnp.int32, fill=0):
        return jnp.full((B, *shape), fill, dtype=dtype)

    board = z(P + 1, 64)
    board = board.at[:, 0].set(roots.board)
    stm = z(P + 1)
    stm = stm.at[:, 0].set(roots.stm)
    ep = z(P + 1, fill=-1)
    ep = ep.at[:, 0].set(roots.ep)
    castling = z(P + 1, 4, fill=-1)
    castling = castling.at[:, 0].set(roots.castling)
    halfmove = z(P + 1)
    halfmove = halfmove.at[:, 0].set(roots.halfmove)
    return SearchState(
        board=board, stm=stm, ep=ep, castling=castling, halfmove=halfmove,
        moves=z(P, MAX_MOVES, fill=-1),
        count=z(P), midx=z(P), searched=z(P),
        alpha=z(P, fill=-INF), beta=z(P, fill=INF),
        best=z(P, fill=-INF), best_move=z(P, fill=-1),
        incheck=z(P, dtype=jnp.bool_),
        pv=z(P, P, fill=-1), pv_len=z(P),
        acc=acc,
        ply=z(), mode=z(), ret=z(),
        nodes=z(),
        depth_limit=depth.astype(jnp.int32),
        node_budget=node_budget.astype(jnp.int32),
        root_score=z(fill=-INF), root_move=z(fill=-1),
    )


def _step_lane(params: nnue.NnueParams, s: SearchState) -> SearchState:
    """One state-machine step for a single lane (vmapped over B)."""
    ply = s.ply

    # ---------------------------------------------------------- phase ENTER
    def phase_enter(s):
        b = _board_at(s, ply)
        us = b.stm
        them = 1 - us
        our_k = king_square(b.board, us)
        their_k = king_square(b.board, them)
        # parent's move was illegal iff the side that just moved (them)
        # left its king attacked (or captured outright)
        parent_illegal = (ply > 0) & (
            (their_k < 0)
            | is_attacked(b.board, jnp.maximum(their_k, 0), us)
        )
        we_are_checked = is_attacked(b.board, jnp.maximum(our_k, 0), them)
        depth_left = s.depth_limit - ply
        over_budget = s.nodes >= s.node_budget
        fifty = b.halfmove >= 100
        is_leaf = (depth_left <= 0) | fifty | over_budget

        # leaf value: NNUE eval (or draw for 50-move). On the board768 fast
        # path the accumulator came down the stack incrementally and only
        # the small layer stack runs here; the halfkav2_hm compat path pays
        # a full refresh per step.
        if nnue.is_board768(params):
            leaf_val = jnp.int32(
                nnue.forward_from_acc(
                    params, s.acc[ply], us, nnue.output_bucket(b.board)
                )
            )
        else:
            leaf_val = jnp.int32(nnue.evaluate(params, b.board, us))
        leaf_val = jnp.clip(leaf_val, -MATE + 1000, MATE - 1000)
        leaf_val = jnp.where(fifty, DRAW, leaf_val)

        gen_moves, gen_count = generate_moves(b)

        ret = jnp.where(parent_illegal, ILLEGAL, leaf_val)
        to_return = parent_illegal | is_leaf
        new_mode = jnp.where(to_return, MODE_RETURN, MODE_TRYMOVE)

        expand = ~to_return
        upd = lambda arr, val: arr.at[ply].set(jnp.where(expand, val, arr[ply]))
        return s._replace(
            moves=s.moves.at[ply].set(
                jnp.where(expand, gen_moves, s.moves[ply])
            ),
            count=upd(s.count, gen_count),
            midx=upd(s.midx, 0),
            searched=upd(s.searched, 0),
            alpha=upd(s.alpha, jnp.where(ply == 0, -INF, -s.beta[ply - 1])),
            beta=upd(s.beta, jnp.where(ply == 0, INF, -s.alpha[ply - 1])),
            best=upd(s.best, -INF),
            best_move=upd(s.best_move, -1),
            incheck=s.incheck.at[ply].set(we_are_checked),
            # leaf nodes must also zero pv_len: the fold at the parent reads
            # pv_len[child_ply], which would otherwise be a stale slot
            pv_len=s.pv_len.at[ply].set(0),
            ret=jnp.where(to_return, ret, s.ret),
            mode=new_mode,
            nodes=s.nodes + jnp.where(parent_illegal, 0, 1),
        )

    s = jax.lax.cond(s.mode == MODE_ENTER, phase_enter, lambda s: s, s)

    # --------------------------------------------------------- phase RETURN
    def phase_return(s):
        # the node at `ply` finished with value s.ret (from its stm's view)
        at_root = ply == 0

        # root: record and park (ret, not best[0] — ret carries the
        # mate/stalemate value when the root had no legal moves)
        root_done = s._replace(
            root_score=jnp.where(at_root, s.ret, s.root_score),
            root_move=jnp.where(at_root, s.best_move[0], s.root_move),
            mode=jnp.where(at_root, MODE_DONE, s.mode),
        )

        # interior: fold into parent at ply-1
        parent = jnp.maximum(ply - 1, 0)
        was_illegal = s.ret == ILLEGAL
        v = -s.ret
        tried = s.moves[parent, jnp.maximum(s.midx[parent] - 1, 0)]
        better = (~was_illegal) & (v > s.best[parent])
        new_best = jnp.where(better, v, s.best[parent])
        new_best_move = jnp.where(better, tried, s.best_move[parent])
        new_alpha = jnp.maximum(s.alpha[parent], new_best)
        new_searched = s.searched[parent] + jnp.where(was_illegal, 0, 1)
        # pv[parent] = tried + pv[ply]
        child_pv = s.pv[ply]
        new_pv_row = jnp.concatenate(
            [tried[None], child_pv[:-1]]
        )
        new_pv_len = jnp.minimum(s.pv_len[ply] + 1, s.pv.shape[-1])

        folded = s._replace(
            best=s.best.at[parent].set(new_best),
            best_move=s.best_move.at[parent].set(new_best_move),
            alpha=s.alpha.at[parent].set(new_alpha),
            searched=s.searched.at[parent].set(new_searched),
            pv=jnp.where(
                better,
                s.pv.at[parent].set(new_pv_row),
                s.pv,
            ),
            pv_len=jnp.where(
                better, s.pv_len.at[parent].set(new_pv_len), s.pv_len
            ),
            ply=parent,
            mode=MODE_TRYMOVE,
        )
        return jax.tree_util.tree_map(
            lambda a, b: jnp.where(at_root, a, b), root_done, folded
        )

    s = jax.lax.cond(s.mode == MODE_RETURN, phase_return, lambda s: s, s)
    ply = s.ply  # may have been decremented by RETURN

    # -------------------------------------------------------- phase TRYMOVE
    def phase_trymove(s):
        # note: the node budget is enforced in ENTER (children degrade to
        # leaf evals), not here — finishing a node early with searched==0
        # would return -INF garbage to the parent
        exhausted = s.midx[ply] >= s.count[ply]
        cutoff = s.alpha[ply] >= s.beta[ply]
        finish = exhausted | cutoff

        # finished node value: best, or mate/stalemate when no legal child
        no_legal = s.searched[ply] == 0
        mate_val = jnp.where(s.incheck[ply], -(MATE - ply), DRAW)
        fin_val = jnp.where(no_legal & exhausted, mate_val, s.best[ply])

        move = s.moves[ply, jnp.minimum(s.midx[ply], MAX_MOVES - 1)]
        parent_b = _board_at(s, ply)
        child = make_move(parent_b, jnp.maximum(move, 0))
        nply = ply + 1

        if nnue.is_board768(params):
            codes, sqs, signs = move_piece_changes(parent_b, jnp.maximum(move, 0))
            child_acc = nnue.apply_acc_updates_768(
                params, s.acc[ply], codes, sqs, signs
            )
            new_acc = s.acc.at[nply].set(child_acc)
        else:
            new_acc = s.acc

        advanced = s._replace(
            midx=s.midx.at[ply].add(1),
            board=s.board.at[nply].set(child.board),
            stm=s.stm.at[nply].set(child.stm),
            ep=s.ep.at[nply].set(child.ep),
            castling=s.castling.at[nply].set(child.castling),
            halfmove=s.halfmove.at[nply].set(child.halfmove),
            acc=new_acc,
            ply=nply,
            mode=MODE_ENTER,
        )
        finished = s._replace(ret=fin_val, mode=MODE_RETURN)
        return jax.tree_util.tree_map(
            lambda a, b: jnp.where(finish, a, b), finished, advanced
        )

    s = jax.lax.cond(s.mode == MODE_TRYMOVE, phase_trymove, lambda s: s, s)
    return s


def make_search_step(params: nnue.NnueParams):
    lane_axes = SearchState(
        *[0 for _ in SearchState._fields]
    )
    return jax.vmap(lambda s: _step_lane(params, s), in_axes=(lane_axes,))


def search_batch(params: nnue.NnueParams, roots: Board, depth, node_budget,
                 max_ply: int, max_steps: int = 2_000_000):
    """Run fixed-depth alpha-beta on B root positions in lockstep.

    Requires max_ply > max(depth): leaves live at ply == depth and need
    stack slots. Returns a dict of (B,)-shaped results; scores are
    centipawn ints from the root side to move's perspective; ±(MATE-n)
    encodes mate in n plies.
    """
    B = roots.stm.shape[0]
    depth = jnp.broadcast_to(jnp.asarray(depth, jnp.int32), (B,))
    node_budget = jnp.broadcast_to(jnp.asarray(node_budget, jnp.int32), (B,))
    state = init_state(params, roots, depth, node_budget, max_ply)
    step = make_search_step(params)

    def cond(carry):
        s, i = carry
        return (i < max_steps) & jnp.any(s.mode != MODE_DONE)

    def body(carry):
        s, i = carry
        return step(s), i + 1

    state, steps = jax.lax.while_loop(cond, body, (state, jnp.int32(0)))
    return {
        "score": state.root_score,
        "move": state.root_move,
        "pv": state.pv[:, 0],
        "pv_len": state.pv_len[:, 0],
        "nodes": state.nodes,
        "steps": steps,
    }


search_batch_jit = jax.jit(search_batch, static_argnames=("max_ply", "max_steps"))
