"""Host-side model of the device search, for exact-equality testing.

The reference's search correctness is carried by Stockfish itself; the
lockstep device search (ops/search.py) needs an oracle instead. This is a
plain recursive negamax that mirrors the device state machine EXACTLY —
same pseudo-legal movegen and move order, same king-capture refutation,
same capture-only quiescence with stand-pat floor, same fifty-move /
repetition / budget / stack-full leaf rules, same mate/stalemate values,
and the same NNUE evaluation path (incremental board768 accumulators or
full refresh) — so `search_batch` results can be asserted bit-identical
at small depth.

It deliberately calls the device ops (fused into two jitted calls per
node, dispatched from the recursion) rather than re-implementing them in
numpy: float summation order then matches the device program exactly,
keeping int-cast evals bit-stable.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ..models import nnue
from . import tt as tt_mod
from .board import (
    TERM_DRAW,
    TERM_LOSS,
    TERM_NONE,
    TERM_WIN,
    Board,
    make_move,
    move_piece_changes,
    node_rules,
)
from .movegen import generate_moves
from .search import DRAW, ILLEGAL, INF, MATE, NULL_R, _PRUNING


@functools.lru_cache(maxsize=8)
def _jitted(b768: bool, variant: str):
    """Two fused device calls per oracle node (single-core dispatch cost
    dominates the oracle's runtime, so everything per-node is batched into
    `classify`, and per-child into `child`)."""

    def classify(params, b: Board, acc, killers, hist):
        us = b.stm
        illegal, checked, term_kind = node_rules(b, variant)
        if b768 and variant != "atomic":
            val = jnp.int32(
                nnue.forward_from_acc(params, acc, us, nnue.output_bucket(b.board))
            )
        else:
            # atomic explosions exceed the 4-slot incremental scheme —
            # full refresh, same as the device step
            val = jnp.int32(nnue.evaluate(params, b.board, us))
        moves, count, noisy = generate_moves(
            b, variant, killers=killers, hist=hist
        )
        h1, h2 = tt_mod.hash_board(b.board, us, b.ep, b.castling, b.extra, variant)
        return illegal, checked, val, moves, count, noisy, h1, h2, term_kind

    def child(params, b: Board, acc, move):
        nb = make_move(b, move, variant)
        if b768 and variant != "atomic":
            codes, sqs, signs = move_piece_changes(b, move, variant)
            nacc = nnue.apply_acc_updates_768(params, acc, codes, sqs, signs)
        else:
            nacc = acc
        return nb, nacc

    return {
        "classify": jax.jit(classify),
        "child": jax.jit(child),
        "acc_root": jax.jit(nnue.accumulators_768),
    }


class _Oracle:
    def __init__(self, params, depth: int, node_budget: int, max_ply: int,
                 variant: str = "standard", history=None):
        self.p = params
        self.depth = depth
        self.budget = node_budget
        self.max_ply = max_ply
        self.variant = variant
        self.nodes = 0
        self.rep_hits = 0  # repetition-draw leaves seen (test instrumentation)
        self.b768 = nnue.is_board768(params)
        self.ops = _jitted(self.b768, variant)
        # [(h1, h2, halfmove, virtual_ply)]: pre-root game history at
        # virtual ply -distance (mirrors ops/search.py hist_hash slots),
        # then entered in-search path nodes at their real plies.
        # history: [(h1, h2, halfmove, distance)] with distance >= 1
        # plies before the root — pre-filtered to doubled positions the
        # same way the engine seeds the device (see engine/tpu.py
        # _history_arrays).
        self.path = [
            (h1, h2, hm, -dist) for h1, h2, hm, dist in (history or [])
        ]
        # quiet-move ordering state, mirroring the device lane's exactly
        # (ops/search.py killer/history update on fail-high)
        self.killers = np.full((max_ply + 2, 2), -1, np.int32)
        self.hist = np.zeros(4096, np.int32)

    def search(self, b: Board, acc, ply: int, alpha: int, beta: int,
               depth_left: int | None = None,
               from_null: bool = False) -> int:
        """depth_left: per-node remaining depth (root: self.depth); None
        derives the pre-reduction value — kept for the depth==ply-derived
        callers in older tests. from_null: this node was reached by a
        null move (mirrors the device's parent null_st == 2)."""
        if depth_left is None:
            depth_left = self.depth - ply
        ops = self.ops
        (illegal, checked, val, moves, count, noisy, h1, h2,
         term_kind) = ops["classify"](
            self.p, b, acc,
            jnp.asarray(self.killers[min(ply, self.max_ply)]),
            jnp.asarray(self.hist),
        )
        if ply > 0 and bool(illegal):
            return ILLEGAL
        over_budget = self.nodes >= self.budget
        self.nodes += 1
        halfmove = int(b.halfmove)
        fifty = halfmove >= 100
        # twofold repetition along the path (mirrors ops/search.py):
        # equal hash through an unbroken reversible chain
        hh = (int(h1), int(h2))
        repet = any(
            (halfmove - ph) == (ply - vp) and (a, c) == hh
            for a, c, ph, vp in self.path
        )
        self.rep_hits += int(repet)
        in_qs = depth_left <= 0
        stack_full = ply >= self.max_ply

        static_val = max(min(int(val), MATE - 1000), -(MATE - 1000))
        leaf_val = DRAW if (fifty or repet) else static_val
        kind = int(term_kind)
        vterm = kind != TERM_NONE
        if vterm:
            leaf_val = {
                TERM_LOSS: -(MATE - ply),
                TERM_WIN: MATE - ply,
                TERM_DRAW: DRAW,
            }[kind]
        count, noisy = int(count), int(noisy)
        # futility pruning (mirrors ops/search.py bit for bit): frontier
        # node with static eval a margin below alpha expands only the
        # noisy prefix with the static eval as fail-soft floor
        futile = False
        if _PRUNING and not in_qs and not bool(checked) and ply > 0:
            f_margin = 150 if depth_left == 1 else 300
            futile = (
                depth_left <= 2
                and static_val + f_margin <= alpha
                and alpha > -(MATE - 1000)
                and alpha < MATE - 1000
            )
        qs_like = in_qs or futile
        is_leaf = (
            fifty or repet or vterm or over_budget or stack_full
            or (qs_like and noisy == 0)
        )
        if in_qs and leaf_val >= beta:  # stand-pat beta cutoff
            is_leaf = True
        if is_leaf:
            return leaf_val

        n = noisy if qs_like else count
        moves = np.asarray(moves)
        if qs_like:
            best = leaf_val  # stand-pat floors best and alpha
            alpha = max(alpha, leaf_val)
        else:
            best = -INF
        searched = 0
        cut = False
        best_move = -1
        board_np = np.asarray(b.board)
        # null-move eligibility, mirroring ops/search.py's nmp_ok bit for
        # bit (antichess excluded there: captures are forced, so passing
        # proves nothing)
        nmp_ok = False
        if _PRUNING and self.variant != "antichess" and not in_qs:
            base = int(b.stm) * 6
            nonpawn = bool(
                ((board_np >= base + 2) & (board_np <= base + 5)).any()
            )
            nmp_ok = (
                depth_left >= 3
                and not bool(checked)
                and not from_null
                and ply > 0
                and static_val >= beta
                and beta < MATE - 1000
                and beta > -(MATE - 1000)
                and nonpawn
            )
        self.path.append((hh[0], hh[1], halfmove, ply))
        try:
            if nmp_ok and not alpha >= beta:
                # same position, opponent to move, ep cleared, halfmove
                # clock reset (breaks repetition chains across the null),
                # searched in the zero-width (beta-1, beta) window at
                # reduced depth — exactly the device's null child
                r = NULL_R + (1 if depth_left >= 7 else 0)
                nb = Board(
                    board=b.board, stm=jnp.int32(1 - int(b.stm)),
                    ep=jnp.int32(-1), castling=b.castling,
                    halfmove=jnp.int32(0), extra=b.extra,
                )
                nv = self.search(
                    nb, acc, ply + 1, -beta, 1 - beta,
                    max(depth_left - 1 - r, 0), from_null=True,
                )
                if nv != ILLEGAL and -nv >= beta and -nv < MATE - 1000:
                    return -nv
            for i in range(n):
                if alpha >= beta:
                    cut = True
                    break
                mv = int(moves[i])
                # late-move reduction, mirroring the device's lmr_ok
                red = 0
                if _PRUNING and not in_qs:
                    mto = (mv >> 6) & 63
                    quiet = ((mv >> 15) & 1) == 1 or (
                        int(board_np[mto]) == 0 and ((mv >> 12) & 7) == 0
                    )
                    if (depth_left >= 3 and i >= 3 and quiet
                            and not bool(checked)):
                        red = 2 if i >= 8 else 1
                cb, cacc = ops["child"](self.p, b, acc, jnp.int32(mv))
                v = self.search(
                    cb, cacc, ply + 1, -beta, -alpha,
                    max(depth_left - 1 - red, 0),
                )
                if v == ILLEGAL:
                    continue
                if red > 0 and -v > alpha:
                    # reduced score beat alpha: re-search at full depth
                    # (the device's RETURN-phase research re-push)
                    v = self.search(
                        cb, cacc, ply + 1, -beta, -alpha,
                        max(depth_left - 1, 0),
                    )
                    if v == ILLEGAL:
                        continue
                searched += 1
                if -v > best:
                    best = -v
                    best_move = mv
                alpha = max(alpha, best)
            # killer/history credit on fail-high, mirroring the device's
            # TRYMOVE update bit for bit (which also fires when the
            # cutoff move happened to be the last one generated)
            if alpha >= beta and best_move >= 0:
                cause = best_move
                cto = (cause >> 6) & 63
                quiet = ((cause >> 15) & 1) == 1 or (
                    int(board_np[cto]) == 0 and ((cause >> 12) & 7) == 0
                )
                if quiet:
                    kp = min(ply, self.max_ply)
                    k0 = int(self.killers[kp, 0])
                    if cause != k0:
                        self.killers[kp] = (cause, k0)
                    dl = max(depth_left, 0)
                    w = min(dl * dl + 1, 1024)
                    idx = cause & 4095
                    self.hist[idx] = min(int(self.hist[idx]) + w, 1 << 20)
        finally:
            self.path.pop()
        # best == -INF mirrors the device's no_legal guard: a futile node
        # whose noisy children were all illegal still carries its static
        # floor in `best` and must return it, not a phantom mate/stalemate
        if searched == 0 and not in_qs and not cut and best == -INF:
            if self.variant == "antichess":
                # the side with no moves (stalemated / out of pieces) WINS
                return MATE - ply
            return -(MATE - ply) if bool(checked) else DRAW
        return best


def oracle_search(params, root: Board, depth: int, node_budget: int,
                  max_ply: int, variant: str = "standard",
                  history=None) -> dict:
    """Search one root exactly like one device lane; → {score, nodes}.

    root: single-lane Board. Matches ops.search.search_batch semantics for
    the same (depth, node_budget, max_ply, variant); scores must agree
    exactly. history: optional [(h1, h2, halfmove, distance)] doubled
    positions from the reversible game tail, distance = plies before the
    root (mirrors the device's hist_hash/hist_halfmove seeding; see
    engine/tpu.py _history_arrays for the Stockfish draw-rule rationale).
    """
    o = _Oracle(params, depth, node_budget, max_ply, variant, history)
    if o.b768:
        acc = o.ops["acc_root"](params, root.board)
    else:
        acc = jnp.zeros((2, params.ft_w.shape[1]), params.ft_w.dtype)
    score = o.search(root, acc, 0, -INF, INF)
    return {"score": score, "nodes": o.nodes, "rep_hits": o.rep_hits}
