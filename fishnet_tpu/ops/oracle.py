"""Host-side model of the device search, for exact-equality testing.

The reference's search correctness is carried by Stockfish itself; the
lockstep device search (ops/search.py) needs an oracle instead. This is a
plain recursive negamax that mirrors the device state machine EXACTLY —
same pseudo-legal movegen and move order, same king-capture refutation,
same capture-only quiescence with stand-pat floor, same fifty-move /
repetition / budget / stack-full leaf rules, same mate/stalemate values,
and the same NNUE evaluation path (incremental board768 accumulators or
full refresh) — so `search_batch` results can be asserted bit-identical
at small depth.

It deliberately calls the device ops (fused into two jitted calls per
node, dispatched from the recursion) rather than re-implementing them in
numpy: float summation order then matches the device program exactly,
keeping int-cast evals bit-stable.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ..models import nnue
from . import tt as tt_mod
from .board import (
    EXTRA_CHECKS,
    Board,
    is_attacked,
    king_square,
    make_move,
    move_piece_changes,
)
from .movegen import generate_moves
from .search import DRAW, ILLEGAL, INF, MATE


@functools.lru_cache(maxsize=8)
def _jitted(b768: bool, variant: str):
    """Two fused device calls per oracle node (single-core dispatch cost
    dominates the oracle's runtime, so everything per-node is batched into
    `classify`, and per-child into `child`)."""

    def classify(params, b: Board, acc):
        us = b.stm
        them = 1 - us
        their_k = king_square(b.board, them)
        illegal = (their_k < 0) | is_attacked(
            b.board, jnp.maximum(their_k, 0), us
        )
        our_k = king_square(b.board, us)
        checked = is_attacked(b.board, jnp.maximum(our_k, 0), them)
        if b768:
            val = jnp.int32(
                nnue.forward_from_acc(params, acc, us, nnue.output_bucket(b.board))
            )
        else:
            val = jnp.int32(nnue.evaluate(params, b.board, us))
        moves, count, noisy = generate_moves(b, variant)
        h1, h2 = tt_mod.hash_board(b.board, us, b.ep, b.castling, b.extra, variant)
        them_checks = jnp.where(
            us == 0, b.extra[EXTRA_CHECKS + 1], b.extra[EXTRA_CHECKS + 0]
        )
        return illegal, checked, val, moves, count, noisy, h1, h2, them_checks

    def child(params, b: Board, acc, move):
        nb = make_move(b, move, variant)
        if b768:
            codes, sqs, signs = move_piece_changes(b, move, variant)
            nacc = nnue.apply_acc_updates_768(params, acc, codes, sqs, signs)
        else:
            nacc = acc
        return nb, nacc

    return {
        "classify": jax.jit(classify),
        "child": jax.jit(child),
        "acc_root": jax.jit(nnue.accumulators_768),
    }


class _Oracle:
    def __init__(self, params, depth: int, node_budget: int, max_ply: int,
                 variant: str = "standard"):
        self.p = params
        self.depth = depth
        self.budget = node_budget
        self.max_ply = max_ply
        self.variant = variant
        self.nodes = 0
        self.rep_hits = 0  # repetition-draw leaves seen (test instrumentation)
        self.b768 = nnue.is_board768(params)
        self.ops = _jitted(self.b768, variant)
        self.path = []  # [(h1, h2, halfmove)] of entered path nodes

    def search(self, b: Board, acc, ply: int, alpha: int, beta: int) -> int:
        ops = self.ops
        (illegal, checked, val, moves, count, noisy, h1, h2,
         them_checks) = ops["classify"](self.p, b, acc)
        if ply > 0 and bool(illegal):
            return ILLEGAL
        depth_left = self.depth - ply
        over_budget = self.nodes >= self.budget
        self.nodes += 1
        halfmove = int(b.halfmove)
        fifty = halfmove >= 100
        # twofold repetition along the path (mirrors ops/search.py):
        # equal hash through an unbroken reversible chain
        hh = (int(h1), int(h2))
        repet = any(
            (halfmove - ph) == (ply - k) and (a, c) == hh
            for k, (a, c, ph) in enumerate(self.path)
        )
        self.rep_hits += int(repet)
        in_qs = depth_left <= 0
        stack_full = ply >= self.max_ply

        leaf_val = DRAW if (fifty or repet) else max(
            min(int(val), MATE - 1000), -(MATE - 1000)
        )
        three = self.variant == "threeCheck" and int(them_checks) >= 3
        if three:
            leaf_val = -(MATE - ply)
        count, noisy = int(count), int(noisy)
        is_leaf = (
            fifty or repet or three or over_budget or stack_full
            or (in_qs and noisy == 0)
        )
        if in_qs and leaf_val >= beta:  # stand-pat beta cutoff
            is_leaf = True
        if is_leaf:
            return leaf_val

        n = noisy if in_qs else count
        moves = np.asarray(moves)
        if in_qs:
            best = leaf_val  # stand-pat floors best and alpha
            alpha = max(alpha, leaf_val)
        else:
            best = -INF
        searched = 0
        cut = False
        self.path.append((hh[0], hh[1], halfmove))
        try:
            for i in range(n):
                if alpha >= beta:
                    cut = True
                    break
                mv = int(moves[i])
                cb, cacc = ops["child"](self.p, b, acc, jnp.int32(mv))
                v = self.search(cb, cacc, ply + 1, -beta, -alpha)
                if v == ILLEGAL:
                    continue
                searched += 1
                if -v > best:
                    best = -v
                alpha = max(alpha, best)
        finally:
            self.path.pop()
        if searched == 0 and not in_qs and not cut:
            return -(MATE - ply) if bool(checked) else DRAW
        return best


def oracle_search(params, root: Board, depth: int, node_budget: int,
                  max_ply: int, variant: str = "standard") -> dict:
    """Search one root exactly like one device lane; → {score, nodes}.

    root: single-lane Board. Matches ops.search.search_batch semantics for
    the same (depth, node_budget, max_ply, variant); scores must agree
    exactly.
    """
    o = _Oracle(params, depth, node_budget, max_ply, variant)
    if o.b768:
        acc = o.ops["acc_root"](params, root.board)
    else:
        acc = jnp.zeros((2, params.ft_w.shape[1]), params.ft_w.dtype)
    score = o.search(root, acc, 0, -INF, INF)
    return {"score": score, "nodes": o.nodes, "rep_hits": o.rep_hits}
