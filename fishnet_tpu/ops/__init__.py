"""Device-side chess ops: board representation, movegen, NNUE eval, search."""
