"""Batched pseudo-legal move generation.

Strategy (TPU-first, no data-dependent shapes): enumerate a fixed candidate
space — (64 sq × 8 dirs × 7 steps) slider slots, (64×8) knight and king
slots, (64×4) pawn slots, (8×3×4) promotion slots (promotions only
originate from the 8 pre-promotion-rank squares), 2 castling slots — as
masks, then compact valid candidates into a fixed (MAX_MOVES,) ORDERED move
list with one single-array sort of packed (ordering_key << 16 | move)
values (see generate_moves for the packing invariants). Legality is *not*
fully resolved here: the search uses king-capture pruning (an illegal mover
is refuted one ply later when its king is captured), so only castling does
attack checks. This keeps the kernel free of pin/evasion logic; the host
library remains the legality oracle for tests.

Single-lane function; `vmap` over lanes gives the batch.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

import numpy as np

from . import tables as T
from .board import (
    EXTRA_POCKET,
    Board,
    attack_map,
    exclusive_cumsum_small,
    king_square,
    piece_color,
    piece_type,
)

# static per-color pawn-target tables. Indexing `board[dynamic_idx]` with a
# data-dependent index array lowers to a serialized kCustom gather on TPU
# (the round-5 device profile measured ~0.5 us per gathered element — five
# such gathers cost ~370 us of the 1.6 ms step). Indexing with a CONSTANT
# table compiles to vectorized code, so every pawn target is gathered per
# color through a constant table and the two results are selected by stm.
_SQ = np.arange(64, dtype=np.int32)
_TO1 = np.stack([np.clip(_SQ + 8, 0, 63), np.clip(_SQ - 8, 0, 63)])  # (2,64)
_TO2 = np.stack([np.clip(_SQ + 16, 0, 63), np.clip(_SQ - 16, 0, 63)])
_CAPS = np.asarray(T.PAWN_CAPTURES)  # (2, 64, 2), -1 padded
_CSQ = np.clip(_CAPS, 0, 63)
# promotion origin squares per color: white promotes from rank 6
# (48..55), black from rank 1 (8..15). Restricting the promo candidate
# section to these 8 rows shrinks the packed sort's input by 768-~96
# slots (round-5 profile: the sort dominates the step) without losing
# any candidate — promo_ok was identically False off these rows.
_PROMO_FROM = np.stack(
    [np.arange(48, 56, dtype=np.int32), np.arange(8, 16, dtype=np.int32)]
)  # (2, 8)

MAX_MOVES = T.MAX_MOVES
# crazyhouse adds up to 5 droppable types × ≤64 empty squares on top of
# ordinary board moves; its program compiles with a wider move list.
# 5*64 + MAX_MOVES is a PROVEN bound (drops can never exceed 5 types ×
# empty squares; board moves are bounded by MAX_MOVES): the compaction
# silently drops overflow beyond the cap, so an unproven cap would be a
# correctness hole — extra width only costs padding in the crazyhouse
# program
MAX_MOVES_ZH = 5 * 64 + MAX_MOVES
DROP_FLAG = 1 << 15  # move encoding: drops are DROP_FLAG | pt<<12 | to<<6 | to


def max_moves_for(variant: str) -> int:
    return MAX_MOVES_ZH if variant == "crazyhouse" else MAX_MOVES


@functools.lru_cache(maxsize=None)
def _hist_idx_tables(variant: str):
    """Per-color (n_candidates,) tables of `cand & 4095` (the from|to
    history index) for every candidate slot, as numpy constants.

    Candidate VALUES are static per side to move — every section below
    mirrors `generate_moves`' candidate assembly (same tables, same
    order) — except the two castling slots, which hold 0 here; castling
    keys are 900, and the history bonus only applies at keys 1000/1100,
    so those slots never read their (meaningless) history value. Constant
    index tables let the per-step history lookup compile to a vectorized
    static gather instead of the serialized dynamic-gather fusion the
    round-5 device profile flagged (tests/test_device_board.py
    test_hist_index_tables_match_candidates pins the mirror)."""
    rsq = np.clip(np.asarray(T.RAYS), 0, None)
    sl = (_SQ[:, None, None] | (rsq << 6)).reshape(-1)
    kn = (_SQ[:, None] | (np.clip(np.asarray(T.KNIGHT_TARGETS), 0, None) << 6)).reshape(-1)
    kg = (_SQ[:, None] | (np.clip(np.asarray(T.KING_TARGETS), 0, None) << 6)).reshape(-1)
    n_promo = 5 if variant == "antichess" else 4
    out = []
    for c in (0, 1):
        pawn_tos = np.stack(
            [_TO1[c], _TO2[c], _CSQ[c][:, 0], _CSQ[c][:, 1]], axis=1
        )
        pw = (_SQ[:, None] | (pawn_tos << 6)).reshape(-1)
        pf = _PROMO_FROM[c]
        promo_tos = np.stack(
            [_TO1[c][pf], _CSQ[c][pf, 0], _CSQ[c][pf, 1]], axis=1
        )
        pr = np.broadcast_to(
            (pf[:, None] | (promo_tos << 6))[:, :, None], (8, 3, n_promo)
        ).reshape(-1)
        secs = [sl, kn, kg, pw, pr, np.zeros(2, np.int32)]
        if variant == "crazyhouse":
            secs.append(
                np.broadcast_to(((_SQ << 6) | _SQ)[None, :], (5, 64)).reshape(-1)
            )
        out.append((np.concatenate(secs) & 4095).astype(np.int32))
    return out[0], out[1]


def _capture_key(victim_type: jnp.ndarray, attacker_type: jnp.ndarray,
                 is_capture: jnp.ndarray, promo: jnp.ndarray) -> jnp.ndarray:
    """MVV-LVA ordering key (smaller = searched first): queen promos, then
    captures by victim desc / attacker asc, then quiets."""
    mvv_lva = (5 - victim_type) * 8 + attacker_type
    key = jnp.where(is_capture, 100 + mvv_lva, 1000)
    key = jnp.where(promo == T.PROMO_Q, key - 90, key)
    return key.astype(jnp.int32)


def generate_moves(b: Board, variant: str = "standard",
                   killers=None, hist=None):
    """→ (moves (max_moves_for(variant),) sorted by ordering key, count (),
    noisy ()).

    noisy = how many leading moves are captures / queen promotions (they
    sort first) — the quiescence search expands only those.
    Moves are encoded from | to<<6 | promo<<12; castling is king-takes-rook.
    `variant` is STATIC (compiled per variant): threeCheck generates like
    standard; crazyhouse appends pocket drops (quiet, after board quiets).

    killers (2,) int32 / hist (4096,) int32: optional quiet-move ordering
    state (killer slots for this node's ply; from|to-indexed history
    counters). They reorder only the quiet tail (keys >= 900), so the
    noisy prefix the quiescence search expands is unaffected.
    """
    white, flat_moves, flat_valid, flat_keys = _candidate_space(b, variant)

    # quiet-move ordering refinements on the FULL candidate space:
    # history first (quiets 1000 → 911..1010, drops 1100 → 1011..1110 by
    # counter magnitude), then killers jump the whole quiet tail to 901
    if hist is not None:
        # candidate from|to indices are static per color (castling slots
        # excepted — their key is 900, never history-adjusted), so the
        # lookup is a constant-index gather per color + a stm select
        hw, hb = _hist_idx_tables(variant)
        hval = jnp.where(white, hist[hw], hist[hb])
        hbonus = jnp.clip(hval >> 5, 0, 99)
        flat_keys = jnp.where(flat_keys == 1000, 1010 - hbonus, flat_keys)
        flat_keys = jnp.where(flat_keys == 1100, 1110 - hbonus, flat_keys)
    if killers is not None:
        # candidates are never -1, so an empty killer slot (-1) matches
        # nothing; invalid candidates are masked out at the pack below
        is_k = (flat_moves == killers[0]) | (flat_moves == killers[1])
        flat_keys = jnp.where(is_k & (flat_keys >= 900), 901, flat_keys)

    # compaction + ordering in ONE single-array sort: pack (key << 16) |
    # move — key < 2048 and move <= 0xFFFF, so valid packs stay positive
    # and below the invalid sentinel — sort ascending, keep the first cap
    # entries. Replaces round 4's 3-array compaction sort + stable
    # ordering sort (the round-5 device profile: 350 us + the argsort
    # gather). Ties within a key break by move encoding (the previous
    # two-stage form broke them by candidate position): any deterministic
    # order is a valid move ordering, and the host oracle calls this same
    # function, so device/oracle equality is unaffected.
    cap = max_moves_for(variant)
    packed = jnp.where(
        flat_valid, (flat_keys << 16) | flat_moves,
        jnp.int32(jnp.iinfo(jnp.int32).max),
    )
    packed = jax.lax.sort(packed, dimension=0, is_stable=False)
    top = jax.lax.slice_in_dim(packed, 0, cap)
    moves = jnp.where(
        top != jnp.iinfo(jnp.int32).max, top & 0xFFFF, jnp.int32(-1)
    )
    count = jnp.minimum(jnp.sum(flat_valid), cap).astype(jnp.int32)
    # captures 100..739, queen promos down to 10; castling 900, quiets 1000
    noisy = jnp.minimum(
        jnp.sum(flat_valid & (flat_keys < 900)), cap
    ).astype(jnp.int32)
    return moves, count, noisy


def _candidate_space(b: Board, variant: str = "standard"):
    """The fixed candidate space for one lane: → (white (), flat_moves,
    flat_valid, flat_keys — each (n_candidates,)).

    Section order (mirrored by _hist_idx_tables; pinned by
    tests/test_device_board.py test_hist_index_tables_match_candidates):
    sliders (64,8,7), knights (64,8), king (64,8), pawns (64,4), promos
    (8,3,n_promo), castling (2,), then crazyhouse drops (5,64)."""
    board = b.board
    us = b.stm
    them = 1 - us
    colors = piece_color(board)  # (64,)
    types = piece_type(board)  # (64,)
    own = colors == us
    occ = board > 0
    sq_idx = jnp.arange(64, dtype=jnp.int32)

    all_moves = []
    all_valid = []
    all_keys = []
    all_iscap = []  # per-candidate capture flags (antichess compulsion)

    # ---------------------------------------------------------------- sliders
    rays = jnp.asarray(T.RAYS)  # (64, 8, 7)
    rvalid = rays >= 0
    rsq = jnp.clip(rays, 0)
    rpiece = board[rsq]  # (64, 8, 7)
    rocc = (rpiece > 0) & rvalid
    before = exclusive_cumsum_small(rocc.astype(jnp.int32), axis=2)
    reachable = rvalid & (before == 0)
    target_own = piece_color(rpiece) == us
    target_enemy = piece_color(rpiece) == them
    slides = jnp.asarray(T.SLIDER_MASK).T[board]  # (64, 8): our piece slides dir?
    valid = (
        own[:, None, None]
        & slides[:, :, None]
        & reachable
        & ~(target_own & rocc)
    )
    cands = sq_idx[:, None, None] | (rsq << 6)
    keys = _capture_key(
        jnp.maximum(piece_type(rpiece), 0), types[:, None, None],
        target_enemy & rocc, jnp.zeros_like(rpiece),
    )
    all_moves.append(cands)
    all_valid.append(valid)
    all_keys.append(keys)
    all_iscap.append(target_enemy & rocc)

    # ---------------------------------------------------------- knights, king
    for table, ptype_want in ((T.KNIGHT_TARGETS, 1), (T.KING_TARGETS, 5)):
        tg = jnp.asarray(table)  # (64, 8)
        tvalid = tg >= 0
        tsq = jnp.clip(tg, 0)
        tpiece = board[tsq]
        valid = (
            own[:, None]
            & (types == ptype_want)[:, None]
            & tvalid
            & ~(piece_color(tpiece) == us)
        )
        if variant == "atomic" and ptype_want == 5:
            # atomic kings never capture (the capture would explode them)
            valid &= ~(piece_color(tpiece) == them)
        cands = sq_idx[:, None] | (tsq << 6)
        keys = _capture_key(
            jnp.maximum(piece_type(tpiece), 0),
            jnp.full_like(tpiece, ptype_want),
            piece_color(tpiece) == them,
            jnp.zeros_like(tpiece),
        )
        all_moves.append(cands)
        all_valid.append(valid)
        all_keys.append(keys)
        all_iscap.append(piece_color(tpiece) == them)

    # ------------------------------------------------------------------ pawns
    white = us == 0
    our_pawn = own & (types == 0)
    ranks = sq_idx >> 3
    start_rank = jnp.where(white, 1, 6)
    pre_promo = ranks == jnp.where(white, 6, 1)

    # every target square/piece via constant-table gathers selected by stm
    # (see _TO1/_CAPS above for why not board[dynamic_idx])
    to1 = jnp.where(white, jnp.asarray(_TO1[0]), jnp.asarray(_TO1[1]))
    b_to1 = jnp.where(white, board[_TO1[0]], board[_TO1[1]])
    to1_ok = our_pawn & (b_to1 == 0)
    to2 = jnp.where(white, jnp.asarray(_TO2[0]), jnp.asarray(_TO2[1]))
    b_to2 = jnp.where(white, board[_TO2[0]], board[_TO2[1]])
    dbl_rank = ranks == start_rank
    if variant == "horde":
        # horde pawns on the back rank may also double-push
        dbl_rank |= white & (ranks == 0)
    to2_ok = to1_ok & dbl_rank & (b_to2 == 0)

    caps = jnp.where(white, jnp.asarray(_CAPS[0]), jnp.asarray(_CAPS[1]))
    cvalid = caps >= 0
    csq = jnp.where(white, jnp.asarray(_CSQ[0]), jnp.asarray(_CSQ[1]))
    cpiece = jnp.where(white, board[_CSQ[0]], board[_CSQ[1]])
    cap_ok = (
        our_pawn[:, None]
        & cvalid
        & ((piece_color(cpiece) == them) | (csq == b.ep))
    )

    # non-promotion pawn moves: [push1, push2, capL, capR]
    pawn_tos = jnp.stack([to1, to2, csq[:, 0], csq[:, 1]], axis=1)  # (64,4)
    b_pawn_tos = jnp.stack(
        [b_to1, b_to2, cpiece[:, 0], cpiece[:, 1]], axis=1
    )  # board[pawn_tos] assembled from the constant-table gathers
    pawn_ok = jnp.stack(
        [to1_ok & ~pre_promo, to2_ok, cap_ok[:, 0] & ~pre_promo[:],
         cap_ok[:, 1] & ~pre_promo[:]], axis=1,
    )
    cands = sq_idx[:, None] | (pawn_tos << 6)
    vict = jnp.maximum(piece_type(b_pawn_tos), 0)
    is_cap = jnp.stack(
        [jnp.zeros(64, bool), jnp.zeros(64, bool), cap_ok[:, 0], cap_ok[:, 1]],
        axis=1,
    )
    keys = _capture_key(vict, jnp.zeros_like(vict), is_cap, jnp.zeros_like(vict))
    all_moves.append(cands)
    all_valid.append(pawn_ok)
    all_keys.append(keys)
    all_iscap.append(is_cap)

    # promotions: [push, capL, capR] × 4 promo pieces (5 in antichess,
    # which allows promotion to king). Only the 8 pre-promotion-rank
    # squares can promote, so the section gathers those rows through the
    # _PROMO_FROM constant table (static per color → vectorized gather,
    # same trick as _TO1/_CAPS) and the pre_promo factor — identically
    # True on the selected rows — drops out. 768 → 8*3*n_promo sort slots.
    def sel8(a):
        return jnp.where(white, a[_PROMO_FROM[0]], a[_PROMO_FROM[1]])

    promo_from = sel8(sq_idx)  # (8,)
    to1_8, b_to1_8, to1_ok_8 = sel8(to1), sel8(b_to1), sel8(to1_ok)
    csq_8, cpiece_8, cap_ok_8 = sel8(csq), sel8(cpiece), sel8(cap_ok)
    promo_tos = jnp.stack([to1_8, csq_8[:, 0], csq_8[:, 1]], axis=1)  # (8, 3)
    b_promo_tos = jnp.stack([b_to1_8, cpiece_8[:, 0], cpiece_8[:, 1]], axis=1)
    promo_ok_base = jnp.stack(
        [to1_ok_8, cap_ok_8[:, 0], cap_ok_8[:, 1]], axis=1
    )
    promo_list = [T.PROMO_N, T.PROMO_B, T.PROMO_R, T.PROMO_Q]
    if variant == "antichess":
        promo_list.append(T.PROMO_K)
    promos = jnp.asarray(promo_list, dtype=jnp.int32)
    cands = (
        promo_from[:, None, None]
        | (promo_tos[:, :, None] << 6)
        | (promos[None, None, :] << 12)
    )
    valid = promo_ok_base[:, :, None] & jnp.ones((1, 1, len(promo_list)), bool)
    vict = jnp.maximum(piece_type(b_promo_tos), 0)[:, :, None]
    is_cap = jnp.stack([jnp.zeros(8, bool), cap_ok_8[:, 0], cap_ok_8[:, 1]], axis=1)
    keys = _capture_key(
        jnp.broadcast_to(vict, cands.shape),
        jnp.zeros_like(cands),
        jnp.broadcast_to(is_cap[:, :, None], cands.shape),
        jnp.broadcast_to(promos[None, None, :], cands.shape),
    )
    all_moves.append(cands)
    all_valid.append(valid)
    all_keys.append(keys)
    all_iscap.append(jnp.broadcast_to(is_cap[:, :, None], cands.shape))

    # --------------------------------------------------------------- castling
    ksq = king_square(board, us)
    ksq_c = jnp.maximum(ksq, 0)
    rook_slots = jnp.take(b.castling, jnp.arange(2, dtype=jnp.int32) + us * 2)  # [kingside, queenside]

    def castle_ok(slot):
        rsq = rook_slots[slot]
        has = (rsq >= 0) & (ksq >= 0)
        rsq_c = jnp.clip(rsq, 0, 63)
        rank_base = jnp.where(us == 0, 0, 56)
        kingside = slot == 0
        k_dest = rank_base + jnp.where(kingside, 6, 2)
        r_dest = rank_base + jnp.where(kingside, 5, 3)
        # all squares the king or rook crosses (inclusive spans), minus the
        # two moving pieces, must be empty
        lo_k = jnp.minimum(ksq_c, k_dest)
        hi_k = jnp.maximum(ksq_c, k_dest)
        lo_r = jnp.minimum(rsq_c, r_dest)
        hi_r = jnp.maximum(rsq_c, r_dest)
        span = ((sq_idx >= lo_k) & (sq_idx <= hi_k)) | (
            (sq_idx >= lo_r) & (sq_idx <= hi_r)
        )
        span = span & (sq_idx != ksq_c) & (sq_idx != rsq_c)
        empty_ok = ~jnp.any(span & occ)
        # king path (origin..dest inclusive, ≤7 contiguous squares on the
        # back rank) must not be attacked, tested with king and castling
        # rook lifted off the board — via the whole-board attack map with
        # those two squares skipped for slider blocking (bit-identical to
        # the old per-square is_attacked on the lifted board; see
        # board.attack_map's profile note for why)
        att = attack_map(board, them, skip_own1=ksq_c, skip_own2=rsq_c)
        kpath = (sq_idx >= lo_k) & (sq_idx <= hi_k)
        safe = ~jnp.any(att & kpath)
        return has & empty_ok & safe, sq_idx[0] * 0 + (ksq_c | (rsq_c << 6))

    ok0, mv0 = castle_ok(jnp.int32(0))
    ok1, mv1 = castle_ok(jnp.int32(1))
    all_moves.append(jnp.stack([mv0, mv1]))
    all_valid.append(jnp.stack([ok0, ok1]))
    all_keys.append(jnp.full((2,), 900, dtype=jnp.int32))
    all_iscap.append(jnp.zeros(2, bool))

    # ------------------------------------------------------ crazyhouse drops
    if variant == "crazyhouse":
        pocket = jax.lax.dynamic_slice(
            b.extra, (us * 5,), (5,)
        )  # (5,) our P N B R Q counts
        empty = board == 0  # (64,)
        pt = jnp.arange(5, dtype=jnp.int32)
        ranks8 = sq_idx >> 3
        pawn_ok_sq = (ranks8 != 0) & (ranks8 != 7)
        valid = (
            (pocket > 0)[:, None]
            & empty[None, :]
            & jnp.where(pt[:, None] == 0, pawn_ok_sq[None, :], True)
        )  # (5, 64)
        cands = DROP_FLAG | (pt[:, None] << 12) | (sq_idx[None, :] << 6) | sq_idx[None, :]
        all_moves.append(cands)
        all_valid.append(valid)
        # drops search after ordinary quiet moves
        all_keys.append(jnp.full((5, 64), 1100, dtype=jnp.int32))
        all_iscap.append(jnp.zeros((5, 64), bool))

    flat_moves = jnp.concatenate([m.reshape(-1) for m in all_moves])
    flat_valid = jnp.concatenate([v.reshape(-1) for v in all_valid])
    flat_keys = jnp.concatenate([k.reshape(-1) for k in all_keys])
    if variant == "antichess":
        # capture compulsion: when any capture exists, ONLY captures are
        # legal (en-passant counts — cap_ok folded it into is_cap above)
        flat_iscap = jnp.concatenate([c.reshape(-1) for c in all_iscap])
        any_cap = jnp.any(flat_valid & flat_iscap)
        flat_valid &= jnp.where(any_cap, flat_iscap, True)
    return white, flat_moves, flat_valid, flat_keys


v_generate_moves = jax.vmap(generate_moves, in_axes=(Board(0, 0, 0, 0, 0, 0),))
