"""Device board representation and move making.

The engine-process boundary of the reference (UCI pipes into Stockfish,
reference: src/stockfish.rs:124-143) becomes a host→device dispatch here:
positions live as SoA tensors and moves are applied by scatter, `vmap`-able
over the batch/lane dimension.

Board tensor layout (one lane):
  board:    (64,) int32, piece codes (tables.py: 0 empty, 1-6 white, 7-12 black)
  stm:      ()   int32, 0 white / 1 black
  ep:       ()   int32, en-passant target square or -1
  castling: (4,) int32, rook squares with castling rights, -1 if gone;
            order [white-kingside, white-queenside, black-kingside,
            black-queenside] (chess960-ready: stores actual rook squares)
  halfmove: ()   int32
"""
from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from ..chess.position import Position
from ..chess.types import scan
from . import tables as T


class Board(NamedTuple):
    board: jnp.ndarray  # (..., 64) int32
    stm: jnp.ndarray  # (...,) int32
    ep: jnp.ndarray  # (...,) int32
    castling: jnp.ndarray  # (..., 4) int32
    halfmove: jnp.ndarray  # (...,) int32
    # variant side-state, zeros for standard chess (EXTRA_* layout below):
    # [0:2]   threeCheck: checks delivered by white, black
    # [0:10]  crazyhouse: pocket counts [white P N B R Q, black P N B R Q]
    # [10:12] crazyhouse: promoted-piece bitboard (low word, high word)
    extra: jnp.ndarray  # (..., 12) int32


EXTRA_W = 12
EXTRA_CHECKS = 0  # +color
EXTRA_POCKET = 0  # +color*5 + ptype
EXTRA_PROMOTED = 10  # +word


def board_array(pos: Position) -> np.ndarray:
    """Host Position → (64,) numpy piece-code array (no device traffic —
    dataset builders iterate millions of positions and a per-position
    device put through the remote-TPU tunnel costs ~ms each)."""
    board = np.zeros(64, dtype=np.int32)
    for color in (0, 1):
        for ptype in range(6):
            for sq in scan(pos.bbs[color][ptype]):
                board[sq] = 1 + ptype + 6 * color
    return board


def from_position(pos: Position) -> Board:
    """Host Position → single-lane Board (numpy)."""
    board = board_array(pos)
    castling = np.full(4, -1, dtype=np.int32)
    # variants without castling (antichess, racingKings) never carry
    # rights on device — the host parses-but-ignores any FEN rights
    # (Position.has_castling), and the device movegen would otherwise
    # generate castle moves from them
    if getattr(pos, "has_castling", True):
        for color in (0, 1):
            ksq = pos.king_sq(color)
            back = 0xFF if color == 0 else 0xFF << 56
            rights = pos.castling & back
            for rsq in scan(rights):
                if ksq is None:
                    continue
                side = 0 if rsq > ksq else 1
                castling[color * 2 + side] = rsq
    extra = np.zeros(EXTRA_W, dtype=np.int32)
    if getattr(pos, "variant", "standard") == "threeCheck":
        for color in (0, 1):
            extra[EXTRA_CHECKS + color] = pos.checks_given[color]
    elif getattr(pos, "variant", "standard") == "crazyhouse":
        for color in (0, 1):
            for ptype in range(5):
                extra[EXTRA_POCKET + color * 5 + ptype] = pos.pockets[color][ptype]
        for w in (0, 1):
            word = (pos.promoted >> (32 * w)) & 0xFFFFFFFF
            extra[EXTRA_PROMOTED + w] = word - (1 << 32) if word >= 1 << 31 else word
    return Board(
        board=jnp.asarray(board),
        stm=jnp.asarray(np.int32(pos.turn)),
        ep=jnp.asarray(np.int32(pos.ep_square if pos.ep_square is not None else -1)),
        castling=jnp.asarray(castling),
        halfmove=jnp.asarray(np.int32(pos.halfmove)),
        extra=jnp.asarray(extra),
    )


def stack_boards(boards) -> Board:
    """List of single-lane Boards → batched Board."""
    return Board(*[jnp.stack([getattr(b, f) for b in boards]) for f in Board._fields])


def piece_color(code: jnp.ndarray) -> jnp.ndarray:
    """0 white, 1 black, -1 empty."""
    return jnp.where(code == 0, -1, jnp.where(code <= 6, 0, 1))


def piece_type(code: jnp.ndarray) -> jnp.ndarray:
    """0..5 = P N B R Q K, -1 empty."""
    return jnp.where(code == 0, -1, (code - 1) % 6)


def exclusive_cumsum_small(x: jnp.ndarray, axis: int) -> jnp.ndarray:
    """Exclusive integer cumsum along a SMALL static axis via log2(n)
    shift-adds (Hillis-Steele). Bit-identical to
    `jnp.cumsum(x, axis) - x` for integer inputs; exists because XLA:TPU
    lowers cumsum to reduce-window, which the round-4 device profile
    showed dominating `is_attacked`/movegen at these tiny axis lengths."""
    n = x.shape[axis]
    acc = x
    shift = 1
    while shift < n:
        pad = [(0, 0)] * x.ndim
        pad[axis] = (shift, 0)
        sliced = jax.lax.slice_in_dim(acc, 0, n - shift, axis=axis)
        acc = acc + jnp.pad(sliced, pad)
        shift *= 2
    return acc - x


# RAY_DIRS order: E, N, NE, NW, W, S, SW, SE → orthogonal dirs 0,1,4,5
_ORTHO_DIR = np.array([True, True, False, False, True, True, False, False])


def attack_map(board64: jnp.ndarray, by_color: jnp.ndarray,
               skip_own1=None, skip_own2=None) -> jnp.ndarray:
    """(64,) bool: every square attacked by `by_color`, in one pass.

    skip_own1/skip_own2 (optional square indices) are treated as EMPTY for
    slider blocking — the castling test lifts the moving king and rook off
    the board. PRECONDITION: skipped squares must hold pieces of
    `by_color`'s OPPONENT (the castler's own king/rook). The skip is only
    applied to slider occupancy; the king/knight/pawn attacker terms still
    read the unskipped board, so a skipped square holding one of
    `by_color`'s own king/knight/pawn attackers would produce a phantom
    attack that lifted-board semantics would not. The castling caller
    satisfies this by construction; any new caller must too.

    Replaces per-square `is_attacked` queries in the search step: the
    round-4 device profile showed the castling path's 14 vmapped
    single-square queries costing ~930 us/step in serialized gather
    fusions, while this whole-board form is elementwise logic over the
    same (64, 8, 7) ray-piece tensor the move generator already gathers
    (shared by XLA CSE). Unbatched; vmap for lanes.
    """
    rsq_t = jnp.asarray(T.RAYS)  # (64, 8, 7) static
    rvalid = rsq_t >= 0
    rpiece = board64[jnp.clip(rsq_t, 0)]  # same gather as movegen → CSE
    rocc = (rpiece > 0) & rvalid
    if skip_own1 is not None:
        rocc &= rsq_t != skip_own1
    if skip_own2 is not None:
        rocc &= rsq_t != skip_own2
    before = exclusive_cumsum_small(rocc.astype(jnp.int32), axis=2)
    is_first = rocc & (before == 0)
    # enemy slider sliding along this (symmetric) direction — elementwise
    # piece-type tests, not a SLIDER_MASK value-gather
    pt = piece_type(rpiece)
    enemy = piece_color(rpiece) == by_color
    ortho = jnp.asarray(_ORTHO_DIR)[None, :, None]
    slider_ok = (pt == 4) | ((pt == 3) & ortho) | ((pt == 2) & ~ortho)
    slider_hit = jnp.any(is_first & slider_ok & enemy, axis=(1, 2))

    king_code = jnp.where(by_color == 0, T.W_KING, T.B_KING)
    king_hit = jnp.any(rvalid[:, :, 0] & (rpiece[:, :, 0] == king_code), axis=1)

    kt = jnp.asarray(T.KNIGHT_TARGETS)  # (64, 8) static
    ktp = jnp.where(kt >= 0, board64[jnp.clip(kt, 0)], 0)
    knight_code = jnp.where(by_color == 0, T.W_KNIGHT, T.B_KNIGHT)
    knight_hit = jnp.any(ktp == knight_code, axis=1)

    # pawns of by_color attacking sq sit on the squares a pawn of the
    # *opposite* color on sq would attack. Gather through each CONSTANT
    # per-color table and select by color — board64[dynamic_idx] lowers to
    # a serialized per-element gather on TPU (round-5 device profile).
    ps0 = np.asarray(T.PAWN_CAPTURES[1])  # (64, 2) static
    ps1 = np.asarray(T.PAWN_CAPTURES[0])
    ps = jnp.where(by_color == 0, jnp.asarray(ps0), jnp.asarray(ps1))
    psp_w = board64[np.clip(ps0, 0, 63)]
    psp_b = board64[np.clip(ps1, 0, 63)]
    psp = jnp.where(ps >= 0, jnp.where(by_color == 0, psp_w, psp_b), 0)
    pawn_code = jnp.where(by_color == 0, T.W_PAWN, T.B_PAWN)
    pawn_hit = jnp.any(psp == pawn_code, axis=1)

    return slider_hit | king_hit | knight_hit | pawn_hit


def is_attacked(board64: jnp.ndarray, sq: jnp.ndarray, by_color: jnp.ndarray) -> jnp.ndarray:
    """Is `sq` attacked by `by_color` on `board64`? Single-square query used
    for check detection and castling-path tests; O(8 dirs × 7 steps) gathers.
    All args unbatched (vmap for lanes)."""
    rays = jnp.asarray(T.RAYS)[sq]  # (8, 7)
    valid = rays >= 0
    ray_pieces = jnp.where(valid, board64[jnp.clip(rays, 0)], 0)  # (8, 7)
    occupied = ray_pieces > 0
    # first occupied step along each ray. exclusive_cumsum_small instead of
    # jnp.cumsum: XLA:TPU lowers cumsum to a reduce-window that cost
    # ~230 us/step across this function's call sites in the round-4 device
    # profile; 3 shifted adds are fused elementwise code.
    before = exclusive_cumsum_small(occupied.astype(jnp.int32), axis=1)
    is_first = occupied & (before == 0)
    slider_ok = jnp.asarray(T.SLIDER_MASK)[
        jnp.arange(8, dtype=jnp.int32)[:, None], ray_pieces
    ]  # (8, 7) does this piece slide along this dir
    enemy = piece_color(ray_pieces) == by_color
    slider_hit = jnp.any(is_first & slider_ok & enemy & valid)

    # king adjacency: first step of each ray
    first_sq_piece = ray_pieces[:, 0]
    king_code = jnp.where(by_color == 0, T.W_KING, T.B_KING)
    king_hit = jnp.any(valid[:, 0] & (first_sq_piece == king_code))

    knight_tgts = jnp.asarray(T.KNIGHT_TARGETS)[sq]  # (8,)
    kvalid = knight_tgts >= 0
    knight_code = jnp.where(by_color == 0, T.W_KNIGHT, T.B_KNIGHT)
    knight_hit = jnp.any(kvalid & (board64[jnp.clip(knight_tgts, 0)] == knight_code))

    # pawns of by_color attacking sq sit on the squares a pawn of the
    # *opposite* color on sq would attack
    pawn_srcs = jnp.asarray(T.PAWN_CAPTURES)[1 - by_color, sq]  # (2,)
    pvalid = pawn_srcs >= 0
    pawn_code = jnp.where(by_color == 0, T.W_PAWN, T.B_PAWN)
    pawn_hit = jnp.any(pvalid & (board64[jnp.clip(pawn_srcs, 0)] == pawn_code))

    return slider_hit | king_hit | knight_hit | pawn_hit


def king_square(board64: jnp.ndarray, color: jnp.ndarray) -> jnp.ndarray:
    """Square of `color`'s king, or -1 if absent (unbatched)."""
    king_code = jnp.where(color == 0, T.W_KING, T.B_KING)
    mask = board64 == king_code
    return jnp.where(jnp.any(mask), jnp.argmax(mask), -1)


def in_check(b: Board) -> jnp.ndarray:
    king_code = jnp.where(b.stm == 0, T.W_KING, T.B_KING)
    return jnp.any(attack_map(b.board, 1 - b.stm) & (b.board == king_code))


# variant-terminal kinds, from the side to move's perspective
TERM_NONE, TERM_LOSS, TERM_WIN, TERM_DRAW = 0, 1, 2, 3


def node_rules(b: Board, variant: str = "standard"):
    """Per-node legality + variant-terminal classification (unbatched).

    The reference delegates these rules to Fairy-Stockfish
    (src/stockfish.rs:245-260 sets UCI_Variant); here each variant is a
    statically compiled branch shared by the device search step and the
    host oracle. Host rule spec: chess/variants.py. Returns:
    - parent_illegal: the move leading HERE violated the mover's duty
      (left its king en prise; exploded its own king in atomic; gave
      check in racingKings). The search refutes the parent move.
    - checked: side to move is in check (mate vs stalemate scoring).
    - term_kind: TERM_* game end by variant rule at this node
      (TERM_LOSS → -(MATE-ply), TERM_WIN → MATE-ply, TERM_DRAW → 0).
    """
    us = b.stm
    them = 1 - us
    our_k = king_square(b.board, us)
    their_k = king_square(b.board, them)
    their_k_c = jnp.maximum(their_k, 0)
    # whole-board attack maps (one per color) instead of per-king
    # is_attacked queries: elementwise over the ray tensors the move
    # generator gathers anyway (see attack_map). A missing king's
    # board==code one-hot is all-False, so the our_k>=0 guard is implicit.
    att_us = attack_map(b.board, us)
    att_them = attack_map(b.board, them)
    our_king_code = jnp.where(us == 0, T.W_KING, T.B_KING)
    their_king_code = jnp.where(them == 0, T.W_KING, T.B_KING)
    their_k_attacked = jnp.any(att_us & (b.board == their_king_code))
    self_check = (their_k < 0) | their_k_attacked
    checked = jnp.any(att_them & (b.board == our_king_code))
    kind = jnp.int32(TERM_NONE)

    if variant == "antichess":
        # no check concept, kings are ordinary pieces; running out of
        # moves/pieces WINS (handled at move-exhaustion, not here)
        return jnp.bool_(False), jnp.bool_(False), kind
    if variant == "atomic":
        adj = (
            (their_k >= 0) & (our_k >= 0)
            & jnp.any(jnp.asarray(T.KING_TARGETS)[their_k_c] == our_k)
        )
        lost = our_k < 0  # mover exploded our king: mover wins — even if
        # its own king exploded too (host: _move_is_safe checks the
        # enemy king first)
        illegal = ~lost & (
            (their_k < 0)
            | (their_k_attacked & ~adj)
        )
        checked = checked & ~adj  # adjacent kings can never be in check
        kind = jnp.where(lost, TERM_LOSS, kind)
        return illegal, checked, kind
    if variant == "horde":
        # white is the kingless horde: no check duty/right for white
        illegal = jnp.where(them == 1, self_check, False)
        checked = jnp.where(us == 1, checked, False)
        white_dead = ~jnp.any(piece_color(b.board) == 0)
        kind = jnp.where((us == 0) & white_dead, TERM_LOSS, kind)
        return illegal, checked, kind
    if variant == "kingOfTheHill":
        hill = (
            (their_k == 27) | (their_k == 28)
            | (their_k == 35) | (their_k == 36)
        )
        kind = jnp.where(hill, TERM_LOSS, kind)  # mover reached the hill
        return self_check, checked, kind
    if variant == "racingKings":
        our8 = our_k >= 56
        their8 = their_k >= 56
        illegal = self_check | checked  # giving check is illegal
        # white moves first, so black gets one rejoinder: white-on-goal
        # is only a win once it is white's move again; black-on-goal wins
        # immediately; both → draw (host: RacingKings._variant_outcome)
        kind = jnp.where(
            our8 & their8, TERM_DRAW,
            jnp.where(
                their8 & (them == 1), TERM_LOSS,
                jnp.where(our8 & (us == 0), TERM_WIN, kind),
            ),
        )
        return illegal, jnp.bool_(False), kind
    if variant == "threeCheck":
        them_checks = jnp.where(
            us == 0, b.extra[EXTRA_CHECKS + 1], b.extra[EXTRA_CHECKS + 0]
        )
        kind = jnp.where(them_checks >= 3, TERM_LOSS, kind)
        return self_check, checked, kind
    return self_check, checked, kind  # standard / chess960 / crazyhouse


def make_move(b: Board, move: jnp.ndarray, variant: str = "standard") -> Board:
    """Apply an encoded move (from | to<<6 | promo<<12) to one lane.

    Castling is encoded king-takes-own-rook (matching the host library and
    UCI_Chess960 semantics); en passant and promotion are inferred from the
    board, so no flag bits are needed. `variant` is a STATIC flag: each
    variant compiles its own program, keeping the standard path free of
    variant branches (reference analog: Fairy-Stockfish's variant rules
    behind `UCI_Variant`, src/stockfish.rs:245-260). Crazyhouse drops are
    encoded as DROP_FLAG | ptype<<12 | to<<6 | to.
    """
    frm = move & 63
    to = (move >> 6) & 63
    promo = (move >> 12) & 7
    is_drop = ((move >> 15) & 1) == 1 if variant == "crazyhouse" else None

    board = b.board
    piece = board[frm]
    target = board[to]
    us = b.stm
    them = 1 - us

    is_pawn = piece_type(piece) == 0
    is_king = piece_type(piece) == 5
    is_castle = is_king & (piece_color(target) == us) & (piece_type(target) == 3)
    if is_drop is not None:
        is_pawn &= ~is_drop
        is_king &= ~is_drop
        is_castle &= ~is_drop

    # en passant capture: pawn moves diagonally onto the empty ep square
    is_ep = is_pawn & (to == b.ep) & (target == 0) & ((to & 7) != (frm & 7))
    ep_victim = jnp.where(us == 0, to - 8, to + 8)
    ep_victim_c = jnp.clip(ep_victim, 0, 63)

    # (for drops frm == to and the square is empty, so clearing is a no-op)
    new_board = board.at[frm].set(0)
    new_board = jnp.where(
        is_ep, new_board.at[ep_victim_c].set(0), new_board
    )

    # normal placement (promotion replaces the pawn)
    promo_piece = jnp.asarray(T.PROMO_TO_PIECE)[jnp.clip(promo, 0, 5)] + 6 * us
    placed = jnp.where(promo > 0, promo_piece, piece)
    if is_drop is not None:
        # dropped piece: promo bits carry the ptype (0..4 = P..Q)
        drop_piece = 1 + jnp.clip(promo, 0, 4) + 6 * us
        placed = jnp.where(is_drop, drop_piece, placed)
    normal_board = new_board.at[to].set(placed)

    # castling: clear rook square too, then place king on g/c and rook on f/d
    rank_base = jnp.where(us == 0, 0, 56)
    kingside = to > frm
    k_dest = rank_base + jnp.where(kingside, 6, 2)
    r_dest = rank_base + jnp.where(kingside, 5, 3)
    castle_board = new_board.at[to].set(0)
    castle_board = castle_board.at[k_dest].set(piece)
    castle_board = castle_board.at[r_dest].set(jnp.where(us == 0, T.W_ROOK, T.B_ROOK))

    out_board = jnp.where(is_castle, castle_board, normal_board)

    # castling rights: clear own on king move; clear a rook square on touch
    cast = b.castling
    own_slots = jnp.arange(4, dtype=jnp.int32) // 2 == us
    cast = jnp.where(is_king & own_slots, -1, cast)
    touched = (cast == frm) | (cast == to)
    if is_drop is not None:
        touched &= ~is_drop
    cast = jnp.where(touched, -1, cast)

    # new ep square on double pawn push
    dbl = is_pawn & (jnp.abs(to - frm) == 16)
    if variant == "horde":
        # back-rank doubles (horde pawns on rank 1) set no ep square
        dbl &= ~((us == 0) & ((frm >> 3) == 0))
    new_ep = jnp.where(dbl, (frm + to) // 2, -1)

    capture = (piece_color(target) == them) | is_ep

    if variant == "atomic":
        # explosion: a capture removes the capturer and every NON-PAWN
        # piece within one king-step of the landing square (the captured
        # piece itself is removed regardless); exploded rook squares lose
        # their castling rights (host spec: chess/variants.py
        # AtomicPosition._post_move_hook)
        zone_sqs = jnp.asarray(T.KING_TARGETS)[to]  # (8,), -1 padded
        # one-hot compare, not scatter: a clipped -1 pad would write False
        # over square a1 (nondeterministically vs a real True at duplicate
        # index 0), letting an a1 piece survive an explosion
        sq64 = jnp.arange(64, dtype=jnp.int32)
        in_zone = jnp.any(
            (sq64[None, :] == zone_sqs[:, None]) & (zone_sqs >= 0)[:, None],
            axis=0,
        )
        in_zone = in_zone | (sq64 == to)
        exploded = jnp.where(
            in_zone & (piece_type(out_board) != 0), 0, out_board
        )
        # the capturer itself is always removed, pawn or not
        exploded = exploded.at[to].set(0)
        out_board = jnp.where(capture, exploded, out_board)
        cast = jnp.where(
            capture & (cast >= 0) & in_zone[jnp.clip(cast, 0, 63)], -1, cast
        )
        # a side whose king explodes has no castling rights (the device
        # representation, like from_position, ties rights to a live king)
        wk_alive = jnp.any(out_board == T.W_KING)
        bk_alive = jnp.any(out_board == T.B_KING)
        slot_alive = jnp.where(jnp.arange(4, dtype=jnp.int32) < 2, wk_alive, bk_alive)
        cast = jnp.where(capture & ~slot_alive, -1, cast)
    pawnish = is_pawn
    if is_drop is not None:
        # a pawn drop is a pawn move (resets the fifty-move clock)
        pawnish |= is_drop & (promo == 0)
    new_halfmove = jnp.where(pawnish | capture, 0, b.halfmove + 1)

    extra = b.extra
    if variant == "threeCheck":
        # did this move give check? (mover attacks the enemy king)
        ek = king_square(out_board, them)
        gave_check = (ek >= 0) & is_attacked(out_board, jnp.maximum(ek, 0), us)
        extra = extra.at[EXTRA_CHECKS + us].add(
            jnp.where(gave_check, 1, 0)
        )
    elif variant == "crazyhouse":
        # promoted-piece bit transport: bit(sq) lives in extra[10 + sq//32]
        def get_bit(e, sq):
            return (e[EXTRA_PROMOTED + sq // 32] >> (sq % 32)) & 1

        def with_bit(e, sq, val):
            w = EXTRA_PROMOTED + sq // 32
            bit = jnp.int32(1) << (sq % 32)
            return e.at[w].set(
                jnp.where(val == 1, e[w] | bit, e[w] & ~bit)
            )

        was_promoted_mover = get_bit(extra, frm) & jnp.where(is_drop, 0, 1)
        cap_sq = jnp.where(is_ep, ep_victim_c, to)
        victim_code = jnp.where(is_ep, board[ep_victim_c], target)
        real_capture = capture & ~is_castle & ~is_drop
        cap_promoted = get_bit(extra, cap_sq) & jnp.where(real_capture, 1, 0)
        # pocket gains the captured piece, demoted to pawn if promoted
        cap_type = jnp.where(
            cap_promoted == 1, 0, jnp.maximum(piece_type(victim_code), 0)
        )
        pocket_slot = EXTRA_POCKET + us * 5 + jnp.clip(cap_type, 0, 4)
        extra = extra.at[pocket_slot].add(jnp.where(real_capture, 1, 0))
        # pocket pays for a drop
        drop_slot = EXTRA_POCKET + us * 5 + jnp.clip(promo, 0, 4)
        extra = extra.at[drop_slot].add(jnp.where(is_drop, -1, 0))
        # bits: clear mover origin + capture square, then set destination
        # when the arriving piece is promoted (fresh promotion or transport)
        extra = with_bit(extra, frm, jnp.int32(0))
        extra = with_bit(
            extra, cap_sq, jnp.where(real_capture, 0, get_bit(extra, cap_sq))
        )
        dest_promoted = jnp.where(
            is_drop, 0, jnp.where(promo > 0, 1, was_promoted_mover)
        )
        extra = with_bit(extra, to, dest_promoted)

    return Board(
        board=out_board,
        stm=them,
        ep=new_ep,
        castling=cast,
        halfmove=new_halfmove,
        extra=extra,
    )


def move_piece_changes(b: Board, move: jnp.ndarray, variant: str = "standard"):
    """The ≤4 piece placements/removals a move causes, as fixed slots
    (codes (4,), squares (4,), signs (4,)); code 0 marks an unused slot.

    Feeds the incremental NNUE accumulator update (board768 path): castling
    touches 4 slots (king out/in, rook out/in), captures/promotions ≤3,
    crazyhouse drops 1 (pockets are invisible to board features).
    Slot layout: [mover out, capture out, mover in, rook in(castle)].
    """
    frm = move & 63
    to = (move >> 6) & 63
    promo = (move >> 12) & 7
    is_drop = ((move >> 15) & 1) == 1 if variant == "crazyhouse" else None
    board = b.board
    piece = board[frm]
    target = board[to]
    us = b.stm

    is_pawn = piece_type(piece) == 0
    is_king = piece_type(piece) == 5
    is_castle = is_king & (piece_color(target) == us) & (piece_type(target) == 3)
    if is_drop is not None:
        is_pawn &= ~is_drop
        is_king &= ~is_drop
        is_castle &= ~is_drop
    is_ep = is_pawn & (to == b.ep) & (target == 0) & ((to & 7) != (frm & 7))
    ep_victim = jnp.where(us == 0, to - 8, to + 8)

    # slot 0: mover leaves frm (unused for drops: nothing leaves the board)
    c0, s0, g0 = piece, frm, jnp.int32(-1)
    if is_drop is not None:
        c0 = jnp.where(is_drop, 0, c0)
    # slot 1: captured piece leaves (normal capture, ep victim, or the
    # castling rook leaving its origin square)
    cap_code = jnp.where(
        is_castle, target,
        jnp.where(is_ep, board[jnp.clip(ep_victim, 0, 63)], target),
    )
    cap_sq = jnp.where(is_ep, jnp.clip(ep_victim, 0, 63), to)
    c1 = jnp.where(piece_color(cap_code) >= 0, cap_code, 0)
    c1 = jnp.where(is_castle | is_ep | (piece_color(target) == 1 - us), c1, 0)
    s1, g1 = cap_sq, jnp.int32(-1)
    # slot 2: mover arrives (promoted piece, or king to its castle square)
    rank_base = jnp.where(us == 0, 0, 56)
    kingside = to > frm
    k_dest = rank_base + jnp.where(kingside, 6, 2)
    promo_piece = jnp.asarray(T.PROMO_TO_PIECE)[jnp.clip(promo, 0, 5)] + 6 * us
    placed = jnp.where(promo > 0, promo_piece, piece)
    if is_drop is not None:
        placed = jnp.where(is_drop, 1 + jnp.clip(promo, 0, 4) + 6 * us, placed)
    c2 = placed
    s2 = jnp.where(is_castle, k_dest, to)
    g2 = jnp.int32(1)
    # slot 3: castling rook arrives
    r_dest = rank_base + jnp.where(kingside, 5, 3)
    c3 = jnp.where(is_castle, jnp.where(us == 0, T.W_ROOK, T.B_ROOK), 0)
    s3, g3 = r_dest, jnp.int32(1)

    codes = jnp.stack([c0, c1, c2, c3])
    sqs = jnp.stack([s0, s1, s2, s3])
    signs = jnp.stack([g0, g1, g2, g3])
    return codes, sqs, signs


# batched versions
_B_AXES = Board(0, 0, 0, 0, 0, 0)
v_make_move = jax.vmap(make_move, in_axes=(_B_AXES, 0))
v_in_check = jax.vmap(in_check, in_axes=(_B_AXES,))


def to_position_debug(b: Board) -> str:
    """ASCII board for debugging (single lane, host)."""
    chars = ".PNBRQKpnbrqk"
    arr = np.asarray(b.board)
    rows = []
    for rank in range(7, -1, -1):
        rows.append(" ".join(chars[arr[rank * 8 + f]] for f in range(8)))
    return "\n".join(rows)
