"""Shared transposition table in HBM for the lockstep batched search.

The reference's engines keep a per-process TT inside Stockfish's C++
(fishnet sizes it via engine defaults; reference: README.md:76 "~64 MiB
RAM per core" is mostly this table). Here ONE table is shared by every
search lane on the chip: entries live in HBM arrays carried through the
search while_loop, probed/stored with batched gathers/scatters.

Race tolerance (SURVEY.md §7.3 "lock-free XOR trick"): a batched scatter
with colliding indices may interleave lanes arbitrarily per ELEMENT, so
an entry row can be torn (lane A's key word with lane B's data word).
Every entry therefore stores `check = hash2 ^ meta ^ move`; a probe
recomputes the XOR and a torn entry simply fails validation and reads as
a miss — stale or corrupt entries can never return a wrong score, only
cost a re-search.

Entry layout (one packed (4,) int32 row per slot — see TTable):
    [0] check: hash2 ^ meta ^ move    (validation word, uint32 bits)
    [1] meta:  (score+32768) << 10 | searched_depth << 2 | flag
    [2] move:  the node's best move encoding (-1 when none)
    [3] generation (0 for plain always-replace stores; see `store`)
Mate-range scores are never stored (ply-relative mate distances don't
transpose; skipping them keeps the table sound without ply adjustment).

Helper-lane stores (Lazy-SMP lane groups, engine/tpu.py) opt into a
depth-preferred, generation-aware replacement policy: within the current
generation a shallower store never evicts a deeper entry, so the flood
of low-depth writes from K-1 helper lanes can't wash out the primary
path's deep entries. The generation word is NOT covered by the XOR check
(a torn generation only mis-prefers replacement, never corrupts
validation) and probes ignore it entirely.
"""
from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

FLAG_EXACT = 0
FLAG_LOWER = 1  # score is a lower bound (fail-high: score >= beta)
FLAG_UPPER = 2  # score is an upper bound (fail-low: score <= alpha0)

_SCORE_BIAS = 32768
_DEPTH_MASK = 0xFF
_MAX_STORE = 30000  # skip mate-range scores (|MATE|-1000 = 31000 > this)

# two independent 32-bit zobrist tables from one seeded PRNG; host-side
# constants baked into the program. Layout: piece-square | ep | castling |
# stm | variant extras (pocket counts, check counters, promoted bits)
_rng = np.random.default_rng(0xF15F_4E7)
_EP_OFF = 13 * 64
_CASTLE_OFF = _EP_OFF + 65
_STM_OFF = _CASTLE_OFF + 4 * 65
_POCKET_OFF = _STM_OFF + 2  # 10 slots × counts 0..16
_CHECKS_OFF = _POCKET_OFF + 10 * 17  # 2 colors × 0..3 checks
_PROMOTED_OFF = _CHECKS_OFF + 2 * 4  # 64 promoted-square bits
_VARIANT_OFF = _PROMOTED_OFF + 64  # per-variant salt (shared-table safety)
_Z_SHAPE = _VARIANT_OFF + 8
# identical boards under different rule sets must never share a TT entry
# (the engine keeps ONE table across all chunks) — each variant XORs a
# fixed salt into the key. threeCheck/crazyhouse extras already perturb
# the hash, but the rule-mask variants have no extra state to do it.
_VARIANT_ID = {
    "standard": 0, "threeCheck": 1, "crazyhouse": 2, "antichess": 3,
    "atomic": 4, "horde": 5, "kingOfTheHill": 6, "racingKings": 7,
}
Z1 = jnp.asarray(_rng.integers(0, 2**32, _Z_SHAPE, dtype=np.uint32))
Z2 = jnp.asarray(_rng.integers(0, 2**32, _Z_SHAPE, dtype=np.uint32))


def hash_boards(boards, variant: str = "standard"):
    """Batched `hash_board` over a stacked Board (N leading dim) —
    used by the engine to hash game-history tails in one dispatch."""
    return jax.vmap(
        lambda b, s, e, c, x: hash_board(b, s, e, c, x, variant)
    )(boards.board, boards.stm, boards.ep, boards.castling, boards.extra)


class TTable(NamedTuple):
    """Packed entry rows: data[..., 0]=check (uint32 bits), 1=meta,
    2=move, 3=pad. One (N, 4) array instead of three (N,) arrays so a
    probe is ONE row gather and a store ONE row scatter — the round-5
    device profile showed each extra big-table gather/scatter costing
    tens of us/step, and the split layout paid 3 gathers + 6 scatters
    per step. (Pad to 4: power-of-two rows tile cleanly.)"""
    data: jnp.ndarray  # (..., N, 4) int32

    @property
    def check(self) -> jnp.ndarray:  # uint32 view
        return jax.lax.bitcast_convert_type(self.data[..., 0], jnp.uint32)

    @property
    def meta(self) -> jnp.ndarray:
        return self.data[..., 1]

    @property
    def move(self) -> jnp.ndarray:
        return self.data[..., 2]

    @property
    def size(self) -> int:
        return self.data.shape[-2]


def make_table(size_log2: int = 20) -> TTable:
    """2**size_log2 slots × 16 bytes (default 2^20 = 16 MiB HBM)."""
    n = 1 << size_log2
    return TTable(data=jnp.zeros((n, 4), jnp.int32))


def hash_board(board64, stm, ep, castling, extra=None, variant: str = "standard"):
    """→ (h1, h2) uint32 pair for one position; batched via vmap/broadcast.

    board64 (…,64) int32 codes 0..12; ep scalar -1..63; castling (…,4)
    rook squares or -1; stm 0|1. halfmove is deliberately excluded
    (standard engine practice: 50-move distance doesn't transpose).
    `variant` (STATIC) folds Board.extra in: crazyhouse pockets + promoted
    bits, threeCheck counters — standard hashes are unchanged."""
    sq = jnp.arange(64, dtype=jnp.int32)
    mask = board64 > 0

    # TPU formulation note (round-5 device profile): `z[board64 * 64 + sq]`
    # is a data-dependent gather that lowers to a serialized kCustom fusion
    # (~29 us/step per table inside the search step). Every dynamic lookup
    # below is therefore a one-hot select against a STATIC slice of z —
    # exactly one branch matches, so the folded values (and the hashes)
    # are bit-identical to the gather form.
    def onehot_pick(zslice, val):
        """XOR term z[off + val] as a one-hot select; zslice (K,) static,
        val (...,) in [0, K)."""
        k = zslice.shape[0]
        oh = val[..., None] == jnp.arange(k, dtype=jnp.int32)
        return jnp.sum(jnp.where(oh, zslice, jnp.uint32(0)), axis=-1)

    def fold(z):
        zps = z[: 13 * 64].reshape(13, 64)  # static slice: [code, sq]
        sel = jnp.zeros_like(board64).astype(jnp.uint32)
        for code in range(1, 13):
            sel = jnp.where(board64 == code, zps[code], sel)
        rows = jnp.where(mask, sel, 0)
        h = jax.lax.reduce(
            rows, jnp.uint32(0), jax.lax.bitwise_xor, (rows.ndim - 1,)
        )
        h ^= onehot_pick(z[_EP_OFF:_EP_OFF + 65], ep + 1)
        for i in range(4):
            off = _CASTLE_OFF + i * 65
            h ^= onehot_pick(z[off:off + 65], castling[..., i] + 1)
        h ^= jnp.where(stm == 0, z[_STM_OFF], z[_STM_OFF + 1])
        vid = _VARIANT_ID.get(variant, 0)
        if vid:
            h ^= z[_VARIANT_OFF + vid]
        if variant == "threeCheck":
            for c in (0, 1):
                off = _CHECKS_OFF + c * 4
                h ^= onehot_pick(z[off:off + 4], jnp.clip(extra[..., c], 0, 3))
        elif variant == "crazyhouse":
            for slot in range(10):
                off = _POCKET_OFF + slot * 17
                h ^= onehot_pick(
                    z[off:off + 17], jnp.clip(extra[..., slot], 0, 16)
                )
            words = extra[..., 10:12]
            bits = (
                jnp.right_shift(words[..., sq // 32], sq % 32) & 1
            ) == 1
            prows = jnp.where(bits, z[_PROMOTED_OFF + sq], 0)
            h ^= jax.lax.reduce(
                prows, jnp.uint32(0), jax.lax.bitwise_xor, (prows.ndim - 1,)
            )
        return h

    return fold(Z1), fold(Z2)


def pack_meta(score, depth, flag):
    return ((score + _SCORE_BIAS) << 10) | (depth << 2) | flag


def unpack_meta(meta):
    score = (meta >> 10) - _SCORE_BIAS
    depth = (meta >> 2) & _DEPTH_MASK
    flag = meta & 3
    return score, depth, flag


def probe(tt: TTable, h1, h2, depth_left, alpha, beta,
          deep_bounds: bool = False):
    """Batched probe: → (usable, score, move, ordering_move).

    usable: entry valid AND deep enough AND its bound cuts the (alpha,
    beta) window. ordering_move: the stored move whenever the entry is
    merely valid (usable for move ordering even when depth is too
    shallow).

    deep_bounds (STATIC): additionally accept DEEPER LOWER/UPPER entries
    as cutoffs (the reference engine's depth >= rule). Sound for finding
    the best MOVE, but the cutoff value then depends on what else was
    searched — move jobs opt in for strength; analysis keeps the exact
    rule below for deterministic scores."""
    slot = (h1 & jnp.uint32(tt.size - 1)).astype(jnp.int32)
    rows = tt.data[slot]  # (..., 4): ONE gather for check+meta+move
    check = jax.lax.bitcast_convert_type(rows[..., 0], jnp.uint32)
    meta = rows[..., 1]
    move = rows[..., 2]
    valid = (check ^ meta.astype(jnp.uint32) ^ move.astype(jnp.uint32)) == h2
    valid &= meta != 0
    score, depth, flag = unpack_meta(meta)
    # EXACT depth match, not >=: an entry stored at depth d is a bound on
    # the depth-d value of the node. The search's value at remaining depth
    # d' < d is a DIFFERENT number (quiescence truncates differently), and
    # a deeper bound does not bound it — substituting deeper values is what
    # made TT-enabled root scores drift hardest from the plain search.
    # Deeper entries still help via the ordering move.
    #
    # Determinism caveat: with null-move pruning + LMR active (the
    # default since round 4), node values are window- and path-dependent
    # (a reduced late move is skipped or re-searched depending on alpha;
    # a null child can't null-move again), so TT cutoffs can shift root
    # scores a little versus the plain search — exactly as they do in
    # Stockfish, whose persistent hash the reference inherits
    # (tests/test_tt.py bounds the drift). Bit-exact TT-on-vs-off scores
    # hold only under FISHNET_TPU_NO_PRUNING=1.
    if deep_bounds:
        # the reference rule: any at-least-as-deep entry cuts (EXACT
        # included — a deeper exact value is the strongest hit of all)
        deep_enough = depth >= jnp.maximum(depth_left, 0)
    else:
        deep_enough = depth == jnp.maximum(depth_left, 0)
    cuts = jnp.where(
        flag == FLAG_EXACT,
        True,
        jnp.where(flag == FLAG_LOWER, score >= beta, score <= alpha),
    )
    usable = valid & deep_enough & cuts
    return usable, score, jnp.where(usable, move, -1), jnp.where(valid, move, -1)


def store(tt: TTable, h1, h2, score, depth, flag, move, mask,
          prefer_deep: bool = False, gen=None):
    """Batched store; lanes with mask=False write nothing. Always-replace
    scheme (simple and effective for short batched searches).

    prefer_deep (STATIC) switches to depth-preferred, generation-aware
    replacement for helper-lane dispatches: a slot holding a same-
    generation entry of strictly greater depth is kept. Entries from any
    other generation (including gen-0 plain stores and empty slots) are
    always replaceable, so the policy self-heals across chunks without a
    sweep. The extra row gather costs one more big-table access per store
    site, which is why the plain path doesn't pay it. A torn old row can
    misreport its depth and squat for the rest of the generation — rare
    (needs a same-slot collision) and bounded to one chunk."""
    storable = mask & (jnp.abs(score) <= _MAX_STORE)
    slot = (h1 & jnp.uint32(tt.size - 1)).astype(jnp.int32)
    gen_i = jnp.int32(0) if gen is None else jnp.asarray(gen, jnp.int32)
    if prefer_deep:
        old = tt.data[slot]  # (..., 4) row gather (pre-write snapshot)
        _, old_depth, _ = unpack_meta(old[..., 1])
        keep_old = (
            (old[..., 1] != 0)
            & (old[..., 3] == gen_i)
            & (old_depth > depth)
        )
        storable = storable & ~keep_old
    slot = jnp.where(storable, slot, tt.size)  # out-of-range → dropped
    meta = pack_meta(score, depth, flag)
    check = h2 ^ meta.astype(jnp.uint32) ^ move.astype(jnp.uint32)
    rows = jnp.stack(
        [
            jax.lax.bitcast_convert_type(check, jnp.int32),
            meta, move, jnp.broadcast_to(gen_i, meta.shape),
        ],
        axis=-1,
    )
    # ONE row scatter; colliding lanes may still interleave per element
    # (rows can tear) — exactly the race the XOR check word tolerates
    return TTable(data=tt.data.at[slot].set(rows, mode="drop"))
