"""Asset management: NNUE weights shipped with the framework.

The reference embeds two engine *binaries* plus their networks in a
zstd-compressed archive, unpacked to a tempdir at startup after CPU feature
detection (reference: src/assets.rs:15, 52-101, 186-227). In a TPU
framework the executable is the XLA program compiled at runtime, so the
asset that remains is the *weights*: packaged .npz files selected by
feature set, resident in HBM once loaded. There is nothing to unpack and
no SIMD dispatch — XLA compiles for whatever chip is attached.
"""
from __future__ import annotations

from pathlib import Path
from typing import Optional

ASSET_DIR = Path(__file__).resolve().parent / "assets"

# one packaged net per feature set actually shipped in fishnet_tpu/assets/
# (board768 is the engine fast path; a halfkav2_hm asset would slot in here
# the moment one is trained/imported — models/nnue_import.py reads real
# Stockfish .nnue files directly when the operator provides one)
DEFAULT_NETS = {
    "board768": "nnue-board768-64.npz",
}


def default_weights_path(feature_set: str = "board768") -> Optional[Path]:
    """Packaged weights for a feature set, or None if not shipped."""
    name = DEFAULT_NETS.get(feature_set)
    if name is None:
        return None
    path = ASSET_DIR / name
    return path if path.exists() else None


def load_default_params(feature_set: str = "board768"):
    """Load packaged weights; falls back to None when absent."""
    from .models import nnue

    path = default_weights_path(feature_set)
    if path is None:
        return None
    return nnue.load_params(path)
