"""Phase heartbeats: the liveness scheme proven in bench.py, generalized.

A hang is only diagnosable if the last recorded phase localizes it
(compile vs dispatch vs idle — docs/tpu-hang.md). Two pieces:

- `stamp`: the timestamped stderr line bench.py streams per phase
  transition, shared so every harness formats hangs the same way.
- `PhaseTracker`: thread-safe current-phase state for processes whose
  liveness is *watched from outside* (engine/host.py): the worker thread
  marks phase transitions, a ticker thread snapshots it into heartbeat
  frames. The watchdog policy this supports: heartbeat frames prove the
  process is alive (a stopped stream means frozen/dead — kill), while the
  carried phase + busy time lets deadlines be enforced per phase (a
  device hang shows as `search` busy beyond the chunk deadline even
  though frames keep flowing, because JAX's blocked dispatch releases
  the GIL and the ticker keeps running).
"""
from __future__ import annotations

import sys
import threading
import time
from typing import Optional, TextIO


def stamp(t0: float, msg: str, tag: str = "hb", file: Optional[TextIO] = None) -> None:
    """One timestamped heartbeat line on stderr (flushed immediately: the
    tail must survive a hard kill). t0 is a `time.monotonic()` reading —
    same clock as PhaseTracker and the trace timeline, so an NTP step
    can't skew a hang forensics log (wall clock would; lint rule
    obs-wall-clock)."""
    print(
        f"[{tag} {time.monotonic() - t0:7.1f}s] {msg}",
        file=file or sys.stderr,
        flush=True,
    )


class PhaseTracker:
    """Current phase + entry time, safe to snapshot from another thread."""

    def __init__(self, phase: str = "start") -> None:
        self._lock = threading.Lock()
        self._phase = phase
        self._since = time.monotonic()
        self._seq = 0  # bumps on every transition; lets watchers see churn

    def enter(self, phase: str) -> None:
        with self._lock:
            self._phase = phase
            self._since = time.monotonic()
            self._seq += 1

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "phase": self._phase,
                "busy_s": round(time.monotonic() - self._since, 3),
                "seq": self._seq,
            }
