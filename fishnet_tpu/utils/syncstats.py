"""Host-device synchronization instrumentation for the segment pipeline.

Every host-blocking materialization of a device value in the streaming
loops (ops/search.py search_stream, engine/tpu.py LaneScheduler) routes
through ONE choke point — SyncStats.fetch — so the per-boundary cost the
round-5 profile flagged (~290 us/step fixed gap, amplified by the
round-7 scheduler's full-result fetch at every boundary) is *measured*,
not guessed: how many transfers, how many elements, and how long the
host sat blocked on the device per segment.

The split reported per segment:

  device_ms  wall-clock the host spent BLOCKED inside fetch() — with a
             single summary fetch per boundary this approximates the
             device's segment compute time;
  host_ms    everything else in the boundary interval — scheduling,
             refill staging, result bookkeeping: the part the pipeline
             overlaps with the next segment's device compute.

fishnet-lint's conc-host-sync rule (lint/concurrency_rules.py) flags
raw int()/np.asarray()/block_until_ready() on jit outputs inside the
scheduler's segment loop; routing through fetch() is the sanctioned
form precisely because it keeps these counters honest.

Keep this module free of JAX imports at module scope — like settings.py
it is imported by conftest and the linter before JAX initializes; numpy
only (np.asarray blocks on jax.Array inputs without importing jax).
"""
from __future__ import annotations

import time
from typing import Optional

import numpy as np

from ..obs import trace as _trace


class SyncStats:
    """Per-segment transfer and blocked-time accounting.

    One instance per streaming run (or one long-lived instance per
    engine); boundary() closes the current segment's accounting window
    and returns its snapshot dict.
    """

    def __init__(self) -> None:
        self.transfers_total = 0
        self.elements_total = 0
        self.blocked_ms_total = 0.0
        self.segments_total = 0
        self._seg_transfers = 0
        self._seg_elements = 0
        self._seg_blocked_ms = 0.0
        self._seg_start = time.monotonic()

    # ------------------------------------------------------------ fetch

    def fetch(self, value, label: str = "") -> np.ndarray:
        """Materialize a device value on the host, counting one transfer
        and the wall-clock spent blocked. The single sanctioned host-sync
        site for the segment loops (lint rule conc-host-sync)."""
        t0 = time.monotonic()
        arr = np.asarray(value)
        dt_ms = (time.monotonic() - t0) * 1000.0
        rec = _trace.RECORDER
        if rec is not None:
            rec.complete(
                "fetch", t0 * 1e6, dt_ms * 1000.0, cat="sync",
                args={"label": label, "elements": int(arr.size)},
            )
        self._seg_transfers += 1
        self._seg_elements += int(arr.size)
        self._seg_blocked_ms += dt_ms
        self.transfers_total += 1
        self.elements_total += int(arr.size)
        self.blocked_ms_total += dt_ms
        return arr

    # --------------------------------------------------------- boundary

    def boundary(self) -> dict:
        """Close the current segment's accounting window.

        Returns {"transfers", "elements", "device_ms", "host_ms"} for
        the interval since the previous boundary() (or construction):
        device_ms is the blocked-in-fetch time, host_ms the remainder of
        the interval's wall-clock.
        """
        now = time.monotonic()
        wall_ms = (now - self._seg_start) * 1000.0
        snap = {
            "transfers": self._seg_transfers,
            "elements": self._seg_elements,
            "device_ms": round(self._seg_blocked_ms, 3),
            "host_ms": round(max(wall_ms - self._seg_blocked_ms, 0.0), 3),
        }
        rec = _trace.RECORDER
        if rec is not None:
            # One "segment" span covering the whole boundary interval,
            # with the device/host split as child spans whose durations
            # are EXACTLY the snapshot's device_ms/host_ms — so
            # tools/trace_report.py's per-segment shares tie out against
            # SyncStats totals by construction, not by re-measurement.
            start_us = self._seg_start * 1e6
            rec.complete("segment", start_us, wall_ms * 1000.0,
                         cat="sync", args=dict(snap))
            rec.complete("segment.device", start_us,
                         snap["device_ms"] * 1000.0, cat="sync")
            rec.complete("segment.host",
                         start_us + snap["device_ms"] * 1000.0,
                         snap["host_ms"] * 1000.0, cat="sync")
        self.segments_total += 1
        self._seg_transfers = 0
        self._seg_elements = 0
        self._seg_blocked_ms = 0.0
        self._seg_start = now
        return snap


class SegmentController:
    """Measured-feedback segment-length tuner (FISHNET_TPU_SEGMENT=auto).

    Holds the boundary-cost share — host_ms / (host_ms + device_ms) per
    segment — inside a hysteresis band by doubling the segment length
    when boundaries dominate and halving it when the host is already
    negligible (shorter segments mean lower deadline/refill latency, so
    the controller never pays for responsiveness it doesn't need).
    Bounds come from the settings registry (FISHNET_TPU_SEGMENT_MIN /
    _MAX); adjustments are power-of-two so the step count revisits the
    same few values instead of drifting. segment_steps is a *traced*
    argument of _run_segment_jit, so retuning never recompiles.
    """

    def __init__(self, lo: int, hi: int, start: Optional[int] = None,
                 low_share: float = 0.02, high_share: float = 0.10) -> None:
        if lo < 1:
            raise ValueError(f"segment lower bound must be >= 1, got {lo}")
        if hi < lo:
            raise ValueError(f"segment bounds inverted: [{lo}, {hi}]")
        self.lo = lo
        self.hi = hi
        self.low_share = low_share
        self.high_share = high_share
        self.steps = min(max(start if start is not None else lo, lo), hi)

    def update(self, ran_full: bool, host_ms: float,
               device_ms: float) -> int:
        """Feed one boundary's measurement; returns the step count for
        the next segment. Segments that ended early (every lane DONE)
        carry no length signal and leave the setting untouched."""
        if not ran_full:
            return self.steps
        total = host_ms + device_ms
        if total <= 0.0:
            return self.steps
        share = host_ms / total
        if share > self.high_share:
            self.steps = min(self.steps * 2, self.hi)
        elif share < self.low_share:
            self.steps = max(self.steps // 2, self.lo)
        return self.steps
