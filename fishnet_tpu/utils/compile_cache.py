"""Persistent XLA compilation cache.

The reference spends build time on PGO so shipped engine binaries start
fast (reference: build.rs:249-261). The TPU analog of that cost is XLA
compilation: the search program takes 20-40 s to compile per lane-bucket
shape. Persisting compiled executables to disk makes every restart after
the first start warm — the same "pay once, run fast forever" trade.

Disabled with FISHNET_TPU_NO_COMPILE_CACHE=1 (e.g. read-only filesystems).
"""
from __future__ import annotations

from pathlib import Path
from typing import Optional

from . import settings

_enabled_path: Optional[Path] = None


def enable_compile_cache(path: Optional[str] = None) -> Optional[Path]:
    """Point JAX's persistent compilation cache at a writable directory.

    Idempotent; returns the cache dir, or None when disabled/unavailable.
    Must be called before the first compilation to benefit it."""
    global _enabled_path
    if settings.get_bool("FISHNET_TPU_NO_COMPILE_CACHE"):
        return None
    if _enabled_path is not None:
        return _enabled_path
    try:
        import jax

        p = Path(
            path
            or settings.get_str("FISHNET_TPU_COMPILE_CACHE")
            or Path.home() / ".cache" / "fishnet-tpu" / "xla"
        )
        # namespace by backend: entries written through a remote-TPU
        # plugin target the REMOTE host's CPU features; loading them in a
        # local CPU run fails per-program (feature mismatch) and turns
        # every tiny eager compile into a load-fail-recompile-rewrite
        # cycle that can stall startup for minutes
        p = p / jax.default_backend()
        p.mkdir(parents=True, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", str(p))
        # default thresholds skip small programs; cache everything — even
        # the small host-callback programs add up across restarts
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
        _enabled_path = p
        return p
    except Exception:
        return None  # old jax / read-only home: run without the cache
