"""Persistent XLA compilation cache — tier 2 of the warm-boot ladder.

The reference spends build time on PGO so shipped engine binaries start
fast (reference: build.rs:249-261). The TPU analog of that cost is XLA
compilation: the search program takes 20-40 s to compile per lane-bucket
shape. Persisting compiled executables to disk makes every restart after
the first start warm — the same "pay once, run fast forever" trade.

Since the AOT asset registry landed (fishnet_tpu/aot/, docs/aot.md)
this cache is the SECOND tier, not the first: a packed bundle loads
serialized executables with zero XLA involvement at all; this cache
only softens the compiles that still happen — AOT misses, export runs
(`pack` itself compiles through it), and programs the bundle doesn't
cover. It stays on by default because the tiers compose: a miss that
falls back to JIT hits this cache before it hits the compiler.

Disabled with FISHNET_TPU_NO_COMPILE_CACHE=1 (e.g. read-only filesystems).
"""
from __future__ import annotations

from pathlib import Path
from typing import Optional

from . import settings

_enabled_path: Optional[Path] = None
_force_disabled = False


def _drop_cache_memo() -> None:
    # jax memoizes "is the persistent cache used" at the first compile
    # (compilation_cache._cache_checked), so flipping the config dir
    # mid-process is silently ignored unless that memo is reset too
    try:
        from jax._src import compilation_cache

        compilation_cache.reset_cache()
    except Exception:
        pass  # private API moved: config-only toggling still covers
        # processes that flip the cache before their first compile


def disable_compile_cache() -> None:
    """Turn the persistent cache off for the rest of this process.

    AOT export (``pack``) requires it: serializing an executable that was
    a persistent-cache HIT yields an incomplete payload that fails at
    deserialize time with "Symbols not found" — exported programs must be
    compiled for real. Later enable_compile_cache() calls become no-ops."""
    global _enabled_path, _force_disabled
    _force_disabled = True
    _enabled_path = None
    try:
        import jax

        jax.config.update("jax_compilation_cache_dir", None)
    except Exception:
        pass  # jax absent/old: nothing was cached anyway
    _drop_cache_memo()


def enable_compile_cache(path: Optional[str] = None) -> Optional[Path]:
    """Point JAX's persistent compilation cache at a writable directory.

    Idempotent; returns the cache dir, or None when disabled/unavailable.
    Must be called before the first compilation to benefit it. `path` is
    a ROOT: a /<backend> namespace dir is appended to it, so never pass
    a previously returned cache dir back in."""
    global _enabled_path
    if _force_disabled:
        return None
    if settings.get_bool("FISHNET_TPU_NO_COMPILE_CACHE"):
        return None
    if _enabled_path is not None:
        return _enabled_path
    try:
        import jax

        p = Path(
            path
            or settings.get_str("FISHNET_TPU_COMPILE_CACHE")
            or Path.home() / ".cache" / "fishnet-tpu" / "xla"
        )
        # namespace by backend: entries written through a remote-TPU
        # plugin target the REMOTE host's CPU features; loading them in a
        # local CPU run fails per-program (feature mismatch) and turns
        # every tiny eager compile into a load-fail-recompile-rewrite
        # cycle that can stall startup for minutes
        p = p / jax.default_backend()
        p.mkdir(parents=True, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", str(p))
        # default thresholds skip small programs; cache everything — even
        # the small host-callback programs add up across restarts
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
        _drop_cache_memo()
        _enabled_path = p
        return p
    except Exception:
        return None  # old jax / read-only home: run without the cache
