"""Runtime invariant sanitizer, behind ``FISHNET_TPU_SANITIZE``.

The static side of this PR's tooling (lint/dataflow_rules.py) proves
what it can about donated-buffer lifetimes and exactly-once ledgers
without running anything; this module is the dynamic complement for
what static analysis cannot see — donation routed through data,
double deliveries produced by a fault path, decayed TT rows read back
from disk. See docs/sanitizer.md for the full catalogue and cost
model.

Zero-overhead-off contract: every hook in the production modules is
gated on a flag captured ONCE (at module import or object
construction, via :func:`enabled`). With the flag off — the default —
``guard_donation`` returns the wrapped callable *unchanged* and the
ledger/stage/TT checks are a single pre-captured boolean test on cold
paths, so results are bit-identical and the pipelined scheduler loop
gains no per-boundary work. Flipping the setting therefore requires a
fresh process (the chaos sanitize CI tier sets it in the environment
before spawning anything).

Donation poisoning: JAX only *warns* when a donated buffer is not
usable (XLA:CPU), so the exact bug class that donation introduces —
reading an input handle after the dispatch that consumed it — can
survive the whole CPU test tier. ``guard_donation`` probes every
donated input leaf with ``is_deleted`` after the call and explicitly
``delete()``\\ s the ones the platform left alive, recording the
donating call site. A later read raises from JAX itself; passing the
dead handle back into any guarded call raises :class:`SanitizeError`
naming the call site that donated it.

Pure stdlib at import time: JAX is imported lazily inside the
donation guard only, so the serve/fleet/supervisor processes (which
never import JAX) can run fully sanitized.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, Mapping, Optional, Sequence

__all__ = [
    "SanitizeError",
    "enabled",
    "guard_donation",
    "deleted_site",
    "check_delivery_once",
    "check_replay_consistent",
    "check_tt_rows",
    "TT_SAMPLE_STRIDE",
]


class SanitizeError(AssertionError):
    """An invariant the sanitizer watches was violated."""


def enabled() -> bool:
    """Read ``FISHNET_TPU_SANITIZE`` through the settings registry.

    Call sites capture the result once (module import / constructor) —
    never per boundary — so the off-mode cost is zero.
    """
    from . import settings

    return settings.get_bool("FISHNET_TPU_SANITIZE")


# ------------------------------------------------------------- donation

# id(leaf) -> donating site, for diagnostics. Bounded: this is a debug
# mode, and a stale label after id() reuse only blurs a message.
_MAX_SITES = 4096
_DONATED_SITES: Dict[int, str] = {}


def _record_site(leaf: Any, site: str) -> None:
    if len(_DONATED_SITES) >= _MAX_SITES:
        _DONATED_SITES.clear()
    _DONATED_SITES[id(leaf)] = site


def deleted_site(leaf: Any) -> Optional[str]:
    """The guarded call that donated this array, if the sanitizer saw
    it (diagnostic aid for 'Array has been deleted' tracebacks)."""
    return _DONATED_SITES.get(id(leaf))


class _DonationGuard:
    """Callable wrapper that poisons donated inputs after dispatch.

    Attribute access (``.lower``, AOT registry metadata, ...) forwards
    to the wrapped callable so tooling built on the bare jits keeps
    working under the sanitizer.
    """

    def __init__(self, site: str, fn: Callable,
                 argnums: Sequence[int], argnames: Sequence[str]) -> None:
        self._site = site
        self._fn = fn
        self._argnums = tuple(argnums)
        self._argnames = tuple(argnames)

    def __getattr__(self, name: str) -> Any:
        return getattr(self._fn, name)

    def __call__(self, *args: Any, **kwargs: Any) -> Any:
        import jax

        donated = [args[i] for i in self._argnums if i < len(args)]
        donated += [kwargs[n] for n in self._argnames if n in kwargs]
        leaves = [
            leaf
            for operand in donated
            for leaf in jax.tree_util.tree_leaves(operand)
            if isinstance(leaf, jax.Array)
        ]
        # pre-call probe: a handle someone already donated is being
        # passed back in — raise here, naming the donating site, before
        # JAX produces its siteless "Array has been deleted"
        for leaf in leaves:
            if leaf.is_deleted():
                prior = _DONATED_SITES.get(
                    id(leaf), "an earlier donating call")
                raise SanitizeError(
                    f"sanitize[{self._site}]: a donated input buffer is "
                    f"already dead — it was donated into {prior}; rebind "
                    f"the variable from that call's outputs"
                )
        out = self._fn(*args, **kwargs)
        # poison the donated inputs the platform left alive, but never
        # a buffer the call aliased into its outputs
        out_ids = set()
        out_ptrs = set()
        for leaf in jax.tree_util.tree_leaves(out):
            if isinstance(leaf, jax.Array):
                out_ids.add(id(leaf))
                try:
                    out_ptrs.add(leaf.unsafe_buffer_pointer())
                except Exception:
                    pass  # sharded/committed arrays may not expose one
        for leaf in leaves:
            if leaf.is_deleted() or id(leaf) in out_ids:
                _record_site(leaf, self._site)
                continue
            try:
                ptr: Optional[int] = leaf.unsafe_buffer_pointer()
            except Exception:
                ptr = None
            if ptr is not None and ptr in out_ptrs:
                continue
            leaf.delete()
            _record_site(leaf, self._site)
        return out


def guard_donation(site: str, fn: Callable, argnums: Sequence[int] = (),
                   argnames: Sequence[str] = (),
                   force: Optional[bool] = None) -> Callable:
    """Wrap a donating jit so its donated inputs die loudly.

    Returns ``fn`` unchanged when the sanitizer is off (the structural
    zero-overhead guarantee). ``force`` overrides the setting for
    tests.
    """
    on = enabled() if force is None else force
    if not on:
        return fn
    return _DonationGuard(site, fn, argnums, argnames)


# -------------------------------------------------- exactly-once ledgers

def check_delivery_once(ledger: Mapping, key: Any, site: str) -> None:
    """Strict exactly-once: the key must not already be in the ledger.

    For delivery points whose downstream effects (streaming hooks,
    trace events) must fire exactly once per key — a duplicate is a bug
    even when the payload matches.
    """
    if key in ledger:
        raise SanitizeError(
            f"sanitize[{site}]: double delivery for {key!r} — the "
            f"exactly-once ledger already holds a response for it"
        )


def check_replay_consistent(ledger: Mapping, key: Any, value: Any,
                            site: str) -> None:
    """Replay-tolerant exactly-once: re-delivering the SAME payload is
    designed (journal replay after a respawn resends partials); the
    same key with a DIFFERENT payload means two answers were computed
    for one fingerprint."""
    prior = ledger.get(key)
    if prior is not None and prior is not value and prior != value:
        raise SanitizeError(
            f"sanitize[{site}]: conflicting re-delivery for {key!r} — "
            f"the ledger holds a different response for this "
            f"fingerprint (double search or cross-wired replay)"
        )


# --------------------------------------------------------- TT integrity

# 1-in-N sampling stride for row verification (docs/sanitizer.md).
TT_SAMPLE_STRIDE = 64

# ops/tt.py invariants: store() never writes FLAG 3, clamps depth into
# its 8-bit field, and refuses |score| beyond the mate margin.
_TT_MAX_STORE_SCORE = 30_000
_TT_SCORE_BIAS = 32_768


def check_tt_rows(rows: Sequence[Sequence[int]], site: str,
                  stride: int = TT_SAMPLE_STRIDE) -> int:
    """Verify sampled TT rows decode to values store() could have
    written.

    The check/meta/move XOR (``check == h2 ^ meta ^ move``) cannot be
    re-verified host-side without the probing position's hash, so the
    sanitizer checks the complement: every occupied row's meta word
    must unpack to a flag store() writes (0/1/2 — never 3), a score
    inside the mate margin, and a depth inside the packed field. A row
    violating this cannot have come from ops/tt.py's store path — it
    is corruption (or a packing regression) that the XOR would merely
    convert into silent probe misses.

    Rows are ``[slot?, check, meta, move, gen]`` (cache/ttwarm.py
    extract format) or ``[check, meta, move, gen]`` (raw table rows).
    Returns the number of rows actually verified.
    """
    checked = 0
    stride = max(1, int(stride))
    for i in range(0, len(rows), stride):
        row = rows[i]
        check, meta, move = (
            (int(row[1]), int(row[2]), int(row[3])) if len(row) >= 5
            else (int(row[0]), int(row[1]), int(row[2]))
        )
        if check == 0 and meta == 0 and move == 0:
            continue  # empty slot
        # mirror ops/tt.py unpack_meta exactly
        flag = meta & 0x3
        depth = (meta >> 2) & 0xFF
        score = (meta >> 10) - _TT_SCORE_BIAS
        if flag == 3 or abs(score) > _TT_MAX_STORE_SCORE:
            raise SanitizeError(
                f"sanitize[{site}]: TT row {i} does not decode to a "
                f"storable entry (flag={flag} score={score} "
                f"depth={depth}) — corrupt or mis-packed meta word"
            )
        checked += 1
    return checked
