"""Single source of truth for every FISHNET_TPU_* environment variable.

The first rounds hand-threaded engine config through five layers and
sprinkled 14 env vars across ~40 scattered `os.environ` read sites.
This registry pins each variable once — name, type, default, doc line,
and whether it is *engine-affecting* (changes search results or engine
behavior, so it must reach the supervised engine host child process) —
and every read in the codebase goes through the typed accessors below.

The registry is enforced statically by `python -m fishnet_tpu.lint`
(config-coherence rule family): a direct `os.environ` read of a
FISHNET_TPU_* name anywhere else, an unregistered name, a stale
docs/config.md table, or a supervisor spawn path that stops forwarding
the engine-affecting vars all fail the gate. Keep this module pure
stdlib — the linter and conftest import it before JAX exists.

IMPORTANT for the linter: the SETTINGS tuple below must stay a literal
(string/bool literals only, no computed values) — the lint extracts it
by AST, without importing arbitrary project code.

Boolean grammar (normalized; the pre-registry sites disagreed on "0" vs
"" vs "1"): unset or empty string means "use the default"; "0", "false",
"no", "off" (case-insensitive) mean False; anything else means True.

Generate the docs table with:  python -m fishnet_tpu.utils.settings
"""
from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

PREFIX = "FISHNET_TPU_"

_FALSE_WORDS = ("0", "false", "no", "off")


@dataclass(frozen=True)
class Setting:
    """One registered environment variable.

    kind: "bool" | "int" | "str" | "csv-int" — drives the typed accessor
    and the generated docs table. default is stored in string form ("":
    no default / unset means None for str and csv-int kinds).
    engine: True when the variable changes engine behavior or search
    results and therefore must be forwarded to the supervised engine
    host child (engine/supervisor.py applies engine_env() on spawn).
    """

    name: str
    kind: str
    default: str
    doc: str
    engine: bool = False


# ---------------------------------------------------------------- registry
#
# PURE LITERALS ONLY in this tuple — the lint reads it via AST.

SETTINGS: Tuple[Setting, ...] = (
    Setting(
        name="FISHNET_TPU_MAX_PLY",
        kind="int",
        default="32",
        doc="Static search stack depth; compile cost scales with it. "
            "Tests/CPU smoke runs set a small value.",
        engine=True,
    ),
    Setting(
        name="FISHNET_TPU_HELPERS",
        kind="int",
        default="4",
        doc="Lazy-SMP helper lanes per analysed position "
            "(engine/tpu.py); 1 disables helpers entirely.",
        engine=True,
    ),
    Setting(
        name="FISHNET_TPU_MAX_LANES",
        kind="int",
        default="1024",
        doc="Per-dispatch lane ceiling (v5e VMEM cliff at ~1024 lanes, "
            "docs/tpu-hang.md round 5).",
        engine=True,
    ),
    Setting(
        name="FISHNET_TPU_REFILL",
        kind="bool",
        default="1",
        doc="Continuous lane refill: the engine keeps the compiled step "
            "at full width by splicing queued positions into DONE lanes "
            "at segment boundaries (engine/tpu.py LaneScheduler); 0 "
            "restores strict chunk-serial dispatch.",
        engine=True,
    ),
    Setting(
        name="FISHNET_TPU_MESH_REFILL",
        kind="bool",
        default="1",
        doc="Continuous lane refill on MESH hosts: the LaneScheduler "
            "drives the shard_map'd segment/refill callables "
            "(parallel/mesh.py) so each device resplices its own lanes "
            "locally; 0 pins meshed engines back to strict chunk-serial "
            "dispatch. No effect on single-device hosts or with "
            "FISHNET_TPU_REFILL=0.",
        engine=True,
    ),
    Setting(
        name="FISHNET_TPU_MESH_HOSTS",
        kind="int",
        default="1",
        doc="Number of jax.distributed processes forming ONE logical "
            "engine over a multi-host mesh (parallel/distributed.py). "
            "1 (default) keeps the single-process mesh path; > 1 makes "
            "the engine call jax.distributed.initialize before first "
            "device use and build its mesh over the GLOBAL device set. "
            "Requires FISHNET_TPU_MESH_COORDINATOR; see docs/mesh.md.",
        engine=True,
    ),
    Setting(
        name="FISHNET_TPU_MESH_COORDINATOR",
        kind="str",
        default="",
        doc="host:port of the jax.distributed coordinator (process 0) "
            "when FISHNET_TPU_MESH_HOSTS > 1. The host-level boundary "
            "exchange (parallel/distributed.py HostExchange) rides one "
            "port above this.",
        engine=True,
    ),
    Setting(
        name="FISHNET_TPU_MESH_PROCESS_ID",
        kind="int",
        default="0",
        doc="This process's id in [0, FISHNET_TPU_MESH_HOSTS) for "
            "jax.distributed.initialize. Process 0 hosts the "
            "coordinator and (in a pod: fleet member) sits inside the "
            "fleet coordinator; workers run the same dispatch sequence "
            "(docs/mesh.md runbook).",
        engine=True,
    ),
    Setting(
        name="FISHNET_TPU_NARROW_FLOOR",
        kind="int",
        default="64",
        doc="search_batch_resumable power-of-two narrowing floor: live "
            "batches never narrow below this width (each width is a "
            "separate XLA program).",
        engine=True,
    ),
    Setting(
        name="FISHNET_TPU_SEGMENT",
        kind="str",
        default="20000",
        doc="Device steps per resumable segment between host checks "
            "(deadline / narrowing / refill boundaries): an integer, or "
            "\"auto\" for the measured-feedback controller that tunes "
            "segment length from the boundary-cost/compute ratio within "
            "[FISHNET_TPU_SEGMENT_MIN, FISHNET_TPU_SEGMENT_MAX].",
        engine=True,
    ),
    Setting(
        name="FISHNET_TPU_SEGMENT_MIN",
        kind="int",
        default="2048",
        doc="Lower bound for FISHNET_TPU_SEGMENT=auto (and its starting "
            "value): the controller never shrinks segments below this.",
        engine=True,
    ),
    Setting(
        name="FISHNET_TPU_SEGMENT_MAX",
        kind="int",
        default="65536",
        doc="Upper bound for FISHNET_TPU_SEGMENT=auto: the controller "
            "never grows segments beyond this (bounds deadline/refill "
            "latency at a boundary check every MAX steps).",
        engine=True,
    ),
    Setting(
        name="FISHNET_TPU_PIPELINE",
        kind="bool",
        default="1",
        doc="Asynchronous segment pipeline: the host stages the next "
            "segment's admissions while the device runs the current one "
            "and fetches one packed boundary summary instead of the full "
            "result set (ops/search.py, engine/tpu.py LaneScheduler); 0 "
            "restores the round-7 synchronous boundary loop bit-for-bit.",
        engine=True,
    ),
    Setting(
        name="FISHNET_TPU_REPLAY",
        kind="bool",
        default="1",
        doc="Crash-safe session recovery (engine/supervisor.py): the "
            "host streams per-position results as partial frames into "
            "the supervisor's session journal, and after a kill the "
            "respawned child is handed only the unfinished suffix of "
            "the chunk (with bisection/quarantine for repeat offenders); "
            "0 restores whole-chunk retry semantics.",
    ),
    Setting(
        name="FISHNET_TPU_BISECT_MAX",
        kind="int",
        default="12",
        doc="Child-death budget per chunk for the supervisor's recovery "
            "ladder (replay retries + bisection splits + quarantine "
            "probes); isolating one poison position in a 6-position "
            "chunk costs up to 7 deaths.",
    ),
    Setting(
        name="FISHNET_TPU_QUARANTINE",
        kind="bool",
        default="1",
        doc="Route bisection-isolated poison positions to the CPU "
            "fallback individually while the rest of the chunk stays on "
            "the TPU path (engine/supervisor.py quarantine list); 0 "
            "lets repeat offenders fail the chunk instead.",
    ),
    Setting(
        name="FISHNET_TPU_ASPIRATION",
        kind="csv-int",
        default="",
        doc="Override aspiration window half-width schedule, e.g. "
            "\"15,120\" (docs/depth.md: measured default).",
        engine=True,
    ),
    Setting(
        name="FISHNET_TPU_SELECT_UPDATES",
        kind="bool",
        default="1",
        doc="Per-lane dynamic row writes as one-hot masked selects "
            "(default) instead of scatter (docs/tpu-hang.md device "
            "fault + 20x step cost; the modes are bit-identical).",
        engine=True,
    ),
    Setting(
        name="FISHNET_TPU_NO_PRUNING",
        kind="bool",
        default="0",
        doc="Disable null-move pruning, LMR and futility pruning "
            "(debug/A-B lever; the oracle mirrors the active mode).",
        engine=True,
    ),
    Setting(
        name="FISHNET_TPU_DTYPE",
        kind="str",
        default="",
        doc="Quantize NNUE weights: \"bf16\" for MXU-native inputs; "
            "\"int8\" is experimental and additionally gated.",
        engine=True,
    ),
    Setting(
        name="FISHNET_TPU_EXPERIMENTAL_INT8",
        kind="bool",
        default="0",
        doc="Unlock the int8 fixed-point ladder (measured a NET LOSS "
            "vs f32 at production shapes, round-5 bench).",
        engine=True,
    ),
    Setting(
        name="FISHNET_TPU_WARMUP_BUCKETS",
        kind="csv-int",
        default="",
        doc="Trim the warmup lane-bucket set, e.g. \"16\" for CPU "
            "smoke runs where each extra compile costs minutes.",
    ),
    Setting(
        name="FISHNET_TPU_WARMUP_VARIANTS",
        kind="str",
        default="auto",
        doc="Variant programs to precompile: comma list, \"all\", "
            "\"none\", or \"auto\" (all on accelerators, none on CPU).",
    ),
    Setting(
        name="FISHNET_TPU_TRACE",
        kind="bool",
        default="0",
        doc="Per-dispatch / per-depth timing lines to stderr "
            "(localize compile-vs-run cost from logs).",
    ),
    Setting(
        name="FISHNET_TPU_TRACE_DIR",
        kind="str",
        default="",
        doc="Enable the trace timeline (obs/trace.py) and write flight-"
            "recorder dumps into this directory on child death, progress "
            "stall, or breaker trip; unset keeps tracing off (the "
            "default: one attribute check per site, zero events).",
        engine=True,
    ),
    Setting(
        name="FISHNET_TPU_TRACE_SAMPLE",
        kind="str",
        default="1.0",
        doc="Fraction of requests that get per-request lifecycle "
            "tracing (request-scoped spans + flow links, obs/trace.py "
            "sampled()): a float in [0, 1]. The decision hashes the "
            "trace_id, so every process traces the same subset of "
            "requests. Only meaningful with FISHNET_TPU_TRACE_DIR set.",
        engine=True,
    ),
    Setting(
        name="FISHNET_TPU_TRACE_BUF",
        kind="int",
        default="65536",
        doc="Trace ring-buffer capacity in events (obs/trace.py); the "
            "ring keeps the most recent events, so this bounds how far "
            "back a flight-recorder dump can see.",
        engine=True,
    ),
    Setting(
        name="FISHNET_TPU_METRICS_PORT",
        kind="int",
        default="0",
        doc="Serve the metrics registry (obs/metrics.py) as Prometheus "
            "text on this loopback port; 0 (default) disables the "
            "endpoint.",
    ),
    Setting(
        name="FISHNET_TPU_SERVE_HOST",
        kind="str",
        default="127.0.0.1",
        doc="Bind address for the analysis-serving endpoint "
            "(`fishnet-tpu serve`, fishnet_tpu/serve/). The default is "
            "loopback; bind a routable address only behind your own "
            "auth/TLS front proxy.",
    ),
    Setting(
        name="FISHNET_TPU_SERVE_PORT",
        kind="int",
        default="9670",
        doc="TCP port for the analysis-serving endpoint; 0 binds an "
            "OS-assigned ephemeral port (smoke tests parse the "
            "\"listening on\" line).",
    ),
    Setting(
        name="FISHNET_TPU_SERVE_MAX_INFLIGHT",
        kind="int",
        default="768",
        doc="Admission controller: maximum positions admitted into the "
            "engine concurrently across all tenants (fishnet_tpu/serve/"
            "admission.py); sized to the lane pool.",
    ),
    Setting(
        name="FISHNET_TPU_SERVE_MAX_QUEUE",
        kind="int",
        default="256",
        doc="Admission controller: positions allowed to wait for a free "
            "in-flight slot before new requests are shed with HTTP 429 "
            "(bounded waiting room, hardest-deadline-first admission).",
    ),
    Setting(
        name="FISHNET_TPU_SERVE_TIMEOUT_MS",
        kind="int",
        default="8000",
        doc="Default and maximum per-request deadline for served "
            "analysis/bestmove requests; a request's own timeout_ms is "
            "clamped to this.",
    ),
    Setting(
        name="FISHNET_TPU_SERVE_DRAIN_S",
        kind="int",
        default="20",
        doc="Graceful-drain grace period on SIGTERM/SIGINT: the server "
            "stops accepting, finishes in-flight requests for up to this "
            "many seconds, flushes stats, then exits.",
    ),
    Setting(
        name="FISHNET_TPU_FLEET_MEMBERS",
        kind="str",
        default="local*1",
        doc="Fleet member specs, comma-separated (fishnet_tpu/fleet/): "
            "'local' or 'local*N' for SupervisedEngine-managed host "
            "children on this machine, 'http://HOST:PORT' (or bare "
            "HOST:PORT) for a remote `fishnet-tpu serve` endpoint. "
            "Used when the coordinator is started without an explicit "
            "--fleet-members.",
    ),
    Setting(
        name="FISHNET_TPU_FLEET_REDISPATCH_MAX",
        kind="int",
        default="3",
        doc="Re-dispatch rounds the fleet coordinator may spend per "
            "chunk after member losses before the chunk fails; each "
            "round re-sends only the lost member's un-acked positions "
            "to survivors (exactly-once ledger, fleet/coordinator.py).",
    ),
    Setting(
        name="FISHNET_TPU_FLEET_LOSS_WINDOW",
        kind="int",
        default="30",
        doc="Seconds a lost fleet member sits out of admission after a "
            "member-loss event before the least-backlog planner "
            "considers it again (its supervisor's own respawn backoff "
            "still applies underneath).",
    ),
    Setting(
        name="FISHNET_TPU_FLEET_RETRY_MAX",
        kind="int",
        default="4",
        doc="In-dispatch retry attempts for transient remote faults "
            "(connect refused, timeout before the request was written) "
            "before the dispatch escalates to a member-loss event "
            "(fleet/faults.py taxonomy). Retries use jittered "
            "exponential backoff bounded by the chunk's deadline slack.",
    ),
    Setting(
        name="FISHNET_TPU_FLEET_COOLDOWN_MAX",
        kind="int",
        default="600",
        doc="Cap in seconds on the fleet's escalating loss cooldown: "
            "each consecutive loss doubles the member's cooldown from "
            "FISHNET_TPU_FLEET_LOSS_WINDOW up to this bound, so a "
            "permanently-dead member costs only periodic probes.",
    ),
    Setting(
        name="FISHNET_TPU_FLEET_PROBATION",
        kind="bool",
        default="1",
        doc="Probed readmission: after its cooldown a lost member "
            "enters probation and must pass a healthz probe plus one "
            "canary chunk before the planner gives it real work again. "
            "0 restores blind readmission at cooldown expiry.",
    ),
    Setting(
        name="FISHNET_TPU_FLEET_HEDGE",
        kind="bool",
        default="0",
        doc="Hedged dispatch: when a dispatched sub-chunk's deadline "
            "slack drops below FISHNET_TPU_FLEET_HEDGE_SLACK_MS and a "
            "healthy member has free capacity, duplicate the unfinished "
            "positions to it; first answer wins via the exactly-once "
            "fingerprint ledger, the loser is discarded and counted. "
            "Results are bit-identical with hedging on or off.",
    ),
    Setting(
        name="FISHNET_TPU_FLEET_HEDGE_SLACK_MS",
        kind="int",
        default="1500",
        doc="Deadline slack threshold for hedged dispatch: a sub-chunk "
            "still unanswered when this many milliseconds remain before "
            "its chunk deadline is duplicated to a free member (only "
            "with FISHNET_TPU_FLEET_HEDGE=1).",
    ),
    Setting(
        name="FISHNET_TPU_AUTOSCALE",
        kind="bool",
        default="0",
        doc="Elastic capacity (fleet/autoscaler.py): run the autoscaling "
            "control loop next to `serve --fleet`, adding local members "
            "under admission-queue pressure or deadline misses and "
            "draining them back to the floor when idle. Capacity changes "
            "never alter search results.",
    ),
    Setting(
        name="FISHNET_TPU_AUTOSCALE_MIN",
        kind="int",
        default="1",
        doc="Autoscaler member-count floor: the loop never drains below "
            "this many members, and only ever drains members it added "
            "itself (the configured fleet is the floor).",
    ),
    Setting(
        name="FISHNET_TPU_AUTOSCALE_MAX",
        kind="int",
        default="4",
        doc="Autoscaler member-count ceiling: scale-up stops here no "
            "matter the backlog (the cost clamp).",
    ),
    Setting(
        name="FISHNET_TPU_AUTOSCALE_INTERVAL_MS",
        kind="int",
        default="1000",
        doc="Autoscaler control-loop tick interval in milliseconds; "
            "hysteresis counts ticks, so the up/down reaction times are "
            "UP_TICKS x this and DOWN_TICKS x this.",
    ),
    Setting(
        name="FISHNET_TPU_AUTOSCALE_UP_QUEUE",
        kind="int",
        default="1",
        doc="Admission-queue depth (queued positions) that counts as "
            "scale-up pressure for a tick; a deadline miss recorded "
            "during the tick counts as pressure regardless.",
    ),
    Setting(
        name="FISHNET_TPU_AUTOSCALE_UP_TICKS",
        kind="int",
        default="2",
        doc="Consecutive pressure ticks before the autoscaler adds a "
            "member (scale-up hysteresis).",
    ),
    Setting(
        name="FISHNET_TPU_AUTOSCALE_DOWN_TICKS",
        kind="int",
        default="5",
        doc="Consecutive fully-idle ticks (no queue, no in-flight, no "
            "member backlog) before the autoscaler drains a member "
            "(scale-down hysteresis; deliberately slower than scale-up "
            "so one burst costs at most one up/down reversal).",
    ),
    Setting(
        name="FISHNET_TPU_AUTOSCALE_LOSS_COOLDOWN_S",
        kind="int",
        default="30",
        doc="Scale-down veto window after a member-loss event: the loop "
            "never drains while any member is in cooldown/probing/"
            "probation or within this many seconds of the last loss "
            "(never shrink mid-recovery-ladder).",
    ),
    Setting(
        name="FISHNET_TPU_AOT",
        kind="bool",
        default="1",
        doc="AOT program assets (fishnet_tpu/aot/): preload serialized "
            "compiled search programs from the registry instead of "
            "JIT-compiling at warmup; misses fall back to JIT with a "
            "warning. 0 disables the registry entirely.",
        engine=True,
    ),
    Setting(
        name="FISHNET_TPU_AOT_DIR",
        kind="str",
        default="",
        doc="AOT program store root "
            "(default ~/.cache/fishnet-tpu/aot). `python -m fishnet_tpu "
            "pack` writes here, engines read at boot.",
        engine=True,
    ),
    Setting(
        name="FISHNET_TPU_AOT_EXPORT",
        kind="bool",
        default="0",
        doc="Background re-export: on an AOT miss, serialize the "
            "JIT-compiled executable back into the store so the next "
            "boot hits (pack sets this implicitly).",
        engine=True,
    ),
    Setting(
        name="FISHNET_TPU_COMPILE_CACHE",
        kind="str",
        default="",
        doc="Persistent XLA compile cache directory "
            "(default ~/.cache/fishnet-tpu/xla).",
    ),
    Setting(
        name="FISHNET_TPU_NO_COMPILE_CACHE",
        kind="bool",
        default="0",
        doc="Disable the persistent XLA compile cache entirely "
            "(e.g. read-only filesystems).",
    ),
    Setting(
        name="FISHNET_TPU_UPDATE_URL",
        kind="str",
        default="https://fishnet-tpu-releases.s3.amazonaws.com/",
        doc="Release bucket for the auto-updater "
            "(tests point it at a local fixture).",
    ),
    Setting(
        name="FISHNET_TPU_CACHE",
        kind="bool",
        default="1",
        doc="Fleet-wide analysis memoization (fishnet_tpu/cache/, "
            "docs/caching.md): memoize search results keyed on position "
            "content + search shape + engine identity, consulted at "
            "serve admission and the fleet coordinator. Cold positions "
            "are bit-identical to cache-off; hits return an "
            "at-least-as-deep stored result.",
    ),
    Setting(
        name="FISHNET_TPU_CACHE_DIR",
        kind="str",
        default="",
        doc="Analysis-cache root "
            "(default ~/.cache/fishnet-tpu/cache): the sqlite index "
            "and per-entry payload files that let hits survive "
            "restarts (FISHNET_TPU_CACHE_PERSIST=0 skips the tier "
            "entirely).",
    ),
    Setting(
        name="FISHNET_TPU_CACHE_PERSIST",
        kind="bool",
        default="1",
        doc="Persist analysis-cache entries to FISHNET_TPU_CACHE_DIR "
            "(0: the bounded in-memory LRU only; nothing survives a "
            "restart).",
    ),
    Setting(
        name="FISHNET_TPU_CACHE_MAX_ENTRIES",
        kind="int",
        default="4096",
        doc="In-memory LRU bound on cached analysis results (entries); "
            "evictions never touch the persisted tier.",
    ),
    Setting(
        name="FISHNET_TPU_CACHE_MAX_MB",
        kind="int",
        default="32",
        doc="In-memory LRU bound on cached analysis results "
            "(payload megabytes); whichever of the entry/byte bounds "
            "trips first evicts.",
    ),
    Setting(
        name="FISHNET_TPU_CACHE_DISK_MAX_ENTRIES",
        kind="int",
        default="65536",
        doc="Persisted-tier bound: oldest index rows (and their payload "
            "files) are dropped beyond this count.",
    ),
    Setting(
        name="FISHNET_TPU_CACHE_TT",
        kind="bool",
        default="0",
        doc="TT warm slices (cache/ttwarm.py): persist the "
            "transposition-table rows a search earned around each "
            "position, keyed by opening-prefix fingerprint, and splice "
            "them back in when a chunk starts on the same prefix. "
            "Warm-started searches may return better-informed answers "
            "than cold ones, so this sits OUTSIDE the cache's "
            "bit-identity guarantee — off by default.",
        engine=True,
    ),
    Setting(
        name="FISHNET_TPU_CACHE_TT_PREFIX",
        kind="int",
        default="8",
        doc="Opening-prefix length (plies) for TT warm-slice keys: "
            "positions sharing this many first moves share a slice.",
        engine=True,
    ),
    Setting(
        name="FISHNET_TPU_SANITIZE",
        kind="bool",
        default="0",
        doc="Runtime invariant sanitizer (utils/sanitize.py, "
            "docs/sanitizer.md): poison donated jit inputs so "
            "use-after-donate raises on CPU too, assert the "
            "exactly-once delivery ledgers never double-deliver, "
            "reject unknown in-flight stage labels, and verify "
            "sampled TT warm rows decode to storable entries. "
            "Captured at import/construction — flipping it needs a "
            "fresh process. Off (default) adds zero overhead.",
        engine=True,
    ),
    Setting(
        name="FISHNET_TPU_PERF_LEDGER",
        kind="str",
        default="",
        doc="Path of the perf-ledger sqlite file (obs/perf.py, "
            "docs/perf.md). Empty (default) resolves to perf_ledger.db "
            "at the checkout root, falling back to "
            "~/.cache/fishnet-tpu/perf_ledger.db for installed "
            "packages. bench.py appends every RESULT row here; "
            "tools/perf_report.py reads the history back for the "
            "regression gate.",
    ),
    Setting(
        name="FISHNET_TPU_PERF_WINDOW",
        kind="int",
        default="5",
        doc="Rolling-baseline window for the perf regression detector: "
            "how many prior same-fingerprint ledger runs average into "
            "the baseline each metric is compared against.",
    ),
    Setting(
        name="FISHNET_TPU_PERF_BAND",
        kind="str",
        default="0.02",
        doc="Minimum relative noise band (fraction) for deterministic "
            "counter metrics in tools/perf_report.py --check; the "
            "band widens automatically to 2x the baseline's relative "
            "stddev when history is noisier than this floor. "
            "Wall-clock metrics use a fixed 15% band and never gate.",
    ),
    Setting(
        name="FISHNET_TPU_PERF_PROGRAMS",
        kind="bool",
        default="1",
        doc="Program cost accounting (obs/perf.py): read "
            "cost_analysis()/memory_analysis() off AOT-compiled "
            "executables wherever a Compiled object already exists "
            "(bench precompile, AOT registry export) and export "
            "fishnet_program_* gauges. Capture never triggers an "
            "extra compile; off skips even the cheap reads.",
    ),
)

_BY_NAME: Dict[str, Setting] = {s.name: s for s in SETTINGS}


class UnregisteredSetting(KeyError):
    """A FISHNET_TPU_* name was used without a registry entry."""


def lookup(name: str) -> Setting:
    try:
        return _BY_NAME[name]
    except KeyError:
        raise UnregisteredSetting(
            f"{name} is not registered in fishnet_tpu/utils/settings.py"
        ) from None


def raw(name: str) -> Optional[str]:
    """The raw environment value, or the registered default when unset
    or empty. Returns None when there is no default either. Reads the
    environment on every call — tests mutate it between imports."""
    s = lookup(name)
    value = os.environ.get(name)
    if value is None or value == "":
        value = s.default
    return value if value != "" else None


def get_bool(name: str) -> bool:
    s = lookup(name)
    if s.kind != "bool":
        raise TypeError(f"{name} is registered as {s.kind}, not bool")
    value = raw(name)
    if value is None:
        return False
    return value.strip().lower() not in _FALSE_WORDS


def get_int(name: str) -> int:
    s = lookup(name)
    if s.kind != "int":
        raise TypeError(f"{name} is registered as {s.kind}, not int")
    value = raw(name)
    assert value is not None, f"{name} registered as int must have a default"
    return int(value)


def get_str(name: str) -> Optional[str]:
    s = lookup(name)
    if s.kind != "str":
        raise TypeError(f"{name} is registered as {s.kind}, not str")
    return raw(name)


def get_segment() -> Optional[int]:
    """FISHNET_TPU_SEGMENT: fixed device-step count per segment, or None
    when set to "auto" — callers run the measured-feedback
    SegmentController (utils/syncstats.py) within the registry bounds
    FISHNET_TPU_SEGMENT_MIN/_MAX instead of a fixed length."""
    value = raw("FISHNET_TPU_SEGMENT")
    assert value is not None, "FISHNET_TPU_SEGMENT has a registry default"
    value = value.strip().lower()
    if value == "auto":
        return None
    return int(value)


def get_csv_int(name: str) -> Optional[Tuple[int, ...]]:
    """Comma-separated ints, or None when unset (callers keep their own
    built-in fallback schedule)."""
    s = lookup(name)
    if s.kind != "csv-int":
        raise TypeError(f"{name} is registered as {s.kind}, not csv-int")
    value = raw(name)
    if value is None:
        return None
    return tuple(int(x) for x in value.split(",") if x)


def is_set(name: str) -> bool:
    """True when the variable is explicitly present and non-empty in the
    environment (regardless of defaults)."""
    lookup(name)
    return bool(os.environ.get(name))


def engine_settings() -> Tuple[Setting, ...]:
    return tuple(s for s in SETTINGS if s.engine)


def engine_env() -> Dict[str, str]:
    """Environment overlay carrying every engine-affecting variable that
    is explicitly set, for the supervised engine host child. The child
    would inherit the parent environment anyway; applying this overlay
    explicitly makes the invariant visible — and statically checkable
    (lint rule config-engine-wire) — so a future sanitized-env spawn
    can't silently strand engine config on the parent side."""
    out: Dict[str, str] = {}
    for s in engine_settings():
        value = os.environ.get(s.name)
        if value:
            out[s.name] = value
    return out


# ------------------------------------------------------------ docs table


def render_rows(rows: List[tuple]) -> str:
    """Render the docs/config.md table from (name, kind, default, doc,
    engine) tuples. Shared by the runtime generator below and the lint's
    AST-extracted staleness check, so the two can never disagree."""
    lines = [
        "# Configuration reference",
        "",
        "Every `FISHNET_TPU_*` environment variable, generated from the",
        "single registry in `fishnet_tpu/utils/settings.py` — do not edit",
        "by hand; regenerate with:",
        "",
        "```",
        "python -m fishnet_tpu.utils.settings > docs/config.md",
        "```",
        "",
        "Boolean grammar: unset/empty uses the default; `0`, `false`,",
        "`no`, `off` (case-insensitive) mean false; anything else true.",
        "Engine-affecting variables are forwarded to the supervised",
        "engine host child on spawn (`settings.engine_env()`).",
        "",
        "| Variable | Type | Default | Engine-affecting | Description |",
        "|---|---|---|---|---|",
    ]
    for name, kind, default, doc, engine in rows:
        default_cell = f"`{default}`" if default != "" else "*(unset)*"
        lines.append(
            f"| `{name}` | {kind} | {default_cell} | "
            f"{'yes' if engine else 'no'} | {doc} |"
        )
    return "\n".join(lines) + "\n"


def render_config_md() -> str:
    return render_rows(
        [(s.name, s.kind, s.default, s.doc, s.engine) for s in SETTINGS]
    )


if __name__ == "__main__":
    import sys

    sys.stdout.write(render_config_md())
