"""Small shared utilities (reference analog: src/util.rs)."""
from .compile_cache import enable_compile_cache

__all__ = ["enable_compile_cache"]
