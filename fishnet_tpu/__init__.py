"""fishnet-tpu: a TPU-native distributed chess analysis framework.

A brand-new implementation of the capabilities of fishnet (the lichess.org
distributed analysis client) with a first-class TPU engine: batched legal
move generation, quantized NNUE evaluation, and lockstep alpha-beta search
as JAX/XLA programs, sharded across TPU meshes.
"""

__version__ = "0.1.0"
