"""Fleet membership: the per-member ledger and the member-spec grammar.

A `FleetMember` wraps any Engine-protocol object with the coordinator's
bookkeeping: backlog (positions handed over, not yet answered), the
in-flight fingerprint set (what re-dispatches after a loss), the ack
journal (position fingerprint → wire response, fed by the supervisor's
`on_partial` hook for local members), and health (down-until cooldown,
drain flag, loss count).

Member specs are a comma-separated string (`FISHNET_TPU_FLEET_MEMBERS`
or `--fleet-members`):

    local            one SupervisedEngine-managed host child here
    local*4          four of them
    pod:2            one giant-B member spanning a 2-process
                     jax.distributed mesh (process 0 is the host child
                     here; workers join per the docs/mesh.md runbook)
    pod:2@h:1234     same, with an explicit coordinator address
    http://h:9670    a remote `fishnet-tpu serve` endpoint
    h:9670           same (bare host:port implies http)

Local members deliberately invert two supervisor defaults
(make_local_member): `bisect_max=0` so the recovery ladder escalates the
FIRST child death as an `EngineError` instead of respawn-and-bisect —
the fleet has survivors to re-dispatch to, which beats bisecting on a
possibly-sick host — and no fallback/quarantine, because masking a loss
inside the member would hide exactly the signal the coordinator's
exactly-once ledger is built on. Replay stays on: partial frames keep
streaming into the member journal, and `on_partial` mirrors each ack
into the fleet ledger so only genuinely un-acked positions re-run.
"""
from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Set

from ..client.ipc import WorkPosition
from ..client.logger import Logger
from ..utils import settings
from .remote import HttpEngine

# a local member's own breaker must never trip before the coordinator
# notices the loss — fleet health lives in the fleet ledger, not N
# private breakers with N private cooldowns
_MEMBER_BREAKER_THRESHOLD = 1_000_000


@dataclass
class FleetMember:
    """One engine plus the coordinator's ledger for it.

    Lifecycle (docs/fleet.md has the full diagram): an eligible member
    takes dispatches; a loss puts it in cooldown (`down_until`,
    escalating with `consecutive_losses`); when the cooldown expires it
    sits in *probation* — the coordinator must pass a healthz probe and
    one canary chunk through it (`probing` while that runs) before it
    is eligible again. A 429 shed parks it until `busy_until` without
    touching the loss ladder. `draining` excludes it from planning while
    in-flight work finishes, after which it can be removed.
    """

    name: str
    engine: object  # Engine protocol (go_multiple/close)
    kind: str = "local"  # "local" | "remote"
    backlog: int = 0  # positions dispatched, not yet answered
    inflight: Dict[str, WorkPosition] = field(default_factory=dict)
    acked: Dict[str, dict] = field(default_factory=dict)  # fp -> wire
    down_until: float = 0.0  # monotonic; loss cooldown
    busy_until: float = 0.0  # monotonic; 429 Retry-After backpressure
    draining: bool = False
    probation: bool = False  # must pass healthz + canary to re-enter
    probing: bool = False  # a probe is in flight right now
    losses: int = 0
    consecutive_losses: int = 0  # resets on a served sub-chunk
    canaries_ok: int = 0
    dispatched_positions: int = 0

    def available(self, now: Optional[float] = None) -> bool:
        """Eligible for new work: not draining, not in loss cooldown or
        probation, not shedding (429), breaker (if any) not open."""
        if self.draining:
            return False
        if now is None:
            now = time.monotonic()
        if now < self.down_until:
            return False
        if self.probation:
            return False
        if now < self.busy_until:
            return False
        if getattr(self.engine, "breaker_open", False):
            return False
        return True

    def probe_due(self, now: Optional[float] = None) -> bool:
        """Cooldown over, probation pending, no probe running yet."""
        if not self.probation or self.probing or self.draining:
            return False
        if now is None:
            now = time.monotonic()
        return now >= self.down_until

    def state(self, now: Optional[float] = None) -> str:
        """One-word lifecycle state for health tables and fleet-ctl."""
        if now is None:
            now = time.monotonic()
        if self.draining:
            return "draining"
        if now < self.down_until:
            return "cooldown"
        if self.probing:
            return "probing"
        if self.probation:
            return "probation"
        if now < self.busy_until:
            return "busy"
        if getattr(self.engine, "breaker_open", False):
            return "breaker-open"
        return "eligible"

    def health(self, now: Optional[float] = None) -> dict:
        """Flat health snapshot (docs/fleet.md: autoscaling signals)."""
        if now is None:
            now = time.monotonic()
        hb = getattr(self.engine, "heartbeat_age", None)
        # local members relay their host child's AOT boot report
        # (engine/host.py ready frame → supervisor.aot_report): an
        # autoscaler reading fleet health can tell warm boots (bundle
        # preloaded, seconds to first dispatch) from cold ones (minutes
        # of XLA compiles) and scale accordingly
        aot = getattr(self.engine, "aot_report", None)
        mesh = getattr(self.engine, "mesh_report", None)
        return {
            "name": self.name,
            "kind": self.kind,
            "mesh": mesh,
            "state": self.state(now),
            "available": self.available(now),
            "backlog": self.backlog,
            "inflight": len(self.inflight),
            "losses": self.losses,
            "consecutive_losses": self.consecutive_losses,
            "canaries_ok": self.canaries_ok,
            "draining": self.draining,
            "cooldown_s": max(self.down_until - now, 0.0),
            "busy_s": max(self.busy_until - now, 0.0),
            "heartbeat_age_s": hb,
            "aot": aot,
        }


def make_local_member(
    name: str,
    *,
    host_cmd: Optional[List[str]] = None,
    backend: str = "tpu",
    weights_path: Optional[str] = None,
    max_depth: Optional[int] = None,
    helper_lanes: Optional[int] = None,
    refill: Optional[bool] = None,
    mesh_refill: Optional[bool] = None,
    logger: Optional[Logger] = None,
    hb_interval: float = 1.0,
    hb_timeout: Optional[float] = None,
    backoff=None,
    env: Optional[dict] = None,
    stats_recorder=None,
) -> FleetMember:
    """A SupervisedEngine-backed member with loss-escalation policy.

    bisect_max=0 / quarantine=False / giant breaker threshold: the first
    child death raises out of `go_multiple` as the member-loss event the
    coordinator re-dispatches on (module docstring has the why). The
    member's partial journal still streams (replay=True) and every
    accepted ack is mirrored into `member.acked` via `on_partial`.

    AOT program assets need no member-level wiring: the FISHNET_TPU_AOT*
    settings are engine-affecting, so the supervisor's engine_env
    overlay forwards them into the host child, the child's TpuEngine
    preloads the bundle, and its ready-frame boot report surfaces here
    as `health()["aot"]` — a scale-out member on a warmed machine boots
    in seconds instead of recompiling every program.
    """
    from ..engine.supervisor import SupervisedEngine

    engine = SupervisedEngine(
        host_cmd,
        backend=backend,
        weights_path=weights_path,
        max_depth=max_depth,
        helper_lanes=helper_lanes,
        refill=refill,
        mesh_refill=mesh_refill,
        logger=logger,
        hb_interval=hb_interval,
        hb_timeout=hb_timeout,
        breaker_threshold=_MEMBER_BREAKER_THRESHOLD,
        fallback_factory=None,
        backoff=backoff,
        env=env,
        replay=True,
        bisect_max=0,
        quarantine=False,
        stats_recorder=stats_recorder,
    )
    member = FleetMember(name=name, engine=engine, kind="local")
    engine.on_partial = (
        lambda fp, wire: member.acked.__setitem__(fp, wire)
    )
    return member


# default jax.distributed coordinator for `pod:` members without an
# explicit @host:port (the host-level boundary exchange rides one port
# above it — parallel/distributed.py)
_POD_DEFAULT_COORDINATOR = "127.0.0.1:9791"


def parse_pod_spec(token: str) -> tuple:
    """'pod:N[@host:port]' → (hosts, coordinator address).

    N is the jax.distributed process count of the pod member's ONE
    logical engine; the member's host child runs as process 0 (it hosts
    the coordinator), workers N>0 are launched out-of-band per the
    docs/mesh.md runbook."""
    body = token[len("pod:"):]
    addr = _POD_DEFAULT_COORDINATOR
    if "@" in body:
        body, addr = body.split("@", 1)
    try:
        hosts = int(body)
    except ValueError:
        raise ValueError(
            f"fleet member spec {token!r}: host count after 'pod:' "
            "must be an integer"
        ) from None
    if hosts < 1:
        raise ValueError(
            f"fleet member spec {token!r}: host count must be >= 1"
        )
    host, _, port = addr.rpartition(":")
    if not host or not port.isdigit():
        raise ValueError(
            f"fleet member spec {token!r}: coordinator must be host:port"
        )
    return hosts, addr


def pod_member_env(hosts: int, coordinator: str) -> Dict[str, str]:
    """The engine-env overlay that turns a host child into pod process 0
    (engine/tpu.py calls parallel.distributed.ensure_initialized from
    these settings before first device use)."""
    return {
        "FISHNET_TPU_MESH_HOSTS": str(hosts),
        "FISHNET_TPU_MESH_COORDINATOR": coordinator,
        "FISHNET_TPU_MESH_PROCESS_ID": "0",
    }


def members_from_specs(
    spec: Optional[str] = None,
    *,
    local_factory: Optional[Callable[[str], FleetMember]] = None,
    pod_factory: Optional[Callable[[str, Dict[str, str]], FleetMember]] = None,
    logger: Optional[Logger] = None,
) -> List[FleetMember]:
    """Parse the member-spec grammar into live FleetMembers.

    `local_factory(name)` builds local members (callers close over their
    Config — app.py — or a fakehost command line — tests/chaos/bench);
    it defaults to a bare `make_local_member(name)` from registry
    settings. `pod_factory(name, env)` builds pod members — local
    members whose host child boots as process 0 of a multi-host mesh via
    the given engine-env overlay (pod_member_env). Remote specs become
    `HttpEngine` members directly.
    """
    if spec is None:
        spec = settings.get_str("FISHNET_TPU_FLEET_MEMBERS")
    log = logger or Logger()
    if local_factory is None:
        local_factory = lambda name: make_local_member(name)  # noqa: E731
    if pod_factory is None:
        pod_factory = (  # noqa: E731
            lambda name, env: make_local_member(name, env=env)
        )
    members: List[FleetMember] = []
    seen: Set[str] = set()
    locals_made = 0
    pods_made = 0
    for raw in spec.split(","):
        token = raw.strip()
        if not token:
            continue
        if token.startswith("pod:"):
            hosts, coord = parse_pod_spec(token)
            name = f"pod{pods_made}"
            pods_made += 1
            member = pod_factory(name, pod_member_env(hosts, coord))
            members.append(member)
        elif token == "local" or token.startswith("local*"):
            count = 1
            if "*" in token:
                try:
                    count = int(token.split("*", 1)[1])
                except ValueError:
                    raise ValueError(
                        f"fleet member spec {token!r}: count after "
                        "'local*' must be an integer"
                    ) from None
            if count < 1:
                raise ValueError(
                    f"fleet member spec {token!r}: count must be >= 1"
                )
            for _ in range(count):
                name = f"local{locals_made}"
                locals_made += 1
                members.append(local_factory(name))
        else:
            engine = HttpEngine(token)  # validates host:port
            name = f"{engine.host}:{engine.port}"
            if name in seen:
                raise ValueError(
                    f"fleet member spec lists {name} twice"
                )
            members.append(
                FleetMember(name=name, engine=engine, kind="remote")
            )
        seen.add(members[-1].name)
    if not members:
        raise ValueError(
            "fleet member spec is empty — set FISHNET_TPU_FLEET_MEMBERS "
            "or pass --fleet-members (e.g. 'local*2,http://host:9670')"
        )
    log.info(
        "fleet: %d member(s): %s"
        % (len(members), ", ".join(m.name for m in members))
    )
    return members
