"""Fleet coordinator: one work queue over N engine hosts.

The reference fishnet is itself a fleet — thousands of independent
clients work-stealing from one lichess queue — while everything below
this package assumes one machine. `FleetCoordinator` (coordinator.py)
closes that gap: it implements the `Engine` protocol (via the
`ChunkSubmit` mixin, engine/session.py), so the lichess client,
`fishnet-tpu serve` and bench feed it unchanged, and it spreads the
positions of every chunk across N members by least-backlog admission.

Members come in two kinds (member.py):

- **local** — a `SupervisedEngine`-managed host child on this machine
  (engine/supervisor.py; the scripted fakehost rides the same path for
  tests/chaos/bench);
- **remote** — another machine's `fishnet-tpu serve` endpoint, spoken
  to over the PR-11 HTTP protocol (remote.py reuses serve/protocol.py
  as the wire, so a fleet spans machines with zero new serde).

Exactly-once under member loss: in-flight positions are journaled by
`position_fingerprint` (client/ipc.py), acks stream in per position
(the supervisor's `on_partial` hook), and when a member dies only its
un-acked work is re-dispatched to survivors — strictly fewer
re-searches than resubmitting the chunk. Repeated-poison fingerprints
are quarantined fleet-wide to the CPU fallback. Member trace rings
merge onto one timeline (obs/trace.py) and member counters fold into
one metrics registry (obs/metrics.py), so the whole fleet is one
Perfetto timeline and one Prometheus endpoint.

`python -m fishnet_tpu fleet` serves the coordinator over HTTP
standalone; `serve`/`run` grow a `--fleet` engine factory. The
`Autoscaler` (autoscaler.py) closes the capacity loop on top: it reads
the admission/SLO/fleet congestion signals and adds or drains members
through a pluggable `CapacityProvider` — docs/autoscaling.md has the
control-loop semantics. docs/fleet.md has the topology, the member-spec
grammar and the failure ladder.
"""
from .autoscaler import (
    AutoscaleConfig,
    Autoscaler,
    CapacityProvider,
    LocalProcessProvider,
)
from .coordinator import FleetCoordinator, FleetStats, LossEvent
from .member import FleetMember, make_local_member, members_from_specs
from .remote import HttpEngine

__all__ = [
    "AutoscaleConfig",
    "Autoscaler",
    "CapacityProvider",
    "FleetCoordinator",
    "FleetMember",
    "FleetStats",
    "HttpEngine",
    "LocalProcessProvider",
    "LossEvent",
    "make_local_member",
    "members_from_specs",
]
