"""Engine-protocol client for a remote `fishnet-tpu serve` endpoint.

A remote fleet member is just another machine running the PR-11 HTTP
front-end (fishnet_tpu/serve/). This module conforms that endpoint to
the `Engine` protocol (engine/base.py): `go_multiple(chunk)` maps the
chunk onto one POST /analyse or /bestmove body built through
serve/protocol.py's `request_to_json` — the same inverse-pair serde the
server parses with — so the fleet spans machines with zero new wire
format. Responses come back in request-position order as the pipe-wire
PositionResponse form (results_to_json mirrors response_to_wire), and
`responses_from_wire` rebuilds them after the chunk-protocol
bookkeeping (position_index, url) this side kept is re-injected.

Transport is asyncio streams end to end (lint rule conc-sock-in-loop:
the coordinator's event loop must never block on a socket), one
connection per request with `Connection: close` — the fleet's member
loss detector wants failures to surface as exceptions on THIS dispatch,
not poison a pooled connection for the next one. Every await is bounded
by the chunk deadline via asyncio.wait_for.

Node-budget note: the chunk's per-position budget survives the HTTP
hop within floor-rounding (the serve side re-applies the 7/6 pre-scale
that NodeLimit.get() undoes), so remote results match local ones
whenever depth or deadline binds before the budget does — the parity
contract tests/test_fleet.py pins.
"""
from __future__ import annotations

import asyncio
import json
import random
import time
from typing import Dict, List, Optional, Tuple
from urllib.parse import urlsplit

from ..client.ipc import Chunk, PositionResponse, responses_from_wire
from ..client.wire import AnalysisWork, MoveWork
from ..engine.base import EngineError
from ..engine.session import PRIORITY_BATCH, ChunkSubmit, PositionRequest
from ..serve.protocol import ServeRequest, request_to_json
from ..utils import settings
from .faults import FAULT_LOSS, FAULT_TRANSIENT, MemberBusy, MemberFault, classify

DEFAULT_TIMEOUT_S = 30.0
MAX_RESPONSE_BYTES = 8 * 1024 * 1024
# transient-retry backoff: first pause ~RETRY_BASE_S, doubling with
# jitter, each pause additionally clamped to the remaining deadline
# slack so the retry budget can never outlive the chunk
RETRY_BASE_S = 0.05
RETRY_PAUSE_CAP_S = 2.0


def parse_member_url(url: str) -> Tuple[str, int]:
    """'http://host:port' (or bare 'host:port') → (host, port)."""
    if "//" not in url:
        url = "http://" + url
    parts = urlsplit(url)
    if parts.scheme not in ("", "http"):
        raise ValueError(
            f"fleet member URL {url!r}: only plain http:// is spoken "
            "(front a TLS proxy for anything routable)"
        )
    if not parts.hostname or not parts.port:
        raise ValueError(f"fleet member URL {url!r} needs host and port")
    return parts.hostname, parts.port


def chunk_to_serve_request(chunk: Chunk, now: Optional[float] = None) -> dict:
    """One chunk → one serve body (serve/protocol.py shape).

    The timeout is the chunk's remaining deadline budget; the remote
    admission controller stamps its own deadline from it, so the search
    cutoff rides along instead of resetting at the hop.
    """
    work = chunk.work
    if now is None:
        now = time.monotonic()
    timeout_ms = max(int((chunk.deadline - now) * 1000.0), 1)
    positions = tuple(
        (wp.root_fen, tuple(wp.moves)) for wp in chunk.positions
    )
    # request context crosses the HTTP hop per position (lint rule
    # obs-orphan-span): a re-dispatched sub-chunk can mix positions from
    # different upstream requests, so each slot ships its own ctx and
    # the remote edge keeps the original trace_id instead of minting one
    ctxs = tuple(
        PositionRequest.freeze_ctx(wp.ctx) for wp in chunk.positions
    )
    position_ctx = ctxs if any(c is not None for c in ctxs) else ()
    if isinstance(work, MoveWork):
        req = ServeRequest(
            kind="bestmove", positions=positions, id=str(work.id),
            variant=chunk.variant, level=work.level.level,
            timeout_ms=min(timeout_ms, 600_000),
            position_ctx=position_ctx,
        )
    else:
        assert isinstance(work, AnalysisWork)
        nodes = work.nodes.get(chunk.flavor.eval_flavor())
        req = ServeRequest(
            kind="analysis", positions=positions, id=str(work.id),
            variant=chunk.variant, depth=work.depth, multipv=work.multipv,
            nodes=max(min(nodes, 1_000_000_000), 1),
            priority=PRIORITY_BATCH,
            timeout_ms=min(timeout_ms, 600_000),
            position_ctx=position_ctx,
        )
    return request_to_json(req)


class HttpEngine(ChunkSubmit):
    """`Engine` over a remote serve endpoint; one POST per chunk."""

    def __init__(
        self,
        url: str,
        timeout_s: float = DEFAULT_TIMEOUT_S,
        retry_max: Optional[int] = None,
    ):
        self.host, self.port = parse_member_url(url)
        self.url = f"http://{self.host}:{self.port}"
        self.timeout_s = timeout_s
        self.retry_max = (
            settings.get_int("FISHNET_TPU_FLEET_RETRY_MAX")
            if retry_max is None else int(retry_max)
        )
        self.retries = 0  # transient faults retried in-dispatch

    # ------------------------------------------------------------- dispatch

    async def go_multiple(self, chunk: Chunk) -> List[PositionResponse]:
        path = (
            "/bestmove" if isinstance(chunk.work, MoveWork) else "/analyse"
        )
        body = chunk_to_serve_request(chunk)
        budget = min(chunk.deadline - time.monotonic(), self.timeout_s)
        if budget <= 0:
            raise EngineError(
                f"fleet member {self.url}: chunk deadline already passed"
            )
        status, payload, retry_after = await self._round_trip(
            "POST", path, body, budget
        )
        if status == 429:
            # admission shed (serve/admission.py): designed backpressure,
            # not member death — surface the Retry-After hint so the
            # coordinator reroutes without a loss event
            hint = retry_after
            if isinstance(payload, dict) and "retry_after" in payload:
                try:
                    hint = float(payload["retry_after"])
                except (TypeError, ValueError):
                    pass
            raise MemberBusy(
                f"fleet member {self.url} shed batch {chunk.work.id} "
                f"(retry after {hint:.0f}s)",
                retry_after=hint,
            )
        if status != 200:
            detail = payload.get("error", "") if isinstance(payload, dict) \
                else ""
            raise EngineError(
                f"fleet member {self.url} answered HTTP {status} "
                f"for batch {chunk.work.id}: {detail}"
            )
        results = payload.get("results") if isinstance(payload, dict) else None
        if not isinstance(results, list) or \
                len(results) != len(chunk.positions):
            raise EngineError(
                f"fleet member {self.url} returned "
                f"{len(results) if isinstance(results, list) else '?'} "
                f"results for {len(chunk.positions)} positions"
            )
        # results_to_json strips the chunk-protocol bookkeeping (the HTTP
        # answer orders by the request's positions list); restore it from
        # the chunk this side still holds before rebuilding responses
        for wp, wire in zip(chunk.positions, results):
            if not isinstance(wire, dict):
                raise EngineError(
                    f"fleet member {self.url} sent a malformed result"
                )
            wire["position_index"] = wp.position_index
            wire["url"] = wp.url
        try:
            return responses_from_wire(chunk.work, results)
        except (KeyError, TypeError, ValueError) as e:
            raise EngineError(
                f"fleet member {self.url} sent a malformed result: {e}"
            ) from e

    async def healthz(self, timeout_s: float = 2.0) -> dict:
        """The serve endpoint's liveness/occupancy summary — the fleet's
        remote heartbeat (queued/inflight feed backlog accounting)."""
        status, payload, _ = await self._round_trip(
            "GET", "/healthz", None, timeout_s
        )
        if status != 200 or not isinstance(payload, dict):
            raise EngineError(
                f"fleet member {self.url} healthz answered HTTP {status}"
            )
        return payload

    async def close(self) -> None:
        pass  # connection-per-request: nothing pooled to tear down

    # ------------------------------------------------------------ transport

    async def _round_trip(
        self, method: str, path: str, body_obj: Optional[dict],
        timeout_s: float,
    ) -> Tuple[int, object, float]:
        """One logical request: transient faults (fleet/faults.py) are
        retried in-dispatch with jittered exponential backoff, bounded
        by BOTH an attempt cap (retry_max) and the deadline slack — a
        single RST never costs a member-loss event. Loss-kind faults
        and exhausted retries escalate as MemberFault(kind=loss)."""
        deadline = time.monotonic() + timeout_s
        pause = RETRY_BASE_S
        last: Optional[MemberFault] = None
        for attempt in range(self.retry_max + 1):
            slack = deadline - time.monotonic()
            if slack <= 0:
                break
            try:
                return await self._attempt(method, path, body_obj, slack)
            except MemberFault as fault:
                if fault.kind != FAULT_TRANSIENT:
                    raise
                last = fault
                if attempt < self.retry_max:
                    self.retries += 1
                    nap = min(
                        pause * (0.5 + random.random()),
                        max(deadline - time.monotonic(), 0.0),
                    )
                    pause = min(pause * 2.0, RETRY_PAUSE_CAP_S)
                    if nap > 0:
                        await asyncio.sleep(nap)
        raise MemberFault(
            f"fleet member {self.url}: transient fault persisted past "
            f"the retry budget ({last})",
            kind=FAULT_LOSS,
        ) from last

    async def _attempt(
        self, method: str, path: str, body_obj: Optional[dict],
        timeout_s: float,
    ) -> Tuple[int, object, float]:
        """One wire attempt, classified: the `wrote` flag survives the
        wait_for cancellation, so a timeout (or reset) before the
        request bytes left this host is transient, after is loss."""
        state: Dict[str, bool] = {"wrote": False}
        try:
            return await asyncio.wait_for(
                self._round_trip_inner(method, path, body_obj, state),
                timeout=timeout_s,
            )
        except asyncio.TimeoutError as e:
            raise MemberFault(
                f"fleet member {self.url}: no answer within "
                f"{timeout_s:.1f}s",
                kind=classify(e, wrote=state["wrote"]),
            ) from None
        except (ConnectionError, OSError, asyncio.IncompleteReadError) as e:
            raise MemberFault(
                f"fleet member {self.url}: connection failed: {e}",
                kind=classify(e, wrote=state["wrote"]),
            ) from e

    async def _round_trip_inner(
        self, method: str, path: str, body_obj: Optional[dict],
        state: Optional[Dict[str, bool]] = None,
    ) -> Tuple[int, object, float]:
        payload = b"" if body_obj is None else \
            json.dumps(body_obj).encode("utf-8")
        reader, writer = await asyncio.open_connection(self.host, self.port)
        try:
            head = (
                f"{method} {path} HTTP/1.1\r\n"
                f"Host: {self.host}:{self.port}\r\n"
                "Content-Type: application/json\r\n"
                f"Content-Length: {len(payload)}\r\n"
                "Connection: close\r\n\r\n"
            )
            if state is not None:
                state["wrote"] = True
            writer.write(head.encode("latin-1") + payload)
            await writer.drain()
            status_line = await reader.readline()
            parts = status_line.decode("latin-1", "replace").split()
            if len(parts) < 2 or not parts[1].isdigit():
                raise EngineError(
                    f"fleet member {self.url} sent a malformed status line"
                )
            status = int(parts[1])
            length = 0
            retry_after = 1.0
            while True:
                line = await reader.readline()
                if line in (b"\r\n", b"\n", b""):
                    break
                name, _, value = line.decode("latin-1").partition(":")
                lowered = name.strip().lower()
                if lowered == "content-length":
                    try:
                        length = int(value.strip())
                    except ValueError:
                        raise EngineError(
                            f"fleet member {self.url} sent a bad "
                            "Content-Length"
                        ) from None
                elif lowered == "retry-after":
                    try:
                        retry_after = float(value.strip())
                    except ValueError:
                        pass  # date-form Retry-After: keep the default
            if length > MAX_RESPONSE_BYTES:
                raise EngineError(
                    f"fleet member {self.url} response too large ({length}B)"
                )
            raw = await reader.readexactly(length) if length > 0 else b""
        finally:
            writer.close()
            try:
                await writer.wait_closed()
            except (ConnectionError, OSError):
                pass  # close raced the peer's reset; already closed
        try:
            body = json.loads(raw.decode("utf-8")) if raw else {}
        except (ValueError, UnicodeDecodeError) as e:
            raise EngineError(
                f"fleet member {self.url} sent a non-JSON body: {e}"
            ) from e
        return status, body, retry_after
