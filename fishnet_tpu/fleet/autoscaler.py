"""Elastic capacity: the autoscaling control loop over the fleet.

The serve/fleet stack exports every congestion signal — admission queue
depth and EWMA drain rate (serve/admission.py), per-tenant deadline-miss
counters (obs/metrics.py SloRecorder), per-member occupancy/backlog and
lifecycle state (fleet/coordinator.py health tables) — but until this
module nothing *decided*: a 10x flash crowd just shed 429s until a human
ran `fleet-ctl add`. The Autoscaler closes ROADMAP item 4's loop:

- **Signals** are read straight off the live objects each tick (the
  same numbers the registry gauges export — docs/serving.md "Capacity
  signals", docs/fleet.md "Autoscaling signals"): admission occupancy
  `(inflight, queued)`, the measured drain rate, the fleet-wide member
  backlog, each member's one-word lifecycle state, and the SloRecorder
  deadline-miss counters out of the registry snapshot.
- **Decisions** are add/drain against a pluggable CapacityProvider.
  LocalProcessProvider spawns local members through the coordinator's
  `add_member("local")` (the same `make_local_member` factory fleet-ctl
  uses); a real TPU-provisioning provider plugs in later behind the
  same four methods.
- **Hysteresis**: scale UP only after `up_ticks` consecutive pressure
  ticks (queued >= up_queue, or a deadline miss recorded this tick);
  scale DOWN only after `down_ticks` consecutive fully-idle ticks.
  The asymmetry (down_ticks >> up_ticks) is the anti-flap guarantee:
  one burst costs at most one up/down reversal.
- **Loss cooldown**: never scale down while the coordinator is
  mid-recovery-ladder — any member in cooldown/probing/probation, or
  within `loss_cooldown_s` of the last loss event. Removing capacity
  while redispatch/probation is running would turn a transient fault
  into a real brown-out; blocked decisions are counted
  (`fishnet_autoscale_down_blocked_total`) and logged.
- **Clamp**: member count stays inside [min_members, max_members].
  Only members the autoscaler itself added are ever drained — the
  configured floor fleet is never touched, so "return to floor" is
  structural, not emergent.
- **Cost accounting**: `fishnet_autoscale_member_seconds_total`
  accumulates members x wall-clock each tick — the number a capacity
  bill is proportional to — next to `fishnet_autoscale_members` /
  `_up_total` / `_down_total` / `_down_blocked_total` in the one
  metrics registry. Every decision also lands as an
  `autoscale.decision` trace instant on the shared timeline.

Capacity changes never alter answers: the autoscaler only calls
add_member/begin_drain/remove_member, and the coordinator's dispatch
planning plus the exactly-once fingerprint ledger keep search results
bit-identical with the loop on or off (tests/test_autoscaler.py).

Pure stdlib, no JAX at module scope (the fleet/serve constraint).
Single-writer: the loop runs as one asyncio task on the serve loop;
nothing else mutates its streak counters.
"""
from __future__ import annotations

import asyncio
import time
from dataclasses import dataclass
from typing import List, Optional

from ..client.logger import Logger
from ..obs import metrics as obs_metrics
from ..obs import trace as obs_trace

__all__ = [
    "AutoscaleConfig",
    "Autoscaler",
    "CapacityProvider",
    "Decision",
    "LocalProcessProvider",
]


class CapacityProvider:
    """How the autoscaler acquires and releases capacity. Four methods,
    deliberately tiny so a cloud TPU provisioner can implement them:
    `add` returns the new member's name once it is serving; drain is
    split into begin/poll/remove so in-flight work always finishes
    before capacity disappears (zero lost positions by construction)."""

    async def add(self) -> str:
        raise NotImplementedError

    def begin_drain(self, name: str) -> None:
        raise NotImplementedError

    def drained(self, name: str) -> bool:
        raise NotImplementedError

    async def remove(self, name: str) -> None:
        raise NotImplementedError


class LocalProcessProvider(CapacityProvider):
    """Local process spawn via the coordinator's runtime-membership
    path: `add_member("local")` builds the member through the same
    `local_factory` / `make_local_member` closure fleet-ctl and the
    POST /fleet/members endpoint use, so an autoscaled member is
    indistinguishable from a hand-added one."""

    def __init__(self, coordinator, spec: str = "local") -> None:
        self.coordinator = coordinator
        self.spec = spec

    async def add(self) -> str:
        row = await self.coordinator.add_member(self.spec)
        return row["name"]

    def begin_drain(self, name: str) -> None:
        self.coordinator.begin_drain(name)

    def drained(self, name: str) -> bool:
        return self.coordinator.drained(name)

    async def remove(self, name: str) -> None:
        await self.coordinator.remove_member(name)


@dataclass(frozen=True)
class AutoscaleConfig:
    """Control-loop knobs (registry: FISHNET_TPU_AUTOSCALE*)."""

    min_members: int = 1
    max_members: int = 4
    interval_s: float = 1.0
    # pressure: queued positions at admission that count as undersized
    up_queue: int = 1
    # hysteresis: consecutive pressure/idle ticks before acting
    up_ticks: int = 2
    down_ticks: int = 5
    # never scale down within this many seconds of a member-loss event
    loss_cooldown_s: float = 30.0
    # a draining member that still holds work after this long gets a
    # drain-stalled decision logged (and keeps draining — work is never
    # abandoned to meet a schedule)
    drain_timeout_s: float = 30.0

    @classmethod
    def from_settings(cls) -> "AutoscaleConfig":
        from ..utils import settings

        return cls(
            min_members=settings.get_int("FISHNET_TPU_AUTOSCALE_MIN"),
            max_members=settings.get_int("FISHNET_TPU_AUTOSCALE_MAX"),
            interval_s=settings.get_int(
                "FISHNET_TPU_AUTOSCALE_INTERVAL_MS") / 1000.0,
            up_queue=settings.get_int("FISHNET_TPU_AUTOSCALE_UP_QUEUE"),
            up_ticks=settings.get_int("FISHNET_TPU_AUTOSCALE_UP_TICKS"),
            down_ticks=settings.get_int("FISHNET_TPU_AUTOSCALE_DOWN_TICKS"),
            loss_cooldown_s=float(settings.get_int(
                "FISHNET_TPU_AUTOSCALE_LOSS_COOLDOWN_S")),
        )


@dataclass(frozen=True)
class Decision:
    """One control-loop action, kept for runbooks and the chaos gate."""

    at: float  # time.monotonic()
    action: str  # up | down | down-blocked | removed | drain-stalled
    reason: str
    members: int


@dataclass
class AutoscalerStats:
    ticks: int = 0
    ups: int = 0
    downs: int = 0
    downs_blocked: int = 0
    member_seconds: float = 0.0


class Autoscaler:
    """The control loop. Reads signals, decides, actuates, accounts.

    One structural change is in flight at a time: a scale-down is a
    begin_drain now and a remove on the later tick that observes the
    drain complete, and no new decision is taken while that drain is
    pending — capacity changes stay serialized and observable.
    """

    def __init__(
        self,
        coordinator,
        admission,
        *,
        provider: Optional[CapacityProvider] = None,
        config: Optional[AutoscaleConfig] = None,
        registry: Optional[obs_metrics.MetricsRegistry] = None,
        logger: Optional[Logger] = None,
    ) -> None:
        self.coordinator = coordinator
        self.admission = admission
        self.provider = provider or LocalProcessProvider(coordinator)
        self.config = config or AutoscaleConfig()
        if self.config.min_members < 1:
            raise ValueError("autoscale: min_members must be >= 1")
        if self.config.max_members < self.config.min_members:
            raise ValueError("autoscale: max_members < min_members")
        self.registry = (registry if registry is not None
                         else getattr(coordinator, "registry", None)
                         or obs_metrics.REGISTRY)
        self.logger = logger or Logger()
        self.stats = AutoscalerStats()
        self.decisions: List[Decision] = []
        self._owned: List[str] = []  # members this loop added (LIFO)
        self._draining: Optional[str] = None
        self._drain_deadline = 0.0
        self._drain_stalled = False
        self._up_streak = 0
        self._down_streak = 0
        self._last_losses: Optional[int] = None
        self._loss_cooldown_until = 0.0
        self._last_miss_total: Optional[float] = None
        self._last_tick: Optional[float] = None
        self._stop = asyncio.Event()
        self._task: Optional[asyncio.Task] = None
        self._g_members = self.registry.gauge(
            "fishnet_autoscale_members",
            "fleet member count as seen by the autoscaler",
        )
        self._g_floor = self.registry.gauge(
            "fishnet_autoscale_floor", "autoscaler min-member clamp")
        self._g_ceiling = self.registry.gauge(
            "fishnet_autoscale_ceiling", "autoscaler max-member clamp")
        self._c_member_seconds = self.registry.counter(
            "fishnet_autoscale_member_seconds_total",
            "accumulated member-count x wall-clock seconds (cost gauge)",
        )
        self._c_up = self.registry.counter(
            "fishnet_autoscale_up_total", "scale-up decisions")
        self._c_down = self.registry.counter(
            "fishnet_autoscale_down_total", "scale-down decisions")
        self._c_down_blocked = self.registry.counter(
            "fishnet_autoscale_down_blocked_total",
            "scale-downs refused mid-recovery-ladder",
        )
        self._g_floor.set(self.config.min_members)
        self._g_ceiling.set(self.config.max_members)

    # ----------------------------------------------------------- lifecycle

    def start(self) -> None:
        """Spawn the loop task on the running event loop."""
        if self._task is not None:
            raise RuntimeError("autoscaler already started")
        self._stop.clear()
        self._task = asyncio.ensure_future(self._run())

    async def stop(self) -> None:
        """Stop the loop; a pending drain is left to the coordinator
        (close() tears members down anyway)."""
        self._stop.set()
        # claim the task before awaiting: a second concurrent stop()
        # sees None and returns instead of cancelling a cleared slot
        task, self._task = self._task, None
        if task is not None:
            try:
                await asyncio.wait_for(task, timeout=10.0)
            except asyncio.TimeoutError:
                task.cancel()

    async def _run(self) -> None:
        while not self._stop.is_set():
            try:
                await self.tick()
            except Exception as e:  # one bad tick must not kill the loop
                self.logger.error(f"autoscale: tick failed: {e}")
            try:
                await asyncio.wait_for(
                    self._stop.wait(), timeout=self.config.interval_s)
            except asyncio.TimeoutError:
                pass

    # ------------------------------------------------------------- signals

    def _miss_delta(self) -> float:
        """Deadline misses recorded since the previous tick, summed over
        every (kind, tenant) SloRecorder counter in the registry."""
        total = sum(
            v for k, v in self.registry.snapshot().items()
            if k.startswith("fishnet_slo_deadline_miss_total_")
        )
        prev = self._last_miss_total
        self._last_miss_total = total
        if prev is None:
            return 0.0
        return max(0.0, total - prev)

    def recovery_ladder_active(self, now: Optional[float] = None) -> bool:
        """True while any member sits on the loss ladder (cooldown /
        probing / probation) or the last loss event is closer than
        loss_cooldown_s — the scale-down veto window."""
        if now is None:
            now = time.monotonic()
        if now < self._loss_cooldown_until:
            return True
        return any(
            m.state(now) in ("cooldown", "probing", "probation")
            for m in self.coordinator.members
        )

    # -------------------------------------------------------------- loop

    def _record(self, action: str, reason: str, members: int) -> None:
        self.decisions.append(
            Decision(at=time.monotonic(), action=action, reason=reason,
                     members=members))
        del self.decisions[:-1000]  # bound the log
        obs_trace.instant("autoscale.decision", "fleet", action=action,
                          reason=reason, members=members)
        self.logger.info(f"autoscale: {action} ({reason}); "
                         f"members={members}")

    # the control loop is the only writer of the streak/drain fields:
    # _run() awaits each tick() to completion before the next, and the
    # serve wiring never calls tick() concurrently with the loop
    # fishnet-lint: single-writer
    async def tick(self) -> None:
        """One control-loop pass. Public so tests and the chaos harness
        can drive the loop deterministically without the timer."""
        cfg = self.config
        now = time.monotonic()
        members = len(self.coordinator.members)
        if self._last_tick is not None:
            dt = now - self._last_tick
            self.stats.member_seconds += members * dt
            self._c_member_seconds.inc(members * dt)
        self._last_tick = now
        self.stats.ticks += 1
        self._g_members.set(members)

        # loss accounting first: a loss this tick opens the veto window
        losses = self.coordinator.stats.losses
        if self._last_losses is None:
            self._last_losses = losses
        elif losses > self._last_losses:
            self._last_losses = losses
            self._loss_cooldown_until = now + cfg.loss_cooldown_s

        inflight, queued = self.admission.occupancy()
        backlog = sum(m.backlog for m in self.coordinator.members)
        misses = self._miss_delta()
        pressure = queued >= cfg.up_queue or misses > 0
        idle = queued == 0 and inflight == 0 and backlog == 0
        self._up_streak = self._up_streak + 1 if pressure else 0
        self._down_streak = self._down_streak + 1 if idle else 0

        # a pending drain serializes all structural change: finish it
        # (or report it stalled) before considering anything else
        if self._draining is not None:
            name = self._draining
            if self.provider.drained(name):
                await self.provider.remove(name)
                self._draining = None
                self._drain_stalled = False
                self._record("removed", f"{name} drained",
                             len(self.coordinator.members))
            elif now > self._drain_deadline and not self._drain_stalled:
                self._drain_stalled = True  # report once, keep draining
                self._record("drain-stalled",
                             f"{name} still busy after "
                             f"{cfg.drain_timeout_s:.0f}s", members)
            return

        if (pressure and self._up_streak >= cfg.up_ticks
                and members < cfg.max_members):
            name = await self.provider.add()
            self._owned.append(name)
            self.stats.ups += 1
            self._c_up.inc()
            self._up_streak = 0
            self._down_streak = 0
            self._record(
                "up",
                f"queued={queued} misses={misses:.0f} -> +{name}",
                len(self.coordinator.members))
            self._g_members.set(len(self.coordinator.members))
            return

        if (self._down_streak >= cfg.down_ticks
                and members > cfg.min_members and self._owned):
            if self.recovery_ladder_active(now):
                self.stats.downs_blocked += 1
                self._c_down_blocked.inc()
                self._down_streak = 0  # re-earn idleness after the ladder
                self._record("down-blocked",
                             "recovery ladder active", members)
                return
            name = self._owned.pop()
            self.provider.begin_drain(name)
            self._draining = name
            self._drain_deadline = now + cfg.drain_timeout_s
            self._drain_stalled = False
            self.stats.downs += 1
            self._c_down.inc()
            self._down_streak = 0
            self._record("down", f"idle -> draining {name}", members)

    # ----------------------------------------------------------- snapshot

    def snapshot(self) -> dict:
        """Machine-readable loop state for /healthz, bench and chaos."""
        return {
            "members": len(self.coordinator.members),
            "floor": self.config.min_members,
            "ceiling": self.config.max_members,
            "owned": list(self._owned),
            "draining": self._draining,
            "ticks": self.stats.ticks,
            "ups": self.stats.ups,
            "downs": self.stats.downs,
            "downs_blocked": self.stats.downs_blocked,
            "member_seconds": round(self.stats.member_seconds, 3),
            "decisions": [
                {"action": d.action, "reason": d.reason,
                 "members": d.members}
                for d in self.decisions[-20:]
            ],
        }
