"""The fleet coordinator: one Engine over N members, exactly-once.

`FleetCoordinator.go_multiple(chunk)` splits the chunk's positions
across the available members (least-backlog greedy: each position goes
to the member with the fewest outstanding positions, counting what this
very planning round already assigned) and dispatches each member its
sub-chunk concurrently. Everything above — `EngineSession`, the lichess
client workers, `fishnet-tpu serve`, bench — feeds it unchanged because
it speaks the same `Engine` protocol via `ChunkSubmit`.

Exactly-once under member loss, the invariant the chaos gate
(tools/chaos.py --scenario fleet-member-loss) enforces:

- every position is keyed by `position_fingerprint` and recorded in the
  member's in-flight ledger before its sub-chunk dispatches;
- acks stream back per position (local members mirror their partial
  journal through `SupervisedEngine.on_partial`; remote members answer
  whole sub-chunks, which ack every position at once);
- when a member's dispatch raises `EngineError` (child SIGKILLed, HTTP
  endpoint gone), the coordinator harvests the acked results it already
  holds and re-dispatches ONLY the un-acked remainder to survivors — a
  strict subset of the member's in-flight set whenever at least one ack
  landed, and always strictly fewer re-searches than resubmitting the
  chunk;
- exactly one loss event per member death: cooldown (`down_until`),
  one `fleet.member-loss` trace instant, one loss counter increment,
  one flight-recorder dump, one `LossEvent` appended to `loss_log`;
- a fingerprint that is un-acked across `POISON_THRESHOLD` distinct
  losses is quarantined fleet-wide (it killed two different members —
  the position is the poison, not the host) and answered by the CPU
  fallback; later chunks pre-route it before it can touch a member.

Re-dispatch rounds are bounded by FISHNET_TPU_FLEET_REDISPATCH_MAX;
a lost member sits out FISHNET_TPU_FLEET_LOSS_WINDOW seconds before
the planner will consider it again (its own supervisor respawn backoff
still applies underneath).

Self-healing (ISSUE 15), four coordinated layers on top of that ledger:

- fault taxonomy (fleet/faults.py): the remote transport retries
  transient faults in-dispatch, surfaces 429 sheds as `MemberBusy`
  (rerouted here without a loss event, the member parked until its
  Retry-After hint expires), and only genuine losses run the ladder;
- probed readmission: after its cooldown a lost member enters
  probation — a healthz probe plus one canary chunk must succeed before
  the planner gives it real work; repeated losses escalate the cooldown
  exponentially up to FISHNET_TPU_FLEET_COOLDOWN_MAX, so a
  permanently-dead member costs only probes;
- hedged dispatch (FISHNET_TPU_FLEET_HEDGE, off by default): when a
  dispatched sub-chunk's deadline slack drops below
  FISHNET_TPU_FLEET_HEDGE_SLACK_MS and a free member exists, the
  unfinished positions are duplicated there; first answer wins through
  the same fingerprint ledger, the loser is discarded and counted —
  results stay bit-identical with hedging on or off;
- runtime membership: add_member/begin_drain/drained/remove_member
  back the serve front-end's /fleet/members admin surface and the
  `fishnet-tpu fleet-ctl` CLI, so a rolling restart is drain → wait
  empty → remove → re-add, with zero lost positions.

Observability folds to one pane: member trace rings already merge into
the shared module recorder (each local supervisor absorbs its child's
spans with a per-member clock sync), the coordinator adds
`fleet.dispatch` spans and loss instants around them, and
`fold_metrics()` mirrors the fleet ledger plus every local member's
`SupervisorStats` into the metrics registry — one Perfetto timeline,
one Prometheus endpoint for the whole fleet.
"""
from __future__ import annotations

import asyncio
import time
from dataclasses import asdict, dataclass, field, replace
from typing import Dict, List, Optional, Set, Tuple

from ..cache.keys import key_for_chunk_position
from ..cache.store import AnalysisCache
from ..client.ipc import (
    Chunk,
    PositionResponse,
    WorkPosition,
    position_fingerprint,
    response_to_wire,
    responses_from_wire,
)
from ..client.logger import Logger
from ..client.wire import AnalysisWork, EngineFlavor, NodeLimit
from ..engine.base import EngineError
from ..engine.session import ChunkSubmit
from ..obs import metrics as obs_metrics
from ..obs import trace as obs_trace
from ..utils import settings
from .faults import MemberBusy
from .member import FleetMember, make_local_member
from .remote import HttpEngine

# distinct member losses with the same fingerprint un-acked before the
# position is declared poison and quarantined fleet-wide
POISON_THRESHOLD = 2

# the canary is a fixed tiny search (startpos, depth 1): cheap enough
# that probing a permanently-dead member forever costs ~nothing, real
# enough that "passed" means the whole dispatch path works
_CANARY_FEN = "rnbqkbnr/pppppppp/8/8/8/8/PPPPPPPP/RNBQKBNR w KQkq - 0 1"
CANARY_TTL_S = 10.0

_Pair = Tuple[str, WorkPosition]  # (fingerprint, position)

# _dispatch_member outcome tags: how _dispatch_all treats the leftover
_OK = "ok"
_LOSS = "loss"  # poison-count, then re-dispatch
_BUSY = "busy"  # reroute only — never a loss, never poison


@dataclass
class LossEvent:
    """One member death, as the exactly-once ledger saw it."""

    member: str
    reason: str
    inflight_fps: Tuple[str, ...]  # what the member held when it died
    acked_fps: Tuple[str, ...]  # harvested — NOT re-searched
    redispatched_fps: Tuple[str, ...]  # un-acked remainder, re-dispatched


@dataclass
class FleetStats:
    """Coordinator counters; absorbed into the metrics registry by
    `fold_metrics` (same shape-contract as SupervisorStats)."""

    chunks_ok: int = 0
    dispatches: int = 0  # member sub-chunk dispatches
    dispatched_positions: int = 0
    acks_harvested: int = 0  # answered from a dead member's acks
    redispatches: int = 0  # positions re-dispatched after a loss
    redispatch_rounds: int = 0
    losses: int = 0
    quarantined: int = 0  # fingerprints quarantined fleet-wide
    quarantine_routed: int = 0  # positions answered by the fallback
    busy_reroutes: int = 0  # positions rerouted off a 429 shed
    probes: int = 0  # probation probes attempted
    probe_failures: int = 0  # probes that re-escalated the cooldown
    canaries_ok: int = 0  # canary chunks served during probation
    readmissions: int = 0  # members readmitted after probation
    hedges: int = 0  # positions duplicated to a second member
    hedge_wins: int = 0  # positions whose hedge answered first
    hedge_losses: int = 0  # hedge dispatches that themselves died
    drains: int = 0  # members put into drain
    members_added: int = 0  # runtime membership adds
    members_removed: int = 0  # runtime membership removals


class FleetCoordinator(ChunkSubmit):
    """`Engine` protocol over N `FleetMember`s."""

    _submit_flavor = EngineFlavor.TPU

    def __init__(
        self,
        members: List[FleetMember],
        *,
        logger: Optional[Logger] = None,
        redispatch_max: Optional[int] = None,
        loss_window: Optional[float] = None,
        registry: Optional[obs_metrics.MetricsRegistry] = None,
        fallback_factory=None,
        hedge: Optional[bool] = None,
        hedge_slack_ms: Optional[int] = None,
        probation: Optional[bool] = None,
        cooldown_max: Optional[float] = None,
        local_factory=None,
        cache: Optional[AnalysisCache] = None,
    ) -> None:
        if not members:
            raise ValueError("a fleet needs at least one member")
        self.members = list(members)
        self.logger = logger or Logger()
        self.redispatch_max = (
            settings.get_int("FISHNET_TPU_FLEET_REDISPATCH_MAX")
            if redispatch_max is None else int(redispatch_max)
        )
        self.loss_window = float(
            settings.get_int("FISHNET_TPU_FLEET_LOSS_WINDOW")
            if loss_window is None else loss_window
        )
        self.hedge = (
            settings.get_bool("FISHNET_TPU_FLEET_HEDGE")
            if hedge is None else bool(hedge)
        )
        self.hedge_slack_s = (
            settings.get_int("FISHNET_TPU_FLEET_HEDGE_SLACK_MS")
            if hedge_slack_ms is None else int(hedge_slack_ms)
        ) / 1000.0
        self.probation = (
            settings.get_bool("FISHNET_TPU_FLEET_PROBATION")
            if probation is None else bool(probation)
        )
        self.cooldown_max = float(
            settings.get_int("FISHNET_TPU_FLEET_COOLDOWN_MAX")
            if cooldown_max is None else cooldown_max
        )
        # runtime `add_member("local")` builds through this (app.py
        # closes it over the Config; tests over a fakehost command line)
        self.local_factory = local_factory
        # the fleet-shared analysis cache (fishnet_tpu/cache/): every
        # member's delivered results land in ONE hit set, so member B
        # never re-searches what member A already answered
        self.cache = cache
        self.registry = registry or obs_metrics.REGISTRY
        self.fallback_factory = fallback_factory
        self.stats = FleetStats()
        self._probe_tasks: Dict[str, asyncio.Task] = {}
        self._stragglers: Set[asyncio.Task] = set()
        self.loss_log: List[LossEvent] = []
        self._quarantine: Set[str] = set()
        self._poison: Dict[str, int] = {}
        self._fallback = None
        self._closing = False
        self._trace_dir = settings.get_str("FISHNET_TPU_TRACE_DIR")
        if self._trace_dir and obs_trace.RECORDER is None:
            obs_trace.install_from_settings("fleet")

    # -------------------------------------------------------------- lifecycle

    async def start(self) -> None:
        """Start every local member's engine host concurrently. A member
        that fails to come up enters loss cooldown instead of failing
        the fleet — survivors carry the queue, the planner retries it
        after the window."""

        async def _start_one(member: FleetMember):
            start = getattr(member.engine, "start", None)
            if start is None:
                return  # remote members have no child to spawn
            try:
                await start()
            except EngineError as e:
                self._note_loss(member, f"start failed: {e}", [], {})

        await asyncio.gather(*(_start_one(m) for m in self.members))
        live = [m for m in self.members if m.available()]
        if not live:
            raise EngineError("fleet: no member came up")
        self.logger.info(
            f"fleet: {len(live)}/{len(self.members)} member(s) ready"
        )

    async def close(self) -> None:
        self._closing = True
        probes = list(self._probe_tasks.values())
        self._probe_tasks.clear()
        for task in probes:
            task.cancel()
        if probes:
            await asyncio.gather(*probes, return_exceptions=True)
        # detached straggler dispatches settle their ledgers before the
        # engines under them are torn down
        if self._stragglers:
            await asyncio.gather(
                *list(self._stragglers), return_exceptions=True
            )
        engines = [m.engine for m in self.members]
        if self._fallback is not None:
            engines.append(self._fallback)
            self._fallback = None
        await asyncio.gather(
            *(e.close() for e in engines), return_exceptions=True
        )

    # ------------------------------------------------------------ membership

    def begin_drain(self, member_name: Optional[str] = None) -> None:
        """Stop planning work onto a member (or all of them); in-flight
        sub-chunks finish normally. The rolling-restart story in
        docs/fleet.md drains a member before removing it."""
        for m in self.members:
            if member_name is None or m.name == member_name:
                if not m.draining:
                    m.draining = True
                    self.stats.drains += 1
                    obs_trace.instant(
                        "fleet.drain", "fleet", member=m.name,
                        backlog=m.backlog, inflight=len(m.inflight),
                    )
                    self.logger.info(
                        f"fleet: draining member {m.name} "
                        f"({m.backlog} position(s) in flight)"
                    )

    def drain_member(self, name: str) -> dict:
        """Validated drain for the admin surface: unknown members raise
        instead of silently matching nothing. Returns the member's
        health row plus whether the drain is already complete."""
        member = self._member(name)
        self.begin_drain(name)
        return {"member": member.health(), "drained": self.drained(name)}

    def drained(self, member_name: str) -> bool:
        """True when a draining member holds no in-flight work — safe
        to SIGTERM/remove with zero lost positions."""
        m = self._member(member_name)
        return m.draining and m.backlog == 0 and not m.inflight

    async def add_member(self, spec: str) -> dict:
        """Grow the fleet at runtime from one member-spec token
        ('local' or 'http://host:port'); local members are started
        before they join the planner. Returns the new health row."""
        token = spec.strip()
        if not token:
            raise EngineError("fleet: empty member spec")
        if token == "local" or token.startswith("local*"):
            if "*" in token:
                raise EngineError(
                    "fleet: add one member at a time (no 'local*N')"
                )
            name = self._next_local_name()
            factory = self.local_factory or (
                lambda n: make_local_member(n, logger=self.logger)
            )
            member = factory(name)
            start = getattr(member.engine, "start", None)
            if start is not None:
                await start()
        else:
            engine = HttpEngine(token)  # validates host:port
            name = f"{engine.host}:{engine.port}"
            if any(m.name == name for m in self.members):
                raise EngineError(f"fleet: member {name} already exists")
            member = FleetMember(name=name, engine=engine, kind="remote")
        self.members.append(member)
        self.stats.members_added += 1
        obs_trace.instant(
            "fleet.member-added", "fleet", member=member.name,
            kind=member.kind,
        )
        self.logger.info(
            f"fleet: member {member.name} added "
            f"({len(self.members)} member(s))"
        )
        self.fold_metrics()
        return member.health()

    async def remove_member(self, name: str, force: bool = False) -> dict:
        """Shrink the fleet at runtime. Refuses while the member still
        holds in-flight work (drain first) unless forced; refuses to
        remove the last member outright."""
        member = self._member(name)
        if len(self.members) == 1:
            raise EngineError(
                "fleet: refusing to remove the last member"
            )
        if not force and (member.backlog or member.inflight):
            raise EngineError(
                f"fleet: member {name} still holds "
                f"{member.backlog} position(s) — drain it first"
            )
        self.members.remove(member)
        task = self._probe_tasks.pop(member.name, None)
        if task is not None:
            task.cancel()
        try:
            await member.engine.close()
        except (EngineError, OSError) as e:
            self.logger.warn(
                f"fleet: closing removed member {name} failed: {e}"
            )
        self.stats.members_removed += 1
        obs_trace.instant(
            "fleet.member-removed", "fleet", member=name, kind=member.kind,
        )
        self.logger.info(
            f"fleet: member {name} removed "
            f"({len(self.members)} member(s) remain)"
        )
        self.fold_metrics()
        return member.health()

    def _member(self, name: str) -> FleetMember:
        for m in self.members:
            if m.name == name:
                return m
        raise EngineError(f"fleet: no member named {name!r}")

    def _next_local_name(self) -> str:
        taken = {m.name for m in self.members}
        n = 0
        while f"local{n}" in taken:
            n += 1
        return f"local{n}"

    # ---------------------------------------------------------------- health

    def attach_cache(self, cache: AnalysisCache) -> None:
        """Install the fleet-shared analysis cache after construction
        (run_serve builds the coordinator before the cache exists)."""
        self.cache = cache

    def health(self) -> dict:
        now = time.monotonic()
        members = [m.health(now) for m in self.members]
        return {
            "members": members,
            "members_live": sum(1 for h in members if h["available"]),
            "quarantined": len(self._quarantine),
            "losses": self.stats.losses,
            "hedge": self.hedge,
            "hedges": self.stats.hedges,
            "hedge_wins": self.stats.hedge_wins,
            "readmissions": self.stats.readmissions,
            "busy_reroutes": self.stats.busy_reroutes,
            "cache": (
                self.cache.counters() if self.cache is not None else None
            ),
        }

    def fold_metrics(self) -> None:
        """Mirror the fleet ledger into the metrics registry: fleet
        gauges + per-member backlog/inflight, and every local member's
        SupervisorStats under its own prefix — the single-endpoint
        contract (one Prometheus scrape sees the whole fleet)."""
        reg = self.registry
        now = time.monotonic()
        reg.gauge(
            "fishnet_fleet_members_live",
            "Fleet members currently eligible for work",
        ).set(sum(1 for m in self.members if m.available(now)))
        reg.gauge(
            "fishnet_fleet_members_total", "Configured fleet members"
        ).set(len(self.members))
        reg.gauge(
            "fishnet_fleet_members_probation",
            "Fleet members awaiting a healthz probe + canary chunk",
        ).set(sum(1 for m in self.members if m.probation))
        reg.gauge(
            "fishnet_fleet_members_draining",
            "Fleet members finishing in-flight work before removal",
        ).set(sum(1 for m in self.members if m.draining))
        reg.absorb_totals("fishnet_fleet", asdict(self.stats))
        if self.cache is not None:
            self.cache.export_metrics()
        # the hedging acceptance counters under their contract names
        # (docs/fleet.md): duplicates dispatched, duplicates that won
        reg.counter(
            "fishnet_fleet_hedges_total",
            "Positions duplicated to a second member by hedged dispatch",
        ).set_total(self.stats.hedges)
        reg.counter(
            "fishnet_fleet_hedge_wins_total",
            "Hedged positions whose duplicate answered first",
        ).set_total(self.stats.hedge_wins)
        for m in self.members:
            reg.gauge(
                f"fishnet_fleet_backlog_{m.name}",
                "Positions dispatched to this member, not yet answered",
            ).set(m.backlog)
            reg.gauge(
                f"fishnet_fleet_inflight_{m.name}",
                "Positions in this member's exactly-once ledger",
            ).set(len(m.inflight))
            reg.counter(
                f"fishnet_fleet_dispatch_positions_total_{m.name}",
                "Positions ever dispatched to this member",
            ).set_total(m.dispatched_positions)
            reg.counter(
                f"fishnet_fleet_losses_total_{m.name}",
                "Member-loss events for this member",
            ).set_total(m.losses)
            stats = getattr(m.engine, "stats", None)
            if stats is not None and m.kind == "local":
                reg.absorb_totals(
                    f"fishnet_fleet_member_{m.name}", asdict(stats)
                )

    # --------------------------------------------------------------- dispatch

    async def go_multiple(self, chunk: Chunk) -> List[PositionResponse]:
        pairs: List[_Pair] = [
            (position_fingerprint(wp), wp) for wp in chunk.positions
        ]
        results: Dict[str, PositionResponse] = {}
        # fleet-wide quarantine pre-route: known-poison positions never
        # touch a member again — straight to the CPU fallback
        pending: List[_Pair] = []
        for fp, wp in pairs:
            if fp in self._quarantine:
                self.stats.quarantine_routed += 1
                results[fp] = await self._go_quarantined(chunk, wp)
            else:
                pending.append((fp, wp))
        # fleet-shared cache consult (fishnet_tpu/cache/): a position
        # ANY member already searched is served from the shared hit set
        # and never dispatched — quarantined positions stay out (their
        # fallback answers come from a different engine identity)
        dispatched: List[_Pair] = pending
        if self.cache is not None and pending:
            cold: List[_Pair] = []
            for fp, wp in pending:
                key, depth = key_for_chunk_position(chunk, wp, self.cache.net)
                wire = self.cache.lookup(key, depth)
                if wire is not None:
                    results[fp] = AnalysisCache.hydrate(
                        wire, wp.position_index, url=wp.url
                    )
                else:
                    cold.append((fp, wp))
            pending = dispatched = cold
        if pending:
            await self._dispatch_all(chunk, pending, results)
        if self.cache is not None:
            # exactly-once fill off the ack journal: everything the
            # dispatch rounds resolved — including results HARVESTED
            # from a lost member's partial acks — lands in the shared
            # set once (store() dedups replayed/re-dispatched copies)
            for fp, wp in dispatched:
                resp = results.get(fp)
                if resp is not None:
                    key, depth = key_for_chunk_position(
                        chunk, wp, self.cache.net
                    )
                    self.cache.store(key, depth, response_to_wire(resp))
        missing = [fp for fp, _ in pairs if fp not in results]
        if missing:  # _dispatch_all raises before this can happen
            raise EngineError(
                f"fleet dropped {len(missing)} position(s) "
                f"of batch {chunk.work.id}"
            )
        self.stats.chunks_ok += 1
        self.fold_metrics()
        return [results[fp] for fp, _ in pairs]

    async def _dispatch_all(
        self,
        chunk: Chunk,
        pending: List[_Pair],
        results: Dict[str, PositionResponse],
    ) -> None:
        """Dispatch rounds until every pending position has a result.
        Round 1 is the normal spread; later rounds re-dispatch only what
        a lost member left un-acked (or a shedding member bounced)."""
        rounds = 0
        while pending:
            self._kick_probes()
            now = time.monotonic()
            available = [m for m in self.members if m.available(now)]
            if not available:
                # last resorts, in order: a due probation probe may
                # readmit someone (bounded by the canary TTL), or every
                # member is merely shedding and the earliest Retry-After
                # hint expires inside the chunk deadline
                await self.probe_members()
                now = time.monotonic()
                available = [m for m in self.members if m.available(now)]
            if not available:
                available = await self._wait_out_backpressure(chunk, now)
            if not available:
                raise EngineError(
                    "fleet: no live members "
                    f"({len(pending)} position(s) stranded)"
                )
            plan = self._plan(pending, available)
            # Admission bookkeeping is synchronous, BEFORE the dispatch
            # tasks are scheduled: concurrent go_multiple() callers plan
            # against each other's load only if the backlog is already
            # visible when their own _plan runs. Ledger order matters
            # too — in-flight is recorded before the engine sees the
            # work, and stale acks from a previous incarnation of the
            # same fingerprint are dropped so a leftover can never be
            # satisfied by an old answer.
            for member, assigned in plan:
                member.backlog += len(assigned)
                member.dispatched_positions += len(assigned)
                self.stats.dispatches += 1
                self.stats.dispatched_positions += len(assigned)
                for fp, wp in assigned:
                    member.acked.pop(fp, None)
                    member.inflight[fp] = wp
            tasks = [
                asyncio.ensure_future(
                    self._dispatch_member(member, chunk, assigned, results)
                )
                for member, assigned in plan
            ]
            hedger = None
            if self.hedge and len(self.members) > 1:
                hedger = asyncio.ensure_future(
                    self._hedge_watch(chunk, plan, tasks, results)
                )
            # First-answer-wins applies to the round barrier too: once
            # every fingerprint this round owns has an answer (a hedge
            # can get there before the straggler's own dispatch comes
            # back) the chunk is done — the straggler keeps running
            # detached to settle its ledger and is reaped on close().
            # A task that leaves unanswered work always completes before
            # the barrier lifts, so its leftover is never orphaned.
            waiting = set(tasks)
            if hedger is not None:
                # the hedger completes right after its hedge answers
                # land — it must be able to lift the barrier itself
                waiting.add(hedger)
            fps_round = [fp for _, assigned in plan for fp, _ in assigned]
            while waiting and not all(fp in results for fp in fps_round):
                _, waiting = await asyncio.wait(
                    waiting, return_when=asyncio.FIRST_COMPLETED
                )
            for task in waiting:
                self._detach(task)
            outcomes = [t.result() for t in tasks if t.done()]
            pending = []
            for status, leftover in outcomes:
                for fp, wp in leftover:
                    if fp in results:
                        continue  # first answer won while we re-planned
                    if status == _BUSY:
                        # a shed is a reroute, never poison evidence —
                        # the member is healthy, just full
                        pending.append((fp, wp))
                        continue
                    count = self._poison.get(fp, 0) + 1
                    self._poison[fp] = count
                    if count >= POISON_THRESHOLD:
                        self._quarantine_fp(fp)
                        self.stats.quarantine_routed += 1
                        results[fp] = await self._go_quarantined(chunk, wp)
                    else:
                        pending.append((fp, wp))
            if pending:
                rounds += 1
                if rounds > self.redispatch_max:
                    raise EngineError(
                        f"fleet: re-dispatch budget exhausted after "
                        f"{rounds - 1} round(s); "
                        f"{len(pending)} position(s) unanswered"
                    )
                self.stats.redispatch_rounds += 1
                self.stats.redispatches += len(pending)
                self.logger.warn(
                    f"fleet: re-dispatching {len(pending)} un-acked "
                    f"position(s) to survivors (round {rounds})"
                )

    def _detach(self, task: asyncio.Task) -> None:
        """Let a superseded dispatch finish in the background (its
        `finally` settles the member ledger); close() reaps the set."""
        self._stragglers.add(task)
        task.add_done_callback(self._stragglers.discard)

    async def _wait_out_backpressure(
        self, chunk: Chunk, now: float
    ) -> List[FleetMember]:
        """Every member is parked on a 429 Retry-After hint: sleep
        until the earliest hint expires (bounded by the chunk deadline)
        rather than failing the chunk — backpressure is a wait, not an
        outage."""
        hints = [
            m.busy_until for m in self.members
            if not m.draining and not m.probation
            and now >= m.down_until and m.busy_until > now
        ]
        if not hints:
            return []
        wake = min(hints)
        if wake >= chunk.deadline:
            return []
        await asyncio.sleep(max(wake - now, 0.0) + 0.005)
        now = time.monotonic()
        return [m for m in self.members if m.available(now)]

    def _plan(
        self, pending: List[_Pair], available: List[FleetMember]
    ) -> List[Tuple[FleetMember, List[_Pair]]]:
        """Greedy least-backlog: positions land one at a time on the
        member with the smallest backlog, counting this round's own
        assignments — an idle fleet gets an even spread, a lopsided one
        (slow member, straggler) gets topped up where there's room."""
        load = {id(m): m.backlog for m in available}
        assigned: Dict[int, List[_Pair]] = {id(m): [] for m in available}
        for pair in pending:
            member = min(available, key=lambda m: load[id(m)])
            load[id(member)] += 1
            assigned[id(member)].append(pair)
        return [
            (m, assigned[id(m)]) for m in available if assigned[id(m)]
        ]

    async def _dispatch_member(
        self,
        member: FleetMember,
        chunk: Chunk,
        assigned: List[_Pair],
        results: Dict[str, PositionResponse],
        hedge: bool = False,
    ) -> Tuple[str, List[_Pair]]:
        """One member's sub-chunk; returns (outcome, leftover) where
        the leftover is empty on success and the outcome tag tells
        `_dispatch_all` whether the leftover is loss evidence (_LOSS:
        poison-count and re-dispatch) or a bounce off a healthy-but-full
        member (_BUSY: reroute only). The caller has already charged
        this work to the member's ledger (backlog, in-flight) — this
        method only runs the engine call and settles the ledger in its
        `finally`. Hedge dispatches (`hedge=True`) write through the
        same first-answer-wins ledger but never feed leftovers back:
        the primary still owns the positions."""
        n = len(assigned)
        sub = replace(chunk, positions=[wp for _, wp in assigned])
        # sampled request contexts in this sub-chunk: the dispatch span
        # lists them and carries each flow, so a post-loss re-dispatch
        # to a survivor shows up as another linked dispatch on the same
        # trace_id (re-dispatch reuses the same WorkPositions)
        tids = sorted({
            wp.ctx["trace_id"] for _, wp in assigned
            if wp.ctx and wp.ctx.get("trace_id")
        })
        tids = [t for t in tids if obs_trace.sampled(t)]
        try:
            with obs_trace.span(
                "fleet.dispatch", "fleet", member=member.name, positions=n,
                batch=str(chunk.work.id), trace_ids=tids,
            ):
                rec = obs_trace.RECORDER
                if rec is not None:
                    for t_id in tids:
                        rec.flow("request", t_id, "t")
                responses = await member.engine.go_multiple(sub)
            if len(responses) != n:
                raise EngineError(
                    f"fleet member {member.name} returned "
                    f"{len(responses)} results for {n} positions"
                )
            # first answer wins: with hedging a fingerprint can be in
            # flight on two members; whichever lands second is discarded
            # here, keeping results bit-identical hedge on or off
            wins = 0
            for (fp, _), res in zip(assigned, responses):
                if fp in results:
                    continue
                results[fp] = res
                if hedge:
                    wins += 1
            if hedge and wins:
                self.stats.hedge_wins += wins
                obs_trace.instant(
                    "fleet.hedge-win", "fleet", member=member.name,
                    positions=wins, batch=str(chunk.work.id),
                )
            member.consecutive_losses = 0
            return (_OK, [])
        except MemberBusy as e:
            # designed backpressure (429 + Retry-After): park the
            # member until the hint expires and bounce the positions
            # back for rerouting — never a loss event, never poison
            member.busy_until = time.monotonic() + max(e.retry_after, 0.1)
            leftover = [
                (fp, wp) for fp, wp in assigned if fp not in results
            ]
            if not hedge:
                self.stats.busy_reroutes += len(leftover)
            obs_trace.instant(
                "fleet.member-busy", "fleet", member=member.name,
                retry_after=e.retry_after, positions=len(leftover),
            )
            self.logger.warn(
                f"fleet: member {member.name} shedding (429, retry "
                f"after {e.retry_after:.0f}s); rerouting "
                f"{len(leftover)} position(s)"
            )
            return (_BUSY, [] if hedge else leftover)
        except EngineError as e:
            # harvest what the member acked before dying: those
            # positions are answered, not re-searched
            acked: Dict[str, dict] = {}
            for fp, _ in assigned:
                wire = member.acked.get(fp)
                if wire is not None and fp not in results:
                    try:
                        results[fp] = responses_from_wire(
                            chunk.work, [wire]
                        )[0]
                        acked[fp] = wire
                        self.stats.acks_harvested += 1
                    except (KeyError, TypeError, ValueError) as bad:
                        self.logger.warn(
                            f"fleet: discarding malformed ack from "
                            f"{member.name}: {bad}"
                        )
            leftover = [
                (fp, wp) for fp, wp in assigned if fp not in results
            ]
            if hedge:
                # the hedge member genuinely died (cooldown and all),
                # but the primary still owns these positions — nothing
                # feeds back into re-dispatch from this side
                self.stats.hedge_losses += 1
                self._note_loss(
                    member, f"hedge dispatch: {e}",
                    [fp for fp, _ in assigned], acked, None,
                )
                return (_LOSS, [])
            self._note_loss(member, str(e), [fp for fp, _ in assigned],
                            acked, leftover)
            return (_LOSS, leftover)
        finally:
            member.backlog -= n
            for fp, _ in assigned:
                member.inflight.pop(fp, None)
                member.acked.pop(fp, None)

    # ------------------------------------------------------------ loss/poison

    def _note_loss(
        self,
        member: FleetMember,
        reason: str,
        inflight_fps: List[str],
        acked: Dict[str, dict],
        leftover: Optional[List[_Pair]] = None,
    ) -> None:
        """Exactly one breaker-visible event per member death: cooldown,
        loss counters, trace instant, flight dump, LossEvent record.
        Consecutive losses escalate the cooldown exponentially (capped
        at cooldown_max) and arm probation: the member re-enters only
        through a healthz probe + canary chunk (flap damping)."""
        now = time.monotonic()
        member.losses += 1
        member.consecutive_losses += 1
        cooldown = self._cooldown(member)
        member.down_until = now + cooldown
        if self.probation:
            member.probation = True
        self.stats.losses += 1
        redisp = tuple(fp for fp, _ in (leftover or []))
        event = LossEvent(
            member=member.name,
            reason=reason,
            inflight_fps=tuple(inflight_fps),
            acked_fps=tuple(acked),
            redispatched_fps=redisp,
        )
        self.loss_log.append(event)
        # trace_ids about to be re-dispatched: the loss instant names
        # them so the merged timeline shows which requests the death hit
        tids = sorted({
            wp.ctx["trace_id"] for _, wp in (leftover or [])
            if wp.ctx and wp.ctx.get("trace_id")
        })
        obs_trace.instant(
            "fleet.member-loss", "fleet", member=member.name,
            reason=reason, inflight=len(inflight_fps),
            acked=len(acked), redispatched=len(redisp),
            cooldown_s=round(cooldown, 1), probation=member.probation,
            trace_ids=[t for t in tids if obs_trace.sampled(t)],
        )
        self.logger.error(
            f"fleet: member {member.name} lost ({reason}); "
            f"{len(acked)} ack(s) harvested, {len(redisp)} position(s) "
            f"to re-dispatch; cooling down {cooldown:.0f}s"
            + (" then probation" if member.probation else "")
        )
        self._flight_dump("member-loss", f"{member.name}: {reason}")

    def _cooldown(self, member: FleetMember) -> float:
        """Escalating cooldown: loss_window doubled per consecutive
        loss, capped at cooldown_max (flap damping)."""
        n = max(member.consecutive_losses, 1)
        return min(self.loss_window * (2.0 ** (n - 1)), self.cooldown_max)

    # ------------------------------------------------------- probation/canary

    def _kick_probes(self, now: Optional[float] = None) -> None:
        """Start a background probe for every member whose cooldown has
        expired into probation. Called opportunistically from the
        dispatch path — probing never blocks real work."""
        if not self.probation or self._closing:
            return
        if now is None:
            now = time.monotonic()
        for m in self.members:
            if m.probe_due(now) and m.name not in self._probe_tasks:
                m.probing = True
                task = asyncio.ensure_future(self._probe_member(m))
                self._probe_tasks[m.name] = task
                task.add_done_callback(
                    lambda t, name=m.name:
                    self._probe_tasks.pop(name, None)
                )

    async def probe_members(self) -> None:
        """Kick and await every due probe — the synchronous form the
        tests, chaos scenarios, and fleet-ctl use."""
        self._kick_probes()
        tasks = list(self._probe_tasks.values())
        if tasks:
            await asyncio.gather(*tasks, return_exceptions=True)

    async def _probe_member(self, member: FleetMember) -> None:
        """Probation gauntlet: healthz (if the engine speaks it), then
        one canary chunk. Success readmits the member; failure is NOT a
        loss event (no work was at risk) — it just escalates the
        cooldown before the next probe, so a permanently-dead member
        costs probes, never re-dispatched work."""
        try:
            with obs_trace.span(
                "fleet.probe", "fleet", member=member.name
            ):
                self.stats.probes += 1
                hz = getattr(member.engine, "healthz", None)
                if hz is not None:
                    await hz()
                canary = self._canary_chunk(member.name)
                fp = position_fingerprint(canary.positions[0])
                obs_trace.instant(
                    "fleet.canary", "fleet", member=member.name
                )
                responses = await member.engine.go_multiple(canary)
                # canary acks must not linger in the exactly-once ledger
                member.acked.pop(fp, None)
                if len(responses) != 1:
                    raise EngineError(
                        f"fleet member {member.name} canary returned "
                        f"{len(responses)} result(s)"
                    )
            member.probation = False
            member.down_until = 0.0
            member.busy_until = 0.0
            member.canaries_ok += 1
            self.stats.canaries_ok += 1
            self.stats.readmissions += 1
            obs_trace.instant(
                "fleet.readmit", "fleet", member=member.name
            )
            self.logger.info(
                f"fleet: member {member.name} readmitted "
                "(healthz + canary ok)"
            )
        except EngineError as e:
            member.consecutive_losses += 1
            cooldown = self._cooldown(member)
            member.down_until = time.monotonic() + cooldown
            self.stats.probe_failures += 1
            obs_trace.instant(
                "fleet.probe-failed", "fleet", member=member.name,
                reason=str(e), cooldown_s=round(cooldown, 1),
            )
            self.logger.warn(
                f"fleet: probe of {member.name} failed ({e}); "
                f"cooling down {cooldown:.0f}s"
            )
        finally:
            member.probing = False

    def _canary_chunk(self, member_name: str) -> Chunk:
        work = AnalysisWork(
            id=f"canary-{member_name}",
            nodes=NodeLimit(sf16=10_000, classical=20_000),
            timeout_s=CANARY_TTL_S, depth=1, multipv=None,
        )
        wp = WorkPosition(
            work=work, position_index=0, url=None, skip=False,
            root_fen=_CANARY_FEN, moves=[],
        )
        return Chunk(
            work=work, deadline=time.monotonic() + CANARY_TTL_S,
            variant="standard", flavor=EngineFlavor.TPU, positions=[wp],
        )

    # ----------------------------------------------------------- hedging

    async def _hedge_watch(
        self,
        chunk: Chunk,
        plan: List[Tuple[FleetMember, List[_Pair]]],
        tasks: List[asyncio.Task],
        results: Dict[str, PositionResponse],
    ) -> None:
        """Tail-latency insurance: wait until the chunk's deadline
        slack shrinks to hedge_slack_s; any sub-chunk still unanswered
        then is duplicated to a member with free capacity. First answer
        wins through the fingerprint ledger (results), the loser is
        discarded and counted."""
        delay = (chunk.deadline - self.hedge_slack_s) - time.monotonic()
        if delay > 0:
            _, still_running = await asyncio.wait(tasks, timeout=delay)
            if not still_running:
                return  # everyone answered with slack to spare
        now = time.monotonic()
        if now >= chunk.deadline:
            return
        hedge_calls = []
        for (member, assigned), task in zip(plan, tasks):
            if task.done():
                continue
            unfinished = [
                (fp, wp) for fp, wp in assigned if fp not in results
            ]
            if not unfinished:
                continue
            target = self._hedge_target(member, now)
            if target is None:
                continue  # nobody free — hedging never queues work
            self.stats.hedges += len(unfinished)
            obs_trace.instant(
                "fleet.hedge", "fleet", slow=member.name,
                target=target.name, positions=len(unfinished),
                batch=str(chunk.work.id),
            )
            self.logger.warn(
                f"fleet: hedging {len(unfinished)} position(s) from "
                f"{member.name} to {target.name} "
                f"({(chunk.deadline - now) * 1000:.0f}ms slack left)"
            )
            # same synchronous ledger charge as _dispatch_all's plan
            target.backlog += len(unfinished)
            target.dispatched_positions += len(unfinished)
            self.stats.dispatches += 1
            self.stats.dispatched_positions += len(unfinished)
            for fp, wp in unfinished:
                target.acked.pop(fp, None)
                target.inflight[fp] = wp
            hedge_calls.append(
                self._dispatch_member(
                    target, chunk, unfinished, results, hedge=True
                )
            )
        if hedge_calls:
            await asyncio.gather(*hedge_calls)

    def _hedge_target(
        self, slow: FleetMember, now: float
    ) -> Optional[FleetMember]:
        """A healthy member with free capacity (empty backlog) that
        isn't the straggler itself."""
        for m in self.members:
            if m is not slow and m.backlog == 0 and m.available(now):
                return m
        return None

    def _quarantine_fp(self, fp: str) -> None:
        if fp in self._quarantine:
            return
        self._quarantine.add(fp)
        self.stats.quarantined += 1
        obs_trace.instant("fleet.quarantine", "fleet", fp=fp)
        self.logger.error(
            f"fleet: position {fp} un-acked across {POISON_THRESHOLD} "
            "member losses — quarantined fleet-wide to the CPU fallback"
        )

    async def _go_quarantined(
        self, chunk: Chunk, wp: WorkPosition
    ) -> PositionResponse:
        if self._fallback is None:
            if self.fallback_factory is not None:
                self._fallback = self.fallback_factory()
            else:
                from ..engine.pyengine import PyEngine

                self._fallback = PyEngine()
        responses = await self._fallback.go_multiple(
            replace(chunk, positions=[wp])
        )
        if len(responses) != 1:
            raise EngineError(
                "fleet fallback returned a mismatched result count"
            )
        return responses[0]

    def _flight_dump(self, slug: str, reason: str) -> None:
        rec = obs_trace.RECORDER
        if rec is None or not self._trace_dir:
            return
        rec.instant("flight-dump", "fleet", reason=reason)
        try:
            path = rec.flight_dump(self._trace_dir, slug)
        except OSError as e:
            self.logger.warn(f"fleet: flight-recorder dump failed: {e}")
        else:
            self.logger.warn(f"fleet: flight recorder dumped to {path}")
