"""The fleet coordinator: one Engine over N members, exactly-once.

`FleetCoordinator.go_multiple(chunk)` splits the chunk's positions
across the available members (least-backlog greedy: each position goes
to the member with the fewest outstanding positions, counting what this
very planning round already assigned) and dispatches each member its
sub-chunk concurrently. Everything above — `EngineSession`, the lichess
client workers, `fishnet-tpu serve`, bench — feeds it unchanged because
it speaks the same `Engine` protocol via `ChunkSubmit`.

Exactly-once under member loss, the invariant the chaos gate
(tools/chaos.py --scenario fleet-member-loss) enforces:

- every position is keyed by `position_fingerprint` and recorded in the
  member's in-flight ledger before its sub-chunk dispatches;
- acks stream back per position (local members mirror their partial
  journal through `SupervisedEngine.on_partial`; remote members answer
  whole sub-chunks, which ack every position at once);
- when a member's dispatch raises `EngineError` (child SIGKILLed, HTTP
  endpoint gone), the coordinator harvests the acked results it already
  holds and re-dispatches ONLY the un-acked remainder to survivors — a
  strict subset of the member's in-flight set whenever at least one ack
  landed, and always strictly fewer re-searches than resubmitting the
  chunk;
- exactly one loss event per member death: cooldown (`down_until`),
  one `fleet.member-loss` trace instant, one loss counter increment,
  one flight-recorder dump, one `LossEvent` appended to `loss_log`;
- a fingerprint that is un-acked across `POISON_THRESHOLD` distinct
  losses is quarantined fleet-wide (it killed two different members —
  the position is the poison, not the host) and answered by the CPU
  fallback; later chunks pre-route it before it can touch a member.

Re-dispatch rounds are bounded by FISHNET_TPU_FLEET_REDISPATCH_MAX;
a lost member sits out FISHNET_TPU_FLEET_LOSS_WINDOW seconds before
the planner will consider it again (its own supervisor respawn backoff
still applies underneath).

Observability folds to one pane: member trace rings already merge into
the shared module recorder (each local supervisor absorbs its child's
spans with a per-member clock sync), the coordinator adds
`fleet.dispatch` spans and loss instants around them, and
`fold_metrics()` mirrors the fleet ledger plus every local member's
`SupervisorStats` into the metrics registry — one Perfetto timeline,
one Prometheus endpoint for the whole fleet.
"""
from __future__ import annotations

import asyncio
import time
from dataclasses import asdict, dataclass, field, replace
from typing import Dict, List, Optional, Set, Tuple

from ..client.ipc import (
    Chunk,
    PositionResponse,
    WorkPosition,
    position_fingerprint,
    responses_from_wire,
)
from ..client.logger import Logger
from ..client.wire import EngineFlavor
from ..engine.base import EngineError
from ..engine.session import ChunkSubmit
from ..obs import metrics as obs_metrics
from ..obs import trace as obs_trace
from ..utils import settings
from .member import FleetMember

# distinct member losses with the same fingerprint un-acked before the
# position is declared poison and quarantined fleet-wide
POISON_THRESHOLD = 2

_Pair = Tuple[str, WorkPosition]  # (fingerprint, position)


@dataclass
class LossEvent:
    """One member death, as the exactly-once ledger saw it."""

    member: str
    reason: str
    inflight_fps: Tuple[str, ...]  # what the member held when it died
    acked_fps: Tuple[str, ...]  # harvested — NOT re-searched
    redispatched_fps: Tuple[str, ...]  # un-acked remainder, re-dispatched


@dataclass
class FleetStats:
    """Coordinator counters; absorbed into the metrics registry by
    `fold_metrics` (same shape-contract as SupervisorStats)."""

    chunks_ok: int = 0
    dispatches: int = 0  # member sub-chunk dispatches
    dispatched_positions: int = 0
    acks_harvested: int = 0  # answered from a dead member's acks
    redispatches: int = 0  # positions re-dispatched after a loss
    redispatch_rounds: int = 0
    losses: int = 0
    quarantined: int = 0  # fingerprints quarantined fleet-wide
    quarantine_routed: int = 0  # positions answered by the fallback


class FleetCoordinator(ChunkSubmit):
    """`Engine` protocol over N `FleetMember`s."""

    _submit_flavor = EngineFlavor.TPU

    def __init__(
        self,
        members: List[FleetMember],
        *,
        logger: Optional[Logger] = None,
        redispatch_max: Optional[int] = None,
        loss_window: Optional[float] = None,
        registry: Optional[obs_metrics.MetricsRegistry] = None,
        fallback_factory=None,
    ) -> None:
        if not members:
            raise ValueError("a fleet needs at least one member")
        self.members = list(members)
        self.logger = logger or Logger()
        self.redispatch_max = (
            settings.get_int("FISHNET_TPU_FLEET_REDISPATCH_MAX")
            if redispatch_max is None else int(redispatch_max)
        )
        self.loss_window = float(
            settings.get_int("FISHNET_TPU_FLEET_LOSS_WINDOW")
            if loss_window is None else loss_window
        )
        self.registry = registry or obs_metrics.REGISTRY
        self.fallback_factory = fallback_factory
        self.stats = FleetStats()
        self.loss_log: List[LossEvent] = []
        self._quarantine: Set[str] = set()
        self._poison: Dict[str, int] = {}
        self._fallback = None
        self._closing = False
        self._trace_dir = settings.get_str("FISHNET_TPU_TRACE_DIR")
        if self._trace_dir and obs_trace.RECORDER is None:
            obs_trace.install_from_settings("fleet")

    # -------------------------------------------------------------- lifecycle

    async def start(self) -> None:
        """Start every local member's engine host concurrently. A member
        that fails to come up enters loss cooldown instead of failing
        the fleet — survivors carry the queue, the planner retries it
        after the window."""

        async def _start_one(member: FleetMember):
            start = getattr(member.engine, "start", None)
            if start is None:
                return  # remote members have no child to spawn
            try:
                await start()
            except EngineError as e:
                self._note_loss(member, f"start failed: {e}", [], {})

        await asyncio.gather(*(_start_one(m) for m in self.members))
        live = [m for m in self.members if m.available()]
        if not live:
            raise EngineError("fleet: no member came up")
        self.logger.info(
            f"fleet: {len(live)}/{len(self.members)} member(s) ready"
        )

    async def close(self) -> None:
        self._closing = True
        engines = [m.engine for m in self.members]
        if self._fallback is not None:
            engines.append(self._fallback)
            self._fallback = None
        await asyncio.gather(
            *(e.close() for e in engines), return_exceptions=True
        )

    def begin_drain(self, member_name: Optional[str] = None) -> None:
        """Stop planning work onto a member (or all of them); in-flight
        sub-chunks finish normally. The autoscaling story in
        docs/fleet.md drains a member before removing it."""
        for m in self.members:
            if member_name is None or m.name == member_name:
                m.draining = True

    # ---------------------------------------------------------------- health

    def health(self) -> dict:
        now = time.monotonic()
        members = [m.health(now) for m in self.members]
        return {
            "members": members,
            "members_live": sum(1 for h in members if h["available"]),
            "quarantined": len(self._quarantine),
            "losses": self.stats.losses,
        }

    def fold_metrics(self) -> None:
        """Mirror the fleet ledger into the metrics registry: fleet
        gauges + per-member backlog/inflight, and every local member's
        SupervisorStats under its own prefix — the single-endpoint
        contract (one Prometheus scrape sees the whole fleet)."""
        reg = self.registry
        now = time.monotonic()
        reg.gauge(
            "fishnet_fleet_members_live",
            "Fleet members currently eligible for work",
        ).set(sum(1 for m in self.members if m.available(now)))
        reg.gauge(
            "fishnet_fleet_members_total", "Configured fleet members"
        ).set(len(self.members))
        reg.absorb_totals("fishnet_fleet", asdict(self.stats))
        for m in self.members:
            reg.gauge(
                f"fishnet_fleet_backlog_{m.name}",
                "Positions dispatched to this member, not yet answered",
            ).set(m.backlog)
            reg.gauge(
                f"fishnet_fleet_inflight_{m.name}",
                "Positions in this member's exactly-once ledger",
            ).set(len(m.inflight))
            reg.counter(
                f"fishnet_fleet_dispatch_positions_total_{m.name}",
                "Positions ever dispatched to this member",
            ).set_total(m.dispatched_positions)
            reg.counter(
                f"fishnet_fleet_losses_total_{m.name}",
                "Member-loss events for this member",
            ).set_total(m.losses)
            stats = getattr(m.engine, "stats", None)
            if stats is not None and m.kind == "local":
                reg.absorb_totals(
                    f"fishnet_fleet_member_{m.name}", asdict(stats)
                )

    # --------------------------------------------------------------- dispatch

    async def go_multiple(self, chunk: Chunk) -> List[PositionResponse]:
        pairs: List[_Pair] = [
            (position_fingerprint(wp), wp) for wp in chunk.positions
        ]
        results: Dict[str, PositionResponse] = {}
        # fleet-wide quarantine pre-route: known-poison positions never
        # touch a member again — straight to the CPU fallback
        pending: List[_Pair] = []
        for fp, wp in pairs:
            if fp in self._quarantine:
                self.stats.quarantine_routed += 1
                results[fp] = await self._go_quarantined(chunk, wp)
            else:
                pending.append((fp, wp))
        if pending:
            await self._dispatch_all(chunk, pending, results)
        missing = [fp for fp, _ in pairs if fp not in results]
        if missing:  # _dispatch_all raises before this can happen
            raise EngineError(
                f"fleet dropped {len(missing)} position(s) "
                f"of batch {chunk.work.id}"
            )
        self.stats.chunks_ok += 1
        self.fold_metrics()
        return [results[fp] for fp, _ in pairs]

    async def _dispatch_all(
        self,
        chunk: Chunk,
        pending: List[_Pair],
        results: Dict[str, PositionResponse],
    ) -> None:
        """Dispatch rounds until every pending position has a result.
        Round 1 is the normal spread; later rounds re-dispatch only what
        a lost member left un-acked."""
        rounds = 0
        while pending:
            now = time.monotonic()
            available = [m for m in self.members if m.available(now)]
            if not available:
                raise EngineError(
                    "fleet: no live members "
                    f"({len(pending)} position(s) stranded)"
                )
            plan = self._plan(pending, available)
            # Admission bookkeeping is synchronous, BEFORE the dispatch
            # tasks are scheduled: concurrent go_multiple() callers plan
            # against each other's load only if the backlog is already
            # visible when their own _plan runs. Ledger order matters
            # too — in-flight is recorded before the engine sees the
            # work, and stale acks from a previous incarnation of the
            # same fingerprint are dropped so a leftover can never be
            # satisfied by an old answer.
            for member, assigned in plan:
                member.backlog += len(assigned)
                member.dispatched_positions += len(assigned)
                self.stats.dispatches += 1
                self.stats.dispatched_positions += len(assigned)
                for fp, wp in assigned:
                    member.acked.pop(fp, None)
                    member.inflight[fp] = wp
            leftovers = await asyncio.gather(
                *(
                    self._dispatch_member(member, chunk, assigned, results)
                    for member, assigned in plan
                )
            )
            pending = []
            for leftover in leftovers:
                for fp, wp in leftover:
                    if fp in results:
                        continue  # first answer won while we re-planned
                    count = self._poison.get(fp, 0) + 1
                    self._poison[fp] = count
                    if count >= POISON_THRESHOLD:
                        self._quarantine_fp(fp)
                        self.stats.quarantine_routed += 1
                        results[fp] = await self._go_quarantined(chunk, wp)
                    else:
                        pending.append((fp, wp))
            if pending:
                rounds += 1
                if rounds > self.redispatch_max:
                    raise EngineError(
                        f"fleet: re-dispatch budget exhausted after "
                        f"{rounds - 1} round(s); "
                        f"{len(pending)} position(s) unanswered"
                    )
                self.stats.redispatch_rounds += 1
                self.stats.redispatches += len(pending)
                self.logger.warn(
                    f"fleet: re-dispatching {len(pending)} un-acked "
                    f"position(s) to survivors (round {rounds})"
                )

    def _plan(
        self, pending: List[_Pair], available: List[FleetMember]
    ) -> List[Tuple[FleetMember, List[_Pair]]]:
        """Greedy least-backlog: positions land one at a time on the
        member with the smallest backlog, counting this round's own
        assignments — an idle fleet gets an even spread, a lopsided one
        (slow member, straggler) gets topped up where there's room."""
        load = {id(m): m.backlog for m in available}
        assigned: Dict[int, List[_Pair]] = {id(m): [] for m in available}
        for pair in pending:
            member = min(available, key=lambda m: load[id(m)])
            load[id(member)] += 1
            assigned[id(member)].append(pair)
        return [
            (m, assigned[id(m)]) for m in available if assigned[id(m)]
        ]

    async def _dispatch_member(
        self,
        member: FleetMember,
        chunk: Chunk,
        assigned: List[_Pair],
        results: Dict[str, PositionResponse],
    ) -> List[_Pair]:
        """One member's sub-chunk; returns the un-acked leftover (empty
        on success). The caller has already charged this work to the
        member's ledger (backlog, in-flight) — this method only runs the
        engine call and settles the ledger in its `finally`."""
        n = len(assigned)
        sub = replace(chunk, positions=[wp for _, wp in assigned])
        # sampled request contexts in this sub-chunk: the dispatch span
        # lists them and carries each flow, so a post-loss re-dispatch
        # to a survivor shows up as another linked dispatch on the same
        # trace_id (re-dispatch reuses the same WorkPositions)
        tids = sorted({
            wp.ctx["trace_id"] for _, wp in assigned
            if wp.ctx and wp.ctx.get("trace_id")
        })
        tids = [t for t in tids if obs_trace.sampled(t)]
        try:
            with obs_trace.span(
                "fleet.dispatch", "fleet", member=member.name, positions=n,
                batch=str(chunk.work.id), trace_ids=tids,
            ):
                rec = obs_trace.RECORDER
                if rec is not None:
                    for t_id in tids:
                        rec.flow("request", t_id, "t")
                responses = await member.engine.go_multiple(sub)
            if len(responses) != n:
                raise EngineError(
                    f"fleet member {member.name} returned "
                    f"{len(responses)} results for {n} positions"
                )
            for (fp, _), res in zip(assigned, responses):
                results[fp] = res
            return []
        except EngineError as e:
            # harvest what the member acked before dying: those
            # positions are answered, not re-searched
            acked: Dict[str, dict] = {}
            for fp, _ in assigned:
                wire = member.acked.get(fp)
                if wire is not None and fp not in results:
                    try:
                        results[fp] = responses_from_wire(
                            chunk.work, [wire]
                        )[0]
                        acked[fp] = wire
                        self.stats.acks_harvested += 1
                    except (KeyError, TypeError, ValueError) as bad:
                        self.logger.warn(
                            f"fleet: discarding malformed ack from "
                            f"{member.name}: {bad}"
                        )
            leftover = [
                (fp, wp) for fp, wp in assigned if fp not in results
            ]
            self._note_loss(member, str(e), [fp for fp, _ in assigned],
                            acked, leftover)
            return leftover
        finally:
            member.backlog -= n
            for fp, _ in assigned:
                member.inflight.pop(fp, None)
                member.acked.pop(fp, None)

    # ------------------------------------------------------------ loss/poison

    def _note_loss(
        self,
        member: FleetMember,
        reason: str,
        inflight_fps: List[str],
        acked: Dict[str, dict],
        leftover: Optional[List[_Pair]] = None,
    ) -> None:
        """Exactly one breaker-visible event per member death: cooldown,
        loss counters, trace instant, flight dump, LossEvent record."""
        now = time.monotonic()
        member.losses += 1
        member.down_until = now + self.loss_window
        self.stats.losses += 1
        redisp = tuple(fp for fp, _ in (leftover or []))
        event = LossEvent(
            member=member.name,
            reason=reason,
            inflight_fps=tuple(inflight_fps),
            acked_fps=tuple(acked),
            redispatched_fps=redisp,
        )
        self.loss_log.append(event)
        # trace_ids about to be re-dispatched: the loss instant names
        # them so the merged timeline shows which requests the death hit
        tids = sorted({
            wp.ctx["trace_id"] for _, wp in (leftover or [])
            if wp.ctx and wp.ctx.get("trace_id")
        })
        obs_trace.instant(
            "fleet.member-loss", "fleet", member=member.name,
            reason=reason, inflight=len(inflight_fps),
            acked=len(acked), redispatched=len(redisp),
            trace_ids=[t for t in tids if obs_trace.sampled(t)],
        )
        self.logger.error(
            f"fleet: member {member.name} lost ({reason}); "
            f"{len(acked)} ack(s) harvested, {len(redisp)} position(s) "
            f"to re-dispatch; cooling down {self.loss_window:.0f}s"
        )
        self._flight_dump("member-loss", f"{member.name}: {reason}")

    def _quarantine_fp(self, fp: str) -> None:
        if fp in self._quarantine:
            return
        self._quarantine.add(fp)
        self.stats.quarantined += 1
        obs_trace.instant("fleet.quarantine", "fleet", fp=fp)
        self.logger.error(
            f"fleet: position {fp} un-acked across {POISON_THRESHOLD} "
            "member losses — quarantined fleet-wide to the CPU fallback"
        )

    async def _go_quarantined(
        self, chunk: Chunk, wp: WorkPosition
    ) -> PositionResponse:
        if self._fallback is None:
            if self.fallback_factory is not None:
                self._fallback = self.fallback_factory()
            else:
                from ..engine.pyengine import PyEngine

                self._fallback = PyEngine()
        responses = await self._fallback.go_multiple(
            replace(chunk, positions=[wp])
        )
        if len(responses) != 1:
            raise EngineError(
                "fleet fallback returned a mismatched result count"
            )
        return responses[0]

    def _flight_dump(self, slug: str, reason: str) -> None:
        rec = obs_trace.RECORDER
        if rec is None or not self._trace_dir:
            return
        rec.instant("flight-dump", "fleet", reason=reason)
        try:
            path = rec.flight_dump(self._trace_dir, slug)
        except OSError as e:
            self.logger.warn(f"fleet: flight-recorder dump failed: {e}")
        else:
            self.logger.warn(f"fleet: flight recorder dumped to {path}")
