"""Fault taxonomy for fleet member dispatch.

The reference client survives flaky volunteer machines because it never
confuses "the network hiccuped" with "the worker is gone" — backoff and
retry on the former, give the work away on the latter. The fleet's
version of that distinction lives here, as three fault kinds a remote
dispatch can surface:

    transient   connect refused / reset / timeout BEFORE the request was
                written — the member never saw the work, so retrying the
                same dispatch is safe and costs nothing but backoff.
    busy        HTTP 429 from serve/admission.py — a *designed*
                backpressure answer carrying Retry-After. The member is
                healthy and loaded, not dead; the coordinator reroutes
                the positions and leaves the member alone until the
                hint expires. Never a loss event.
    loss        the request hit the wire and the answer never (fully)
                came back, or transient retries exhausted their budget —
                the member may be searching the positions, may be gone;
                either way the exactly-once ledger takes over (harvest
                acks, re-dispatch the remainder, cooldown).

`classify(exc, wrote=...)` maps a transport exception onto a kind; the
`wrote` flag is the load-bearing bit: the same ConnectionResetError is
transient before the request bytes left this host and a loss after.
`MemberFault` subclasses EngineError so every existing handler still
fires; `MemberBusy` additionally carries the Retry-After hint.
"""
from __future__ import annotations

import asyncio

from ..engine.base import EngineError

FAULT_TRANSIENT = "transient"
FAULT_BUSY = "busy"
FAULT_LOSS = "loss"

# transport exceptions that mean "the connection itself failed" — the
# classification table in tests/test_fleet_health.py pins this set
_TRANSPORT_ERRORS = (
    ConnectionError,
    OSError,
    asyncio.IncompleteReadError,
    asyncio.TimeoutError,
    TimeoutError,
)


class MemberFault(EngineError):
    """An EngineError with a fault kind the coordinator can route on."""

    kind = FAULT_LOSS

    def __init__(self, message: str, *, kind: str | None = None):
        super().__init__(message)
        if kind is not None:
            self.kind = kind

    @property
    def retriable(self) -> bool:
        return self.kind == FAULT_TRANSIENT


class MemberBusy(MemberFault):
    """HTTP 429 backpressure: reroute, don't bury (satellite bugfix —
    HttpEngine used to raise this as a plain EngineError and the
    coordinator counted a member death for a designed shed answer)."""

    kind = FAULT_BUSY

    def __init__(self, message: str, *, retry_after: float = 1.0):
        super().__init__(message, kind=FAULT_BUSY)
        self.retry_after = max(float(retry_after), 0.0)


def classify(exc: BaseException, *, wrote: bool) -> str:
    """Transport exception → fault kind.

    Anything after the request was written is a loss: the member may
    already be searching, so a blind retry would double-execute and the
    deadline slack is mostly spent anyway. Before the write, connection
    failures and timeouts are transient — the member provably never
    received the work.
    """
    if wrote:
        return FAULT_LOSS
    if isinstance(exc, _TRANSPORT_ERRORS):
        return FAULT_TRANSIENT
    return FAULT_LOSS
