"""Perft (move-path enumeration) for validating the rules library."""
from __future__ import annotations

from .position import Position


def perft(pos: Position, depth: int) -> int:
    if depth == 0:
        return 1
    moves = pos.legal_moves()
    if depth == 1:
        return len(moves)
    total = 0
    for move in moves:
        total += perft(pos.push(move), depth - 1)
    return total


def perft_divide(pos: Position, depth: int) -> dict:
    out = {}
    for move in pos.legal_moves():
        out[move.uci()] = perft(pos.push(move), depth - 1) if depth > 1 else 1
    return out
