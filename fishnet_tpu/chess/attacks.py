"""Precomputed attack tables (classical ray approach).

These tables are the host-side mirror of the device-side attack tensors in
fishnet_tpu.ops.movegen; both are generated from the same geometry so the
batched TPU movegen can be property-tested against this library.
"""
from __future__ import annotations

from .types import FULL_BB, bb, lsb, msb, square, square_file, square_rank

# Direction deltas as (df, dr)
_KNIGHT_D = [(1, 2), (2, 1), (2, -1), (1, -2), (-1, -2), (-2, -1), (-2, 1), (-1, 2)]
_KING_D = [(1, 0), (1, 1), (0, 1), (-1, 1), (-1, 0), (-1, -1), (0, -1), (1, -1)]
_BISHOP_D = [(1, 1), (-1, 1), (-1, -1), (1, -1)]
_ROOK_D = [(1, 0), (0, 1), (-1, 0), (0, -1)]


def _step_table(deltas):
    table = [0] * 64
    for sq in range(64):
        f, r = square_file(sq), square_rank(sq)
        mask = 0
        for df, dr in deltas:
            nf, nr = f + df, r + dr
            if 0 <= nf < 8 and 0 <= nr < 8:
                mask |= bb(square(nf, nr))
        table[sq] = mask
    return table


KNIGHT_ATTACKS = _step_table(_KNIGHT_D)
KING_ATTACKS = _step_table(_KING_D)

# PAWN_ATTACKS[color][sq] = squares attacked by a pawn of `color` on sq
PAWN_ATTACKS = [
    _step_table([(-1, 1), (1, 1)]),   # white attacks up
    _step_table([(-1, -1), (1, -1)]),  # black attacks down
]


def _ray_table():
    """RAYS[dir][sq]: open-board ray from sq (exclusive) in direction dir.

    Directions 0-3 are "positive" (increasing square index): E, N, NE, NW... we
    order so that dirs 0..3 go toward higher square indices and 4..7 lower, so
    blocker cutting uses lsb for 0..3 and msb for 4..7.
    """
    dirs = [(1, 0), (0, 1), (1, 1), (-1, 1), (-1, 0), (0, -1), (-1, -1), (1, -1)]
    rays = [[0] * 64 for _ in range(8)]
    for d, (df, dr) in enumerate(dirs):
        for sq in range(64):
            f, r = square_file(sq), square_rank(sq)
            mask = 0
            nf, nr = f + df, r + dr
            while 0 <= nf < 8 and 0 <= nr < 8:
                mask |= bb(square(nf, nr))
                nf += df
                nr += dr
            rays[d][sq] = mask
    return rays


RAYS = _ray_table()
_POSITIVE_DIRS = (0, 1, 2, 3)  # E, N, NE, NW — ray squares all above sq
_NEGATIVE_DIRS = (4, 5, 6, 7)  # W, S, SW, SE — ray squares all below sq
_ROOK_DIRS = (0, 1, 4, 5)
_BISHOP_DIRS = (2, 3, 6, 7)

# BETWEEN[a][b]: squares strictly between a and b if aligned, else 0
BETWEEN = [[0] * 64 for _ in range(64)]
# LINE[a][b]: full line through a and b (incl. both) if aligned, else 0
LINE = [[0] * 64 for _ in range(64)]
for _a in range(64):
    for _d in range(8):
        ray = RAYS[_d][_a]
        for _b in range(64):
            if ray & bb(_b):
                opp = (_d + 4) % 8
                BETWEEN[_a][_b] = ray & RAYS[opp][_b]
                LINE[_a][_b] = (ray | bb(_a)) | (RAYS[opp][_a] & (RAYS[opp][_b] | bb(_b))) | (RAYS[_d][_b])
                LINE[_a][_b] |= bb(_b)


def _slider_attacks(sq: int, occ: int, dirs) -> int:
    att = 0
    for d in dirs:
        ray = RAYS[d][sq]
        blockers = ray & occ
        if blockers:
            first = lsb(blockers) if d in _POSITIVE_DIRS else msb(blockers)
            ray &= ~RAYS[d][first]
        att |= ray
    return att


def rook_attacks(sq: int, occ: int) -> int:
    return _slider_attacks(sq, occ, _ROOK_DIRS)


def bishop_attacks(sq: int, occ: int) -> int:
    return _slider_attacks(sq, occ, _BISHOP_DIRS)


def queen_attacks(sq: int, occ: int) -> int:
    return _slider_attacks(sq, occ, _ROOK_DIRS) | _slider_attacks(sq, occ, _BISHOP_DIRS)
