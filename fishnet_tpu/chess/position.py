"""Standard-chess position with X-FEN/Chess960 castling, copy-make semantics.

Fills shakmaty's role from the reference client (FEN parsing, UCI move
replay, legality — reference: src/queue.rs:554-581, Cargo.toml:42).
Variant rules (reference: src/logger.rs:201-213 lists the lichess variants)
live in fishnet_tpu.chess.variants as subclasses.
"""
from __future__ import annotations

from typing import Iterator, List, Optional, Tuple

from .attacks import (
    BETWEEN,
    KING_ATTACKS,
    KNIGHT_ATTACKS,
    PAWN_ATTACKS,
    bishop_attacks,
    rook_attacks,
)
from .types import (
    BLACK,
    FULL_BB,
    KING,
    KNIGHT,
    BISHOP,
    PAWN,
    QUEEN,
    ROOK,
    WHITE,
    Move,
    bb,
    lsb,
    parse_piece_char,
    parse_square,
    piece_char,
    popcount,
    scan,
    square,
    square_file,
    square_name,
    square_rank,
)

RANK_1 = 0x00000000000000FF
RANK_2 = 0x000000000000FF00
RANK_4 = 0x00000000FF000000
RANK_5 = 0x000000FF00000000
RANK_7 = 0x00FF000000000000
RANK_8 = 0xFF00000000000000
BACK_RANKS = (RANK_1, RANK_8)
PROMO_RANKS = (RANK_8, RANK_1)

STARTING_FEN = "rnbqkbnr/pppppppp/8/8/8/8/PPPPPPPP/RNBQKBNR w KQkq - 0 1"


class IllegalMoveError(ValueError):
    pass


class InvalidFenError(ValueError):
    pass


class Position:
    """Mutable-via-copy chess position. Use `push(move)` to get a successor."""

    variant = "standard"
    has_castling = True

    __slots__ = (
        "bbs",
        "occ",
        "occ_all",
        "turn",
        "castling",
        "ep_square",
        "halfmove",
        "fullmove",
        "pockets",
        "promoted",
        "checks_given",
    )

    def __init__(self) -> None:
        self.bbs = [[0] * 6, [0] * 6]
        self.occ = [0, 0]
        self.occ_all = 0
        self.turn = WHITE
        self.castling = 0  # bitboard of rook squares retaining castling rights
        self.ep_square: Optional[int] = None
        self.halfmove = 0
        self.fullmove = 1
        self.pockets = None  # crazyhouse: [[int]*5, [int]*5] counts P N B R Q
        self.promoted = 0  # crazyhouse: bitboard of promoted pieces
        self.checks_given = None  # threeCheck: [white_given, black_given]

    # ------------------------------------------------------------------ setup

    @classmethod
    def initial(cls) -> "Position":
        return cls.from_fen(cls.starting_fen())

    @classmethod
    def starting_fen(cls) -> str:
        return STARTING_FEN

    def copy(self) -> "Position":
        p = self.__class__.__new__(self.__class__)
        p.bbs = [list(self.bbs[0]), list(self.bbs[1])]
        p.occ = list(self.occ)
        p.occ_all = self.occ_all
        p.turn = self.turn
        p.castling = self.castling
        p.ep_square = self.ep_square
        p.halfmove = self.halfmove
        p.fullmove = self.fullmove
        p.pockets = None if self.pockets is None else [list(self.pockets[0]), list(self.pockets[1])]
        p.promoted = self.promoted
        p.checks_given = None if self.checks_given is None else list(self.checks_given)
        return p

    # ------------------------------------------------------------------- FEN

    @classmethod
    def from_fen(cls, fen: str) -> "Position":
        pos = cls()
        parts = fen.strip().split()
        if len(parts) < 1:
            raise InvalidFenError(f"empty FEN: {fen!r}")
        board = parts[0]

        # crazyhouse pocket may appear as "...[PNBq]" after the board field
        pocket_str = None
        if "[" in board:
            board, rest = board.split("[", 1)
            if not rest.endswith("]"):
                raise InvalidFenError(f"unterminated pocket: {fen!r}")
            pocket_str = rest[:-1]
        elif board.count("/") == 8:
            # shredder-style pocket as a 9th rank segment
            board, pocket_str = board.rsplit("/", 1)

        ranks = board.split("/")
        if len(ranks) != 8:
            raise InvalidFenError(f"expected 8 ranks: {fen!r}")
        prev_promoted = 0
        for r_idx, rank_str in enumerate(ranks):
            rank = 7 - r_idx
            file = 0
            last_sq = None
            for c in rank_str:
                if c.isdigit():
                    file += int(c)
                    last_sq = None
                elif c == "~":
                    if last_sq is None:
                        raise InvalidFenError(f"dangling ~ in FEN: {fen!r}")
                    prev_promoted |= bb(last_sq)
                else:
                    if file > 7:
                        raise InvalidFenError(f"rank overflow: {fen!r}")
                    color, ptype = parse_piece_char(c)
                    sq = square(file, rank)
                    pos.bbs[color][ptype] |= bb(sq)
                    last_sq = sq
                    file += 1
            if file != 8:
                raise InvalidFenError(f"bad rank length {rank_str!r}: {fen!r}")
        pos.promoted = prev_promoted
        pos._refresh_occ()

        if pos.pockets is not None or pocket_str is not None:
            pos.pockets = [[0] * 5, [0] * 5]
            if pocket_str and pocket_str != "-":
                for c in pocket_str:
                    color, ptype = parse_piece_char(c)
                    if ptype == KING:
                        raise InvalidFenError(f"king in pocket: {fen!r}")
                    pos.pockets[color][ptype] += 1

        pos.turn = WHITE
        if len(parts) > 1:
            if parts[1] not in ("w", "b"):
                raise InvalidFenError(f"bad side to move: {fen!r}")
            pos.turn = WHITE if parts[1] == "w" else BLACK

        pos.castling = 0
        if len(parts) > 2 and parts[2] != "-":
            pos.castling = pos._parse_castling(parts[2])

        pos.ep_square = None
        if len(parts) > 3 and parts[3] != "-":
            pos.ep_square = parse_square(parts[3])

        # optional threeCheck field before the counters, e.g. "3+3" or "+0+0"
        idx = 4
        if len(parts) > idx and ("+" in parts[idx]):
            pos._parse_checks_field(parts[idx])
            idx += 1
        if len(parts) > idx:
            try:
                pos.halfmove = int(parts[idx])
            except ValueError as e:
                raise InvalidFenError(f"bad halfmove clock: {fen!r}") from e
        idx += 1
        if len(parts) > idx:
            try:
                pos.fullmove = max(1, int(parts[idx]))
            except ValueError as e:
                raise InvalidFenError(f"bad fullmove number: {fen!r}") from e
        idx += 1
        if len(parts) > idx and "+" in parts[idx]:
            pos._parse_checks_field(parts[idx])

        pos._validate()
        return pos

    def _parse_checks_field(self, field: str) -> None:
        raise InvalidFenError(f"unexpected check-count field {field!r} for {self.variant}")

    def _parse_castling(self, field: str) -> int:
        rights = 0
        for c in field:
            if c in "KQkq":
                color = WHITE if c.isupper() else BLACK
                back = BACK_RANKS[color]
                king_bb = self.bbs[color][KING] & back
                if not king_bb:
                    continue
                ksq = lsb(king_bb)
                rooks = self.bbs[color][ROOK] & back
                if c.upper() == "K":
                    candidates = [s for s in scan(rooks) if s > ksq]
                    if candidates:
                        rights |= bb(max(candidates))
                else:
                    candidates = [s for s in scan(rooks) if s < ksq]
                    if candidates:
                        rights |= bb(min(candidates))
            elif c.upper() in "ABCDEFGH":
                color = WHITE if c.isupper() else BLACK
                file = "abcdefgh".index(c.lower())
                sq = square(file, 0 if color == WHITE else 7)
                rights |= bb(sq)
            else:
                raise InvalidFenError(f"bad castling field: {field!r}")
        return rights

    def castling_fen(self) -> str:
        out = ""
        for color, chars in ((WHITE, "KQ"), (BLACK, "kq")):
            back = BACK_RANKS[color]
            king_bb = self.bbs[color][KING] & back
            ksq = lsb(king_bb) if king_bb else None
            rooks = self.bbs[color][ROOK] & back
            rights = sorted(scan(self.castling & back), reverse=True)
            for rsq in rights:
                if ksq is not None and rsq > ksq:
                    outer = [s for s in scan(rooks) if s > ksq]
                    if outer and rsq == max(outer):
                        out += chars[0]
                        continue
                if ksq is not None and rsq < ksq:
                    outer = [s for s in scan(rooks) if s < ksq]
                    if outer and rsq == min(outer):
                        out += chars[1]
                        continue
                c = "abcdefgh"[square_file(rsq)]
                out += c.upper() if color == WHITE else c
        return out or "-"

    def to_fen(self) -> str:
        rows = []
        for rank in range(7, -1, -1):
            row = ""
            empty = 0
            for file in range(8):
                sq = square(file, rank)
                pc = self.piece_at(sq)
                if pc is None:
                    empty += 1
                else:
                    if empty:
                        row += str(empty)
                        empty = 0
                    row += piece_char(*pc)
                    if self.promoted & bb(sq):
                        row += "~"
            if empty:
                row += str(empty)
            rows.append(row)
        board = "/".join(rows)
        if self.pockets is not None:
            pocket = ""
            for color in (WHITE, BLACK):
                for ptype in (QUEEN, ROOK, BISHOP, KNIGHT, PAWN):
                    pocket += piece_char(color, ptype) * self.pockets[color][ptype]
            board += f"[{pocket}]"
        parts = [
            board,
            "w" if self.turn == WHITE else "b",
            self.castling_fen(),
            square_name(self.ep_square) if self.ep_square is not None else "-",
        ]
        extra = self._fen_extra()
        if extra:
            parts.append(extra)
        parts.append(str(self.halfmove))
        parts.append(str(self.fullmove))
        return " ".join(parts)

    def _fen_extra(self) -> Optional[str]:
        return None

    def _validate(self) -> None:
        for color in (WHITE, BLACK):
            kings = popcount(self.bbs[color][KING])
            if kings != 1:
                raise InvalidFenError(f"{'white' if color == WHITE else 'black'} must have exactly one king")
        if self.bbs[WHITE][PAWN] & (RANK_1 | RANK_8) or self.bbs[BLACK][PAWN] & (RANK_1 | RANK_8):
            raise InvalidFenError("pawn on back rank")
        # side not to move must not be in check (their king capturable)
        them = self.turn ^ 1
        their_king = self.bbs[them][KING]
        if their_king and self.attackers(self.turn, lsb(their_king)):
            raise InvalidFenError("side not to move is in check")

    # ------------------------------------------------------------- inspection

    def _refresh_occ(self) -> None:
        self.occ[WHITE] = 0
        self.occ[BLACK] = 0
        for ptype in range(6):
            self.occ[WHITE] |= self.bbs[WHITE][ptype]
            self.occ[BLACK] |= self.bbs[BLACK][ptype]
        self.occ_all = self.occ[WHITE] | self.occ[BLACK]

    def piece_at(self, sq: int) -> Optional[Tuple[int, int]]:
        # scans bbs directly (not occ) so it stays correct mid-_apply
        m = bb(sq)
        for color in (WHITE, BLACK):
            col_bbs = self.bbs[color]
            for ptype in range(6):
                if col_bbs[ptype] & m:
                    return (color, ptype)
        return None

    def king_sq(self, color: int) -> Optional[int]:
        k = self.bbs[color][KING]
        return lsb(k) if k else None

    def attackers(self, color: int, sq: int, occ: Optional[int] = None) -> int:
        """Bitboard of pieces of `color` attacking `sq` given occupancy."""
        if occ is None:
            occ = self.occ_all
        b = KNIGHT_ATTACKS[sq] & self.bbs[color][KNIGHT]
        b |= KING_ATTACKS[sq] & self.bbs[color][KING]
        b |= PAWN_ATTACKS[color ^ 1][sq] & self.bbs[color][PAWN]
        rq = self.bbs[color][ROOK] | self.bbs[color][QUEEN]
        if rq:
            b |= rook_attacks(sq, occ) & rq
        bq = self.bbs[color][BISHOP] | self.bbs[color][QUEEN]
        if bq:
            b |= bishop_attacks(sq, occ) & bq
        return b

    def checkers(self) -> int:
        ksq = self.king_sq(self.turn)
        if ksq is None:
            return 0
        return self.attackers(self.turn ^ 1, ksq)

    def is_check(self) -> bool:
        return bool(self.checkers())

    # -------------------------------------------------------- move generation

    def _pawn_moves(self, us: int) -> Iterator[Move]:
        them = us ^ 1
        pawns = self.bbs[us][PAWN]
        empty = ~self.occ_all & FULL_BB
        promo_rank = PROMO_RANKS[us]
        fwd = 8 if us == WHITE else -8
        double_src = self._double_push_sources(us)
        for frm in scan(pawns):
            to = frm + fwd
            if 0 <= to < 64 and empty & bb(to):
                if bb(to) & promo_rank:
                    for promo in self._promotion_pieces():
                        yield Move(frm, to, promotion=promo)
                else:
                    yield Move(frm, to)
                    if bb(frm) & double_src:
                        to2 = to + fwd
                        if 0 <= to2 < 64 and empty & bb(to2):
                            yield Move(frm, to2)
            caps = PAWN_ATTACKS[us][frm]
            targets = caps & self.occ[them]
            if self.ep_square is not None and caps & bb(self.ep_square):
                targets |= bb(self.ep_square)
            for to in scan(targets):
                if bb(to) & promo_rank:
                    for promo in self._promotion_pieces():
                        yield Move(frm, to, promotion=promo)
                else:
                    yield Move(frm, to)

    def _double_push_sources(self, us: int) -> int:
        return RANK_2 if us == WHITE else RANK_7

    def _double_sets_ep(self, frm: int, us: int) -> bool:
        return True  # horde: back-rank doubles can't be captured en passant

    def _promotion_pieces(self) -> Tuple[int, ...]:
        return (QUEEN, ROOK, BISHOP, KNIGHT)

    def _piece_moves(self, us: int) -> Iterator[Move]:
        own = self.occ[us]
        occ = self.occ_all
        for frm in scan(self.bbs[us][KNIGHT]):
            for to in scan(KNIGHT_ATTACKS[frm] & ~own):
                yield Move(frm, to)
        for frm in scan(self.bbs[us][BISHOP]):
            for to in scan(bishop_attacks(frm, occ) & ~own):
                yield Move(frm, to)
        for frm in scan(self.bbs[us][ROOK]):
            for to in scan(rook_attacks(frm, occ) & ~own):
                yield Move(frm, to)
        for frm in scan(self.bbs[us][QUEEN]):
            for to in scan((rook_attacks(frm, occ) | bishop_attacks(frm, occ)) & ~own):
                yield Move(frm, to)
        for frm in scan(self.bbs[us][KING]):
            for to in scan(KING_ATTACKS[frm] & ~own):
                yield Move(frm, to)

    def _castling_moves(self, us: int) -> Iterator[Move]:
        if not self.has_castling:
            return
        ksq = self.king_sq(us)
        if ksq is None:
            return
        back = BACK_RANKS[us]
        if not (bb(ksq) & back):
            return
        them = us ^ 1
        if self.attackers(them, ksq):
            return  # cannot castle out of check
        for rsq in scan(self.castling & back & self.bbs[us][ROOK]):
            kingside = rsq > ksq
            k_dest = square(6 if kingside else 2, square_rank(ksq))
            r_dest = square(5 if kingside else 3, square_rank(ksq))
            # squares that must be empty (other than the king and rook themselves)
            path = (
                BETWEEN[ksq][k_dest]
                | BETWEEN[rsq][r_dest]
                | bb(k_dest)
                | bb(r_dest)
            ) & ~bb(ksq) & ~bb(rsq)
            if path & self.occ_all:
                continue
            # king's path (excluding start) must not be attacked; occupancy
            # without the king and castling rook (they move away)
            occ = self.occ_all & ~bb(ksq) & ~bb(rsq)
            king_path = BETWEEN[ksq][k_dest] | bb(k_dest)
            if any(self.attackers(them, s, occ) for s in scan(king_path)):
                continue
            yield Move(ksq, rsq)

    def _drop_moves(self, us: int) -> Iterator[Move]:
        return iter(())

    def generate_pseudo_legal(self) -> Iterator[Move]:
        us = self.turn
        yield from self._pawn_moves(us)
        yield from self._piece_moves(us)
        yield from self._castling_moves(us)
        yield from self._drop_moves(us)

    def is_castling_move(self, move: Move) -> bool:
        if move.drop is not None:
            return False
        pc = self.piece_at(move.from_sq)
        return (
            pc is not None
            and pc[1] == KING
            and bool(self.occ[self.turn] & bb(move.to_sq))
        )

    def _move_is_safe(self, move: Move) -> bool:
        """After applying `move`, is the mover's king not capturable?"""
        child = self.copy()
        child._apply(move)
        ksq = child.king_sq(self.turn)
        if ksq is None:
            return True
        return not child.attackers(child.turn, ksq)

    def legal_moves(self) -> List[Move]:
        moves = []
        for move in self.generate_pseudo_legal():
            if self.is_castling_move(move):
                moves.append(move)  # castling generator already ensured safety
            elif self._move_is_safe(move):
                moves.append(move)
        return moves

    def is_legal(self, move: Move) -> bool:
        return move in self.legal_moves()

    # ------------------------------------------------------------ move making

    def push(self, move: Move) -> "Position":
        """Return the successor position (copy-make)."""
        child = self.copy()
        child._apply(move)
        return child

    def push_uci(self, uci: str) -> "Position":
        move = self.parse_uci(uci)
        return self.push(move)

    def parse_uci(self, uci: str) -> Move:
        """Parse a UCI move, accepting both standard (e1g1) and Chess960
        (king-takes-rook, e1h1) castling notation; validates legality."""
        move = Move.parse_uci(uci)
        move = self.normalize_move(move)
        legal = self.legal_moves()
        if move not in legal:
            raise IllegalMoveError(f"illegal move {uci!r} in {self.to_fen()!r}")
        return move

    def normalize_move(self, move: Move) -> Move:
        """Convert standard-notation castling (e1g1) to king-takes-rook."""
        if move.drop is not None:
            return move
        pc = self.piece_at(move.from_sq)
        if pc is None or pc[1] != KING or not self.has_castling:
            return move
        us = pc[0]
        if self.occ[us] & self.bbs[us][ROOK] & bb(move.to_sq):
            return move  # already king-takes-rook form
        df = square_file(move.to_sq) - square_file(move.from_sq)
        if abs(df) == 2 and square_rank(move.to_sq) == square_rank(move.from_sq):
            back = BACK_RANKS[us]
            rights = self.castling & back & self.bbs[us][ROOK]
            candidates = [
                s for s in scan(rights) if (s > move.from_sq) == (df > 0)
            ]
            if candidates:
                rsq = max(candidates) if df > 0 else min(candidates)
                return Move(move.from_sq, rsq)
        return move

    def _remove_piece(self, sq: int) -> Optional[Tuple[int, int]]:
        pc = self.piece_at(sq)
        if pc is None:
            return None
        self.bbs[pc[0]][pc[1]] &= ~bb(sq)
        self.promoted &= ~bb(sq)
        return pc

    def _set_piece(self, sq: int, color: int, ptype: int, promoted: bool = False) -> None:
        self._remove_piece(sq)
        self.bbs[color][ptype] |= bb(sq)
        if promoted:
            self.promoted |= bb(sq)

    def _apply(self, move: Move) -> None:
        us = self.turn
        them = us ^ 1
        self.halfmove += 1
        new_ep: Optional[int] = None
        captured: Optional[Tuple[int, int, int]] = None  # (color, ptype, sq)

        if move.drop is not None:
            assert self.pockets is not None, "drop in non-crazyhouse game"
            self.pockets[us][move.drop] -= 1
            self._set_piece(move.to_sq, us, move.drop)
            self.halfmove = 0 if move.drop == PAWN else self.halfmove
        elif self.is_castling_move(move):
            ksq, rsq = move.from_sq, move.to_sq
            kingside = rsq > ksq
            rank = square_rank(ksq)
            self._remove_piece(ksq)
            self._remove_piece(rsq)
            self._set_piece(square(6 if kingside else 2, rank), us, KING)
            self._set_piece(square(5 if kingside else 3, rank), us, ROOK)
            back = BACK_RANKS[us]
            self.castling &= ~back
        else:
            pc = self.piece_at(move.from_sq)
            if pc is None:
                raise IllegalMoveError(f"no piece on {square_name(move.from_sq)}")
            color, ptype = pc
            was_promoted = bool(self.promoted & bb(move.from_sq))
            self._remove_piece(move.from_sq)

            # captures (including en passant)
            cap_sq = move.to_sq
            if ptype == PAWN and self.ep_square is not None and move.to_sq == self.ep_square and not (
                self.occ_all & bb(move.to_sq)
            ):
                cap_sq = move.to_sq + (-8 if us == WHITE else 8)
            cap_pc = self.piece_at(cap_sq)
            if cap_pc is not None:
                cap_was_promoted = bool(self.promoted & bb(cap_sq))
                self._remove_piece(cap_sq)
                captured = (cap_pc[0], cap_pc[1], cap_sq)
                self.halfmove = 0
                self.castling &= ~bb(cap_sq)  # capturing a rook kills its right
                self._on_capture(us, cap_pc, cap_sq, cap_was_promoted)

            if ptype == PAWN:
                self.halfmove = 0
                if abs(move.to_sq - move.from_sq) == 16 and self._double_sets_ep(
                    move.from_sq, us
                ):
                    new_ep = (move.from_sq + move.to_sq) // 2
            if move.promotion is not None:
                self._set_piece(move.to_sq, us, move.promotion, promoted=self.pockets is not None)
            else:
                self._set_piece(move.to_sq, us, ptype, promoted=was_promoted)

            if ptype == KING:
                self.castling &= ~BACK_RANKS[us]
            self.castling &= ~bb(move.from_sq)  # moving a rook kills its right

            self._post_move_hook(move, us, ptype, captured)

        self._refresh_occ()
        self.ep_square = new_ep
        self.turn = them
        if us == BLACK:
            self.fullmove += 1
        self._post_turn_hook(us)

    def _on_capture(self, us: int, cap_pc: Tuple[int, int], cap_sq: int, cap_was_promoted: bool) -> None:
        pass

    def _post_move_hook(self, move: Move, us: int, ptype: int, captured) -> None:
        pass

    def _post_turn_hook(self, prev_turn: int) -> None:
        pass

    # --------------------------------------------------------------- outcomes

    def is_insufficient_material(self) -> bool:
        if self.bbs[WHITE][PAWN] | self.bbs[BLACK][PAWN]:
            return False
        if any(self.bbs[c][ROOK] | self.bbs[c][QUEEN] for c in (WHITE, BLACK)):
            return False
        minors = popcount(
            self.bbs[WHITE][KNIGHT] | self.bbs[WHITE][BISHOP]
            | self.bbs[BLACK][KNIGHT] | self.bbs[BLACK][BISHOP]
        )
        return minors <= 1

    def outcome(self, legal_moves: Optional[List[Move]] = None) -> Optional[Tuple[Optional[int], str]]:
        """Return (winner_color_or_None_for_draw, reason) if game is over.

        Pass precomputed `legal_moves` to avoid regenerating them (search
        engines call this once per node)."""
        special = self._variant_outcome()
        if special is not None:
            return special
        if legal_moves is None:
            legal_moves = self.legal_moves()
        if not legal_moves:
            if self.is_check():
                return (self.turn ^ 1, "checkmate")
            return (None, "stalemate")
        if self.is_insufficient_material():
            return (None, "insufficient material")
        if self.halfmove >= 100:
            return (None, "75-move rule" if self.halfmove >= 150 else "50-move rule")
        return None

    def _variant_outcome(self) -> Optional[Tuple[Optional[int], str]]:
        return None

    def __repr__(self) -> str:
        return f"<{self.__class__.__name__} {self.to_fen()!r}>"


class Chess960Position(Position):
    """Chess960: identical rules; castling is already rook-square based."""

    variant = "chess960"
