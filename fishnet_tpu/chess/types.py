"""Core chess types: colors, pieces, squares, moves.

Host-side rules library filling the role shakmaty plays in the reference
client (reference: src/queue.rs:554-581 replays every UCI move to validate
server input). Square indexing is a1=0 .. h8=63 (little-endian rank-file).
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

WHITE = 0
BLACK = 1
COLORS = (WHITE, BLACK)

PAWN = 0
KNIGHT = 1
BISHOP = 2
ROOK = 3
QUEEN = 4
KING = 5
PIECE_TYPES = (PAWN, KNIGHT, BISHOP, ROOK, QUEEN, KING)

PIECE_CHARS = "pnbrqk"

FILES = "abcdefgh"
RANKS = "12345678"

FULL_BB = (1 << 64) - 1


def square(file: int, rank: int) -> int:
    return rank * 8 + file


def square_file(sq: int) -> int:
    return sq & 7


def square_rank(sq: int) -> int:
    return sq >> 3


def square_name(sq: int) -> str:
    return FILES[sq & 7] + RANKS[sq >> 3]


def parse_square(name: str) -> int:
    if len(name) != 2 or name[0] not in FILES or name[1] not in RANKS:
        raise ValueError(f"invalid square: {name!r}")
    return square(FILES.index(name[0]), RANKS.index(name[1]))


def bb(sq: int) -> int:
    return 1 << sq


def lsb(b: int) -> int:
    """Index of least significant set bit."""
    return (b & -b).bit_length() - 1


def msb(b: int) -> int:
    return b.bit_length() - 1


def popcount(b: int) -> int:
    return bin(b).count("1")


def scan(b: int):
    """Iterate square indices of set bits, low to high."""
    while b:
        s = (b & -b).bit_length() - 1
        yield s
        b &= b - 1


def piece_char(color: int, ptype: int) -> str:
    c = PIECE_CHARS[ptype]
    return c.upper() if color == WHITE else c


def parse_piece_char(c: str) -> tuple[int, int]:
    """Return (color, piece_type) for a FEN piece letter."""
    lower = c.lower()
    if lower not in PIECE_CHARS:
        raise ValueError(f"invalid piece: {c!r}")
    return (WHITE if c.isupper() else BLACK, PIECE_CHARS.index(lower))


@dataclass(frozen=True)
class Move:
    """A chess move.

    Castling is always encoded internally as king-takes-own-rook
    (from=king square, to=rook square), matching UCI_Chess960 semantics —
    the reference always runs engines with UCI_Chess960=true
    (reference: src/stockfish.rs:200). `drop` is a piece type for
    crazyhouse drops (UCI "P@e4"). `promotion` is a piece type or None.
    """

    from_sq: int
    to_sq: int
    promotion: Optional[int] = None
    drop: Optional[int] = None

    def uci(self, chess960: bool = True) -> str:
        if self.drop is not None:
            return PIECE_CHARS[self.drop].upper() + "@" + square_name(self.to_sq)
        s = square_name(self.from_sq) + square_name(self.to_sq)
        if self.promotion is not None:
            s += PIECE_CHARS[self.promotion]
        return s

    @staticmethod
    def parse_uci(s: str) -> "Move":
        if "@" in s:
            pc, sq = s.split("@", 1)
            color, ptype = parse_piece_char(pc)
            return Move(0, parse_square(sq), drop=ptype)
        if len(s) not in (4, 5):
            raise ValueError(f"invalid uci move: {s!r}")
        frm = parse_square(s[0:2])
        to = parse_square(s[2:4])
        promo = None
        if len(s) == 5:
            if s[4] not in PIECE_CHARS:
                raise ValueError(f"invalid promotion: {s!r}")
            promo = PIECE_CHARS.index(s[4])
        return Move(frm, to, promotion=promo)

    def __str__(self) -> str:
        return self.uci()
